"""Deterministic datasets shared by the parity fixtures and their tests.

The fixture generator (``scripts/make_parity_fixtures.py``) trains the
REFERENCE implementation (built from ``/root/reference`` into
``.refbuild/lib_lightgbm.so``) on exactly these arrays and commits the
resulting model texts / predictions / bin boundaries under
``tests/fixtures/``.  ``tests/test_parity.py`` regenerates the same
arrays (NumPy ``Generator`` bit streams are stable across versions) and
asserts this implementation reproduces the committed outputs.
"""

from __future__ import annotations

import numpy as np

SEED = 20260730
N_ROWS = 2000
PRED_ROWS = 256          # rows predicted in the fixtures


def make_features(rows: int = N_ROWS) -> np.ndarray:
    """(rows, 10) float64 with the distribution shapes the reference's
    GreedyFindBin has to handle: normal, skewed, low-cardinality,
    missing-heavy, constant, binary, heavy-tailed, scaled, zero-inflated,
    uniform."""
    rng = np.random.default_rng(SEED)
    cols = [
        rng.standard_normal(rows),
        rng.lognormal(0.0, 1.0, rows),
        rng.integers(0, 5, rows).astype(np.float64),
        np.where(rng.random(rows) < 0.15, np.nan,
                 rng.standard_normal(rows)),
        np.full(rows, 3.14),
        (rng.random(rows) < 0.3).astype(np.float64),
        rng.standard_t(3, rows),
        rng.standard_normal(rows) * 100.0,
        np.where(rng.random(rows) < 0.7, 0.0, rng.exponential(2.0, rows)),
        rng.random(rows),
    ]
    return np.ascontiguousarray(np.stack(cols, axis=1))


def make_labels(x: np.ndarray):
    """(binary, regression, multiclass3) labels from a fixed concept."""
    rng = np.random.default_rng(SEED + 1)
    z = np.nan_to_num(x[:, 0]) + 0.5 * np.log1p(x[:, 1]) \
        + 0.3 * x[:, 2] - 0.2 * np.nan_to_num(x[:, 3]) \
        + 0.01 * x[:, 7] + np.abs(x[:, 9] - 0.5)
    y_bin = (z + 0.3 * rng.standard_normal(len(z)) > np.median(z)) \
        .astype(np.float64)
    y_reg = z + 0.1 * rng.standard_normal(len(z))
    q = np.quantile(z, [1 / 3, 2 / 3])
    y_mc = np.digitize(z, q).astype(np.float64)
    return y_bin, y_reg, y_mc


def make_categorical_features(rows: int = N_ROWS) -> np.ndarray:
    """(rows, 4) with two genuine categorical columns (ids 0..29 / 0..7)
    and two numeric ones, for the categorical-split model fixture."""
    rng = np.random.default_rng(SEED + 2)
    return np.ascontiguousarray(np.stack([
        rng.integers(0, 30, rows).astype(np.float64),
        rng.integers(0, 8, rows).astype(np.float64),
        rng.standard_normal(rows),
        rng.random(rows),
    ], axis=1))


def make_categorical_labels(xc: np.ndarray) -> np.ndarray:
    rng = np.random.default_rng(SEED + 3)
    lut = np.asarray([1.0 if (i * 2654435761) % 5 < 2 else -1.0
                      for i in range(30)])
    z = lut[xc[:, 0].astype(np.int64)] + 0.4 * (xc[:, 1] >= 4.0) \
        + 0.5 * xc[:, 2]
    return (z + 0.3 * rng.standard_normal(len(z)) > 0).astype(np.float64)


# FindBin parity cases: (name, max_bin, min_data_in_bin, values-builder)
def bin_cases():
    rng = np.random.default_rng(SEED + 4)
    yield "normal_255", 255, 3, rng.standard_normal(5000)
    yield "normal_63", 63, 3, rng.standard_normal(5000)
    yield "lognormal_255", 255, 3, rng.lognormal(0, 2, 5000)
    yield "small_distinct", 255, 3, rng.integers(0, 9, 4000) \
        .astype(np.float64)
    yield "with_nan", 255, 3, np.where(rng.random(3000) < 0.2, np.nan,
                                       rng.standard_normal(3000))
    yield "zero_inflated", 255, 3, np.where(
        rng.random(6000) < 0.8, 0.0, rng.exponential(1.0, 6000))
    yield "negative_heavy", 127, 3, -np.abs(rng.standard_t(2, 5000))
    yield "tiny_sample", 16, 1, rng.standard_normal(40)
    yield "ties_heavy", 31, 5, np.round(rng.standard_normal(5000), 1)
    yield "single_value", 255, 3, np.full(100, 7.25)
