"""Parity + hot-swap suite for the packed-ensemble serving subsystem.

Pins the acceptance contract of ``lightgbm_tpu/serve/`` (docs/
Serving.md): leaf ROUTING bit-identical to the host ``Tree.predict_leaf``
walk across numerical ``<=`` splits, NaN/zero missing default
directions, categorical bitsets, multiclass and iteration slicing;
file-loaded (no ``train_set``) Boosters on the device path; one device
dispatch per batch; and the window loop's zero-retrace ``swap()``.
"""

import numpy as np
import pytest

from lightgbm_tpu import basic as lgb_basic
from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.serve import (PredictionServer, pack_ensemble, pack_gbdt,
                                predict_leaves, predict_scores)


def _train(params, x, y, n_iters=8, categorical=()):
    cfg = Config({"verbosity": -1, "device_growth": "on",
                  "num_leaves": 15, "min_data_in_leaf": 5, **params})
    ds = BinnedDataset.construct_from_matrix(x, cfg, list(categorical))
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    bst._flush_pending()
    return bst


def _host_leaves(models, xq):
    return np.stack([t.predict_leaf(xq) for t in models], axis=1) \
        if models else np.zeros((xq.shape[0], 0), np.int32)


def _assert_parity(bst, xq, start=0, num=-1):
    """Exact leaf routing + value tolerance for a tree slice."""
    total = bst.num_iterations()
    end = total if num <= 0 else min(start + num, total)
    k = bst.num_model
    pe = pack_ensemble(bst.models, k, start_iteration=start,
                       num_iteration=num,
                       num_features=bst.max_feature_idx + 1)
    leaves = predict_leaves(pe, xq)
    host = _host_leaves(bst.models[start * k:end * k], xq)
    np.testing.assert_array_equal(leaves, host)
    bst.config.device_predict = "off"
    raw_host = bst.predict_raw(xq, num_iteration=num, start_iteration=start)
    raw_dev = predict_scores(pe, xq)
    np.testing.assert_allclose(raw_dev, raw_host, rtol=1e-5, atol=1e-6)


def test_packed_parity_numerical_nan():
    """Numerical <= splits with NaNs in train AND query: exact default-
    direction routing (missing type NaN)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((3000, 8)).astype(np.float32)
    x[rng.random(x.shape) < 0.05] = np.nan
    y = (np.nan_to_num(x[:, 0]) + np.abs(np.nan_to_num(x[:, 1]))
         > 0.4).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y)
    xq = rng.standard_normal((700, 8)).astype(np.float64)
    xq[rng.random(xq.shape) < 0.15] = np.nan
    _assert_parity(bst, xq)


def test_packed_parity_zero_missing():
    """zero_as_missing exercises missing type Zero: |v| <= 1e-35 takes
    the default direction, including exact zeros in the query."""
    rng = np.random.default_rng(1)
    x = rng.standard_normal((3000, 6)).astype(np.float32)
    x[rng.random(x.shape) < 0.3] = 0.0
    y = (x[:, 0] + x[:, 1] > 0.3).astype(np.float32)
    bst = _train({"objective": "binary", "zero_as_missing": True}, x, y)
    xq = rng.standard_normal((600, 6)).astype(np.float64)
    xq[rng.random(xq.shape) < 0.3] = 0.0
    xq[rng.random(xq.shape) < 0.05] = 1e-40   # inside the zero window
    _assert_parity(bst, xq)


def test_packed_parity_categorical():
    """Categorical bitset splits: member/non-member/unseen/negative/NaN
    category values all route exactly."""
    rng = np.random.default_rng(13)
    n = 4000
    cat = rng.integers(0, 12, n)
    x = np.column_stack([
        cat.astype(np.float32),
        rng.standard_normal(n).astype(np.float32),
        rng.standard_normal(n).astype(np.float32)])
    effect = np.asarray([2.0, -1.0, 0.5, 3.0, -2.0, 0.0,
                         1.5, -0.5, 2.5, -1.5, 0.7, -2.5])
    y = (effect[cat] + x[:, 1] + 0.1 * rng.standard_normal(n)) \
        .astype(np.float32)
    bst = _train({"objective": "regression", "num_leaves": 31,
                  "min_data_in_leaf": 40, "min_gain_to_split": 1e-3},
                 x, y, n_iters=5, categorical=[0])
    assert any(t.num_cat > 0 for t in bst.models)
    xq = np.column_stack([
        rng.integers(-3, 40, 900).astype(np.float64),   # incl. unseen
        rng.standard_normal(900),
        rng.standard_normal(900)])
    xq[rng.random(900) < 0.1, 0] = np.nan
    _assert_parity(bst, xq)


def test_packed_parity_multiclass_and_slicing():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2500, 6)).astype(np.float32)
    y = (np.digitize(x[:, 0] + 0.5 * x[:, 1],
                     [-0.5, 0.5])).astype(np.float32)
    bst = _train({"objective": "multiclass", "num_class": 3}, x, y, 6)
    assert bst.num_model == 3
    xq = rng.standard_normal((400, 6)).astype(np.float64)
    _assert_parity(bst, xq)                      # full model
    _assert_parity(bst, xq, start=2, num=3)      # interior slice
    _assert_parity(bst, xq, start=4, num=-1)     # open-ended tail


def test_packed_file_loaded_booster_serves_on_device():
    """The whole point of raw-value packing: a Booster loaded from a
    model STRING (no train_set, no bin mappers) takes the device path
    and matches its own host walk exactly."""
    rng = np.random.default_rng(9)
    x = rng.standard_normal((2500, 7)).astype(np.float32)
    y = (x[:, 0] - x[:, 2] > 0.1).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y)
    loaded = GBDT.load_model_from_string(bst.model_to_string())
    assert loaded.train_set is None
    xq = rng.standard_normal((500, 7)).astype(np.float64)
    xq[rng.random(xq.shape) < 0.1] = np.nan
    pe = pack_gbdt(loaded)
    np.testing.assert_array_equal(predict_leaves(pe, xq),
                                  _host_leaves(loaded.models, xq))
    loaded.config.device_predict = "force"
    dev = loaded.predict_raw(xq)
    loaded.config.device_predict = "off"
    host = loaded.predict_raw(xq)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_pred_leaf_honors_start_iteration():
    """Regression: predict(pred_leaf=True) used to slice trees
    [0, num_iteration) and ignore start_iteration, while predict_raw
    honored it."""
    rng = np.random.default_rng(6)
    x = rng.standard_normal((2000, 5)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y, 8)
    xq = rng.standard_normal((150, 5)).astype(np.float64)
    leaves = bst.predict(xq, pred_leaf=True, num_iteration=3,
                         start_iteration=2)
    assert leaves.shape == (150, 3)
    np.testing.assert_array_equal(leaves,
                                  _host_leaves(bst.models[2:5], xq))
    # default slice unchanged: all trees from 0
    full = bst.predict(xq, pred_leaf=True)
    assert full.shape == (150, len(bst.models))


def test_server_predict_matches_booster_predict():
    """PredictionServer applies the same output conversion as
    Booster.predict (sigmoid here), from any of Booster / GBDT / path."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2500, 6))
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    ds = lgb_basic.Dataset(x, label=y,
                           params={"objective": "binary",
                                   "verbosity": -1, "num_leaves": 15})
    booster = lgb_basic.Booster(params={"objective": "binary",
                                        "verbosity": -1,
                                        "num_leaves": 15}, train_set=ds)
    for _ in range(6):
        booster.update()
    xq = rng.standard_normal((300, 6))
    server = PredictionServer(booster)
    got = server.predict(xq)
    want = booster.predict(xq)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    raw = server.predict(xq, raw_score=True)
    want_raw = booster.predict(xq, raw_score=True)
    np.testing.assert_allclose(raw, want_raw, rtol=1e-5, atol=1e-6)


def _window_booster(seed, n_iters=4):
    """Same-config retrain windows over fresh data.  max_depth caps the
    structural depth inside one pow2 pad bucket and the strong signal
    fills all 15 leaves, so every window packs to identical pads."""
    wrng = np.random.default_rng(seed)
    x = wrng.standard_normal((2000, 8)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    return _train({"objective": "binary", "num_leaves": 15,
                   "max_depth": 6}, x, y, n_iters)


def test_server_hot_swap_zero_retraces():
    """The cache-admission steady state: same-shaped retrain windows
    swap into the server with ZERO new traces/compiles (obs jit
    counters over the packed traversal program), and every predict is
    ONE device dispatch."""
    from lightgbm_tpu import obs

    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    try:
        reg = obs.registry()
        server = PredictionServer(_window_booster(1))
        xq = np.random.default_rng(0).standard_normal((300, 8))
        server.predict(xq)

        def compiles():
            return sum(v["compiles"]
                       for v in reg.snapshot()["jit"].values())

        warm = compiles()
        swaps0 = reg.counter("serve.swaps")
        batches0 = reg.counter("serve.device_batches")
        # window 2 and 3: same config + same shapes -> same pads
        for seed in (2, 3):
            assert server.swap(_window_booster(seed)) is True
            server.predict(xq)
        assert compiles() == warm, reg.snapshot()["jit"]
        assert reg.counter("serve.swaps") == swaps0 + 2
        # one device dispatch per predict call
        assert reg.counter("serve.device_batches") == batches0 + 2
        # different row counts inside one pow2 bucket (257..512 all pad
        # to 512, like the warm 300-row batch) reuse the program
        server.predict(xq[:260])
        server.predict(xq[:290])
        assert compiles() == warm
        # a DIFFERENT tree count changes the pad signature: the swap
        # reports it and the next predict may retrace
        assert server.swap(_window_booster(4, n_iters=9)) is False
        assert reg.counter("serve.swap_shape_changes") >= 1
    finally:
        if not was_enabled:
            obs.configure(enabled=False)


def test_server_microbatch_queue():
    """submit() coalesces requests and resolves each future to exactly
    what predict() returns for those rows."""
    rng = np.random.default_rng(7)
    server = PredictionServer(_window_booster(11), max_batch=4096,
                              max_wait_ms=5.0)
    queries = [rng.standard_normal((n, 8)) for n in (17, 64, 33)]
    with server:
        futures = [server.submit(q) for q in queries]
        got = [f.result(timeout=30) for f in futures]
    for q, g in zip(queries, got):
        np.testing.assert_allclose(g, server.predict(q),
                                   rtol=1e-6, atol=1e-7)
    with pytest.raises(Exception):
        server.submit(queries[0])   # worker stopped


def test_server_accepts_model_file(tmp_path):
    bst = _window_booster(21)
    path = str(tmp_path / "model.txt")
    bst.save_model_to_file(path)
    server = PredictionServer(path)
    xq = np.random.default_rng(1).standard_normal((100, 8))
    bst.config.device_predict = "off"
    want = bst.predict(xq)
    np.testing.assert_allclose(server.predict(xq), want,
                               rtol=1e-5, atol=1e-6)


def test_serve_capi_roundtrip():
    """The LGBM_Serve* C-API surface: create from a trained booster,
    predict CSR through the server, swap, free."""
    import scipy.sparse as sp

    from lightgbm_tpu import c_api as C

    rng = np.random.default_rng(17)
    x = sp.random(3000, 20, density=0.3, random_state=rng,
                  data_rvs=lambda k: rng.standard_normal(k)).tocsr()
    y = (np.asarray(x[:, :4].sum(axis=1)).ravel() > 0.2) \
        .astype(np.float32)
    params = "objective=binary num_leaves=15 verbosity=-1"

    def check(rc):
        assert rc == 0, C.LGBM_GetLastError()

    ds = C.Ref()
    check(C.LGBM_DatasetCreateFromCSR(
        x.indptr, C.C_API_DTYPE_INT32, x.indices, x.data,
        C.C_API_DTYPE_FLOAT64, len(x.indptr), len(x.data), 20, params,
        None, ds))
    check(C.LGBM_DatasetSetField(ds.value, "label", y, len(y),
                                 C.C_API_DTYPE_FLOAT32))
    bst = C.Ref()
    check(C.LGBM_BoosterCreate(ds.value, params, bst))
    fin = C.Ref()
    check(C.LGBM_BoosterUpdateChunked(bst.value, 5, 5, fin))

    srv = C.Ref()
    check(C.LGBM_ServeCreate(bst.value, params, srv))
    nq = 400
    xq = x[:nq]
    out_len = C.Ref()
    check(C.LGBM_ServeCalcNumPredict(srv.value, nq, out_len))
    assert out_len.value == nq
    result = np.zeros(nq, np.float64)
    check(C.LGBM_ServePredictForCSR(
        srv.value, xq.indptr, C.C_API_DTYPE_INT32, xq.indices, xq.data,
        C.C_API_DTYPE_FLOAT64, len(xq.indptr), len(xq.data), 20,
        C.C_API_PREDICT_NORMAL, out_len, result))
    assert out_len.value == nq
    # must match the booster's own CSR predict path (value tolerance:
    # f32 device accumulation vs the host walk)
    ref = np.zeros(nq, np.float64)
    check(C.LGBM_BoosterPredictForCSR(
        bst.value, xq.indptr, C.C_API_DTYPE_INT32, xq.indices, xq.data,
        C.C_API_DTYPE_FLOAT64, len(xq.indptr), len(xq.data), 20,
        C.C_API_PREDICT_NORMAL, 0, params, out_len, ref))
    np.testing.assert_allclose(result, ref, rtol=1e-5, atol=1e-6)
    # swap to the same booster (same shapes) and free everything
    check(C.LGBM_ServeSwap(srv.value, bst.value))
    check(C.LGBM_ServeFree(srv.value))
    assert C.LGBM_ServePredictForCSR(
        srv.value, xq.indptr, C.C_API_DTYPE_INT32, xq.indices, xq.data,
        C.C_API_DTYPE_FLOAT64, len(xq.indptr), len(xq.data), 20,
        C.C_API_PREDICT_NORMAL, out_len, result) != 0   # stale handle
    check(C.LGBM_BoosterFree(bst.value))
    check(C.LGBM_DatasetFree(ds.value))


def test_host_fallback_interleave_multiclass_and_rf():
    """Regression for the host-fallback tenant interleave
    (``ModelMeta.host_raw``'s ``out[i % num_model]``): for multiclass
    ensembles the iteration-major interleave must match the packed
    tree order, and RF models must apply the per-slice averaging — so
    degraded answers are BYTE-identical to ``Booster.predict``'s host
    path."""
    from lightgbm_tpu.robust import faults
    from lightgbm_tpu.robust.retry import CircuitBreaker

    rng = np.random.default_rng(23)
    x = rng.standard_normal((1500, 6)).astype(np.float32)
    y_mc = np.digitize(x[:, 0] + 0.5 * x[:, 1],
                       [-0.5, 0.5]).astype(np.float32)
    y_bin = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    mc = _train({"objective": "multiclass", "num_class": 3}, x, y_mc, 4)
    rf = _train({"objective": "binary", "boosting": "rf",
                 "bagging_freq": 1, "bagging_fraction": 0.7},
                x, y_bin, 4)
    xq = rng.standard_normal((250, 6))
    for bst in (mc, rf):
        srv = PredictionServer(bst, breaker=CircuitBreaker(
            failure_threshold=1, reprobe_interval_s=60.0))
        dev = srv.predict(xq)
        faults.configure("serve.dispatch:persist")
        try:
            got = srv.predict(xq)
        finally:
            faults.clear()
        bst.config.device_predict = "off"
        want = bst.predict(xq)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_allclose(dev, want, rtol=1e-4, atol=1e-6)


def test_packed_empty_and_stump_models():
    """Degenerate shapes: zero query rows, stump-only models."""
    rng = np.random.default_rng(30)
    x = rng.standard_normal((500, 4)).astype(np.float32)
    y = np.zeros(500, np.float32)   # constant label -> stumps
    bst = _train({"objective": "regression",
                  "boost_from_average": True}, x, y, 2)
    xq = rng.standard_normal((50, 4))
    pe = pack_gbdt(bst)
    bst.config.device_predict = "off"
    host = bst.predict_raw(xq)
    np.testing.assert_allclose(predict_scores(pe, xq), host,
                               rtol=1e-6, atol=1e-7)
    # zero rows
    assert predict_scores(pe, np.zeros((0, 4))).shape == (1, 0)
    assert predict_leaves(pe, np.zeros((0, 4))).shape[0] == 0
