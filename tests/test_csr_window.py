"""CSR-native ingestion + the fork harness's retrain-every-window pattern.

The fork's real entry point (``src/test.cpp:243-298``) replays a request
trace in sliding windows; per window it builds a fresh Dataset from CSR
feature rows (inter-arrival gaps + size/cost), trains 50 iterations through
the C API, and predicts the next window, forever.  These tests assert the
TPU build serves that workload: sparse inputs bin without densifying,
repeated retrains stay bounded in time, and predictions flow from CSR."""

import time

import numpy as np
import scipy.sparse as sp

from lightgbm_tpu import basic as lgb_basic
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset


def _sparse_window(rng, n, nf=30, density=0.15):
    """LRB-style features: mostly-zero inter-arrival gap columns + a few
    dense size/cost columns, binary admission labels."""
    x = sp.random(n, nf, density=density, random_state=rng,
                  data_rvs=lambda k: rng.exponential(50.0, k).astype(
                      np.float64)).tocsr()
    dense_cols = rng.standard_normal((n, 2))
    sig = np.asarray(x[:, :5].sum(axis=1)).ravel() / 100.0 + dense_cols[:, 0]
    y = (sig + 0.3 * rng.standard_normal(n) > 0.5).astype(np.float64)
    # stack two dense columns on as CSR too
    full = sp.hstack([x, sp.csr_matrix(dense_cols)]).tocsr()
    return full, y


def test_csr_matches_dense_binning():
    rng = np.random.default_rng(0)
    x, y = _sparse_window(rng, 5000)
    cfg = Config({"objective": "binary", "max_bin": 63})
    ds_sparse = BinnedDataset.construct_from_csr(
        x.indptr, x.indices, x.data, x.shape[1], cfg)
    ds_dense = BinnedDataset.construct_from_matrix(x.toarray(), cfg)
    assert ds_sparse.num_groups == ds_dense.num_groups
    np.testing.assert_array_equal(ds_sparse.binned, ds_dense.binned)
    for ms, md in zip(ds_sparse.bin_mappers, ds_dense.bin_mappers):
        np.testing.assert_array_equal(ms.bin_upper_bound, md.bin_upper_bound)


def test_csr_validation_alignment():
    rng = np.random.default_rng(1)
    x, y = _sparse_window(rng, 4000)
    xv, yv = _sparse_window(rng, 1000)
    cfg = Config({"objective": "binary", "max_bin": 63})
    train = BinnedDataset.construct_from_csr(
        x.indptr, x.indices, x.data, x.shape[1], cfg)
    valid = BinnedDataset.construct_from_csr(
        xv.indptr, xv.indices, xv.data, xv.shape[1], cfg, reference=train)
    ref = BinnedDataset.construct_from_matrix(xv.toarray(), cfg,
                                              reference=train)
    np.testing.assert_array_equal(valid.binned, ref.binned)


def test_windowed_retrain_harness():
    """Three windows of fresh-CSR retraining (the fork harness loop):
    each window constructs a Dataset from CSR, trains 50 iterations,
    and scores the next window.  Wall-clock per window must stay bounded
    (no cross-window state growth) and the model must beat chance."""
    rng = np.random.default_rng(2)
    times = []
    aucs = []
    windows = [_sparse_window(rng, 20000) for _ in range(4)]
    from sklearn.metrics import roc_auc_score
    for w in range(3):
        x, y = windows[w]
        t0 = time.perf_counter()
        ds = lgb_basic.Dataset(x, label=y,
                               params={"objective": "binary",
                                       "num_leaves": 31, "max_bin": 63,
                                       "learning_rate": 0.1,
                                       "verbosity": -1})
        bst = lgb_basic.Booster(params={"objective": "binary",
                                        "num_leaves": 31, "max_bin": 63,
                                        "learning_rate": 0.1,
                                        "verbosity": -1},
                                train_set=ds)
        for _ in range(50):
            bst.update()
        xn, yn = windows[w + 1]
        pred = bst.predict(xn)        # CSR prediction, chunked densify
        times.append(time.perf_counter() - t0)
        aucs.append(roc_auc_score(yn, pred))
    assert min(aucs) > 0.8, aucs
    # bounded per-window cost: the slowest window stays within 2.5x the
    # fastest (catches cross-window state accumulation / leaks)
    assert max(times) < 2.5 * min(times) + 1.0, times


def test_grower_cache_warm_window_zero_new_traces():
    """The retrain-every-window pattern builds a fresh DeviceGrower per
    window; the process-level program cache (ops/grow.py) must make the
    SECOND same-shaped window reuse the first window's jitted programs —
    zero new traces/compiles, counted through the obs jit tracker."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config

    params = {"objective": "binary", "device_growth": "on",
              "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
              "verbosity": -1}

    def window(seed):
        wrng = np.random.default_rng(seed)
        x = wrng.standard_normal((2000, 8)).astype(np.float32)
        y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
        cfg = Config(params)
        ds = BinnedDataset.construct_from_matrix(x, cfg)
        ds.metadata.set_label(y)
        bst = create_boosting(cfg)
        bst.init_train(ds)
        assert bst._grower is not None
        bst.train_chunked(4, chunk=2)
        bst._flush_pending()
        return bst

    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    try:
        reg = obs.registry()
        hits0 = reg.counter("grow.cache_hits")
        b1 = window(1)
        progs1 = b1._grower.programs
        compiles_after_w1 = sum(
            v["compiles"] for v in reg.snapshot()["jit"].values())
        b2 = window(2)
        compiles_after_w2 = sum(
            v["compiles"] for v in reg.snapshot()["jit"].values())
        # same programs object adopted (cache hit), zero new compiles
        assert b2._grower.programs is progs1
        assert reg.counter("grow.cache_hits") >= hits0 + 1
        assert compiles_after_w2 == compiles_after_w1, \
            reg.snapshot()["jit"]
        # the obs tracker can only see compiles it can attribute; the
        # underlying jax.jit caches are the ground truth.  The fused
        # program is the hazard: grad_fn is a STATIC argument, so a
        # fresh per-window closure would silently re-trace the whole
        # scan (DeviceGradFn's stable eq/hash is what prevents it)
        fused_sizes = {ln: tj._cache_size()
                       for ln, tj in progs1._fused.items()}
        assert fused_sizes and all(v == 1 for v in fused_sizes.values()), \
            fused_sizes
        # both windows actually trained (the cached programs served
        # window 2's different data through the argument-passed arrays)
        assert len(b1.models) == len(b2.models) == 4
    finally:
        if not was_enabled:
            obs.configure(enabled=False)


def test_sparse_dataset_never_densifies(monkeypatch):
    """The Dataset construction path must not call toarray() on sparse
    input (memory ~ nnz is the CSR-ingestion contract)."""
    rng = np.random.default_rng(3)
    x, y = _sparse_window(rng, 3000)
    called = {"n": 0}
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **k):
        called["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)
    ds = lgb_basic.Dataset(x, label=y, params={"objective": "binary"})
    ds.construct()
    assert called["n"] == 0
    assert ds._handle.binned is not None
