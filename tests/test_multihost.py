"""Multi-host pod-slice training (docs/Sharding.md, multi-controller).

Two layers:

* **Unit tests** (tier-1): the pure pieces of the pod contract — the
  bring-up parameter resolver, the pod row layout (contiguity +
  per-device bucket), the length-prefixed reference broadcast and its
  serialization, the row-span-filtered streaming round, and the
  ack/commit snapshot protocol — all in-process, no jax.distributed.
* **Pod scenarios** (``slow`` + one fast fail-fast case): N real OS
  processes under a localhost coordinator via
  tests/_multihost_worker.py, each rank forcing ``4 // hosts`` CPU
  devices so every leg runs the same 4-device global mesh.  Asserted:
  1-vs-2-vs-4-process tree BYTE-identity under ``grad_quant_bits=8``,
  bagging/feature_fraction host-invariance, mapper-broadcast layout
  equality, kill-one-host -> resume byte-identity, zero warm-window
  retraces per host, and bounded fail-fast on a dead coordinator.

Where the container cannot bring up multi-process jax (gloo missing,
jax.distributed unavailable), the workers report ``{"skip": reason}``
and the tests record it — environmental; the contract is validated on
real pod slices.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import _multihost_worker as mhw   # noqa: E402 — path set above

_WORKER = os.path.join(os.path.dirname(__file__),
                       "_multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pod(scenario, hosts, outdir, timeout=420,
             expected_exits=None):
    """Launch ``hosts`` worker ranks, wait for all, and return the
    per-rank JSON reports (None for a rank that wrote none, e.g.
    killA's victim).  Skips the calling test if any rank reports an
    environmental bring-up skip."""
    outdir = str(outdir)
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = []
    for rank in range(hosts):
        log = open(os.path.join(outdir, f"{scenario}_r{rank}.log"),
                   "w")
        procs.append((rank, log, subprocess.Popen(
            [sys.executable, _WORKER, scenario, str(rank),
             str(hosts), str(port), outdir],
            stdout=log, stderr=subprocess.STDOUT, env=env)))
    deadline = time.monotonic() + timeout
    exits = {}
    try:
        for rank, _, proc in procs:
            left = deadline - time.monotonic()
            exits[rank] = proc.wait(timeout=max(left, 1.0))
    except subprocess.TimeoutExpired:
        for _, _, proc in procs:
            proc.kill()
        raise AssertionError(
            f"pod scenario {scenario} ({hosts} hosts) timed out "
            f"after {timeout}s; see {outdir}/{scenario}_r*.log")
    finally:
        for _, log, _ in procs:
            log.close()
    reports = []
    for rank in range(hosts):
        path = os.path.join(outdir, f"{scenario}_r{rank}.json")
        reports.append(json.load(open(path))
                       if os.path.exists(path) else None)
    for rep in reports:
        if rep and "skip" in rep:
            pytest.skip(rep["skip"])
    expected = expected_exits or {r: 0 for r in range(hosts)}
    for rank, code in exits.items():
        assert code == expected.get(rank, 0), \
            (f"{scenario} rank {rank} exited {code} (expected "
             f"{expected.get(rank, 0)}); see "
             f"{outdir}/{scenario}_r{rank}.log")
    return reports


@pytest.fixture(scope="module")
def pod_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("multihost")
    mhw.write_csv(str(d))
    return d


@pytest.fixture(scope="module")
def baseline(pod_dir):
    """Single-process single_controller leg over the SAME csv/loader —
    the byte-identity reference for every pod leg."""
    sub = pod_dir / "base"
    sub.mkdir()
    os.link(mhw.data_path(str(pod_dir)), mhw.data_path(str(sub)))
    return _run_pod("train", 1, sub)[0]


# ---------------------------------------------------------------------------
# unit layer: bring-up params, row layout, broadcast, filtered round two,
# ack/commit protocol
# ---------------------------------------------------------------------------

def test_multihost_params_resolution(monkeypatch):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ops.shard import (ENV_HOST_RANK, ENV_NUM_HOSTS,
                                        multihost_params)
    from lightgbm_tpu.utils.log import LightGBMError
    assert multihost_params(Config({})) is None
    cfg = Config({"coordinator_address": "h0:1234", "num_hosts": 4,
                  "host_rank": 3})
    assert multihost_params(cfg) == ("h0:1234", 4, 3)
    # env fallback completes a partial config
    monkeypatch.setenv(ENV_NUM_HOSTS, "2")
    monkeypatch.setenv(ENV_HOST_RANK, "1")
    assert multihost_params(
        Config({"coordinator_address": "h0:1234"})) == ("h0:1234", 2, 1)
    monkeypatch.delenv(ENV_NUM_HOSTS)
    monkeypatch.delenv(ENV_HOST_RANK)
    # partial or malformed triples must raise, not guess
    with pytest.raises(LightGBMError, match="ALL of"):
        multihost_params(Config({"coordinator_address": "h0:1234"}))
    with pytest.raises(LightGBMError, match="out of range"):
        multihost_params(Config({"coordinator_address": "h0:1234",
                                 "num_hosts": 2, "host_rank": 2}))
    with pytest.raises(LightGBMError, match="host:port"):
        multihost_params(Config({"coordinator_address": "h0",
                                 "num_hosts": 2, "host_rank": 0}))


class _FakeDev:
    def __init__(self, pid, did):
        self.process_index = pid
        self.id = did


class _FakeMesh:
    def __init__(self, pids):
        arr = np.empty(len(pids), dtype=object)
        for i, p in enumerate(pids):
            arr[i] = _FakeDev(p, i)
        self.devices = arr


def test_process_row_span_contiguity():
    from lightgbm_tpu.ops.shard import process_row_span
    from lightgbm_tpu.utils.log import LightGBMError
    mesh = _FakeMesh([0, 0, 1, 1])
    assert process_row_span(mesh, 1000, process_index=0) == (0, 2000)
    assert process_row_span(mesh, 1000, process_index=1) == (2000, 4000)
    with pytest.raises(LightGBMError, match="owns no devices"):
        process_row_span(mesh, 1000, process_index=7)
    # interleaved device ownership breaks the streamed-slab contract
    with pytest.raises(LightGBMError, match="not contiguous"):
        process_row_span(_FakeMesh([0, 1, 0, 1]), 1000,
                         process_index=0)


def test_shard_local_rows_covers_global_rows():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ops.shard import shard_local_rows
    for n, d in [(2500, 4), (100_000, 4), (7, 2), (1, 4)]:
        for extra in ({}, {"grad_quant_bits": 8},
                      {"train_row_bucketing": False}):
            n_loc = shard_local_rows(n, d, Config(extra))
            assert n_loc * d >= n
            assert n_loc % 1 == 0 and n_loc > 0


def test_broadcast_blob_roundtrip(tmp_path):
    import threading
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel.network import broadcast_blob
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    cfg = Config({"network_timeout": 2, "network_retries": 5})
    payload = b"\x00mapper-reference\xff" * 1000
    got = {}

    def peer(rank):
        got[rank] = broadcast_blob(None, address=addr, num_hosts=3,
                                   rank=rank, config=cfg)

    threads = [threading.Thread(target=peer, args=(r,))
               for r in (1, 2)]
    for t in threads:
        t.start()
    out0 = broadcast_blob(payload, address=addr, num_hosts=3, rank=0,
                          config=cfg)
    for t in threads:
        t.join(timeout=30)
    assert out0 == payload
    assert got[1] == payload and got[2] == payload


def test_reference_broadcast_bytes_roundtrip(tmp_path):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.pipeline.bins import (reference_from_bytes,
                                            reference_layout_digest,
                                            reference_to_bytes)
    from lightgbm_tpu.utils.log import LightGBMError
    rng = np.random.default_rng(3)
    x = rng.standard_normal((400, 5))
    ds = BinnedDataset.construct_from_matrix(x, Config({"max_bin": 31}))
    blob = reference_to_bytes(ds, extra={"n_total": 400})
    skel, extra = reference_from_bytes(blob)
    assert extra == {"n_total": 400}
    assert reference_layout_digest(skel) == reference_layout_digest(ds)
    assert [m.num_bin for m in skel.bin_mappers] == \
        [m.num_bin for m in ds.bin_mappers]
    assert [g.feature_indices for g in skel.groups] == \
        [g.feature_indices for g in ds.groups]
    with pytest.raises(LightGBMError, match="magic mismatch"):
        reference_from_bytes(b"garbage-not-a-reference")


def test_round_two_row_span_filter(tmp_path):
    """The filtered round bins exactly the global block [lo, hi) at
    local coordinates, and parses every label."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.data.stream_loader import (_Format, _round_one,
                                                 _round_two)
    csv = str(tmp_path / "mini.csv")
    mhw_rows = 200
    rng = np.random.default_rng(5)
    x = rng.standard_normal((mhw_rows, 4))
    y = (x[:, 0] > 0).astype(float)
    with open(csv, "w") as fh:
        for i in range(mhw_rows):
            fh.write(",".join([repr(float(y[i]))]
                              + [repr(float(v)) for v in x[i]]) + "\n")
    cfg = Config({"two_round": True, "max_bin": 31})
    fmt = _Format(csv, cfg)
    sample, n_total, num_cols = _round_one(csv, fmt, cfg)
    full = BinnedDataset.construct_streaming_begin(
        sample, n_total, num_cols, cfg)
    full_label = _round_two(csv, fmt, full, num_cols, n_total)
    lo, hi = 64, 160
    part = BinnedDataset.construct_streaming_begin(
        np.zeros((0, num_cols)), hi - lo, num_cols, cfg,
        reference=full)
    part_label = _round_two(csv, fmt, part, num_cols, n_total,
                            row_span=(lo, hi))
    assert np.array_equal(part.binned, full.binned[lo:hi])
    assert np.array_equal(part_label, full_label)
    # a span past the real rows bins nothing but still parses labels
    tail = BinnedDataset.construct_streaming_begin(
        np.zeros((0, num_cols)), 64, num_cols, cfg, reference=full)
    tail_label = _round_two(csv, fmt, tail, num_cols, n_total,
                            row_span=(n_total + 64, n_total + 128))
    assert not tail.binned.any()
    assert np.array_equal(tail_label, full_label)


def test_pod_ack_commit_protocol(tmp_path):
    from lightgbm_tpu.robust import checkpoint as ck
    from lightgbm_tpu.utils.log import LightGBMError
    path = str(tmp_path / "snap.txt")
    score = np.arange(6, dtype=np.float32).reshape(1, 6)
    digest = ck.pod_state_digest("tree...", score, 3)
    assert digest == ck.pod_state_digest("tree...", score.copy(), 3)
    assert digest != ck.pod_state_digest("tree...", score, 4)
    # happy path: both hosts ack, host 0 commits, peer sees it
    ck.write_pod_ack(path, 0, digest)
    ck.write_pod_ack(path, 1, digest)
    ck.await_pod_acks(path, 2, digest, timeout_s=5.0)
    ck.clear_pod_acks(path, 2)
    ck.commit_pod(path, digest)
    assert ck.has_pod_commit(path)
    ck.await_pod_commit(path, digest, timeout_s=5.0)
    # a commit marker from an OLDER snapshot must not satisfy the wait
    with pytest.raises(LightGBMError, match="commit"):
        ck.await_pod_commit(path, "different-digest", timeout_s=0.3)
    # missing ack: timeout error NAMES the dead host
    os.remove(ck.pod_commit_path(path))
    ck.write_pod_ack(path, 0, digest)
    with pytest.raises(LightGBMError, match=r"no ack from host\(s\) "
                                            r"\[1\]"):
        ck.await_pod_acks(path, 2, digest, timeout_s=0.3)
    # diverged ack: refuse loudly, never time out silently
    ck.write_pod_ack(path, 1, "poisoned-digest")
    with pytest.raises(LightGBMError, match="diverged"):
        ck.await_pod_acks(path, 2, digest, timeout_s=5.0)


def test_multihost_forbids_machine_parallel_learner():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel import create_tree_learner
    from lightgbm_tpu.utils.log import LightGBMError
    cfg = Config({"tree_learner": "data", "num_machines": 2,
                  "data_sharding": "multi_controller",
                  "coordinator_address": "h0:1", "num_hosts": 2,
                  "host_rank": 0})
    with pytest.raises(LightGBMError, match="multi_controller"):
        create_tree_learner(cfg, None)


# ---------------------------------------------------------------------------
# pod scenarios (real processes)
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_dead_coordinator_fails_fast(tmp_path):
    """A rank whose coordinator never answers raises the bounded
    peer-probe error instead of hanging in initialize."""
    rep = _run_pod("deadcoord", 1, tmp_path, timeout=90)[0]
    assert rep["failfast_error"] is not None
    assert "unreachable" in rep["failfast_error"]
    assert rep["elapsed_s"] < 60.0


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_pod_byte_identity_2proc(pod_dir, baseline):
    reps = _run_pod("train", 2, pod_dir)
    assert reps[0]["trees"] == reps[1]["trees"], \
        "pod hosts emitted different trees"
    assert reps[0]["trees"] == baseline["trees"], \
        "2-process pod diverged from single-process single_controller"
    # mapper broadcast: every host adopted the identical layout
    digests = {baseline["layout_digest"]} | \
        {r["layout_digest"] for r in reps}
    assert len(digests) == 1
    # zero new traces on the warm same-shape window, per host
    assert [r["warm_new_compiles"] for r in reps] == [0, 0]
    assert reps[0]["hosts_gauge"] == 2
    assert (reps[0]["ingest_rows_per_s"] or 0) > 0


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_pod_byte_identity_4proc(pod_dir, baseline):
    reps = _run_pod("train", 4, pod_dir)
    trees = {r["trees"] for r in reps}
    assert len(trees) == 1
    assert trees.pop() == baseline["trees"]
    assert [r["warm_new_compiles"] for r in reps] == [0, 0, 0, 0]


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_pod_bagging_feature_fraction_host_invariant(pod_dir):
    sub = pod_dir / "bagff1"
    sub.mkdir()
    os.link(mhw.data_path(str(pod_dir)), mhw.data_path(str(sub)))
    one = _run_pod("bagff", 1, sub)[0]
    two = _run_pod("bagff", 2, pod_dir)
    assert two[0]["trees"] == two[1]["trees"] == one["trees"], \
        "bagging/feature_fraction draws depend on the host count"


@pytest.mark.slow
@pytest.mark.timeout(1200)
def test_pod_kill_one_host_resume_byte_identical(pod_dir, baseline):
    kill_dir = pod_dir / "kill"
    kill_dir.mkdir()
    os.link(mhw.data_path(str(pod_dir)), mhw.data_path(str(kill_dir)))
    # phase A: last rank dies before acking the iter-4 snapshot
    reps = _run_pod("killA", 2, kill_dir,
                    expected_exits={0: 0, 1: mhw.KILLED_EXIT})
    r0 = reps[0]
    assert r0["commit2"] is True, "iter-2 snapshot never committed"
    assert r0["commit4"] is False, \
        "iter-4 snapshot committed without the victim's ack"
    assert "no ack from host(s) [1]" in r0["ack_timeout_error"]
    # phase B: fresh pod refuses the uncommitted snapshot, resumes the
    # committed one, finishes byte-identical to the uninterrupted run
    reps = _run_pod("killB", 2, kill_dir)
    for rep in reps:
        assert rep["uncommitted_refused"] is True
        assert rep["commit2"] is True and rep["commit4"] is False
        assert rep["trees"] == baseline["trees"], \
            "resume after host death diverged from the straight run"
