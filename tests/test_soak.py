"""Composed chaos-soak suite (``lightgbm_tpu/soak/``; docs/Soak.md).

Fast cases pin the deterministic scenario layer — JSON round-trips,
seed-keyed timeline compilation, the single up-front fault-spec
string, shape-stable window payloads — and the verdict builder against
synthetic driver outcomes (each gate must both pass on a clean outcome
and FIRE on the matching defect).  The ``slow``-marked cases run the
real composed soak end to end on CPU: same-seed replay must agree on
the ``strip_volatile`` projection byte-for-byte, a mid-window kill
must resume byte-identical at fleet scale, and the persistent
device-death flavor must FAIL the availability gate (the SLO engine
proving it can fire, not just pass).
"""

import json
import tempfile

import numpy as np
import pytest

from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.soak import (SoakScenario, build_verdict,
                               compile_timeline, fault_spec,
                               run_and_report, strip_volatile,
                               timeline_digest)
from lightgbm_tpu.soak.scenario import (kill_points, poison_ticks)

# full end-to-end runs are expensive (~15 s each); slow cases share
# them through this cache so replay determinism, kill identity and the
# PASS verdict are asserted on the same two runs
_RUNS = {}


def _default_run(tag):
    if tag not in _RUNS:
        sc = SoakScenario()
        wd = tempfile.mkdtemp(prefix=f"soak_test_{tag}_")
        _RUNS[tag] = run_and_report(sc, workdir=wd)
    return _RUNS[tag]


# ---------------------------------------------------------------------------
# scenario layer (tier-1)
# ---------------------------------------------------------------------------

def test_scenario_json_roundtrip():
    sc = SoakScenario(tenants=3, windows=4, cadence=(1, 2, 1),
                      kills=2, seed=11)
    doc = sc.to_json()
    assert doc["cadence"] == [1, 2, 1]
    back = SoakScenario.from_json(json.loads(json.dumps(doc)))
    assert back == sc
    with pytest.raises(LightGBMError, match="unknown keys"):
        SoakScenario.from_json({"tenants": 2, "typo_key": 1})


def test_scenario_validation():
    with pytest.raises(LightGBMError, match="windows >= 2"):
        SoakScenario(windows=1, kills=1).validate()
    with pytest.raises(LightGBMError, match="2\\*sample_rows"):
        SoakScenario(requests_per_window=1024,
                     sample_rows=1024).validate()
    with pytest.raises(LightGBMError, match="one entry per tenant"):
        SoakScenario(cadence=(1,)).validate()
    with pytest.raises(LightGBMError, match=">= 2 "):
        # every tenant retrains only window 0 -> no kill candidate
        SoakScenario(windows=2, cadence=(2, 2), kills=1).validate()
    assert SoakScenario().validate() is not None


def test_schedule_cadence():
    sc = SoakScenario(tenants=2, windows=6, cadence=(1, 3), kills=0)
    assert sc.schedule(0) == [0, 1, 2, 3, 4, 5]
    assert sc.schedule(1) == [0, 3]


def test_timeline_deterministic_and_seed_keyed():
    sc = SoakScenario()
    a, b = compile_timeline(sc), compile_timeline(sc)
    assert [e.to_json() for e in a] == [e.to_json() for e in b]
    assert timeline_digest(sc, a) == timeline_digest(sc, b)
    other = SoakScenario(seed=8)
    assert timeline_digest(sc) != timeline_digest(other)
    # kills target window >= 1 within the tenant's own schedule
    for e in a:
        if e.kind == "kill":
            assert e.window >= 1
            assert e.window in sc.schedule(e.tenant)


def test_fault_spec_single_arming_string():
    sc = SoakScenario()  # 1 kill, 1 poison, 1 dead peer, 1 clock skew
    events = compile_timeline(sc)
    spec = fault_spec(sc, events)
    assert "soak.kill:n=1" in spec
    assert "soak.load:after=" in spec and ":error=timeout" in spec
    assert "soak.clock:after=1:n=1" in spec
    assert len(poison_ticks(events)) == 1
    kp = kill_points(events)
    assert sum(len(v) for v in kp.values()) == 1
    persist = SoakScenario(device_deaths=1, device_death_persist=True)
    assert ":persist" in fault_spec(persist)
    burst = SoakScenario(device_deaths=2)
    assert "serve.fleet.dispatch:after=" in fault_spec(burst)
    assert ":n=4" in fault_spec(burst)  # 2 deaths x burst 2


def test_window_payload_shape_stable_and_pure():
    sc = SoakScenario()
    a = sc.window_payload(0, 0)
    b = sc.window_payload(0, 0)
    np.testing.assert_array_equal(a.label, b.label)
    for x, y in zip(a.csr[:3], b.csr[:3]):
        np.testing.assert_array_equal(x, y)
    # every (tenant, window) trims to exactly sample_rows rows of the
    # same feature count -> shape-stable retrains (zero-retrace gate)
    c = sc.window_payload(1, 2)
    assert a.num_rows == c.num_rows == sc.sample_rows
    assert a.csr[3] == c.csr[3]
    # distinct windows are distinct workloads
    assert not np.array_equal(a.label, c.label)


# ---------------------------------------------------------------------------
# verdict builder on synthetic outcomes (tier-1)
# ---------------------------------------------------------------------------

def _synthetic_outcome():
    sc = SoakScenario(tenants=1, windows=2, kills=1, poison_batches=0,
                      dead_peers=0, clock_skews=0).validate()
    events = compile_timeline(sc)
    win = [{"window": 0, "swap_same_shape": None, "train_s": 1.0,
            "rows_trained": sc.sample_rows, "tenant": 0},
           {"window": 1, "swap_same_shape": True, "train_s": 1.0,
            "rows_trained": sc.sample_rows, "tenant": 0}]
    return {
        "scenario": sc.to_json(),
        "fault_spec": fault_spec(sc, events),
        "timeline": [e.to_json() for e in events],
        "timeline_digest": timeline_digest(sc, events),
        "slo": {"ok": True, "objectives": [
            {"name": "availability", "comparator": ">=",
             "target": 0.999, "observed": 1.0, "ok": True}],
            "counts": {"dark_fraction": 0.0}},
        "windows": {"0": win},
        "kills": [{"tenant": 0, "window": 1, "payload_index": 1,
                   "checkpoint_window": 0, "resumed": True}],
        "byte_identity": [{"tenant": 0, "kills": 1, "resumed": 1,
                           "byte_identical": True}],
        "tenant_errors": {},
        "load": {"submitted": 10, "answered": 10, "rejected": 0,
                 "poison_sent": 0, "dead_peer_timeouts": 0},
        "clock_faults_fired": 0,
        "counters": {"serve.fleet.swap_shape_changes": 0},
        "export": {"flushes": 3, "dropped": 0, "write_errors": 0},
        "elapsed_s": 2.5, "started_unix": 1.0, "evaluated_unix": 3.5,
    }


def test_build_verdict_clean_outcome_passes():
    v = build_verdict(_synthetic_outcome())
    assert v["ok"] is True
    assert all(g["ok"] for g in v["gates"].values())
    assert isinstance(v["chip_pending"], bool)
    # off-TPU the throughput gate is informational, value still carried
    assert v["gates"]["throughput"]["train_s_per_1M_sampled_rows"] > 0


@pytest.mark.parametrize("mutate,gate", [
    (lambda o: o["export"].update(dropped=2), "export"),
    (lambda o: o["byte_identity"][0].update(byte_identical=False),
     "resume_byte_identity"),
    (lambda o: o["kills"].clear(), "resume_byte_identity"),
    (lambda o: o["windows"]["0"][1].update(swap_same_shape=False),
     "zero_retrace_swaps"),
    (lambda o: o["tenant_errors"].update({"0": "boom"}), "completed"),
    (lambda o: o["slo"]["objectives"][0].update(ok=False,
                                                observed=0.9),
     "availability"),
])
def test_build_verdict_gate_fires(mutate, gate):
    o = _synthetic_outcome()
    mutate(o)
    if gate == "availability":
        o["slo"]["ok"] = False
    v = build_verdict(o)
    assert v["gates"][gate]["ok"] is False
    assert v["ok"] is False


def test_strip_volatile_is_replay_stable_projection():
    v = build_verdict(_synthetic_outcome())
    s = strip_volatile(v)
    blob = json.dumps(s, sort_keys=True)
    assert "elapsed_s" not in s and "counters" not in s
    assert "train_s" not in blob and "started_unix" not in blob
    assert s["timeline_digest"] == v["timeline_digest"]
    assert s["gates"] == {k: True for k in v["gates"]}
    # volatile fields must not leak through the kill records either
    v2 = build_verdict(_synthetic_outcome())
    v2["elapsed_s"] = 99.0
    v2["kills"][0]["resume_s"] = 1.23
    assert json.dumps(strip_volatile(v2), sort_keys=True) == blob


# ---------------------------------------------------------------------------
# composed end-to-end runs (slow; scripts/check.sh dedicated step)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_default_scenario_passes_on_cpu():
    v = _default_run("a")
    assert v["ok"] is True, json.dumps(v["gates"], indent=1,
                                       default=str)
    assert v["chip_pending"] is True  # CPU container honesty flag
    assert v["gates"]["availability"]["ok"] is True
    assert v["gates"]["zero_retrace_swaps"]["ok"] is True
    assert v["gates"]["export"]["stats"]["dropped"] == 0


@pytest.mark.slow
def test_soak_kill_resumes_byte_identical_at_fleet_scale():
    v = _default_run("a")
    assert len(v["kills"]) == 1
    k = v["kills"][0]
    assert k["resumed"] is True and k["window"] >= 1
    ident = v["gates"]["resume_byte_identity"]["tenants"]
    assert ident and all(r["byte_identical"] for r in ident)


@pytest.mark.slow
def test_soak_same_seed_replay_identical():
    a, b = _default_run("a"), _default_run("b")
    assert a["timeline"] == b["timeline"]
    assert a["timeline_digest"] == b["timeline_digest"]
    assert (json.dumps(strip_volatile(a), sort_keys=True)
            == json.dumps(strip_volatile(b), sort_keys=True))
    # wall timings DO differ run to run; the projection must not
    assert a["elapsed_s"] != b["elapsed_s"] or True


@pytest.mark.slow
def test_soak_persistent_device_death_fails_availability():
    sc = SoakScenario(tenants=1, windows=2, kills=0, poison_batches=0,
                      dead_peers=0, clock_skews=0, device_deaths=1,
                      device_death_persist=True)
    wd = tempfile.mkdtemp(prefix="soak_test_fail_")
    v = run_and_report(sc, workdir=wd)
    assert v["gates"]["availability"]["ok"] is False, json.dumps(
        v["gates"]["availability"], default=str)
    assert v["gates"]["slo"]["ok"] is False
    assert v["ok"] is False
