"""Multi-tenant model-fleet serving suite (``serve/fleet.py``).

Pins the fleet acceptance contract (docs/Serving.md "Model fleets"):
per-tenant routing AND scores byte-identical to each tenant's solo
``PackedEnsemble`` (missing modes, categorical bitsets, file-loaded
boosters, mixed ``tenant_ids`` batches), tenant hot-swap as a
zero-retrace device index write while the other tenants keep serving,
per-replica degrade-to-host byte-exactness, the bf16 value variant's
routing-exact/values-quantize split, and the host-fallback tenant
interleave (``ModelMeta.host_raw``'s ``out[i % num_model]``) against
the packed tree order for multiclass and RF-averaged tenants.
"""

import numpy as np
import pytest

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.robust import faults
from lightgbm_tpu.robust.retry import CircuitBreaker
from lightgbm_tpu.serve import (FleetServer, PredictionServer,
                                fleet_predict_leaves,
                                fleet_predict_scores, pack_fleet,
                                predict_leaves, predict_scores)
from lightgbm_tpu.utils.log import LightGBMError


def _train(params, x, y, n_iters=5, categorical=()):
    cfg = Config({"verbosity": -1, "device_growth": "on",
                  "num_leaves": 15, "min_data_in_leaf": 5,
                  "max_depth": 6, **params})
    ds = BinnedDataset.construct_from_matrix(x, cfg, list(categorical))
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    bst._flush_pending()
    return bst


def _binary_booster(seed, nf=8, n_iters=5, nan_frac=0.05, **params):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((1500, nf)).astype(np.float32)
    if nan_frac:
        x[rng.random(x.shape) < nan_frac] = np.nan
    y = (np.nan_to_num(x[:, 0]) + np.abs(np.nan_to_num(x[:, 1]))
         > 0.4).astype(np.float32)
    return _train({"objective": "binary", **params}, x, y, n_iters)


def _query(seed, nf=8, n=400):
    rng = np.random.default_rng(seed)
    xq = rng.standard_normal((n, nf))
    xq[rng.random(xq.shape) < 0.1] = np.nan
    return xq


@pytest.fixture(scope="module")
def trio():
    """Three same-config binary tenants + their solo packs + fleet."""
    boosters = [_binary_booster(s) for s in (1, 2, 3)]
    fl, packs = pack_fleet(boosters)
    return boosters, packs, fl


def _assert_tenant_identity(fl, packs, xq):
    """Leaves AND scores of every tenant byte-identical to its solo
    pack — the core fleet contract."""
    for m, pe in enumerate(packs):
        np.testing.assert_array_equal(
            fleet_predict_leaves(fl, m, xq)[:, :pe.num_trees],
            predict_leaves(pe, xq))
        np.testing.assert_array_equal(
            fleet_predict_scores(fl, m, xq), predict_scores(pe, xq))


def test_fleet_per_tenant_byte_identity(trio):
    _, packs, fl = trio
    _assert_tenant_identity(fl, packs, _query(0))


def test_fleet_missing_mode_tenant_mix():
    """A zero_as_missing tenant stacked next to NaN-missing tenants:
    each keeps its own missing semantics, byte-identical to solo."""
    rng = np.random.default_rng(11)
    xz = rng.standard_normal((1500, 8)).astype(np.float32)
    xz[rng.random(xz.shape) < 0.3] = 0.0
    yz = (xz[:, 0] + xz[:, 1] > 0.3).astype(np.float32)
    zb = _train({"objective": "binary", "zero_as_missing": True},
                xz, yz)
    boosters = [_binary_booster(1), zb]
    fl, packs = pack_fleet(boosters)
    xq = _query(5)
    xq[rng.random(xq.shape) < 0.2] = 0.0
    xq[rng.random(xq.shape) < 0.05] = 1e-40   # inside the zero window
    _assert_tenant_identity(fl, packs, xq)


def test_fleet_categorical_tenants():
    """Tenants with DIFFERENT categorical bitsets (different word
    counts -> the word-pad path) route byte-identically to solo."""
    def cat_booster(seed):
        rng = np.random.default_rng(seed)
        n = 2000
        cat = rng.integers(0, 12, n)
        x = np.column_stack([
            cat.astype(np.float32),
            rng.standard_normal(n).astype(np.float32),
            rng.standard_normal(n).astype(np.float32)])
        effect = rng.standard_normal(12) * 2.0
        y = (effect[cat] + x[:, 1]).astype(np.float32)
        return _train({"objective": "regression", "num_leaves": 31,
                       "min_data_in_leaf": 40,
                       "min_gain_to_split": 1e-3},
                      x, y, n_iters=4, categorical=[0])

    boosters = [cat_booster(13), cat_booster(29)]
    assert all(any(t.num_cat > 0 for t in b.models) for b in boosters)
    fl, packs = pack_fleet(boosters)
    rng = np.random.default_rng(7)
    xq = np.column_stack([
        rng.integers(-3, 40, 600).astype(np.float64),   # incl. unseen
        rng.standard_normal(600), rng.standard_normal(600)])
    xq[rng.random(600) < 0.1, 0] = np.nan
    _assert_tenant_identity(fl, packs, xq)


def test_fleet_file_loaded_tenant(trio):
    """A tenant loaded from a model STRING (no train_set) serves
    byte-identically to its solo pack — raw-value packing end to end."""
    boosters, _, _ = trio
    loaded = GBDT.load_model_from_string(boosters[0].model_to_string())
    assert loaded.train_set is None
    fl, packs = pack_fleet([loaded, boosters[1]])
    _assert_tenant_identity(fl, packs, _query(2))


def test_fleet_mixed_tenant_batch(trio):
    """A mixed tenant_ids batch answers every row exactly as that
    tenant's solo pack/server would — scores AND converted outputs."""
    boosters, packs, fl = trio
    xq = _query(3)
    rng = np.random.default_rng(4)
    tids = rng.integers(0, len(packs), xq.shape[0]).astype(np.int32)
    mixed = fleet_predict_scores(fl, tids, xq)
    fs = FleetServer(boosters)
    out = fs.predict(tids, xq)
    for m, pe in enumerate(packs):
        rows = np.nonzero(tids == m)[0]
        np.testing.assert_array_equal(mixed[:, rows],
                                      predict_scores(pe, xq[rows]))
        np.testing.assert_array_equal(
            out[rows], PredictionServer(boosters[m]).predict(xq[rows]))


def test_fleet_swap_zero_retrace_while_others_serve(trio):
    """The acceptance gate in miniature: after warmup, retraining one
    tenant swaps in as a device index write with ZERO new jit compiles
    while the other tenants keep answering byte-identically."""
    from lightgbm_tpu import obs

    boosters, packs, _ = trio
    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    try:
        reg = obs.registry()
        fs = FleetServer(boosters)
        xq = _query(6)
        fs.warmup([xq.shape[0]])
        fs.predict(0, xq)

        def compiles():
            return sum(v["compiles"]
                       for v in reg.snapshot()["jit"].values())

        warm = compiles()
        swaps0 = reg.counter("serve.fleet.swaps")
        before2 = predict_scores(packs[2], xq)
        for seed in (21, 22):
            assert fs.swap_tenant(1, _binary_booster(seed)) is True
            np.testing.assert_array_equal(fs.predict(2, xq, True),
                                          before2[0])
            fs.predict(1, xq)
        assert compiles() == warm, reg.snapshot()["jit"]
        assert reg.counter("serve.fleet.swaps") == swaps0 + 2
    finally:
        if not was_enabled:
            obs.configure(enabled=False)


def test_fleet_swap_shape_growth(trio):
    """A retrained tenant that outgrows the fleet pads re-pads the
    whole fleet (reported as a shape change) and still serves every
    tenant byte-identically to solo."""
    boosters, _, _ = trio
    fs = FleetServer(boosters)
    big = _binary_booster(31, n_iters=9)   # 9 iters > the 8-tree pad
    assert fs.swap_tenant(1, big) is False
    xq = _query(8)
    _, packs = pack_fleet([boosters[0], big, boosters[2]])
    _assert_tenant_identity(fs.fleet, packs, xq)


def test_fleet_per_replica_degrade_to_host(trio):
    """Per-replica degradation: a dead device path on replica 0 trips
    only replica 0's breaker; its answers come from the host walk
    BYTE-identical to each tenant's Booster.predict, and replica 1
    keeps the device path."""
    boosters, packs, _ = trio
    fs = FleetServer(
        boosters, replicas=2,
        breaker_factory=lambda i: CircuitBreaker(
            failure_threshold=1, reprobe_interval_s=60.0))
    xq = _query(9)
    rng = np.random.default_rng(10)
    tids = rng.integers(0, len(boosters), xq.shape[0]).astype(np.int32)
    want_host = np.empty(xq.shape[0], np.float64)
    for m, b in enumerate(boosters):
        rows = np.nonzero(tids == m)[0]
        b.config.device_predict = "off"
        want_host[rows] = b.predict(xq[rows])
    faults.configure("serve.fleet.dispatch:persist")
    try:
        got = fs.predict(tids, xq, replica=0)
        np.testing.assert_array_equal(got, want_host)
        assert fs.degraded_replicas() == [0]
    finally:
        faults.clear()
    # replica 1 never tripped: device path, matches solo device scores
    dev = fs.predict(tids, xq, raw_score=True, replica=1)
    for m, pe in enumerate(packs):
        rows = np.nonzero(tids == m)[0]
        np.testing.assert_array_equal(dev[rows],
                                      predict_scores(pe, xq[rows])[0])
    assert fs.degraded_replicas() == [0]
    # replica 0 stays dark (re-probe window far out) and stays exact
    np.testing.assert_array_equal(fs.predict(tids, xq, replica=0),
                                  want_host)


def test_fleet_bf16_values_quantize_routing_exact(trio):
    """value_dtype=bf16: leaf ROUTING identical to the f32 fleet and
    to solo packs; accumulated VALUES quantize (close, not equal)."""
    boosters, packs, _ = trio
    fs = FleetServer(boosters, value_dtype="bf16")
    xq = _query(12)
    for m, pe in enumerate(packs):
        np.testing.assert_array_equal(
            fleet_predict_leaves(fs.fleet, m, xq)[:, :pe.num_trees],
            predict_leaves(pe, xq))
        sq = fleet_predict_scores(fs.fleet, m, xq)
        ss = predict_scores(pe, xq)
        np.testing.assert_allclose(sq, ss, rtol=0.05, atol=0.05)
        assert not np.array_equal(sq, ss)   # it really quantized
    assert str(fs.fleet.leaf_value.dtype) == "bfloat16"


def test_fleet_multiclass_and_rf_host_interleave():
    """Regression for the host-fallback tenant interleave
    (``ModelMeta.host_raw``'s ``out[i % num_model]``) against the
    packed tree order, with M>1 stacked tenants: multiclass ensembles
    (num_model=3) and RF averaging must answer BYTE-identically to
    ``Booster.predict``'s host path when the device is dark."""
    rng = np.random.default_rng(40)

    def mc_booster(seed):
        r = np.random.default_rng(seed)
        x = r.standard_normal((1500, 6)).astype(np.float32)
        y = np.digitize(x[:, 0] + 0.5 * x[:, 1],
                        [-0.5, 0.5]).astype(np.float32)
        return _train({"objective": "multiclass", "num_class": 3},
                      x, y, 4)

    def rf_booster(seed):
        r = np.random.default_rng(seed)
        x = r.standard_normal((1500, 6)).astype(np.float32)
        y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
        return _train({"objective": "binary", "boosting": "rf",
                       "bagging_freq": 1, "bagging_fraction": 0.7},
                      x, y, 4)

    for make in (mc_booster, rf_booster):
        boosters = [make(41), make(42)]
        fs = FleetServer(
            boosters,
            breaker_factory=lambda i: CircuitBreaker(
                failure_threshold=1, reprobe_interval_s=60.0))
        xq = rng.standard_normal((300, 6))
        tids = rng.integers(0, 2, 300).astype(np.int32)
        # device answers first (interleave must match the packed order
        # up to f32 accumulation)
        dev = fs.predict(tids, xq)
        faults.configure("serve.fleet.dispatch:persist")
        try:
            got = fs.predict(tids, xq)
        finally:
            faults.clear()
        want = np.empty_like(np.asarray(got))
        for m, b in enumerate(boosters):
            rows = np.nonzero(tids == m)[0]
            b.config.device_predict = "off"
            want[rows] = b.predict(xq[rows])
        np.testing.assert_array_equal(got, want)
        np.testing.assert_allclose(dev, want, rtol=1e-4, atol=1e-6)


def test_tenant_handle_surface(trio):
    """TenantHandle: the solo-server surface over one tenant (the
    pipeline's swap target) — predict/swap/_model route to the fleet."""
    boosters, packs, _ = trio
    fs = FleetServer(boosters)
    h = fs.tenant(2)
    xq = _query(14)
    np.testing.assert_array_equal(h.predict(xq), fs.predict(2, xq))
    assert h._model is fs._snapshot().metas[2]
    nb = _binary_booster(51)
    assert h.swap(nb) is True
    np.testing.assert_array_equal(h.predict(xq),
                                  PredictionServer(nb).predict(xq))
    with pytest.raises(LightGBMError, match="out of range"):
        fs.tenant(3)


def test_fleet_submit_round_robin(trio):
    """submit() coalesces per replica and resolves each Future to
    exactly what predict() returns for those (tenant_ids, rows)."""
    boosters, _, _ = trio
    fs = FleetServer(boosters, replicas=2, max_wait_ms=5.0)
    rng = np.random.default_rng(15)
    queries = [(m, rng.standard_normal((n, 8)))
               for m, n in ((0, 17), (1, 64), (2, 33))]
    with fs:
        futs = [fs.submit(m, q) for m, q in queries]
        got = [f.result(timeout=30) for f in futs]
    for (m, q), g in zip(queries, got):
        np.testing.assert_allclose(g, fs.predict(m, q),
                                   rtol=1e-6, atol=1e-7)
    with pytest.raises(LightGBMError):
        fs.submit(0, queries[0][1])   # workers stopped


def test_fleet_input_errors(trio):
    boosters, _, _ = trio
    fs = FleetServer(boosters)
    with pytest.raises(LightGBMError, match="tenant_ids"):
        fs.predict(np.array([0, 1]), np.zeros((3, 8)))   # length mismatch
    with pytest.raises(LightGBMError, match=r"\[0, 3\)"):
        fs.predict(7, np.zeros((3, 8)))                  # bad tenant
    with pytest.raises(LightGBMError, match="features"):
        fs.predict(0, np.zeros((3, 2)))                  # too narrow
    assert fs.degraded_replicas() == []                  # no breaker hit
    with pytest.raises(LightGBMError, match="at least one tenant"):
        FleetServer([])
    with pytest.raises(LightGBMError, match="value_dtype"):
        FleetServer(boosters, value_dtype="fp8")
