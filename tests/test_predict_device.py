"""Device-traversal batch prediction matches the host tree walk.

GBDT.predict_raw routes large batches through binning + on-device
traversal (_predict_raw_device); these tests pin agreement with the
host Tree.predict path — leaf routing exactly, values to float32
accumulation tolerance — including NaN routing and multiclass.
"""

import numpy as np

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset


def _train(params, x, y, n_iters=10):
    cfg = Config({"verbosity": -1, "device_growth": "on",
                  "num_leaves": 15, "min_data_in_leaf": 5, **params})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    return bst


def _compare(bst, xq, monkeypatch):
    host = bst.predict_raw(xq.astype(np.float64))
    monkeypatch.setattr(type(bst), "DEVICE_PREDICT_ROWS", 1)
    dev = bst.predict_raw(xq.astype(np.float64))
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_device_predict_matches_host_binary(monkeypatch):
    rng = np.random.default_rng(4)
    x = rng.standard_normal((3000, 8)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.4).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y)
    xq = rng.standard_normal((500, 8)).astype(np.float64)
    xq[rng.random(xq.shape) < 0.1] = np.nan   # exercise missing routing
    _compare(bst, xq, monkeypatch)


def test_device_predict_matches_host_multiclass(monkeypatch):
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2500, 6)).astype(np.float32)
    y = (np.digitize(x[:, 0] + 0.5 * x[:, 1],
                     [-0.5, 0.5])).astype(np.float32)
    bst = _train({"objective": "multiclass", "num_class": 3}, x, y, 6)
    xq = rng.standard_normal((400, 6)).astype(np.float64)
    _compare(bst, xq, monkeypatch)


def test_device_predict_respects_iteration_window(monkeypatch):
    rng = np.random.default_rng(6)
    x = rng.standard_normal((2000, 5)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y, 8)
    xq = rng.standard_normal((300, 5)).astype(np.float64)
    host = bst.predict_raw(xq, num_iteration=3, start_iteration=2)
    monkeypatch.setattr(type(bst), "DEVICE_PREDICT_ROWS", 1)
    dev = bst.predict_raw(xq, num_iteration=3, start_iteration=2)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)
