"""Packed-forest batch prediction matches the host tree walk.

GBDT.predict_raw routes large batches (``device_predict=auto`` with
``device_predict_min_rows``, or ``force``) through the packed-ensemble
device kernel (serve/packed.py); these tests pin agreement with the
host Tree.predict path — leaf routing exactly, values to float32
accumulation tolerance — including NaN routing and multiclass.  The
full routing/parity suite lives in tests/test_serve.py.
"""

import numpy as np

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset


def _train(params, x, y, n_iters=10):
    cfg = Config({"verbosity": -1, "device_growth": "on",
                  "num_leaves": 15, "min_data_in_leaf": 5, **params})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    return bst


def _compare(bst, xq):
    bst.config.device_predict = "off"
    host = bst.predict_raw(xq.astype(np.float64))
    bst.config.device_predict = "force"
    dev = bst.predict_raw(xq.astype(np.float64))
    bst.config.device_predict = "auto"
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_device_predict_matches_host_binary():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((3000, 8)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.4).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y)
    xq = rng.standard_normal((500, 8)).astype(np.float64)
    xq[rng.random(xq.shape) < 0.1] = np.nan   # exercise missing routing
    _compare(bst, xq)


def test_device_predict_matches_host_multiclass():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((2500, 6)).astype(np.float32)
    y = (np.digitize(x[:, 0] + 0.5 * x[:, 1],
                     [-0.5, 0.5])).astype(np.float32)
    bst = _train({"objective": "multiclass", "num_class": 3}, x, y, 6)
    xq = rng.standard_normal((400, 6)).astype(np.float64)
    _compare(bst, xq)


def test_device_predict_respects_iteration_window():
    rng = np.random.default_rng(6)
    x = rng.standard_normal((2000, 5)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = _train({"objective": "binary"}, x, y, 8)
    xq = rng.standard_normal((300, 5)).astype(np.float64)
    bst.config.device_predict = "off"
    host = bst.predict_raw(xq, num_iteration=3, start_iteration=2)
    bst.config.device_predict = "force"
    dev = bst.predict_raw(xq, num_iteration=3, start_iteration=2)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)


def test_device_predict_min_rows_param_routes():
    """The documented param replaces the old DEVICE_PREDICT_ROWS class
    constant: auto routing obeys it in both directions."""
    rng = np.random.default_rng(7)
    x = rng.standard_normal((1500, 5)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = _train({"objective": "binary",
                  "device_predict_min_rows": 100}, x, y, 4)
    assert bst.config.device_predict_min_rows == 100
    xq = rng.standard_normal((200, 5)).astype(np.float64)
    assert bst._device_predict_wanted(200, None)          # >= threshold
    assert not bst._device_predict_wanted(99, None)       # below it
    assert not bst._device_predict_wanted(200, (1, None))  # early stop
    # and the routed results agree
    dev = bst.predict_raw(xq)
    bst.config.device_predict = "off"
    host = bst.predict_raw(xq)
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-6)
