"""LGBM_* C-API shim: the fork harness's call pattern, ported verbatim.

The reference harness (src/test.cpp:243-298) trains a fresh booster per
trace window through LGBM_DatasetCreateFromCSR / LGBM_DatasetSetField /
LGBM_BoosterCreate / LGBM_BoosterUpdateOneIter and evaluates the next
window through LGBM_BoosterPredictForCSR (src/test.cpp:211-241).  These
tests drive the shim through exactly those entry points.
"""

import numpy as np
import scipy.sparse as sp

from lightgbm_tpu import c_api as C


def _window(rng, n, nf=20, density=0.2):
    x = sp.random(n, nf, density=density, random_state=rng,
                  data_rvs=lambda k: rng.exponential(50.0, k)).tocsr()
    sig = np.asarray(x[:, :5].sum(axis=1)).ravel() / 100.0
    y = (sig + 0.3 * rng.standard_normal(n) > 0.35).astype(np.float32)
    return x, y


def _create_dataset(x, y, params="objective=binary num_leaves=15 "
                    "min_data_in_leaf=5 verbosity=-1", reference=None):
    ds = C.Ref()
    rc = C.LGBM_DatasetCreateFromCSR(
        x.indptr.astype(np.int32), C.C_API_DTYPE_INT32,
        x.indices.astype(np.int32), x.data.astype(np.float64),
        C.C_API_DTYPE_FLOAT64, len(x.indptr), x.nnz, x.shape[1],
        params, reference, ds)
    assert rc == 0, C.LGBM_GetLastError()
    rc = C.LGBM_DatasetSetField(ds.value, "label", y, len(y),
                                C.C_API_DTYPE_FLOAT32)
    assert rc == 0, C.LGBM_GetLastError()
    return ds.value


def test_fork_harness_window_loop():
    """Two windows of trainModel/evaluateModel via the C API surface."""
    rng = np.random.default_rng(0)
    windows = [_window(rng, 3000) for _ in range(3)]
    aucs = []
    for w in range(2):
        x, y = windows[w]
        ds = _create_dataset(x, y)
        bst = C.Ref()
        assert C.LGBM_BoosterCreate(
            ds, "objective=binary num_leaves=15 min_data_in_leaf=5 "
            "verbosity=-1", bst) == 0, C.LGBM_GetLastError()
        fin = C.Ref()
        for _ in range(30):
            assert C.LGBM_BoosterUpdateOneIter(bst.value, fin) == 0
            if fin.value:
                break
        it = C.Ref()
        assert C.LGBM_BoosterGetCurrentIteration(bst.value, it) == 0
        assert it.value >= 1
        # evaluateModel on the NEXT window (fp/fn sweep in the harness)
        xn, yn = windows[w + 1]
        out_len = C.Ref()
        assert C.LGBM_BoosterCalcNumPredict(
            bst.value, xn.shape[0], C.C_API_PREDICT_NORMAL, -1,
            out_len) == 0
        buf = np.zeros(out_len.value, np.float64)
        got = C.Ref()
        assert C.LGBM_BoosterPredictForCSR(
            bst.value, xn.indptr.astype(np.int32), C.C_API_DTYPE_INT32,
            xn.indices.astype(np.int32), xn.data.astype(np.float64),
            C.C_API_DTYPE_FLOAT64, len(xn.indptr), xn.nnz, xn.shape[1],
            C.C_API_PREDICT_NORMAL, -1, "", got, buf) == 0, \
            C.LGBM_GetLastError()
        assert got.value == xn.shape[0]
        order = np.argsort(-buf)
        tp = np.cumsum(yn[order])
        fp = np.cumsum(1 - yn[order])
        auc = float(np.trapezoid(tp, fp) / (tp[-1] * fp[-1]))
        aucs.append(auc)
        assert C.LGBM_BoosterFree(bst.value) == 0
        assert C.LGBM_DatasetFree(ds) == 0
    assert min(aucs) > 0.6, aucs


def test_handle_semantics():
    rng = np.random.default_rng(1)
    x, y = _window(rng, 500)
    ds = _create_dataset(x, y)
    nd = C.Ref()
    assert C.LGBM_DatasetGetNumData(ds, nd) == 0 and nd.value == 500
    nf = C.Ref()
    assert C.LGBM_DatasetGetNumFeature(ds, nf) == 0 and nf.value == 20
    # free invalidates; double free fails with a message, not a crash
    assert C.LGBM_DatasetFree(ds) == 0
    assert C.LGBM_DatasetFree(ds) == -1
    assert "invalid Dataset handle" in C.LGBM_GetLastError()
    # booster from a freed dataset handle fails cleanly
    bst = C.Ref()
    assert C.LGBM_BoosterCreate(ds, "objective=binary", bst) == -1


def test_dtype_mismatch_rejected():
    rng = np.random.default_rng(2)
    x, y = _window(rng, 400)
    ds = C.Ref()
    rc = C.LGBM_DatasetCreateFromCSR(
        x.indptr.astype(np.int64), C.C_API_DTYPE_INT32,   # declared int32!
        x.indices.astype(np.int32), x.data.astype(np.float64),
        C.C_API_DTYPE_FLOAT64, len(x.indptr), x.nnz, x.shape[1],
        "", None, ds)
    assert rc == -1
    assert "does not match declared" in C.LGBM_GetLastError()
    # label must be float32 like the C layer requires
    ds2 = _create_dataset(x, y)
    rc = C.LGBM_DatasetSetField(ds2, "label", y.astype(np.float64),
                                len(y), C.C_API_DTYPE_FLOAT32)
    assert rc == -1


def test_model_string_roundtrip_and_eval():
    rng = np.random.default_rng(3)
    x, y = _window(rng, 2000)
    ds = _create_dataset(x, y, params="objective=binary num_leaves=15 "
                         "metric=binary_logloss verbosity=-1")
    bst = C.Ref()
    assert C.LGBM_BoosterCreate(
        ds, "objective=binary num_leaves=15 metric=binary_logloss "
        "verbosity=-1", bst) == 0
    fin = C.Ref()
    for _ in range(10):
        C.LGBM_BoosterUpdateOneIter(bst.value, fin)
    # eval on training data (data_idx 0)
    cnt = C.Ref()
    assert C.LGBM_BoosterGetEvalCounts(bst.value, cnt) == 0
    res = np.zeros(max(cnt.value, 1), np.float64)
    ln = C.Ref()
    assert C.LGBM_BoosterGetEval(bst.value, 0, ln, res) == 0
    assert ln.value == cnt.value and res[0] < 0.7   # below chance logloss
    # save/load round trip preserves predictions
    slen = C.Ref()
    sstr = C.Ref()
    assert C.LGBM_BoosterSaveModelToString(bst.value, 0, -1, 0, slen,
                                           sstr) == 0
    nit = C.Ref()
    bst2 = C.Ref()
    assert C.LGBM_BoosterLoadModelFromString(sstr.value, nit, bst2) == 0
    dense = x.toarray().astype(np.float64)
    for h in (bst.value, bst2.value):
        out = np.zeros(x.shape[0], np.float64)
        got = C.Ref()
        assert C.LGBM_BoosterPredictForMat(
            h, dense, C.C_API_DTYPE_FLOAT64, x.shape[0], x.shape[1], 1,
            C.C_API_PREDICT_NORMAL, -1, "", got, out) == 0
        if h == bst.value:
            first = out.copy()
    np.testing.assert_allclose(out, first, atol=1e-6)
    imp = np.zeros(x.shape[1], np.float64)
    assert C.LGBM_BoosterFeatureImportance(bst.value, -1, 0, imp) == 0
    assert imp.sum() > 0
