"""Zero-recompile cold start: persistent compile cache, AOT warmup,
training-shape bucketing (docs/ColdStart.md).

Covers the cold-start subsystem end to end: library-level activation of
JAX's persistent compilation cache (``lightgbm_tpu.compile_cache``),
pow2 training-row bucketing in the device grower (byte-identical trees,
one program family per bucket), the AOT warmup entry points, the
cross-process determinism of the program-cache signature, and the
``GrowerPrograms`` LRU eviction contract.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu import compile_cache, obs
from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.utils.log import set_verbosity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _subprocess_env(**extra):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "LGBM_TPU_CHUNK": os.environ.get("LGBM_TPU_CHUNK",
                                                 "8192")})
    env.update(extra)
    return env


def _train_small(x, y, extra, n_iters=4, chunk=2, per_iter=False):
    cfg = Config({"objective": "binary", "num_leaves": 15,
                  "verbosity": -1, "device_growth": "on",
                  "min_data_in_leaf": 5, **extra})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    if per_iter:
        for _ in range(n_iters):
            bst.train_one_iter()
    else:
        bst.train_chunked(n_iters, chunk=chunk)
    bst._flush_pending()
    return bst


def _trees_only(bst) -> str:
    return bst.model_to_string().split("parameters:")[0]


# ---------------------------------------------------------------------------
# training-shape bucketing
# ---------------------------------------------------------------------------

def test_row_bucketing_trees_byte_identical():
    """Bucketed growth (pow2 row pad + traced num_valid) must emit
    byte-identical trees to the exact-rows path, including with the
    fork harness's bagging + feature_fraction config, on both the fused
    and per-iteration drivers."""
    set_verbosity(-1)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((1500, 8))
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.4).astype(np.float32)
    extra = {"bagging_fraction": 0.8, "bagging_freq": 2,
             "feature_fraction": 0.8}
    on = _train_small(x, y, {**extra, "train_row_bucketing": True})
    off = _train_small(x, y, {**extra, "train_row_bucketing": False})
    assert on._grower.row_bucket == 2048
    assert off._grower.row_bucket == 1500
    assert _trees_only(on) == _trees_only(off)
    on_pi = _train_small(x, y, {**extra, "train_row_bucketing": True},
                         per_iter=True)
    assert _trees_only(on_pi) == _trees_only(on)


def test_row_bucketing_shares_programs_across_window_sizes():
    """Two retrain windows with DIFFERENT row counts in the same pow2
    bucket must adopt the same GrowerPrograms object and trigger zero
    new traces — the whole point of keying the cache on the bucket."""
    set_verbosity(-1)
    rng = np.random.default_rng(4)
    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    try:
        reg = obs.registry()

        def window(n):
            x = rng.standard_normal((n, 8))
            y = (x[:, 0] > 0).astype(np.float32)
            return _train_small(x, y, {"train_row_bucketing": True})

        b1 = window(2800)
        compiles1 = sum(v["compiles"]
                        for v in reg.snapshot()["jit"].values())
        b2 = window(3600)
        compiles2 = sum(v["compiles"]
                        for v in reg.snapshot()["jit"].values())
        assert b1._grower.row_bucket == 4096
        assert b2._grower.row_bucket == 4096
        assert b2._grower.programs is b1._grower.programs
        assert compiles2 == compiles1, reg.snapshot()["jit"]
    finally:
        obs.configure(enabled=was_enabled)


def test_row_bucketing_gates():
    """Bucketing auto-disables where its contracts cannot hold: int8
    quantization (rounding stream is keyed on the padded shape) and
    lambdarank (query-segment gradients are not row-local)."""
    from lightgbm_tpu.ops.grow import DeviceGrower

    set_verbosity(-1)
    rng = np.random.default_rng(5)
    x = rng.standard_normal((700, 6))
    cfg = Config({"objective": "binary", "grad_quant_bits": 8,
                  "verbosity": -1})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label((x[:, 0] > 0).astype(np.float32))
    g = DeviceGrower(ds, cfg)
    assert g.row_bucket == 700          # quant: exact rows

    # lambdarank: the init_train gate reads device_grad_rowwise
    cfg = Config({"objective": "lambdarank", "verbosity": -1,
                  "device_growth": "on", "min_data_in_leaf": 2,
                  "num_leaves": 7})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    md = ds.metadata
    md.set_label(rng.integers(0, 3, 700).astype(np.float32))
    md.set_query(np.full(70, 10, np.int64))
    bst = create_boosting(cfg)
    bst.init_train(ds)
    assert bst._grower is not None
    assert bst._grower.row_bucket == 700


# ---------------------------------------------------------------------------
# signature determinism across processes
# ---------------------------------------------------------------------------

_SIG_SCRIPT = """
import json, sys
sys.path.insert(0, {repo!r})
from lightgbm_tpu.config import Config
from lightgbm_tpu.ops import grow
from lightgbm_tpu.ops import stage_plan
cfg = Config({{"objective": "binary", "num_leaves": 31, "max_bin": 63,
              "metric": "auc", "categorical_feature": [2, 1],
              "monotone_constraints": [0, 1, -1],
              "some_unknown_extra": "x", "another_extra": 7}})
sig = grow.programs_signature(10000, 5, 64, 5, True, cfg)
plan = grow.default_stage_plan(10000, cfg)
print(json.dumps({{"sig": repr(sig),
                  "digest": grow._config_digest(cfg),
                  "plan": stage_plan.plan_digest(plan)}}))
"""


@pytest.mark.timeout(120)
def test_programs_signature_stable_across_hashseeds():
    """The program-cache signature / config digest / stage-plan digest
    must be identical under different PYTHONHASHSEED values — a
    hash-order-dependent key would silently defeat the persistent
    compile cache (every process would compute a fresh key)."""
    script = _SIG_SCRIPT.format(repo=REPO)
    outs = []
    for seed in ("1", "271828"):
        r = subprocess.run(
            [sys.executable, "-c", script],
            env=_subprocess_env(PYTHONHASHSEED=seed),
            capture_output=True, text=True, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

def test_compile_cache_configure(tmp_path):
    import jax

    # falsy values leave the cache alone
    assert compile_cache.configure(None) is None
    assert compile_cache.configure("") is None
    assert compile_cache.configure("0") is None
    assert compile_cache.configure("off") is None
    target = tmp_path / "cc"
    path = compile_cache.configure(str(target))
    try:
        assert path == str(target)
        assert os.path.isdir(path)
        assert compile_cache.cache_dir() == path
        assert jax.config.jax_compilation_cache_dir == path
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
        # param beats env; env used when param empty
        cfg = Config({"compile_cache_dir": str(tmp_path / "p"),
                      "verbosity": -1})
        assert compile_cache.configure_from_config(cfg) \
            == str(tmp_path / "p")
        # a param-configured dir is PINNED against env-only reconfigures
        # (PredictionServer / capi_embed call configure_from_env): the
        # env var must not flip the process-wide cache mid-training
        os.environ[compile_cache.ENV_VAR] = str(tmp_path / "env")
        try:
            assert compile_cache.configure_from_env() \
                == str(tmp_path / "p")
            assert jax.config.jax_compilation_cache_dir \
                == str(tmp_path / "p")
        finally:
            del os.environ[compile_cache.ENV_VAR]
        c = compile_cache.counters()
        assert set(c) >= {"hits", "misses", "requests",
                          "backend_compile_s"}
    finally:
        # restore the session-wide cache dir AND clear the sticky
        # module state this test set (knobs + explicit-dir pin), so
        # later tests' configure_from_env behavior doesn't depend on
        # whether this test ran first
        with compile_cache._LOCK:
            compile_cache._STATE.pop("pinned", None)
            compile_cache._STATE.pop("min_entry_bytes", None)
            compile_cache._STATE.pop("strict_keys", None)
        compile_cache.configure(os.path.expanduser(
            "~/.cache/lgbm_tpu_xla"), _pin=False)


_COLD_SCRIPT = """
import json, os, sys, time
sys.path.insert(0, {repo!r})
from lightgbm_tpu import compile_cache
from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.utils.log import set_verbosity
from lightgbm_tpu.warmup import _synth_dataset
import jax
set_verbosity(-1)
cfg = Config({{"objective": "binary", "num_leaves": 31, "max_bin": 63,
              "num_iterations": 2, "fused_chunk": 2,
              "device_growth": "on", "verbosity": -1}})
compile_cache.configure_from_env()
ds = _synth_dataset(3000, 8, cfg)
t0 = time.perf_counter()
bst = create_boosting(cfg)
bst.init_train(ds)
bst.train_chunked(2, chunk=2)
jax.block_until_ready(bst.train_score)
wall = time.perf_counter() - t0
out = compile_cache.counters()
out["warmup_wall_s"] = wall
print(json.dumps(out))
"""


@pytest.mark.timeout(300)
def test_warm_cold_start_5x_less_compile(tmp_path):
    """Acceptance: a fresh subprocess training the same (bucketed
    shape, config) against a warmed cache dir pays >= 5x less XLA
    compilation than the empty-cache run — and reports ZERO
    persistent-cache misses.  The 5x gate is asserted on the actual
    backend-compile seconds (the component the cache removes); on CPU
    backends per-process *tracing* dominates the residual wall clock,
    so the wall-clock gate there is strictly-faster (the TPU bench
    gates the >= 5x wall ratio via ``bench.py --suite coldstart``)."""
    script = _COLD_SCRIPT.format(repo=REPO)
    env = _subprocess_env(LGBM_TPU_COMPILE_CACHE=str(tmp_path / "cc"))
    runs = []
    for tag in ("cold", "warm"):
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, cwd=REPO)
        assert r.returncode == 0, f"{tag}: {r.stderr[-2000:]}"
        runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    cold, warm = runs
    assert cold["misses"] > 0
    assert warm["misses"] == 0, warm
    assert warm["hits"] >= cold["misses"]
    # the compile component the persistent cache removes: >= 5x
    assert cold["backend_compile_s"] >= 5.0 * max(
        warm["backend_compile_s"], 1e-3), (cold, warm)
    # and the end-to-end cold start is strictly faster
    assert warm["warmup_wall_s"] < cold["warmup_wall_s"], (cold, warm)


# ---------------------------------------------------------------------------
# AOT warmup entry points
# ---------------------------------------------------------------------------

def test_warmup_iters_schedule():
    from lightgbm_tpu.warmup import _warmup_iters

    assert _warmup_iters(50, 25) == 25          # divides: one chunk
    assert _warmup_iters(7, 3) == 4             # chunk + remainder
    assert _warmup_iters(2, 0) == 2             # per-iteration only
    assert _warmup_iters(2, 20) == 2            # fewer iters than chunk


def test_warmup_serve_compiles_declared_buckets():
    from lightgbm_tpu.warmup import (_depth_pads, _shape_family,
                                     warmup_serve)

    assert _depth_pads(4) == [8]
    assert _depth_pads(31) == [8, 16, 32]
    # node pads enumerate the REALIZED-tree possibilities (easy data
    # can top trees out below the declared leaf budget)
    assert _shape_family(4) == [(1, 8), (2, 8), (4, 8)]
    report = warmup_serve([64], 4, params={
        "objective": "binary", "num_iterations": 2, "num_leaves": 4,
        "verbosity": -1})
    assert report["row_buckets"] == [128]       # min pow2 bucket
    assert report["node_pads"] == [1, 2, 4]
    assert report["depth_pads"] == [8]
    assert report["programs"] == 3


def test_warmup_train_then_zero_miss_probe():
    """In-process version of the CI smoke (scripts/check_coldstart.py
    runs the cross-process one): warmup must raise no errors and report
    its shape/bucket."""
    from lightgbm_tpu.warmup import warmup_train

    report = warmup_train(1100, 6, params={
        "objective": "binary", "num_leaves": 7, "num_iterations": 2,
        "fused_chunk": 2, "device_growth": "on", "verbosity": -1})
    assert report["rows"] == 1100
    assert report["row_bucket"] == 2048
    assert report["device_growth"] is True


def test_run_warmup_requires_declaration():
    from lightgbm_tpu.utils.log import LightGBMError
    from lightgbm_tpu.warmup import run_warmup

    with pytest.raises(LightGBMError, match="declared shape"):
        run_warmup(Config({"verbosity": -1}))


# ---------------------------------------------------------------------------
# GrowerPrograms LRU eviction
# ---------------------------------------------------------------------------

def test_grower_programs_lru_eviction():
    """Filling the process-level program cache past its bound must
    evict the oldest signature (a later request rebuilds FRESH programs
    whose jits would re-trace) while resident signatures keep returning
    the same object (zero re-traces)."""
    from lightgbm_tpu.ops import grow

    cfg = Config({"objective": "binary", "num_leaves": 4,
                  "verbosity": -1})
    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    with grow._PROGRAM_CACHE_LOCK:
        saved = dict(grow._PROGRAM_CACHE)
        grow._PROGRAM_CACHE.clear()
    try:
        reg = obs.registry()

        def get(nf):
            return grow.get_grower_programs(1024, nf, 64, nf, False, cfg)

        m0 = reg.counter("grow.cache_misses")
        h0 = reg.counter("grow.cache_hits")
        first = get(1)
        assert get(1) is first                       # warm hit
        cap = grow._PROGRAM_CACHE_MAX
        for nf in range(2, 2 + cap):                 # fill past the bound
            get(nf)
        assert len(grow._PROGRAM_CACHE) == cap
        resident = get(1 + cap)                      # newest: still a hit
        assert resident is get(1 + cap)
        rebuilt = get(1)                             # evicted: rebuilt
        assert rebuilt is not first
        # fresh programs own fresh jit wrappers -> a dispatch would
        # re-trace; resident ones kept their (possibly warm) wrappers
        assert rebuilt._grow is not first._grow
        assert reg.counter("grow.cache_misses") == m0 + 1 + cap + 1
        assert reg.counter("grow.cache_hits") == h0 + 3
    finally:
        with grow._PROGRAM_CACHE_LOCK:
            grow._PROGRAM_CACHE.clear()
            grow._PROGRAM_CACHE.update(saved)
        obs.configure(enabled=was_enabled)


# ---------------------------------------------------------------------------
# satellites: serve warmup defaults, pallas guard
# ---------------------------------------------------------------------------

def test_serve_warmup_includes_min_rows_bucket():
    """PredictionServer.warmup() defaults must include the bucket the
    device_predict_min_rows auto-routing threshold implies, so the
    first large batch is not a cold compile."""
    from lightgbm_tpu.serve import PredictionServer

    set_verbosity(-1)
    rng = np.random.default_rng(6)
    x = rng.standard_normal((400, 5))
    y = (x[:, 0] > 0).astype(np.float32)
    bst = _train_small(x, y, {}, n_iters=2)

    server = PredictionServer(bst, device_predict_min_rows=3000)
    assert 4096 in server.default_warmup_buckets()
    done = server.warmup()
    assert 4096 in done and 128 in done

    # no explicit override: adopt the booster config's threshold
    server2 = PredictionServer(bst)
    assert server2.device_predict_min_rows == 65536
    assert 65536 in server2.default_warmup_buckets()


def test_pallas_lane_overflow_raises_value_error():
    """ops/hist_pallas.py must reject k*w > 128 with a ValueError (an
    assert would vanish under python -O)."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.hist_pallas import wave_hist_pallas

    binned = jnp.zeros((1024, 1), jnp.uint8)
    leaf = jnp.zeros((1024,), jnp.int32)
    ghk = jnp.zeros((1024, 3), jnp.bfloat16)
    pend = jnp.arange(64, dtype=jnp.int32)
    with pytest.raises(ValueError, match="lane"):
        wave_hist_pallas(binned, leaf, ghk, pend, g=1, nb=64, k=3,
                         w=64, interpret=True)
