"""Int8-quantized gradient histograms (grad_quant_bits=8, ops/grow.py).

The quantized path stochastically rounds grad/hess to int8 against a
per-tree global scale, runs the wave contraction int8->int32, dequantizes
once per histogram before split-gain evaluation and refits leaf values
from the full-precision gradients.  These tests pin the contract: close
quality vs f32 (split agreement + AUC within 2e-3 on the bench
synthetic), exact integer counts (striped layout included), seed
determinism, and bit-identical fused-vs-per-iteration training with
quantization on.
"""

import os
import sys

import numpy as np
import pytest
from conftest import assert_models_bit_identical, train_device_booster

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.config import Config


def _bench_synth(rows, seed=7):
    """The bench.py planted-signal HIGGS-shaped synthetic."""
    from bench import synth_higgs
    return synth_higgs(rows, seed=seed)


def _train(params, x, y, n_iters, chunk=0):
    return train_device_booster(
        {"objective": "binary", "verbosity": -1, "device_growth": "on",
         "num_leaves": 31, "max_bin": 63, "min_data_in_leaf": 20,
         **params},
        x, y, n_iters, chunk=chunk)


def _auc(scores, labels):
    order = np.argsort(-scores, kind="stable")
    lbl = labels[order]
    tps = np.cumsum(lbl)
    fps = np.cumsum(1.0 - lbl)
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2
    return float(trapezoid(tps, fps) / (tps[-1] * fps[-1]))


_assert_bit_identical = assert_models_bit_identical


# slow: trains two 40-iteration boosters on the 16384-row synthetic plus
# 20000-row predicts (~2.5 min CPU) — scripts/check.sh full mode runs it;
# tier-1 keeps the cheaper exactness/determinism/parity quant tests
@pytest.mark.slow
def test_quant_auc_and_split_agreement_vs_f32():
    """Acceptance: AUC within 2e-3 of f32 on the bench synthetic, and
    the trees mostly agree on split features (8-bit stochastic rounding
    is noise at the histogram-sum level, not a different model).  40
    iterations so both models are past the underfit regime where early
    split-path divergence, not quantization, drives the AUC gap."""
    x, y = _bench_synth(16384)
    xt, yt = _bench_synth(20000, seed=1234)
    a = _train({"learning_rate": 0.15}, x, y, 40)
    b = _train({"learning_rate": 0.15, "grad_quant_bits": 8}, x, y, 40)
    auc_f32 = _auc(a.predict(xt), yt)
    auc_q8 = _auc(b.predict(xt), yt)
    assert abs(auc_f32 - auc_q8) < 2e-3, (auc_f32, auc_q8)
    # split-decision agreement is only well-defined where both models
    # saw the SAME state: tree 0 (identical gradients), where any
    # disagreement is pure quantization noise.  Later trees sit on
    # diverged boosting paths, so compare those at the model level via
    # feature-importance correlation instead (measured ~0.99).
    t0a, t0b = a.models[0], b.models[0]
    n0 = min(t0a.num_leaves, t0b.num_leaves) - 1
    poswise = np.mean(np.asarray(t0a.split_feature[:n0])
                      == np.asarray(t0b.split_feature[:n0]))
    assert poswise > 0.7, poswise
    imp_corr = np.corrcoef(a.feature_importance(),
                           b.feature_importance())[0, 1]
    assert imp_corr > 0.95, imp_corr


def test_quant_counts_exact_and_striped_layout_identical():
    """Counts ride the integer path, so the striped (k=6) and plain
    (k=3) quantized layouts must produce BYTE-identical trees — the
    stripe only splits the int32 accumulation, and integer addition is
    associative.  Also checks recorded counts are conserved integers."""
    import lightgbm_tpu.ops.grow as growmod
    rng = np.random.default_rng(5)
    # > n_pad/2 rows so BOTH stripes carry real data (the stripe
    # boundary sits at n_pad // 2 = 4096 under the conftest
    # LGBM_TPU_CHUNK=8192): a bug in the second-stripe columns must not
    # hide behind zero-weight padding
    n = 6000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 2 * (x[:, 1] > 0.3) - 1.5 * (x[:, 2] < -0.5)
         + 0.1 * rng.standard_normal(n) > 0).astype(np.float32)
    params = {"grad_quant_bits": 8, "num_leaves": 15}
    old = growmod.COUNT_SPLIT_ROWS
    try:
        # force striped on small data; threshold <= n < 2x threshold
        # keeps the config device-eligible
        growmod.COUNT_SPLIT_ROWS = 5000
        bs = _train(params, x, y, 5)
        assert bs._grower.hist_cols == 6
        growmod.COUNT_SPLIT_ROWS = old
        bp = _train(params, x, y, 5)
        assert bp._grower.hist_cols == 3
        _assert_bit_identical(bs, bp)
        for tree in bp.models:
            for node in range(tree.num_leaves - 1):
                lc = tree.internal_count[node]
                assert lc == int(lc)
            # root count conservation: every row lands in exactly one leaf
            assert int(np.sum(tree.leaf_count[:tree.num_leaves])) == n
    finally:
        growmod.COUNT_SPLIT_ROWS = old


def test_quant_deterministic_across_runs():
    rng = np.random.default_rng(9)
    x = rng.standard_normal((3000, 8)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    params = {"grad_quant_bits": 8, "seed": 42}
    a = _train(params, x, y, 6)
    b = _train(params, x, y, 6)
    _assert_bit_identical(a, b)


def test_quant_fused_parity_with_fork_harness_config():
    """Fused-vs-per-iteration must stay byte-identical WITH quantization
    on: the rounding noise is keyed by the global tree index, exactly
    like the feature_fraction/bagging draws (tests/test_fused.py)."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((3000, 10)).astype(np.float32)
    logit = x[:, 0] + np.abs(x[:, 1]) - 0.5 * x[:, 2]
    y = (rng.random(3000) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    params = {"grad_quant_bits": 8, "feature_fraction": 0.8,
              "bagging_freq": 5, "bagging_fraction": 0.8,
              "num_leaves": 15, "min_data_in_leaf": 5}
    a = _train(params, x, y, 10)
    b = _train(params, x, y, 10, chunk=4)
    _assert_bit_identical(a, b)


def test_quant_pallas_byte_identical_to_einsum():
    """The int8 Pallas wave-histogram kernel (interpret mode on CPU)
    must yield BYTE-identical models to the int8 einsum: both
    accumulate int8->int32 and integer addition is associative, so any
    divergence is a layout/masking bug, never rounding."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((3000, 10)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    base = {"grad_quant_bits": 8, "num_leaves": 15,
            "min_data_in_leaf": 5}
    a = _train({**base, "hist_kernel": "einsum"}, x, y, 5)
    b = _train({**base, "hist_kernel": "interpret"}, x, y, 5)
    assert a._grower.hist_kernel_tag == "einsum_int8"
    assert b._grower.hist_kernel_tag == "pallas_int8"
    assert a._grower.int_scan and b._grower.int_scan
    _assert_bit_identical(a, b)


def test_quant_pallas_striped_byte_identical():
    """Same contract on the striped six-column layout (the >= 2^24-row
    path, forced small via COUNT_SPLIT_ROWS)."""
    import lightgbm_tpu.ops.grow as growmod

    rng = np.random.default_rng(8)
    n = 6000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 2 * (x[:, 1] > 0.3) > 0.5).astype(np.float32)
    base = {"grad_quant_bits": 8, "num_leaves": 15, "seed": 77}
    old = growmod.COUNT_SPLIT_ROWS
    try:
        growmod.COUNT_SPLIT_ROWS = 5000
        a = _train({**base, "hist_kernel": "einsum"}, x, y, 4)
        b = _train({**base, "hist_kernel": "interpret"}, x, y, 4)
        assert a._grower.hist_cols == b._grower.hist_cols == 6
        assert b._grower.hist_kernel_tag == "pallas_int8"
        _assert_bit_identical(a, b)
    finally:
        growmod.COUNT_SPLIT_ROWS = old


def test_quant_int_scan_bound_and_f32_fallback():
    """The int32 find-best scan engages below INT32_SCAN_ROWS (every
    |sum| <= 127 * rows fits int32) and falls back to the PR-4 f32
    dequantized scan above it — the fallback still trains and keeps
    counts integer-exact."""
    import lightgbm_tpu.ops.grow as growmod

    assert growmod.INT32_SCAN_ROWS == ((1 << 31) - 1) // 127
    rng = np.random.default_rng(12)
    x = rng.standard_normal((2000, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    old = growmod.INT32_SCAN_ROWS
    try:
        growmod.INT32_SCAN_ROWS = 1000    # force the f32 fallback
        b = _train({"grad_quant_bits": 8, "num_leaves": 15}, x, y, 4)
        assert not b._grower.int_scan
    finally:
        growmod.INT32_SCAN_ROWS = old
    a = _train({"grad_quant_bits": 8, "num_leaves": 15}, x, y, 4)
    assert a._grower.int_scan
    for bst in (a, b):
        for tree in bst.models:
            nl = tree.num_leaves
            assert int(np.sum(tree.leaf_count[:nl])) == 2000
    # same data, same seeds: the two scans pick from identical exact
    # integer histograms, differing only in representation at gain
    # math — models agree on quality-level behaviour
    assert len(a.models) == len(b.models)


def test_quant_default_off_and_validation():
    x = np.random.default_rng(0).standard_normal((500, 4)) \
        .astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    bst = _train({}, x, y, 1)
    assert bst._grower.quant_bits == 0
    assert bst._grower.hist_cols == 3
    with pytest.raises(ValueError):
        Config({"grad_quant_bits": 4})
    # gpu_use_dp wins over quantization (precision request)
    cfg = Config({"grad_quant_bits": 8, "gpu_use_dp": True})
    assert cfg.grad_quant_bits == 0
