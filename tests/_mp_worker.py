"""Worker process for tests/test_multiprocess.py.

Runs under ``jax.distributed`` as one of N real OS processes (the
reference analog: one LightGBM machine process over its socket linker,
``src/network/linkers_socket.cpp:20-100``).  Each process:

1. finds bins for ITS feature block from its LOCAL sample and exchanges
   serialized mappers through the real ``jax_process_gather`` hook;
2. runs a data-parallel histogram + best-split step over a GLOBAL mesh
   spanning both processes' devices (shard_map + psum over ICI/DCN —
   the actual collective the data-parallel learner issues per wave);
3. writes its results to OUT so the parent asserts cross-process
   equality and parity with a single-process reference computation.

Usage: python _mp_worker.py <coordinator> <num_procs> <rank> <outdir>
"""

import json
import os
import sys

rank = int(sys.argv[3])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
if rank > 0:
    # fail FAST with a clear "peer unreachable" error if the
    # coordinator (rank 0) never comes up, instead of hanging the whole
    # mesh inside the runtime's own much longer handshake
    from lightgbm_tpu.parallel.network import wait_for_peer
    wait_for_peer(sys.argv[1], attempts=60, timeout_s=2.0,
                  base_delay_s=0.05)
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=int(sys.argv[2]),
                           process_id=rank)

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.distributed import (allgather_mappers,
                                           find_bin_shard,
                                           jax_process_gather)

nproc = int(sys.argv[2])
outdir = sys.argv[4]
assert len(jax.devices()) == 4 * nproc, \
    f"expected a global device view, got {len(jax.devices())}"

# --- 1. distributed find-bin with the real gather hook -----------------
rng = np.random.default_rng(100 + rank)
local_sample = rng.standard_normal((2000, 10)).astype(np.float64)
cfg = Config({"objective": "binary", "max_bin": 63, "verbosity": -1})
pair = find_bin_shard(local_sample, rank, nproc, cfg,
                      total_sample_cnt=2000, num_data=2000 * nproc)
mappers = allgather_mappers([pair], gather_fn=lambda p: jax_process_gather(
    p[0]), num_total_features=10)
mapper_sig = [m.to_state() for m in mappers]

# --- 2. one data-parallel step over the GLOBAL mesh --------------------
# per-process gradient block (deterministic), global histogram via psum
# inside shard_map — the per-wave collective of the data-parallel
# learner — then an identical best-bin decision on every process
mesh = Mesh(np.asarray(jax.devices()), ("workers",))
G = 8 * nproc   # rows per device block
bins_all = np.arange(4 * nproc * G, dtype=np.int32) % 16
grad_all = np.sin(np.arange(4 * nproc * G, dtype=np.float32))

arr_bins = jax.make_array_from_callback(
    (4 * nproc * G,), NamedSharding(mesh, P("workers")),
    lambda idx: bins_all[idx])
arr_grad = jax.make_array_from_callback(
    (4 * nproc * G,), NamedSharding(mesh, P("workers")),
    lambda idx: grad_all[idx])


@jax.jit
def dp_step(b, g):
    def local(b_, g_):
        oh = jax.nn.one_hot(b_, 16, dtype=jnp.float32)
        hist = jnp.einsum("nb,n->b", oh, g_)
        return jax.lax.psum(hist, "workers")

    hist = shard_map(local, mesh=mesh, in_specs=(P("workers"),
                                                 P("workers")),
                     out_specs=P())(b, g)
    return hist, jnp.argmax(hist)


hist, best = dp_step(arr_bins, arr_grad)
# hist is replicated over the global mesh; read this process's replica
hist_local = np.asarray(hist.addressable_data(0))

expected = np.zeros(16, np.float32)
np.add.at(expected, bins_all, grad_all)

out = {
    "rank": rank,
    "num_mappers": len(mapper_sig),
    "mapper_hash": hash(json.dumps(mapper_sig, sort_keys=True)) & 0xFFFFFFFF,
    "mapper_sig": mapper_sig,
    "best_bin": int(np.asarray(best.addressable_data(0))),
    "hist_max_err": float(np.abs(hist_local - expected).max()),
}
with open(os.path.join(outdir, f"rank{rank}.json"), "w") as fh:
    json.dump(out, fh)
print(f"rank {rank} OK", flush=True)
