"""Worker process for tests/test_shard.py (and scripts/check_shard.py).

Runs under a FORCED 4-device host mesh (XLA_FLAGS must be set before
jax imports, hence the subprocess) and exercises the single-controller
sharded trainer (docs/Sharding.md) against the single-device fused
path.  Prints exactly one JSON line; any shard-environment failure
(shard_map unavailable, mesh creation failing on this jax build) is
reported as ``{"skip": reason}`` so callers record WHY instead of
failing — the ROADMAP memory note: such failures in the CPU container
are environmental, the contract is validated on real multi-chip.

Usage: python _shard_worker.py <scenario> [outdir]
Scenarios: core | bucketing | checkpoint | fused_find
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
# small chunk keeps the tiny test shapes fast on CPU
os.environ.setdefault("LGBM_TPU_CHUNK", "8192")

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

ROWS = 2500
FEATURES = 8
BASE = {
    "objective": "binary", "verbosity": -1, "device_growth": "on",
    "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
    "seed": 20260804, "wave_plan": "fixed",
}
SHARD = {"data_sharding": "single_controller"}


def _probe_shard_env():
    """Mesh + one psum through the compat shard_map: the exact plumbing
    the sharded grower uses.  Returns None when healthy, else the
    reason string the caller records in its skip."""
    try:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from lightgbm_tpu.ops.shard import (make_shard_mesh,
                                            shard_map_compat)
        mesh = make_shard_mesh(4)
        out = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum(x, "shards"), mesh,
            (P("shards"),), P()))(jnp.arange(8, dtype=jnp.float32))
        float(out.sum())
        return None
    except Exception as e:   # noqa: BLE001 — any env failure is a skip
        return f"{type(e).__name__}: {e}"


def _data(rows=ROWS, seed=11):
    import numpy as np
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows, FEATURES)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    return x, y


def _train(x, y, extra, iters=4, chunk=2, per_iter=False,
           return_booster=False):
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    cfg = Config({**BASE, **extra})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    if per_iter:
        for _ in range(iters):
            bst.train_one_iter()
    else:
        bst.train_chunked(iters, chunk=chunk)
    bst._flush_pending()
    if return_booster:
        return bst
    return trees_of(bst.model_to_string())


def trees_of(model_str: str) -> str:
    """The model string minus the parameters echo (which legitimately
    differs by the data_sharding setting itself)."""
    return model_str.split("\nparameters:", 1)[0]


def scenario_core():
    """Identity/determinism/invariance in ONE process (shared compiles):

    * quant8 1-vs-4-device byte identity, fused AND per-iteration;
    * f32 sharded run-to-run determinism;
    * bagging + feature_fraction shard-invariance (quant8 identity with
      both sampling paths active);
    * warm same-shape second window traces NOTHING new.
    """
    from lightgbm_tpu import obs
    obs.configure(enabled=True)
    x, y = _data()
    q = {"grad_quant_bits": 8}
    out = {}
    single = _train(x, y, q)
    sharded = _train(x, y, {**q, **SHARD})
    out["identity_fused"] = single == sharded
    out["identity_per_iter"] = \
        sharded == _train(x, y, {**q, **SHARD}, per_iter=True)
    f1 = _train(x, y, SHARD)
    f2 = _train(x, y, SHARD)
    out["f32_deterministic"] = f1 == f2
    bagff = {**q, "bagging_fraction": 0.7, "bagging_freq": 2,
             "feature_fraction": 0.75}
    out["invariance_bag_ff"] = \
        _train(x, y, bagff) == _train(x, y, {**bagff, **SHARD})

    # warm window: a NEW same-shape dataset through a FRESH booster must
    # re-dispatch into the already-traced sharded programs
    snap = obs.registry().snapshot()
    before = {k: v["compiles"] for k, v in snap["jit"].items()
              if "sharded" in k}
    hits_before = snap["counters"].get("grow.cache_hits", 0)
    x2, y2 = _data(seed=12)
    _train(x2, y2, {**q, **SHARD})
    snap = obs.registry().snapshot()
    after = {k: v["compiles"] for k, v in snap["jit"].items()
             if "sharded" in k}
    out["warm_window_new_compiles"] = \
        sum(after.values()) - sum(before.values())
    out["warm_window_cache_hit"] = \
        snap["counters"].get("grow.cache_hits", 0) > hits_before
    out["shard_digest"] = obs.summary().get("shard")
    return out


def scenario_bucketing():
    """train_row_bucketing shard-invariance: bucketed vs exact-row
    sharded runs must emit byte-identical trees (pad rows carry zero
    stats — per shard AND through the psum), on a row count where the
    per-shard bucket actually differs from the exact chunk pad."""
    rows = 280_000   # ceil(/4)=70000: bucket 131072 vs chunk pad 98304
    x, y = _data(rows=rows)
    cfg = {"bagging_fraction": 0.8, "bagging_freq": 2,
           "feature_fraction": 0.8}
    a = _train(x, y, {**cfg, **SHARD, "train_row_bucketing": True},
               iters=2, chunk=2)
    b = _train(x, y, {**cfg, **SHARD, "train_row_bucketing": False},
               iters=2, chunk=2)
    return {"bucketing_invariant": a == b, "rows": rows}


def scenario_checkpoint(outdir):
    """Mid-train checkpoint on the 4-device mesh resumes byte-identical
    (PR 8's contract composed with sharding)."""
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    x, y = _data()
    extra = {**SHARD, "grad_quant_bits": 8}
    straight = _train(x, y, extra, iters=6, chunk=2)

    path = os.path.join(outdir, "shard_ckpt.txt")
    cfg = Config({**BASE, **extra})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(6, chunk=2, snapshot_freq=4, snapshot_path=path)
    snap_path = f"{path}.snapshot_iter_4"
    have_snap = os.path.exists(snap_path)

    resumed = None
    if have_snap:
        bst2 = create_boosting(cfg)
        bst2.init_train(ds)
        bst2.resume_from_checkpoint(snap_path)
        bst2.train_chunked(2, chunk=2)
        bst2._flush_pending()
        resumed = trees_of(bst2.model_to_string())
    return {"snapshot_written": have_snap,
            "resume_identical": resumed == straight}


def scenario_fused_find():
    """Fused find-best-in-wave composed with sharding: under quant8
    (the exact-arithmetic regime) the 4-device mesh must emit trees
    byte-identical to the single-device run in BOTH wave layouts, and
    the two layouts must agree with each other — the psum lands inside
    the fused program directly ahead of the replicated gain scan
    (ops/shard.py determinism contract)."""
    x, y = _data()
    q = {"grad_quant_bits": 8}
    out = {}
    ref = _train(x, y, {**q, "find_best_fusion": "fused"})
    out["fused_1v4_identical"] = \
        ref == _train(x, y, {**q, **SHARD, "find_best_fusion": "fused"})
    two = _train(x, y, {**q, "find_best_fusion": "two_pass"})
    out["two_pass_1v4_identical"] = \
        two == _train(x, y,
                      {**q, **SHARD, "find_best_fusion": "two_pass"})
    out["fused_eq_two_pass"] = ref == two
    return out


def main():
    scenario = sys.argv[1] if len(sys.argv) > 1 else "core"
    outdir = sys.argv[2] if len(sys.argv) > 2 else "."
    reason = _probe_shard_env()
    if reason is not None:
        print(json.dumps({"skip": f"shard_map environment failed "
                                  f"(environmental, see ROADMAP memory "
                                  f"note): {reason}"}))
        return 0
    if scenario == "core":
        out = scenario_core()
    elif scenario == "bucketing":
        out = scenario_bucketing()
    elif scenario == "checkpoint":
        out = scenario_checkpoint(outdir)
    elif scenario == "fused_find":
        out = scenario_fused_find()
    else:
        raise SystemExit(f"unknown scenario {scenario!r}")
    out["scenario"] = scenario
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
