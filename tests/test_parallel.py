"""Multi-device tests on the 8-device CPU mesh: collective verbs + the
three distributed learners' equivalence with serial training.

The reference has no deterministic multi-node test harness (SURVEY.md §4 —
distributed modes are exercised only by running N processes by hand); here
every mode runs single-process over 8 virtual devices, asserting
data/feature-parallel trees are IDENTICAL to serial trees on the same data
(the design guarantee: global histograms + global counts => same argmax),
and voting-parallel is identical when top_k covers all features.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset, Metadata
from lightgbm_tpu.tree.learner import SerialTreeLearner
from lightgbm_tpu.parallel import create_tree_learner
from lightgbm_tpu.parallel.network import Network
from lightgbm_tpu.boosting import create_boosting


@pytest.fixture(scope="module")
def net():
    return Network(num_machines=8)


# ---------------------------------------------------------------------------
# collective verbs
# ---------------------------------------------------------------------------
def test_network_verbs(net):
    d = net.num_machines
    x = jnp.arange(d * 4, dtype=jnp.float32)
    xs = net.shard_rows(x)

    f = net.run_sharded(lambda a: net.allreduce(a.sum()),
                        in_specs=P(net.axis), out_specs=P())
    assert float(jax.jit(f)(xs)) == float(x.sum())

    g = net.run_sharded(lambda a: net.all_gather(a),
                        in_specs=P(net.axis), out_specs=P(net.axis, None))
    gathered = jax.jit(g)(xs)   # each device's gather stacks to (d*d, 4)
    assert gathered.shape == (d * d, 4)
    np.testing.assert_array_equal(np.asarray(gathered[:d]),
                                  np.asarray(x).reshape(d, 4))

    h = net.run_sharded(lambda a: net.allreduce_max(a.max()),
                        in_specs=P(net.axis), out_specs=P())
    assert float(jax.jit(h)(xs)) == float(x.max())


def test_argmax_allreduce_tiebreak(net):
    d = net.num_machines
    # equal keys everywhere: the smallest tie_id's payload must win
    keys = jnp.ones(d, jnp.float32)
    tie = jnp.asarray(np.arange(d)[::-1].copy(), jnp.int32)   # rank r: d-1-r
    payload = jnp.arange(d, dtype=jnp.float32) * 10

    def body(k, t, p):
        out, owner = net.argmax_allreduce(k[0], p[0], t[0])
        return out[None]

    f = net.run_sharded(body, in_specs=(P(net.axis),) * 3, out_specs=P(net.axis))
    out = np.asarray(jax.jit(f)(keys, tie, payload))
    # tie_id is minimal (0) on the last rank, whose payload is 70
    assert np.allclose(out, (d - 1) * 10)


# ---------------------------------------------------------------------------
# learner equivalence
# ---------------------------------------------------------------------------
def _grad_hess_binary(y):
    p = 0.5
    return (jnp.asarray((p - y).astype(np.float32)),
            jnp.full(len(y), p * (1 - p), jnp.float32))


def _tree_equal(a, b, atol=1e-5):
    assert a.num_leaves == b.num_leaves
    for name in ("split_feature", "threshold", "leaf_value", "leaf_count",
                 "decision_type"):
        av = np.asarray(getattr(a, name), np.float64)
        bv = np.asarray(getattr(b, name), np.float64)
        np.testing.assert_allclose(av, bv, atol=atol, err_msg=name)


@pytest.fixture(scope="module")
def binary_learn_setup(binary_data):
    x, y, _, _ = binary_data
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "num_machines": 8, "top_k": 40})
    ds = BinnedDataset.construct_from_matrix(x, cfg, ())
    ds.metadata.set_label(y)
    grad, hess = _grad_hess_binary(y)
    serial_cfg = Config({"objective": "binary", "num_leaves": 31})
    t_serial = SerialTreeLearner(serial_cfg, ds).train(grad, hess)
    return cfg, ds, grad, hess, t_serial


@pytest.mark.parametrize("kind", ["data", "feature", "voting"])
def test_parallel_tree_equals_serial(binary_learn_setup, kind):
    cfg, ds, grad, hess, t_serial = binary_learn_setup
    cfg2 = Config(dict(cfg.raw_params, tree_learner=kind))
    learner = create_tree_learner(cfg2, ds)
    t = learner.train(grad, hess)
    _tree_equal(t_serial, t)


def test_factory_serial_fallback(binary_learn_setup):
    cfg, ds, *_ = binary_learn_setup
    cfg1 = Config({"objective": "binary", "tree_learner": "data",
                   "num_machines": 1})
    learner = create_tree_learner(cfg1, ds)
    assert type(learner) is SerialTreeLearner


def test_data_parallel_update_score(binary_learn_setup):
    cfg, ds, grad, hess, t_serial = binary_learn_setup
    cfg2 = Config(dict(cfg.raw_params, tree_learner="data"))
    dp = create_tree_learner(cfg2, ds)
    t = dp.train(grad, hess)
    s = SerialTreeLearner(Config({"objective": "binary",
                                  "num_leaves": 31}), ds)
    ts = s.train(grad, hess)
    zero = jnp.zeros(ds.num_data, jnp.float32)
    np.testing.assert_allclose(np.asarray(dp.update_score(zero, t)),
                               np.asarray(s.update_score(zero, ts)),
                               atol=1e-6)
    li_s, li_d = s.leaf_indices_host(), dp.leaf_indices_host()
    for leaf in li_s:
        assert set(li_s[leaf].tolist()) == set(li_d[leaf].tolist())


# ---------------------------------------------------------------------------
# full boosting stack on the mesh
# ---------------------------------------------------------------------------
def _train_boosted(params, x, y, rounds, valid=None):
    cfg = Config(params)
    ds = BinnedDataset.construct_from_matrix(x, cfg, ())
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    if valid is not None:
        vx, vy = valid
        vds = BinnedDataset.construct_from_matrix(vx, cfg, (), reference=ds)
        vds.metadata = Metadata(len(vy))
        vds.metadata.set_label(vy)
        bst.add_valid(vds, "valid_0")
    for _ in range(rounds):
        if bst.train_one_iter():
            break
    return bst


@pytest.mark.parametrize("kind", ["data", "feature", "voting"])
def test_boosting_parallel_matches_serial(binary_data, kind):
    x, y, xt, yt = binary_data
    base = {"objective": "binary", "metric": "auc", "num_leaves": 15,
            "learning_rate": 0.1, "top_k": 40}
    serial = _train_boosted(base, x, y, 10, valid=(xt, yt))
    par = _train_boosted(dict(base, tree_learner=kind, num_machines=8),
                         x, y, 10, valid=(xt, yt))
    res_s = dict((n, v) for _, n, v, _ in serial.eval_valid())
    res_p = dict((n, v) for _, n, v, _ in par.eval_valid())
    assert abs(res_s["auc"] - res_p["auc"]) < 1e-6, (res_s, res_p)
    np.testing.assert_allclose(serial.predict(xt), par.predict(xt),
                               atol=1e-5)


def test_data_parallel_bagging(binary_data):
    x, y, xt, yt = binary_data
    bst = _train_boosted({"objective": "binary", "metric": "auc",
                          "num_leaves": 15, "learning_rate": 0.1,
                          "bagging_fraction": 0.7, "bagging_freq": 1,
                          "tree_learner": "data", "num_machines": 8},
                         x, y, 15, valid=(xt, yt))
    res = dict((n, v) for _, n, v, _ in bst.eval_valid())
    assert res["auc"] > 0.74, res


def test_voting_small_k_quality(binary_data):
    x, y, xt, yt = binary_data
    bst = _train_boosted({"objective": "binary", "metric": "auc",
                          "num_leaves": 15, "learning_rate": 0.1,
                          "tree_learner": "voting", "num_machines": 8,
                          "top_k": 5}, x, y, 15, valid=(xt, yt))
    res = dict((n, v) for _, n, v, _ in bst.eval_valid())
    assert res["auc"] > 0.74, res


@pytest.mark.parametrize("kind", ["data", "voting"])
def test_goss_under_row_sharded_learners(binary_data, kind):
    """Per-shard GOSS (rank-local top-k, reference goss.hpp:88-133) must
    reach the serial-GOSS quality level on the binary fixture."""
    x, y, xt, yt = binary_data
    base = {"objective": "binary", "metric": "auc", "boosting": "goss",
            "num_leaves": 15, "learning_rate": 0.1, "top_rate": 0.3,
            "other_rate": 0.2, "top_k": 40}
    serial = _train_boosted(base, x, y, 25, valid=(xt, yt))
    par = _train_boosted(dict(base, tree_learner=kind, num_machines=8),
                         x, y, 25, valid=(xt, yt))
    auc_s = dict((n, v) for _, n, v, _ in serial.eval_valid())["auc"]
    auc_p = dict((n, v) for _, n, v, _ in par.eval_valid())["auc"]
    assert auc_p > auc_s - 0.01, (auc_s, auc_p)


def test_comm_volume_data_vs_voting(binary_data):
    """Substantiate the per-split comm claims with measured payloads
    (VERDICT r3 item 8): data-parallel's dominant collective is the full
    O(total_bins) histogram psum (data_parallel_tree_learner.cpp:159-160
    analog), voting's is the elected-features-only gather
    (voting_parallel_tree_learner.cpp:365-366) — O(2k*256) and several
    times smaller.  Network logs payload bytes at trace time; each logged
    entry is one collective op in the compiled split program."""
    from lightgbm_tpu.parallel.network import make_mesh

    x, y, _, _ = binary_data

    def largest_hist_payload(kind, extra):
        cfg = Config(dict({"objective": "binary", "num_leaves": 15,
                           "tree_learner": kind, "num_machines": 8,
                           "verbosity": -1}, **extra))
        ds = BinnedDataset.construct_from_matrix(x, cfg, ())
        ds.metadata.set_label(y)
        learner = create_tree_learner(cfg, ds, mesh=make_mesh(8))
        net = learner.net
        net.reset_comm_log()
        g = jnp.asarray((0.5 - y).astype(np.float32))
        h = jnp.full(len(y), 0.25, jnp.float32)
        tree = learner.train(g, h)
        assert tree.num_leaves > 1
        allred = [b for v, b in net.comm_log if v == "allreduce"]
        return max(allred), ds

    top_k = 2
    data_bytes, ds = largest_hist_payload("data", {})
    voting_bytes, _ = largest_hist_payload("voting", {"top_k": top_k})

    # data-parallel: one full (G, 256, 3) f32 histogram allreduce
    total_bins_bytes = ds.num_groups * 256 * 3 * 4
    assert data_bytes == total_bins_bytes, (data_bytes, total_bins_bytes)
    # voting: 2k elected features' histograms (256 bins, 3 stats, f32)
    elect_bytes = 2 * top_k * 256 * 3 * 4
    assert voting_bytes <= elect_bytes + 3 * 4, (voting_bytes, elect_bytes)
    assert data_bytes > 5 * voting_bytes, (data_bytes, voting_bytes)


def test_distributed_long_run_with_bagging_and_valid(binary_data):
    """20+ iteration distributed train (bagging + valid set) reaches the
    serial run's quality; GOSS voting likewise (VERDICT r3 item 8).
    Exact tree equality cannot hold under bagging (the bag is drawn over
    per-shard permutation buffers), so quality parity is the contract."""
    x, y, xt, yt = binary_data
    base = {"objective": "binary", "metric": "auc", "num_leaves": 31,
            "learning_rate": 0.1, "bagging_fraction": 0.8,
            "bagging_freq": 2}
    serial = _train_boosted(base, x, y, 22, valid=(xt, yt))
    auc_s = dict((n, v) for _, n, v, _ in serial.eval_valid())["auc"]
    par = _train_boosted(dict(base, tree_learner="data", num_machines=8),
                         x, y, 22, valid=(xt, yt))
    auc_p = dict((n, v) for _, n, v, _ in par.eval_valid())["auc"]
    assert auc_p > auc_s - 0.01, (auc_s, auc_p)

    goss = _train_boosted({"objective": "binary", "metric": "auc",
                           "boosting": "goss", "num_leaves": 31,
                           "learning_rate": 0.1, "top_rate": 0.3,
                           "other_rate": 0.2, "tree_learner": "voting",
                           "num_machines": 8, "top_k": 10},
                          x, y, 22, valid=(xt, yt))
    auc_g = dict((n, v) for _, n, v, _ in goss.eval_valid())["auc"]
    assert auc_g > auc_s - 0.02, (auc_s, auc_g)
