"""Pallas wave-histogram kernel (ops/hist_pallas.py): pad/layout edge
cases and the int8 variant, all in interpret mode on CPU.

The kernel's contracts the grower relies on:

* bf16 stat columns -> f32 accumulators, int8 -> int32 (byte-identical
  to the einsum — integer accumulation is associative);
* rows must divide the grid chunk (ValueError otherwise, not silent
  truncation);
* all stat columns must fit ONE 128-lane tile (k*w <= 128 ValueError —
  a documented single-tile kernel, multi-tile waves stay on the einsum);
* odd group counts exercise the pair loop's single-group tail.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.hist_pallas import wave_hist_pallas


def _np_ref(binned, leaf, ghk, pending, g, nb, k, w):
    """Direct scalar accumulation oracle: out[gi*nb + b, kk, wi] = sum
    of ghk[row, kk] over rows with binned[row, gi] == b and
    leaf[row] == pending[wi]."""
    out = np.zeros((g * nb, k, w), np.float64)
    ghk64 = np.asarray(ghk, np.float64)
    for wi in range(w):
        rows = np.asarray(leaf) == int(pending[wi])
        for gi in range(g):
            idx = gi * nb + np.asarray(binned)[rows, gi].astype(np.int64)
            for kk in range(k):
                np.add.at(out[:, kk, wi], idx, ghk64[rows, kk])
    return out


def _inputs(n=2048, g=3, nb=64, k=3, w=5, seed=0, dtype=jnp.int8):
    rng = np.random.default_rng(seed)
    binned = jnp.asarray(rng.integers(0, nb, (n, g)).astype(np.uint8))
    leaf = jnp.asarray(rng.integers(-1, w + 1, n).astype(np.int32))
    if dtype == jnp.int8:
        ghk = jnp.asarray(rng.integers(-127, 128, (n, k))
                          .astype(np.int8))
    else:
        ghk = jnp.asarray(rng.standard_normal((n, k)).astype(np.float32)
                          .astype(np.float16)).astype(dtype)
    pending = jnp.arange(w, dtype=jnp.int32)
    return binned, leaf, ghk, pending


def test_int8_kernel_matches_oracle_odd_groups():
    """int8 -> int32 accumulation, odd group count (the pair loop's
    single-group tail), bit-exact against the scalar oracle."""
    g, nb, k, w = 3, 64, 3, 5
    binned, leaf, ghk, pending = _inputs(g=g, k=k, w=w)
    out = wave_hist_pallas(binned, leaf, ghk, pending, g=g, nb=nb,
                           k=k, w=w, interpret=True)
    assert out.dtype == jnp.int32
    ref = _np_ref(binned, leaf, ghk, pending, g, nb, k, w)
    np.testing.assert_array_equal(np.asarray(out, np.int64),
                                  ref.astype(np.int64))


def test_int8_kernel_striped_six_columns():
    """The striped layout's six int8 stat columns (>= 2^24-row datasets,
    ops/grow.py k=6) fit the same kernel; exact vs the oracle."""
    g, nb, k, w = 2, 64, 6, 4
    binned, leaf, ghk, pending = _inputs(g=g, k=k, w=w, seed=3)
    out = wave_hist_pallas(binned, leaf, ghk, pending, g=g, nb=nb,
                           k=k, w=w, interpret=True)
    assert out.dtype == jnp.int32
    ref = _np_ref(binned, leaf, ghk, pending, g, nb, k, w)
    np.testing.assert_array_equal(np.asarray(out, np.int64),
                                  ref.astype(np.int64))


def test_bf16_kernel_matches_oracle():
    """bf16 columns keep the f32 accumulator path (regression: the int8
    extension must not perturb the original kernel)."""
    g, nb, k, w = 3, 64, 3, 5
    binned, leaf, ghk, pending = _inputs(g=g, k=k, w=w, seed=1,
                                         dtype=jnp.bfloat16)
    out = wave_hist_pallas(binned, leaf, ghk, pending, g=g, nb=nb,
                           k=k, w=w, interpret=True)
    assert out.dtype == jnp.float32
    ref = _np_ref(binned, leaf, np.asarray(ghk, np.float32), pending,
                  g, nb, k, w)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-2,
                               rtol=1e-3)


def test_rows_not_divisible_by_chunk_raises():
    """CH not dividing n_pad is a loud ValueError, never a silent
    truncation of the tail rows."""
    binned, leaf, ghk, pending = _inputs(n=1500, g=2, w=4)
    with pytest.raises(ValueError, match="divisible"):
        wave_hist_pallas(binned, leaf, ghk, pending, g=2, nb=64, k=3,
                         w=4, interpret=True)
    # explicit non-dividing chunk on an otherwise fine row count
    binned, leaf, ghk, pending = _inputs(n=2048, g=2, w=4)
    with pytest.raises(ValueError, match="divisible"):
        wave_hist_pallas(binned, leaf, ghk, pending, g=2, nb=64, k=3,
                         w=4, ch=768, interpret=True)


def test_kw_over_one_tile_raises_for_int8_too():
    """The single-tile contract (k*w <= 128) gates the int8 variant the
    same way as bf16 (test_coldstart pins the bf16 case)."""
    binned, leaf, ghk, pending = _inputs(n=1024, g=1, k=6, w=4)
    pend_wide = jnp.arange(32, dtype=jnp.int32)
    with pytest.raises(ValueError, match="lane"):
        wave_hist_pallas(binned, leaf, ghk, pend_wide, g=1, nb=64,
                         k=6, w=32, interpret=True)


def test_unsupported_dtype_message_names_both_paths():
    """f32 stat columns are rejected with a message naming the accepted
    dtypes (the old 'bf16 only' text went stale when int8 landed)."""
    binned, leaf, _, pending = _inputs(n=1024, g=1, w=4)
    ghk32 = jnp.zeros((1024, 3), jnp.float32)
    with pytest.raises(ValueError, match="bf16 or int8"):
        wave_hist_pallas(binned, leaf, ghk32, pending, g=1, nb=64,
                         k=3, w=4, interpret=True)
