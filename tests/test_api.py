"""Python API layer: engine.train/cv, sklearn wrappers, callbacks, basic
Dataset/Booster mechanics, CLI — modelled on the reference's primary suite
(tests/python_package_test/test_engine.py, test_sklearn.py, test_basic.py;
SURVEY.md §4).  These layers previously had zero coverage."""


import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import Booster, Dataset


@pytest.fixture(scope="module")
def bin_data():
    rng = np.random.default_rng(0)
    n = 6000
    x = rng.standard_normal((n, 8)).astype(np.float64)
    w = rng.standard_normal(8)
    p = 1 / (1 + np.exp(-(x @ w + np.abs(x[:, 0]))))
    y = (p > rng.random(n)).astype(np.float64)
    return x[:5000], y[:5000], x[5000:], y[5000:]


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(1)
    n = 4000
    x = rng.standard_normal((n, 6)).astype(np.float64)
    y = x[:, 0] * 2 + np.sin(x[:, 1] * 3) + 0.1 * rng.standard_normal(n)
    return x[:3000], y[:3000], x[3000:], y[3000:]


# ---------------------------------------------------------------------------
# engine.train
# ---------------------------------------------------------------------------
def test_train_binary_with_valid(bin_data):
    x, y, xt, yt = bin_data
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": "binary_logloss",
                     "num_leaves": 31, "learning_rate": 0.1, "verbosity": -1},
                    Dataset(x, label=y), num_boost_round=30,
                    valid_sets=[Dataset(xt, label=yt)],
                    valid_names=["v"], evals_result=evals,
                    verbose_eval=False)
    assert bst.current_iteration() == 30
    assert len(evals["v"]["binary_logloss"]) == 30
    assert evals["v"]["binary_logloss"][-1] < 0.55
    pred = bst.predict(xt)
    assert ((pred > 0.5) == (yt > 0)).mean() > 0.75


def test_train_early_stopping(bin_data):
    x, y, xt, yt = bin_data
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": "auc",
                     "num_leaves": 31, "learning_rate": 0.3,
                     "verbosity": -1},
                    Dataset(x, label=y), num_boost_round=400,
                    valid_sets=[Dataset(xt, label=yt)],
                    early_stopping_rounds=5, evals_result=evals,
                    verbose_eval=False)
    assert bst.best_iteration > 0
    assert bst.current_iteration() < 400   # actually stopped early


def test_train_learning_rates_callback(reg_data):
    x, y, _, _ = reg_data
    lrs = []

    def snoop(env):
        lrs.append(env.params.get("learning_rate"))

    lgb.train({"objective": "regression", "verbosity": -1,
               "num_leaves": 15},
              Dataset(x, label=y), num_boost_round=5,
              learning_rates=lambda it: 0.2 * (0.9 ** it),
              callbacks=[snoop], verbose_eval=False)


def test_train_continue_from_init_model(reg_data, tmp_path):
    x, y, xt, yt = reg_data
    p = {"objective": "regression", "metric": "l2", "num_leaves": 15,
         "learning_rate": 0.1, "verbosity": -1}
    bst1 = lgb.train(p, Dataset(x, label=y, free_raw_data=False),
                     num_boost_round=10, verbose_eval=False)
    mse1 = float(np.mean((bst1.predict(xt) - yt) ** 2))
    path = str(tmp_path / "m.txt")
    bst1.save_model(path)
    bst2 = lgb.train(p, Dataset(x, label=y, free_raw_data=False),
                     num_boost_round=10, init_model=path,
                     verbose_eval=False)
    assert bst2.current_iteration() == 20
    mse2 = float(np.mean((bst2.predict(xt) - yt) ** 2))
    assert mse2 < mse1


def test_cv_returns_means_and_stdv(bin_data):
    x, y, _, _ = bin_data
    res = lgb.cv({"objective": "binary", "metric": "auc",
                  "num_leaves": 15, "verbosity": -1},
                 Dataset(x, label=y), num_boost_round=5, nfold=3,
                 stratified=True, verbose_eval=False)
    assert len(res["auc-mean"]) == 5
    assert len(res["auc-stdv"]) == 5
    assert res["auc-mean"][-1] > 0.7


# ---------------------------------------------------------------------------
# sklearn wrappers
# ---------------------------------------------------------------------------
def test_sklearn_classifier(bin_data):
    x, y, xt, yt = bin_data
    clf = lgb.LGBMClassifier(n_estimators=25, num_leaves=31,
                             learning_rate=0.1)
    clf.fit(x, y)
    proba = clf.predict_proba(xt)
    assert proba.shape == (len(yt), 2)
    assert np.allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    acc = (clf.predict(xt) == yt).mean()
    assert acc > 0.75
    imp = clf.feature_importances_
    assert imp.shape == (x.shape[1],) and imp.sum() > 0


def test_sklearn_regressor_custom_objective(reg_data):
    x, y, xt, yt = reg_data

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    reg = lgb.LGBMRegressor(n_estimators=30, num_leaves=15,
                            learning_rate=0.1, objective=l2_obj)
    reg.fit(x, y)
    mse = float(np.mean((reg.predict(xt) - yt) ** 2))
    reg2 = lgb.LGBMRegressor(n_estimators=30, num_leaves=15,
                             learning_rate=0.1)
    reg2.fit(x, y)
    mse2 = float(np.mean((reg2.predict(xt) - yt) ** 2))
    assert mse == pytest.approx(mse2, rel=0.2)


def test_sklearn_ranker():
    rng = np.random.default_rng(3)
    n, q = 1200, 60
    x = rng.standard_normal((n, 5))
    rel = np.clip((x[:, 0] + 0.3 * rng.standard_normal(n)) * 2, 0,
                  4).astype(int)
    group = np.full(q, n // q)
    rk = lgb.LGBMRanker(n_estimators=20, num_leaves=15, learning_rate=0.1)
    rk.fit(x, rel, group=group)
    s = rk.predict(x)
    # within-query ordering should correlate with relevance
    from scipy.stats import spearmanr
    rho = spearmanr(s, rel).statistic
    assert rho > 0.5


def test_sklearn_clone_and_get_params(bin_data):
    from sklearn.base import clone
    clf = lgb.LGBMClassifier(n_estimators=5, num_leaves=7)
    c2 = clone(clf)
    assert c2.get_params()["num_leaves"] == 7


# ---------------------------------------------------------------------------
# basic Dataset / Booster mechanics
# ---------------------------------------------------------------------------
def test_dataset_subset_and_reference(bin_data):
    x, y, _, _ = bin_data
    full = Dataset(x, label=y, params={"verbosity": -1}).construct()
    sub = full.subset(np.arange(0, 2000))
    sub.construct()
    assert sub.num_data() == 2000
    np.testing.assert_array_equal(sub.get_label(), y[:2000])


def test_booster_model_roundtrip_file(bin_data, tmp_path):
    x, y, xt, _ = bin_data
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, Dataset(x, label=y),
                    num_boost_round=8, verbose_eval=False)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    loaded = Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(xt), bst.predict(xt),
                               atol=1e-6)
    assert loaded.num_trees() == bst.num_trees()


def test_booster_feature_importance_and_names(bin_data):
    x, y, _, _ = bin_data
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1},
                    Dataset(x, label=y,
                            feature_name=[f"f{i}" for i in range(8)]),
                    num_boost_round=5, verbose_eval=False)
    assert bst.feature_name() == [f"f{i}" for i in range(8)]
    assert bst.feature_importance().sum() > 0


def test_weights_change_training(reg_data):
    x, y, xt, yt = reg_data
    w = np.where(y > np.median(y), 10.0, 0.1)
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b1 = lgb.train(p, Dataset(x, label=y), num_boost_round=10,
                   verbose_eval=False)
    b2 = lgb.train(p, Dataset(x, label=y, weight=w), num_boost_round=10,
                   verbose_eval=False)
    assert not np.allclose(b1.predict(xt), b2.predict(xt))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def test_cli_train_and_predict(tmp_path, bin_data):
    x, y, xt, yt = bin_data
    train_file = tmp_path / "train.csv"
    pred_file = tmp_path / "test.csv"
    np.savetxt(train_file, np.column_stack([y, x]), delimiter=",")
    np.savetxt(pred_file, np.column_stack([yt, xt]), delimiter=",")
    model_file = tmp_path / "model.txt"
    out_file = tmp_path / "pred.txt"
    from lightgbm_tpu.cli import main
    main([f"data={train_file}", "objective=binary", "num_leaves=15",
          "num_iterations=5", f"output_model={model_file}",
          "verbosity=-1"])
    assert model_file.exists()
    main(["task=predict", f"data={pred_file}",
          f"input_model={model_file}", f"output_result={out_file}",
          "verbosity=-1"])
    preds = np.loadtxt(out_file)
    assert preds.shape[0] == len(yt)
    assert ((preds > 0.5) == (yt > 0)).mean() > 0.7


# ---------------------------------------------------------------------------
def test_parameters_doc_not_stale():
    """docs/Parameters.md is generated from the params schema; a schema
    change without regenerating the doc must fail (the reference keeps
    docs/Parameters.rst in lockstep via helper/parameter_generator.py)."""
    import pathlib
    from lightgbm_tpu.utils.gen_docs import render
    repo = pathlib.Path(__file__).resolve().parents[1]
    committed = (repo / "docs" / "Parameters.md").read_text()
    assert committed == render(), (
        "docs/Parameters.md is stale; regenerate with "
        "`python -m lightgbm_tpu.utils.gen_docs docs/Parameters.md`")


def test_pred_contrib_batch_matches_scalar_oracle():
    """The vectorized TreeSHAP (tree_shap_batch) must agree with the
    per-row recursive oracle bit-for-bit, and contributions must sum to
    the prediction up to the f32-stored expected_value rounding."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.tree.tree import tree_shap_batch

    rng = np.random.default_rng(0)
    n, f = 400, 8
    x = rng.standard_normal((n, f))
    x[rng.random((n, f)) < 0.1] = np.nan
    y = (np.nan_to_num(x[:, 0]) * 2 + np.abs(np.nan_to_num(x[:, 1]))
         + 0.1 * rng.standard_normal(n))
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(x, label=y), num_boost_round=8)
    g = bst._gbdt
    g._flush_pending()
    rows = np.ascontiguousarray(x[:48], np.float64)
    nf = g.max_feature_idx + 1
    want = np.zeros((48, nf + 1))
    for it in range(g.num_iterations()):
        tree = g.models[it]
        for i in range(48):
            tree.predict_contrib_row(rows[i], want[i])
    got = np.zeros((48, nf + 1))
    for it in range(g.num_iterations()):
        tree_shap_batch(g.models[it], rows, got)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12)

    contrib = np.asarray(bst.predict(rows, pred_contrib=True))
    np.testing.assert_allclose(contrib, want, rtol=1e-9, atol=1e-12)
    host_pred = sum(t.predict(rows) for t in g.models)
    np.testing.assert_allclose(contrib.sum(1), host_pred, atol=2e-3)
