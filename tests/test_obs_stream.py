"""Streaming telemetry (docs/Observability.md "Streaming & SLOs"):
rolling-window determinism over replayed timestamps, exporter
bounded-queue drop semantics, SLO pass/fail boundary cases, Prometheus
exposition rendering, serve request-outcome counters, and the
per-window feature-gain telemetry events."""

import importlib.util
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import slo
from lightgbm_tpu.obs.export import (StreamExporter, prometheus_text,
                                     sanitize_metric_name)
from lightgbm_tpu.obs.rolling import HIST_BOUNDS, RollingRegistry
from lightgbm_tpu.obs.state import STATE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "validate_metrics", os.path.join(REPO, "scripts",
                                     "validate_metrics.py"))
validate_metrics = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(validate_metrics)


@pytest.fixture(autouse=True)
def _clean_obs_state():
    def clean():
        exp = STATE.exporter
        STATE.exporter = None
        if exp is not None:
            exp.stop(timeout_s=2.0)
        obs.configure(enabled=False)
        obs.reset()
        STATE.rolling = None
        STATE.rolling_opt_out = False
        STATE.last_slo = None
        STATE.pending_slo_spec = None
        STATE.metrics_path = STATE.trace_path = STATE.events_path = None
    clean()
    yield
    clean()


T0 = 1_700_000_000.0


def _replayed_registry():
    r = RollingRegistry(bucket_seconds=1.0, num_buckets=60,
                        clock=lambda: T0)
    for i in range(100):
        r.observe("serve.predict", 0.001 * (i + 1),
                  now=T0 - 49 + i * 0.4)
    r.inc("serve.ok", 7, now=T0 - 5)
    r.inc("serve.ok", 3, now=T0 - 30)
    r.inc("serve.ok", 99, now=T0 - 300)        # far outside the ring
    r.set_gauge("serve.degraded", 1, now=T0 - 50)
    r.set_gauge("serve.degraded", 0, now=T0 - 20)
    return r


class TestRollingWindow:
    def test_replayed_timestamps_are_deterministic(self):
        a = _replayed_registry().window(60.0, T0)
        b = _replayed_registry().window(60.0, T0)
        assert a == b
        # percentiles are fixed bucket bounds (clamped to window max):
        # the defining property that makes replayed runs byte-identical
        t = a["timings"]["serve.predict"]
        for key in ("p50_s", "p95_s", "p99_s"):
            assert any(abs(t[key] - round(b, 6)) < 1e-12
                       for b in HIST_BOUNDS) or t[key] == t["max_s"]
        assert t["count"] == 100
        assert t["p50_s"] <= t["p95_s"] <= t["p99_s"] <= t["max_s"]

    def test_counter_delta_and_window_expiry(self):
        r = _replayed_registry()
        assert r.counter_delta("serve.ok", 60.0, T0) == 10
        # a 10 s window sees only the T0-5 increment
        assert r.counter_delta("serve.ok", 10.0, T0) == 7
        # everything expires once the window slides past it
        assert r.counter_delta("serve.ok", 60.0, T0 + 120) == 0
        snap = r.window(60.0, T0)
        assert snap["counters"]["serve.ok"]["delta"] == 10
        assert snap["counters"]["serve.ok"]["rate_per_s"] == \
            pytest.approx(10 / 60.0, abs=1e-6)

    def test_gauge_time_weighted_mean(self):
        r = _replayed_registry()
        # degraded 1 from T0-50 to T0-20, 0 after: integration starts
        # at the first known transition -> (30*1 + 20*0) / 50
        assert r.gauge_mean("serve.degraded", 60.0, T0) == \
            pytest.approx(0.6)
        assert r.gauge_last("serve.degraded") == 0
        # value carries FORWARD past the last transition
        assert r.gauge_mean("serve.degraded", 10.0, T0) == \
            pytest.approx(0.0)
        assert r.gauge_mean("never.set", 60.0, T0) is None

    def test_timing_window_excludes_old_samples(self):
        r = RollingRegistry(bucket_seconds=1.0, num_buckets=60)
        r.observe("lat", 5.0, now=T0 - 59)
        r.observe("lat", 0.001, now=T0 - 1)
        full = r.timing_stats("lat", 60.0, T0)
        assert full["count"] == 2 and full["max_s"] == 5.0
        recent = r.timing_stats("lat", 10.0, T0)
        assert recent["count"] == 1
        assert recent["p99_s"] <= 0.0015

    def test_out_of_order_late_write_is_dropped(self):
        r = RollingRegistry(bucket_seconds=1.0, num_buckets=4)
        r.inc("c", 1, now=T0)
        r.inc("c", 1, now=T0 - 100)    # slot now owned by a newer epoch
        assert r.counter_delta("c", 4.0, T0) == 1
        # gauges obey the same contract: a late write never rewinds
        # gauge_last nor creates a negative-weight segment
        r.set_gauge("g", 2, now=T0)
        r.set_gauge("g", 7, now=T0 - 5)
        assert r.gauge_last("g") == 2
        assert r.gauge_mean("g", 4.0, T0) == pytest.approx(2.0)


class TestSloBoundaries:
    def _base(self, ok=999, failed=1, dark=None):
        r = RollingRegistry(bucket_seconds=1.0, num_buckets=120,
                            clock=lambda: T0)
        if ok:
            r.inc("serve.ok", ok, now=T0 - 1)
        if failed:
            r.inc("serve.failed", failed, now=T0 - 1)
        if dark is not None:
            r.set_gauge("serve.degraded", dark, now=T0 - 119)
        return r

    def test_availability_exact_boundary_passes(self):
        r = self._base(ok=999, failed=1)
        spec = slo.SloSpec.parse("availability>=0.999,window_s=120")
        rep = spec.evaluate(rolling=r, now=T0)
        assert rep.objective("availability").observed == \
            pytest.approx(0.999)
        assert rep.ok

    def test_availability_below_boundary_fails(self):
        r = self._base(ok=998, failed=2)
        rep = slo.SloSpec.parse("availability>=0.999,window_s=120") \
            .evaluate(rolling=r, now=T0)
        assert not rep.ok

    def test_input_errors_do_not_count_against_availability(self):
        r = self._base(ok=10, failed=0)
        r.inc("serve.input_errors", 500, now=T0 - 1)
        rep = slo.SloSpec.parse("availability>=1.0,window_s=120") \
            .evaluate(rolling=r, now=T0)
        assert rep.ok
        assert rep.counts["input_errors"] == 500

    def test_dark_time_counts_against_availability(self):
        # every request answered (by fallback), but the breaker was
        # open the whole window: availability collapses to ~0
        r = self._base(ok=0, failed=0, dark=1)
        r.inc("serve.fallback_requests", 100, now=T0 - 1)
        rep = slo.SloSpec.parse("availability>=0.999,window_s=120") \
            .evaluate(rolling=r, now=T0)
        avail = rep.objective("availability")
        assert not avail.ok and avail.observed < 0.05
        assert rep.counts["dark_fraction"] > 0.9

    def test_latency_boundary(self):
        r = self._base()
        for _ in range(100):
            r.observe("serve.predict", 0.010, now=T0 - 1)
        spec = slo.SloSpec.parse("availability>=0.5,window_s=120,"
                                 "p95_ms<=100")
        rep = spec.evaluate(rolling=r, now=T0)
        p95 = rep.objective("p95_ms")
        assert p95.ok and p95.observed == pytest.approx(10.0)
        # a bound exactly AT the observed value still passes (<= + eps)
        tight = slo.SloSpec.parse(
            f"availability>=0.5,window_s=120,p95_ms<={p95.observed}")
        assert tight.evaluate(rolling=r, now=T0).objective("p95_ms").ok
        below = slo.SloSpec.parse("availability>=0.5,window_s=120,"
                                  "p95_ms<=9.9")
        assert not below.evaluate(rolling=r, now=T0).objective(
            "p95_ms").ok

    def test_no_latency_samples_fails_with_detail(self):
        r = self._base()
        rep = slo.SloSpec.parse("p95_ms<=100,window_s=120") \
            .evaluate(rolling=r, now=T0)
        o = rep.objective("p95_ms")
        assert not o.ok and o.observed is None and "no" in o.detail

    def test_burn_rate(self):
        # availability 0.99 against a 0.999 target: burning the error
        # budget at 10x
        r = self._base(ok=990, failed=10)
        rep = slo.SloSpec.parse(
            "availability>=0.999,burn<=10,window_s=120") \
            .evaluate(rolling=r, now=T0)
        burn = rep.objective("burn")
        assert burn.observed == pytest.approx(10.0)
        assert burn.ok                      # exactly at the bound
        rep2 = slo.SloSpec.parse(
            "availability>=0.999,burn<=9.5,window_s=120") \
            .evaluate(rolling=r, now=T0)
        assert not rep2.objective("burn").ok

    def test_freshness(self):
        r = self._base()
        r.set_gauge("pipeline.last_swap_unix", T0 - 12, now=T0 - 12)
        rep = slo.SloSpec.parse("freshness_s<=30,window_s=120") \
            .evaluate(rolling=r, now=T0)
        f = rep.objective("freshness_s")
        assert f.ok and f.observed == pytest.approx(12.0)
        assert not slo.SloSpec.parse("freshness_s<=5,window_s=120") \
            .evaluate(rolling=r, now=T0).objective("freshness_s").ok
        # never swapped -> objective fails with a detail, not a crash
        bare = self._base()
        o = slo.SloSpec.parse("freshness_s<=30,window_s=120") \
            .evaluate(rolling=bare, now=T0).objective("freshness_s")
        assert not o.ok and o.observed is None

    def test_spec_parse_errors(self):
        for bad in ("", "availability<=0.9", "p95_ms>=5", "burn<=2",
                    "nonsense>=1", "availability>=2.0",
                    "availability>=x"):
            with pytest.raises(slo.SloSpecError):
                slo.SloSpec.parse(bad)

    def test_window_beyond_ring_capacity_raises(self):
        # a silently clamped window would turn an outage older than
        # the ring into a FALSE PASS — the evaluator must error loudly
        r = RollingRegistry(bucket_seconds=1.0, num_buckets=120,
                            clock=lambda: T0)
        r.inc("serve.ok", 5, now=T0 - 1)
        spec = slo.SloSpec.parse("availability>=0.999,window_s=600")
        with pytest.raises(slo.SloSpecError, match="capacity"):
            spec.evaluate(rolling=r, now=T0)
        # a registry actually built for 600 s evaluates fine
        big = RollingRegistry(bucket_seconds=5.0, num_buckets=120,
                              clock=lambda: T0)
        big.inc("serve.ok", 5, now=T0 - 1)
        assert spec.evaluate(rolling=big, now=T0).ok

    def test_source_prefix(self):
        r = RollingRegistry(clock=lambda: T0)
        r.inc("serve.fleet.ok", 50, now=T0 - 1)
        rep = slo.SloSpec.parse(
            "source=serve.fleet,availability>=0.999,window_s=60") \
            .evaluate(rolling=r, now=T0)
        assert rep.ok and rep.counts["ok"] == 50


class TestExporter:
    def test_jammed_queue_drops_and_counts(self, tmp_path):
        obs.configure(enabled=True)
        obs.inc("serve.ok", 3)
        exp = StreamExporter(stream_path=str(tmp_path / "s.jsonl"),
                             queue_max=2)
        # writer not started: the bounded queue jams after 2 offers and
        # every further emit() drops NON-BLOCKINGLY
        for _ in range(5):
            exp.emit()
        assert exp.dropped == 3
        assert obs.registry().counter("export.dropped") == 3
        # draining the jam writes the two queued snapshots
        exp.start()
        exp.stop()
        lines = [json.loads(ln)
                 for ln in open(tmp_path / "s.jsonl")]
        assert len(lines) >= 2
        for doc in lines:
            assert validate_metrics.validate_stream_line(doc) == []

    def test_prom_file_and_stream_validate(self, tmp_path):
        obs.configure(enabled=True)
        obs.inc("serve.ok", 4)
        obs.observe("serve.predict", 0.002)
        obs.set_gauge("serve.degraded", 0)
        sp, pp = str(tmp_path / "s.jsonl"), str(tmp_path / "m.prom")
        exp = StreamExporter(stream_path=sp, prom_path=pp)
        exp.flush_now()
        assert validate_metrics.validate_prometheus(open(pp).read()) \
            == []
        doc = json.loads(open(sp).readline())
        assert validate_metrics.validate_stream_line(doc) == []
        assert doc["counters"]["serve.ok"]["delta"] == 4

    def test_write_errors_counted_not_raised(self, tmp_path):
        obs.configure(enabled=True)
        exp = StreamExporter(
            stream_path=str(tmp_path / "no_such_dir" / "s.jsonl"))
        exp.flush_now()      # must not raise
        assert exp.write_errors == 1
        assert obs.registry().counter("export.write_errors") == 1

    def test_configure_idempotent_per_window(self, tmp_path):
        sp = str(tmp_path / "s.jsonl")
        obs.configure(enabled=True, stream_path=sp)
        first = STATE.exporter
        # the per-window configure_from_config path: same target, no
        # thread churn
        obs.configure(enabled=True, stream_path=sp)
        assert STATE.exporter is first

    def test_partial_reconfigure_is_additive(self, tmp_path):
        # env-started stream + param-added prom must coexist: a
        # partial reconfigure inherits the running exporter's targets
        sp, pp = str(tmp_path / "s.jsonl"), str(tmp_path / "m.prom")
        obs.configure(enabled=True, stream_path=sp)
        obs.configure(enabled=True, prom_path=pp)
        assert STATE.exporter.stream_path == sp
        assert STATE.exporter.prom_path == pp

    def test_unevaluable_spec_is_counted_not_silent(self, tmp_path):
        # parses fine, but the window exceeds the default ring: each
        # tick must count the failure (and warn once), never crash
        obs.configure(enabled=True)
        exp = StreamExporter(stream_path=str(tmp_path / "s.jsonl"),
                             slo_spec="availability>=0.999,window_s=900")
        exp.flush_now()
        exp.flush_now()
        assert obs.registry().counter("export.slo_errors") == 2
        for ln in open(tmp_path / "s.jsonl"):
            assert "slo" not in json.loads(ln)

    def test_malformed_slo_spec_raises_at_configure(self, tmp_path):
        with pytest.raises(slo.SloSpecError):
            StreamExporter(stream_path=str(tmp_path / "s.jsonl"),
                           slo_spec="availabilty>=0.999")   # typo
        with pytest.raises(slo.SloSpecError):
            obs.configure(enabled=True,
                          stream_path=str(tmp_path / "s2.jsonl"),
                          slo_spec="p95_ms>=5")
        # ...and even with no exporter at all: the spec is validated,
        # not silently dropped
        with pytest.raises(slo.SloSpecError):
            obs.configure(enabled=True, slo_spec="availabilty>=0.999")

    def test_slo_spec_without_exporter_adopted_later(self, tmp_path):
        # configure(slo_spec=) before any export target: the spec is
        # stashed and the next exporter start picks it up
        obs.configure(enabled=True, slo_spec="availability>=0.999")
        assert STATE.pending_slo_spec is not None
        obs.inc("serve.ok", 5)
        sp = str(tmp_path / "s.jsonl")
        obs.configure(enabled=True, stream_path=sp)
        obs.flush()
        doc = json.loads(open(sp).readline())
        assert doc["slo"]["ok"] is True

    def test_failing_evaluation_clears_stale_digest(self, tmp_path):
        obs.configure(enabled=True)
        obs.inc("serve.ok", 5)
        sp = str(tmp_path / "s.jsonl")
        exp = StreamExporter(stream_path=sp,
                             slo_spec="availability>=0.9")
        exp.flush_now()
        assert STATE.last_slo is not None
        # the rolling mirror disappears: evaluation starts failing and
        # the stale "ok" digest must stop riding on fresh lines
        STATE.rolling = None
        exp.flush_now()
        assert STATE.last_slo is None
        last = json.loads(open(sp).readlines()[-1])
        assert "slo" not in last

    def test_rolling_opt_out_is_sticky(self):
        obs.configure(enabled=True, rolling=False)
        assert STATE.rolling is None
        # the per-window configure_from_config path must not undo it
        obs.configure(enabled=True)
        assert STATE.rolling is None
        obs.configure(enabled=True, rolling=True)
        assert STATE.rolling is not None

    def test_scrape_endpoint(self, tmp_path):
        from urllib.request import urlopen
        obs.configure(enabled=True)
        obs.inc("serve.ok", 2)
        exp = StreamExporter(http_port=0).start()
        try:
            exp.flush_now()
            body = urlopen(
                f"http://127.0.0.1:{exp.http_port}/metrics",
                timeout=5).read().decode()
        finally:
            exp.stop()
        assert validate_metrics.validate_prometheus(body) == []
        assert "lgbm_serve_ok_total 2" in body


class TestPrometheusText:
    def test_sanitize_and_dedup(self):
        assert sanitize_metric_name("serve.fleet.tenant.0.rows") == \
            "lgbm_serve_fleet_tenant_0_rows"
        # two raw names colliding after sanitization: one sample, one
        # collision — never a duplicate-sample exposition
        snap = {"counters": {"a.b": 1, "a_b": 2}, "gauges": {},
                "timings": {}}
        text, collisions = prometheus_text(snap)
        assert collisions == 1
        assert validate_metrics.validate_prometheus(text) == []

    def test_summary_quantiles_prefer_rolling(self):
        cum = {"counters": {}, "gauges": {},
               "timings": {"serve.predict": {
                   "count": 10, "total_s": 1.0, "mean_s": 0.1,
                   "p50_s": 0.1, "p95_s": 0.2, "max_s": 0.3}}}
        roll = {"timings": {"serve.predict": {
            "count": 4, "total_s": 0.02, "mean_s": 0.005,
            "p50_s": 0.004, "p95_s": 0.006, "p99_s": 0.007,
            "max_s": 0.008}}}
        text, _ = prometheus_text(cum, roll)
        assert 'quantile="0.5"} 0.004' in text      # rolling, not 0.1
        assert "_sum 1" in text                     # cumulative sum
        assert validate_metrics.validate_prometheus(text) == []


def _small_booster(rounds=4):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((500, 6))
    y = (x[:, 0] + x[:, 1] ** 2 > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "metric": "none", "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(x, label=y),
                    num_boost_round=rounds, verbose_eval=False)
    return bst, x


class TestServeOutcomeCounters:
    def test_healthy_prefix_anchors_dark_fraction(self):
        # every device success writes serve.degraded=0, so a breaker
        # trip late in a window integrates as a PARTIAL dark fraction,
        # not a full-window outage
        from lightgbm_tpu.serve.engine import PredictionServer
        obs.configure(enabled=True)
        bst, x = _small_booster()
        srv = PredictionServer(bst)
        srv.predict(x[:64])
        trans = STATE.rolling._gauges.get("serve.degraded")
        assert trans and trans[-1][1] == 0

    def test_ok_and_input_error_distinguished(self):
        from lightgbm_tpu.serve.engine import PredictionServer
        from lightgbm_tpu.utils.log import LightGBMError
        obs.configure(enabled=True)
        bst, x = _small_booster()
        srv = PredictionServer(bst)
        srv.predict(x[:64])
        assert obs.registry().counter("serve.ok") == 1
        assert STATE.rolling.counter_delta("serve.ok") == 1
        with pytest.raises(LightGBMError):
            srv.predict(x[:8, :2])       # too narrow: an input fault
        assert obs.registry().counter("serve.input_errors") == 1
        assert obs.registry().counter("serve.failed") == 0

    def test_breaker_live_dark_seconds(self):
        from lightgbm_tpu.robust import CircuitBreaker
        t = [100.0]
        br = CircuitBreaker(failure_threshold=1, reprobe_interval_s=50,
                            clock=lambda: t[0])
        assert br.dark_seconds() == 0.0
        br.record_failure()              # trips at t=100
        t[0] = 103.0
        # still open: live accounting, no recovery needed
        assert br.dark_seconds() == pytest.approx(3.0)
        assert br.record_success() == pytest.approx(3.0)
        assert br.dark_seconds() == pytest.approx(3.0)   # accumulated
        br.record_failure()
        t[0] = 105.0
        assert br.dark_seconds() == pytest.approx(5.0)

    def test_slo_over_live_serving(self):
        from lightgbm_tpu.serve.engine import PredictionServer
        obs.configure(enabled=True)
        bst, x = _small_booster()
        srv = PredictionServer(bst)
        for _ in range(10):
            srv.predict(x[:64])
        rep = slo.evaluate("availability>=0.999,p95_ms<=60000")
        assert rep.ok and rep.counts["ok"] == 10
        assert obs.summary()["slo"]["ok"] is True
        assert obs.snapshot()["slo"]["ok"] is True


class TestWindowFeatureTelemetry:
    def test_per_window_gain_events(self, tmp_path):
        from lightgbm_tpu.pipeline import PreppedWindow, RetrainPipeline
        obs.configure(enabled=True)

        def prep(w):
            rng = np.random.default_rng(100 + w)
            x = rng.standard_normal((800, 6))
            y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
            return PreppedWindow(label=y, dense=x)

        pipe = RetrainPipeline(
            {"objective": "binary", "num_leaves": 7, "verbosity": -1,
             "metric": "none", "num_iterations": 3,
             "min_data_in_leaf": 5},
            window_policy="fresh", rebin_on_drift=False, serve=False,
            pipelined=False)
        pipe.run(range(2), prep)

        path = tmp_path / "events.jsonl"
        obs.dump_events_jsonl(str(path))
        events = [json.loads(ln) for ln in open(path)]
        feats = [e for e in events
                 if e["name"] == "pipeline.window_features"]
        assert len(feats) == 2
        windows = sorted(e["args"]["window"] for e in feats)
        assert windows == [0, 1]
        for e in feats:
            args = e["args"]
            assert e["kind"] == "instant" and e["cat"] == "pipeline"
            assert args["policy"] == "fresh"
            assert args["features"] == 6
            assert args["total_gain"] > 0
            assert args["top"], "no features with positive gain?"
            for f, gain, splits in args["top"]:
                assert isinstance(f, int) and 0 <= f < 6
                assert gain > 0 and isinstance(splits, int)
            # split counts are bounded by the ensemble's split total
            assert sum(t[2] for t in args["top"]) <= 3 * 6
        assert obs.registry().counter("pipeline.feature_events") == 2
        assert obs.registry().gauge("pipeline.gain_top_share") > 0
        # the freshness anchor only lands when serving swaps; with
        # serve=False it must stay unset rather than lie
        assert obs.registry().gauge("pipeline.last_swap_unix") is None
