"""End-to-end tests of the device ops + serial tree learner (no boosting)."""

import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.ops.histogram import build_histogram, bucket_size
from lightgbm_tpu.tree.learner import SerialTreeLearner


def _make_dataset(x, config, categorical=()):
    return BinnedDataset.construct_from_matrix(x, config, categorical)


def test_histogram_matches_numpy():
    rng = np.random.RandomState(0)
    n, f = 5000, 6
    x = rng.randn(n, f)
    cfg = Config({"max_bin": 63, "min_data_in_leaf": 1, "min_data_in_bin": 1})
    ds = _make_dataset(x, cfg)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32) + 0.1

    m = bucket_size(n)
    idx = np.zeros(m, np.int32)
    idx[:n] = np.arange(n)
    hist = np.asarray(build_histogram(
        jnp.asarray(ds.binned), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(idx), n))

    # numpy reference: per group, accumulate by slot
    for gid in range(ds.num_groups):
        slots = ds.binned[:, gid]
        expect_g = np.bincount(slots, weights=g, minlength=256)
        expect_h = np.bincount(slots, weights=h, minlength=256)
        expect_c = np.bincount(slots, minlength=256)
        np.testing.assert_allclose(hist[gid, :, 0], expect_g, rtol=2e-4,
                                   atol=2e-4)
        np.testing.assert_allclose(hist[gid, :, 1], expect_h, rtol=2e-4,
                                   atol=2e-4)
        np.testing.assert_allclose(hist[gid, :, 2], expect_c, atol=0.5)


def test_single_tree_reduces_mse():
    rng = np.random.RandomState(42)
    n = 4000
    x = rng.randn(n, 5)
    y = (2.0 * (x[:, 0] > 0.3) + x[:, 1] * 1.5
         + np.sin(3 * x[:, 2]) + 0.05 * rng.randn(n))
    cfg = Config({"num_leaves": 31, "min_data_in_leaf": 20})
    ds = _make_dataset(x, cfg)
    learner = SerialTreeLearner(cfg, ds)

    # L2 objective: grad = pred - y with pred = 0
    grad = jnp.asarray(np.asarray(0.0 - y, np.float32))
    hess = jnp.ones(n, jnp.float32)
    tree = learner.train(grad, hess)

    assert tree.num_leaves > 1
    pred = tree.predict(x)
    mse0 = np.mean(y ** 2)
    mse1 = np.mean((y - pred) ** 2)
    assert mse1 < 0.5 * mse0
    # leaf partition must agree with tree prediction routing
    leaf_idx = learner.leaf_indices_host()
    pred_leaf = tree.predict_leaf(x)
    for leaf, idx in leaf_idx.items():
        assert (pred_leaf[idx] == leaf).all(), f"leaf {leaf} routing mismatch"


def test_score_update_matches_prediction():
    rng = np.random.RandomState(1)
    n = 2000
    x = rng.randn(n, 4)
    y = x[:, 0] - 2 * x[:, 1] + 0.1 * rng.randn(n)
    cfg = Config({"num_leaves": 15, "min_data_in_leaf": 10})
    ds = _make_dataset(x, cfg)
    learner = SerialTreeLearner(cfg, ds)
    grad = jnp.asarray(np.asarray(-y, np.float32))
    hess = jnp.ones(n, jnp.float32)
    tree = learner.train(grad, hess)

    score = jnp.zeros(n, jnp.float32)
    score = learner.update_score(score, tree)
    np.testing.assert_allclose(np.asarray(score), tree.predict(x), rtol=1e-5,
                               atol=1e-5)


def test_min_data_in_leaf_respected():
    rng = np.random.RandomState(3)
    n = 1000
    x = rng.randn(n, 3)
    y = x[:, 0] + rng.randn(n) * 0.01
    cfg = Config({"num_leaves": 63, "min_data_in_leaf": 50})
    ds = _make_dataset(x, cfg)
    learner = SerialTreeLearner(cfg, ds)
    tree = learner.train(jnp.asarray(np.asarray(-y, np.float32)),
                         jnp.ones(n, jnp.float32))
    counts = tree.leaf_count[:tree.num_leaves]
    assert (counts >= 50).all()
    assert counts.sum() == n


def test_categorical_split():
    rng = np.random.RandomState(7)
    n = 3000
    cat = rng.randint(0, 8, n)
    noise = rng.randn(n, 2)
    y = np.where(np.isin(cat, [1, 3, 5]), 2.0, -1.0) + 0.05 * rng.randn(n)
    x = np.column_stack([cat.astype(np.float64), noise])
    cfg = Config({"num_leaves": 8, "min_data_in_leaf": 20,
                  "max_cat_to_onehot": 4})
    ds = _make_dataset(x, cfg, categorical=[0])
    learner = SerialTreeLearner(cfg, ds)
    tree = learner.train(jnp.asarray(np.asarray(-y, np.float32)),
                         jnp.ones(n, jnp.float32))
    pred = tree.predict(x)
    assert np.mean((y - pred) ** 2) < 0.1 * np.mean(y ** 2)
    assert tree.num_cat > 0


def test_monotone_constraints():
    rng = np.random.RandomState(11)
    n = 4000
    x = rng.uniform(-2, 2, (n, 2))
    y = 1.5 * x[:, 0] + np.sin(2 * x[:, 1]) + 0.1 * rng.randn(n)
    cfg = Config({"num_leaves": 31, "min_data_in_leaf": 20,
                  "monotone_constraints": [1, 0]})
    ds = _make_dataset(x, cfg)
    learner = SerialTreeLearner(cfg, ds)
    tree = learner.train(jnp.asarray(np.asarray(-y, np.float32)),
                         jnp.ones(n, jnp.float32))
    # brute-force monotonicity scan on feature 0 (reference
    # test_engine.py:663-702 style)
    probe = np.tile(np.median(x, axis=0), (200, 1))
    probe[:, 0] = np.linspace(-2, 2, 200)
    pred = tree.predict(probe)
    assert (np.diff(pred) >= -1e-10).all()
