"""Per-host worker process for tests/test_multihost.py (and
scripts/check_multihost.py).

One OS process per pod host: the driver launches ``hosts`` copies with
ranks 0..hosts-1 against a localhost coordinator, each forcing
``4 // hosts`` CPU devices so every leg (1, 2 or 4 processes) runs the
SAME 4-device global mesh — the mesh-invariant program signature plus
the int32 quant scan is what makes the legs byte-identical
(docs/Sharding.md).  Prints exactly one JSON line and mirrors it to
``<outdir>/<scenario>_r<rank>.json`` (stdout of a dead rank is lost;
the files let the driver post-mortem).  A pod bring-up failure in this
container (gloo/jax.distributed unavailable) is reported as
``{"skip": reason}`` — environmental, the contract is validated on
real pod slices.

Usage: python _multihost_worker.py makedata <outdir>
       python _multihost_worker.py <scenario> <rank> <hosts> <port> <outdir>
Scenarios: train | bagff | bench | killA | killB | deadcoord
"""

import json
import os
import sys

TOTAL_DEVICES = 4
ROWS = 2500
FEATURES = 8
BASE = {
    "objective": "binary", "verbosity": -1, "device_growth": "on",
    "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
    "seed": 20260804, "wave_plan": "fixed", "grad_quant_bits": 8,
    "two_round": True,
}
BAGFF = {"bagging_fraction": 0.7, "bagging_freq": 2,
         "feature_fraction": 0.75}
CSV_NAME = "pod_train.csv"
CKPT2 = "pod_ck_iter2.txt"
CKPT4 = "pod_ck_iter4.txt"
#: killA's victim exits with this code so drivers can tell the
#: intentional death from a crash
KILLED_EXIT = 17


def data_path(outdir):
    return os.path.join(outdir, CSV_NAME)


def write_csv(outdir):
    """Deterministic label-first CSV shared by every leg (same bytes =>
    same reservoir sample => same mappers on every loader path)."""
    import numpy as np
    rng = np.random.default_rng(11)
    x = rng.standard_normal((ROWS, FEATURES)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    path = data_path(outdir)
    with open(path, "w") as fh:
        for i in range(ROWS):
            fh.write(",".join([repr(float(y[i]))]
                              + [repr(float(v)) for v in x[i]]) + "\n")
    return path


def trees_of(model_str):
    """Model string minus the parameters echo (host_rank legitimately
    differs per host)."""
    return model_str.split("\nparameters:", 1)[0]


def _params(rank, hosts, port, extra=None):
    p = dict(BASE)
    if hosts > 1:
        p.update({"data_sharding": "multi_controller",
                  "coordinator_address": f"localhost:{port}",
                  "num_hosts": hosts, "host_rank": rank,
                  "network_timeout": 2, "network_retries": 5})
    else:
        p.update({"data_sharding": "single_controller",
                  "shard_devices": TOTAL_DEVICES})
    p.update(extra or {})
    return p


def _probe_pod(cfg):
    """Bring-up + one psum across the pod mesh — the exact plumbing
    training uses.  None when healthy, else the skip reason."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P
        from lightgbm_tpu.ops.shard import (make_pod_mesh,
                                            multihost_setup,
                                            shard_map_compat)
        multihost_setup(cfg)
        mesh = make_pod_mesh()
        out = jax.jit(shard_map_compat(
            lambda x: jax.lax.psum(x, "shards"), mesh,
            (P("shards"),), P()))(
            jnp.arange(int(mesh.devices.size) * 2, dtype=jnp.float32))
        float(np.asarray(out).sum())
        return None
    except Exception as e:   # noqa: BLE001 — any env failure is a skip
        return f"{type(e).__name__}: {e}"


def _load(params, csv):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.stream_loader import (load_text_multihost,
                                                 load_text_two_round)
    cfg = Config(params)
    if params.get("data_sharding") == "multi_controller":
        ds, _ = load_text_multihost(csv, cfg)
    else:
        ds, _ = load_text_two_round(csv, cfg)
    return cfg, ds


def _boost(cfg, ds):
    from lightgbm_tpu.boosting import create_boosting
    bst = create_boosting(cfg)
    bst.init_train(ds)
    return bst


def _train(cfg, ds, iters=6, chunk=2):
    bst = _boost(cfg, ds)
    bst.train_chunked(iters, chunk=chunk)
    bst._flush_pending()
    return bst


def _total_compiles():
    from lightgbm_tpu import obs
    snap = obs.registry().snapshot()
    return sum(v["compiles"] for v in snap["jit"].values())


def scenario_train(rank, hosts, port, outdir):
    """6-iteration quant8 training + layout digest + warm-window
    retrace count (a second same-shape window must compile NOTHING)."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.pipeline.bins import reference_layout_digest
    obs.configure(enabled=True)
    cfg, ds = _load(_params(rank, hosts, port), data_path(outdir))
    bst = _train(cfg, ds)
    out = {"trees": trees_of(bst.model_to_string()),
           "layout_digest": reference_layout_digest(ds),
           "hosts_gauge": obs.registry().snapshot()["gauges"].get(
               "shard.hosts"),
           "ingest_rows_per_s": obs.registry().snapshot()["gauges"].get(
               "ingest.rows_per_s")}
    before = _total_compiles()
    _train(cfg, ds)
    out["warm_new_compiles"] = _total_compiles() - before
    return out


def scenario_bench(rank, hosts, port, outdir):
    """Timed leg for ``bench.py --suite shard --hosts N``: 2 warmup
    iterations (compile window), then 4 timed — every host times its
    own dispatch loop, the driver reads host 0's number (the pod runs
    in lockstep; stragglers show up as identical times everywhere)."""
    import time
    from lightgbm_tpu import obs
    obs.configure(enabled=True)
    t0 = time.perf_counter()
    cfg, ds = _load(_params(rank, hosts, port), data_path(outdir))
    load_s = time.perf_counter() - t0
    bst = _boost(cfg, ds)
    bst.train_chunked(2, chunk=2)
    bst._flush_pending()
    t0 = time.perf_counter()
    bst.train_chunked(4, chunk=2)
    bst._flush_pending()
    timed_s = time.perf_counter() - t0
    snap = obs.registry().snapshot()
    return {"ms_per_tree": round(timed_s / 4 * 1e3, 2),
            "load_s": round(load_s, 3),
            "trees": trees_of(bst.model_to_string()),
            "ingest_rows_per_s": snap["gauges"].get("ingest.rows_per_s"),
            "broadcast_bytes": snap["counters"].get(
                "net.broadcast_bytes", 0)}


def scenario_bagff(rank, hosts, port, outdir):
    """Bagging + feature_fraction must be host-count-invariant: the
    draws key on canonical GLOBAL shapes, not per-host ones."""
    cfg, ds = _load(_params(rank, hosts, port, BAGFF),
                    data_path(outdir))
    bst = _train(cfg, ds)
    return {"trees": trees_of(bst.model_to_string())}


def scenario_kill_a(rank, hosts, port, outdir):
    """Phase A of the kill-one-host contract: snapshot at iteration 2
    commits on every host, then the LAST rank dies before acking the
    iteration-4 snapshot — host 0 must time out naming it and leave NO
    commit marker (the snapshot never becomes resumable)."""
    from lightgbm_tpu.robust.checkpoint import has_pod_commit
    from lightgbm_tpu.utils.log import LightGBMError
    import numpy as np
    cfg, ds = _load(_params(rank, hosts, port), data_path(outdir))
    ck2 = os.path.join(outdir, CKPT2)
    ck4 = os.path.join(outdir, CKPT4)
    bst = _boost(cfg, ds)
    bst.train_chunked(2, chunk=2)
    bst.save_checkpoint(ck2)
    bst.train_chunked(2, chunk=2)
    victim = hosts - 1
    if rank == victim:
        # drain this host's dispatched collectives so the survivors'
        # in-flight programs complete, then die without acking
        bst._flush_pending()
        np.asarray(bst.train_score)
        os._exit(KILLED_EXIT)
    err = None
    try:
        bst.save_checkpoint(ck4)
    except LightGBMError as e:
        err = str(e)
    return {"commit2": has_pod_commit(ck2),
            "commit4": has_pod_commit(ck4),
            "victim": victim, "ack_timeout_error": err}


def scenario_kill_b(rank, hosts, port, outdir):
    """Phase B: a fresh pod refuses the uncommitted iteration-4
    snapshot, resumes from the committed iteration-2 one, and finishes
    byte-identical to an uninterrupted 6-iteration run."""
    from lightgbm_tpu.robust.checkpoint import has_pod_commit
    from lightgbm_tpu.utils.log import LightGBMError
    cfg, ds = _load(_params(rank, hosts, port), data_path(outdir))
    ck2 = os.path.join(outdir, CKPT2)
    ck4 = os.path.join(outdir, CKPT4)
    out = {"commit2": has_pod_commit(ck2),
           "commit4": has_pod_commit(ck4)}
    bst = _boost(cfg, ds)
    try:
        bst.resume_from_checkpoint(ck4)
        out["uncommitted_refused"] = False
    except LightGBMError:
        out["uncommitted_refused"] = True
    bst.resume_from_checkpoint(ck2)
    bst.train_chunked(4, chunk=2)
    bst._flush_pending()
    out["trees"] = trees_of(bst.model_to_string())
    return out


def scenario_deadcoord(rank, hosts, port, outdir):
    """Fail-fast bring-up: a rank whose coordinator never answers must
    raise the bounded peer-probe error, not hang in initialize."""
    import time
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ops.shard import multihost_setup
    from lightgbm_tpu.utils.log import LightGBMError
    cfg = Config(_params(1, 2, port, {"network_timeout": 1,
                                      "network_retries": 3}))
    t0 = time.perf_counter()
    try:
        multihost_setup(cfg)
        return {"failfast_error": None,
                "elapsed_s": time.perf_counter() - t0}
    except LightGBMError as e:
        return {"failfast_error": str(e),
                "elapsed_s": time.perf_counter() - t0}


def main():
    scenario = sys.argv[1]
    if scenario == "makedata":
        write_csv(sys.argv[2])
        print(json.dumps({"ok": True}))
        return 0
    rank, hosts = int(sys.argv[2]), int(sys.argv[3])
    port, outdir = int(sys.argv[4]), sys.argv[5]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count="
          f"{TOTAL_DEVICES // hosts}").strip()
    os.environ.setdefault("LGBM_TPU_CHUNK", "8192")
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    if scenario == "deadcoord":
        out = scenario_deadcoord(rank, hosts, port, outdir)
    else:
        if hosts > 1:
            from lightgbm_tpu.config import Config
            reason = _probe_pod(Config(_params(rank, hosts, port)))
            if reason is not None:
                out = {"skip": f"pod bring-up failed (environmental, "
                               f"see ROADMAP memory note): {reason}"}
                print(json.dumps(out))
                _write(outdir, scenario, rank, out)
                return 0
        fn = {"train": scenario_train, "bagff": scenario_bagff,
              "bench": scenario_bench,
              "killA": scenario_kill_a, "killB": scenario_kill_b}.get(
            scenario)
        if fn is None:
            raise SystemExit(f"unknown scenario {scenario!r}")
        out = fn(rank, hosts, port, outdir)
    out["scenario"] = scenario
    out["rank"] = rank
    print(json.dumps(out), flush=True)
    _write(outdir, scenario, rank, out)
    if scenario == "killA":
        # skip interpreter teardown: the jax.distributed shutdown
        # barrier aborts the process when it notices the (deliberately)
        # dead victim — the result is already on disk
        os._exit(0)
    return 0


def _write(outdir, scenario, rank, out):
    path = os.path.join(outdir, f"{scenario}_r{rank}.json")
    with open(path + ".tmp", "w") as fh:
        json.dump(out, fh)
    os.replace(path + ".tmp", path)


if __name__ == "__main__":
    sys.exit(main())
