"""Booster.refit / GBDT.refit_leaves in the multi-window loop.

The fork's windowed harness warm-starts each window from the previous
ensemble (ROADMAP item 5); ``refit``/``refit_decay_rate`` existed but
had never been exercised in any loop.  These tests pin the contract:
routing structure preserved, decay semantics exact, the leaf formula
equal to the reference's ``CalculateSplittedLeafOutput`` on new-data
gradients, and multi-window refit quality no worse than fresh retrains
on a stationary stream.
"""

import numpy as np
import pytest

from lightgbm_tpu import basic as lgb


def _binary_window(seed, n=4000, nf=8):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nf))
    y = (x[:, 0] + 0.5 * x[:, 1]
         + 0.3 * rng.standard_normal(n) > 0).astype(np.float64)
    return x, y


def _train(x, y, params, iters=15):
    ds = lgb.Dataset(x, label=y, params=params)
    bst = lgb.Booster(params=params, train_set=ds)
    bst.update_chunked(iters, chunk=5)
    return bst


PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none"}


def _assert_same_structure(a, b):
    """Routing structure equal: split features exact, thresholds to
    text-round-trip precision (refit clones via model_to_string)."""
    assert len(a.models) == len(b.models)
    for ta, tb in zip(a.models, b.models):
        assert ta.num_leaves == tb.num_leaves
        n = ta.num_leaves - 1
        np.testing.assert_array_equal(ta.split_feature[:n],
                                      tb.split_feature[:n])
        np.testing.assert_allclose(ta.threshold[:n], tb.threshold[:n],
                                   rtol=1e-12, atol=1e-30)


def test_refit_preserves_structure_and_decay_semantics():
    x, y = _binary_window(0)
    bst = _train(x, y, PARAMS)
    x2, y2 = _binary_window(1)

    # decay=1.0: leaf values must be UNCHANGED (new = 1*old + 0*opt)
    same = bst.refit(x2, y2, decay_rate=1.0)
    for t0, t1 in zip(bst._gbdt.models, same._gbdt.models):
        np.testing.assert_allclose(t0.leaf_value[:t0.num_leaves],
                                   t1.leaf_value[:t1.num_leaves])

    # decay=0.5: structure identical, values moved
    rb = bst.refit(x2, y2, decay_rate=0.5)
    _assert_same_structure(rb._gbdt, bst._gbdt)
    moved = any(
        not np.allclose(t0.leaf_value[:t0.num_leaves],
                        t1.leaf_value[:t1.num_leaves])
        for t0, t1 in zip(bst._gbdt.models, rb._gbdt.models))
    assert moved
    # the original booster is untouched (refit clones)
    again = bst.refit(x2, y2, decay_rate=0.5)
    for t0, t1 in zip(rb._gbdt.models, again._gbdt.models):
        np.testing.assert_allclose(t0.leaf_value[:t0.num_leaves],
                                   t1.leaf_value[:t1.num_leaves])


def test_refit_leaf_formula_matches_reference_math():
    """decay=0, l1=l2=0, regression: every non-empty leaf's refit value
    must be exactly learning_rate * mean(y - pred) over its rows
    (-sum_grad / sum_hess with grad = pred - y, hess = 1)."""
    params = {"objective": "regression", "num_leaves": 8, "max_bin": 63,
              "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
              "lambda_l1": 0.0, "lambda_l2": 0.0, "learning_rate": 0.1}
    rng = np.random.default_rng(2)
    x = rng.standard_normal((3000, 6))
    y = x[:, 0] * 2.0 + rng.standard_normal(3000) * 0.1
    bst = _train(x, y, params, iters=5)
    x2 = rng.standard_normal((2000, 6))
    y2 = x2[:, 0] * 2.0 + rng.standard_normal(2000) * 0.1

    rb = bst.refit(x2, y2, decay_rate=0.0)
    pred = bst.predict(x2)          # gradients taken at the model's preds
    for t_old, t_new in zip(bst._gbdt.models, rb._gbdt.models):
        leaves = t_old.predict_leaf(x2)
        for leaf in range(t_old.num_leaves):
            rows = leaves == leaf
            if not rows.any():
                # empty leaves keep their old value
                np.testing.assert_allclose(t_new.leaf_value[leaf],
                                           t_old.leaf_value[leaf])
                continue
            expect = 0.1 * float(np.mean(y2[rows] - pred[rows]))
            np.testing.assert_allclose(t_new.leaf_value[leaf], expect,
                                       rtol=1e-5, atol=1e-7)


def test_refit_multiwindow_quality_no_worse_than_fresh():
    """The harness loop: N windows from a stationary stream.  Policy A
    retrains fresh every window; policy B trains once and refits leaf
    values each window.  Refit quality (AUC on the NEXT window) must be
    within noise of the fresh retrain — the satellite contract that
    warm starts don't cost accuracy on stationary traffic."""
    pytest.importorskip("sklearn")
    from sklearn.metrics import roc_auc_score

    windows = [_binary_window(10 + w, n=5000) for w in range(4)]
    fresh_aucs, refit_aucs = [], []
    refit_bst = None
    for w in range(3):
        x, y = windows[w]
        xn, yn = windows[w + 1]
        fresh = _train(x, y, PARAMS)
        fresh_aucs.append(roc_auc_score(yn, fresh.predict(xn)))
        refit_bst = fresh if refit_bst is None \
            else refit_bst.refit(x, y, decay_rate=0.9)
        refit_aucs.append(roc_auc_score(yn, refit_bst.predict(xn)))
    assert min(refit_aucs) > 0.85, (refit_aucs, fresh_aucs)
    assert np.mean(refit_aucs) >= np.mean(fresh_aucs) - 0.02, \
        (refit_aucs, fresh_aucs)


def test_refit_multiclass_and_loaded_objective_extras():
    """Multiclass refit runs per-class gradients; a model loaded from
    string keeps its objective extras (sigmoid) through refit."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2000, 6))
    y = (x[:, 0] > 0).astype(np.float64) + (x[:, 1] > 0)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 8,
              "min_data_in_leaf": 5, "verbosity": -1, "metric": "none"}
    bst = _train(x, y, params, iters=4)
    rb = bst.refit(x, y, decay_rate=0.3)
    assert rb.num_model_per_iteration() == 3
    _assert_same_structure(rb._gbdt, bst._gbdt)

    # sigmoid extra survives the string round-trip into refit gradients
    x2, y2 = _binary_window(4, n=1500)
    b2 = _train(x2, y2, {**PARAMS, "sigmoid": 2.0})
    loaded = lgb.Booster(model_str=b2.model_to_string(), params={})
    obj = loaded._gbdt._refit_objective()
    assert obj.sigmoid == pytest.approx(2.0)
