"""CI-entrypoint pieces: docs freshness and the metrics-validator
self-test (scripts/check.sh runs the same gates plus ruff/jaxlint)."""

import importlib.util
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _load(script_name, module_name):
    spec = importlib.util.spec_from_file_location(
        module_name, REPO / "scripts" / script_name)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_parameter_docs_are_fresh():
    mod = _load("check_docs_params.py", "_check_docs_params")
    assert mod.main([]) == 0, (
        "docs/Parameters.md is stale; regenerate with "
        "`python scripts/check_docs_params.py --write`")


def test_parameter_docs_check_catches_drift(tmp_path, monkeypatch):
    mod = _load("check_docs_params.py", "_check_docs_params_drift")
    doc = tmp_path / "Parameters.md"
    doc.write_text("# stale\n")
    monkeypatch.setattr(mod, "DOC", doc)
    assert mod.main([]) == 1
    assert mod.main(["--write"]) == 0
    assert mod.main([]) == 0


def test_validate_metrics_self_test():
    mod = _load("validate_metrics.py", "_validate_metrics")
    assert mod.self_test() == 0
    # and via the CLI flag, as check.sh invokes it
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "validate_metrics.py"),
         "--self-test"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def test_check_sh_exists_and_is_executable():
    sh = REPO / "scripts" / "check.sh"
    assert sh.exists()
    assert sh.stat().st_mode & 0o111, "scripts/check.sh must be executable"
    # every gate is wired in (cheap textual pin so a refactor that drops
    # one fails here rather than silently in CI)
    text = sh.read_text()
    for needle in ("ruff", "jaxlint", "--self-test", "check_docs_params",
                   "pytest"):
        assert needle in text, f"check.sh lost its {needle} gate"
