"""Real multi-process (multi-controller) execution.

Spawns 2 OS processes under ``jax.distributed`` (the analog of two
LightGBM machines over the socket linker,
``src/network/linkers_socket.cpp:20-100``) and asserts:

* the serialized-BinMapper allgather (``jax_process_gather``) produces
  IDENTICAL full mapper lists on every process, equal to a
  single-process reference computation;
* a data-parallel histogram + best-split step over a global mesh
  spanning both processes (shard_map + psum across process boundaries)
  matches the single-process numpy result exactly on both ranks.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.distributed import (allgather_mappers,
                                           find_bin_shard)


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# enforced by pytest-timeout when installed, else by the SIGALRM
# fallback fixture in conftest.py — either way the 420 s cap is real.
# slow: two fresh interpreters each pay full jax + XLA compile startup —
# minutes of wall-clock tier-1 can't spare (scripts/check.sh full mode
# runs the slow set in its own step)
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_two_process_distributed(tmp_path):
    nproc = 2
    coord = f"localhost:{_free_port()}"
    worker = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, str(nproc), str(r),
         str(tmp_path)], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for r in range(nproc)]
    outs = [p.communicate(timeout=390)[0].decode() for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

    results = []
    for r in range(nproc):
        with open(tmp_path / f"rank{r}.json") as fh:
            results.append(json.load(fh))

    # every process assembled the same full mapper list
    assert results[0]["num_mappers"] == 10
    assert results[0]["mapper_sig"] == results[1]["mapper_sig"]

    # and it equals the single-process computation from the same samples
    cfg = Config({"objective": "binary", "max_bin": 63, "verbosity": -1})
    pairs = []
    for r in range(nproc):
        rng = np.random.default_rng(100 + r)
        sample = rng.standard_normal((2000, 10)).astype(np.float64)
        pairs.append(find_bin_shard(sample, r, nproc, cfg,
                                    total_sample_cnt=2000,
                                    num_data=2000 * nproc))
    ref = [m.to_state() for m in
           allgather_mappers(pairs, num_total_features=10)]
    assert results[0]["mapper_sig"] == ref

    # the cross-process data-parallel step agreed on both ranks and
    # matched numpy exactly
    assert results[0]["best_bin"] == results[1]["best_bin"]
    for r in results:
        assert r["hist_max_err"] < 1e-3
