"""utils/log.py coverage: verbosity thresholds, callback redirection,
Timer semantics (stop-without-start, stop_sync blocking, report format,
thread safety)."""

import threading

import pytest

from lightgbm_tpu.utils import log
from lightgbm_tpu.utils.log import (LightGBMError, Timer, get_verbosity,
                                    log_debug, log_fatal, log_info,
                                    log_warning, register_log_callback,
                                    set_verbosity)


@pytest.fixture(autouse=True)
def _restore_log_state():
    old_v = get_verbosity()
    yield
    set_verbosity(old_v)
    register_log_callback(None)
    log.set_timer_sink(None)


def _capture():
    lines = []
    register_log_callback(lines.append)
    return lines


class TestVerbosity:
    def test_thresholds(self):
        for level, expect in [(-1, set()), (0, {"W"}), (1, {"W", "I"}),
                              (2, {"W", "I", "D"})]:
            lines = _capture()
            set_verbosity(level)
            log_warning("W")
            log_info("I")
            log_debug("D")
            got = {ln.strip()[-1] for ln in lines}
            assert got == expect, f"verbosity={level}"

    def test_fatal_raises_at_any_verbosity(self):
        set_verbosity(-1)
        with pytest.raises(LightGBMError, match="boom"):
            log_fatal("boom")

    def test_message_format(self):
        lines = _capture()
        set_verbosity(1)
        log_info("hello")
        assert lines == ["[LightGBM-TPU] [Info] hello\n"]


class TestCallbackRedirection:
    def test_redirect_and_restore(self, capsys):
        set_verbosity(1)
        lines = _capture()
        log_info("redirected")
        assert len(lines) == 1
        assert capsys.readouterr().err == ""     # nothing hit stderr
        register_log_callback(None)              # restore default sink
        log_info("to stderr")
        assert len(lines) == 1                   # callback no longer called
        assert "to stderr" in capsys.readouterr().err


class TestTimer:
    def test_accumulates_and_counts(self):
        t = Timer()
        for _ in range(3):
            t.start("a")
            t.stop("a")
        assert t.counts["a"] == 3
        assert t.acc["a"] >= 0.0

    def test_stop_without_start_is_noop(self):
        t = Timer()
        t.stop("never_started")          # must not raise
        assert "never_started" not in t.acc
        assert "never_started" not in t.counts

    def test_report_includes_counts_and_mean(self):
        t = Timer()
        t.acc = {"hist": 1.2, "once": 0.5}
        t.counts = {"hist": 240, "once": 1}
        rep = t.report()
        assert "hist=1.200s/240 (5.0ms)" in rep
        assert "once=0.500s" in rep      # single call: no count suffix
        assert "/1" not in rep

    def test_reset(self):
        t = Timer()
        t.start("a")
        t.stop("a")
        t.start("pending")
        t.reset()
        assert t.acc == {} and t.counts == {} and t._t0 == {}

    def test_stop_sync_blocks_when_sync_on(self, monkeypatch):
        import jax
        blocked = []
        monkeypatch.setattr(jax, "block_until_ready", blocked.append)
        t = Timer()
        t.sync = True
        t.start("x")
        out = t.stop_sync("x", "devval")
        assert out == "devval"
        assert blocked == ["devval"]     # blocked BEFORE stopping the clock
        assert t.counts["x"] == 1

    def test_stop_sync_does_not_block_when_sync_off(self, monkeypatch):
        import jax
        def _boom(_):
            raise AssertionError("must not block with sync=False")
        monkeypatch.setattr(jax, "block_until_ready", _boom)
        t = Timer()
        t.start("x")
        assert t.stop_sync("x", "devval") == "devval"
        assert t.counts["x"] == 1

    def test_stop_sync_none_value_never_blocks(self, monkeypatch):
        import jax
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda v: (_ for _ in ()).throw(
                                AssertionError("blocked on None")))
        t = Timer()
        t.sync = True
        t.start("x")
        t.stop_sync("x", None)
        assert t.counts["x"] == 1

    def test_thread_safety(self):
        t = Timer()
        n_threads, n_iter = 8, 200

        def work(i):
            tag = f"tag{i}"
            for _ in range(n_iter):
                t.start(tag)
                t.stop(tag)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert sum(t.counts.values()) == n_threads * n_iter
        assert all(t.counts[f"tag{i}"] == n_iter for i in range(n_threads))

    def test_sink_receives_stops(self):
        seen = []
        log.set_timer_sink(lambda tag, dt: seen.append((tag, dt)))
        try:
            t = Timer()
            t.start("a")
            t.stop("a")
            t.stop("a")              # no matching start: sink not called
        finally:
            log.set_timer_sink(None)
        assert len(seen) == 1
        assert seen[0][0] == "a" and seen[0][1] >= 0.0
