"""jaxlint: rule firing on the fixture corpus, suppression mechanics,
baseline round-trips, and the tier-1 gate over ``lightgbm_tpu/``.

The corpus under ``tests/fixtures/jaxlint_corpus/`` marks every planted
defect with ``# PLANT: JLxxx``; the tests assert the analyzer reports
exactly those (rule, line) pairs — no misses, no extras — so both rule
recall and false-positive regressions fail loudly.
"""

import json
import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from lightgbm_tpu.tools import jaxlint
from lightgbm_tpu.tools.jaxlint import baseline as jl_baseline
from lightgbm_tpu.tools.jaxlint.cli import main as jaxlint_main

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "lightgbm_tpu"
CORPUS = REPO / "tests" / "fixtures" / "jaxlint_corpus"
BASELINE = REPO / "jaxlint_baseline.json"
PLANT_RE = re.compile(r"#\s*PLANT:\s*(JL\d{3})")

CORPUS_FILES = sorted(CORPUS.glob("*.py"))


def planted(path: Path):
    """[(rule, line)] of the ``# PLANT:`` markers in a corpus file."""
    out = []
    for i, line in enumerate(path.read_text().splitlines(), 1):
        m = PLANT_RE.search(line)
        if m:
            out.append((m.group(1), i))
    return out


# ---------------------------------------------------------------------------
# rule firing on the corpus
# ---------------------------------------------------------------------------

def test_corpus_has_plants_for_every_rule():
    rules = {r for p in CORPUS_FILES for r, _ in planted(p)}
    assert rules == set(jaxlint.RULES), \
        f"corpus must exercise every shipped rule; missing " \
        f"{set(jaxlint.RULES) - rules}"


@pytest.mark.parametrize("path", CORPUS_FILES, ids=lambda p: p.stem)
def test_each_planted_defect_fires_exactly_once(path):
    res = jaxlint.analyze_paths([str(path)], root=str(REPO))
    assert not res.errors
    got = sorted((f.rule, f.line) for f in res.findings)
    assert got == sorted(planted(path)), \
        "findings must match the # PLANT markers exactly (rule, line)"


def test_empty_baseline_reports_whole_corpus_exactly_once():
    res = jaxlint.analyze_paths([str(CORPUS)], root=str(REPO))
    new, stale = jl_baseline.apply(res.findings, {})   # empty baseline
    got = sorted((Path(f.path).name, f.rule, f.line) for f in new)
    want = sorted((p.name, rule, line)
                  for p in CORPUS_FILES for rule, line in planted(p))
    assert got == want and not stale


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

SET_LOOP = "def f(x):\n    for v in set(x):  # {}\n        print(v)\n"


def _findings_of(src, name="mod.py"):
    res = jaxlint.analyze_source(src, name)
    assert not res.errors
    return res


def test_unsuppressed_fixture_fires():
    res = _findings_of(SET_LOOP.format("no comment"))
    assert [f.rule for f in res.findings] == ["JL005"]


def test_inline_disable_same_line():
    res = _findings_of(SET_LOOP.format("jaxlint: disable=JL005"))
    assert not res.findings
    assert [f.rule for f in res.suppressed] == ["JL005"]


def test_inline_disable_wrong_code_does_not_suppress():
    res = _findings_of(SET_LOOP.format("jaxlint: disable=JL001"))
    assert [f.rule for f in res.findings] == ["JL005"]


def test_inline_disable_all():
    res = _findings_of(SET_LOOP.format("jaxlint: disable=all"))
    assert not res.findings and len(res.suppressed) == 1


def test_disable_next_line():
    src = ("def f(x):\n"
           "    # jaxlint: disable-next=JL005\n"
           "    for v in set(x):\n"
           "        print(v)\n")
    res = _findings_of(src)
    assert not res.findings and len(res.suppressed) == 1


def test_corpus_recompile_file_suppresses_its_jl003():
    # recompile.py isolates JL002 by suppressing the JL003 findings its
    # jit decorators would otherwise raise — which also pins down that
    # same-line suppression works on decorator lines
    res = jaxlint.analyze_paths([str(CORPUS / "recompile.py")],
                                root=str(REPO))
    assert {f.rule for f in res.suppressed} == {"JL003"}
    assert {f.rule for f in res.findings} == {"JL002"}


# ---------------------------------------------------------------------------
# baseline add/remove round-trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    res = jaxlint.analyze_paths([str(CORPUS)], root=str(REPO))
    bl = tmp_path / "bl.json"
    jl_baseline.write(str(bl), res.findings)

    loaded = jl_baseline.load(str(bl))
    assert sum(loaded.values()) == len(res.findings)
    new, stale = jl_baseline.apply(res.findings, loaded)
    assert new == [] and stale == []

    # removing one entry re-exposes exactly that finding as new
    doc = json.loads(bl.read_text())
    removed = doc["entries"].pop(0)
    removed_key = (removed["file"], removed["rule"], removed["snippet"])
    bl.write_text(json.dumps(doc))
    new, stale = jl_baseline.apply(res.findings, jl_baseline.load(str(bl)))
    assert len(new) == removed["count"] and not stale
    assert all(jl_baseline.finding_key(f) == removed_key for f in new)

    # a baseline entry with no surviving finding is reported stale
    res_none = jaxlint.AnalysisResult()
    new, stale = jl_baseline.apply(res_none.findings,
                                   jl_baseline.load(str(bl)))
    assert not new and sum(n for _, n in stale) == len(res.findings) - \
        removed["count"]


def test_baseline_is_line_number_independent():
    src = "def f(x):\n    for v in set(x):\n        print(v)\n"
    res1 = _findings_of(src)
    # same code shifted two lines down: same baseline key
    res2 = _findings_of("# pad\n# pad\n" + src)
    assert res1.findings[0].line != res2.findings[0].line
    new, _ = jl_baseline.apply(
        res2.findings, {jl_baseline.finding_key(res1.findings[0]): 1})
    assert new == []


# ---------------------------------------------------------------------------
# the tier-1 gate: the package is clean against the committed baseline
# ---------------------------------------------------------------------------

def test_package_clean_against_committed_baseline():
    accepted = jl_baseline.load(str(BASELINE))
    res = jaxlint.analyze_paths([str(PKG)], root=str(REPO))
    assert not res.errors
    new, _ = jl_baseline.apply(res.findings, accepted)
    assert not new, (
        "new jaxlint findings (fix them or regenerate the baseline with "
        "`python -m lightgbm_tpu.tools.jaxlint lightgbm_tpu "
        "--write-baseline` and justify in the PR):\n"
        + "\n".join(f"  {f.path}:{f.line}: {f.rule} {f.message}"
                    for f in new))


def test_analyzer_is_clean_on_itself():
    res = jaxlint.analyze_paths([str(PKG / "tools")], root=str(REPO))
    assert not res.errors and not res.findings


# ---------------------------------------------------------------------------
# CLI surface (in-process and the acceptance subprocess path)
# ---------------------------------------------------------------------------

def test_cli_list_rules(capsys):
    assert jaxlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in jaxlint.RULES:
        assert code in out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert jaxlint_main(["--select", "JL999", str(CORPUS)]) == 2


def test_cli_json_format(capsys):
    rc = jaxlint_main([str(CORPUS / "set_order.py"), "--no-baseline",
                       "--format", "json", "--root", str(REPO)])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["total"] == len(planted(CORPUS / "set_order.py"))
    assert all(f["rule"] == "JL005" for f in doc["new"])


def test_cli_package_with_baseline_exits_zero(capsys):
    rc = jaxlint_main([str(PKG), "--baseline", str(BASELINE),
                       "--root", str(REPO)])
    assert rc == 0, capsys.readouterr().out


def test_cli_write_baseline_refuses_select(tmp_path, capsys):
    # a rule-filtered write would silently erase the other rules'
    # accepted entries
    bl = tmp_path / "bl.json"
    rc = jaxlint_main([str(CORPUS), "--baseline", str(bl), "--select",
                       "JL001", "--write-baseline", "--root", str(REPO)])
    assert rc == 2 and not bl.exists()


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    bl = tmp_path / "bl.json"
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--write-baseline", "--root", str(REPO)]) == 0
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--root", str(REPO)]) == 0


def test_cli_injected_defect_fails_package_scan(tmp_path):
    """Acceptance: copying a known-bad corpus file into the package makes
    `python -m lightgbm_tpu.tools.jaxlint lightgbm_tpu` exit nonzero
    against the committed baseline."""
    shutil.copytree(PKG, tmp_path / "lightgbm_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copy(BASELINE, tmp_path / "jaxlint_baseline.json")
    env_cmd = [sys.executable, "-m", "lightgbm_tpu.tools.jaxlint",
               "lightgbm_tpu"]

    clean = subprocess.run(env_cmd, cwd=tmp_path, capture_output=True,
                           text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    shutil.copy(CORPUS / "hot_sync.py",
                tmp_path / "lightgbm_tpu" / "_injected_bad.py")
    bad = subprocess.run(env_cmd, cwd=tmp_path, capture_output=True,
                         text=True)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "_injected_bad.py" in bad.stdout
