"""jaxlint: rule firing on the fixture corpus, suppression mechanics,
baseline round-trips, and the tier-1 gate over ``lightgbm_tpu/``.

The corpus under ``tests/fixtures/jaxlint_corpus/`` marks every planted
defect with ``# PLANT: JLxxx``; the tests assert the analyzer reports
exactly those (rule, line) pairs — no misses, no extras — so both rule
recall and false-positive regressions fail loudly.
"""

import json
import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from lightgbm_tpu.tools import jaxlint
from lightgbm_tpu.tools.jaxlint import baseline as jl_baseline
from lightgbm_tpu.tools.jaxlint.cli import main as jaxlint_main

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "lightgbm_tpu"
CORPUS = REPO / "tests" / "fixtures" / "jaxlint_corpus"
BASELINE = REPO / "jaxlint_baseline.json"
PLANT_RE = re.compile(r"#\s*PLANT:\s*(JL\d{3})")

CORPUS_FILES = sorted(CORPUS.glob("*.py"))


def planted(path: Path):
    """[(rule, line)] of the ``# PLANT:`` markers in a corpus file."""
    out = []
    for i, line in enumerate(path.read_text().splitlines(), 1):
        m = PLANT_RE.search(line)
        if m:
            out.append((m.group(1), i))
    return out


# ---------------------------------------------------------------------------
# rule firing on the corpus
# ---------------------------------------------------------------------------

def test_corpus_has_plants_for_every_rule():
    rules = {r for p in CORPUS_FILES for r, _ in planted(p)}
    assert rules == set(jaxlint.RULES), \
        f"corpus must exercise every shipped rule; missing " \
        f"{set(jaxlint.RULES) - rules}"


@pytest.mark.parametrize("path", CORPUS_FILES, ids=lambda p: p.stem)
def test_each_planted_defect_fires_exactly_once(path):
    res = jaxlint.analyze_paths([str(path)], root=str(REPO))
    assert not res.errors
    got = sorted((f.rule, f.line) for f in res.findings)
    assert got == sorted(planted(path)), \
        "findings must match the # PLANT markers exactly (rule, line)"


def test_empty_baseline_reports_whole_corpus_exactly_once():
    res = jaxlint.analyze_paths([str(CORPUS)], root=str(REPO))
    new, stale = jl_baseline.apply(res.findings, {})   # empty baseline
    got = sorted((Path(f.path).name, f.rule, f.line) for f in new)
    want = sorted((p.name, rule, line)
                  for p in CORPUS_FILES for rule, line in planted(p))
    assert got == want and not stale


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

SET_LOOP = "def f(x):\n    for v in set(x):  # {}\n        print(v)\n"


def _findings_of(src, name="mod.py"):
    res = jaxlint.analyze_source(src, name)
    assert not res.errors
    return res


def test_unsuppressed_fixture_fires():
    res = _findings_of(SET_LOOP.format("no comment"))
    assert [f.rule for f in res.findings] == ["JL005"]


def test_inline_disable_same_line():
    res = _findings_of(SET_LOOP.format("jaxlint: disable=JL005"))
    assert not res.findings
    assert [f.rule for f in res.suppressed] == ["JL005"]


def test_inline_disable_wrong_code_does_not_suppress():
    res = _findings_of(SET_LOOP.format("jaxlint: disable=JL001"))
    assert [f.rule for f in res.findings] == ["JL005"]


def test_inline_disable_all():
    res = _findings_of(SET_LOOP.format("jaxlint: disable=all"))
    assert not res.findings and len(res.suppressed) == 1


def test_disable_next_line():
    src = ("def f(x):\n"
           "    # jaxlint: disable-next=JL005\n"
           "    for v in set(x):\n"
           "        print(v)\n")
    res = _findings_of(src)
    assert not res.findings and len(res.suppressed) == 1


def test_corpus_recompile_file_suppresses_its_jl003():
    # recompile.py isolates JL002 by suppressing the JL003 findings its
    # jit decorators would otherwise raise — which also pins down that
    # same-line suppression works on decorator lines
    res = jaxlint.analyze_paths([str(CORPUS / "recompile.py")],
                                root=str(REPO))
    assert {f.rule for f in res.suppressed} == {"JL003"}
    assert {f.rule for f in res.findings} == {"JL002"}


# ---------------------------------------------------------------------------
# baseline add/remove round-trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    res = jaxlint.analyze_paths([str(CORPUS)], root=str(REPO))
    bl = tmp_path / "bl.json"
    jl_baseline.write(str(bl), res.findings)

    loaded = jl_baseline.load(str(bl))
    assert sum(loaded.values()) == len(res.findings)
    new, stale = jl_baseline.apply(res.findings, loaded)
    assert new == [] and stale == []

    # removing one entry re-exposes exactly that finding as new
    doc = json.loads(bl.read_text())
    removed = doc["entries"].pop(0)
    removed_key = (removed["file"], removed["rule"], removed["snippet"])
    bl.write_text(json.dumps(doc))
    new, stale = jl_baseline.apply(res.findings, jl_baseline.load(str(bl)))
    assert len(new) == removed["count"] and not stale
    assert all(jl_baseline.finding_key(f) == removed_key for f in new)

    # a baseline entry with no surviving finding is reported stale
    res_none = jaxlint.AnalysisResult()
    new, stale = jl_baseline.apply(res_none.findings,
                                   jl_baseline.load(str(bl)))
    assert not new and sum(n for _, n in stale) == len(res.findings) - \
        removed["count"]


def test_baseline_is_line_number_independent():
    src = "def f(x):\n    for v in set(x):\n        print(v)\n"
    res1 = _findings_of(src)
    # same code shifted two lines down: same baseline key
    res2 = _findings_of("# pad\n# pad\n" + src)
    assert res1.findings[0].line != res2.findings[0].line
    new, _ = jl_baseline.apply(
        res2.findings, {jl_baseline.finding_key(res1.findings[0]): 1})
    assert new == []


# ---------------------------------------------------------------------------
# the tier-1 gate: the package is clean against the committed baseline
# ---------------------------------------------------------------------------

def test_package_clean_against_committed_baseline():
    accepted = jl_baseline.load(str(BASELINE))
    res = jaxlint.analyze_paths([str(PKG)], root=str(REPO))
    assert not res.errors
    new, _ = jl_baseline.apply(res.findings, accepted)
    assert not new, (
        "new jaxlint findings (fix them or regenerate the baseline with "
        "`python -m lightgbm_tpu.tools.jaxlint lightgbm_tpu "
        "--write-baseline` and justify in the PR):\n"
        + "\n".join(f"  {f.path}:{f.line}: {f.rule} {f.message}"
                    for f in new))


def test_analyzer_is_clean_on_itself():
    res = jaxlint.analyze_paths([str(PKG / "tools")], root=str(REPO))
    assert not res.errors and not res.findings


# ---------------------------------------------------------------------------
# CLI surface (in-process and the acceptance subprocess path)
# ---------------------------------------------------------------------------

def test_cli_list_rules(capsys):
    assert jaxlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in jaxlint.RULES:
        assert code in out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert jaxlint_main(["--select", "JL999", str(CORPUS)]) == 2


def test_cli_json_format(capsys):
    rc = jaxlint_main([str(CORPUS / "set_order.py"), "--no-baseline",
                       "--format", "json", "--root", str(REPO)])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["total"] == len(planted(CORPUS / "set_order.py"))
    assert all(f["rule"] == "JL005" for f in doc["new"])


def test_cli_package_with_baseline_exits_zero(capsys):
    rc = jaxlint_main([str(PKG), "--baseline", str(BASELINE),
                       "--root", str(REPO)])
    assert rc == 0, capsys.readouterr().out


def test_cli_select_write_baseline_preserves_other_rules(tmp_path,
                                                         capsys):
    # regression: a rule-filtered `--write-baseline` used to hold only
    # the selected findings, silently erasing every other rule's
    # accepted entries; now it merges
    bl = tmp_path / "bl.json"
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--write-baseline", "--root", str(REPO)]) == 0
    before = jl_baseline.load(str(bl))
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl), "--select",
                         "JL005", "--write-baseline",
                         "--root", str(REPO)]) == 0
    after = jl_baseline.load(str(bl))
    assert after == before, \
        "unselected rules' entries must survive a --select write"
    # and the merged baseline still gates a full run clean
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--root", str(REPO)]) == 0


def test_cli_select_filters_baseline_entries(tmp_path, capsys):
    # regression: a --select run used to judge itself against the FULL
    # baseline, reporting every other rule's entries as stale
    bl = tmp_path / "bl.json"
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--write-baseline", "--root", str(REPO)]) == 0
    capsys.readouterr()
    rc = jaxlint_main([str(CORPUS), "--baseline", str(bl), "--select",
                       "JL005", "--root", str(REPO)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "stale" not in out, out


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    bl = tmp_path / "bl.json"
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--write-baseline", "--root", str(REPO)]) == 0
    assert jaxlint_main([str(CORPUS), "--baseline", str(bl),
                         "--root", str(REPO)]) == 0


def test_cli_injected_defect_fails_package_scan(tmp_path):
    """Acceptance: copying a known-bad corpus file into the package makes
    `python -m lightgbm_tpu.tools.jaxlint lightgbm_tpu` exit nonzero
    against the committed baseline."""
    shutil.copytree(PKG, tmp_path / "lightgbm_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copy(BASELINE, tmp_path / "jaxlint_baseline.json")
    env_cmd = [sys.executable, "-m", "lightgbm_tpu.tools.jaxlint",
               "lightgbm_tpu"]

    clean = subprocess.run(env_cmd, cwd=tmp_path, capture_output=True,
                           text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    shutil.copy(CORPUS / "hot_sync.py",
                tmp_path / "lightgbm_tpu" / "_injected_bad.py")
    bad = subprocess.run(env_cmd, cwd=tmp_path, capture_output=True,
                         text=True)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "_injected_bad.py" in bad.stdout


# ---------------------------------------------------------------------------
# JL1xx project rules: injected defects in REAL package code.  One
# package copy per test module; each test applies a mutation, runs the
# analyzer CLI in a subprocess and asserts the exact rule fires, then
# restores the file.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pkg_copy(tmp_path_factory):
    root = tmp_path_factory.mktemp("jl1xx")
    shutil.copytree(PKG, root / "lightgbm_tpu",
                    ignore=shutil.ignore_patterns("__pycache__"))
    shutil.copy(BASELINE, root / "jaxlint_baseline.json")
    return root


def _lint(root, *extra):
    cmd = [sys.executable, "-m", "lightgbm_tpu.tools.jaxlint",
           "lightgbm_tpu", *extra]
    return subprocess.run(cmd, cwd=root, capture_output=True, text=True)


def _mutate(root, rel, old, new):
    p = root / rel
    src = p.read_text()
    assert old in src, f"{rel} no longer contains the injection anchor"
    p.write_text(src.replace(old, new, 1))
    return p, src


def test_injected_jl101_dropped_signature_field(pkg_copy):
    """Dropping INT32_SCAN_ROWS from programs_signature — the exact
    PR-9 review bug — must fire JL101 at the constant's compare site."""
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/ops/grow.py",
                      "_CHUNK, COUNT_SPLIT_ROWS, INT32_SCAN_ROWS,",
                      "_CHUNK, COUNT_SPLIT_ROWS,")
    try:
        r = _lint(pkg_copy, "--select", "JL101", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL101" in r.stdout and "INT32_SCAN_ROWS" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl101_traced_param_in_key(pkg_copy):
    """Un-excluding learning_rate (the PR-4 review bug: lr decay forced
    a program-cache miss per iteration) must fire JL101."""
    p, orig = _mutate(
        pkg_copy, "lightgbm_tpu/ops/grow.py",
        '_NON_TRACE_PARAMS = ("wave_plan", "grower_cache", '
        '"learning_rate")',
        '_NON_TRACE_PARAMS = ("wave_plan", "grower_cache")')
    try:
        r = _lint(pkg_copy, "--select", "JL101", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL101" in r.stdout and "learning_rate" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl101_fusion_mode_excluded_from_key(pkg_copy):
    """Excluding find_best_fusion from the digest while ``_grow_impl``
    reads it in the traced region (the fused-vs-two-pass wave-layout
    branch) must fire JL101: the two layouts are different programs, so
    an un-keyed mode would let a cached trace serve the other layout."""
    p, orig = _mutate(
        pkg_copy, "lightgbm_tpu/ops/grow.py",
        '_NON_TRACE_PARAMS = ("wave_plan", "grower_cache", '
        '"learning_rate")',
        '_NON_TRACE_PARAMS = ("wave_plan", "grower_cache", '
        '"learning_rate", "find_best_fusion")')
    try:
        r = _lint(pkg_copy, "--select", "JL101", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL101" in r.stdout and "find_best_fusion" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl111_f32_upcast_in_quant_path(pkg_copy):
    """An f32 upcast on the int8 stat mask upstream of the dequantize
    point (the shape of PR-9's 'f32 dequantize left upstream of the
    find-best scan' bug) must fire JL111."""
    anchor = "            m8 = one_f.astype(jnp.int8)\n"
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/ops/grow.py", anchor,
                      anchor + "            m8 = m8.astype(jnp.float32)\n")
    try:
        r = _lint(pkg_copy, "--select", "JL111", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL111" in r.stdout and "f32 upcast" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl121_lock_order_inversion(pkg_copy):
    """Opposite acquisition orders of the program-cache and plan-cache
    locks across ops/grow.py and ops/stage_plan.py must fire JL121 on
    both edges."""
    grow = pkg_copy / "lightgbm_tpu/ops/grow.py"
    plan = pkg_copy / "lightgbm_tpu/ops/stage_plan.py"
    g_orig, p_orig = grow.read_text(), plan.read_text()
    grow.write_text(g_orig + (
        "\n\ndef _diag_flush_plans(base):\n"
        "    with _PROGRAM_CACHE_LOCK:\n"
        "        return stage_plan_mod.cached_plan(base)\n"))
    plan.write_text(p_orig + (
        "\n\ndef _diag_rebuild(config):\n"
        "    from . import grow\n"
        "    with _PLAN_CACHE_LOCK:\n"
        "        return grow.get_grower_programs(1024, 1, 64, 4,\n"
        "                                        False, config)\n"))
    try:
        r = _lint(pkg_copy, "--select", "JL121", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert r.stdout.count("JL121") >= 2
        assert "lock-order inversion" in r.stdout
    finally:
        grow.write_text(g_orig)
        plan.write_text(p_orig)


def test_injected_jl131_wall_clock_in_checkpoint(pkg_copy):
    """A wall-clock stamp in the pipeline checkpoint meta payload must
    fire JL131 at the sink call."""
    anchor = 'meta={"policy": policy, "rows": int(rows),'
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/pipeline/core.py", anchor,
                      'meta={"policy": policy, "at": time.time(),'
                      ' "rows": int(rows),')
    try:
        r = _lint(pkg_copy, "--select", "JL131", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL131" in r.stdout and "wall-clock" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl141_dropped_context_handoff(pkg_copy):
    """Deleting the pipeline worker's ``tracing.set_current(root_ctx)``
    handoff (the PR-16 causal-chain invariant) must fire JL141 at the
    worker spawn."""
    anchor = ("            tracing.set_current(root_ctx)"
              "   # thread-local; dies with us\n")
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/pipeline/core.py",
                      anchor, "")
    try:
        r = _lint(pkg_copy, "--select", "JL141", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL141" in r.stdout and "SpanContext" in r.stdout
        assert "pipeline/core.py" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl141_untimed_queue_get(pkg_copy):
    """Stripping the timeout from the stream loader's consumer-side
    ``q.get`` — the exact hang this PR's audit fixed — must fire
    JL141."""
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/data/stream_loader.py",
                      "return q.get(timeout=0.5)", "return q.get()")
    try:
        r = _lint(pkg_copy, "--select", "JL141", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL141" in r.stdout and "stream_loader.py" in r.stdout
    finally:
        p.write_text(orig)


def _ensure_abi_inputs(pkg_copy):
    """pkg_copy holds only lightgbm_tpu/ — the ABI directives are inert
    until the header/cpp they name exist at the matching relative
    locations."""
    inc = pkg_copy / "include" / "lightgbm_tpu"
    if not inc.exists():
        inc.mkdir(parents=True)
        shutil.copy(REPO / "include" / "lightgbm_tpu" / "c_api.h",
                    inc / "c_api.h")
        capi = pkg_copy / "src" / "capi"
        capi.mkdir(parents=True)
        shutil.copy(REPO / "src" / "capi" / "lgbm_capi.cpp",
                    capi / "lgbm_capi.cpp")


def test_injected_jl151_skewed_binding_arity(pkg_copy):
    """Dropping a parameter from the LGBM_ServeSwap binding while the
    header still declares two must fire JL151 at the def."""
    _ensure_abi_inputs(pkg_copy)
    clean = _lint(pkg_copy, "--select", "JL151", "--no-baseline")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    p, orig = _mutate(
        pkg_copy, "lightgbm_tpu/c_api.py",
        "def LGBM_ServeSwap(serve_handle, booster_handle):",
        "def LGBM_ServeSwap(serve_handle):")
    try:
        r = _lint(pkg_copy, "--select", "JL151", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL151" in r.stdout and "LGBM_ServeSwap" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl161_removed_registry_entry(pkg_copy):
    """Deleting ``stream.parse`` from KNOWN_SITES while the loader
    still arms it must fire JL161 at the arming call."""
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/robust/faults.py",
                      '"stream.parse", "obs.export",',
                      '"obs.export",')
    try:
        r = _lint(pkg_copy, "--select", "JL161", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL161" in r.stdout and "stream.parse" in r.stdout
        assert "stream_loader.py" in r.stdout
    finally:
        p.write_text(orig)


def test_injected_jl161_dead_registry_entry(pkg_copy):
    """Deleting the loader's ``faults.check("stream.parse")`` call
    leaves a registry entry nothing arms — JL161 must flag it dead at
    the KNOWN_SITES assignment."""
    p, orig = _mutate(pkg_copy, "lightgbm_tpu/data/stream_loader.py",
                      '        faults.check("stream.parse")\n', "")
    try:
        r = _lint(pkg_copy, "--select", "JL161", "--no-baseline")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL161" in r.stdout and "stream.parse" in r.stdout
        assert "faults.py" in r.stdout
    finally:
        p.write_text(orig)


def test_baseline_has_no_project_rule_entries():
    """New rules start at zero debt: the committed baseline may not
    contain a single JL1xx entry."""
    accepted = jl_baseline.load(str(BASELINE))
    bad = [k for k in accepted if k[1].startswith("JL1")]
    assert not bad, f"JL1xx baseline entries are not allowed: {bad}"
    assert sum(accepted.values()) <= 20, \
        "baseline ratchet: keep the accepted-debt total at or below 20"


# ---------------------------------------------------------------------------
# incremental cache
# ---------------------------------------------------------------------------

def test_cache_warm_run_replays_identical_findings(tmp_path):
    corpus_copy = tmp_path / "corpus"
    shutil.copytree(CORPUS, corpus_copy)
    cache = tmp_path / ".jaxlint_cache"
    cold = jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                                 cache_dir=str(cache))
    assert not cold.from_cache and (cache / "cache.json").exists()
    warm = jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                                 cache_dir=str(cache))
    assert warm.from_cache
    key = lambda fs: sorted((f.path, f.rule, f.line, f.message)
                            for f in fs)
    assert key(warm.findings) == key(cold.findings)
    assert key(warm.suppressed) == key(cold.suppressed)


def test_cache_invalidated_by_content_change(tmp_path):
    corpus_copy = tmp_path / "corpus"
    shutil.copytree(CORPUS, corpus_copy)
    cache = tmp_path / ".jaxlint_cache"
    jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                          cache_dir=str(cache))
    target = corpus_copy / "set_order.py"
    target.write_text(target.read_text()
                      + "\n\ndef extra(x):\n    for v in set(x):\n"
                      "        print(v)\n")
    res = jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                                cache_dir=str(cache))
    assert not res.from_cache
    assert any(f.path.endswith("set_order.py")
               and f.line > len(target.read_text().splitlines()) - 4
               for f in res.findings if f.rule == "JL005")
    # warm again after the change is cached
    res2 = jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                                 cache_dir=str(cache))
    assert res2.from_cache


def test_cache_invalidated_by_abi_input_edit(tmp_path):
    """Editing ONLY the C header a directive names — no .py content
    changed — must invalidate the project tier: directive-declared
    extra inputs are content-hashed into the tree sha."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "m.py").write_text(
        "# jaxlint: abi-header=m.h\n"
        "def LGBM_Fx(a, b):\n    return 0\n")
    (proj / "m.h").write_text("int LGBM_Fx(int a, int b);\n")
    cache = tmp_path / ".jaxlint_cache"
    cold = jaxlint.analyze_paths([str(proj)], root=str(tmp_path),
                                 cache_dir=str(cache))
    assert not cold.findings
    warm = jaxlint.analyze_paths([str(proj)], root=str(tmp_path),
                                 cache_dir=str(cache))
    assert warm.from_cache and not warm.findings
    (proj / "m.h").write_text("int LGBM_Fx(int a, int b, int c);\n")
    res = jaxlint.analyze_paths([str(proj)], root=str(tmp_path),
                                cache_dir=str(cache))
    assert not res.from_cache
    assert [f.rule for f in res.findings] == ["JL151"]
    res2 = jaxlint.analyze_paths([str(proj)], root=str(tmp_path),
                                 cache_dir=str(cache))
    assert res2.from_cache
    assert [f.rule for f in res2.findings] == ["JL151"]


def test_cache_select_run_filters_but_never_writes(tmp_path):
    corpus_copy = tmp_path / "corpus"
    shutil.copytree(CORPUS, corpus_copy)
    cache = tmp_path / ".jaxlint_cache"
    jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                          cache_dir=str(cache))
    stamp = (cache / "cache.json").read_bytes()
    res = jaxlint.analyze_paths([str(corpus_copy)], root=str(tmp_path),
                                select={"JL005"}, cache_dir=str(cache))
    assert {f.rule for f in res.findings} == {"JL005"}
    assert (cache / "cache.json").read_bytes() == stamp


# ---------------------------------------------------------------------------
# --explain
# ---------------------------------------------------------------------------

def test_cli_explain_prints_rule_doc(capsys):
    assert jaxlint_main(["--explain", "JL101"]) == 0
    out = capsys.readouterr().out
    assert "JL101" in out and "programs_signature" in out


def test_cli_explain_unknown_rule(capsys):
    assert jaxlint_main(["--explain", "JL999"]) == 2


# ---------------------------------------------------------------------------
# review regressions: rule false negatives caught and fixed in PR 10
# ---------------------------------------------------------------------------

def _project_findings(rule_mod, src, name="m.py"):
    from lightgbm_tpu.tools.jaxlint.context import FileContext
    from lightgbm_tpu.tools.jaxlint.project import ProjectContext
    return list(rule_mod.check_project(
        ProjectContext([FileContext(src, name)])))


def test_jl121_multi_item_with_orders_left_to_right():
    # `with A, B:` acquires A then B — an inversion written that way
    # must be flagged just like nested `with` blocks
    from lightgbm_tpu.tools.jaxlint.rules import lock_order
    src = (
        "import threading\n"
        "_A_LOCK = threading.Lock()\n"
        "_B_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _A_LOCK, _B_LOCK:\n"
        "        pass\n"
        "def g():\n"
        "    with _B_LOCK:\n"
        "        with _A_LOCK:\n"
        "            pass\n")
    findings = _project_findings(lock_order, src)
    assert len(findings) >= 2
    assert all("lock-order inversion" in f.message for f in findings)


def test_jl131_param_taint_survives_local_alias():
    # a callee that copies its tainted parameter into a local before
    # the sink call must still attribute the hit to the caller
    from lightgbm_tpu.tools.jaxlint.rules import determinism
    src = (
        "import time\n"
        "def save_pipeline_checkpoint(d, meta):\n"
        "    pass\n"
        "def _save(d, meta):\n"
        "    m = meta\n"
        "    save_pipeline_checkpoint(d, m)\n"
        "def caller(d):\n"
        "    meta = {\"at\": time.time()}\n"
        "    _save(d, meta)\n")
    findings = _project_findings(determinism, src)
    assert any("wall-clock" in f.message for f in findings)
