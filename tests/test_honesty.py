"""Previously accepted-but-ignored parameters now do what they say:
multiclass init_score validation, prediction early stopping
(prediction_early_stop.cpp), forced splits (ForceSplits,
serial_tree_learner.cpp:546-701), gpu_use_dp accumulation."""

import json

import numpy as np
import pytest

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.utils.log import LightGBMError


def _dataset(params, x, y, init_score=None):
    cfg = Config(params)
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    if init_score is not None:
        ds.metadata.set_init_score(init_score)
    return cfg, ds


@pytest.fixture(scope="module")
def mc_data():
    rng = np.random.default_rng(0)
    n = 3000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int) \
        + (x[:, 2] > 0.8).astype(int)
    return x, y.astype(np.float32)


def test_multiclass_init_score_wrong_size_rejected(mc_data):
    x, y = mc_data
    cfg, ds = _dataset({"objective": "multiclass", "num_class": 3}, x, y,
                       init_score=np.zeros(len(y)))  # must be 3*N
    bst = create_boosting(cfg)
    with pytest.raises(LightGBMError, match="Initial score size"):
        bst.init_train(ds)


def test_multiclass_init_score_full_size_used(mc_data):
    x, y = mc_data
    n = len(y)
    init = np.zeros(3 * n)
    init[:n] = 2.0      # class 0 biased up (class-major layout)
    cfg, ds = _dataset({"objective": "multiclass", "num_class": 3}, x, y,
                       init_score=init)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    score = np.asarray(bst.train_score)
    assert score.shape == (3, n)
    assert np.allclose(score[0], 2.0) and np.allclose(score[1:], 0.0)


def test_prediction_early_stopping(mc_data):
    rng = np.random.default_rng(1)
    n = 4000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 0.3 * rng.standard_normal(n) > 0).astype(np.float32)
    cfg, ds = _dataset({"objective": "binary", "num_leaves": 15,
                        "learning_rate": 0.3}, x, y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    for _ in range(30):
        bst.train_one_iter()
    full = bst.predict(x[:500], raw_score=True)
    # huge margin -> identical predictions
    bst.config.pred_early_stop = True
    bst.config.pred_early_stop_margin = 1e10
    bst.config.pred_early_stop_freq = 5
    same = bst.predict(x[:500], raw_score=True)
    np.testing.assert_allclose(full, same)
    # tiny margin -> rows freeze after the first check period
    bst.config.pred_early_stop_margin = 0.0
    stopped = bst.predict(x[:500], raw_score=True)
    assert not np.allclose(full, stopped)
    short = bst.predict(x[:500], raw_score=True, num_iteration=5)
    np.testing.assert_allclose(stopped, short)
    bst.config.pred_early_stop = False


def test_forced_splits(tmp_path, mc_data):
    rng = np.random.default_rng(2)
    n = 4000
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = (x[:, 3] * 2.0 + 0.1 * rng.standard_normal(n)).astype(np.float32)
    forced = {"feature": 2, "threshold": 0.0,
              "left": {"feature": 4, "threshold": 0.5}}
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(forced))
    cfg, ds = _dataset({"objective": "regression", "num_leaves": 15,
                        "forcedsplits_filename": str(path)}, x, y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_one_iter()
    tree = bst.models[0]
    # node 0 must split feature 2 at ~0.0; its left child on feature 4
    assert int(tree.split_feature[0]) == 2
    assert abs(float(tree.threshold[0])) < 0.1
    left = int(tree.left_child[0])
    assert left >= 0 and int(tree.split_feature[left]) == 4
    assert abs(float(tree.threshold[left]) - 0.5) < 0.15
    # model text round-trips with the forced structure intact
    from lightgbm_tpu.boosting.gbdt import GBDT
    loaded = GBDT.load_model_from_string(bst.model_to_string())
    np.testing.assert_allclose(loaded.predict(x[:100], raw_score=True),
                               bst.predict(x[:100], raw_score=True),
                               atol=1e-6)


def test_gpu_use_dp_accumulation():
    """gpu_use_dp = Kahan compensation across histogram chunks: once the
    running total dwarfs a chunk's contribution, plain f32 accumulation
    drifts by O(num_chunks * ulp(total)) while the compensated sum stays
    within one ulp (the SURVEY §7 billion-row accumulation concern)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import _histogram_scan
    n = 512 * 8192                    # 512 chunks, total ~4.2M
    bins = jnp.asarray(np.zeros((n, 1), np.uint8))
    g = np.full(n, 1.0001, np.float32)
    gh = jnp.asarray(np.stack([g, g, np.ones(n, np.float32)], 1))
    exact = float(np.sum(g.astype(np.float64)))
    h32 = np.asarray(_histogram_scan(bins, gh, 512, False))[0, 0]
    hdp = np.asarray(_histogram_scan(bins, gh, 512, True))[0, 0]
    err32 = abs(h32[0] - exact)
    errdp = abs(hdp[0] - exact)
    assert errdp < err32 / 10, (err32, errdp)
    assert errdp / exact < 1e-5, errdp


def test_gpu_use_dp_odd_tail_still_compensated():
    """A window NOT divisible by the 512-row granule must still get the
    compensated accumulation (the tail is an extra Kahan step, not a
    collapse to one uncompensated chunk)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import _histogram_scan
    n = 512 * 4096 + 137              # odd tail
    bins = jnp.asarray(np.zeros((n, 1), np.uint8))
    g = np.full(n, 1.0001, np.float32)
    gh = jnp.asarray(np.stack([g, g, np.ones(n, np.float32)], 1))
    exact = float(np.sum(g.astype(np.float64)))
    hdp = np.asarray(_histogram_scan(bins, gh, 1, True))[0, 0]
    assert abs(hdp[0] - exact) / exact < 1e-5
    assert hdp[2] == n


def test_greedy_find_bin_vectorized_matches_scalar_oracle():
    """The vectorized _greedy_find_bin must be bit-identical to the
    reference-shaped scalar oracle over random inputs (the docstring's
    claimed regression guard, bin.cpp:74-150 semantics)."""
    from lightgbm_tpu.data.binning import (_greedy_find_bin,
                                           _greedy_find_bin_scalar)
    rng = np.random.default_rng(20260730)
    for case in range(400):
        num_distinct = int(rng.integers(1, 400))
        vals = np.unique(rng.normal(0, 10, num_distinct).round(2))
        # skewed counts so big-bin handling paths are exercised
        counts = rng.integers(1, 50, len(vals)).astype(np.int64)
        if case % 3 == 0:
            counts[rng.integers(0, len(vals))] += int(rng.integers(100, 2000))
        total = int(counts.sum())
        max_bin = int(rng.integers(2, 70))
        mdib = int(rng.choice([0, 1, 3, 10]))
        got = _greedy_find_bin(vals, counts, max_bin, total, mdib)
        want = _greedy_find_bin_scalar(vals, counts, max_bin, total, mdib)
        assert got == want, (case, max_bin, mdib, got, want)
