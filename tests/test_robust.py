"""The fault-tolerance layer (lightgbm_tpu/robust/, docs/Robustness.md).

Contracts under test:

* fault injection is DETERMINISTIC — count/at/after rules fire on exact
  invocation indices, the probabilistic mode replays identically for
  the same seed, and error flavors inherit the right builtin types so
  real retry/except paths treat them like the failures they imitate;
* ``with_retries`` retries only retryable errors, backs off with capped
  deterministic jitter, and exhausts into a RetryError naming the
  attempt count;
* the circuit breaker trips on consecutive failures, blocks until the
  re-probe window, and reports the dark-period duration on recovery;
* atomic checkpoint writes never leave a torn file — a crash injected
  between temp-write and rename preserves the previous content;
* GBDT snapshot/resume continues a killed run BYTE-IDENTICALLY
  (exact-score sidecar + host-learner RNG state);
* the PredictionServer degrades to the host walk under injected device
  death (zero dropped requests, byte-exact vs the host predict path)
  and recovers once the fault clears;
* one poisoned micro-batch submit fails only its own Future.
"""

import threading
import time

import numpy as np
import pytest

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.robust import (CircuitBreaker, InjectedFault,
                                 InjectedOSError, InjectedTimeout,
                                 RetryError, RetryPolicy, backoff_delay,
                                 checkpoint, faults, with_retries)
from lightgbm_tpu.utils.log import LightGBMError


@pytest.fixture(autouse=True)
def _clean_faults():
    """No test leaks an armed registry into the rest of the suite."""
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

def _survives(site, n):
    out = []
    for _ in range(n):
        try:
            faults.check(site)
            out.append(True)
        except InjectedFault:
            out.append(False)
    return out


def test_fault_count_and_at_rules():
    faults.configure("grow.dispatch:n=2,serve.dispatch:at=3")
    assert _survives("grow.dispatch", 4) == [False, False, True, True]
    assert _survives("serve.dispatch", 5) == [True, True, True, False,
                                              True]
    assert faults.counts() == {"grow.dispatch": 2, "serve.dispatch": 1}
    # unarmed sites never fire
    assert _survives("net.send", 3) == [True, True, True]


def test_fault_after_and_persist():
    faults.configure("net.recv:after=2:n=1,io.read:at=1:persist")
    assert _survives("net.recv", 5) == [True, True, False, True, True]
    assert _survives("io.read", 5) == [True, False, False, False, False]


def test_fault_probabilistic_mode_is_seed_deterministic():
    faults.configure("io.write:p=0.5:seed=7")
    pattern_a = _survives("io.write", 64)
    faults.configure("io.write:p=0.5:seed=7")
    assert _survives("io.write", 64) == pattern_a
    faults.configure("io.write:p=0.5:seed=8")
    assert _survives("io.write", 64) != pattern_a
    assert 8 < sum(pattern_a) < 56      # actually probabilistic


def test_fault_error_flavors_inherit_builtin_types():
    faults.configure("net.connect:n=1:error=oserror,"
                     "net.recv:n=1:error=timeout")
    with pytest.raises(OSError) as ei:
        faults.check("net.connect")
    assert isinstance(ei.value, InjectedOSError)
    with pytest.raises(TimeoutError) as ei:
        faults.check("net.recv")
    assert isinstance(ei.value, InjectedTimeout)


def test_fault_env_and_config_arming(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "pipeline.prep:n=1")
    faults.configure_from_env()
    assert faults.active()
    with pytest.raises(InjectedFault):
        faults.check("pipeline.prep")
    # config arming is idempotent for an unchanged spec: counters keep
    # their progress across repeated init_train-style re-reads
    cfg = Config({"fault_spec": "serve.dispatch:at=1", "verbosity": -1})
    faults.configure_from_config(cfg)
    faults.check("serve.dispatch")              # invocation 0 passes
    faults.configure_from_config(cfg)           # must NOT reset to 0
    with pytest.raises(InjectedFault):
        faults.check("serve.dispatch")          # invocation 1 fires


def test_fault_spec_rejects_garbage():
    with pytest.raises(LightGBMError):
        faults.parse_fault_spec("serve.dispatch:bogus")
    with pytest.raises(LightGBMError):
        faults.parse_fault_spec("serve.dispatch:error=nope")


# ---------------------------------------------------------------------------
# retries + breaker
# ---------------------------------------------------------------------------

def test_with_retries_recovers_and_backs_off():
    calls, delays = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return 42

    policy = RetryPolicy(max_attempts=5, base_delay_s=0.01,
                         max_delay_s=0.5, retry_on=(OSError,))
    assert with_retries(flaky, policy, site="t",
                        sleep=delays.append) == 42
    assert len(calls) == 3 and len(delays) == 2
    # capped exponential with deterministic jitter: replay matches
    assert delays == [backoff_delay(policy, 0, "t"),
                      backoff_delay(policy, 1, "t")]
    assert all(0 < d <= 0.5 for d in delays)


def test_with_retries_exhausts_with_context():
    def always():
        raise OSError("down")

    with pytest.raises(RetryError, match="failed after 3 attempts"):
        with_retries(always, RetryPolicy(max_attempts=3,
                                         base_delay_s=0.001,
                                         retry_on=(OSError,)),
                     site="net.connect", sleep=lambda d: None)


def test_with_retries_propagates_non_retryable_immediately():
    calls = []

    def bad_shape():
        calls.append(1)
        raise ValueError("wrong shape")

    with pytest.raises(ValueError):
        with_retries(bad_shape,
                     RetryPolicy(max_attempts=5, retry_on=(OSError,)),
                     sleep=lambda d: None)
    assert len(calls) == 1


def test_circuit_breaker_lifecycle():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=2, reprobe_interval_s=1.0,
                       clock=lambda: t[0])
    assert b.allow() and b.state == "closed"
    assert b.record_failure() is False          # 1 failure: still closed
    assert b.record_failure() is True           # trips
    assert b.state == "open" and not b.allow()
    t[0] = 0.5
    assert not b.allow()                        # before the probe window
    t[0] = 1.1
    assert b.allow()                            # probe due
    assert b.record_failure() is False          # failed probe: stay open
    assert not b.allow()                        # window pushed out
    t[0] = 2.5
    assert b.allow()
    dark = b.record_success()                   # recovery
    assert dark == pytest.approx(2.5)           # total open duration
    assert b.state == "closed" and b.allow()
    assert b.record_success() is None           # steady-state success


# ---------------------------------------------------------------------------
# atomic checkpoints
# ---------------------------------------------------------------------------

def test_atomic_write_survives_injected_crash(tmp_path):
    p = str(tmp_path / "f.txt")
    checkpoint.atomic_write_text(p, "GENERATION-1")
    faults.configure("io.write:n=1")
    with pytest.raises(InjectedFault):
        checkpoint.atomic_write_text(p, "GENERATION-2")
    assert open(p).read() == "GENERATION-1"     # old content intact
    faults.clear()
    checkpoint.atomic_write_text(p, "GENERATION-2")
    assert open(p).read() == "GENERATION-2"


def test_pipeline_checkpoint_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    assert checkpoint.load_pipeline_checkpoint(d) is None
    checkpoint.save_pipeline_checkpoint(
        d, window=3, model_str="tree\nversion=v2\n",
        meta={"policy": "fresh"})
    cp = checkpoint.load_pipeline_checkpoint(d)
    assert cp.window == 3
    assert cp.model_string() == "tree\nversion=v2\n"
    assert cp.bins_path is None
    assert cp.meta["policy"] == "fresh"
    assert checkpoint.has_pipeline_checkpoint(d)


def test_latest_snapshot_requires_state_sidecar(tmp_path):
    base = str(tmp_path / "m.txt")
    for it in (2, 4):
        checkpoint.atomic_write_text(f"{base}.snapshot_iter_{it}", "x")
        checkpoint.save_train_state(
            f"{base}.snapshot_iter_{it}.state.npz",
            np.zeros((1, 4), np.float32), it)
    # a bare model file without the sidecar cannot resume exactly
    checkpoint.atomic_write_text(f"{base}.snapshot_iter_6", "x")
    assert checkpoint.latest_snapshot(base).endswith("snapshot_iter_4")
    assert checkpoint.latest_snapshot(str(tmp_path / "none.txt")) is None


# ---------------------------------------------------------------------------
# GBDT snapshot/resume (train_chunked snapshot_freq contract)
# ---------------------------------------------------------------------------

TRAIN_PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
                "bagging_fraction": 0.8, "bagging_freq": 3,
                "feature_fraction": 0.8}


def _train_data(seed=0, n=2000, nf=8):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nf))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    return x, y


def _booster(params, x, y):
    cfg = Config(dict(params))
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    return bst


@pytest.mark.parametrize("device_growth", ["off", "on"])
def test_train_chunked_snapshot_resume_byte_identical(tmp_path,
                                                      device_growth):
    """A killed run resumed from its last snapshot finishes with a
    model string byte-identical to the uninterrupted run — on the host
    path (sequential feature_fraction RNG restored from the sidecar)
    AND the device path (fold_in-keyed draws)."""
    params = {**TRAIN_PARAMS, "device_growth": device_growth}
    x, y = _train_data()
    ref = _booster(params, x, y)
    ref.train_chunked(6, chunk=4)
    ref_str = ref.model_to_string()

    base = str(tmp_path / "m.txt")
    killed = _booster(params, x, y)
    killed.train_chunked(4, chunk=4, snapshot_freq=2, snapshot_path=base)
    snap = checkpoint.latest_snapshot(base)
    assert snap is not None and snap.endswith("snapshot_iter_4")

    resumed = _booster(params, x, y)
    resumed.resume_from_checkpoint(snap)
    assert resumed.iter == 4
    resumed.train_chunked(2, chunk=4)
    assert resumed.model_to_string() == ref_str


def test_resume_rejects_mismatched_data(tmp_path):
    x, y = _train_data()
    bst = _booster(TRAIN_PARAMS, x, y)
    base = str(tmp_path / "m.txt")
    bst.train_chunked(2, chunk=2, snapshot_freq=2, snapshot_path=base)
    other = _booster(TRAIN_PARAMS, *_train_data(seed=1, n=500))
    with pytest.raises(LightGBMError, match="SAME training data"):
        other.resume_from_checkpoint(checkpoint.latest_snapshot(base))


# ---------------------------------------------------------------------------
# serving degradation
# ---------------------------------------------------------------------------

def _served_booster():
    x, y = _train_data(seed=3, n=1500, nf=6)
    bst = _booster({"objective": "binary", "num_leaves": 15,
                    "max_bin": 63, "verbosity": -1, "metric": "none"},
                   x, y)
    bst.train_chunked(5, chunk=5)
    bst._flush_pending()
    return bst, x


def test_serve_degrades_to_host_and_recovers():
    from lightgbm_tpu.serve.engine import PredictionServer
    bst, x = _served_booster()
    srv = PredictionServer(bst, breaker=CircuitBreaker(
        failure_threshold=2, reprobe_interval_s=0.05))
    srv.warmup([256])
    q = x[:256]
    host_ref = np.asarray(bst.predict(q))   # host walk (small batch)

    faults.configure("serve.dispatch:persist")
    outs = [np.asarray(srv.predict(q)) for _ in range(4)]
    for out in outs:                        # zero dropped, EXACT parity
        np.testing.assert_array_equal(out, host_ref)
    assert srv.degraded

    faults.clear()
    time.sleep(0.06)                        # past the re-probe window
    out = np.asarray(srv.predict(q))        # probe recovers the device
    assert not srv.degraded
    np.testing.assert_allclose(out, host_ref, rtol=1e-4, atol=1e-6)


def test_serve_input_error_is_not_a_device_failure():
    from lightgbm_tpu.serve.engine import PredictionServer
    bst, x = _served_booster()
    srv = PredictionServer(bst)
    with pytest.raises(LightGBMError, match="features"):
        srv.predict(np.zeros((4, 2)))       # too-narrow input
    assert not srv.degraded                 # breaker untouched
    assert np.isfinite(np.asarray(srv.predict(x[:8]))).all()


def test_serve_microbatch_poison_isolated():
    """One poisoned submit fails only its own Future; the worker keeps
    draining later batches."""
    from lightgbm_tpu.serve.engine import PredictionServer
    bst, x = _served_booster()
    srv = PredictionServer(bst, max_wait_ms=20.0)
    srv.warmup([128])
    with srv:
        good1 = srv.submit(x[:8])
        poison = srv.submit(np.zeros((4, 2)))   # wrong feature count
        good2 = srv.submit(x[8:16])
        assert np.isfinite(good1.result(timeout=10)).all()
        assert isinstance(poison.exception(timeout=10), LightGBMError)
        assert np.isfinite(good2.result(timeout=10)).all()
        # the worker survived: a fresh submit still resolves
        again = srv.submit(x[:8]).result(timeout=10)
        np.testing.assert_allclose(again, good1.result(), rtol=1e-6)


# ---------------------------------------------------------------------------
# device-dispatch retry path
# ---------------------------------------------------------------------------

def test_transient_dispatch_fault_is_retried_and_absorbed():
    """An injected transient grow.dispatch failure is retried within
    dispatch_retries and training completes with the same model."""
    x, y = _train_data(seed=5)
    params = {**TRAIN_PARAMS, "device_growth": "on",
              "dispatch_retries": 2}
    ref = _booster(params, x, y)
    ref.train_chunked(4, chunk=2)
    ref_str = ref.model_to_string()

    faults.configure("grow.dispatch:at=1")
    bst = _booster(params, x, y)
    bst.train_chunked(4, chunk=2)
    faults.clear()
    assert bst.model_to_string() == ref_str
    assert faults.counts() == {}            # cleared


def test_persistent_dispatch_fault_exhausts_retries():
    x, y = _train_data(seed=6, n=800)
    params = {**TRAIN_PARAMS, "device_growth": "on",
              "dispatch_retries": 1}
    bst = _booster(params, x, y)
    faults.configure("grow.dispatch:persist")
    with pytest.raises(RetryError, match="grow.dispatch failed after "
                                         "2 attempts"):
        bst.train_chunked(2, chunk=2)


# ---------------------------------------------------------------------------
# network point-to-point helpers (parallel/network.py)
# ---------------------------------------------------------------------------

def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_connect_bounded_retries_against_never_listening_port():
    """A peer that never listens exhausts the bounded retries with a
    clear 'unreachable after N attempts' error instead of hanging the
    worker mesh."""
    from lightgbm_tpu.parallel.network import connect_with_retries
    delays = []
    t0 = time.perf_counter()
    with pytest.raises(LightGBMError,
                       match="unreachable after 3 attempts"):
        connect_with_retries("127.0.0.1", _free_port(), attempts=3,
                             timeout_s=0.5, base_delay_s=0.01,
                             sleep=delays.append)
    assert len(delays) == 2                 # attempts - 1 backoffs
    assert time.perf_counter() - t0 < 5.0   # bounded, not hanging


def test_wait_for_peer_validates_and_probes():
    from lightgbm_tpu.parallel.network import wait_for_peer
    with pytest.raises(LightGBMError, match="bad peer address"):
        wait_for_peer("not-an-address", attempts=1)
    with pytest.raises(LightGBMError, match="unreachable"):
        wait_for_peer(f"127.0.0.1:{_free_port()}", attempts=2,
                      timeout_s=0.2, base_delay_s=0.01,
                      sleep=lambda d: None)


def test_send_recv_roundtrip_and_timeout():
    import socket

    from lightgbm_tpu.parallel.network import (connect_with_retries,
                                               recv_bytes, send_bytes)
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    host, port = srv.getsockname()
    ready = threading.Event()

    def peer():
        conn, _ = srv.accept()
        payload = recv_bytes(conn, timeout_s=5.0)
        send_bytes(conn, payload[::-1], timeout_s=5.0)
        ready.wait(5.0)                     # then go silent
        conn.close()
        srv.close()

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    sock = connect_with_retries(host, port, attempts=3, timeout_s=2.0)
    send_bytes(sock, b"serialized mappers")
    assert recv_bytes(sock) == b"sreppam dezilaires"
    with pytest.raises(LightGBMError, match="network timeout"):
        recv_bytes(sock, timeout_s=0.2)     # peer is silent now
    ready.set()
    sock.close()
    t.join(timeout=5.0)


def test_network_params_thread_through_config():
    """network_retries / network_timeout are NOT inert: a Config passed
    to the helpers governs attempts and the socket timeout."""
    from lightgbm_tpu.parallel.network import connect_with_retries
    cfg = Config({"network_retries": 2, "network_timeout": 0.25,
                  "verbosity": -1})
    delays = []
    with pytest.raises(LightGBMError,
                       match="unreachable after 2 attempts"):
        connect_with_retries("127.0.0.1", _free_port(), config=cfg,
                             base_delay_s=0.001, sleep=delays.append)
    assert len(delays) == 1                 # attempts - 1
    # explicit arguments win over the config
    with pytest.raises(LightGBMError,
                       match="unreachable after 4 attempts"):
        connect_with_retries("127.0.0.1", _free_port(), attempts=4,
                             config=cfg, base_delay_s=0.001,
                             sleep=lambda d: None)


def test_recv_rejects_corrupt_length_prefix():
    """A garbage length prefix becomes a bounded protocol error with
    peer context, never a giant allocation."""
    import socket
    import struct

    from lightgbm_tpu.parallel.network import recv_bytes
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("<Q", 1 << 60))
        with pytest.raises(LightGBMError, match="length prefix"):
            recv_bytes(b, timeout_s=2.0)
    finally:
        a.close()
        b.close()


def test_cancelled_future_does_not_kill_microbatch_worker():
    """A caller cancelling its submitted Future (result timeout) must
    not crash the worker thread when the batch later resolves."""
    from lightgbm_tpu.serve.engine import PredictionServer
    bst, x = _served_booster()
    # a long batch window: the worker picks `doomed` up immediately,
    # then waits for more items — the cancel lands deterministically
    # BEFORE the batch resolves
    srv = PredictionServer(bst, max_wait_ms=500.0)
    srv.warmup([128])
    with srv:
        doomed = srv.submit(x[:4])
        assert doomed.cancel()          # worker never marks it running
        live = srv.submit(x[:8])
        assert np.isfinite(live.result(timeout=10)).all()
        # the worker survived the cancelled future in its batch
        again = srv.submit(x[:8]).result(timeout=10)
        np.testing.assert_allclose(again, live.result(), rtol=1e-6)


def test_checkpoint_crash_between_payload_and_manifest(tmp_path):
    """Versioned-payload contract: a crash AFTER window 2's model
    landed but BEFORE the manifest rename leaves window 1's manifest
    pointing at window 1's intact files."""
    d = str(tmp_path / "ckpt")
    checkpoint.save_pipeline_checkpoint(d, window=1, model_str="W1")
    # io.write fires per atomic write: invocation 0 = window 2's model,
    # invocation 1 would be the manifest — crash in between
    faults.configure("io.write:at=1")
    with pytest.raises(InjectedFault):
        checkpoint.save_pipeline_checkpoint(d, window=2,
                                            model_str="W2")
    faults.clear()
    cp = checkpoint.load_pipeline_checkpoint(d)
    assert cp.window == 1 and cp.model_string() == "W1"
    # clean retry commits window 2 and GCs window 1's payload
    checkpoint.save_pipeline_checkpoint(d, window=2, model_str="W2")
    cp = checkpoint.load_pipeline_checkpoint(d)
    assert cp.window == 2 and cp.model_string() == "W2"
    import os
    assert not os.path.exists(os.path.join(d, "model.1.txt"))


def test_injected_net_fault_is_retried():
    """An oserror-flavored injected connect fault consumes retries like
    a real refused connection (the retry_on contract)."""
    import socket

    from lightgbm_tpu.parallel.network import connect_with_retries
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    host, port = srv.getsockname()
    faults.configure("net.connect:n=2:error=oserror")
    sock = connect_with_retries(host, port, attempts=3, timeout_s=1.0,
                                base_delay_s=0.001,
                                sleep=lambda d: None)
    assert faults.counts() == {"net.connect": 2}
    sock.close()
    srv.close()
