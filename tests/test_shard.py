"""Single-controller sharded training (docs/Sharding.md).

The contracts under test need a multi-device mesh, and XLA's forced
host-device count must be set before jax initializes — so the actual
training runs in a subprocess (tests/_shard_worker.py) under
``XLA_FLAGS=--xla_force_host_platform_device_count=4``, and these tests
assert on its JSON report:

* (a) 1-vs-4-device tree BYTE-identity with ``grad_quant_bits=8`` (the
  int32 histogram scan psums integer-exactly), fused and per-iteration;
* (b) f32 sharded training is bit-reproducible run-to-run;
* (c) bagging + feature_fraction + train_row_bucketing are
  shard-invariant (global-row-indexed draws);
* (d) a mid-train checkpoint on the 4-device mesh resumes
  byte-identical;
* a warm same-shape retrain window traces NOTHING new (the program
  cache holds across windows under sharding).

Where the container's shard_map environment fails, the worker reports
``{"skip": reason}`` and the tests record that reason (ROADMAP memory
note: such failures are environmental; validate on real multi-chip).
"""

import json
import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_shard_worker.py")


def _run_worker(scenario, outdir=".", timeout=420):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, _WORKER, scenario, str(outdir)], env=env,
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"shard worker failed:\n{proc.stderr[-3000:]}"
    for ln in reversed(proc.stdout.splitlines()):
        try:
            out = json.loads(ln)
            break
        except json.JSONDecodeError:
            continue
    else:
        raise AssertionError(
            f"worker printed no JSON:\n{proc.stdout[-2000:]}")
    if "skip" in out:
        pytest.skip(out["skip"])
    return out


@pytest.fixture(scope="module")
def core_report():
    # ONE subprocess covers identity/determinism/invariance/warm-window:
    # the scenarios share the jax import and the compiled programs, so
    # tier-1 pays the (minutes-scale on CPU) mesh compile cost once
    return _run_worker("core")


@pytest.mark.timeout(460)
def test_shard_quant8_byte_identity(core_report):
    # acceptance gate: on 4 forced host devices with grad_quant_bits=8
    # the sharded model's trees are byte-identical to the single-device
    # fused path, on BOTH dispatch paths
    assert core_report["identity_fused"] is True
    assert core_report["identity_per_iter"] is True


def test_shard_f32_run_to_run_deterministic(core_report):
    assert core_report["f32_deterministic"] is True


def test_shard_bagging_feature_fraction_invariant(core_report):
    # the in-scan sampling draws are global-row-indexed, so the same
    # rows/features are picked whatever the mesh size — pinned by byte
    # identity with both samplers active under the int32 scan
    assert core_report["invariance_bag_ff"] is True


def test_shard_warm_window_traces_nothing(core_report):
    assert core_report["warm_window_new_compiles"] == 0
    assert core_report["warm_window_cache_hit"] is True


def test_shard_obs_digest(core_report):
    digest = core_report["shard_digest"]
    assert digest is not None
    assert digest["devices"] == 4
    assert digest["sharded_dispatches"] > 0


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_shard_row_bucketing_invariant():
    # needs a row count whose per-shard pow2 bucket differs from the
    # exact chunk pad, so it actually exercises two program families —
    # minutes on CPU, hence slow-marked (scripts/check.sh full mode)
    out = _run_worker("bucketing", timeout=580)
    assert out["bucketing_invariant"] is True


@pytest.mark.slow
@pytest.mark.timeout(460)
def test_shard_checkpoint_resume_identical(tmp_path):
    # its own subprocess (fresh jax + mesh compiles): minutes-class on
    # the 1-core container, so it runs in check.sh's slow step —
    # tier-1's identity/determinism gates above share one worker
    out = _run_worker("checkpoint", outdir=tmp_path)
    assert out["snapshot_written"] is True
    assert out["resume_identical"] is True
