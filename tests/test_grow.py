"""On-device wave grower (ops/grow.py) vs the host-driven learner.

The device grower must reproduce the host learner's trees exactly when no
budget pressure or numeric near-ties are involved, and match its metrics
otherwise.  Runs on the CPU backend (conftest forces the 8-device CPU
mesh); the same code path runs on real TPU."""

import numpy as np
import pytest

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.ops.grow import device_growth_eligible


def _make(params, x, y, device):
    cfg = Config({**params,
                  "device_growth": "on" if device else "off"})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    return bst


def _split_set(tree):
    return sorted((int(tree.split_feature_inner[i]),
                   int(tree.threshold_in_bin[i]),
                   int(tree.internal_count[i]))
                  for i in range(tree.num_leaves - 1))


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(5)
    n = 4000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 2 * (x[:, 1] > 0.3) - 1.5 * (x[:, 2] < -0.5)
         + 0.1 * rng.standard_normal(n)).astype(np.float32)
    return x, y


def test_device_tree_matches_host(reg_data):
    """With a generous leaf budget and gpu_use_dp (f32-exact histogram
    accumulation) both paths should produce the same split set (wave
    batching only reorders node numbering).  The default 3-column bf16
    histogram may move near-tie thresholds by one bin — the documented
    fast-path tradeoff (reference GPU f32 vs CPU f64 histograms,
    docs/GPU-Performance.rst:128-161) — so it gets a looser check."""
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 64,
              "learning_rate": 0.1, "min_data_in_leaf": 50,
              "gpu_use_dp": True}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    assert bd._grower is not None and bh._grower is None
    bh.train_one_iter()
    bd.train_one_iter()
    bd._flush_pending()
    th, td = bh.models[0], bd.models[0]
    assert th.num_leaves == td.num_leaves
    assert _split_set(th) == _split_set(td)
    assert np.allclose(bh.predict(x), bd.predict(x), atol=1e-5)
    # fast default (bf16 stat columns): identical up to near-tie bins
    bf = _make({k: v for k, v in params.items() if k != "gpu_use_dp"},
               x, y, True)
    bf.train_one_iter()
    bf._flush_pending()
    tf = bf.models[0]
    assert tf.num_leaves == th.num_leaves
    diff = set(_split_set(th)) ^ set(_split_set(tf))
    assert len(diff) <= 2 * max(1, th.num_leaves // 16), diff
    mse_h = float(np.mean((bh.predict(x) - y) ** 2))
    mse_f = float(np.mean((bf.predict(x) - y) ** 2))
    assert mse_f == pytest.approx(mse_h, rel=1e-3)


def test_device_binary_auc(reg_data):
    rng = np.random.default_rng(7)
    n = 20000
    x = rng.standard_normal((n, 10)).astype(np.float32)
    w = rng.standard_normal(10)
    p = 1 / (1 + np.exp(-(x @ w + np.abs(x[:, 0]))))
    y = (p > rng.random(n)).astype(np.float32)
    params = {"objective": "binary", "metric": "auc", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20}
    from sklearn.metrics import roc_auc_score
    aucs = []
    for device in (False, True):
        bst = _make(params, x, y, device)
        for _ in range(20):
            if bst.train_one_iter():
                break
        aucs.append(roc_auc_score(y, bst.predict(x, raw_score=True)))
    assert aucs[1] > aucs[0] - 0.01, aucs


def test_device_model_roundtrip(reg_data):
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 31,
              "learning_rate": 0.2}
    bst = _make(params, x, y, True)
    for _ in range(5):
        bst.train_one_iter()
    text = bst.model_to_string()
    from lightgbm_tpu.boosting.gbdt import GBDT
    loaded = GBDT.load_model_from_string(text)
    assert np.allclose(loaded.predict(x, raw_score=True),
                       bst.predict(x, raw_score=True), atol=1e-6)


def test_device_stop_on_unsplittable():
    """Constant labels -> zero gain everywhere -> training must stop and
    trailing stump iterations be trimmed (host parity)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((500, 4)).astype(np.float32)
    y = np.zeros(500, np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1}
    bst = _make(params, x, y, True)
    stopped = False
    for _ in range(40):
        if bst.train_one_iter():
            stopped = True
            break
    assert stopped
    bst._flush_pending()
    assert all(t.num_leaves <= 1 for t in bst.models) or not bst.models


def test_device_stall_short_run_predict_consistent(reg_data):
    """ADVICE r3 (high): a run that stalls within the first few
    iterations must not keep stump trees carrying the unshrunk root
    output — predict() has to agree with the host path and with the
    (unchanged-after-bias) training scores."""
    x, y = reg_data
    y = y + 20.0       # nonzero mean: boost_from_average bias matters
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1, "min_gain_to_split": 1e9}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    for _ in range(10):
        if bh.train_one_iter():
            break
    for _ in range(10):
        if bd.train_one_iter():
            break
    ph = bh.predict(x[:50])
    pd = bd.predict(x[:50])
    np.testing.assert_allclose(pd, ph, atol=1e-6)
    # prediction must equal the training score (the bias only)
    ts = np.asarray(bd.train_score)[0][:50]
    np.testing.assert_allclose(pd, ts, atol=1e-6)
    # valid catch-up must deliver the bias too (not drop stump trees)
    bd2 = _make(params, x, y, True)
    cfg = Config({**params, "device_growth": "off"})
    vds = BinnedDataset.construct_from_matrix(
        x[:200], cfg, reference=bd2.train_set)
    from lightgbm_tpu.data.dataset import Metadata
    vds.metadata = Metadata(200)
    vds.metadata.set_label(y[:200])
    bd2.add_valid(vds, "v")
    for _ in range(6):
        if bd2.train_one_iter():
            break
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bd2.eval_valid())
    direct = float(np.mean((bd2.predict(x[:200]) - y[:200]) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)


def test_device_valid_eval_catches_up(reg_data):
    """The device path defers valid-score updates to evaluation time; the
    caught-up score must equal predicting the valid rows directly."""
    x, y = reg_data
    xt, yt = x[:1000], y[:1000]
    params = {"objective": "regression", "metric": "l2", "num_leaves": 31,
              "learning_rate": 0.1}
    bd = _make(params, x, y, True)
    cfg = bd.config
    vds = BinnedDataset.construct_from_matrix(xt, cfg,
                                              reference=bd.train_set)
    from lightgbm_tpu.data.dataset import Metadata
    vds.metadata = Metadata(len(yt))
    vds.metadata.set_label(yt)
    bd.add_valid(vds, "v")
    for _ in range(8):
        bd.train_one_iter()
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bd.eval_valid())
    direct = float(np.mean((bd.predict(xt) - yt) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)


@pytest.mark.parametrize("device", [False, True])
def test_rollback_valid_scores_consistent(reg_data, device):
    """ADVICE r3 (medium): rollback_one_iter must leave every valid
    set's score equal to predicting its rows with the shortened model,
    on both the eager (host) and deferred (device) valid-update paths —
    including a rollback that straddles a mid-training catch-up."""
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1}
    bst = _make(params, x, y, device)
    cfg = Config({**params, "device_growth": "off"})
    vds = BinnedDataset.construct_from_matrix(x[:200], cfg,
                                              reference=bst.train_set)
    from lightgbm_tpu.data.dataset import Metadata
    vds.metadata = Metadata(200)
    vds.metadata.set_label(y[:200])
    bst.add_valid(vds, "v")
    for _ in range(4):
        bst.train_one_iter()
    bst.eval_valid()            # device path: catch up part-way
    for _ in range(3):
        bst.train_one_iter()
    bst.rollback_one_iter()
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    direct = float(np.mean((bst.predict(x[:200]) - y[:200]) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)
    # rollback + retrain: the replacement tree must reach valid scores
    bst.train_one_iter()
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    direct = float(np.mean((bst.predict(x[:200]) - y[:200]) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)


def test_eligibility_gates():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((300, 4)).astype(np.float32)
    y = rng.standard_normal(300).astype(np.float32)
    # bagging disables the device path
    cfg = Config({"objective": "regression", "bagging_fraction": 0.5,
                  "bagging_freq": 1})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    from lightgbm_tpu.objectives import create_objective
    obj = create_objective(cfg)
    obj.init(ds.metadata or __import__(
        "lightgbm_tpu.data.dataset", fromlist=["Metadata"]).Metadata(300),
        300)
    assert not device_growth_eligible(cfg, ds, obj, 1)
    cfg2 = Config({"objective": "regression"})
    assert device_growth_eligible(cfg2, ds, obj, 1)
    assert not device_growth_eligible(cfg2, ds, obj, 3)
