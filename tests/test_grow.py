"""On-device wave grower (ops/grow.py) vs the host-driven learner.

The device grower must reproduce the host learner's trees exactly when no
budget pressure or numeric near-ties are involved, and match its metrics
otherwise.  Runs on the CPU backend (conftest forces the 8-device CPU
mesh); the same code path runs on real TPU."""

import numpy as np
import pytest

from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.ops.grow import device_growth_eligible


def _make(params, x, y, device):
    cfg = Config({**params,
                  "device_growth": "on" if device else "off"})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    return bst


def _split_set(tree):
    return sorted((int(tree.split_feature_inner[i]),
                   int(tree.threshold_in_bin[i]),
                   int(tree.internal_count[i]))
                  for i in range(tree.num_leaves - 1))


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(5)
    n = 4000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 2 * (x[:, 1] > 0.3) - 1.5 * (x[:, 2] < -0.5)
         + 0.1 * rng.standard_normal(n)).astype(np.float32)
    return x, y



def _assert_trees_close(th, td, max_flips=2):
    """Identical up to near-tie threshold flips: host and device
    accumulate f32 histograms in different orders (per-leaf scan vs wave
    matmul), so a handful of one-bin threshold moves on equal-gain ties
    are legitimate even under gpu_use_dp."""
    assert th.num_leaves == td.num_leaves
    sh, sd = set(_split_set(th)), set(_split_set(td))
    only_h = sorted(sh - sd)
    only_d = sorted(sd - sh)
    assert len(only_h) == len(only_d) <= max_flips, (only_h, only_d)
    for (fh, bh_, ch), (fd, bd_, cd) in zip(only_h, only_d):
        assert fh == fd and ch == cd and abs(bh_ - bd_) <= 2, \
            (only_h, only_d)


def test_device_tree_matches_host(reg_data):
    """With a generous leaf budget and gpu_use_dp (f32-exact histogram
    accumulation) both paths should produce the same split set (wave
    batching only reorders node numbering).  The default 3-column bf16
    histogram may move near-tie thresholds by one bin — the documented
    fast-path tradeoff (reference GPU f32 vs CPU f64 histograms,
    docs/GPU-Performance.rst:128-161) — so it gets a looser check."""
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 64,
              "learning_rate": 0.1, "min_data_in_leaf": 50,
              "gpu_use_dp": True}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    assert bd._grower is not None and bh._grower is None
    bh.train_one_iter()
    bd.train_one_iter()
    bd._flush_pending()
    th, td = bh.models[0], bd.models[0]
    assert th.num_leaves == td.num_leaves
    assert _split_set(th) == _split_set(td)
    assert np.allclose(bh.predict(x), bd.predict(x), atol=1e-5)
    # fast default (bf16 stat columns): identical up to near-tie bins
    bf = _make({k: v for k, v in params.items() if k != "gpu_use_dp"},
               x, y, True)
    bf.train_one_iter()
    bf._flush_pending()
    tf = bf.models[0]
    assert tf.num_leaves == th.num_leaves
    diff = set(_split_set(th)) ^ set(_split_set(tf))
    assert len(diff) <= 2 * max(1, th.num_leaves // 16), diff
    mse_h = float(np.mean((bh.predict(x) - y) ** 2))
    mse_f = float(np.mean((bf.predict(x) - y) ** 2))
    assert mse_f == pytest.approx(mse_h, rel=1e-3)


def test_device_binary_auc(reg_data):
    rng = np.random.default_rng(7)
    n = 20000
    x = rng.standard_normal((n, 10)).astype(np.float32)
    w = rng.standard_normal(10)
    p = 1 / (1 + np.exp(-(x @ w + np.abs(x[:, 0]))))
    y = (p > rng.random(n)).astype(np.float32)
    params = {"objective": "binary", "metric": "auc", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20}
    from sklearn.metrics import roc_auc_score
    aucs = []
    for device in (False, True):
        bst = _make(params, x, y, device)
        for _ in range(20):
            if bst.train_one_iter():
                break
        aucs.append(roc_auc_score(y, bst.predict(x, raw_score=True)))
    assert aucs[1] > aucs[0] - 0.01, aucs


def test_device_model_roundtrip(reg_data):
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 31,
              "learning_rate": 0.2}
    bst = _make(params, x, y, True)
    for _ in range(5):
        bst.train_one_iter()
    text = bst.model_to_string()
    from lightgbm_tpu.boosting.gbdt import GBDT
    loaded = GBDT.load_model_from_string(text)
    assert np.allclose(loaded.predict(x, raw_score=True),
                       bst.predict(x, raw_score=True), atol=1e-6)


def test_device_stop_on_unsplittable():
    """Constant labels -> zero gain everywhere -> training must stop and
    trailing stump iterations be trimmed (host parity)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((500, 4)).astype(np.float32)
    y = np.zeros(500, np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1}
    bst = _make(params, x, y, True)
    stopped = False
    for _ in range(40):
        if bst.train_one_iter():
            stopped = True
            break
    assert stopped
    bst._flush_pending()
    assert all(t.num_leaves <= 1 for t in bst.models) or not bst.models


def test_device_stall_short_run_predict_consistent(reg_data):
    """ADVICE r3 (high): a run that stalls within the first few
    iterations must not keep stump trees carrying the unshrunk root
    output — predict() has to agree with the host path and with the
    (unchanged-after-bias) training scores."""
    x, y = reg_data
    y = y + 20.0       # nonzero mean: boost_from_average bias matters
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1, "min_gain_to_split": 1e9}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    for _ in range(10):
        if bh.train_one_iter():
            break
    for _ in range(10):
        if bd.train_one_iter():
            break
    ph = bh.predict(x[:50])
    pd = bd.predict(x[:50])
    np.testing.assert_allclose(pd, ph, atol=1e-6)
    # prediction must equal the training score (the bias only)
    ts = np.asarray(bd.train_score)[0][:50]
    np.testing.assert_allclose(pd, ts, atol=1e-6)
    # valid catch-up must deliver the bias too (not drop stump trees)
    bd2 = _make(params, x, y, True)
    cfg = Config({**params, "device_growth": "off"})
    vds = BinnedDataset.construct_from_matrix(
        x[:200], cfg, reference=bd2.train_set)
    from lightgbm_tpu.data.dataset import Metadata
    vds.metadata = Metadata(200)
    vds.metadata.set_label(y[:200])
    bd2.add_valid(vds, "v")
    for _ in range(6):
        if bd2.train_one_iter():
            break
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bd2.eval_valid())
    direct = float(np.mean((bd2.predict(x[:200]) - y[:200]) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)


def test_device_valid_eval_catches_up(reg_data):
    """The device path defers valid-score updates to evaluation time; the
    caught-up score must equal predicting the valid rows directly."""
    x, y = reg_data
    xt, yt = x[:1000], y[:1000]
    params = {"objective": "regression", "metric": "l2", "num_leaves": 31,
              "learning_rate": 0.1}
    bd = _make(params, x, y, True)
    cfg = bd.config
    vds = BinnedDataset.construct_from_matrix(xt, cfg,
                                              reference=bd.train_set)
    from lightgbm_tpu.data.dataset import Metadata
    vds.metadata = Metadata(len(yt))
    vds.metadata.set_label(yt)
    bd.add_valid(vds, "v")
    for _ in range(8):
        bd.train_one_iter()
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bd.eval_valid())
    direct = float(np.mean((bd.predict(xt) - yt) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)


@pytest.mark.parametrize("device", [False, True])
def test_rollback_valid_scores_consistent(reg_data, device):
    """ADVICE r3 (medium): rollback_one_iter must leave every valid
    set's score equal to predicting its rows with the shortened model,
    on both the eager (host) and deferred (device) valid-update paths —
    including a rollback that straddles a mid-training catch-up."""
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.1}
    bst = _make(params, x, y, device)
    cfg = Config({**params, "device_growth": "off"})
    vds = BinnedDataset.construct_from_matrix(x[:200], cfg,
                                              reference=bst.train_set)
    from lightgbm_tpu.data.dataset import Metadata
    vds.metadata = Metadata(200)
    vds.metadata.set_label(y[:200])
    bst.add_valid(vds, "v")
    for _ in range(4):
        bst.train_one_iter()
    bst.eval_valid()            # device path: catch up part-way
    for _ in range(3):
        bst.train_one_iter()
    bst.rollback_one_iter()
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    direct = float(np.mean((bst.predict(x[:200]) - y[:200]) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)
    # rollback + retrain: the replacement tree must reach valid scores
    bst.train_one_iter()
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    direct = float(np.mean((bst.predict(x[:200]) - y[:200]) ** 2))
    assert res["v:l2"] == pytest.approx(direct, rel=1e-5)


def test_eligibility_gates():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((300, 4)).astype(np.float32)
    y = rng.standard_normal(300).astype(np.float32)
    # bagging and multiclass are now device-eligible; renew objectives
    # (L1-style leaf refits) still fall back to the host learner
    cfg = Config({"objective": "regression", "bagging_fraction": 0.5,
                  "bagging_freq": 1})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    from lightgbm_tpu.objectives import create_objective
    obj = create_objective(cfg)
    obj.init(ds.metadata or __import__(
        "lightgbm_tpu.data.dataset", fromlist=["Metadata"]).Metadata(300),
        300)
    assert device_growth_eligible(cfg, ds, obj, 1)
    cfg2 = Config({"objective": "regression"})
    assert device_growth_eligible(cfg2, ds, obj, 1)
    assert device_growth_eligible(cfg2, ds, obj, 3)
    cfg3 = Config({"objective": "regression_l1"})
    obj3 = create_objective(cfg3)
    obj3.init(ds.metadata, 300)
    assert not device_growth_eligible(cfg3, ds, obj3, 1)


def test_pallas_hist_matches_einsum(reg_data):
    """The Pallas wave-histogram kernel (interpret mode on CPU) must
    agree with the XLA einsum formulation bin-for-bin."""
    import jax.numpy as jnp
    x, y = reg_data
    # grower_cache off: the flags tweaked below live on the (otherwise
    # process-shared) GrowerPrograms object, so this test needs a
    # private instance
    params = {"objective": "regression", "num_leaves": 64,
              "min_data_in_leaf": 50, "grower_cache": False}
    bd = _make(params, x, y, True)
    grower = bd._grower.programs
    assert grower is not None
    binned = bd._grower.binned
    n = grower.n_pad
    rng = np.random.default_rng(0)
    leaf = jnp.asarray(rng.integers(0, 8, n).astype(np.int32))
    g = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    h = jnp.asarray(rng.random(n).astype(np.float32))
    one = jnp.ones((n,), jnp.bfloat16)
    ghk = jnp.stack([g.astype(jnp.bfloat16), h.astype(jnp.bfloat16),
                     one], 1)
    # the kernel handles single-tile widths (w*k <= 128); pin the wave
    # width into that range (the production path gates the same way)
    grower.wave_width = min(grower.wave_width, 128 // grower.hist_cols)
    pending = jnp.asarray(
        np.concatenate([np.arange(6), [-1] * (grower.wave_width - 6)])
        .astype(np.int32))
    grower.use_pallas = False
    ref = np.asarray(grower._wave_hist(binned, leaf, ghk, pending))
    grower.use_pallas = True
    grower.pallas_interpret = True
    got = np.asarray(grower._wave_hist(binned, leaf, ghk, pending))
    np.testing.assert_allclose(got, ref, atol=1e-3, rtol=1e-4)


def test_device_bagging_matches_host(reg_data):
    """Bagging routes a row mask into the device grower; with the same
    seed both paths draw the same bag, so gpu_use_dp trees must match
    split-for-split."""
    x, y = reg_data
    # num_leaves far above the natural stop (min_data_in_leaf halts
    # growth first): wave batching only deviates from strict best-first
    # under budget pressure (see grow.py module docstring)
    params = {"objective": "regression", "num_leaves": 64,
              "learning_rate": 0.1, "bagging_fraction": 0.6,
              "bagging_freq": 1, "bagging_seed": 9, "gpu_use_dp": True,
              "min_data_in_leaf": 60}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    assert bd._grower is not None
    for _ in range(3):
        bh.train_one_iter()
        bd.train_one_iter()
    bd._flush_pending()
    for th, td in zip(bh.models, bd.models):
        _assert_trees_close(th, td)
    np.testing.assert_allclose(bd.predict(x[:100]), bh.predict(x[:100]),
                               atol=5e-3)


def test_device_multiclass_matches_host():
    rng = np.random.default_rng(11)
    n = 3000
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0.5).astype(np.float32) \
        + (x[:, 2] > 0.8) * 1.0
    # min_gain_to_split suppresses noise splits (the exhausted class-2
    # residual yields gains ~1e-5 where host/device f32 rounding
    # legitimately disagrees about positivity)
    params = {"objective": "multiclass", "num_class": 3,
              "num_leaves": 64, "learning_rate": 0.1,
              "gpu_use_dp": True, "min_data_in_leaf": 100,
              "min_gain_to_split": 1e-3}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    assert bd._grower is not None and bd.num_model == 3
    # 3 iterations of exact tree equality; beyond that, accumulated f32
    # score drift (~1e-6/iter) legitimately flips near-tie thresholds
    for _ in range(3):
        bh.train_one_iter()
        bd.train_one_iter()
    bd._flush_pending()
    assert len(bd.models) == len(bh.models) == 9
    for th, td in zip(bh.models, bd.models):
        _assert_trees_close(th, td)
    np.testing.assert_allclose(bd.predict(x[:100]), bh.predict(x[:100]),
                               atol=5e-3)
    # accuracy sanity
    pred = np.argmax(bd.predict(x), axis=1)
    assert (pred == y).mean() > 0.8


def test_device_goss_matches_host(reg_data):
    x, y = reg_data
    params = {"objective": "regression", "boosting": "goss",
              "num_leaves": 64, "learning_rate": 0.3,
              "top_rate": 0.3, "other_rate": 0.2, "gpu_use_dp": True,
              "min_data_in_leaf": 60}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    assert bd._grower is not None
    # train past the GOSS warm-up (1/lr = 3 iters) so sampling kicks in
    for _ in range(6):
        bh.train_one_iter()
        bd.train_one_iter()
    bd._flush_pending()
    assert any(t.num_leaves > 1 for t in bd.models[3:])
    for th, td in zip(bh.models, bd.models):
        _assert_trees_close(th, td)
    np.testing.assert_allclose(bd.predict(x[:100]), bh.predict(x[:100]),
                               atol=5e-3)


def test_device_categorical_matches_host():
    """Categorical optimal splits route through the device grower: the
    winning category set is carried as an 8-word bin bitset and replayed
    into Tree.split_categorical."""
    rng = np.random.default_rng(13)
    n = 4000
    cat = rng.integers(0, 12, n)
    x = np.column_stack([
        cat.astype(np.float32),
        rng.standard_normal(n).astype(np.float32),
        rng.standard_normal(n).astype(np.float32)])
    effect = np.asarray([2.0, -1.0, 0.5, 3.0, -2.0, 0.0,
                         1.5, -0.5, 2.5, -1.5, 0.7, -2.5])
    y = (effect[cat] + x[:, 1] + 0.1 * rng.standard_normal(n)) \
        .astype(np.float32)
    params = {"objective": "regression", "num_leaves": 64,
              "learning_rate": 0.1, "min_data_in_leaf": 60,
              "gpu_use_dp": True, "min_gain_to_split": 1e-3,
              "categorical_feature": [0]}
    cfg_h = Config({**params, "device_growth": "off"})
    cfg_d = Config({**params, "device_growth": "on"})
    from lightgbm_tpu.boosting import create_boosting
    out = {}
    for tag, cfg in (("h", cfg_h), ("d", cfg_d)):
        ds = BinnedDataset.construct_from_matrix(x, cfg, categorical=[0])
        ds.metadata.set_label(y)
        bst = create_boosting(cfg)
        bst.init_train(ds)
        for _ in range(3):
            bst.train_one_iter()
        bst._flush_pending()
        out[tag] = bst
    assert out["d"]._grower is not None
    assert out["h"]._grower is None
    for th, td in zip(out["h"].models, out["d"].models):
        _assert_trees_close(th, td)
    # at least one categorical split must exist and round-trip
    assert any(t.num_cat > 0 for t in out["d"].models)
    np.testing.assert_allclose(out["d"].predict(x[:200]),
                               out["h"].predict(x[:200]), atol=5e-3)
    from lightgbm_tpu.boosting.gbdt import GBDT
    loaded = GBDT.load_model_from_string(out["d"].model_to_string())
    np.testing.assert_allclose(loaded.predict(x[:200], raw_score=True),
                               out["d"].predict(x[:200], raw_score=True),
                               atol=1e-6)


@pytest.mark.parametrize("boosting", ["dart", "rf"])
def test_device_dart_rf_match_host(reg_data, boosting):
    """DART and RF route through the device grower (DART flushes pending
    records before re-scaling dropped trees; RF feeds its fixed targets
    through the gradient hook)."""
    x, y = reg_data
    params = {"objective": "regression", "boosting": boosting,
              "num_leaves": 64, "learning_rate": 0.1,
              "min_data_in_leaf": 60, "gpu_use_dp": True,
              "min_gain_to_split": 1e-3,
              "bagging_fraction": 0.7, "bagging_freq": 1,
              "drop_seed": 4}
    bh = _make(params, x, y, False)
    bd = _make(params, x, y, True)
    assert bd._grower is not None
    for _ in range(4):
        bh.train_one_iter()
        bd.train_one_iter()
    bd._flush_pending()
    assert len(bd.models) == len(bh.models)
    for th, td in zip(bh.models, bd.models):
        _assert_trees_close(th, td)
    np.testing.assert_allclose(bd.predict(x[:100]), bh.predict(x[:100]),
                               atol=5e-3)


@pytest.mark.parametrize("extra,striped_cols,plain_cols", [
    ({}, 4, 3),
    ({"gpu_use_dp": True}, 6, 5),
], ids=["plain", "gpu_use_dp"])
def test_striped_count_columns_match_default(reg_data, extra,
                                             striped_cols, plain_cols):
    """N >= COUNT_SPLIT_ROWS switches the wave matmul to two striped
    count columns (hist_cols 3->4, and 5->6 under gpu_use_dp so the
    extra-precision path does not reintroduce the single-column count
    overflow).  Forced on small data, the striped device trees must
    match the default device layout exactly: identical g/h columns and
    counts exact in both layouts at this size (the stripe only changes
    the matmul's column split, summed back before any consumer)."""
    import lightgbm_tpu.ops.grow as growmod
    x, y = reg_data
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 20, **extra}
    old = growmod.COUNT_SPLIT_ROWS
    try:
        # threshold <= N < 2x threshold keeps the config device-eligible
        growmod.COUNT_SPLIT_ROWS = 3000
        bs = _make(params, x, y, True)
        assert bs._grower is not None
        assert bs._grower.hist_cols == striped_cols
        growmod.COUNT_SPLIT_ROWS = old
        bp = _make(params, x, y, True)
        assert bp._grower.hist_cols == plain_cols
        for _ in range(5):
            bs.train_one_iter()
            bp.train_one_iter()
        bs._flush_pending()
        bp._flush_pending()
        np.testing.assert_allclose(np.asarray(bs.predict(x[:256])),
                                   np.asarray(bp.predict(x[:256])),
                                   rtol=1e-5, atol=1e-6)
    finally:
        growmod.COUNT_SPLIT_ROWS = old
