"""Telemetry subsystem (lightgbm_tpu.obs): registry math, spans/trace
export, jit recompile tracking, engine integration, callback ordering,
and the end-to-end enabled path via a 2-iteration ``bench.py
--metrics`` subprocess schema-checked by ``scripts/validate_metrics.py``.
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs.registry import MetricsRegistry, RESERVOIR_SIZE
from lightgbm_tpu.obs.state import STATE

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "validate_metrics", os.path.join(REPO, "scripts",
                                     "validate_metrics.py"))
validate_metrics = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(validate_metrics)


@pytest.fixture(autouse=True)
def _clean_obs_state():
    obs.configure(enabled=False)
    obs.reset()
    STATE.metrics_path = STATE.trace_path = STATE.events_path = None
    STATE.sync = False
    yield
    obs.configure(enabled=False)
    obs.reset()
    STATE.metrics_path = STATE.trace_path = STATE.events_path = None
    STATE.sync = False


def _small_train(params_extra=None, rounds=4, evals=True):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((400, 5))
    y = (x[:, 0] + x[:, 1] ** 2 > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "metric": "binary_logloss", "min_data_in_leaf": 5}
    params.update(params_extra or {})
    ds = lgb.Dataset(x, label=y)
    return lgb.train(params, ds, num_boost_round=rounds,
                     valid_sets=[ds] if evals else None,
                     verbose_eval=False)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counters_and_gauges(self):
        r = MetricsRegistry()
        r.inc("c")
        r.inc("c", 4)
        assert r.counter("c") == 5
        r.set_gauge("g", 2.0)
        r.set_gauge("g", 1.0)
        assert r.gauge("g") == 1.0
        r.max_gauge("peak", 10)
        r.max_gauge("peak", 3)
        assert r.gauge("peak") == 10

    def test_timing_percentiles(self):
        r = MetricsRegistry()
        for ms in range(1, 101):             # 1..100 ms
            r.observe("t", ms / 1000.0)
        d = r.snapshot()["timings"]["t"]
        assert d["count"] == 100
        assert d["max_s"] == pytest.approx(0.100)
        assert d["total_s"] == pytest.approx(5.050)
        assert d["mean_s"] == pytest.approx(0.0505)
        assert 0.045 <= d["p50_s"] <= 0.055
        assert 0.090 <= d["p95_s"] <= 0.100
        assert d["p50_s"] <= d["p95_s"] <= d["max_s"]

    def test_reservoir_bounded_and_deterministic(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        for i in range(RESERVOIR_SIZE * 3):
            r1.observe("t", i * 1e-6)
            r2.observe("t", i * 1e-6)
        s1 = r1.snapshot()["timings"]["t"]
        s2 = r2.snapshot()["timings"]["t"]
        assert s1 == s2                       # seeded reservoir
        assert s1["count"] == RESERVOIR_SIZE * 3

    def test_jit_attribution(self):
        r = MetricsRegistry()
        r.record_compile("grow", "(f32[8])")
        r.record_compile("grow", "(f32[8])")
        r.record_compile("grow", "(f32[16])")
        snap = r.snapshot()["jit"]["grow"]
        assert snap["compiles"] == 3
        assert snap["signatures"] == {"(f32[8])": 2, "(f32[16])": 1}


# ---------------------------------------------------------------------------
# spans / trace export
# ---------------------------------------------------------------------------

class TestSpans:
    def test_disabled_span_records_nothing(self):
        with obs.span("x"):
            pass
        obs.inc("c")
        obs.observe("t", 1.0)
        obs.instant("i")
        snap = STATE.registry.snapshot()
        assert snap["counters"] == {} and snap["timings"] == {}
        assert len(STATE.trace) == 0

    def test_span_records_timing_and_event(self):
        obs.configure(enabled=True)
        with obs.span("work", cat="test", k=1) as sp:
            sp.set(extra="v")
        snap = STATE.registry.snapshot()
        assert snap["timings"]["work"]["count"] == 1
        assert len(STATE.trace) == 1

    def test_chrome_trace_structure(self, tmp_path):
        obs.configure(enabled=True)
        with obs.span("s", cat="c", a=1):
            pass
        obs.instant("marker", note="hi")
        obs.counter_sample("mem", bytes_in_use=123)
        path = str(tmp_path / "trace.json")
        obs.dump_trace(path)
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        assert isinstance(evs, list)
        by_ph = {e["ph"]: e for e in evs}
        assert set(by_ph) == {"M", "X", "i", "C"}
        x = by_ph["X"]
        assert x["name"] == "s" and x["dur"] >= 0 and "ts" in x \
            and "pid" in x and "tid" in x
        assert by_ph["C"]["args"] == {"bytes_in_use": 123}
        assert by_ph["i"]["s"] == "t"

    def test_jsonl_export(self, tmp_path):
        obs.configure(enabled=True)
        with obs.span("s", iter=3):
            pass
        path = str(tmp_path / "ev.jsonl")
        obs.dump_events_jsonl(path)
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 1
        rec = lines[0]
        assert rec["name"] == "s" and rec["kind"] == "span"
        assert rec["dur_s"] >= 0 and rec["args"] == {"iter": 3}

    def test_buffer_cap_counts_drops(self):
        from lightgbm_tpu.obs import events
        buf = events.TraceBuffer()
        old = events.MAX_EVENTS
        try:
            events.MAX_EVENTS = 3
            for i in range(5):
                buf.add(f"e{i}")
        finally:
            events.MAX_EVENTS = old
        assert len(buf) == 3 and buf.dropped == 2


# ---------------------------------------------------------------------------
# jit recompile tracking
# ---------------------------------------------------------------------------

class TestTrackJit:
    def test_counts_one_compile_per_signature(self):
        import jax
        import jax.numpy as jnp
        obs.configure(enabled=True)
        fn = obs.track_jit("tj_test", jax.jit(lambda x: x * 2))
        a = jnp.ones((4,), jnp.float32)
        b = jnp.ones((8,), jnp.float32)
        fn(a), fn(a), fn(b), fn(a)
        snap = STATE.registry.snapshot()
        ent = snap["jit"]["tj_test"]
        assert ent["compiles"] == 2
        assert len(ent["signatures"]) == 2
        assert all("float32" in s for s in ent["signatures"])
        assert snap["counters"]["jit.compiles_total"] == 2
        assert snap["timings"]["jit_compile.tj_test"]["count"] == 2

    def test_fresh_instance_recounts(self):
        # new jit object == new compile cache: the per-window cost the
        # tracker exists to surface
        import jax
        import jax.numpy as jnp
        obs.configure(enabled=True)
        a = jnp.ones((4,), jnp.float32)
        for _ in range(3):
            obs.track_jit("tj_window", jax.jit(lambda x: x + 1))(a)
        ent = STATE.registry.snapshot()["jit"]["tj_window"]
        assert ent["compiles"] == 3
        assert list(ent["signatures"].values()) == [3]

    def test_disabled_is_passthrough(self):
        import jax
        import jax.numpy as jnp
        fn = obs.track_jit("tj_off", jax.jit(lambda x: x - 1))
        fn(jnp.ones((4,), jnp.float32))
        assert STATE.registry.snapshot()["jit"] == {}

    def test_warm_cache_is_not_a_compile(self):
        # a jit warmed while tracking was off must not be reported as a
        # compile once tracking turns on (the cache-size check)
        import jax
        import jax.numpy as jnp
        a = jnp.ones((4,), jnp.float32)
        fn = obs.track_jit("tj_warm", jax.jit(lambda x: x * 3))
        fn(a)                       # disabled: compiles, not recorded
        obs.configure(enabled=True)
        fn(a)                       # warm: must record nothing
        assert "tj_warm" not in STATE.registry.snapshot()["jit"]
        fn(jnp.ones((8,), jnp.float32))   # cold shape: a real compile
        ent = STATE.registry.snapshot()["jit"]["tj_warm"]
        assert ent["compiles"] == 1


# ---------------------------------------------------------------------------
# engine integration + callback ordering
# ---------------------------------------------------------------------------

class TestEngineIntegration:
    def test_metrics_enabled_param_collects(self):
        bst = _small_train({"metrics_enabled": True}, rounds=4)
        assert bst.current_iteration() == 4
        snap = obs.snapshot()
        assert snap["timings"]["train.iter"]["count"] == 4
        assert snap["timings"]["engine.iter"]["count"] == 4
        assert any(k.startswith("phase.") for k in snap["timings"])
        assert snap["counters"]["train.init_train"] == 1
        # no jit-compile assertion here: when the full suite runs first,
        # the module-level learner jits may already be cache-warm for
        # these shapes and correctly record zero compiles (the bench
        # subprocess test covers the fresh-process compile path)
        assert validate_metrics.validate(snap) == []

    def test_trace_path_param_writes_file(self, tmp_path):
        path = str(tmp_path / "t.trace.json")
        _small_train({"trace_path": path}, rounds=2)
        doc = json.load(open(path))
        names = {e["name"] for e in doc["traceEvents"]}
        assert "train.iter" in names and "engine_iter" in names

    def test_metrics_path_param_writes_valid_file(self, tmp_path):
        path = str(tmp_path / "m.json")
        _small_train({"metrics_path": path}, rounds=2)
        doc = json.load(open(path))
        # plain schema check: this run's jit caches are warm from the
        # previous test, so zero new compiles is the CORRECT reading
        assert validate_metrics.validate(doc) == []
        assert doc["timings"]["train.iter"]["count"] == 2

    def test_disabled_by_default_and_overhead_free(self):
        _small_train(rounds=2)
        assert not obs.enabled()
        snap = STATE.registry.snapshot()
        assert snap["timings"] == {} and snap["jit"] == {}

    def test_windowed_retrain_accumulates(self):
        # two boosters (two "windows"): counts accumulate, recompiles
        # attributed across both
        _small_train({"metrics_enabled": True}, rounds=2)
        _small_train({"metrics_enabled": True}, rounds=2)
        snap = obs.snapshot()
        assert snap["counters"]["train.init_train"] == 2
        assert snap["timings"]["train.iter"]["count"] == 4

    def test_callbacks_keep_insertion_order(self):
        calls = []

        def make(tag):
            def cb(env):
                calls.append(tag)
            return cb

        a, b, c = make("a"), make("b"), make("c")
        _small_train({}, rounds=1)   # warm (not under test)
        calls.clear()
        rng = np.random.default_rng(1)
        x = rng.standard_normal((100, 3))
        y = (x[:, 0] > 0).astype(np.float64)
        lgb.train({"objective": "binary", "verbosity": -1,
                   "min_data_in_leaf": 5},
                  lgb.Dataset(x, label=y), num_boost_round=2,
                  callbacks=[a, b, c], verbose_eval=False)
        assert calls == ["a", "b", "c"] * 2

    def test_callbacks_deduped(self):
        calls = []

        def cb(env):
            calls.append("x")

        rng = np.random.default_rng(1)
        x = rng.standard_normal((100, 3))
        y = (x[:, 0] > 0).astype(np.float64)
        lgb.train({"objective": "binary", "verbosity": -1,
                   "min_data_in_leaf": 5},
                  lgb.Dataset(x, label=y), num_boost_round=2,
                  callbacks=[cb, cb], verbose_eval=False)
        assert calls == ["x", "x"]   # once per iteration, not twice


# ---------------------------------------------------------------------------
# validate_metrics negative cases
# ---------------------------------------------------------------------------

class TestValidator:
    def _good(self):
        obs.configure(enabled=True)
        obs.observe("train.iter", 0.01)
        STATE.registry.record_compile("grow", "(f32[4])")
        return obs.snapshot()

    def test_good_doc_passes(self):
        assert validate_metrics.validate_training_run(self._good()) == []

    @pytest.mark.parametrize("mutate,frag", [
        (lambda d: d.pop("schema"), "schema"),
        (lambda d: d.update(schema_version=99), "schema_version"),
        (lambda d: d.pop("timings"), "timings"),
        (lambda d: d["timings"]["train.iter"].pop("p95_s"), "p95_s"),
        (lambda d: d["counters"].update(bad=-1), "bad"),
        (lambda d: d["jit"]["grow"].update(compiles=5), "signature"),
        (lambda d: d.pop("device_memory"), "device_memory"),
        (lambda d: d.pop("events"), "events"),
    ])
    def test_bad_docs_fail(self, mutate, frag):
        doc = self._good()
        mutate(doc)
        errs = validate_metrics.validate(doc) \
            or validate_metrics.validate_training_run(doc)
        assert errs and any(frag in e for e in errs), errs


# ---------------------------------------------------------------------------
# end to end: bench.py --metrics/--trace subprocess (the enabled path
# tier-1 exercises, per ISSUE acceptance)
# ---------------------------------------------------------------------------

class TestBenchEndToEnd:
    def test_bench_metrics_and_trace(self, tmp_path):
        m = str(tmp_path / "m.json")
        t = str(tmp_path / "t.trace.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--rows", "4096", "--iters", "2", "--chunk", "0",
             "--num-leaves", "7", "--max-bin", "15", "--eval-rows", "0",
             "--engine", "host", "--suite", "higgs",
             "--metrics", m, "--trace", t],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        # obs digest rides alongside the phase dict in the bench JSON
        assert "obs" in result and "phases_s" in result
        assert result["obs"]["jit_compiles_total"] >= 1
        assert result["obs"]["iter_p95_ms"] is not None

        doc = json.load(open(m))
        assert validate_metrics.validate_training_run(doc) == []
        assert doc["timings"]["train.iter"]["count"] >= 2
        assert any(k.startswith("phase.") for k in doc["timings"])

        # the validator CLI agrees
        proc2 = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "validate_metrics.py"), m],
            capture_output=True, text=True, timeout=60)
        assert proc2.returncode == 0, proc2.stderr

        trace = json.load(open(t))
        assert isinstance(trace["traceEvents"], list)
        assert len(trace["traceEvents"]) > 2
        phs = {e["ph"] for e in trace["traceEvents"]}
        assert "X" in phs   # at least one complete span for the timeline
