"""The async windowed-retrain pipeline (lightgbm_tpu/pipeline/).

Contracts under test (docs/Pipeline.md):

* determinism — with drift-rebinding off and ``window_policy=fresh``,
  the PIPELINED loop's trees are byte-identical to the serial loop's
  (the background prep thread changes wall-clock, never results);
* fault isolation — a prep-thread exception surfaces on the caller's
  thread as :class:`PipelineError` with the completed windows attached,
  and serving keeps answering from the last good model;
* drift-gated rebinding — stationary streams never re-run find-bin, a
  distribution shift does (and the statistic is noise-adjusted, so
  small windows don't read pseudo-drift);
* warm-start policies — ``refit`` keeps the ensemble size and routing
  structure, ``warm`` grows it by ``warm_iterations``, both fall back
  to ``fresh`` when there is no previous model;
* mapper persistence — a saved ``BinMapperCache`` reloads in a fresh
  "process" and bins identically.
"""

import numpy as np
import pytest
import scipy.sparse as sp

from lightgbm_tpu.config import Config
from lightgbm_tpu.pipeline import (BinMapperCache, PipelineError,
                                   PreppedWindow, RetrainPipeline)

PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
          "num_iterations": 8}


def _dense_window(seed, n=3000, nf=8, shift=0.0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nf)) + shift
    y = (x[:, 0] + 0.5 * x[:, 1] > shift).astype(np.float64)
    return x, y


def _dense_prep(seed_base, with_eval=False):
    def prep(w):
        x, y = _dense_window(seed_base + w)
        return PreppedWindow(label=y, dense=x,
                             eval_dense=x if with_eval else None,
                             eval_label=y if with_eval else None)
    return prep


def _model_strings(results):
    return [r.booster.model_to_string() for r in results]


def test_pipelined_byte_identical_to_serial():
    """The determinism contract: rebin off + fresh policy -> the
    pipelined run's per-window models are byte-identical to the serial
    run's (same prep, no thread)."""
    kw = dict(window_policy="fresh", rebin_on_drift=False, serve=False)
    serial = RetrainPipeline(PARAMS, pipelined=False, **kw)
    rs = serial.run(range(3), _dense_prep(40))
    piped = RetrainPipeline(PARAMS, pipelined=True, **kw)
    rp = piped.run(range(3), _dense_prep(40))
    assert _model_strings(rs) == _model_strings(rp)
    assert [r.rebinned for r in rp] == [True, False, False]
    assert all(r.drift is None for r in rp[:1])


def test_prep_fault_surfaces_and_serving_survives():
    """Window 2's prep explodes: PipelineError carries the window index
    and the two completed results; the server still answers from the
    last good model afterwards."""
    base = _dense_prep(60, with_eval=True)

    def prep(w):
        if w == 2:
            raise ValueError("featurization blew up")
        return base(w)

    pipe = RetrainPipeline(PARAMS, window_policy="fresh")
    with pytest.raises(PipelineError) as ei:
        pipe.run(range(4), prep, eval_fn=lambda pred, pw: {})
    err = ei.value
    assert err.window == 2
    assert [r.window for r in err.results] == [0, 1]
    assert isinstance(err.__cause__, ValueError)
    # serving survived: the last good model keeps predicting
    x, y = _dense_window(61)
    pred = pipe.server.predict(x)
    assert np.isfinite(np.asarray(pred)).all()
    ref = err.results[-1].booster.predict(x)
    np.testing.assert_allclose(np.asarray(pred), ref, rtol=1e-4,
                               atol=1e-6)


def test_drift_rebind_on_shift_only():
    """Stationary windows never rebin (noise-adjusted statistic);
    a real distribution shift rebins exactly once and re-stabilizes."""
    def prep(w):
        x, y = _dense_window(80 + w, shift=4.0 if w >= 2 else 0.0)
        return PreppedWindow(label=y, dense=x)

    pipe = RetrainPipeline(PARAMS, window_policy="fresh", serve=False,
                           drift_threshold=0.1)
    res = pipe.run(range(4), prep)
    assert [r.rebinned for r in res] == [True, False, True, False]
    assert res[2].drift > 0.1          # the shift window
    assert res[1].drift < 0.05         # stationary: ~noise only
    assert res[3].drift < 0.05         # re-stabilized on new mappers
    # only the DRIFT-triggered re-run counts (window 0's initial
    # find-bin is not a rebind)
    assert pipe.bins.rebinds == 1


def test_policies_refit_and_warm():
    cfg = dict(rebin_on_drift=False, serve=False)
    refit = RetrainPipeline(PARAMS, window_policy="refit", **cfg)
    rr = refit.run(range(3), _dense_prep(100))
    assert [r.policy for r in rr] == ["fresh", "refit", "refit"]
    assert [r.num_trees for r in rr] == [8, 8, 8]
    # refit keeps routing structure, moves leaf values
    t0 = rr[0].booster.models[2]
    t1 = rr[1].booster.models[2]
    np.testing.assert_array_equal(
        t0.split_feature[:t0.num_leaves - 1],
        t1.split_feature[:t1.num_leaves - 1])
    assert not np.allclose(t0.leaf_value[:t0.num_leaves],
                           t1.leaf_value[:t1.num_leaves])

    warm = RetrainPipeline(PARAMS, window_policy="warm",
                           warm_iterations=4, **cfg)
    rw = warm.run(range(3), _dense_prep(100))
    assert [r.policy for r in rw] == ["fresh", "warm", "warm"]
    assert [r.num_trees for r in rw] == [8, 12, 16]
    # the warm ensemble's prefix is the refit of the previous window
    prefix = rw[1].booster.models[:8]
    np.testing.assert_array_equal(
        rw[0].booster.models[2].split_feature[:14],
        prefix[2].split_feature[:14])


def test_per_window_policy_callable():
    pol = {0: "fresh", 1: "refit", 2: "warm"}
    pipe = RetrainPipeline(PARAMS, window_policy=lambda w: pol[w],
                           warm_iterations=2, rebin_on_drift=False,
                           serve=False)
    res = pipe.run(range(3), _dense_prep(120))
    assert [r.policy for r in res] == ["fresh", "refit", "warm"]
    assert [r.num_trees for r in res] == [8, 8, 10]


def test_csr_prep_and_eval_through_server():
    """CSR-native prep windows (the harness's shape) bin without
    densifying, eval rows flow chunked through the serving path, and
    the quality metric arrives in the result."""
    def prep(w):
        rng = np.random.default_rng(140 + w)
        x = sp.random(2500, 12, density=0.3, random_state=rng,
                      data_rvs=lambda k: rng.exponential(2.0, k)).tocsr()
        y = (np.asarray(x[:, :3].sum(axis=1)).ravel() > 1.5).astype(
            np.float64)
        csr = (x.indptr, x.indices, x.data, x.shape[1])
        return PreppedWindow(label=y, csr=csr, eval_csr=csr,
                             eval_label=y)

    def eval_fn(pred, pw):
        err = float(np.mean((np.asarray(pred) >= 0.5)
                            != (pw.eval_label >= 0.5)))
        return {"err": err}

    pipe = RetrainPipeline(PARAMS, eval_chunk_rows=1024)
    res = pipe.run(range(3), prep, eval_fn=eval_fn)
    assert res[0].eval_metrics is None      # no model to score yet
    assert res[1].eval_metrics["err"] < 0.2
    # swap happened on every window (shape stability depends on the
    # models' depth pads, asserted in the dense test + CI smoke)
    assert res[2].swap_same_shape is not None
    assert res[1].rows == 2500


def test_bin_mapper_cache_save_load_roundtrip(tmp_path):
    cfg = Config({**PARAMS,
                  "monotone_constraints": "1,0,-1,0,0,0,0,0"})
    cache = BinMapperCache(rebin_on_drift=False)
    x, y = _dense_window(160)
    ds0, info0 = cache.dataset_for(cfg, dense=x, label=y)
    assert info0["rebinned"]
    path = str(tmp_path / "bins.pkl")
    cache.save(path)

    x2, y2 = _dense_window(161)
    ds_a, info_a = cache.dataset_for(cfg, dense=x2, label=y2)

    fresh = BinMapperCache.load(path)       # a "restarted process"
    ds_b, info_b = fresh.dataset_for(cfg, dense=x2, label=y2)
    assert not info_a["rebinned"] and not info_b["rebinned"]
    np.testing.assert_array_equal(ds_a.binned, ds_b.binned)
    assert info_b["drift"] == pytest.approx(info_a["drift"], rel=1e-9)
    # constraints/penalties survive the restart (reference-constructed
    # datasets adopt them verbatim)
    np.testing.assert_array_equal(ds_b.monotone_constraints,
                                  ds_a.monotone_constraints)
    assert ds_b.monotone_constraints[0] == 1
    np.testing.assert_array_equal(ds_b.feature_penalty,
                                  ds_a.feature_penalty)


def test_checkpoint_resume_byte_identical(tmp_path):
    """Fault-tolerance contract (docs/Robustness.md): a pipeline killed
    mid-stream by an injected prep fault resumes from its per-window
    checkpoint, skips the completed windows' prep entirely, and — under
    the deterministic config (rebin off, fresh policy) — finishes with
    a final model BYTE-IDENTICAL to an uninterrupted run."""
    from lightgbm_tpu.robust import faults

    kw = dict(window_policy="fresh", rebin_on_drift=False, serve=False)
    ref = RetrainPipeline(PARAMS, **kw)
    ref_final = ref.run(range(4), _dense_prep(200))[-1] \
        .booster.model_to_string()

    ckpt = str(tmp_path / "ckpt")
    faults.configure("pipeline.prep:at=2")
    try:
        pipe = RetrainPipeline(PARAMS, checkpoint_dir=ckpt, **kw)
        with pytest.raises(PipelineError) as ei:
            pipe.run(range(4), _dense_prep(200))
        assert ei.value.window == 2
        assert [r.window for r in ei.value.results] == [0, 1]
    finally:
        faults.clear()

    calls = []
    resumed = RetrainPipeline.resume(ckpt, PARAMS, **kw)
    prep = _dense_prep(200)

    def counting_prep(w):
        calls.append(w)
        return prep(w)

    res = resumed.run(range(4), counting_prep)
    assert [r.window for r in res] == [2, 3]
    assert calls == [2, 3]                  # completed windows skipped
    assert res[-1].booster.model_to_string() == ref_final
    # the resumed run re-committed its own progress
    from lightgbm_tpu.robust.checkpoint import load_pipeline_checkpoint
    assert load_pipeline_checkpoint(ckpt).window == 3


def test_checkpoint_resume_serves_last_good_model(tmp_path):
    """Resume restores the previous model into serving (and the warm
    policies) before any new window trains."""
    ckpt = str(tmp_path / "ckpt")
    kw = dict(window_policy="fresh", rebin_on_drift=False, serve=False)
    pipe = RetrainPipeline(PARAMS, checkpoint_dir=ckpt, **kw)
    first = pipe.run(range(2), _dense_prep(220, with_eval=True))

    resumed = RetrainPipeline.resume(ckpt, PARAMS,
                                     window_policy="fresh",
                                     rebin_on_drift=False)
    # the checkpointed window-1 model came back as _prev...
    assert resumed._prev is not None
    np.testing.assert_allclose(
        resumed._prev.predict(_dense_window(221)[0][:64]),
        first[-1].booster.predict(_dense_window(221)[0][:64]),
        rtol=1e-12)
    res = resumed.run(
        range(4), _dense_prep(220, with_eval=True),
        eval_fn=lambda pred, pw: {"n": len(np.asarray(pred))})
    # ...so window 2 was scored against it BEFORE retraining (the
    # test-then-train order survives the restart)
    assert res[0].window == 2 and res[0].eval_metrics is not None
    # and serving ends on the final window's model
    x, _ = _dense_window(221)
    np.testing.assert_allclose(
        np.asarray(resumed.server.predict(x[:64])),
        np.asarray(res[-1].booster.predict(x[:64])), rtol=1e-4,
        atol=1e-6)


def test_overlap_accounting():
    """Pipelined mode hides prep behind training (overlap ~1 when prep
    is cheap and training long); serial mode reports 0 overlap."""
    serial = RetrainPipeline(PARAMS, pipelined=False, serve=False)
    serial.run(range(3), _dense_prep(180))
    assert serial.overlap_fraction == pytest.approx(0.0)

    piped = RetrainPipeline(PARAMS, pipelined=True, serve=False)
    piped.run(range(3), _dense_prep(180))
    assert piped.overlap_fraction is not None
    assert piped.overlap_fraction > 0.2
