"""construct_from_device_matrix must reproduce host binning exactly.

The device path compares float32 inputs against bin boundaries rounded
down to float32, which is provably equivalent to the host's
``v <= bound64`` for float32 data — these tests pin that bit-for-bit,
including NaN routing, the reference= (CreateValid) path, and training
equivalence end to end.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.utils.log import LightGBMError


def _data(rows=5000, cols=12, seed=0, nan_frac=0.05):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows, cols)).astype(np.float32)
    if nan_frac:
        x[rng.random(x.shape) < nan_frac] = np.nan
    return x


@pytest.mark.parametrize("max_bin", [63, 255])
def test_device_binning_matches_host(max_bin):
    x = _data()
    cfg = Config({"objective": "binary", "max_bin": max_bin,
                  "verbosity": -1})
    host = BinnedDataset.construct_from_matrix(x, cfg)
    dev = BinnedDataset.construct_from_device_matrix(jnp.asarray(x), cfg)
    assert dev.device_binned
    np.testing.assert_array_equal(np.asarray(dev.binned), host.binned)
    assert [m.num_bin for m in dev.bin_mappers] == \
        [m.num_bin for m in host.bin_mappers]


def test_device_binning_reference_path():
    x = _data(seed=1)
    xq = _data(rows=700, seed=2)
    cfg = Config({"objective": "binary", "verbosity": -1})
    train_h = BinnedDataset.construct_from_matrix(x, cfg)
    valid_h = BinnedDataset.construct_from_matrix(xq, cfg,
                                                  reference=train_h)
    valid_d = BinnedDataset.construct_from_device_matrix(
        jnp.asarray(xq), cfg, reference=train_h)
    np.testing.assert_array_equal(np.asarray(valid_d.binned),
                                  valid_h.binned)


def test_device_binning_training_equivalence():
    # same data binned on host vs device must train the same model
    from lightgbm_tpu.boosting import create_boosting
    x = _data(rows=3000, cols=8, seed=3, nan_frac=0.0)
    rng = np.random.default_rng(3)
    y = (x[:, 0] + np.abs(x[:, 1])
         + 0.1 * rng.standard_normal(3000) > 0.4).astype(np.float32)
    models = []
    for device in (False, True):
        cfg = Config({"objective": "binary", "num_leaves": 15,
                      "verbosity": -1, "device_growth": "on",
                      "min_data_in_leaf": 5})
        if device:
            ds = BinnedDataset.construct_from_device_matrix(
                jnp.asarray(x), cfg)
        else:
            ds = BinnedDataset.construct_from_matrix(x, cfg)
        ds.metadata.set_label(y)
        bst = create_boosting(cfg)
        bst.init_train(ds)
        bst.train_chunked(8, chunk=4)
        models.append(bst.model_to_string())
    assert models[0] == models[1]


def test_device_binning_efb_bundles_match_host():
    # disjoint-support sparse columns bundle under EFB, exercising the
    # multi-feature group branch (bin offsets, default-bin shift,
    # last-writer order) that dense gaussian data never hits
    rng = np.random.default_rng(9)
    rows, nf = 4000, 6
    x = np.zeros((rows, nf), np.float32)
    owner = np.arange(rows) % nf
    for f in range(nf):
        sel = owner == f
        x[sel, f] = rng.random(int(sel.sum())).astype(np.float32) + 0.5
    # small max_bin keeps all 6 features under the 256-bin group cap
    cfg = Config({"objective": "binary", "verbosity": -1,
                  "enable_bundle": True, "max_bin": 16})
    host = BinnedDataset.construct_from_matrix(x, cfg)
    assert host.num_groups < host.num_features, \
        "fixture failed to trigger EFB bundling"
    dev = BinnedDataset.construct_from_device_matrix(jnp.asarray(x), cfg)
    assert dev.num_groups == host.num_groups
    np.testing.assert_array_equal(np.asarray(dev.binned), host.binned)


def test_device_binning_rejects_categorical():
    x = _data(rows=500, cols=4, nan_frac=0.0)
    cfg = Config({"objective": "binary", "verbosity": -1,
                  "categorical_feature": "1"})
    host = BinnedDataset.construct_from_matrix(
        np.abs(x).astype(np.float32), cfg, categorical=[1])
    assert host is not None   # host path supports it
    with pytest.raises(LightGBMError):
        BinnedDataset.construct_from_device_matrix(
            jnp.abs(jnp.asarray(x)), cfg, reference=host)
