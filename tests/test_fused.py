"""Fused multi-iteration device training (GBDT.train_chunked).

The fused path runs K whole boosting iterations per device dispatch
(gradients computed inside the scan, ops/grow.py fused_train); these
tests pin that it trains THE SAME model as the per-iteration device
path, falls back when ineligible, and stops on stump stalls.
"""

import numpy as np
import pytest
from conftest import assert_models_bit_identical, train_device_booster

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset


def _binary_data(rows=3000, cols=10, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows, cols)).astype(np.float32)
    logit = x[:, 0] + np.abs(x[:, 1]) - 0.5 * x[:, 2]
    y = (rng.random(rows) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return x, y


def _rank_data(rows=1200, cols=8, seed=5):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows, cols)).astype(np.float32)
    sizes = []
    left = rows
    while left > 0:
        s = min(int(rng.integers(5, 40)), left)
        sizes.append(s)
        left -= s
    util = x[:, 0] + 0.5 * np.abs(x[:, 1]) + rng.standard_normal(rows)
    y = np.digitize(util, np.quantile(util, [0.6, 0.85, 0.96]))
    return x, y.astype(np.float32), np.asarray(sizes, np.int64)


def _train(params, x, y, n_iters, chunk=0, query=None):
    return train_device_booster(
        {"verbosity": -1, "device_growth": "on", "num_leaves": 15,
         "min_data_in_leaf": 5, **params},
        x, y, n_iters, chunk=chunk, query=query)


def _assert_same_models(a, b):
    assert len(a.models) == len(b.models)
    for ta, tb in zip(a.models, b.models):
        assert ta.num_leaves == tb.num_leaves
        np.testing.assert_array_equal(
            ta.split_feature[:ta.num_leaves - 1],
            tb.split_feature[:tb.num_leaves - 1])
        np.testing.assert_allclose(
            ta.leaf_value[:ta.num_leaves],
            tb.leaf_value[:tb.num_leaves], rtol=2e-4, atol=1e-6)


_assert_bit_identical = assert_models_bit_identical


def test_binary_chunked_matches_per_iter():
    x, y = _binary_data()
    a = _train({"objective": "binary"}, x, y, 12)
    b = _train({"objective": "binary"}, x, y, 12, chunk=4)
    _assert_same_models(a, b)
    np.testing.assert_allclose(np.asarray(a.train_score),
                               np.asarray(b.train_score),
                               rtol=2e-4, atol=1e-5)


# slow: trains the same model three ways (chunked + remainder +
# reference) => an extra fused-scan compile tier-1 can't spare
@pytest.mark.slow
def test_binary_chunk_remainder_uses_per_iter_path():
    # 10 = 2 chunks of 4 + remainder 2 via train_one_iter
    x, y = _binary_data(rows=1500)
    a = _train({"objective": "binary"}, x, y, 10)
    b = _train({"objective": "binary"}, x, y, 10, chunk=4)
    _assert_same_models(a, b)


def test_regression_chunked_matches_per_iter():
    rng = np.random.default_rng(11)
    x = rng.standard_normal((2000, 8)).astype(np.float32)
    y = (x[:, 0] * 2 + np.abs(x[:, 1])
         + 0.1 * rng.standard_normal(2000)).astype(np.float32)
    a = _train({"objective": "regression"}, x, y, 8)
    b = _train({"objective": "regression"}, x, y, 8, chunk=4)
    _assert_same_models(a, b)


# slow: the lambdarank device gradient compiles a large sorted-pair
# program inside the fused scan
@pytest.mark.slow
def test_lambdarank_chunked_matches_per_iter():
    x, y, q = _rank_data()
    a = _train({"objective": "lambdarank"}, x, y, 8, query=q)
    b = _train({"objective": "lambdarank"}, x, y, 8, chunk=4, query=q)
    _assert_same_models(a, b)


# the fork harness's exact training knobs (src/test.cpp:66-87) — the
# workload this repo exists for; round-5 VERDICT found it could never
# fuse before the draws moved on device
FORK_HARNESS_PARAMS = {"objective": "binary", "feature_fraction": 0.8,
                       "bagging_freq": 5, "bagging_fraction": 0.8}


def test_fused_eligible_under_fork_harness_config():
    x, y = _binary_data(rows=500)
    bst = _train(FORK_HARNESS_PARAMS, x, y, 0)
    assert bst.fused_eligible()


def test_bagging_chunked_bit_identical():
    # bagging_freq > 1: the scan must REUSE the carried mask between
    # redraw boundaries and re-draw exactly at them
    x, y = _binary_data()
    params = {"objective": "binary", "bagging_fraction": 0.7,
              "bagging_freq": 2, "bagging_seed": 11}
    a = _train(params, x, y, 12)
    b = _train(params, x, y, 12, chunk=4)
    _assert_bit_identical(a, b)


def test_feature_fraction_chunked_bit_identical():
    x, y = _binary_data()
    params = {"objective": "binary", "feature_fraction": 0.6,
              "feature_fraction_seed": 7}
    a = _train(params, x, y, 12)
    b = _train(params, x, y, 12, chunk=4)
    _assert_bit_identical(a, b)


# slow: the heaviest parity case (bagging + feature_fraction, 14
# iterations, chunk remainder) — scripts/check.sh full mode runs it
@pytest.mark.slow
def test_fork_harness_config_chunked_bit_identical():
    # bagging + feature_fraction together, chunk boundaries landing both
    # on and off the bagging_freq=5 redraw cadence, plus a per-iteration
    # remainder (14 = 3 chunks of 4 + 2) — the strongest parity claim
    x, y = _binary_data()
    a = _train(FORK_HARNESS_PARAMS, x, y, 14)
    b = _train(FORK_HARNESS_PARAMS, x, y, 14, chunk=4)
    _assert_bit_identical(a, b)


def test_ineligible_config_falls_back():
    # GOSS overrides the gradient/bagging hooks, so the fused path must
    # refuse and train_chunked must still train correctly per-iteration
    x, y = _binary_data(rows=1500)
    params = {"objective": "binary", "boosting": "goss",
              "learning_rate": 0.3}
    a = _train(params, x, y, 6)
    b = _train(params, x, y, 6, chunk=3)
    _assert_same_models(a, b)
    cfg_bst = _train(params, x, y, 0)
    assert cfg_bst._fused_grad_fn() is None
    assert not cfg_bst.fused_eligible()


def test_chunked_stump_stall_stops():
    # constant labels: zero gradients after boost_from_average -> every
    # tree is a stump -> the lagged chunk check must stop training and
    # trim to the single bias-carrying stump (host-path semantics)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((500, 5)).astype(np.float32)
    y = np.full(500, 3.25, np.float32)
    bst = _train({"objective": "regression"}, x, y, 12, chunk=4)
    assert len(bst.models) == 1
    assert bst.models[0].num_leaves == 1
    pred = bst.predict(x[:8])
    np.testing.assert_allclose(pred, 3.25, rtol=1e-6)


def test_fused_grad_objectives_exposed():
    # the fused path exists iff device_grad() returns a (fn, args) pair
    # after init — pin that for the three covered objectives
    from lightgbm_tpu.objectives import create_objective
    x, y = _binary_data(rows=200)
    cfg = Config({"objective": "binary"})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    for obj_name, query in (("binary", None), ("regression", None),
                            ("lambdarank", np.asarray([120, 80],
                                                      np.int64))):
        if query is not None:
            ds.metadata.set_query(query)
        obj = create_objective(Config({"objective": obj_name}))
        obj.init(ds.metadata, ds.num_data)
        fg = obj.device_grad()
        assert fg is not None and callable(fg[0]), obj_name
