"""Fused find-best-in-wave (``find_best_fusion``, ops/grow.py).

The fused layout runs each growth wave as ONE traced program — the
per-feature gain scan consumes the wave histograms where the histogram
contraction produced them — instead of the legacy two-pass layout's
second find-best dispatch over a concatenated (2W, S, 3) stack.  These
tests pin the contract from ISSUE 18:

* fused vs two-pass trains BYTE-identical models in every guaranteed
  regime — f32, int8 einsum, int8 Pallas (interpret on CPU), the
  striped >= 2^24-row count layout (forced small), and composed with
  the fused multi-iteration scan;
* ``find_best_fusion`` joins ``programs_signature`` (two layouts must
  never share a compiled program);
* a warm same-shape retrain window under the fused layout traces
  NOTHING new;
* (slow) 1-vs-4 forced-host-mesh shard identity under quant8, both
  layouts (tests/_shard_worker.py ``fused_find`` scenario).
"""

import numpy as np
import pytest
from conftest import assert_models_bit_identical, train_device_booster

from lightgbm_tpu.config import Config


def _data(rows=3000, cols=10, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows, cols)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    return x, y


def _train(params, x, y, n_iters=5, chunk=0):
    return train_device_booster(
        {"objective": "binary", "verbosity": -1, "device_growth": "on",
         "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
         "seed": 7, **params},
        x, y, n_iters, chunk=chunk)


def _pair(extra, x, y, **kw):
    a = _train({**extra, "find_best_fusion": "fused"}, x, y, **kw)
    b = _train({**extra, "find_best_fusion": "two_pass"}, x, y, **kw)
    assert a._grower.fused_find and not b._grower.fused_find
    return a, b


def test_fused_find_f32_byte_identical():
    x, y = _data()
    a, b = _pair({}, x, y)
    assert_models_bit_identical(a, b)


def test_fused_find_int8_einsum_byte_identical():
    # the exact-arithmetic regime: the int32 scan sees the identical
    # integer histograms either way, so identity is law, not luck
    x, y = _data(seed=5)
    a, b = _pair({"grad_quant_bits": 8}, x, y)
    assert a._grower.int_scan and b._grower.int_scan
    assert_models_bit_identical(a, b)


def test_fused_find_int8_pallas_interpret_byte_identical():
    x, y = _data(seed=6)
    a, b = _pair({"grad_quant_bits": 8, "hist_kernel": "interpret"},
                 x, y)
    assert a._grower.hist_kernel_tag == "pallas_int8"
    assert b._grower.hist_kernel_tag == "pallas_int8"
    assert_models_bit_identical(a, b)


def test_fused_find_striped_byte_identical():
    # the striped six-column count layout (>= 2^24 rows in production,
    # forced small here) scans per lane exactly like the plain layout
    import lightgbm_tpu.ops.grow as growmod

    rng = np.random.default_rng(8)
    n = 6000
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = (x[:, 0] + 2 * (x[:, 1] > 0.3) > 0.5).astype(np.float32)
    old = growmod.COUNT_SPLIT_ROWS
    try:
        growmod.COUNT_SPLIT_ROWS = 5000
        a, b = _pair({"grad_quant_bits": 8}, x, y, n_iters=4)
        assert a._grower.hist_cols == b._grower.hist_cols == 6
        assert_models_bit_identical(a, b)
    finally:
        growmod.COUNT_SPLIT_ROWS = old


def test_fused_find_composes_with_fused_scan():
    # fused find-best inside fused multi-iteration training must match
    # the per-iteration two-pass run: both tentpoles at once
    x, y = _data(seed=9)
    params = {"grad_quant_bits": 8, "feature_fraction": 0.8,
              "bagging_freq": 5, "bagging_fraction": 0.8}
    a = _train({**params, "find_best_fusion": "fused"}, x, y,
               n_iters=8, chunk=4)
    b = _train({**params, "find_best_fusion": "two_pass"}, x, y,
               n_iters=8)
    assert_models_bit_identical(a, b)


def test_programs_signature_includes_find_best_fusion():
    from lightgbm_tpu.ops.grow import programs_signature

    base = {"objective": "binary", "device_growth": "on",
            "num_leaves": 15}
    sigs = {
        mode: programs_signature(
            8192, 10, 64, 10, False,
            Config({**base, "find_best_fusion": mode}))
        for mode in ("auto", "fused", "two_pass")
    }
    # every mode value must key its own trace family — auto included,
    # because auto may RESOLVE differently than an explicit setting
    assert len(set(sigs.values())) == 3


def test_resolve_find_fusion_modes():
    from lightgbm_tpu.ops import stage_plan as sp
    from lightgbm_tpu.ops.grow import (programs_signature,
                                       resolve_find_fusion)

    base = {"objective": "binary", "device_growth": "on"}
    assert resolve_find_fusion(
        Config({**base, "find_best_fusion": "fused"})) == "fused"
    assert resolve_find_fusion(
        Config({**base, "find_best_fusion": "two_pass"})) == "two_pass"
    cfg = Config({**base, "find_best_fusion": "auto"})
    assert resolve_find_fusion(cfg) == "fused"
    # auto adopts a cached wave_plan=profiled verdict for the signature
    sig = programs_signature(8192, 10, 64, 10, False, cfg)
    try:
        sp.cache_fusion(sig, "two_pass", persist=False)
        assert resolve_find_fusion(cfg, sig) == "two_pass"
    finally:
        sp._FUSION_CACHE.pop(sig, None)
    with pytest.raises(ValueError):
        sp.cache_fusion(sig, "bogus", persist=False)
    # the config layer rejects unknown modes outright (wave_plan idiom)
    with pytest.raises(ValueError, match="find_best_fusion"):
        Config({**base, "find_best_fusion": "bogus"})


def test_fused_find_warm_window_zero_new_traces():
    from lightgbm_tpu import obs

    was_enabled = obs.enabled()
    try:
        obs.configure(enabled=True)
        x, y = _data(seed=21)
        _train({"find_best_fusion": "fused"}, x, y)
        before = {k: v["compiles"]
                  for k, v in obs.registry().snapshot()["jit"].items()}
        # a NEW same-shape dataset through a FRESH booster must land in
        # the already-traced fused programs
        x2, y2 = _data(seed=22)
        _train({"find_best_fusion": "fused"}, x2, y2)
        after = {k: v["compiles"]
                 for k, v in obs.registry().snapshot()["jit"].items()}
        assert sum(after.values()) == sum(before.values()), (
            {k: after[k] - before.get(k, 0)
             for k in after if after[k] != before.get(k, 0)})
    finally:
        obs.configure(enabled=was_enabled)


def test_fused_find_dispatch_counters():
    from lightgbm_tpu import obs

    was_enabled = obs.enabled()
    try:
        obs.configure(enabled=True)
        x, y = _data(seed=30)

        def deltas(extra):
            before = obs.registry().snapshot()["counters"]
            _train(extra, x, y)
            now = obs.registry().snapshot()["counters"]
            hist = sum(now.get(k, 0) - before.get(k, 0)
                       for k in now if k.startswith("grow.hist."))
            fused = sum(now.get(k, 0) - before.get(k, 0)
                        for k in now
                        if k.startswith("grow.fused_find."))
            gauge = obs.registry().snapshot()["gauges"].get(
                "grow.wave_dispatch_factor")
            return hist, fused, gauge

        hist, fused, gauge = deltas({"find_best_fusion": "fused"})
        assert hist > 0 and fused == hist and gauge == 1
        hist, fused, gauge = deltas({"find_best_fusion": "two_pass"})
        assert hist > 0 and fused == 0 and gauge == 2
    finally:
        obs.configure(enabled=was_enabled)


def test_stage_plan_fused_wave_accounting():
    """A fused hist+find dispatch counts as ONE wave in the simulator
    (the PR-16 counts-as-waves bug class): layout changes the dispatch
    factor, never the wave count."""
    from lightgbm_tpu.ops import stage_plan as sp

    plan = sp.legacy_stage_plan(31, 30, 3)
    cost_fused, waves_fused = sp.plan_cost_fn(
        plan, 31, sp.wave_cost_fn(3, 1.0, 0.01))
    cost_two, waves_two = sp.plan_cost_fn(
        plan, 31, sp.wave_cost_fn(3, 1.0, 0.01,
                                  find_ms={4: 0.5, 30: 0.5},
                                  fusion="two_pass"))
    assert waves_fused == waves_two
    assert cost_two > cost_fused            # the second dispatch costs
    assert sp.plan_dispatches(plan, 31, fused=True) == waves_fused
    assert sp.plan_dispatches(plan, 31, fused=False) == 2 * waves_fused


def test_derive_stage_plan_frontier_packing_knob():
    from lightgbm_tpu.ops import stage_plan as sp

    # flat measured costs (fixed cost dominates): packing merges the
    # under-full narrow waves into fewer, wider stages
    meas = {4: 1.0, 8: 1.0, 16: 1.0, 30: 1.0}
    packed = sp.derive_stage_plan(31, 30, 3, 1.0, 1e-6,
                                  measured_ms=meas)
    full = sp.derive_stage_plan(31, 30, 3, 1.0, 1e-6,
                                measured_ms=meas,
                                frontier_packing=False)
    assert len(packed) < len(full)
    # the unpacked ladder is strictly width-matched: every rung whose
    # stage cap (2w) fits under the leaf budget is present
    assert [w for w, _ in full] == \
        [w for w in sp._ladder(30) if 2 * w < 31] + [30]


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_fused_find_shard_1v4_byte_identity():
    # quant8 on the forced 4-device host mesh: both layouts must match
    # their single-device runs AND each other (ops/shard.py contract)
    from test_shard import _run_worker

    out = _run_worker("fused_find", timeout=580)
    assert out["fused_1v4_identical"] is True
    assert out["two_pass_1v4_identical"] is True
    assert out["fused_eq_two_pass"] is True
