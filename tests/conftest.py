"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The reference has no mockable network backend (SURVEY.md §4); here every
distributed mode is exercised deterministically in-process by forcing the CPU
platform with 8 virtual devices.

NOTE: a sitecustomize may import jax before this file runs (and the ambient
env may pin JAX_PLATFORMS to a remote TPU tunnel with ~170ms roundtrips —
unusable for a test loop), so env vars alone are NOT enough; the platform
must be overridden through jax.config, which works until the first backend
initialisation.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# device-grower histogram chunk: the wave einsum runs over n_pad =
# ceil(rows, CHUNK) rows, so the production default of 32768 makes every
# small-dataset CPU test pay 32768-row matmuls regardless of its actual
# size — 8192 cuts that ~4x.  Trees are padding-invariant (padded rows
# carry zero weight); only float reduction order shifts, which the
# tolerance-based tests already absorb.
os.environ.setdefault("LGBM_TPU_CHUNK", "8192")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")   # effective even post-import
assert jax.default_backend() == "cpu", "tests must run on the CPU mesh"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

# persistent compilation cache: the padded-bucket shapes recur across tests,
# so reruns skip nearly all XLA compiles (routed through the library's
# own activation path so tests exercise what production uses; tests that
# need their OWN cache dir re-call compile_cache.configure)
from lightgbm_tpu import compile_cache  # noqa: E402

compile_cache.configure(os.environ.get(
    compile_cache.ENV_VAR, os.path.expanduser("~/.cache/lgbm_tpu_xla")))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_EXAMPLES = "/root/reference/examples"


def pytest_configure(config):
    # @pytest.mark.timeout(N) comes from the pytest-timeout plugin (dev
    # extras).  When the plugin is absent the mark must still be KNOWN
    # (no unknown-mark warning) and ENFORCED — the SIGALRM fixture below
    # supplies the enforcement, so the 420 s multiprocess guard exists
    # on bare tier-1 environments too.
    if not config.pluginmanager.hasplugin("timeout"):
        config.addinivalue_line(
            "markers",
            "timeout(seconds): fail the test if it runs longer than "
            "`seconds` (SIGALRM fallback when pytest-timeout is not "
            "installed)")


@pytest.fixture(autouse=True)
def _timeout_guard(request):
    """SIGALRM-based enforcement of @pytest.mark.timeout when the
    pytest-timeout plugin is unavailable (main-thread, POSIX only —
    exactly the tier-1 environment)."""
    marker = request.node.get_closest_marker("timeout")
    if (marker is None
            or request.config.pluginmanager.hasplugin("timeout")):
        yield
        return
    import signal
    import threading
    seconds = int(marker.args[0]) if marker.args else 0
    if seconds <= 0 or threading.current_thread() is not threading.main_thread():
        yield
        return

    def _raise(signum, frame):
        pytest.fail(f"test exceeded the {seconds}s timeout mark",
                    pytrace=False)

    old = signal.signal(signal.SIGALRM, _raise)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def train_device_booster(params, x, y, n_iters, chunk=0, query=None):
    """Construct + train a device-growth booster (shared by the fused
    and quantized parity suites; base params come from the caller)."""
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    cfg = Config(dict(params))
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    if query is not None:
        ds.metadata.set_query(query)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    if chunk:
        bst.train_chunked(n_iters, chunk=chunk)
    else:
        for _ in range(n_iters):
            if bst.train_one_iter():
                break
    bst._flush_pending()
    return bst


def assert_models_bit_identical(a, b):
    """Trees, thresholds, leaf values AND final training scores must be
    byte-equal: the fused scan re-draws bagging/feature_fraction masks
    (and int8 quantization noise) on device with the per-iteration
    path's exact seeding, so there is no tolerance to hide behind."""
    assert len(a.models) == len(b.models)
    for i, (ta, tb) in enumerate(zip(a.models, b.models)):
        assert ta.num_leaves == tb.num_leaves, f"tree {i}"
        nl = ta.num_leaves
        np.testing.assert_array_equal(ta.split_feature[:nl - 1],
                                      tb.split_feature[:nl - 1])
        np.testing.assert_array_equal(ta.threshold[:nl - 1],
                                      tb.threshold[:nl - 1])
        np.testing.assert_array_equal(ta.leaf_value[:nl],
                                      tb.leaf_value[:nl])
    np.testing.assert_array_equal(np.asarray(a.train_score),
                                  np.asarray(b.train_score))


def load_svmlight(path, n_features=None):
    """Tiny LibSVM reader for the lambdarank fixtures."""
    labels, rows, cols, vals = [], [], [], []
    with open(path) as fh:
        for i, line in enumerate(fh):
            parts = line.strip().split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                c, v = tok.split(":")
                rows.append(i)
                cols.append(int(c))
                vals.append(float(v))
    n = len(labels)
    nf = (max(cols) + 1) if n_features is None else n_features
    x = np.zeros((n, nf), np.float64)
    x[rows, cols] = vals
    return x, np.asarray(labels, np.float64)


@pytest.fixture(scope="session")
def regression_data():
    d = np.loadtxt(f"{REFERENCE_EXAMPLES}/regression/regression.train")
    dt = np.loadtxt(f"{REFERENCE_EXAMPLES}/regression/regression.test")
    return d[:, 1:], d[:, 0], dt[:, 1:], dt[:, 0]


@pytest.fixture(scope="session")
def binary_data():
    d = np.loadtxt(f"{REFERENCE_EXAMPLES}/binary_classification/binary.train")
    dt = np.loadtxt(f"{REFERENCE_EXAMPLES}/binary_classification/binary.test")
    return d[:, 1:], d[:, 0], dt[:, 1:], dt[:, 0]


@pytest.fixture(scope="session")
def rank_data():
    base = f"{REFERENCE_EXAMPLES}/lambdarank"
    x, y = load_svmlight(f"{base}/rank.train")
    xt, yt = load_svmlight(f"{base}/rank.test", n_features=x.shape[1])
    q = np.loadtxt(f"{base}/rank.train.query").astype(np.int64)
    qt = np.loadtxt(f"{base}/rank.test.query").astype(np.int64)
    return x, y, q, xt, yt, qt
