"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The reference has no mockable network backend (SURVEY.md §4); here every
distributed mode is exercised deterministically in-process by forcing the CPU
platform with 8 virtual devices.

NOTE: a sitecustomize may import jax before this file runs (and the ambient
env may pin JAX_PLATFORMS to a remote TPU tunnel with ~170ms roundtrips —
unusable for a test loop), so env vars alone are NOT enough; the platform
must be overridden through jax.config, which works until the first backend
initialisation.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")   # effective even post-import
assert jax.default_backend() == "cpu", "tests must run on the CPU mesh"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

# persistent compilation cache: the padded-bucket shapes recur across tests,
# so reruns skip nearly all XLA compiles
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/lgbm_tpu_xla"))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_EXAMPLES = "/root/reference/examples"


def load_svmlight(path, n_features=None):
    """Tiny LibSVM reader for the lambdarank fixtures."""
    labels, rows, cols, vals = [], [], [], []
    with open(path) as fh:
        for i, line in enumerate(fh):
            parts = line.strip().split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                c, v = tok.split(":")
                rows.append(i)
                cols.append(int(c))
                vals.append(float(v))
    n = len(labels)
    nf = (max(cols) + 1) if n_features is None else n_features
    x = np.zeros((n, nf), np.float64)
    x[rows, cols] = vals
    return x, np.asarray(labels, np.float64)


@pytest.fixture(scope="session")
def regression_data():
    d = np.loadtxt(f"{REFERENCE_EXAMPLES}/regression/regression.train")
    dt = np.loadtxt(f"{REFERENCE_EXAMPLES}/regression/regression.test")
    return d[:, 1:], d[:, 0], dt[:, 1:], dt[:, 0]


@pytest.fixture(scope="session")
def binary_data():
    d = np.loadtxt(f"{REFERENCE_EXAMPLES}/binary_classification/binary.train")
    dt = np.loadtxt(f"{REFERENCE_EXAMPLES}/binary_classification/binary.test")
    return d[:, 1:], d[:, 0], dt[:, 1:], dt[:, 0]


@pytest.fixture(scope="session")
def rank_data():
    base = f"{REFERENCE_EXAMPLES}/lambdarank"
    x, y = load_svmlight(f"{base}/rank.train")
    xt, yt = load_svmlight(f"{base}/rank.test", n_features=x.shape[1])
    q = np.loadtxt(f"{base}/rank.train.query").astype(np.int64)
    qt = np.loadtxt(f"{base}/rank.test.query").astype(np.int64)
    return x, y, q, xt, yt, qt
