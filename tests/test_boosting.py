"""Boosting-layer end-to-end tests against the reference example fixtures
(modelled on the reference tests/python_package_test/test_engine.py)."""

import numpy as np

from lightgbm_tpu.config import Config
from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.data.dataset import BinnedDataset


def _train(params, x, y, rounds, weights=None, group=None,
           valid=None, categorical=()):
    cfg = Config(params)
    ds = BinnedDataset.construct_from_matrix(x, cfg, categorical)
    ds.metadata.set_label(y)
    if weights is not None:
        ds.metadata.set_weights(weights)
    if group is not None:
        ds.metadata.set_query(group)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    if valid is not None:
        vx, vy = valid
        vds = BinnedDataset.construct_from_matrix(vx, cfg, categorical,
                                                  reference=ds)
        vds.metadata = __import__(
            "lightgbm_tpu.data.dataset", fromlist=["Metadata"]
        ).Metadata(len(vy))
        vds.metadata.set_label(vy)
        bst.add_valid(vds, "valid_0")
    for _ in range(rounds):
        if bst.train_one_iter():
            break
    return bst


def test_binary():
    # mirrors reference test_engine.py:28-48 (breast_cancer, logloss < 0.15)
    from sklearn.datasets import load_breast_cancer
    from sklearn.model_selection import train_test_split
    x, y = load_breast_cancer(return_X_y=True)
    x, xt, y, yt = train_test_split(x, y, test_size=0.1, random_state=42)
    bst = _train({"objective": "binary", "metric": "binary_logloss",
                  "num_leaves": 31, "learning_rate": 0.1,
                  "min_data_in_bin": 1}, x, y, 50, valid=(xt, yt))
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    assert res["valid_0:binary_logloss"] < 0.15
    pred = bst.predict(xt)
    assert ((pred > 0.5) == (yt > 0)).mean() > 0.95


def test_binary_fixture_auc(binary_data):
    # the reference examples/binary_classification run: AUC ~0.78 @ 100
    x, y, xt, yt = binary_data
    bst = _train({"objective": "binary", "metric": "auc",
                  "num_leaves": 31, "learning_rate": 0.1}, x, y, 60,
                 valid=(xt, yt))
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    assert res["valid_0:auc"] > 0.76


def test_regression(regression_data):
    # sklearn HistGBM reaches valid mse 0.174 at the same settings
    x, y, xt, yt = regression_data
    bst = _train({"objective": "regression", "metric": "l2",
                  "num_leaves": 31, "learning_rate": 0.05}, x, y, 100,
                 valid=(xt, yt))
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    assert res["valid_0:l2"] < 0.2


def test_regression_l1_and_huber(regression_data):
    x, y, xt, yt = regression_data
    for obj, metric in [("regression_l1", "l1"), ("huber", "huber"),
                        ("fair", "fair"), ("quantile", "quantile"),
                        ("mape", "mape")]:
        bst = _train({"objective": obj, "metric": metric, "num_leaves": 31,
                      "learning_rate": 0.1}, x, y, 30, valid=(xt, yt))
        res = bst.eval_valid()
        assert len(res) >= 1 and np.isfinite(res[0][2]), (obj, res)


def test_multiclass():
    rng = np.random.RandomState(5)
    n = 3000
    x = rng.randn(n, 6)
    y = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0.5).astype(int)
    bst = _train({"objective": "multiclass", "num_class": 3,
                  "metric": "multi_logloss", "num_leaves": 15,
                  "learning_rate": 0.1}, x, y, 30, valid=(x, y))
    res = dict((f"{d}:{n2}", v) for d, n2, v, _ in bst.eval_valid())
    assert res["valid_0:multi_logloss"] < 0.35
    pred = bst.predict(x)
    assert pred.shape == (n, 3)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    assert (pred.argmax(axis=1) == y).mean() > 0.9


def test_poisson_gamma_tweedie():
    rng = np.random.RandomState(9)
    n = 2000
    x = rng.rand(n, 4)
    mu = np.exp(0.5 * x[:, 0] + x[:, 1])
    for obj, gen in [("poisson", rng.poisson(mu) * 1.0),
                     ("gamma", rng.gamma(2.0, mu / 2.0) + 0.01),
                     ("tweedie", mu)]:
        bst = _train({"objective": obj, "metric": obj, "num_leaves": 15,
                      "learning_rate": 0.05, "min_data_in_leaf": 20},
                     x, gen, 40)
        pred = bst.predict(x)
        assert (pred > 0).all(), obj
        corr = np.corrcoef(pred, mu)[0, 1]
        assert corr > 0.5, (obj, corr)


def test_lambdarank(rank_data):
    x, y, q, xt, yt, qt = rank_data
    bst = _train({"objective": "lambdarank", "metric": "ndcg",
                  "num_leaves": 31, "learning_rate": 0.1,
                  "eval_at": [1, 3, 5], "min_data_in_leaf": 1,
                  "min_sum_hessian_in_leaf": 0}, x, y, 50,
                 group=q, valid=None)
    res = dict((n, v) for _, n, v, _ in bst.eval_train())
    # reference test_sklearn.py:59 asserts ndcg floor ~0.57 at 50 rounds
    assert res["ndcg@1"] > 0.55, res
    assert res["ndcg@3"] > 0.55, res


def test_goss_and_dart(regression_data):
    x, y, xt, yt = regression_data
    for boosting in ("goss", "dart"):
        bst = _train({"objective": "regression", "metric": "l2",
                      "boosting": boosting, "num_leaves": 31,
                      "learning_rate": 0.1}, x, y, 30, valid=(xt, yt))
        res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
        assert res["valid_0:l2"] < 1.0, (boosting, res)


def test_rf():
    # mirrors reference test_engine.py:50-73 (breast_cancer, rf,
    # binary_logloss < 0.25, predict == eval score)
    from sklearn.datasets import load_breast_cancer
    from sklearn.model_selection import train_test_split
    x, y = load_breast_cancer(return_X_y=True)
    x, xt, y, yt = train_test_split(x, y, test_size=0.1, random_state=42)
    bst = _train({"objective": "binary", "boosting": "rf",
                  "metric": "binary_logloss", "num_leaves": 50,
                  "bagging_freq": 1, "bagging_fraction": 0.5,
                  "feature_fraction": 0.5, "min_data_in_bin": 1},
                 x, y, 50, valid=(xt, yt))
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    assert res["valid_0:binary_logloss"] < 0.25
    # predict must match the eval-time averaged probabilities
    pred = bst.predict(xt)
    eps = 1e-15
    ll = -np.mean(yt * np.log(np.clip(pred, eps, 1))
                  + (1 - yt) * np.log(np.clip(1 - pred, eps, 1)))
    assert abs(ll - res["valid_0:binary_logloss"]) < 1e-5


def test_bagging_weights(regression_data):
    x, y, xt, yt = regression_data
    w = np.abs(np.random.RandomState(0).randn(len(y))) + 0.5
    bst = _train({"objective": "regression", "metric": "l2",
                  "bagging_fraction": 0.8, "bagging_freq": 1,
                  "num_leaves": 31, "learning_rate": 0.05},
                 x, y, 50, weights=w, valid=(xt, yt))
    res = dict((f"{d}:{n}", v) for d, n, v, _ in bst.eval_valid())
    assert res["valid_0:l2"] < 1.0


def test_model_roundtrip(binary_data, tmp_path):
    from lightgbm_tpu.boosting.gbdt import GBDT
    x, y, xt, yt = binary_data
    bst = _train({"objective": "binary", "num_leaves": 15,
                  "learning_rate": 0.1}, x, y, 10)
    path = str(tmp_path / "model.txt")
    bst.save_model_to_file(path)
    loaded = GBDT.load_model_from_file(path)
    np.testing.assert_allclose(loaded.predict(xt), bst.predict(xt),
                               rtol=1e-6, atol=1e-6)
    assert loaded.num_iterations() == 10


def test_early_stopping_rollback(regression_data):
    x, y, xt, yt = regression_data
    bst = _train({"objective": "regression", "num_leaves": 15,
                  "learning_rate": 0.1}, x, y, 10)
    before = bst.predict(xt)
    n_models = len(bst.models)
    bst.train_one_iter()
    bst.rollback_one_iter()
    assert len(bst.models) == n_models
    np.testing.assert_allclose(bst.predict(xt), before, rtol=1e-4, atol=1e-5)
