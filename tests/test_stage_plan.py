"""Wave-stage planner (ops/stage_plan.py): cost model, plan derivation,
byte-stable default, and the profile-guided install path."""

import numpy as np

from lightgbm_tpu.ops import stage_plan as sp


def test_legacy_plan_matches_historical_doubling():
    # the exact plan ops/grow.py hardcoded pre-refactor for L=255, k=3
    plan = sp.legacy_stage_plan(255, 128, 3)
    assert plan == [(4, 8), (16, 32), (32, 64), (64, 128), (128, None)]
    # dp (k=5) scales widths by 3/5, cap list unchanged
    plan5 = sp.legacy_stage_plan(255, 76, 5)
    assert plan5 == [(4, 8), (16, 32), (19, 64), (38, 128), (76, None)]
    # small trees collapse to the single full-width stage
    assert sp.legacy_stage_plan(15, 14, 3) == [(4, 8), (14, None)]


def test_plan_cost_counts_frontier_limited_waves():
    # frontier-limited growth: 1->2->4->...->128->255 is 8 waves no
    # matter how wide the stage is (only existing leaves can split)
    cost, waves = sp.plan_cost([(128, None)], 255, 3, 10.0, 0.1)
    assert waves == 8
    # the doubling ladder runs the SAME wave count but each early wave
    # carries fewer columns, so it is never more expensive
    legacy = sp.legacy_stage_plan(255, 128, 3)
    cost_l, waves_l = sp.plan_cost(legacy, 255, 3, 10.0, 0.1)
    assert waves_l == 8
    assert cost_l < cost
    # a too-narrow stage defers frontier splits => more waves
    _, waves_n = sp.plan_cost([(4, 128), (128, None)], 255, 3, 10.0, 0.1)
    assert waves_n > 8


def test_derive_prefers_wide_when_fixed_dominates():
    # flat measured cost curve (per-wave fixed cost dominates at small
    # frontiers): staging saves nothing, so fewer, wider stages win
    flat = {w: 100.0 for w in (4, 8, 16, 32, 64, 128)}
    plan = sp.derive_stage_plan(255, 128, 3, 100.0, 1e-4,
                                measured_ms=flat)
    assert plan == [(128, None)]
    # column cost dominates: staging pays for itself
    plan2 = sp.derive_stage_plan(255, 128, 3, fixed_ms=1e-3, col_ms=1.0)
    assert len(plan2) > 1
    c1, _ = sp.plan_cost(plan2, 255, 3, 1e-3, 1.0)
    c2, _ = sp.plan_cost([(128, None)], 255, 3, 1e-3, 1.0)
    assert c1 < c2


def test_fit_wave_costs_recovers_linear_model():
    widths = [4, 8, 16, 32, 64, 128]
    fixed, col = 12.0, 0.25
    ms = [fixed + col * w * 3 for w in widths]
    f, c = sp.fit_wave_costs(widths, ms, 3)
    np.testing.assert_allclose([f, c], [fixed, col], rtol=1e-6)
    # degenerate probes fall back to the chip constants
    f2, c2 = sp.fit_wave_costs([4], [1.0], 3)
    assert (f2, c2) == (sp.DEFAULT_FIXED_MS, sp.DEFAULT_COL_MS)
    # ... row-scaled when the caller's shape is known
    f3, c3 = sp.fit_wave_costs([4], [1.0], 3, num_data=sp.REF_ROWS // 2)
    np.testing.assert_allclose(
        [f3, c3], [sp.DEFAULT_FIXED_MS / 2, sp.DEFAULT_COL_MS / 2])


def test_plan_digest_stable_and_cache_roundtrip():
    plan = [(4, 8), (128, None)]
    d1 = sp.plan_digest(plan)
    assert d1 == sp.plan_digest([[4, 8], [128, None]])
    assert d1 != sp.plan_digest([(8, 16), (128, None)])
    sig = ("test-sig", 1, 2)
    assert sp.cached_plan(sig) is None
    sp.cache_plan(sig, plan)
    assert sp.cached_plan(sig) == [(4, 8), (128, None)]


def test_profile_stage_plan_records_and_installs():
    """End-to-end: probe timings land in obs, the derived plan installs
    on the grower, and a second same-signature grower picks it up from
    the plan cache (wave_plan=auto)."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    rng = np.random.default_rng(11)
    x = rng.standard_normal((1500, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    params = {"objective": "binary", "device_growth": "on",
              "num_leaves": 31, "max_bin": 63, "verbosity": -1,
              "seed": 1234567}   # unique seed => private cache signature

    def build():
        cfg = Config(params)
        ds = BinnedDataset.construct_from_matrix(x, cfg)
        ds.metadata.set_label(y)
        bst = create_boosting(cfg)
        bst.init_train(ds)
        return bst

    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    try:
        b1 = build()
        out = b1._grower.profile_stage_plan(reps=1)
        assert out["stage_ms"], out
        assert out["plan"][-1][1] is None
        assert b1._grower.stage_plan == out["plan"]
        gauges = obs.registry().snapshot()["gauges"]
        assert any(k.startswith("grow.stage.w") for k in gauges), gauges
        # second grower with the same signature adopts the cached plan
        b2 = build()
        assert b2._grower.stage_plan == out["plan"]
        assert b2._grower.plan_source == "profiled"
        # the plan-cache signature must ignore wave_plan itself: a
        # profiled-config run of the same workload adopts the cached
        # plan instead of digesting differently and re-measuring
        params["wave_plan"] = "profiled"
        b3 = build()
        assert b3._grower.stage_plan == out["plan"]
        assert b3._grower.plan_source == "profiled"
        params["wave_plan"] = "auto"
        # the re-planned grower still trains
        for _ in range(2):
            b2.train_one_iter()
        b2._flush_pending()
        assert len(b2.models) == 2
    finally:
        if not was_enabled:
            obs.configure(enabled=False)
