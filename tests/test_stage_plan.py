"""Wave-stage planner (ops/stage_plan.py): cost model, plan derivation,
byte-stable default, the profile-guided install path, and the on-disk
plan store beside the persistent compile cache."""

import contextlib
import json
import os
import subprocess
import sys

import numpy as np

from lightgbm_tpu.ops import stage_plan as sp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@contextlib.contextmanager
def _plan_store(tmp_path):
    """Point the compile cache (and thus the stage-plan store) at a tmp
    dir, restoring the session-wide default afterwards."""
    from lightgbm_tpu import compile_cache

    prev = compile_cache.cache_dir()
    compile_cache.configure(str(tmp_path / "cc"), _pin=False)
    try:
        yield
    finally:
        compile_cache.configure(
            prev or os.path.expanduser("~/.cache/lgbm_tpu_xla"),
            _pin=False)


def test_legacy_plan_matches_historical_doubling():
    # the exact plan ops/grow.py hardcoded pre-refactor for L=255, k=3
    plan = sp.legacy_stage_plan(255, 128, 3)
    assert plan == [(4, 8), (16, 32), (32, 64), (64, 128), (128, None)]
    # dp (k=5) scales widths by 3/5, cap list unchanged
    plan5 = sp.legacy_stage_plan(255, 76, 5)
    assert plan5 == [(4, 8), (16, 32), (19, 64), (38, 128), (76, None)]
    # small trees collapse to the single full-width stage
    assert sp.legacy_stage_plan(15, 14, 3) == [(4, 8), (14, None)]


def test_plan_cost_counts_frontier_limited_waves():
    # frontier-limited growth: 1->2->4->...->128->255 is 8 waves no
    # matter how wide the stage is (only existing leaves can split)
    cost, waves = sp.plan_cost([(128, None)], 255, 3, 10.0, 0.1)
    assert waves == 8
    # the doubling ladder runs the SAME wave count but each early wave
    # carries fewer columns, so it is never more expensive
    legacy = sp.legacy_stage_plan(255, 128, 3)
    cost_l, waves_l = sp.plan_cost(legacy, 255, 3, 10.0, 0.1)
    assert waves_l == 8
    assert cost_l < cost
    # a too-narrow stage defers frontier splits => more waves
    _, waves_n = sp.plan_cost([(4, 128), (128, None)], 255, 3, 10.0, 0.1)
    assert waves_n > 8


def test_derive_prefers_wide_when_fixed_dominates():
    # flat measured cost curve (per-wave fixed cost dominates at small
    # frontiers): staging saves nothing, so fewer, wider stages win
    flat = {w: 100.0 for w in (4, 8, 16, 32, 64, 128)}
    plan = sp.derive_stage_plan(255, 128, 3, 100.0, 1e-4,
                                measured_ms=flat)
    assert plan == [(128, None)]
    # column cost dominates: staging pays for itself
    plan2 = sp.derive_stage_plan(255, 128, 3, fixed_ms=1e-3, col_ms=1.0)
    assert len(plan2) > 1
    c1, _ = sp.plan_cost(plan2, 255, 3, 1e-3, 1.0)
    c2, _ = sp.plan_cost([(128, None)], 255, 3, 1e-3, 1.0)
    assert c1 < c2


def test_fit_wave_costs_recovers_linear_model():
    widths = [4, 8, 16, 32, 64, 128]
    fixed, col = 12.0, 0.25
    ms = [fixed + col * w * 3 for w in widths]
    f, c = sp.fit_wave_costs(widths, ms, 3)
    np.testing.assert_allclose([f, c], [fixed, col], rtol=1e-6)
    # degenerate probes fall back to the chip constants
    f2, c2 = sp.fit_wave_costs([4], [1.0], 3)
    assert (f2, c2) == (sp.DEFAULT_FIXED_MS, sp.DEFAULT_COL_MS)
    # ... row-scaled when the caller's shape is known
    f3, c3 = sp.fit_wave_costs([4], [1.0], 3, num_data=sp.REF_ROWS // 2)
    np.testing.assert_allclose(
        [f3, c3], [sp.DEFAULT_FIXED_MS / 2, sp.DEFAULT_COL_MS / 2])


def test_plan_digest_stable_and_cache_roundtrip():
    plan = [(4, 8), (128, None)]
    d1 = sp.plan_digest(plan)
    assert d1 == sp.plan_digest([[4, 8], [128, None]])
    assert d1 != sp.plan_digest([(8, 16), (128, None)])
    sig = ("test-sig", 1, 2)
    assert sp.cached_plan(sig) is None
    sp.cache_plan(sig, plan)
    assert sp.cached_plan(sig) == [(4, 8), (128, None)]


def test_derive_beats_legacy_gate():
    """plan_beats prices candidate vs incumbent with the same wave-cost
    function derive uses, requiring the 2% MIN_IMPROVEMENT margin —
    the wave_plan=auto gate that keeps the byte-stable legacy ladder
    on flat-cost shapes."""
    legacy = sp.legacy_stage_plan(255, 128, 3)
    # inverted measured curve (narrow waves cost MORE than the full
    # width — a dispatch/tile floor): the single-stage plan's 8 waves
    # at 100 ms beat the ladder's 7 narrow waves at 150 + 1 at 100
    floor = {4: 150.0, 8: 150.0, 16: 150.0, 32: 150.0, 64: 150.0,
             128: 100.0}
    assert sp.plan_beats([(128, None)], legacy, 255, 3, 100.0, 1e-4,
                         measured_ms=floor)
    # perfectly flat curve: equal wave counts => equal cost => no 2%
    # win, the incumbent survives (derive still picks fewer stages on
    # ties, but auto keeps the byte-stable legacy ladder)
    flat = {w: 100.0 for w in (4, 8, 16, 32, 64, 128)}
    assert not sp.plan_beats([(128, None)], legacy, 255, 3, 100.0,
                             1e-4, measured_ms=flat)
    # column-dominated cost: the ladder is cheaper, a one-stage plan
    # does NOT beat it
    assert not sp.plan_beats([(128, None)], legacy, 255, 3, 1e-3, 1.0)
    # a plan never beats itself (the margin requirement)
    assert not sp.plan_beats(legacy, legacy, 255, 3, 10.0, 0.1)


def test_plan_persistence_roundtrip(tmp_path):
    """save_plan/load_plan round-trip beside the compile cache; corrupt
    digests, foreign signatures and absent stores all degrade to None
    (-> legacy plan), never to a bad plan."""
    sig = ("persist-sig", 4096, 3, 64, False, "digest")
    plan = [(4, 8), (16, 32), (128, None)]
    with _plan_store(tmp_path):
        assert sp.load_plan(sig) is None
        path = sp.save_plan(sig, plan)
        assert path is not None and os.path.exists(path)
        assert sp.load_plan(sig) == plan
        # cache_plan writes through to disk by default
        sig2 = sig + ("v2",)
        sp.cache_plan(sig2, plan)
        assert sp.load_plan(sig2) == plan
        # ... and persist=False keeps it process-local
        sig3 = sig + ("v3",)
        sp.cache_plan(sig3, plan, persist=False)
        assert sp.load_plan(sig3) is None
        # digest mismatch (hand-edited/corrupt file) -> fallback
        with open(path) as fh:
            payload = json.load(fh)
        payload["plan"] = [[8, 16], [128, None]]     # digest now stale
        with open(path, "w") as fh:
            json.dump(payload, fh)
        assert sp.load_plan(sig) is None
        # signature mismatch (hash-prefix collision paranoia) -> None
        sp.save_plan(sig, plan)
        with open(path) as fh:
            payload = json.load(fh)
        payload["signature"] = "something else"
        with open(path, "w") as fh:
            json.dump(payload, fh)
        assert sp.load_plan(sig) is None
        # unparseable file -> None
        with open(path, "w") as fh:
            fh.write("{not json")
        assert sp.load_plan(sig) is None
        # forget_plan removes both layers
        sp.save_plan(sig, plan)
        sp.cache_plan(sig, plan, persist=False)
        sp.forget_plan(sig)
        assert sp.cached_plan(sig) is None
        assert sp.load_plan(sig) is None
    # no active store: save/load are clean no-ops
    from lightgbm_tpu import compile_cache
    if compile_cache.cache_dir() is None:
        assert sp.save_plan(sig, plan) is None


def test_auto_grower_adopts_persisted_plan(tmp_path):
    """get_grower_programs under wave_plan=auto adopts a persisted plan
    from a 'previous process' (plan_source='persisted'), and a corrupt
    file falls back to the legacy plan."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ops import grow

    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "verbosity": -1, "seed": 424243})
    sig = grow.programs_signature(4096, 3, 64, 3, False, cfg)
    custom = [(8, 16), (31, None)]
    with _plan_store(tmp_path):
        sp.forget_plan(sig)
        sp.save_plan(sig, custom)
        progs = grow.get_grower_programs(4096, 3, 64, 3, False, cfg)
        assert progs.stage_plan == custom
        assert progs.plan_source == "persisted"
        # corrupt the file: a FRESH signature lookup (cleared caches)
        # degrades to the legacy default
        sp.forget_plan(sig)
        path = sp.save_plan(sig, custom)
        with open(path, "w") as fh:
            fh.write("garbage")
        with grow._PROGRAM_CACHE_LOCK:
            saved = dict(grow._PROGRAM_CACHE)
            grow._PROGRAM_CACHE.clear()
        try:
            progs2 = grow.get_grower_programs(4096, 3, 64, 3, False, cfg)
            assert progs2.plan_source == "default"
            assert progs2.stage_plan == grow.default_stage_plan(4096,
                                                                cfg)
        finally:
            with grow._PROGRAM_CACHE_LOCK:
                grow._PROGRAM_CACHE.clear()
                grow._PROGRAM_CACHE.update(saved)
            sp.forget_plan(sig)


def test_persisted_plan_key_stable_across_hashseeds(tmp_path):
    """The on-disk plan filename must be PYTHONHASHSEED-independent —
    a hash-order-dependent key would quietly defeat the cross-process
    adoption (mirrors test_coldstart's programs_signature contract)."""
    script = """
import json, sys
sys.path.insert(0, {repo!r})
from lightgbm_tpu import compile_cache
from lightgbm_tpu.ops import stage_plan as sp
compile_cache.configure({store!r}, _pin=False)
sig = ("sig", 4096, 3, 64, False, "abc123")
print(json.dumps({{"path": sp._plan_path(sig)}}))
""".format(repo=REPO, store=str(tmp_path / "cc"))
    outs = []
    for seed in ("1", "271828"):
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": seed})
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, cwd=REPO)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    assert outs[0] == outs[1]


def test_profile_stage_plan_records_and_installs():
    """End-to-end: probe timings land in obs, the derived plan installs
    on the grower, and a second same-signature grower picks it up from
    the plan cache (wave_plan=auto)."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    rng = np.random.default_rng(11)
    x = rng.standard_normal((1500, 6)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    params = {"objective": "binary", "device_growth": "on",
              "num_leaves": 31, "max_bin": 63, "verbosity": -1,
              "seed": 1234567}   # unique seed => private cache signature

    def build():
        cfg = Config(params)
        ds = BinnedDataset.construct_from_matrix(x, cfg)
        ds.metadata.set_label(y)
        bst = create_boosting(cfg)
        bst.init_train(ds)
        return bst

    was_enabled = obs.enabled()
    obs.configure(enabled=True)
    try:
        # a previous RUN of this test may have persisted a plan for
        # this very signature beside the session compile cache (the
        # profile path now writes through to disk): forget it so the
        # probe actually measures, then rebuild from a clean slate
        pre = build()
        base_sig = pre._grower._base_signature
        sp.forget_plan(base_sig)
        from lightgbm_tpu.ops import grow as growmod
        with growmod._PROGRAM_CACHE_LOCK:
            for key in [k for k in growmod._PROGRAM_CACHE
                        if k[:len(base_sig)] == base_sig]:
                growmod._PROGRAM_CACHE.pop(key)
        b1 = build()
        assert b1._grower.plan_source == "default"
        out = b1._grower.profile_stage_plan(reps=1)
        assert out["stage_ms"], out
        assert out["plan"][-1][1] is None
        assert b1._grower.stage_plan == out["plan"]
        gauges = obs.registry().snapshot()["gauges"]
        assert any(k.startswith("grow.stage.w") for k in gauges), gauges
        # second grower with the same signature adopts the cached plan
        b2 = build()
        assert b2._grower.stage_plan == out["plan"]
        assert b2._grower.plan_source == "profiled"
        # the plan-cache signature must ignore wave_plan itself: a
        # profiled-config run of the same workload adopts the cached
        # plan instead of digesting differently and re-measuring
        params["wave_plan"] = "profiled"
        b3 = build()
        assert b3._grower.stage_plan == out["plan"]
        assert b3._grower.plan_source == "profiled"
        params["wave_plan"] = "auto"
        # the re-planned grower still trains
        for _ in range(2):
            b2.train_one_iter()
        b2._flush_pending()
        assert len(b2.models) == 2
    finally:
        if not was_enabled:
            obs.configure(enabled=False)
