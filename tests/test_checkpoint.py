"""CLI-level checkpoint/resume round trips (docs/Robustness.md).

The satellite contract: ``snapshot_freq`` files written by the CLI load
back and continue training to the SAME final model as an uninterrupted
run — exercised through the real ``cli.main`` entry point (the
``Application`` lifecycle), both for task=train snapshots and for
task=pipeline window checkpoints.
"""

import numpy as np
import pytest

from lightgbm_tpu import cli


def _write_train_file(path, seed=0, n=1500, nf=6):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, nf))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    np.savetxt(path, np.column_stack([y, x]), delimiter="\t", fmt="%.6g")


BASE = ["objective=binary", "num_leaves=15", "max_bin=63",
        "min_data_in_leaf=5", "verbosity=-1", "metric=none",
        "bagging_fraction=0.8", "bagging_freq=3", "feature_fraction=0.8"]


@pytest.mark.timeout(120)
def test_cli_snapshot_resume_matches_uninterrupted(tmp_path):
    data = str(tmp_path / "train.tsv")
    _write_train_file(data)

    # uninterrupted 6-iteration reference
    ref_model = str(tmp_path / "ref.txt")
    assert cli.main([f"data={data}", f"output_model={ref_model}",
                     "num_iterations=6", *BASE]) == 0

    # "killed" run: 4 iterations with snapshots every 2
    out_model = str(tmp_path / "model.txt")
    assert cli.main([f"data={data}", f"output_model={out_model}",
                     "num_iterations=4", "snapshot_freq=2",
                     *BASE]) == 0
    snap = f"{out_model}.snapshot_iter_4"
    import os
    assert os.path.exists(snap) and os.path.exists(snap + ".state.npz")

    # resumed run: --resume picks up snapshot_iter_4, trains 2 more
    assert cli.main([f"data={data}", f"output_model={out_model}",
                     "num_iterations=6", "snapshot_freq=2", "--resume",
                     *BASE]) == 0

    ref = open(ref_model).read()
    out = open(out_model).read()
    # identical trees; only the knobs that DEFINE the interrupted run
    # (paths, snapshot cadence, the resume flag) may differ
    strip = lambda t: "\n".join(              # noqa: E731
        line for line in t.splitlines()
        if not line.startswith(("[output_model", "[snapshot_freq",
                                "[resume_training")))
    assert strip(out) == strip(ref)


@pytest.mark.timeout(120)
def test_cli_resume_without_snapshot_warns_and_trains(tmp_path):
    data = str(tmp_path / "train.tsv")
    _write_train_file(data, seed=1)
    out_model = str(tmp_path / "model.txt")
    assert cli.main([f"data={data}", f"output_model={out_model}",
                     "num_iterations=2", "--resume", *BASE]) == 0
    assert "tree" in open(out_model).read()


@pytest.mark.timeout(180)
def test_cli_pipeline_checkpoint_resume(tmp_path):
    """task=pipeline with pipeline_checkpoint_dir commits every window;
    a resumed run skips the committed windows and saves the same final
    model as the straight-through run (fresh policy, rebin off)."""
    data = str(tmp_path / "train.tsv")
    _write_train_file(data, seed=2, n=4000)
    args = [f"data={data}", "task=pipeline", "pipeline_windows=3",
            "pipeline_rebin=false", "num_iterations=4", *BASE]

    ref_model = str(tmp_path / "ref.txt")
    assert cli.main(args + [f"output_model={ref_model}"]) == 0

    # straight run WITH checkpointing, then resume over the same file:
    # every window is already committed, so resume retrains nothing and
    # re-saves the checkpointed final model
    ckpt = str(tmp_path / "ckpt")
    out_model = str(tmp_path / "model.txt")
    assert cli.main(args + [f"output_model={out_model}",
                            f"pipeline_checkpoint_dir={ckpt}"]) == 0
    out_model2 = str(tmp_path / "model2.txt")
    assert cli.main(args + [f"output_model={out_model2}",
                            f"pipeline_checkpoint_dir={ckpt}",
                            "--resume"]) == 0

    strip = lambda t: "\n".join(              # noqa: E731
        line for line in t.splitlines()
        if not line.startswith(("[output_model", "[pipeline_checkpoint",
                                "[resume_training", "[task")))
    ref = strip(open(ref_model).read())
    assert strip(open(out_model).read()) == ref
    assert strip(open(out_model2).read()) == ref
