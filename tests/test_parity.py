"""Reference-parity golden tests (VERDICT r3 item 4).

Fixtures under ``tests/fixtures/`` were produced by driving the REFERENCE
implementation's own C API (``scripts/make_parity_fixtures.py`` against
``lib_lightgbm.so`` built from ``/root/reference``):

* ``ref_bins.jsonl``          — ``BinMapper::FindBin`` outputs
  (``src/io/bin.cpp:74-151`` via ``scripts/dump_ref_bins.cpp``)
* ``ref_<model>.model.txt``   — v2 model text saved by the reference
  (``src/boosting/gbdt_model_text.cpp:243-330``)
* ``ref_<model>.preds.txt``   — the reference's raw-score predictions
* ``ref_<model>.eval.json``   — the reference's train-metric curve
* ``ours_binary.model.txt`` / ``ref_preds_on_ours.txt`` — OUR saved
  model and what the reference predicted after loading it

These pin this framework to reference semantics: loading a verbatim
reference model must reproduce the reference's predictions; our binning
must match GreedyFindBin bit-for-bit; our training on identical data
must track the reference's metric curve.
"""

import json
import os

import numpy as np
import pytest

import parity_data as pd
from lightgbm_tpu.basic import Booster, Dataset
from lightgbm_tpu.data.binning import BinMapper

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _fixture(name):
    path = os.path.join(FIXDIR, name)
    if not os.path.exists(path):
        pytest.skip(f"fixture {name} missing")
    return path


# ----------------------------------------------------------------------
# (b) bin boundaries match GreedyFindBin
# ----------------------------------------------------------------------
def test_bin_boundaries_match_reference():
    with open(_fixture("ref_bins.jsonl")) as fh:
        golden = {rec["name"]: rec
                  for rec in (json.loads(l) for l in fh if l.strip())}
    cases = {name: (max_bin, mdib, values)
             for name, max_bin, mdib, values in pd.bin_cases()}
    assert set(golden) == set(cases)
    for name, (max_bin, mdib, values) in cases.items():
        ref = golden[name]
        m = BinMapper()
        m.find_bin(np.asarray(values, np.float64), len(values), max_bin,
                   mdib, 0, use_missing=True, zero_as_missing=False)
        assert m.num_bin == ref["num_bin"], name
        # reference enum order: None=0, Zero=1, NaN=2 (bin.h:22-26)
        mt = {"none": 0, "zero": 1, "nan": 2}[m.missing_type]
        assert mt == ref["missing_type"], name
        ours = [m.bin_to_value(b) for b in range(m.num_bin)]
        want = list(ref["upper_bounds"])
        if ref["missing_type"] == 2:
            # the fork's NaN-bin upper bound is the enum value NaN=2
            # implicitly converted to double (bin.cpp:285 pushes
            # MissingType::NaN -> 2.0); it is never compared against
            # (NaN routing is special-cased), so exempt that slot
            ours, want = ours[:-1], want[:-1]
        np.testing.assert_allclose(
            ours, want, rtol=1e-12, atol=0.0,
            err_msg=f"bin upper bounds diverge for case {name}")


# ----------------------------------------------------------------------
# (a) loading verbatim reference model text reproduces its predictions
# ----------------------------------------------------------------------
@pytest.mark.parametrize("name", ["binary", "regression", "multiclass",
                                  "categorical"])
def test_reference_model_predictions(name):
    model_path = _fixture(f"ref_{name}.model.txt")
    preds_path = _fixture(f"ref_{name}.preds.txt")
    x = (pd.make_categorical_features() if name == "categorical"
         else pd.make_features())[:pd.PRED_ROWS]
    want = np.loadtxt(preds_path)
    bst = Booster(model_file=model_path)
    got = np.asarray(bst.predict(x, raw_score=True), np.float64).reshape(-1)
    np.testing.assert_allclose(
        got, want.reshape(-1), rtol=1e-5, atol=1e-6,
        err_msg=f"predictions diverge from the reference for {name}")


# ----------------------------------------------------------------------
# our saved model, loaded by the reference, predicted the same thing
# ----------------------------------------------------------------------
def test_our_model_reference_roundtrip():
    model_path = _fixture("ours_binary.model.txt")
    preds_path = _fixture("ref_preds_on_ours.txt")
    x = pd.make_features()[:pd.PRED_ROWS]
    want = np.loadtxt(preds_path)
    bst = Booster(model_file=model_path)
    got = np.asarray(bst.predict(x, raw_score=True), np.float64).reshape(-1)
    np.testing.assert_allclose(
        got, want, rtol=1e-5, atol=1e-6,
        err_msg="our saved model predicts differently than the reference "
                "loading the same file")


# ----------------------------------------------------------------------
# (c) training on identical data tracks the reference's metric curve
# ----------------------------------------------------------------------
def test_training_curve_tracks_reference():
    with open(_fixture("ref_binary.eval.json")) as fh:
        golden = json.load(fh)
    ref_curve = [e[0] for e in golden["evals"]]
    x = pd.make_features()
    y_bin, _, _ = pd.make_labels(x)
    params = {"objective": "binary", "metric": "binary_logloss",
              "num_leaves": 15, "learning_rate": 0.1,
              "min_data_in_leaf": 5, "max_bin": 255, "verbosity": -1,
              "device_growth": "off"}
    train = Dataset(x, label=y_bin, params=params)
    bst = Booster(params, train)
    ours = []
    for _ in range(len(ref_curve)):
        bst.update()
        ours.append(bst.eval_train()[0][2])
    # identical bins + identical split rules should give a near-identical
    # optimization trajectory; bf16 histogram rounding allows small drift
    np.testing.assert_allclose(
        ours, ref_curve, rtol=0.02,
        err_msg="binary_logloss curve diverges from the reference run")
    assert abs(ours[-1] - ref_curve[-1]) / ref_curve[-1] < 0.02
