"""Causal trace-context propagation (lightgbm_tpu/obs/tracing.py) and
the XLA cost/attribution helpers (lightgbm_tpu/obs/profile.py).

Pins the propagation edges docs/Observability.md "Tracing &
attribution" promises:

* prep thread -> train -> swap -> serve: a served request's
  ``model_span_id`` link walks back to the exact pipeline window that
  trained the answering model, all on ONE trace_id;
* ``submit`` -> worker flush: the ``serve.request`` span event parents
  under the submitter's active span (solo server and fleet);
* checkpoint/resume: the manifest carries the originating trace_id and
  the resumed pipeline's windows keep it;
* disabled hot path: ``span()`` stays the shared no-op singleton,
  ``capture()``/``new_root()`` allocate nothing, spans record no ids.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import profile, tracing
from lightgbm_tpu.obs.state import STATE
from lightgbm_tpu.pipeline import PreppedWindow, RetrainPipeline
from lightgbm_tpu.robust.checkpoint import load_pipeline_checkpoint
from lightgbm_tpu.serve import PredictionServer
from lightgbm_tpu.serve.fleet import FleetServer

PIPE_PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
               "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
               "device_growth": "on", "num_iterations": 4}


@pytest.fixture(autouse=True)
def _clean_obs_state():
    obs.configure(enabled=False)
    obs.reset()
    yield
    obs.configure(enabled=False)
    obs.reset()


def _trace_on():
    obs.configure(enabled=True, trace_context=True)


def _events():
    with STATE.trace._lock:
        return list(STATE.trace._events)


def _spans():
    """{span_id: (name, args)} for every recorded event carrying one."""
    out = {}
    for ev in _events():
        args = ev.args or {}
        if args.get("span_id"):
            out[args["span_id"]] = (ev.name, args)
    return out


def _by_name(name):
    return [ev.args or {} for ev in _events() if ev.name == name]


def _small_booster(seed=0, rounds=4):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((400, 5))
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "metric": "none", "min_data_in_leaf": 5}
    return lgb.train(params, lgb.Dataset(x, label=y),
                     num_boost_round=rounds)


def _prep(seed_base, n=1500, nf=6):
    def prep(w):
        rng = np.random.default_rng(seed_base + w)
        x = rng.standard_normal((n, nf))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
        return PreppedWindow(label=y, dense=x, eval_dense=x,
                             eval_label=y)
    return prep


# ---------------------------------------------------------------------------
# prep -> train -> swap -> serve
# ---------------------------------------------------------------------------

class TestPipelineChain:
    def test_trace_survives_prep_train_swap_serve(self):
        """The tentpole edge: every pipeline span shares one trace_id,
        and a post-run serve.predict links through the swap span to
        the training window that produced its model."""
        _trace_on()
        pipe = RetrainPipeline(PIPE_PARAMS, chunk=2)
        pipe.run(range(2), _prep(100))
        pipe.server.predict(np.zeros((32, 6)))

        spans = _spans()
        pipeline_traces = {a["trace_id"] for name, a in spans.values()
                          if name.startswith("pipeline.")
                          or name in ("serve.swap", "flush_pending")}
        assert pipeline_traces == {pipe._trace_id}

        preds = [a for a in _by_name("serve.predict")
                 if a.get("model_span_id")]
        assert preds, "serve.predict never linked to its model"
        link = preds[-1]
        assert link["model_trace_id"] == pipe._trace_id
        # walk the parent chain from the linked swap span to the root
        chain, cur = [], link["model_span_id"]
        while cur is not None and cur in spans and len(chain) < 20:
            name, args = spans[cur]
            chain.append(name)
            cur = args.get("parent_id")
        assert cur is None, f"chain broke at unknown span {cur}"
        assert chain[0] == "serve.swap"
        assert "pipeline.window" in chain
        assert "pipeline.prep_window" in chain

    def test_prep_thread_spans_join_callers_trace(self):
        """The prep worker runs on its own thread with an empty
        contextvars context — its spans must still join the pipeline's
        root trace (the explicit capture()/set_current() handoff)."""
        _trace_on()
        pipe = RetrainPipeline(PIPE_PARAMS, chunk=2, serve=False)
        pipe.run(range(2), _prep(200))
        preps = _by_name("pipeline.prep_window")
        assert len(preps) == 2
        assert {a["trace_id"] for a in preps} == {pipe._trace_id}
        assert all(a.get("span_id") for a in preps)


# ---------------------------------------------------------------------------
# submit -> worker flush
# ---------------------------------------------------------------------------

class TestSubmitFlush:
    def test_serve_request_parents_under_submitter(self):
        _trace_on()
        srv = PredictionServer(_small_booster())
        srv.start()
        try:
            with obs.span("caller.request", cat="serve"):
                srv.submit(np.zeros((16, 5))).result(timeout=30)
        finally:
            srv.stop()
        spans = _spans()
        caller = [sid for sid, (name, _) in spans.items()
                  if name == "caller.request"]
        assert len(caller) == 1
        reqs = _by_name("serve.request")
        assert reqs, "worker flush emitted no serve.request span event"
        assert reqs[-1]["parent_id"] == caller[0]
        assert reqs[-1]["trace_id"] == spans[caller[0]][1]["trace_id"]

    def test_fleet_submit_flush_and_model_link(self):
        """FleetServer: swap under a 'training' span, then (a) a
        single-tenant predict links to that swap's context and (b) the
        micro-batch flush parents the serve.fleet.request event (with
        its replica) under the submitter's span."""
        _trace_on()
        b0, b1 = _small_booster(0), _small_booster(1)
        fleet = FleetServer([b0, b1], replicas=1)
        with obs.span("train.window", cat="train") as swap_parent:
            fleet.swap_tenant(1, b1)
        tid = np.ones(16, np.int32)
        fleet.predict(tid, np.zeros((16, 5)))
        fleet.start()
        try:
            with obs.span("caller.request", cat="serve"):
                fleet.submit(tid[:8], np.zeros((8, 5))).result(
                    timeout=30)
        finally:
            fleet.stop()

        spans = _spans()
        swaps = [a for n, a in spans.values()
                 if n == "serve.fleet.swap"]
        assert len(swaps) == 1
        preds = [a for a in _by_name("serve.fleet.predict")
                 if a.get("model_span_id")]
        assert preds, "single-tenant predict never linked its model"
        assert preds[-1]["model_span_id"] == swaps[0]["span_id"]
        assert preds[-1]["model_trace_id"] == swaps[0]["trace_id"]
        assert preds[-1]["tenant"] == 1

        caller = [sid for sid, (n, _) in spans.items()
                  if n == "caller.request"]
        reqs = _by_name("serve.fleet.request")
        assert reqs, "fleet flush emitted no serve.fleet.request event"
        assert reqs[-1]["parent_id"] == caller[0]
        assert reqs[-1]["replica"] == 0


# ---------------------------------------------------------------------------
# checkpoint -> resume
# ---------------------------------------------------------------------------

class TestCheckpointResume:
    def test_resume_keeps_originating_trace_id(self, tmp_path):
        _trace_on()
        cpdir = str(tmp_path / "cp")
        kw = dict(chunk=2, serve=False, window_policy="fresh",
                  rebin_on_drift=False)
        pipe = RetrainPipeline(PIPE_PARAMS, checkpoint_dir=cpdir, **kw)
        pipe.run(range(2), _prep(300))
        origin = pipe._trace_id
        assert origin

        cp = load_pipeline_checkpoint(cpdir)
        assert cp.trace_id == origin

        obs.reset()          # drop the first run's buffered spans
        _trace_on()
        resumed = RetrainPipeline.resume(cpdir, PIPE_PARAMS, **kw)
        assert resumed._trace_id == origin
        resumed.run(range(3), _prep(300))   # windows 0-1 skip, 2 runs
        windows = _by_name("pipeline.window")
        assert windows, "resumed run recorded no window span"
        assert {a["trace_id"] for a in windows} == {origin}


# ---------------------------------------------------------------------------
# disabled hot path
# ---------------------------------------------------------------------------

class TestDisabled:
    def test_disabled_allocates_no_context(self):
        obs.configure(enabled=False)
        assert obs.span("a", cat="x") is obs.span("b", cat="y")
        assert tracing.capture() is None
        assert tracing.current() is None
        assert tracing.new_root() is None
        assert tracing.set_current(None) is None
        tracing.reset(None)                 # must not raise
        assert tracing.link_args(None) == {}
        assert _events() == []

    def test_enabled_without_trace_context_records_no_ids(self):
        obs.configure(enabled=True, trace_context=False)
        with obs.span("plain", cat="x"):
            assert tracing.capture() is None
        args = _by_name("plain")[0]
        assert "span_id" not in args and "trace_id" not in args

    def test_context_is_flag_gated_live(self):
        """Flipping trace_context off mid-flight makes capture() None
        even with a context set — the single-flag-check contract."""
        _trace_on()
        tok = tracing.set_current(tracing.new_root("t" * 16))
        try:
            assert tracing.capture() is not None
            obs.configure(enabled=True, trace_context=False)
            assert tracing.capture() is None
        finally:
            obs.configure(enabled=True, trace_context=True)
            tracing.reset(tok)


# ---------------------------------------------------------------------------
# obs.profile helpers
# ---------------------------------------------------------------------------

class TestProfile:
    def test_normalize_cost_dict_and_list_forms(self):
        got = profile.normalize_cost({"flops": 10, "bytes accessed": 5,
                                      "transcendentals": 2})
        assert got == {"flops": 10.0, "bytes_accessed": 5.0,
                       "transcendentals": 2.0}
        # newer jax returns a one-element list; underscore key alias
        got = profile.normalize_cost([{"flops": 3,
                                       "bytes_accessed": 7}])
        assert got["flops"] == 3.0 and got["bytes_accessed"] == 7.0

    def test_normalize_cost_unusable_inputs(self):
        assert profile.normalize_cost(None) is None
        assert profile.normalize_cost({}) is None
        assert profile.normalize_cost([]) is None
        assert profile.normalize_cost("not a dict") is None

    def test_attribution_report_math_and_clamp(self):
        rep = profile.attribution_report(10.0, {"a": 6.0, "b": 3.0})
        assert rep["attributed_ms"] == pytest.approx(9.0)
        assert rep["coverage"] == pytest.approx(0.9)
        assert rep["unattributed_ms"] == pytest.approx(1.0)
        assert list(rep["phases"]) == ["a", "b"]   # sorted by ms desc
        assert rep["phases"]["a"]["share"] == pytest.approx(0.6)
        # probes can overshoot the fused loop: coverage clamps at 1.0
        over = profile.attribution_report(10.0, {"a": 12.0})
        assert over["attributed_ratio"] == pytest.approx(1.2)
        assert over["coverage"] == 1.0

    def test_attribution_report_costs_attach(self):
        rep = profile.attribution_report(
            10.0, {"a": 5.0}, costs={"a": {"flops": 5e9}})
        ph = rep["phases"]["a"]
        assert ph["cost"]["flops"] == 5e9
        # 5 GFLOP in 5 ms -> 1000 GFLOP/s
        assert ph["achieved_gflops"] == pytest.approx(1000.0)

    def test_cost_of_degrades_to_none(self):
        assert profile.cost_of(lambda x: x, 1) is None   # no .lower

    def test_device_trace_noop_without_path(self):
        with profile.device_trace(None) as profiled:
            assert profiled is False
