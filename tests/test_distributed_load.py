"""Distributed find-bin + pre-partitioned loading (VERDICT r3 missing #3;
reference dataset_loader.cpp:765-923 / :657-704 semantics)."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.binning import BinMapper
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.data.distributed import (allgather_mappers,
                                           construct_pre_partitioned,
                                           find_bin_shard,
                                           partition_features)


def test_partition_matches_reference_arithmetic():
    # dataset_loader.cpp:846-857: contiguous blocks of ceil(nf/m)
    for nf, m in [(28, 4), (10, 3), (3, 8), (136, 8), (1, 2)]:
        start, length = partition_features(nf, m)
        assert sum(length) == nf
        assert start[0] == 0
        for i in range(m - 1):
            assert start[i + 1] == start[i] + length[i]
        assert max(length) <= max((nf + m - 1) // m, 1)


def test_identical_samples_reproduce_local_mappers():
    """With every shard holding the SAME sample, the distributed path
    must reproduce single-host find_bin exactly (mapper serialization
    round-trips bit-exactly)."""
    rng = np.random.default_rng(0)
    x = np.ascontiguousarray(np.stack([
        rng.standard_normal(3000),
        rng.lognormal(0, 1, 3000),
        np.where(rng.random(3000) < 0.2, np.nan, rng.standard_normal(3000)),
        np.where(rng.random(3000) < 0.7, 0.0, rng.exponential(1, 3000)),
        rng.integers(0, 6, 3000).astype(np.float64),
    ], axis=1))
    cfg = Config({"objective": "regression", "max_bin": 63,
                  "verbosity": -1})
    pairs = [find_bin_shard(x, rank, 4, cfg) for rank in range(4)]
    mappers = allgather_mappers(pairs)
    assert len(mappers) == x.shape[1]
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    for f in range(x.shape[1]):
        ref = ds.bin_mappers[f]
        got = mappers[f]
        if ref is None:
            continue
        assert got.num_bin == ref.num_bin, f
        for b in range(ref.num_bin):
            assert got.bin_to_value(b) == ref.bin_to_value(b) or (
                np.isnan(got.bin_to_value(b))
                and np.isnan(ref.bin_to_value(b))), (f, b)


def test_pre_partitioned_trains_to_single_host_quality(binary_data):
    """Shard rows over 4 'machines', run the full pre-partitioned
    pipeline, train data-parallel on the 8-device mesh; quality must
    match single-host construction (bins are an owner-shard
    approximation, so trees may differ slightly — the contract is
    metric parity, like the reference's own distributed tests)."""
    from lightgbm_tpu.boosting import create_boosting

    x, y, xt, yt = binary_data
    params = {"objective": "binary", "metric": "auc", "num_leaves": 15,
              "learning_rate": 0.1, "tree_learner": "data",
              "num_machines": 8, "verbosity": -1}
    cfg = Config(params)

    # single-host baseline
    ds0 = BinnedDataset.construct_from_matrix(x, cfg, ())
    ds0.metadata.set_label(y)
    b0 = create_boosting(cfg)
    b0.init_train(ds0)
    for _ in range(10):
        b0.train_one_iter()

    # pre-partitioned: contiguous row shards, per-shard find-bin
    cuts = np.linspace(0, len(y), 5).astype(int)
    shards = [x[cuts[i]:cuts[i + 1]] for i in range(4)]
    ds1, offsets = construct_pre_partitioned(shards, cfg)
    assert offsets[-1] == len(y)
    ds1.metadata.set_label(np.concatenate(
        [y[cuts[i]:cuts[i + 1]] for i in range(4)]))
    b1 = create_boosting(cfg)
    b1.init_train(ds1)
    for _ in range(10):
        b1.train_one_iter()

    from sklearn.metrics import roc_auc_score
    a0 = roc_auc_score(yt, np.asarray(b0.predict(xt, raw_score=True)))
    a1 = roc_auc_score(yt, np.asarray(b1.predict(xt, raw_score=True)))
    assert a1 > a0 - 0.01, (a0, a1)


def test_misaligned_shards_rejected():
    with pytest.raises(Exception, match="misaligned"):
        allgather_mappers([(0, [BinMapper().to_state()]),
                           (5, [BinMapper().to_state()])])
