"""JL101 fixture: trace-key completeness around ``programs_signature``.

Planted: a trace-shaping constant missing from the signature, a
fusion-mode gating constant likewise unkeyed, a config attribute
excluded from the key but read inside a traced region, and a
runtime-traced attribute hashed into the key.  Exempt variants: a
constant that IS in the key, a host bookkeeping bound whose compares
never meet a shape, an ``int(...)`` structural config read, a
host-side fusion-mode string compare (no shape involved), and a
suppressed occurrence.
"""

import hashlib

import jax
import jax.numpy as jnp

from lightgbm_tpu import obs

_CHUNK = 1024
STRIPE_ROWS = 1 << 20
_HOST_CACHE_MAX = 8
_CACHE = {}

# wave-layout knobs (the find_best_fusion idiom): the frontier bound
# selects program structure, the default mode string never meets a shape
FUSED_FIND_MIN_FRONTIER = 8
DEFAULT_FIND_FUSION = "fused"

_NON_TRACE_PARAMS = ("learning_rate", "plan_mode")


def _config_digest(config):
    items = sorted((k, repr(v)) for k, v in config.to_dict().items()
                   if k not in _NON_TRACE_PARAMS)
    return hashlib.sha1(repr(items).encode()).hexdigest()


def programs_signature(num_data, config):
    # _CHUNK is keyed; STRIPE_ROWS (below) is not
    return (num_data, _CHUNK, _config_digest(config))


class Programs:
    def __init__(self, num_data, config):
        self.n_pad = max(int(num_data), _CHUNK)
        self.striped = num_data >= STRIPE_ROWS   # PLANT: JL101
        self.num_leaves = int(config.num_leaves)
        # the fused wave layout is only worth its trace above a frontier
        # bound — which makes the bound trace-shaping, and unkeyed here
        self.fused = \
            self.num_leaves >= FUSED_FIND_MIN_FRONTIER  # PLANT: JL101
        self.lr = float(config.shrinkage)        # PLANT: JL101
        self.grow = obs.track_jit("fixture_grow", jax.jit(_grow_impl))

    def dispatch(self, score):
        return self.grow(score, self.lr)

    def evict_needed(self):
        # host bookkeeping bound: the compare never meets a shape
        return len(_CACHE) > _HOST_CACHE_MAX


def suppressed_variant(num_data):
    # jaxlint: disable-next=JL101
    return num_data >= STRIPE_ROWS


def fusion_mode(config):
    # exempt: a host-side mode-string compare — the constant never
    # meets a shape, so it is resolution logic, not a trace key hole
    return str(config.find_best_fusion) == DEFAULT_FIND_FUSION


def _grow_impl(score, lr):
    return score * lr


def scan_body(config):
    def body(carry, x):
        # traced region reading an excluded ("traced-only") param:
        # the compiled program bakes in a value the key doesn't cover
        mode = config.plan_mode   # PLANT: JL101
        return carry + x, mode
    return jax.lax.scan(body, jnp.zeros(()), jnp.arange(4))
