"""JL005 fixture: set iteration order reaching the output —
the engine.py callback-dedupe bug class fixed by hand in PR 1."""


def callback_order(callbacks):
    deduped = set(callbacks)
    out = []
    for cb in deduped:  # PLANT: JL005
        out.append(cb)
    return out


def feature_list(names):
    return list({n.lower() for n in names})  # PLANT: JL005


def joined(tags):
    return ",".join(set(tags))  # PLANT: JL005


def comprehension_over_set(rows):
    return [r * 2 for r in {1, 2, 3}]  # PLANT: JL005


def sorted_is_clean(tags):
    return ",".join(sorted(set(tags)))


def membership_is_clean(tags, t):
    return t in set(tags)


def reduction_is_clean(vals):
    return sum(set(vals)), len(set(vals)), max(set(vals))
