"""Corpus: JL141 — thread/queue concurrency-graph hazards.

Planted defects: a spawned thread that opens spans with no
SpanContext handoff, blocking queue ops / a bare acquire in dispatch
scopes, and a join executed under a lock the joined thread acquires.
The good twins (context handed off, timeouts everywhere, join after
release) must stay silent.  The nested workers carry
``disable=JL161`` because this fixture deliberately has no
fault-site registry wiring (fault_coverage.py owns that).
"""
import queue
import threading

import obs
import tracing


# -- (a) spans on a spawned thread --------------------------------------

def spawn_without_handoff():
    def worker():  # jaxlint: disable=JL161
        with obs.span("corpus.window", cat="corpus"):
            pass

    t = threading.Thread(target=worker)  # PLANT: JL141
    t.start()
    return t


def spawn_with_set_current(captured):
    def worker():  # jaxlint: disable=JL161
        tracing.set_current(captured)
        with obs.span("corpus.window", cat="corpus"):
            pass

    t = threading.Thread(target=worker)  # ok: context activated inside
    t.start()
    return t


def spawn_with_ctx_param(span_ctx):
    def worker(ctx):  # jaxlint: disable=JL161
        with obs.span("corpus.window", cat="corpus"):
            pass

    t = threading.Thread(target=worker, args=(span_ctx,))  # ok: ctx arg
    t.start()
    return t


# -- (b) blocking calls in dispatch scopes ------------------------------

def dispatch_blocking():
    q = queue.Queue(maxsize=4)

    def worker():  # jaxlint: disable=JL161
        while True:
            try:
                if q.get(timeout=0.5) is None:  # ok: timed
                    return
            except queue.Empty:
                continue

    t = threading.Thread(target=worker)
    t.start()
    q.put("work")  # PLANT: JL141
    return q.get()  # PLANT: JL141


def dispatch_nonblocking():
    q = queue.Queue()  # unbounded: puts never block

    def worker():  # jaxlint: disable=JL161
        try:
            q.get(timeout=0.1)
        except queue.Empty:
            pass

    t = threading.Thread(target=worker)
    t.start()
    q.put("work")  # ok: unbounded put
    try:
        return q.get(timeout=1.0)  # ok: timed
    except queue.Empty:
        return None


def dispatch_bare_acquire():
    lock = threading.Lock()

    def worker():  # jaxlint: disable=JL161
        with lock:  # ok: context manager
            pass

    t = threading.Thread(target=worker)
    t.start()
    lock.acquire()  # PLANT: JL141
    try:
        return t
    finally:
        lock.release()


# -- (c) join while holding the target's lock ---------------------------

class Flusher:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._flush_loop)
        self._t.start()

    def _flush_loop(self):  # jaxlint: disable=JL161
        with self._lock:
            pass

    def stop_deadlocks(self):
        with self._lock:
            self._t.join()  # PLANT: JL141

    def stop_ok(self):
        with self._lock:
            t = self._t
        t.join()  # ok: lock released before the join
