"""JL004 fixture: float64 flowing into device code under disabled x64."""

import numpy as np

import jax.numpy as jnp


def make_scores(n):
    a = jnp.zeros(n, dtype=np.float64)  # PLANT: JL004
    b = jnp.asarray(np.arange(n), "float64")  # PLANT: JL004
    c = jnp.float64(3.14)  # PLANT: JL004
    host = np.asarray([1.0, 2.0], np.float64)   # host-side f64: clean
    d = jnp.asarray(host, jnp.float32)          # explicit 32-bit: clean
    return a, b, c, d
