"""JL121 fixture: lock-order inversion and thread-shared state.

Planted: a two-lock order inversion (both acquisition sites are
findings) and an unguarded ``self.<attr>`` write inside a thread entry
point of a lock-owning class.  Exempt variants: consistently ordered
nested acquisition, a locked self-attr write, and a suppressed
occurrence.
"""

import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()


def a_then_b():
    with _A_LOCK:
        with _B_LOCK:       # PLANT: JL121
            pass


def b_then_a():
    with _B_LOCK:
        with _A_LOCK:       # PLANT: JL121
            pass


_C_LOCK = threading.Lock()
_D_LOCK = threading.Lock()


def c_then_d_only():
    # one global order, no inversion anywhere: exempt
    with _C_LOCK:
        with _D_LOCK:
            pass


def c_then_d_again():
    with _C_LOCK:
        with _D_LOCK:
            pass


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._results = None
        self._progress = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):  # jaxlint: disable=JL161
        self._progress = 1          # PLANT: JL121
        with self._lock:
            self._results = []
        # jaxlint: disable-next=JL121
        self._progress = 2

    def snapshot(self):
        with self._lock:
            return self._results, self._progress
