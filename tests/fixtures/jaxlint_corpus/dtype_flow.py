"""JL111 fixture: int8/int32 quantization dtype-contract breaks.

Planted: an int8 contraction without ``preferred_element_type``, a
premature f32 upcast of int8 state, and an f32 upcast of int32
quantized accumulation state.  Exempt variants: the pinned int8->int32
contraction, the sanctioned ``.astype(float32) * scale`` dequantize,
a dequantize helper function, and a suppressed occurrence.
"""

import jax.numpy as jnp

F64_EDGE = jnp.asarray([0.5], jnp.float32)


def histogram_bad(one_hot_i8, stats_i8):
    # int8 operands, no preferred_element_type: off the MXU int path
    oh = one_hot_i8.astype(jnp.int8)
    st = stats_i8.astype(jnp.int8)
    return jnp.einsum("cgn,cb->gnb", oh, st)   # PLANT: JL111


def histogram_good(one_hot_i8, stats_i8):
    oh = one_hot_i8.astype(jnp.int8)
    st = stats_i8.astype(jnp.int8)
    return jnp.einsum("cgn,cb->gnb", oh, st,
                      preferred_element_type=jnp.int32)


def upcast_bad(mask, grad_q):
    m8 = mask.astype(jnp.int8)
    m8 = m8.astype(jnp.float32)                # PLANT: JL111
    return m8 * grad_q


def upcast_scan_state_bad(one_hot_i8, stats_i8):
    hist = jnp.matmul(one_hot_i8.astype(jnp.int8),
                      stats_i8.astype(jnp.int8),
                      preferred_element_type=jnp.int32)
    totals = hist.sum(0)
    return totals.astype(jnp.float32)          # PLANT: JL111


def dequantize_good(one_hot_i8, stats_i8, scales):
    hist = jnp.matmul(one_hot_i8.astype(jnp.int8),
                      stats_i8.astype(jnp.int8),
                      preferred_element_type=jnp.int32)
    # the sanctioned idiom: dequantize ONCE, scale applied immediately
    return hist.astype(jnp.float32) * scales[0]


def suppressed_variant(mask):
    m8 = mask.astype(jnp.int8)
    return m8.astype(jnp.float32)  # jaxlint: disable=JL111
