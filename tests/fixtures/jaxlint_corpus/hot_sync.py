"""JL001 fixture: host-device syncs planted inside hot-path loops.

Each ``# PLANT: JLxxx`` marks one defect the analyzer must report
exactly once; unmarked code is clean by construction and must stay
silent (tests/test_jaxlint.py enforces both directions).
"""
# jaxlint: hot-path

import numpy as np

import jax
import jax.numpy as jnp


def per_iteration_item(trees):
    total = 0.0
    for t in trees:
        total += t.value.item()  # PLANT: JL001
    return total


def float_of_device_value(n):
    scores = jnp.zeros((n,))
    out = []
    for i in range(n):
        out.append(float(scores[i]))  # PLANT: JL001
    return out


def int_of_asarray(handles):
    count = 0
    for h in handles:
        count += int(np.asarray(h))  # PLANT: JL001
    return count


def scalar_subscript_in_while(tree):
    s = 0.0
    while s < 10.0:
        s += float(tree.leaf_value[0])  # PLANT: JL001
    return s


def asarray_per_iteration(n):
    x = jnp.ones((4,))
    rows = []
    for _ in range(n):
        rows.append(np.asarray(x))  # PLANT: JL001
    return rows


def comprehension_sync(handles):
    x = jnp.ones((4,))
    return [int(np.asarray(x)[i]) for i, _ in enumerate(handles)]  # PLANT: JL001


def batched_fetch_is_clean(handles):
    host = jax.device_get(handles)   # one batched transfer, outside loops
    return [int(v) for v in host]


def shape_reads_are_clean(n):
    x = jnp.ones((n, 4))
    dims = []
    for _ in range(3):
        dims.append(int(x.shape[0]))   # metadata read, no transfer
    return dims
