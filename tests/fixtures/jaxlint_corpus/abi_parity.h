/* Miniature C header for the JL151 corpus fixture (abi_parity.py).
 *
 * Deliberate skew vs the sibling .py/.cpp:
 *   - LGBM_FixtureCreate takes THREE parameters here; the Python
 *     binding declares two               -> arity finding at the def.
 *   - LGBM_FixtureMissing is declared but has no Python binding
 *                                        -> finding at the directive.
 *   - the cpp defines LGBM_FixtureExtra, absent here
 *                                        -> finding at the impl line.
 */
#ifndef JAXLINT_CORPUS_ABI_PARITY_H_
#define JAXLINT_CORPUS_ABI_PARITY_H_

#define FIXTURE_C_EXPORT int

FIXTURE_C_EXPORT LGBM_FixtureCreate(const char* params, int n,
                                    void** out);
FIXTURE_C_EXPORT LGBM_FixtureFree(void* handle);
FIXTURE_C_EXPORT LGBM_FixturePredict(void* handle, const double* data,
                                     int nrow, double* out);
FIXTURE_C_EXPORT LGBM_FixtureMissing(void* handle);

#endif  /* JAXLINT_CORPUS_ABI_PARITY_H_ */
