"""JL002 fixture: recompile hazards around ``jax.jit``.

The jit decorators carry ``# jaxlint: disable=JL003`` so this file
isolates JL002 (and doubles as a suppression-mechanics fixture).
"""

import functools

import jax
import jax.numpy as jnp


@jax.jit  # jaxlint: disable=JL003
def _scale(x, factor):
    return x * factor


@functools.partial(jax.jit, static_argnames=("k",))  # jaxlint: disable=JL003
def _topk_static_is_clean(x, k):
    if k > x.shape[0]:   # static arg + shape read: no hazard
        k = x.shape[0]
    return jnp.sort(x)[-k:]


@jax.jit  # jaxlint: disable=JL003
def _clip(x, lo):
    if lo > 0:  # PLANT: JL002
        return jnp.maximum(x, lo)
    return x


@jax.jit  # jaxlint: disable=JL003
def _optional_is_clean(x, mask):
    if mask is None:   # `is None` is a trace-time static
        return x
    return x * mask


def run(x):
    y = _scale(x, 2.0)  # PLANT: JL002
    z = jax.jit(lambda a: a + 1)(y)  # PLANT: JL002
    ok = _scale(x, jnp.asarray(2.0))   # device scalar: clean
    return y, z, ok


def run_params(x):
    return _scale(x, {"lr": 0.1})  # PLANT: JL002
