"""JL131 fixture: nondeterminism taint reaching serialized bytes.

Planted: wall-clock into a checkpoint payload (directly and through a
helper's return value), an unseeded RNG draw reaching a digest, and a
set hash-order materialization feeding a model string sink.  Exempt
variants: seeded RNG, telemetry (not a sink), sorted() order, and a
suppressed occurrence.
"""

import time

import numpy as np


def plan_digest(plan):
    return repr(plan)


def save_pipeline_checkpoint(directory, model_str, meta):
    del directory, model_str, meta


def observe(name, value):
    del name, value


def commit_bad(directory, model_str):
    meta = {"rows": 4, "at": time.time()}
    save_pipeline_checkpoint(directory, model_str, meta)  # PLANT: JL131


def stamp():
    return time.time()


def commit_indirect_bad(directory, model_str):
    save_pipeline_checkpoint(directory, model_str,        # PLANT: JL131
                             {"at": stamp()})


def digest_bad(plan):
    jitter = np.random.uniform()
    return plan_digest([plan, jitter])                    # PLANT: JL131


def model_string_bad(features):
    order = list(set(features))  # jaxlint: disable=JL005
    return save_model(order)                              # PLANT: JL131


def save_model(columns):
    return "\n".join(str(c) for c in columns)


def commit_good(directory, model_str, seed):
    rng = np.random.default_rng(seed)
    meta = {"rows": 4, "noise_seed": int(rng.integers(1 << 30))}
    save_pipeline_checkpoint(directory, model_str, meta)
    # telemetry is not a sink: wall-clock timings are fine
    observe("checkpoint_s", time.time())


def model_string_good(features):
    return save_model(sorted(set(features)))


def suppressed_variant(directory, model_str):
    meta = {"at": time.time()}
    # jaxlint: disable-next=JL131
    save_pipeline_checkpoint(directory, model_str, meta)
