/* Miniature C implementation for the JL151 corpus fixture.
 *
 * Pairs with abi_parity.h / abi_parity.py.  Skew planted here:
 *   - LGBM_FixtureExtra is defined but never declared in the header;
 *   - LGBM_FixturePredict builds FIVE Py_BuildValue items for the
 *     four-parameter `fixture_predict` adapter.
 */
#include "abi_parity.h"

extern "C" int LGBM_FixtureCreate(const char* params, int n,
                                  void** out) {
  PyObject* args = Py_BuildValue("(si)", params, n);
  return call_adapter("fixture_create", args, out);
}

extern "C" int LGBM_FixtureFree(void* handle) {
  PyObject* args = Py_BuildValue("(L)", (long long)handle);
  return call_adapter("fixture_free", args, NULL);
}

extern "C" int LGBM_FixturePredict(void* handle, const double* data,
                                   int nrow, double* out) {
  PyObject* args = Py_BuildValue("(LNiiN)", (long long)handle,
                                 wrap(data), nrow, 0, wrap(out));
  return call_adapter("fixture_predict", args, NULL);
}

extern "C" int LGBM_FixtureMissing(void* handle) {
  PyObject* args = Py_BuildValue("(L)", (long long)handle);
  return call_adapter("fixture_missing", args, NULL);
}

extern "C" int LGBM_FixtureExtra(void* handle) {
  PyObject* args = Py_BuildValue("(L)", (long long)handle);
  return call_adapter("fixture_free", args, NULL);
}
