"""JL003 fixture: jitted callables missing obs.track_jit registration.

``obs`` is only referenced, never imported for real — the analyzer is
purely static.
"""

import functools

import jax
import jax.numpy as jnp

from lightgbm_tpu import obs


@jax.jit  # PLANT: JL003
def _untracked_square(x):
    return x * x


@functools.partial(jax.jit, static_argnames=("n",))  # PLANT: JL003
def _untracked_pad(x, n):
    return jnp.pad(x, (0, n))


@jax.jit
def _tracked_sum(x):
    return x.sum()


_tracked_sum = obs.track_jit("tracked_sum", _tracked_sum)

_inline_tracked = obs.track_jit("inline", jax.jit(lambda x: x - 1.0))

_untracked_assign = jax.jit(lambda x: x + 1.0)  # PLANT: JL003
