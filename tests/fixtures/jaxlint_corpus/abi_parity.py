"""Corpus: JL151 — cross-language C-ABI parity.

Miniature four-surface ABI: the sibling ``abi_parity.h`` declares the
entry points, ``abi_parity.cpp`` defines them and drives the adapter
table below through ``Py_BuildValue``/``call_adapter`` pairs.  Each
planted line carries exactly one deliberate skew; everything else is
in perfect sync and must stay silent.
"""
# jaxlint: abi-header=abi_parity.h  # PLANT: JL151
# jaxlint: abi-impl=abi_parity.cpp  # PLANT: JL151
#
# The two plants above anchor the surface-level findings: the header
# declares LGBM_FixtureMissing with no binding below (header line),
# and the cpp defines LGBM_FixtureExtra that the header never
# declares (impl line).


def LGBM_FixtureCreate(params, n):  # PLANT: JL151
    # header declares THREE parameters (params, n, out)
    return 0


def LGBM_FixtureFree(handle):
    return 0


def LGBM_FixturePredict(handle, data, nrow, out):
    return 0


# -- adapter table (what the embedded interpreter dispatches into) ------

def _call(fn, *args):
    rc = fn(*args)
    if rc != 0:
        raise RuntimeError(f"fixture ABI call failed: {rc}")


def fixture_create(params, n):
    _call(LGBM_FixtureCreate, n, params, 0)  # PLANT: JL151


def fixture_free(handle):
    _call(LGBM_FixtureFree, handle, 0)  # PLANT: JL151


def fixture_predict(handle, data, nrow, out):  # PLANT: JL151
    # the cpp builds FIVE Py_BuildValue items for this adapter
    _call(LGBM_FixturePredict, handle, data, nrow, out)


def fixture_missing(handle):
    # intact adapter: 1 format value in the cpp, 1 parameter here
    return 0
