"""JL006 fixture: module-level state mutated with and without a lock —
the pattern obs/registry.py solved with a per-registry lock."""

import threading

_REGISTRY = {}
_EVENTS = []
_LOCK = threading.Lock()
_next_id = 0


def register(name, value):
    _REGISTRY[name] = value  # PLANT: JL006


def record(evt):
    _EVENTS.append(evt)  # PLANT: JL006


def bump():
    global _next_id
    _next_id += 1  # PLANT: JL006
    return _next_id


def register_safe(name, value):
    with _LOCK:
        _REGISTRY[name] = value


def record_safe(evt):
    with _LOCK:
        _EVENTS.append(evt)


def read_only(name):
    return _REGISTRY.get(name)
