"""Corpus: JL161 — fault-site registry coverage.

Self-contained miniature of the robust/faults.py contract: a
module-level ``KNOWN_SITES`` registry, arming calls that pass a site
string (positionally and by keyword), and thread workers that must be
reachable from at least one armed site.  Planted: one registry entry
no call ever arms (dead), one call arming a typo'd site (unknown),
and one worker no fault site can reach.
"""
import threading

KNOWN_SITES = ("fixture.alpha", "fixture.beta", "fixture.dead")  # PLANT: JL161


def check(site):
    del site            # the real one raises an injected fault


def with_retries(fn, site=""):
    del site
    return fn()


def armed_path():
    check("fixture.alpha")      # positional site resolves via check()


def typo_path():
    check("fixture.alfa")  # PLANT: JL161


def beta_path():
    return with_retries(lambda: None, site="fixture.beta")


def covered_worker():
    while armed_path() is None:
        return


def uncovered_worker():  # PLANT: JL161
    return


def spawn_all():
    threading.Thread(target=covered_worker).start()
    threading.Thread(target=uncovered_worker).start()
