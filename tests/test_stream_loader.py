"""Two-round streaming text load (data/stream_loader.py).

The streaming path must produce the SAME dataset as the in-memory path
whenever the sample covers every row (both then see identical inputs for
bin finding), and must never materialize the full float64 matrix —
checked by keeping the declared chunk size far below the file's row
count so multiple chunks are actually exercised.
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.data import stream_loader
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.data.stream_loader import load_text_two_round


@pytest.fixture(autouse=True)
def small_chunks(monkeypatch):
    # force many small chunks so the chunked path is really exercised
    monkeypatch.setattr(stream_loader, "_CHUNK_BYTES", 4096)


def _write_csv(path, x, y):
    np.savetxt(path, np.column_stack([y, x]), delimiter=",", fmt="%.6g")


def test_streaming_matches_in_memory(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((3000, 6))
    x[rng.random((3000, 6)) < 0.2] = 0.0        # sparse-ish columns
    y = rng.standard_normal(3000)
    f = tmp_path / "train.csv"
    _write_csv(f, x, y)

    cfg = Config({"objective": "regression", "max_bin": 63,
                  "bin_construct_sample_cnt": 10000})  # sample >= n
    ds_stream, label = load_text_two_round(str(f), cfg)
    ds_mem = BinnedDataset.construct_from_matrix(
        np.loadtxt(f, delimiter=",")[:, 1:], cfg)

    assert ds_stream.num_data == 3000
    assert ds_stream.num_groups == ds_mem.num_groups
    np.testing.assert_array_equal(ds_stream.binned, ds_mem.binned)
    for ms, md in zip(ds_stream.bin_mappers, ds_mem.bin_mappers):
        np.testing.assert_array_equal(ms.bin_upper_bound,
                                      md.bin_upper_bound)
    np.testing.assert_allclose(label, y, atol=1e-5)


def test_streaming_sampled_still_trains(tmp_path):
    """With a sample smaller than the file, boundaries differ from the
    full-data ones but training must still work end to end."""
    rng = np.random.default_rng(1)
    n = 5000
    x = rng.standard_normal((n, 5))
    y = (x[:, 0] > 0.3).astype(np.float64)
    f = tmp_path / "train.csv"
    _write_csv(f, x, y)

    cfg = Config({"objective": "binary", "max_bin": 31,
                  "bin_construct_sample_cnt": 500, "num_leaves": 15,
                  "num_iterations": 10, "verbosity": -1})
    ds, label = load_text_two_round(str(f), cfg)
    assert ds.num_data == n
    from lightgbm_tpu.boosting import create_boosting
    bst = create_boosting(cfg)
    bst.init_train(ds)
    for _ in range(10):
        bst.train_one_iter()
    pred = bst.predict(x)
    assert ((pred > 0.5) == (y > 0.5)).mean() > 0.9


def test_streaming_libsvm(tmp_path):
    rng = np.random.default_rng(2)
    n, nf = 800, 12
    lines = []
    x = np.zeros((n, nf))
    y = rng.integers(0, 2, n)
    for i in range(n):
        cols = np.sort(rng.choice(nf, 4, replace=False))
        vals = rng.standard_normal(4).round(4)
        x[i, cols] = vals
        lines.append(f"{y[i]} " + " ".join(
            f"{c}:{v}" for c, v in zip(cols, vals)))
    f = tmp_path / "train.svm"
    f.write_text("\n".join(lines) + "\n")

    cfg = Config({"objective": "binary", "max_bin": 31,
                  "bin_construct_sample_cnt": 10000})
    ds, label = load_text_two_round(str(f), cfg)
    ds_mem = BinnedDataset.construct_from_matrix(x, cfg)
    assert ds.num_data == n
    np.testing.assert_array_equal(ds.binned, ds_mem.binned)
    np.testing.assert_allclose(label, y.astype(np.float64))


def test_cli_two_round(tmp_path):
    """two_round=true routes the CLI loader through the streaming path
    and trains the same model text as the in-memory path when the sample
    covers the file."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((2000, 4))
    y = x[:, 0] * 2 + rng.standard_normal(2000) * 0.1
    f = tmp_path / "train.csv"
    _write_csv(f, x, y)
    from lightgbm_tpu.cli import main
    m1 = tmp_path / "m1.txt"
    m2 = tmp_path / "m2.txt"
    base = [f"data={f}", "objective=regression", "num_leaves=15",
            "num_iterations=5", "verbosity=-1",
            "bin_construct_sample_cnt=10000"]
    main(base + [f"output_model={m1}"])
    main(base + [f"output_model={m2}", "two_round=true"])
    t1 = m1.read_text()
    t2 = m2.read_text()
    # identical up to the parameters block (paths / the two_round flag)
    strip = lambda t: "\n".join(
        l for l in t.splitlines()
        if not l.startswith(("[two_round", "[output_model")))
    assert strip(t1) == strip(t2)


def test_virtual_file_io(tmp_path):
    """file_io scheme dispatch: gzip transparency, clear errors for
    unregistered schemes, and pluggable handlers (the VirtualFileReader
    analog, reference src/io/file_io.cpp:13,54)."""
    import gzip
    import io
    import pytest
    from lightgbm_tpu.utils.file_io import open_text, register_scheme, exists
    from lightgbm_tpu.utils.log import LightGBMError
    from lightgbm_tpu.data.parser import load_text_file
    from lightgbm_tpu.config import Config

    body = "".join(f"{i % 2}\t{i}\t{i * 2}\n" for i in range(100))
    gz = tmp_path / "data.tsv.gz"
    with gzip.open(gz, "wt") as fh:
        fh.write(body)
    # transparent .gz through the full loader path
    x, y, _ = load_text_file(str(gz), Config({"verbosity": -1}))
    assert x.shape == (100, 2) and y.shape == (100,)
    assert exists(str(gz)) and not exists(str(tmp_path / "nope"))

    with pytest.raises(LightGBMError, match="no filesystem registered"):
        open_text("hdfs://cluster/path.tsv")
    with pytest.raises(LightGBMError, match="could not open"):
        open_text(str(tmp_path / "missing.tsv"))

    from lightgbm_tpu.utils import file_io
    register_scheme("mem", lambda path, mode: io.StringIO(body))
    try:
        with open_text("mem://whatever") as fh:
            assert len(fh.readlines()) == 100
    finally:
        file_io._SCHEMES.pop("mem", None)   # don't leak into other tests


def test_truncated_row_surfaces_with_file_and_line_context(tmp_path):
    """A mid-stream parse error (the satellite contract): the consumer
    gets a LightGBMError naming the FILE and the offending LINE, and
    the double-buffered reader thread never hangs behind the full
    queue (its bounded put notices the abandoned generator)."""
    import threading
    import time

    from lightgbm_tpu.utils.log import LightGBMError

    path = tmp_path / "trunc.libsvm"
    with open(path, "w") as fh:
        for i in range(300):
            fh.write(f"{i % 2} 0:1.5 2:{i}.0 4:0.5\n")
        fh.write("1 0:2.0 3:\n")          # truncated token, line 301
        for i in range(100):
            fh.write(f"{i % 2} 1:0.5\n")

    cfg = Config({"objective": "binary", "verbosity": -1,
                  "bin_construct_sample_cnt": 1000})
    before = threading.active_count()
    with pytest.raises(LightGBMError) as ei:
        load_text_two_round(str(path), cfg)
    msg = str(ei.value)
    assert "trunc.libsvm" in msg and "301" in msg
    # reader thread reaped: active threads return to the baseline
    for _ in range(50):
        if threading.active_count() <= before:
            break
        time.sleep(0.1)
    assert threading.active_count() <= before


def test_ragged_csv_row_context(tmp_path):
    """CSV flavor: a ragged row (extra cells — e.g. a torn/concatenated
    line from an interrupted writer) is located exactly.  Non-numeric
    CELLS intentionally do not raise: ``_atof`` maps them to NaN, the
    reference's lenient-parse behaviour."""
    from lightgbm_tpu.utils.log import LightGBMError

    path = tmp_path / "bad.csv"
    with open(path, "w") as fh:
        for i in range(200):
            fh.write(f"{i % 2},{i}.5,3.25\n")
        fh.write("1,2.0,3.0,4.0,5.0,6.0\n")   # ragged, line 201
        fh.write("0,1.0,2.0\n")

    cfg = Config({"objective": "binary", "verbosity": -1,
                  "bin_construct_sample_cnt": 1000})
    with pytest.raises(LightGBMError) as ei:
        load_text_two_round(str(path), cfg)
    assert "bad.csv" in str(ei.value) and "201" in str(ei.value)
