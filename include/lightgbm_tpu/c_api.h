/*!
 * lightgbm_tpu native ABI — the subset of the fork's C/C++ API surface
 * that its cache-admission harness consumes
 * (reference: /root/reference/include/LightGBM/c_api.h:38,144-160,
 *  271,293-300,341-346,374,430,591-600,621-640,715-720 and the call
 *  sites in /root/reference/src/test.cpp:243-298).
 *
 * Signatures match the fork's header verbatim, including its
 * std::unordered_map<std::string, std::string> parameter passing (the
 * fork patched the upstream plain-C signatures to C++ maps), so
 * test.cpp-shaped code compiles against this header unchanged and links
 * against liblgbm_tpu.so, which embeds CPython and executes the
 * lightgbm_tpu runtime.
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <cstdint>
#include <string>
#include <unordered_map>

#define LIGHTGBM_C_EXPORT extern "C" __attribute__((visibility("default")))
#define LIGHTGBM_CPP_EXPORT __attribute__((visibility("default")))

typedef void* DatasetHandle;
typedef void* BoosterHandle;

#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)
#define C_API_DTYPE_INT32   (2)
#define C_API_DTYPE_INT64   (3)

#define C_API_PREDICT_NORMAL     (0)
#define C_API_PREDICT_RAW_SCORE  (1)
#define C_API_PREDICT_LEAF_INDEX (2)
#define C_API_PREDICT_CONTRIB    (3)

LIGHTGBM_C_EXPORT const char* LGBM_GetLastError();

/* unordered_map parameters => C++ linkage, like the fork's header */
LIGHTGBM_CPP_EXPORT int LGBM_DatasetCreateFromCSR(
    const void* indptr, int indptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t nindptr, int64_t nelem,
    int64_t num_col,
    std::unordered_map<std::string, std::string> parameters,
    const DatasetHandle reference, DatasetHandle* out);

LIGHTGBM_C_EXPORT int LGBM_DatasetSetField(DatasetHandle handle,
                                           const char* field_name,
                                           const void* field_data,
                                           int num_element, int type);

LIGHTGBM_C_EXPORT int LGBM_DatasetGetNumData(DatasetHandle handle,
                                             int64_t* out);

LIGHTGBM_C_EXPORT int LGBM_DatasetFree(DatasetHandle handle);

LIGHTGBM_CPP_EXPORT int LGBM_BoosterCreate(
    const DatasetHandle train_data,
    std::unordered_map<std::string, std::string> parameters,
    BoosterHandle* out);

LIGHTGBM_C_EXPORT int LGBM_BoosterFree(BoosterHandle handle);

LIGHTGBM_C_EXPORT int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                                int* is_finished);

/* lightgbm_tpu extension (not in the fork's ABI): run num_iters
 * boosting iterations in fused device dispatches of up to `chunk`
 * whole iterations each.  Replaces an UpdateOneIter loop with one call
 * per retrain window so wall-clock tracks device throughput instead of
 * per-iteration host dispatch latency.  Sets *is_finished to 1 when
 * training stopped early (no more splittable leaves). */
LIGHTGBM_C_EXPORT int LGBM_BoosterUpdateChunked(BoosterHandle handle,
                                                int num_iters, int chunk,
                                                int* is_finished);

LIGHTGBM_C_EXPORT int LGBM_BoosterGetCurrentIteration(
    BoosterHandle handle, int64_t* out_iteration);

LIGHTGBM_C_EXPORT int LGBM_BoosterCalcNumPredict(BoosterHandle handle,
                                                 int num_row,
                                                 int predict_type,
                                                 int num_iteration,
                                                 int64_t* out_len);

LIGHTGBM_CPP_EXPORT int LGBM_BoosterPredictForCSR(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int num_iteration,
    std::unordered_map<std::string, std::string> parameter,
    int64_t* out_len, double* out_result);

LIGHTGBM_C_EXPORT int LGBM_BoosterSaveModel(BoosterHandle handle,
                                            int start_iteration,
                                            int num_iteration,
                                            const char* filename);

/* ---------------------------------------------------------------------
 * Prediction server (lightgbm_tpu extension, not in the fork's ABI):
 * a hot-swap packed-ensemble predictor.  The windowed harness creates
 * ONE server, scores every request window against it, and swaps in
 * each freshly retrained booster — a swap whose padded model shape
 * matches the previous window re-dispatches into already-compiled
 * device programs (zero recompiles at steady state).  The server keeps
 * its own copy of the model, so the booster may be freed after a swap.
 * ------------------------------------------------------------------ */
typedef void* ServeHandle;

/* Recognized parameters: num_iteration_predict (served tree slice),
 * serve_max_batch / serve_max_wait_ms (micro-batch queue). */
LIGHTGBM_CPP_EXPORT int LGBM_ServeCreate(
    const BoosterHandle booster,
    std::unordered_map<std::string, std::string> parameters,
    ServeHandle* out);

LIGHTGBM_C_EXPORT int LGBM_ServeSwap(ServeHandle handle,
                                     const BoosterHandle booster);

LIGHTGBM_C_EXPORT int LGBM_ServeCalcNumPredict(ServeHandle handle,
                                               int num_row,
                                               int64_t* out_len);

/* predict_type: C_API_PREDICT_NORMAL or C_API_PREDICT_RAW_SCORE. */
LIGHTGBM_C_EXPORT int LGBM_ServePredictForCSR(
    ServeHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int64_t* out_len, double* out_result);

LIGHTGBM_C_EXPORT int LGBM_ServeFree(ServeHandle handle);

/* ---------------------------------------------------------------------
 * Model fleet (lightgbm_tpu extension, not in the fork's ABI): M
 * tenants stacked into ONE packed array family — a single jitted
 * program serves any (tenant_ids, rows) batch, and a per-tenant
 * retrain hands off via a zero-retrace device index write while the
 * other tenants keep answering (docs/Serving.md "Model fleets").
 * ------------------------------------------------------------------ */
typedef void* FleetHandle;

/* All num_tenants tenants start as copies of `booster`'s model;
 * specialize them with LGBM_FleetSwapTenant.  Recognized parameters:
 * num_iteration_predict, serve_replicas, fleet_value_dtype,
 * serve_max_batch / serve_max_wait_ms. */
LIGHTGBM_CPP_EXPORT int LGBM_FleetCreate(
    const BoosterHandle booster, int num_tenants,
    std::unordered_map<std::string, std::string> parameters,
    FleetHandle* out);

LIGHTGBM_C_EXPORT int LGBM_FleetSwapTenant(FleetHandle handle,
                                           int tenant_id,
                                           const BoosterHandle booster);

LIGHTGBM_C_EXPORT int LGBM_FleetCalcNumPredict(FleetHandle handle,
                                               int num_row,
                                               int64_t* out_len);

/* tenant_ids routes each CSR row to its tenant; num_tenant_ids == 1
 * broadcasts one tenant to the whole batch.  predict_type:
 * C_API_PREDICT_NORMAL or C_API_PREDICT_RAW_SCORE. */
LIGHTGBM_C_EXPORT int LGBM_FleetPredictForCSR(
    FleetHandle handle, const int32_t* tenant_ids,
    int64_t num_tenant_ids, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int64_t* out_len, double* out_result);

LIGHTGBM_C_EXPORT int LGBM_FleetFree(FleetHandle handle);

/* ---------------------------------------------------------------------
 * AOT compile warmup (lightgbm_tpu extension, not in the fork's ABI):
 * precompile the declared (rows, features, parameters) training /
 * serving program families into the persistent XLA compile cache
 * (parameters key compile_cache_dir, or env LGBM_TPU_COMPILE_CACHE),
 * so a deployment's FIRST real retrain window / first large predict
 * batch runs warm.  Call once at container start, before the request
 * loop; *out_num_compiled returns the number of fresh cache entries
 * written (0 = the cache was already warm for this declaration).
 * num_row <= 0 on WarmupServe warms the prediction server's default
 * row buckets.  See docs/ColdStart.md.
 * ------------------------------------------------------------------ */
LIGHTGBM_CPP_EXPORT int LGBM_WarmupTrain(
    std::unordered_map<std::string, std::string> parameters,
    int64_t num_row, int32_t num_feature, int* out_num_compiled);

LIGHTGBM_CPP_EXPORT int LGBM_WarmupServe(
    std::unordered_map<std::string, std::string> parameters,
    int64_t num_row, int32_t num_feature, int* out_num_compiled);

#endif  /* LIGHTGBM_TPU_C_API_H_ */
