"""Objective functions: gradients/hessians on device.

Factory mirrors the reference ``CreateObjectiveFunction``
(``src/objective/objective_function.cpp:1-88``).
"""

from .base import ObjectiveFunction
from .regression import (RegressionL2, RegressionL1, Huber, Fair, Poisson,
                         Quantile, Mape, Gamma, Tweedie)
from .binary import BinaryLogloss
from .multiclass import MulticlassSoftmax, MulticlassOVA
from .xentropy import CrossEntropy, CrossEntropyLambda
from .rank import LambdarankNDCG

_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": Mape,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config):
    name = config.objective
    if name in ("none", "null", "custom", "na"):
        return None
    cls = _REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown objective: {name}")
    return cls(config)
