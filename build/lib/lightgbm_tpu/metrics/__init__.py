"""Evaluation metrics (reference ``src/metric/``, factory ``metric.cpp:1-58``).

Host-side numpy implementations; scores arrive as (num_model, N) float64 raw
scores, objectives provide the output transformation where the reference does
(sigmoid / exp / softmax).  Every metric exposes ``bigger_is_better`` used by
early stopping (gbdt.cpp:518).
"""

from __future__ import annotations

import numpy as np

from ..utils.log import LightGBMError, log_warning


class Metric:
    name = "metric"
    bigger_is_better = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data):
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float64) \
            if metadata.label is not None else np.zeros(num_data)
        self.weights = (np.asarray(metadata.weights, np.float64)
                        if metadata.weights is not None else None)
        self.sum_weights = (float(self.weights.sum())
                            if self.weights is not None else float(num_data))
        self.metadata = metadata

    def eval(self, score, objective):
        """score: (num_model, N) raw; returns [(name, value)]."""
        raise NotImplementedError

    def _avg(self, losses):
        if self.weights is None:
            return float(np.mean(losses))
        return float(np.sum(losses * self.weights) / self.sum_weights)


def _convert(score, objective):
    if objective is not None:
        return objective.convert_output(score)
    return score


# ---------------------------------------------------------------------------
# regression metrics (regression_metric.hpp:108-300)
# ---------------------------------------------------------------------------

class _PointwiseMetric(Metric):
    def eval(self, score, objective):
        pred = _convert(score[0], objective)
        return [(self.name, self._point(pred))]

    def _point(self, pred):
        raise NotImplementedError


class L2Metric(_PointwiseMetric):
    name = "l2"

    def _point(self, pred):
        return self._avg((pred - self.label) ** 2)


class RMSEMetric(_PointwiseMetric):
    name = "rmse"

    def _point(self, pred):
        return float(np.sqrt(self._avg((pred - self.label) ** 2)))


class L1Metric(_PointwiseMetric):
    name = "l1"

    def _point(self, pred):
        return self._avg(np.abs(pred - self.label))


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def _point(self, pred):
        a = float(self.config.alpha)
        d = self.label - pred
        return self._avg(np.where(d >= 0, a * d, (a - 1) * d))


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def _point(self, pred):
        a = float(self.config.alpha)
        d = np.abs(pred - self.label)
        return self._avg(np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a)))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def _point(self, pred):
        c = float(self.config.fair_c)
        x = np.abs(pred - self.label)
        return self._avg(c * c * (x / c - np.log1p(x / c)))


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def _point(self, pred):
        eps = 1e-10
        p = np.maximum(pred, eps)
        return self._avg(p - self.label * np.log(p))


class MapeMetric(_PointwiseMetric):
    name = "mape"

    def _point(self, pred):
        return self._avg(np.abs((self.label - pred)
                                / np.maximum(1.0, np.abs(self.label))))


class GammaMetric(_PointwiseMetric):
    """Gamma NLL with unit shape: label/score + log(score)
    (regression_metric.hpp GammaMetric::LossOnPoint)."""

    name = "gamma"

    def _point(self, pred):
        x = np.maximum(pred, 1e-10)
        return self._avg(self.label / x + np.log(x))


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def _point(self, pred):
        ratio = self.label / (pred + 1e-9)
        return self._avg(ratio - np.log(np.maximum(ratio, 1e-300)) - 1.0)


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def _point(self, pred):
        rho = float(self.config.tweedie_variance_power)
        eps = 1e-10
        p = np.maximum(pred, eps)
        a = self.label * np.power(p, 1.0 - rho) / (1.0 - rho)
        b = np.power(p, 2.0 - rho) / (2.0 - rho)
        return self._avg(-a + b)


# ---------------------------------------------------------------------------
# binary metrics (binary_metric.hpp)
# ---------------------------------------------------------------------------

class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def _point(self, prob):
        eps = 1e-15
        p = np.clip(prob, eps, 1 - eps)
        y = (self.label > 0)
        return self._avg(np.where(y, -np.log(p), -np.log(1 - p)))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def _point(self, prob):
        y = (self.label > 0)
        pred_pos = prob > 0.5
        return self._avg((pred_pos != y).astype(np.float64))


class AUCMetric(Metric):
    """Weighted AUC via rank-sum over descending predictions
    (binary_metric.hpp:157-266)."""

    name = "auc"
    bigger_is_better = True

    def eval(self, score, objective):
        pred = score[0]          # AUC is monotone-invariant: raw score is fine
        y = (self.label > 0)
        w = self.weights if self.weights is not None \
            else np.ones_like(pred)
        order = np.argsort(pred, kind="mergesort")
        ys, ws, ps = y[order], w[order], pred[order]
        # handle ties: group by identical prediction
        cum_pos = 0.0
        cum_neg = 0.0
        auc = 0.0
        i = 0
        n = len(ps)
        while i < n:
            j = i
            tie_pos = 0.0
            tie_neg = 0.0
            while j < n and ps[j] == ps[i]:
                if ys[j]:
                    tie_pos += ws[j]
                else:
                    tie_neg += ws[j]
                j += 1
            auc += tie_pos * (cum_neg + tie_neg * 0.5)
            cum_pos += tie_pos
            cum_neg += tie_neg
            i = j
        denom = cum_pos * cum_neg
        return [(self.name, float(auc / denom) if denom > 0 else 1.0)]


# ---------------------------------------------------------------------------
# multiclass metrics (multiclass_metric.hpp)
# ---------------------------------------------------------------------------

class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective):
        prob = _convert(score, objective)      # (K, N)
        eps = 1e-15
        li = self.label.astype(np.int64)
        p = np.clip(prob[li, np.arange(len(li))], eps, None)
        return [(self.name, self._avg(-np.log(p)))]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective):
        li = self.label.astype(np.int64)
        pred = np.argmax(score, axis=0)
        return [(self.name, self._avg((pred != li).astype(np.float64)))]


# ---------------------------------------------------------------------------
# cross-entropy metrics (xentropy_metric.hpp)
# ---------------------------------------------------------------------------

class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def _point(self, prob):
        eps = 1e-15
        p = np.clip(prob, eps, 1 - eps)
        y = self.label
        return self._avg(-(y * np.log(p) + (1 - y) * np.log(1 - p)))


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective):
        # loss on point with hhat = log1p(exp(f)) (xentropy_metric.hpp)
        f = score[0]
        hhat = np.log1p(np.exp(f))
        y = self.label
        w = self.weights if self.weights is not None else 1.0
        z = 1.0 - np.exp(-w * hhat)
        eps = 1e-15
        z = np.clip(z, eps, 1 - eps)
        loss = -(y * np.log(z) + (1 - y) * np.log(1 - z))
        return [(self.name, float(np.mean(loss)))]


class KLDivMetric(_PointwiseMetric):
    name = "kullback_leibler"

    def _point(self, prob):
        eps = 1e-15
        p = np.clip(prob, eps, 1 - eps)
        y = np.clip(self.label, eps, 1 - eps)
        return self._avg(y * np.log(y / p)
                         + (1 - y) * np.log((1 - y) / (1 - p)))


# ---------------------------------------------------------------------------
# ranking metrics (rank_metric.hpp, map_metric.hpp, dcg_calculator.cpp)
# ---------------------------------------------------------------------------

class NDCGMetric(Metric):
    name = "ndcg"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise LightGBMError("The NDCG metric requires query information")
        self.qb = metadata.query_boundaries
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        gains = list(self.config.label_gain or [])
        if not gains:
            gains = [float((1 << i) - 1) for i in range(31)]
        self.gains = np.asarray(gains, np.float64)
        self.query_weights = metadata.query_weights

    def eval(self, score, objective):
        pred = score[0]
        ks = self.eval_at
        nq = len(self.qb) - 1
        res = np.zeros((len(ks), nq))
        for q in range(nq):
            lo, hi = self.qb[q], self.qb[q + 1]
            labels = self.label[lo:hi].astype(np.int64)
            order = np.argsort(-pred[lo:hi], kind="stable")
            sorted_gain = self.gains[labels[order]]
            ideal_gain = np.sort(self.gains[labels])[::-1]
            disc = 1.0 / np.log2(np.arange(2, 2 + hi - lo))
            for ki, k in enumerate(ks):
                kk = min(k, hi - lo)
                maxdcg = float((ideal_gain[:kk] * disc[:kk]).sum())
                if maxdcg <= 0.0:
                    res[ki, q] = 1.0
                else:
                    dcg = float((sorted_gain[:kk] * disc[:kk]).sum())
                    res[ki, q] = dcg / maxdcg
        if self.query_weights is not None:
            qw = np.asarray(self.query_weights, np.float64)
            vals = (res * qw).sum(axis=1) / qw.sum()
        else:
            vals = res.mean(axis=1)
        return [(f"ndcg@{k}", float(v)) for k, v in zip(ks, vals)]


class MapMetric(Metric):
    name = "map"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise LightGBMError("The MAP metric requires query information")
        self.qb = metadata.query_boundaries
        self.eval_at = [int(k) for k in (self.config.eval_at or [1, 2, 3, 4, 5])]
        self.query_weights = metadata.query_weights

    def eval(self, score, objective):
        pred = score[0]
        ks = self.eval_at
        nq = len(self.qb) - 1
        res = np.zeros((len(ks), nq))
        for q in range(nq):
            lo, hi = self.qb[q], self.qb[q + 1]
            rel = (self.label[lo:hi] > 0)
            order = np.argsort(-pred[lo:hi], kind="stable")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            prec = hits / np.arange(1, hi - lo + 1)
            for ki, k in enumerate(ks):
                kk = min(k, hi - lo)
                nrel = int(rel_sorted[:kk].sum())
                if nrel == 0:
                    res[ki, q] = 1.0 if rel.sum() == 0 else 0.0
                else:
                    res[ki, q] = float(
                        (prec[:kk] * rel_sorted[:kk]).sum() / nrel)
        if self.query_weights is not None:
            qw = np.asarray(self.query_weights, np.float64)
            vals = (res * qw).sum(axis=1) / qw.sum()
        else:
            vals = res.mean(axis=1)
        return [(f"map@{k}", float(v)) for k, v in zip(ks, vals)]


# ---------------------------------------------------------------------------

_REGISTRY = {
    "l1": L1Metric,
    "l2": L2Metric,
    "rmse": RMSEMetric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MapeMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "multi_logloss": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KLDivMetric,
    "ndcg": NDCGMetric,
    "map": MapMetric,
}


def create_metric(name, config):
    cls = _REGISTRY.get(name)
    if cls is None:
        raise LightGBMError(f"unknown metric: {name}")
    return cls(config)


def create_metrics(config):
    return [create_metric(m, config) for m in config.metric]
