"""Device-side ops: histogram construction, best-split scan, partition.

These are the TPU-native replacements for the reference's hot loops
(``src/io/dense_bin.hpp:106-175`` histogram gather,
``src/treelearner/feature_histogram.hpp`` threshold scans,
``src/treelearner/data_partition.hpp`` stable partition) — formulated as
large batched matmuls / prefix scans / sorts that XLA tiles onto the MXU and
VPU instead of scalar loops with atomics.
"""

from .histogram import build_histogram, subtract_histogram  # noqa: F401
from .split import SplitContext, find_best_split  # noqa: F401
from .partition import partition_leaf, goes_left_matrix  # noqa: F401
