from .tree import Tree  # noqa: F401
from .learner import SerialTreeLearner  # noqa: F401
