"""Boosting layer (reference ``src/boosting/``).

Factory mirrors ``Boosting::CreateBoosting`` (boosting.cpp:30-64).
"""

from .gbdt import GBDT
from .dart import DART
from .goss import GOSS
from .rf import RF


def create_boosting(config):
    name = config.boosting
    if name == "gbdt":
        return GBDT(config)
    if name == "dart":
        return DART(config)
    if name == "goss":
        return GOSS(config)
    if name == "rf":
        return RF(config)
    raise ValueError(f"unknown boosting type: {name}")
