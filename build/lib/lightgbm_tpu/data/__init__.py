from .binning import BinMapper
from .dataset import BinnedDataset, FeatureGroupInfo, Metadata

__all__ = ["BinMapper", "BinnedDataset", "FeatureGroupInfo", "Metadata"]
