from .log import (
    LightGBMError,
    Timer,
    log_debug,
    log_fatal,
    log_info,
    log_warning,
    register_log_callback,
    set_verbosity,
)
from .random import derive_seeds, make_rng, sample_k

__all__ = [
    "LightGBMError", "Timer", "log_debug", "log_fatal", "log_info",
    "log_warning", "register_log_callback", "set_verbosity",
    "derive_seeds", "make_rng", "sample_k",
]
