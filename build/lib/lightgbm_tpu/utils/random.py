"""Deterministic host-side RNG helpers.

The reference uses a tiny xorshift-style ``Random`` (utils/random.h) for
bagging / feature-fraction / bundling so results are reproducible per seed.
We use numpy Generators seeded deterministically instead — same guarantees
(deterministic per seed), idiomatic host code.  Device-side sampling (GOSS,
DART masks, bagging masks when fused) uses jax.random with keys derived from
the same master seed.
"""

from __future__ import annotations

import numpy as np


def make_rng(seed: int) -> np.random.Generator:
    return np.random.default_rng(np.uint64(seed & 0xFFFFFFFFFFFFFFFF))


def sample_k(rng: np.random.Generator, n: int, k: int) -> np.ndarray:
    """Sample k distinct indices from range(n), sorted (reference Random::Sample)."""
    k = max(0, min(k, n))
    if k == 0:
        return np.empty(0, dtype=np.int32)
    idx = rng.choice(n, size=k, replace=False)
    idx.sort()
    return idx.astype(np.int32)


def derive_seeds(master_seed: int):
    """Derive sub-seeds for each consumer from one master seed.

    Mirrors the reference Config behaviour where ``seed`` overrides
    data_random_seed / feature_fraction_seed / bagging_seed / drop_seed
    deterministically.
    """
    ss = np.random.SeedSequence(master_seed)
    children = ss.spawn(5)
    names = ("data", "feature_fraction", "bagging", "drop", "objective")
    return {n: int(c.generate_state(1)[0]) for n, c in zip(names, children)}
