"""Logging with LightGBM-style levels (reference: utils/log.h:1-105).

Levels: Fatal < Warning < Info < Debug.  ``log_fatal`` raises, matching the
reference where ``Log::Fatal`` throws ``std::runtime_error``.  Verbosity is
controlled globally via :func:`set_verbosity` (config param ``verbosity``:
<0 fatal only, 0 warning, 1 info, >=2 debug).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Optional

_FATAL, _WARNING, _INFO, _DEBUG = -1, 0, 1, 2
_verbosity = _INFO
_callback: Optional[Callable[[str], None]] = None


class LightGBMError(RuntimeError):
    """Error raised by the framework (reference: Log::Fatal throw)."""


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level


def get_verbosity() -> int:
    return _verbosity


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    """Redirect log output (reference: R callback redirection)."""
    global _callback
    _callback = cb


def _emit(msg: str) -> None:
    if _callback is not None:
        _callback(msg + "\n")
    else:
        sys.stderr.write(msg + "\n")
        sys.stderr.flush()


def log_debug(msg: str) -> None:
    if _verbosity >= _DEBUG:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= _INFO:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= _WARNING:
        _emit(f"[LightGBM-TPU] [Warning] {msg}")


def log_fatal(msg: str) -> None:
    raise LightGBMError(msg)


class Timer:
    """Accumulating per-phase wall-clock timer.

    First-class version of the reference's compile-time TIMETAG counters
    (``serial_tree_learner.cpp:14-41``): ``timer.start("hist")`` /
    ``timer.stop("hist")`` accumulate, ``timer.report()`` pretty-prints.

    With ``sync=True`` the :meth:`stop_sync` variant blocks on the device
    value before stopping the clock, so phase times attribute device work to
    the phase that dispatched it (JAX dispatch is async; without syncing,
    device time piles up at the next host fetch).  Leave ``sync=False`` in
    production — blocking per phase serialises the device pipeline.
    """

    def __init__(self):
        self.acc = {}
        self.counts = {}
        self._t0 = {}
        self.sync = False

    def start(self, tag: str) -> None:
        self._t0[tag] = time.perf_counter()

    def stop(self, tag: str) -> None:
        t0 = self._t0.pop(tag, None)
        if t0 is not None:
            self.acc[tag] = self.acc.get(tag, 0.0) + time.perf_counter() - t0
            self.counts[tag] = self.counts.get(tag, 0) + 1

    def stop_sync(self, tag: str, value=None):
        """Stop after blocking on ``value`` when ``sync`` profiling is on."""
        if self.sync and value is not None:
            import jax
            jax.block_until_ready(value)
        self.stop(tag)
        return value

    def report(self) -> str:
        return ", ".join(f"{k}={v:.3f}s" for k, v in sorted(self.acc.items()))

    def reset(self) -> None:
        self.acc.clear()
        self.counts.clear()
        self._t0.clear()


#: process-global training-phase timer (wired through the tree learner and
#: the boosting loop; ``bench.py`` reads and resets it)
TRAIN_TIMER = Timer()
