/*!
 * test.cpp-shaped smoke harness: proves C++ code compiled against
 * include/lightgbm_tpu/c_api.h trains and predicts through the native
 * ABI the way the fork's cache-admission harness does
 * (/root/reference/src/test.cpp:243-298 trainModel / evaluateModel).
 *
 * Builds a synthetic windowed CSR matrix with the fork's feature layout
 * (HISTFEATURES gap features + size + cacheAvail + cost), trains a
 * binary booster per window (fresh booster for the second window, like
 * the fork's "train a new booster" branch), predicts the next window,
 * and checks the outputs are sane probabilities.  Exit 0 = pass.
 */
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

#include "../../include/lightgbm_tpu/c_api.h"

#define HISTFEATURES 50

static std::unordered_map<std::string, std::string> trainParams = {
    {"boosting", "gbdt"},          {"objective", "binary"},
    {"max_bin", "255"},            {"num_iterations", "8"},
    {"learning_rate", "0.1"},      {"num_leaves", "31"},
    {"tree_learner", "serial"},    {"feature_fraction", "0.8"},
    {"bagging_freq", "5"},         {"bagging_fraction", "0.8"},
    {"min_data_in_leaf", "50"},    {"min_sum_hessian_in_leaf", "5.0"},
    {"verbosity", "-1"},
};

/* synthetic window: gap features correlated with the label, like
 * deriveFeatures' output shape (test.cpp:125-209) */
static void make_window(int rows, unsigned seed, std::vector<float>* labels,
                        std::vector<int32_t>* indptr,
                        std::vector<int32_t>* indices,
                        std::vector<double>* data) {
  std::mt19937 gen(seed);
  std::uniform_real_distribution<> uni(0.0, 1.0);
  std::uniform_int_distribution<> nhist(1, HISTFEATURES);
  indptr->push_back(0);
  for (int i = 0; i < rows; i++) {
    const bool hot = uni(gen) < 0.4;
    labels->push_back(hot ? 1.0f : 0.0f);
    const int k = nhist(gen);
    int32_t idx = 0;
    for (; idx < k; idx++) {
      const double base = hot ? 200.0 : 20000.0;
      indices->push_back(idx);
      data->push_back(base * (0.5 + uni(gen)));
    }
    indices->push_back(HISTFEATURES);
    data->push_back(std::round(100.0 * std::log2(64.0 + 4096.0 * uni(gen))));
    indices->push_back(HISTFEATURES + 1);
    data->push_back(std::round(100.0 * std::log2(1 << 30)));
    indices->push_back(HISTFEATURES + 2);
    data->push_back(1.0);
    indptr->push_back(indptr->back() + idx + 3);
  }
}

static int check(int rc, const char* what) {
  if (rc != 0) {
    std::fprintf(stderr, "FAIL %s: %s\n", what, LGBM_GetLastError());
    std::exit(1);
  }
  return rc;
}

int main() {
  const int rows = 4000;
  BoosterHandle booster = nullptr;
  ServeHandle server = nullptr;
  bool init = true;

  /* deployment-init AOT warmup (docs/ColdStart.md): precompile the
   * declared training + serving program families before the window
   * loop.  With LGBM_TPU_COMPILE_CACHE set this persists executables so
   * a RESTARTED harness starts warm; without it it still front-loads
   * the in-process compiles. */
  int warmed = -1;
  check(LGBM_WarmupTrain(trainParams, rows, HISTFEATURES + 3, &warmed),
        "WarmupTrain");
  std::printf("warmup: train programs compiled (%d fresh cache entries)\n",
              warmed);
  check(LGBM_WarmupServe(trainParams, 4096, HISTFEATURES + 3, &warmed),
        "WarmupServe");
  std::printf("warmup: serve programs compiled (%d fresh cache entries)\n",
              warmed);

  for (int window = 0; window < 2; window++) {
    std::vector<float> labels;
    std::vector<int32_t> indptr, indices;
    std::vector<double> data;
    make_window(rows, 7 + window, &labels, &indptr, &indices, &data);

    auto t0 = std::chrono::system_clock::now();
    DatasetHandle trainData;
    check(LGBM_DatasetCreateFromCSR(
              static_cast<void*>(indptr.data()), C_API_DTYPE_INT32,
              indices.data(), static_cast<void*>(data.data()),
              C_API_DTYPE_FLOAT64, indptr.size(), data.size(),
              HISTFEATURES + 3, trainParams, nullptr, &trainData),
          "DatasetCreateFromCSR");
    check(LGBM_DatasetSetField(trainData, "label",
                               static_cast<void*>(labels.data()),
                               labels.size(), C_API_DTYPE_FLOAT32),
          "DatasetSetField");
    int64_t ndata = 0;
    check(LGBM_DatasetGetNumData(trainData, &ndata), "GetNumData");
    if (ndata != rows) {
      std::fprintf(stderr, "FAIL num_data %lld != %d\n",
                   static_cast<long long>(ndata), rows);
      return 1;
    }

    /* fork pattern: first window trains `booster`; later windows train
     * a NEW booster and swap (test.cpp:256-293) */
    BoosterHandle target;
    check(LGBM_BoosterCreate(trainData, trainParams, &target),
          "BoosterCreate");
    /* fused driver: the whole window's iterations in chunked device
     * dispatches (falls back per-iteration when not eligible) */
    {
      int isFinished;
      check(LGBM_BoosterUpdateChunked(
                target, std::stoi(trainParams["num_iterations"]),
                /*chunk=*/10, &isFinished),
            "UpdateChunked");
    }
    /* serving hand-off: window 0 creates the prediction server, later
     * windows atomically swap in the freshly trained model (the server
     * keeps its own packed copy, so the old booster frees safely) */
    if (server == nullptr) {
      check(LGBM_ServeCreate(target, trainParams, &server), "ServeCreate");
    } else {
      check(LGBM_ServeSwap(server, target), "ServeSwap");
    }
    if (!init) {
      check(LGBM_BoosterFree(booster), "BoosterFree(old)");
    }
    booster = target;
    init = false;

    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  std::chrono::system_clock::now() - t0)
                  .count();
    std::printf("window %d: trained %d rows in %lld ms\n", window, rows,
                static_cast<long long>(ms));

    /* evaluateModel pattern: predict the window through the booster */
    int64_t len = 0;
    check(LGBM_BoosterCalcNumPredict(booster, rows, C_API_PREDICT_NORMAL,
                                     0, &len),
          "CalcNumPredict");
    std::vector<double> result(len);
    check(LGBM_BoosterPredictForCSR(
              booster, static_cast<void*>(indptr.data()),
              C_API_DTYPE_INT32, indices.data(),
              static_cast<void*>(data.data()), C_API_DTYPE_FLOAT64,
              indptr.size(), data.size(), HISTFEATURES + 3,
              C_API_PREDICT_NORMAL, 0, trainParams, &len, result.data()),
          "PredictForCSR");
    if (len != rows) {
      std::fprintf(stderr, "FAIL predict len %lld != %d\n",
                   static_cast<long long>(len), rows);
      return 1;
    }
    int correct = 0;
    for (int i = 0; i < rows; i++) {
      if (result[i] < 0.0 || result[i] > 1.0 || result[i] != result[i]) {
        std::fprintf(stderr, "FAIL prob out of range: %f\n", result[i]);
        return 1;
      }
      if ((result[i] >= 0.5) == (labels[i] >= 0.5f)) correct++;
    }
    const double acc = static_cast<double>(correct) / rows;
    std::printf("window %d: train accuracy %.3f\n", window, acc);
    if (acc < 0.75) {
      std::fprintf(stderr, "FAIL accuracy %.3f < 0.75 — the planted "
                           "signal was not learned\n", acc);
      return 1;
    }

    /* the packed-ensemble server must agree with the booster walk
     * (float32 device accumulation => small value tolerance) */
    int64_t slen = 0;
    check(LGBM_ServeCalcNumPredict(server, rows, &slen),
          "ServeCalcNumPredict");
    std::vector<double> sresult(slen);
    check(LGBM_ServePredictForCSR(
              server, static_cast<void*>(indptr.data()),
              C_API_DTYPE_INT32, indices.data(),
              static_cast<void*>(data.data()), C_API_DTYPE_FLOAT64,
              indptr.size(), data.size(), HISTFEATURES + 3,
              C_API_PREDICT_NORMAL, &slen, sresult.data()),
          "ServePredictForCSR");
    if (slen != rows) {
      std::fprintf(stderr, "FAIL serve predict len %lld != %d\n",
                   static_cast<long long>(slen), rows);
      return 1;
    }
    for (int i = 0; i < rows; i++) {
      if (std::fabs(sresult[i] - result[i]) > 1e-4) {
        std::fprintf(stderr,
                     "FAIL serve/booster mismatch at %d: %f vs %f\n", i,
                     sresult[i], result[i]);
        return 1;
      }
    }
    std::printf("window %d: serve predict matches booster\n", window);

    /* model fleet: 2 tenants seeded/swapped from the same booster must
     * answer a mixed-tenant batch exactly like the solo server */
    if (window == 1) {
      FleetHandle fleet = nullptr;
      check(LGBM_FleetCreate(booster, 2, trainParams, &fleet),
            "FleetCreate");
      check(LGBM_FleetSwapTenant(fleet, 1, booster), "FleetSwapTenant");
      std::vector<int32_t> tenantIds(rows);
      for (int i = 0; i < rows; i++) tenantIds[i] = i % 2;
      int64_t flen = 0;
      check(LGBM_FleetCalcNumPredict(fleet, rows, &flen),
            "FleetCalcNumPredict");
      std::vector<double> fresult(flen);
      check(LGBM_FleetPredictForCSR(
                fleet, tenantIds.data(), rows,
                static_cast<void*>(indptr.data()), C_API_DTYPE_INT32,
                indices.data(), static_cast<void*>(data.data()),
                C_API_DTYPE_FLOAT64, indptr.size(), data.size(),
                HISTFEATURES + 3, C_API_PREDICT_NORMAL, &flen,
                fresult.data()),
            "FleetPredictForCSR");
      for (int i = 0; i < rows; i++) {
        if (std::fabs(fresult[i] - sresult[i]) > 1e-12) {
          std::fprintf(stderr,
                       "FAIL fleet/serve mismatch at %d: %f vs %f\n",
                       i, fresult[i], sresult[i]);
          return 1;
        }
      }
      check(LGBM_FleetFree(fleet), "FleetFree");
      std::printf("window %d: fleet predict matches serve\n", window);
    }
    check(LGBM_DatasetFree(trainData), "DatasetFree");
  }
  check(LGBM_BoosterSaveModel(booster, 0, -1, "/tmp/lgbm_capi_smoke.model"),
        "SaveModel");
  check(LGBM_ServeFree(server), "ServeFree");
  check(LGBM_BoosterFree(booster), "BoosterFree");
  std::printf("native ABI smoke: PASS\n");
  return 0;
}
