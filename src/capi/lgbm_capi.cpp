/*!
 * Native LGBM_* ABI for lightgbm_tpu: a thin C++ layer that embeds
 * CPython and forwards every call to ``lightgbm_tpu.capi_embed``.
 *
 * Design: the TPU runtime (JAX/XLA dispatch, binning, boosting) lives
 * in Python; this library provides the fork-compatible link surface
 * (reference /root/reference/include/LightGBM/c_api.h, impl
 * /root/reference/src/c_api.cpp:47-380) so that test.cpp-shaped C++
 * harnesses train against the framework without a Python toplevel.
 * Caller buffers cross the boundary as memoryviews — no copies on the
 * C++ side; predictions are written straight into the caller's array.
 *
 * Environment: set LGBM_TPU_PYROOT to the repo/package root if
 * lightgbm_tpu is not importable from the default sys.path.
 */
#include <Python.h>

#include <cstdint>
#include <mutex>   /* std::call_once */
#include <string>
#include <unordered_map>

#include "../../include/lightgbm_tpu/c_api.h"

namespace {

/* thread-local like the reference's error buffer: the returned pointer
 * stays valid for the calling thread regardless of other threads'
 * failures */
thread_local std::string g_last_error = "everything is fine";

void set_last_error(const std::string& msg) { g_last_error = msg; }

/* Initialize the interpreter once; release the GIL so every API entry
 * can use PyGILState_Ensure regardless of calling thread. */
void ensure_python() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

/* RAII GIL hold. */
struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

PyObject* adapter_module() {
  static PyObject* mod = nullptr;
  if (mod == nullptr) {
    PyRun_SimpleString(
        "import sys, os\n"
        "_p = os.environ.get('LGBM_TPU_PYROOT')\n"
        "if _p and _p not in sys.path:\n"
        "    sys.path.insert(0, _p)\n");
    mod = PyImport_ImportModule("lightgbm_tpu.capi_embed");
  }
  return mod;
}

std::string py_error_string() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return msg;
}

/* Call adapter ``fn`` with an argument tuple (reference stolen).
 * Returns the result object or nullptr (error recorded). */
PyObject* call_adapter(const char* fn, PyObject* args) {
  PyObject* mod = adapter_module();
  if (mod == nullptr) {
    set_last_error("cannot import lightgbm_tpu.capi_embed: "
                   + py_error_string());
    Py_XDECREF(args);
    return nullptr;
  }
  if (args == nullptr) {
    set_last_error("argument marshalling failed: " + py_error_string());
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  if (f == nullptr) {
    set_last_error(std::string("missing adapter: ") + fn);
    Py_DECREF(args);
    return nullptr;
  }
  PyObject* res = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_DECREF(args);
  if (res == nullptr) {
    set_last_error(std::string(fn) + ": " + py_error_string());
    return nullptr;
  }
  return res;
}

PyObject* mv_read(const void* ptr, Py_ssize_t nbytes) {
  return PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<void*>(ptr)), nbytes,
      PyBUF_READ);
}

PyObject* mv_write(void* ptr, Py_ssize_t nbytes) {
  return PyMemoryView_FromMemory(reinterpret_cast<char*>(ptr), nbytes,
                                 PyBUF_WRITE);
}

Py_ssize_t dtype_size(int dtype) {
  switch (dtype) {
    case C_API_DTYPE_FLOAT32: return 4;
    case C_API_DTYPE_FLOAT64: return 8;
    case C_API_DTYPE_INT32:   return 4;
    case C_API_DTYPE_INT64:   return 8;
    default:                  return 0;
  }
}

/* map -> the c_api.py "k1=v1 k2=v2" parameter string */
std::string params_string(
    const std::unordered_map<std::string, std::string>& params) {
  std::string out;
  for (const auto& kv : params) {
    if (!out.empty()) out += " ";
    out += kv.first + "=" + kv.second;
  }
  return out;
}

int handle_result(PyObject* res, void** out) {
  if (res == nullptr) return -1;
  if (out != nullptr) {
    *out = reinterpret_cast<void*>(
        static_cast<intptr_t>(PyLong_AsLongLong(res)));
  }
  Py_DECREF(res);
  return 0;
}

int int_result(PyObject* res, int64_t* out) {
  if (res == nullptr) return -1;
  if (out != nullptr) *out = PyLong_AsLongLong(res);
  Py_DECREF(res);
  return 0;
}

int none_result(PyObject* res) {
  if (res == nullptr) return -1;
  Py_DECREF(res);
  return 0;
}

int64_t as_id(const void* handle) {
  return static_cast<int64_t>(reinterpret_cast<intptr_t>(handle));
}

}  // namespace

extern "C" const char* LGBM_GetLastError() {
  return g_last_error.c_str();
}

int LGBM_DatasetCreateFromCSR(
    const void* indptr, int indptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t nindptr, int64_t nelem,
    int64_t num_col,
    std::unordered_map<std::string, std::string> parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(NiNNiLLLsL)",
      mv_read(indptr, nindptr * dtype_size(indptr_type)), indptr_type,
      mv_read(indices, nelem * 4),
      mv_read(data, nelem * dtype_size(data_type)), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), params_string(parameters).c_str(),
      static_cast<long long>(as_id(reference)));
  return handle_result(call_adapter("dataset_from_csr", args), out);
}

extern "C" int LGBM_DatasetSetField(DatasetHandle handle,
                                    const char* field_name,
                                    const void* field_data,
                                    int num_element, int type) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(LsNii)", static_cast<long long>(as_id(handle)), field_name,
      mv_read(field_data, num_element * dtype_size(type)), num_element,
      type);
  return none_result(call_adapter("dataset_set_field", args));
}

extern "C" int LGBM_DatasetGetNumData(DatasetHandle handle,
                                      int64_t* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  return int_result(call_adapter("dataset_num_data", args), out);
}

extern "C" int LGBM_DatasetFree(DatasetHandle handle) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  return none_result(call_adapter("dataset_free", args));
}

int LGBM_BoosterCreate(
    const DatasetHandle train_data,
    std::unordered_map<std::string, std::string> parameters,
    BoosterHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Ls)", static_cast<long long>(as_id(train_data)),
      params_string(parameters).c_str());
  return handle_result(call_adapter("booster_create", args), out);
}

extern "C" int LGBM_BoosterFree(BoosterHandle handle) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  return none_result(call_adapter("booster_free", args));
}

extern "C" int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                         int* is_finished) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  int64_t fin = 0;
  int rc = int_result(call_adapter("booster_update_one_iter", args),
                      &fin);
  if (rc == 0 && is_finished != nullptr) {
    *is_finished = static_cast<int>(fin);
  }
  return rc;
}

extern "C" int LGBM_BoosterUpdateChunked(BoosterHandle handle,
                                         int num_iters, int chunk,
                                         int* is_finished) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Lii)", static_cast<long long>(as_id(handle)), num_iters, chunk);
  int64_t fin = 0;
  int rc = int_result(call_adapter("booster_update_chunked", args), &fin);
  if (rc == 0 && is_finished != nullptr) {
    *is_finished = static_cast<int>(fin);
  }
  return rc;
}

extern "C" int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                               int64_t* out_iteration) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  return int_result(call_adapter("booster_current_iteration", args),
                    out_iteration);
}

extern "C" int LGBM_BoosterCalcNumPredict(BoosterHandle handle,
                                          int num_row, int predict_type,
                                          int num_iteration,
                                          int64_t* out_len) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Liii)", static_cast<long long>(as_id(handle)), num_row,
      predict_type, num_iteration);
  return int_result(call_adapter("booster_calc_num_predict", args),
                    out_len);
}

int LGBM_BoosterPredictForCSR(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int num_iteration,
    std::unordered_map<std::string, std::string> parameter,
    int64_t* out_len, double* out_result) {
  ensure_python();
  Gil gil;
  /* the caller pre-allocated out_result to CalcNumPredict's length */
  int64_t out_cap = 0;
  {
    PyObject* cargs = Py_BuildValue(
        "(Liii)", static_cast<long long>(as_id(handle)),
        static_cast<int>(nindptr - 1), predict_type, num_iteration);
    if (int_result(call_adapter("booster_calc_num_predict", cargs),
                   &out_cap) != 0) {
      return -1;
    }
  }
  PyObject* args = Py_BuildValue(
      "(LNiNNiLLLiisN)", static_cast<long long>(as_id(handle)),
      mv_read(indptr, nindptr * dtype_size(indptr_type)), indptr_type,
      mv_read(indices, nelem * 4),
      mv_read(data, nelem * dtype_size(data_type)), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type, num_iteration,
      params_string(parameter).c_str(),
      mv_write(out_result, out_cap * 8));
  return int_result(call_adapter("booster_predict_for_csr", args),
                    out_len);
}

extern "C" int LGBM_BoosterSaveModel(BoosterHandle handle,
                                     int start_iteration,
                                     int num_iteration,
                                     const char* filename) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Liis)", static_cast<long long>(as_id(handle)), start_iteration,
      num_iteration, filename);
  return none_result(call_adapter("booster_save_model", args));
}

/* ------------------------------------------------------------------ */
/* Prediction server (lightgbm_tpu extension)                          */
/* ------------------------------------------------------------------ */

int LGBM_ServeCreate(
    const BoosterHandle booster,
    std::unordered_map<std::string, std::string> parameters,
    ServeHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Ls)", static_cast<long long>(as_id(booster)),
      params_string(parameters).c_str());
  return handle_result(call_adapter("serve_create", args), out);
}

extern "C" int LGBM_ServeSwap(ServeHandle handle,
                              const BoosterHandle booster) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(LL)", static_cast<long long>(as_id(handle)),
      static_cast<long long>(as_id(booster)));
  return none_result(call_adapter("serve_swap", args));
}

extern "C" int LGBM_ServeCalcNumPredict(ServeHandle handle, int num_row,
                                        int64_t* out_len) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Li)", static_cast<long long>(as_id(handle)), num_row);
  return int_result(call_adapter("serve_calc_num_predict", args),
                    out_len);
}

extern "C" int LGBM_ServePredictForCSR(
    ServeHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int64_t* out_len, double* out_result) {
  ensure_python();
  Gil gil;
  /* the caller pre-allocated out_result to ServeCalcNumPredict's len */
  int64_t out_cap = 0;
  {
    PyObject* cargs = Py_BuildValue(
        "(Li)", static_cast<long long>(as_id(handle)),
        static_cast<int>(nindptr - 1));
    if (int_result(call_adapter("serve_calc_num_predict", cargs),
                   &out_cap) != 0) {
      return -1;
    }
  }
  PyObject* args = Py_BuildValue(
      "(LNiNNiLLLiN)", static_cast<long long>(as_id(handle)),
      mv_read(indptr, nindptr * dtype_size(indptr_type)), indptr_type,
      mv_read(indices, nelem * 4),
      mv_read(data, nelem * dtype_size(data_type)), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type,
      mv_write(out_result, out_cap * 8));
  return int_result(call_adapter("serve_predict_for_csr", args),
                    out_len);
}

extern "C" int LGBM_ServeFree(ServeHandle handle) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  return none_result(call_adapter("serve_free", args));
}

/* ------------------------------------------------------------------ */
/* Model fleet (lightgbm_tpu extension)                                */
/* ------------------------------------------------------------------ */

int LGBM_FleetCreate(
    const BoosterHandle booster, int num_tenants,
    std::unordered_map<std::string, std::string> parameters,
    FleetHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Lis)", static_cast<long long>(as_id(booster)), num_tenants,
      params_string(parameters).c_str());
  return handle_result(call_adapter("fleet_create", args), out);
}

extern "C" int LGBM_FleetSwapTenant(FleetHandle handle, int tenant_id,
                                    const BoosterHandle booster) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(LiL)", static_cast<long long>(as_id(handle)), tenant_id,
      static_cast<long long>(as_id(booster)));
  return none_result(call_adapter("fleet_swap_tenant", args));
}

extern "C" int LGBM_FleetCalcNumPredict(FleetHandle handle, int num_row,
                                        int64_t* out_len) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Li)", static_cast<long long>(as_id(handle)), num_row);
  return int_result(call_adapter("fleet_calc_num_predict", args),
                    out_len);
}

extern "C" int LGBM_FleetPredictForCSR(
    FleetHandle handle, const int32_t* tenant_ids,
    int64_t num_tenant_ids, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int64_t* out_len, double* out_result) {
  ensure_python();
  Gil gil;
  /* the caller pre-allocated out_result to FleetCalcNumPredict's len */
  int64_t out_cap = 0;
  {
    PyObject* cargs = Py_BuildValue(
        "(Li)", static_cast<long long>(as_id(handle)),
        static_cast<int>(nindptr - 1));
    if (int_result(call_adapter("fleet_calc_num_predict", cargs),
                   &out_cap) != 0) {
      return -1;
    }
  }
  PyObject* args = Py_BuildValue(
      "(LNLNiNNiLLLiN)", static_cast<long long>(as_id(handle)),
      mv_read(tenant_ids, num_tenant_ids * 4),
      static_cast<long long>(num_tenant_ids),
      mv_read(indptr, nindptr * dtype_size(indptr_type)), indptr_type,
      mv_read(indices, nelem * 4),
      mv_read(data, nelem * dtype_size(data_type)), data_type,
      static_cast<long long>(nindptr), static_cast<long long>(nelem),
      static_cast<long long>(num_col), predict_type,
      mv_write(out_result, out_cap * 8));
  return int_result(call_adapter("fleet_predict_for_csr", args),
                    out_len);
}

extern "C" int LGBM_FleetFree(FleetHandle handle) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue("(L)",
                                 static_cast<long long>(as_id(handle)));
  return none_result(call_adapter("fleet_free", args));
}

/* ------------------------------------------------------------------ */
/* AOT compile warmup (lightgbm_tpu extension)                         */
/* ------------------------------------------------------------------ */

int LGBM_WarmupTrain(
    std::unordered_map<std::string, std::string> parameters,
    int64_t num_row, int32_t num_feature, int* out_num_compiled) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(sLi)", params_string(parameters).c_str(),
      static_cast<long long>(num_row), static_cast<int>(num_feature));
  int64_t n = 0;
  int rc = int_result(call_adapter("warmup_train", args), &n);
  if (rc == 0 && out_num_compiled != nullptr) {
    *out_num_compiled = static_cast<int>(n);
  }
  return rc;
}

int LGBM_WarmupServe(
    std::unordered_map<std::string, std::string> parameters,
    int64_t num_row, int32_t num_feature, int* out_num_compiled) {
  ensure_python();
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(sLi)", params_string(parameters).c_str(),
      static_cast<long long>(num_row), static_cast<int>(num_feature));
  int64_t n = 0;
  int rc = int_result(call_adapter("warmup_serve", args), &n);
  if (rc == 0 && out_num_compiled != nullptr) {
    *out_num_compiled = static_cast<int>(n);
  }
  return rc;
}
