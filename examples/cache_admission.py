#!/usr/bin/env python
"""The fork's windowed cache-admission harness, end to end.

Reproduces ``/root/reference/src/test.cpp`` — the workload this fork of
LightGBM exists for — against the lightgbm_tpu runtime through the same
C-API surface (``lightgbm_tpu.c_api``):

* request stream in fixed windows (``processRequest``, test.cpp:300-343)
* Belady-style OPT labels: sort last-seen intervals by byte-volume and
  admit until the window's cache volume fills (``calculateOPT``,
  test.cpp:97-122)
* features per sampled request: up to 50 inter-arrival gaps + log2 size
  + available cache bytes + cost, CSR layout (``deriveFeatures``,
  test.cpp:125-209)
* per-window retrain of a FRESH booster with the fork's exact training
  parameters, then evaluation of the next window against the cutoff
  (``trainModel`` / ``evaluateModel``, test.cpp:211-298)

The reference ships its wall-clock in its result logs: TrainNewModel
~125.4 s per 20M-request window (``/root/reference/model:2``), feature
derivation 94.6 s (``/root/reference/time:2``).  This harness prints the
same per-phase timings as one JSON line, normalized per million
requests, so runs at any --window compare against that baseline.

No real CDN trace is on disk, so --trace synth generates a Zipf-popular
object stream (ids ~ Zipf(0.8), lognormal sizes), the standard shape of
the traces the fork was built for.  A file in the fork's whitespace
format (``seq id size cost`` per line) is accepted too.

Two execution modes share the summary schema: the default SERIAL loop
(the reference's phase order through the C API, with window 0's bin
mappers reused as the ``reference=`` for every later window), and
``--pipeline``, which runs the same workload as a thin client of
``lightgbm_tpu.pipeline.RetrainPipeline`` — host prep of window N+1
overlapped against window N's training, drift-gated rebinding,
``--window-policy`` warm starts, and serving that never goes down
(docs/Pipeline.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

HISTFEATURES = 50

# the fork's exact training parameters (test.cpp:66-87), minus the
# host-threading knob that has no TPU meaning
TRAIN_PARAMS = ("boosting=gbdt objective=binary max_bin=255 "
                "num_iterations=50 learning_rate=0.1 num_leaves=31 "
                "tree_learner=serial feature_fraction=0.8 "
                "bagging_freq=5 bagging_fraction=0.8 "
                "min_data_in_leaf=50 min_sum_hessian_in_leaf=5.0 "
                "verbosity=-1")
NUM_ITERATIONS = 50
# iterations fused per device dispatch (LGBM_BoosterUpdateChunked /
# GBDT.train_chunked).  The fork's bagging_freq=5 + feature_fraction=0.8
# config is fused-eligible since the draws moved inside the device scan
TRAIN_CHUNK = 25


def synth_trace(n_requests: int, n_objects: int, seed: int = 7):
    """Zipf-popularity request stream with per-object lognormal sizes."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_objects + 1, dtype=np.float64)
    probs = ranks ** -0.8
    probs /= probs.sum()
    ids = rng.choice(n_objects, size=n_requests, p=probs).astype(np.int64)
    obj_size = np.clip(rng.lognormal(9.0, 1.5, n_objects), 64,
                       1 << 26).astype(np.int64)
    sizes = obj_size[ids]
    costs = np.ones(n_requests, np.float64)
    return ids, sizes, costs


def calculate_opt(ids, sizes, cache_size, window_size):
    """OPT admission labels (test.cpp:97-122): an interval's volume is
    (reuse distance x size); admit smallest volumes until the window's
    cache volume budget fills."""
    n = len(ids)
    # next-occurrence interval per request, vectorized over the id-sorted
    # permutation (same-id requests are adjacent, original order kept)
    order = np.lexsort((np.arange(n), ids))
    sid = ids[order]
    spos = np.arange(n)[order]
    same = sid[:-1] == sid[1:]
    has_next = np.zeros(n, bool)
    volume = np.full(n, np.iinfo(np.int64).max, np.int64)
    prev_idx = spos[:-1][same]
    next_idx = spos[1:][same]
    has_next[prev_idx] = True
    volume[prev_idx] = (next_idx - prev_idx) * sizes[prev_idx]

    to_cache = np.zeros(n, bool)
    cache_volume = cache_size * window_size
    by_vol = np.argsort(volume, kind="stable")
    vol_cum = np.cumsum(volume[by_vol].astype(np.float64))
    # the C++ admits while the running volume has not yet exceeded the
    # budget (checked BEFORE adding), entries without a next skip
    admit = np.concatenate([[True], vol_cum[:-1] <= cache_volume])
    sel = by_vol[admit & has_next[by_vol]]
    to_cache[sel] = True
    return to_cache, float(to_cache.sum()) / n


def derive_features(ids, sizes, costs, to_cache, cache_size,
                    sample_size, sampling, rng):
    """Gap features + size/cacheAvail/cost, CSR (test.cpp:125-209).

    Gap features are vectorized: within the id-sorted order, feature k
    of a request is the gap between its (k)th and (k+1)th most recent
    past occurrences.  The running cacheAvailBytes simulation
    (admission state machine) is inherently sequential and runs as a
    compact python loop over the window.
    """
    n = len(ids)
    order = np.lexsort((np.arange(n), ids))
    sid = ids[order]
    spos = np.arange(n)[order].astype(np.int64)
    # occ_k[p] = position of the k-th previous occurrence of sid[p]
    gaps = np.zeros((n, HISTFEATURES), np.float64)
    gap_count = np.zeros(n, np.int32)
    prev = spos.copy()
    prev_valid = np.ones(n, bool)
    for k in range(HISTFEATURES):
        shifted = np.empty(n, np.int64)
        shifted[1 + k:] = spos[:n - 1 - k]
        shifted[:1 + k] = -1
        valid = np.zeros(n, bool)
        valid[1 + k:] = sid[1 + k:] == sid[:n - 1 - k]
        valid &= prev_valid
        g = np.where(valid, prev - shifted, 0)
        gaps[spos[valid], k] = g[valid]
        gap_count[spos[valid]] += 1
        prev = np.where(valid, shifted, prev)
        prev_valid = valid

    # sequential admission-state walk for cacheAvailBytes
    cache_avail = np.empty(n, np.float64)
    avail = float(cache_size)
    cached = {}
    for i in range(n):
        cache_avail[i] = 0.0 if avail <= 0 else np.round(
            100.0 * np.log2(avail))
        oid = int(ids[i])
        adm = bool(to_cache[i])
        if oid not in cached:
            if adm:
                avail -= float(sizes[i])
                cached[oid] = float(sizes[i])
        elif not adm:
            avail += cached.pop(oid)

    if sampling == 1:
        keep = np.arange(n) >= (n - sample_size)
    elif sampling == 2:
        keep = rng.random(n) < sample_size / n
    else:
        keep = np.ones(n, bool)

    kn = int(keep.sum())
    gc = gap_count[keep]
    row_nnz = gc + 3
    indptr = np.zeros(kn + 1, np.int32)
    np.cumsum(row_nnz, out=indptr[1:])
    nnz = int(indptr[-1])
    indices = np.zeros(nnz, np.int32)
    data = np.zeros(nnz, np.float64)
    # scatter gap features: row r occupies indptr[r] : indptr[r]+gc[r]
    rows = np.repeat(np.arange(kn), gc)
    col_in_row = np.arange(int(gc.sum()), dtype=np.int64) \
        - np.repeat(np.cumsum(gc, dtype=np.int64) - gc, gc)
    flat = indptr[:-1][rows] + col_in_row
    kgaps = gaps[keep]
    indices[flat] = col_in_row
    data[flat] = kgaps[rows, col_in_row]
    # the three fixed features
    tail = indptr[1:] - 3
    indices[tail] = HISTFEATURES
    data[tail] = np.round(100.0 * np.log2(sizes[keep]))
    indices[tail + 1] = HISTFEATURES + 1
    data[tail + 1] = cache_avail[keep]
    indices[tail + 2] = HISTFEATURES + 2
    data[tail + 2] = costs[keep]
    labels = to_cache[keep].astype(np.float32)
    return labels, indptr, indices, data


class CApiTrainer:
    """trainModel/evaluateModel (test.cpp:211-298) over lightgbm_tpu's
    C-API compatibility layer — fresh booster per window, like the
    fork's 'train a new booster' branch.  The READ side goes through
    the hot-swap prediction server (LGBM_Serve*): window 0 creates it,
    every later window atomically ``swap``s in the freshly trained
    model, and evaluation predicts against the server's packed
    ensemble — at steady state the swap re-dispatches into already-
    compiled device programs (zero retraces, docs/Serving.md)."""

    def __init__(self):
        from lightgbm_tpu import c_api as C
        self.C = C
        self.booster = None
        self.server = None
        # window 0's dataset handle survives as the bin-mapper
        # reference: later windows construct AGAINST it (CreateValid
        # semantics) instead of re-running find-bin, so feature groups
        # — and therefore device program signatures — stay frozen
        # across the whole run (docs/Pipeline.md)
        self.ref_ds = None

    def _check(self, rc):
        if rc != 0:
            raise RuntimeError(self.C.LGBM_GetLastError())

    def train_window(self, labels, indptr, indices, data) -> bool:
        """Train one window; returns True when this window ran
        find-bin (only the first window does — every later one reuses
        the cached reference mappers)."""
        C = self.C
        ds = C.Ref()
        rebinned = self.ref_ds is None
        self._check(C.LGBM_DatasetCreateFromCSR(
            indptr, C.C_API_DTYPE_INT32, indices, data,
            C.C_API_DTYPE_FLOAT64, len(indptr), len(data),
            HISTFEATURES + 3, TRAIN_PARAMS, self.ref_ds, ds))
        self._check(C.LGBM_DatasetSetField(
            ds.value, "label", labels, len(labels), C.C_API_DTYPE_FLOAT32))
        bst = C.Ref()
        self._check(C.LGBM_BoosterCreate(ds.value, TRAIN_PARAMS, bst))
        # one chunked call per window (test.cpp's 50-iteration
        # UpdateOneIter loop collapses into NUM_ITERATIONS/TRAIN_CHUNK
        # device dispatches when the fused path is eligible)
        fin = C.Ref()
        self._check(C.LGBM_BoosterUpdateChunked(
            bst.value, NUM_ITERATIONS, TRAIN_CHUNK, fin))
        # hand the new model to the serving side (the server keeps its
        # own packed copy, so the old booster frees safely)
        if self.server is None:
            srv = C.Ref()
            self._check(C.LGBM_ServeCreate(bst.value, TRAIN_PARAMS, srv))
            self.server = srv.value
        else:
            self._check(C.LGBM_ServeSwap(self.server, bst.value))
        if self.booster is not None:
            self._check(C.LGBM_BoosterFree(self.booster))
        self.booster = bst.value
        if rebinned:
            self.ref_ds = ds.value    # keep alive: the mapper source
        else:
            self._check(C.LGBM_DatasetFree(ds.value))
        return rebinned

    def evaluate(self, labels, indptr, indices, data, cutoff):
        C = self.C
        nrow = len(indptr) - 1
        out_len = C.Ref()
        result = np.zeros(nrow, np.float64)
        self._check(C.LGBM_ServePredictForCSR(
            self.server, indptr, C.C_API_DTYPE_INT32, indices, data,
            C.C_API_DTYPE_FLOAT64, len(indptr), len(data),
            HISTFEATURES + 3, C.C_API_PREDICT_NORMAL, out_len, result))
        fp = float(((labels < cutoff) & (result >= cutoff)).sum())
        fn = float(((labels >= cutoff) & (result < cutoff)).sum())
        return fp / len(labels), fn / len(labels)


def build_arg_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", default="synth",
                    help="'synth' or a file of 'seq id size cost' lines")
    ap.add_argument("--requests", type=int, default=2_000_000)
    ap.add_argument("--objects", type=int, default=200_000)
    ap.add_argument("--cache-size", type=int, default=1 << 30)
    ap.add_argument("--window", type=int, default=1_000_000)
    ap.add_argument("--sample", type=int, default=500_000)
    ap.add_argument("--cutoff", type=float, default=0.5)
    ap.add_argument("--sampling", type=int, default=1,
                    choices=(0, 1, 2))
    ap.add_argument("--metrics", default="",
                    help="write the telemetry metrics JSON snapshot "
                         "(docs/Observability.md) — per-window retrain "
                         "counts, recompiles, iteration percentiles")
    ap.add_argument("--obs-trace", default="",
                    help="write a Chrome-trace/Perfetto timeline of the "
                         "whole windowed session (--trace is taken by "
                         "the input trace file)")
    ap.add_argument("--compile-cache",
                    default=os.environ.get("LGBM_TPU_COMPILE_CACHE", ""),
                    help="persistent XLA compile cache dir "
                         "(lightgbm_tpu.compile_cache): a restarted "
                         "harness process re-loads every window's "
                         "compiled programs from disk instead of "
                         "recompiling (docs/ColdStart.md); '' disables "
                         "unless LGBM_TPU_COMPILE_CACHE is set")
    ap.add_argument("--pipeline", action="store_true",
                    help="run the windowed loop through the async "
                         "retrain pipeline (lightgbm_tpu.pipeline, "
                         "docs/Pipeline.md): window N+1's host prep "
                         "(OPT labels, gap features, CSR binning) "
                         "overlaps window N's device training while "
                         "serving hot-swaps, instead of the serial "
                         "C-API loop")
    ap.add_argument("--window-policy", default="fresh",
                    choices=("fresh", "refit", "warm"),
                    help="--pipeline: how each window's model starts "
                         "(fresh booster / leaf refit with decay / "
                         "refit + continued boosting)")
    ap.add_argument("--drift-threshold", type=float, default=0.1,
                    help="--pipeline: re-run find-bin when the noise-"
                         "adjusted bin-occupancy drift exceeds this")
    ap.add_argument("--no-rebin", action="store_true",
                    help="--pipeline: never re-run find-bin (freeze "
                         "window 0's mappers for the whole run)")
    return ap


def _run_serial(args, ids, sizes, costs, rng) -> list:
    """The reference's serial loop (label -> eval -> derive -> train)
    through the C API; returns the per-window record list."""
    from lightgbm_tpu import obs
    trainer = CApiTrainer()
    windows = []
    n_windows = len(ids) // args.window
    for w in range(n_windows):
        obs.instant("window_start", cat="harness", window=w)
        lo, hi = w * args.window, (w + 1) * args.window
        wid, wsz, wco = ids[lo:hi], sizes[lo:hi], costs[lo:hi]

        t0 = time.perf_counter()
        to_cache, opt_ratio = calculate_opt(wid, wsz, args.cache_size,
                                            args.window)
        t_opt = time.perf_counter() - t0

        t0 = time.perf_counter()
        if w > 0:
            # evaluateModel: previous booster scored on THIS window
            ev = derive_features(wid, wsz, wco, to_cache,
                                 args.cache_size, args.window, 0, rng)
            fp, fn = trainer.evaluate(*ev, args.cutoff)
        else:
            fp = fn = None
        t_eval = time.perf_counter() - t0

        t0 = time.perf_counter()
        feats = derive_features(wid, wsz, wco, to_cache, args.cache_size,
                                args.sample, args.sampling, rng)
        t_derive = time.perf_counter() - t0

        t0 = time.perf_counter()
        rebinned = trainer.train_window(*feats)
        t_train = time.perf_counter() - t0

        windows.append({
            "window": w, "opt_admit_ratio": round(opt_ratio, 4),
            "rows_trained": int(len(feats[0])), "rebinned": rebinned,
            "opt_s": round(t_opt, 2), "derive_s": round(t_derive, 2),
            "train_s": round(t_train, 2), "eval_s": round(t_eval, 2),
            "fp": round(fp, 4) if fp is not None else None,
            "fn": round(fn, 4) if fn is not None else None,
        })
        print(json.dumps(windows[-1]), file=sys.stderr, flush=True)
    return windows


def _csr_row_subset(indptr, indices, data, keep):
    """CSR rows selected by boolean mask ``keep`` (one gather)."""
    rows = np.flatnonzero(keep)
    counts = (indptr[rows + 1] - indptr[rows]).astype(np.int64)
    out_indptr = np.zeros(len(rows) + 1, np.int32)
    out_indptr[1:] = np.cumsum(counts)
    flat = np.repeat(indptr[rows].astype(np.int64)
                     - out_indptr[:-1], counts) \
        + np.arange(int(out_indptr[-1]), dtype=np.int64)
    return out_indptr, indices[flat], data[flat]


def _run_pipelined(args, ids, sizes, costs, rng):
    """The same windowed workload as a thin client of
    ``lightgbm_tpu.pipeline.RetrainPipeline``: OPT labeling + feature
    derivation + CSR binning run on the pipeline's prep thread
    (overlapped with the previous window's training), models hot-swap
    into its PredictionServer, and the previous model is scored on each
    window's full request stream before retraining.

    Prep derives each window's features ONCE: the serial loop — faithful
    to test.cpp — runs deriveFeatures twice per window (all rows for
    evaluateModel, sampled rows for trainModel), but the training rows
    are exactly a row subset of the full-window CSR (gap features and
    the admission state walk are computed over the whole window either
    way), so the pipeline carves them out with one gather instead of a
    second derivation pass.  Returns ``(windows, pipe)``."""
    from lightgbm_tpu.pipeline import PreppedWindow, RetrainPipeline

    n_windows = len(ids) // args.window
    ncol = HISTFEATURES + 3

    def prep(w):
        lo, hi = w * args.window, (w + 1) * args.window
        wid, wsz, wco = ids[lo:hi], sizes[lo:hi], costs[lo:hi]
        t0 = time.perf_counter()
        to_cache, opt_ratio = calculate_opt(wid, wsz, args.cache_size,
                                            args.window)
        t_opt = time.perf_counter() - t0
        t0 = time.perf_counter()
        if w > 0:
            # one full-window derivation serves eval AND training
            ev = derive_features(wid, wsz, wco, to_cache,
                                 args.cache_size, args.window, 0, rng)
            n = len(ev[0])
            if args.sampling == 1:
                keep = np.arange(n) >= (n - args.sample)
            elif args.sampling == 2:
                keep = rng.random(n) < args.sample / n
            else:
                keep = np.ones(n, bool)
            indptr, indices, data = _csr_row_subset(ev[1], ev[2],
                                                    ev[3], keep)
            labels = ev[0][keep]
            eval_label, eval_csr = ev[0], (ev[1], ev[2], ev[3], ncol)
        else:
            # window 0 is never evaluated: derive the sampled rows only
            labels, indptr, indices, data = derive_features(
                wid, wsz, wco, to_cache, args.cache_size, args.sample,
                args.sampling, rng)
            eval_label = eval_csr = None
        t_derive = time.perf_counter() - t0
        return PreppedWindow(
            label=labels, csr=(indptr, indices, data, ncol),
            eval_label=eval_label, eval_csr=eval_csr,
            meta={"opt_admit_ratio": round(opt_ratio, 4),
                  "opt_s": round(t_opt, 2),
                  "derive_s": round(t_derive, 2)})

    def eval_fn(pred, pw):
        labels = pw.eval_label
        fp = float(((labels < args.cutoff)
                    & (pred >= args.cutoff)).sum()) / len(labels)
        fn = float(((labels >= args.cutoff)
                    & (pred < args.cutoff)).sum()) / len(labels)
        return {"fp": round(fp, 4), "fn": round(fn, 4)}

    pipe = RetrainPipeline(
        TRAIN_PARAMS, num_iterations=NUM_ITERATIONS, chunk=TRAIN_CHUNK,
        window_policy=args.window_policy,
        rebin_on_drift=not args.no_rebin,
        drift_threshold=args.drift_threshold,
        keep_boosters=False)
    windows = []

    def on_window(res):
        windows.append(res.to_json())
        print(json.dumps(windows[-1]), file=sys.stderr, flush=True)

    pipe.run(range(n_windows), prep, eval_fn=eval_fn,
             on_window=on_window)
    return windows, pipe


def run(args) -> dict:
    """Run the windowed harness; returns the summary dict (the JSON
    line ``main`` prints).  Importable — ``bench.py --suite cache``
    drives this directly."""
    from lightgbm_tpu import compile_cache, obs
    if args.metrics or args.obs_trace:
        obs.configure(enabled=True, metrics_path=args.metrics or None,
                      trace_path=args.obs_trace or None)
    compile_cache.configure(getattr(args, "compile_cache", ""))

    if args.trace == "synth":
        ids, sizes, costs = synth_trace(args.requests, args.objects)
    else:
        raw = np.loadtxt(args.trace)
        ids = raw[:, 1].astype(np.int64)
        sizes = raw[:, 2].astype(np.int64)
        costs = raw[:, 3].astype(np.float64)

    rng = np.random.default_rng(13)
    pipelined = bool(getattr(args, "pipeline", False))
    t_start = time.perf_counter()
    overlap = None
    if pipelined:
        windows, pipe = _run_pipelined(args, ids, sizes, costs, rng)
        overlap = pipe.overlap_fraction
    else:
        windows = _run_serial(args, ids, sizes, costs, rng)
    total_s = time.perf_counter() - t_start

    # reference per-window wall-clock at 20M requests -> normalize per 1M
    steady = windows[1:] or windows
    train_per_m = float(np.mean([w["train_s"] for w in steady])) \
        / (args.sample / 1e6)
    derive_per_m = float(np.mean([w["derive_s"] for w in steady])) \
        / (args.window / 1e6)
    obs_summary = None
    if obs.enabled():
        obs.flush()
        obs_summary = obs.summary()
    return {
        "metric": "cache_admission_train_s_per_1M_sampled_rows",
        "value": round(train_per_m, 3), "unit": "s",
        "baseline_ref_train_s_per_1M": round(125.4 / 20.0, 3),
        "vs_baseline": round(train_per_m / (125.4 / 20.0), 4),
        "baseline_source": "/root/reference/model:2 (TrainNewModel "
                           "125.4 s / 20M-request window)",
        "derive_s_per_1M_requests": round(derive_per_m, 3),
        "ref_derive_s_per_1M": round(94.6 / 20.0, 3),
        "train_chunk": TRAIN_CHUNK,
        "pipeline": pipelined,
        "total_s": round(total_s, 2),
        "overlap_fraction": (None if overlap is None
                             else round(overlap, 4)),
        "rebinned_windows": sum(1 for w in windows if w.get("rebinned")),
        "windows": windows,
        "obs": obs_summary,
    }


def main() -> int:
    print(json.dumps(run(build_arg_parser().parse_args())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
