#!/usr/bin/env python
"""Phase attribution for the device grower + int8 histogram probe.

Goal (round 5): account for the ~90 ms/tree gap between the production
while_loop program (468 ms/tree at HIGGS shape) and the sum of the
measured phases (~377 ms), and measure whether int8 MXU matmuls (2x
bf16 peak on v5e) can cut the wave-histogram floor.

Protocol: scripts/ubench_hist.py's data-dependent fori_loop timing —
(T(k) - T(1)) / (k - 1) cancels dispatch floor and RTT.

Usage: python scripts/ubench_phases.py [--rows N] [--cases a,b,...]
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import time

import sys as _sys
_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

CHUNK = 32768


def run_case(name, body, state0, arrays=(), iters=8, flops=None):
    def make(k):
        @jax.jit
        def run(s, *arrs):
            s = jax.lax.fori_loop(0, k, lambda i, t: body(t, i, arrs), s)
            return jax.tree.map(
                lambda x: jnp.sum(x.astype(jnp.float32)) if x.ndim else x,
                s)
        return run

    def timed(run, s0):
        out = run(s0, *arrays)
        jax.block_until_ready(jax.tree.map(np.asarray, out))
        t0 = time.perf_counter()
        out = run(s0, *arrays)
        jax.tree.map(np.asarray, out)
        return time.perf_counter() - t0

    t1 = timed(make(1), state0)
    tk = timed(make(iters), state0)
    ms = (tk - t1) / (iters - 1) * 1e3
    rec = {"case": name, "ms": round(ms, 2),
           "ms_1": round(t1 * 1e3, 1), "ms_k": round(tk * 1e3, 1)}
    if flops:
        rec["tflops"] = round(flops / (ms / 1e3) / 1e12, 1)
    print(json.dumps(rec), flush=True)
    return ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_500_000)
    ap.add_argument("--groups", type=int, default=28)
    ap.add_argument("--nb", type=int, default=64)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--cases", type=str, default="")
    args = ap.parse_args()

    n = (args.rows + CHUNK - 1) // CHUNK * CHUNK
    g, nb, L = args.groups, args.nb, args.leaves
    S = g * nb
    it = args.iters
    rng = np.random.default_rng(0)
    binned_np = rng.integers(0, nb, (n, g), dtype=np.uint8)
    binned = jnp.asarray(binned_np)
    binned_t = jnp.asarray(np.ascontiguousarray(binned_np.T))
    leaf_id = jnp.asarray(rng.integers(0, 128, n, dtype=np.int32))
    grad = jnp.asarray(rng.standard_normal(n, dtype=np.float32))
    hess = jnp.asarray(rng.random(n, dtype=np.float32))
    print(json.dumps({"case": "setup", "rows": n, "device":
                      str(jax.devices()[0])}), flush=True)
    want = set(args.cases.split(",")) if args.cases else None

    def on(name):
        return want is None or name in want

    gh3 = jnp.stack([grad.astype(jnp.bfloat16), hess.astype(jnp.bfloat16),
                     jnp.ones((n,), jnp.bfloat16)], 1)
    # int8 probe: gradients quantized to +-127 by a global scale; counts
    # stay exact (0/1 columns, int32 accumulation)
    gs = 127.0 / float(np.abs(np.asarray(grad)).max())
    gh3_i8 = jnp.stack([
        jnp.clip(jnp.round(grad * gs), -127, 127).astype(jnp.int8),
        jnp.clip(jnp.round(hess * 127.0), -127, 127).astype(jnp.int8),
        jnp.ones((n,), jnp.int8)], 1)

    def hist_body(w, dtype, st, i, arrs):
        binned_a, leaf_a, ghk = arrs
        acc_sum, pending = st
        k = ghk.shape[1]
        n_chunks = n // CHUNK
        binned_c = binned_a.reshape(n_chunks, CHUNK, g)
        leaf_c = leaf_a.reshape(n_chunks, CHUNK)
        gh_c = ghk.reshape(n_chunks, CHUNK, k)
        acc_t = jnp.int32 if dtype == jnp.int8 else jnp.float32

        def body(acc, xs):
            b, l, g5 = xs
            oh = jax.nn.one_hot(b, nb, dtype=dtype)
            lm = (l[:, None] == pending[None, :]).astype(dtype)
            if dtype == jnp.int8:
                bmat = (lm[:, :, None] * g5[:, None, :]).reshape(
                    CHUNK, w * k)
            else:
                bmat = (lm[:, :, None] * g5[:, None, :]).reshape(
                    CHUNK, w * k)
            out = jnp.einsum("cgn,cb->gnb", oh, bmat,
                             preferred_element_type=acc_t)
            return acc + out, None

        acc0 = jnp.zeros((g, nb, w * k), acc_t)
        acc, _ = jax.lax.scan(body, acc0, (binned_c, leaf_c, gh_c))
        s = jnp.sum(acc.astype(jnp.float32))
        shift = (s * 1e-30).astype(jnp.int32) + 1
        return acc_sum + s, (pending + shift) % 64

    for name, ghk, w, dt in [
            ("hist3_bf16_w128", gh3, 128, jnp.bfloat16),
            ("hist3_int8_w128", gh3_i8, 128, jnp.int8),
            ("hist3_bf16_w4", gh3, 4, jnp.bfloat16),
            ("hist3_int8_w4", gh3_i8, 4, jnp.int8),
            ("hist3_int8_w170", gh3_i8, 170, jnp.int8)]:
        if not on(name):
            continue
        pend0 = jnp.arange(w, dtype=jnp.int32)
        flops = n * g * nb * w * ghk.shape[1] * 2
        run_case(name, functools.partial(hist_body, w, dt),
                 (jnp.float32(0), pend0), arrays=(binned, leaf_id, ghk),
                 iters=it, flops=flops)

    # ---- find_best over the full leaf table (N-independent) ------------
    if on("find_best_2w"):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.data.dataset import BinnedDataset
        from lightgbm_tpu.ops.split import (FeatureMeta, SplitHyper,
                                            find_best_split_impl)
        xs = rng.standard_normal((4096, g)).astype(np.float32)
        cfg = Config({"objective": "binary", "max_bin": nb - 1,
                      "num_leaves": L})
        ds = BinnedDataset.construct_from_matrix(xs, cfg)
        meta = FeatureMeta.from_dataset(ds, slot_stride=nb)
        hp = SplitHyper.from_config(cfg)
        find_one = functools.partial(find_best_split_impl, meta=meta,
                                     hp=hp, has_cat=False)
        W2 = 256
        hists = jnp.asarray(
            rng.random((W2, S, 3), np.float32) * 100.0)
        fmask = jnp.ones((len(np.asarray(ds.f_group)),), bool)

        def find_body(st, i, arrs):
            hists_a, = arrs
            acc, bump = st
            cons = jnp.asarray([-jnp.inf, jnp.inf], jnp.float32)
            h = hists_a + bump
            totals = h[:, :nb, :].sum(1)
            packed, _ = jax.vmap(
                lambda hh, t: find_one(hh, t, cons, fmask))(h, totals)
            s = jnp.sum(packed[:, 0])
            return acc + s, (s * 1e-30)

        run_case("find_best_2w", find_body,
                 (jnp.float32(0), jnp.float32(0)), arrays=(hists,),
                 iters=it)

    # ---- split apply (int16 chain over (W, N)) --------------------------
    def apply_body(w, st, i, arrs):
        binned_t_a, leaf_a = arrs
        leaf, acc = st
        grp = (jnp.arange(w, dtype=jnp.int32) + acc.astype(jnp.int32)) % g
        thr = jnp.full((w,), nb // 2, jnp.int16)
        i16 = lambda a: a.astype(jnp.int16)
        cols = i16(jnp.take(binned_t_a, grp, axis=0))
        lsel = jnp.arange(w, dtype=jnp.int32)
        mask = (leaf[None, :] == lsel[:, None]) & (cols > thr[:, None])
        upd = jnp.sum(mask * jnp.int32(1), axis=0, dtype=jnp.int32)
        leaf2 = leaf + upd
        s = jnp.sum(upd.astype(jnp.float32)) * 1e-30
        return (leaf2 - upd, acc + s + 1.0)   # restore ids, keep dep

    if on("apply_w128"):
        run_case("apply_w128", functools.partial(apply_body, 128),
                 (leaf_id, jnp.float32(0)), arrays=(binned_t, leaf_id),
                 iters=it)

    # ---- score update (one-hot L einsum) --------------------------------
    def score_body(st, i, arrs):
        leaf_a, = arrs
        score, = st
        vals = jnp.arange(L, dtype=jnp.float32) * 1e-6 \
            + score[0] * 1e-30
        oh = jax.nn.one_hot(leaf_a % L, L, dtype=jnp.bfloat16)
        vhi = vals.astype(jnp.bfloat16)
        vlo = (vals - vhi.astype(jnp.float32)).astype(jnp.bfloat16)
        upd = jnp.einsum("nl,lk->nk", oh, jnp.stack([vhi, vlo], 1),
                         preferred_element_type=jnp.float32)
        return (score + upd[:, 0] + upd[:, 1],)

    if on("score_upd"):
        run_case("score_upd", score_body,
                 (jnp.zeros((n,), jnp.float32),), arrays=(leaf_id,),
                 iters=it)

    # ---- gradient compute (binary logloss) ------------------------------
    def grad_body(st, i, arrs):
        label_a, = arrs
        score, = st
        r = -label_a / (1.0 + jnp.exp(label_a * score))
        g_ = r
        h_ = jnp.abs(r) * (1.0 - jnp.abs(r))
        return (score + (g_ * h_).sum() * 1e-30 + 1e-6,)

    if on("grad_binary"):
        run_case("grad_binary", grad_body,
                 (jnp.zeros((n,), jnp.float32),),
                 arrays=(jnp.asarray(np.where(
                     rng.random(n) > 0.5, 1.0, -1.0).astype(np.float32)),),
                 iters=it)


if __name__ == "__main__":
    main()
