#!/usr/bin/env python
"""CI streaming-telemetry smoke (docs/Observability.md "Streaming &
SLOs").

Gates four contracts of the streaming layer, chaos-coupled so the SLO
gate is proven able to FIRE, not just to pass:

1. **Healthy run passes** — a trained model served normally meets an
   ``availability>=0.999`` + generous p95 spec evaluated from the
   rolling window.
2. **Injected device death fails availability** — the SAME serve loop
   under ``LGBM_TPU_FAULTS=serve.dispatch:persist`` answers every
   request through the host fallback, but the breaker's dark time
   counts against availability, so the spec must FAIL on exactly the
   availability objective (and only because of dark time: every
   request is still answered).
3. **Exports validate** — the JSONL stream lines, the Prometheus
   exposition file (metric-name legality, no duplicate samples) and
   the full metrics snapshot all pass ``scripts/validate_metrics.py``.
4. **Disabled hot path stays a flag check** — with telemetry off,
   spans are the shared no-op singleton, nothing lands in the registry
   or the rolling window, and serving answers normally.

Exit 0 on success, 1 with diagnostics on failure.
"""

import importlib.util
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "validate_metrics", os.path.join(REPO, "scripts",
                                     "validate_metrics.py"))
validate_metrics = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(validate_metrics)

PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
          "num_iterations": 6, "device_growth": "on"}
FEATURES = 8
SPEC = "availability>=0.999,p95_ms<=60000,window_s=60"


def train_model():
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    rng = np.random.default_rng(7)
    x = rng.standard_normal((3000, FEATURES))
    y = (x[:, 0] > 0).astype(np.float64)
    cfg = Config(dict(PARAMS))
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(PARAMS["num_iterations"], chunk=3)
    bst._flush_pending()
    return bst, x


def serve_loop(bst, x, requests=30):
    from lightgbm_tpu.robust import CircuitBreaker
    from lightgbm_tpu.serve.engine import PredictionServer

    srv = PredictionServer(bst, breaker=CircuitBreaker(
        failure_threshold=2, reprobe_interval_s=30.0))
    srv.warmup([256])
    q = x[:256]
    for _ in range(requests):
        srv.predict(q)
    return srv


def gate_healthy(failures, bst, x):
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import slo

    obs.reset()
    serve_loop(bst, x)
    rep = slo.evaluate(SPEC)
    if not rep.ok:
        failures.append(f"healthy run FAILED its SLO spec: "
                        f"{json.dumps(rep.to_json())}")
    return rep.to_json()


def gate_injected_death(failures, bst, x):
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import slo
    from lightgbm_tpu.robust import faults

    obs.reset()
    os.environ["LGBM_TPU_FAULTS"] = "serve.dispatch:persist"
    try:
        faults.configure_from_env()
        srv = serve_loop(bst, x)
        rep = slo.evaluate(SPEC)
    finally:
        faults.clear()
        os.environ.pop("LGBM_TPU_FAULTS", None)
    if srv.dark_seconds <= 0:
        failures.append("breaker reports no live dark time while open "
                        "(CircuitBreaker.dark_seconds)")
    avail = rep.objective("availability")
    if rep.ok or avail is None or avail.ok:
        failures.append(
            f"injected device death did NOT fail the availability "
            f"SLO — the gate cannot fire: {json.dumps(rep.to_json())}")
    if rep.counts.get("failed", 0):
        failures.append(
            f"injected device death DROPPED "
            f"{rep.counts['failed']} requests (fallback contract "
            f"broken; availability should fail on dark time alone)")
    if rep.counts.get("dark_fraction", 0) <= 0:
        failures.append("breaker dark time did not register in the "
                        "rolling window")
    return rep.to_json()


def gate_exports(failures, bst, x):
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs.state import STATE

    obs.reset()
    d = tempfile.mkdtemp(prefix="lgbm_obs_smoke_")
    stream = os.path.join(d, "stream.jsonl")
    prom = os.path.join(d, "metrics.prom")
    metrics = os.path.join(d, "metrics.json")
    obs.configure(stream_path=stream, prom_path=prom,
                  export_interval_s=0.5, slo_spec=SPEC)
    try:
        serve_loop(bst, x)
        obs.flush()
    finally:
        exp = STATE.exporter
        STATE.exporter = None
        if exp is not None:
            exp.stop()
    obs.dump_metrics(metrics)

    n_lines = 0
    for i, line in enumerate(open(stream), 1):
        n_lines += 1
        errs = validate_metrics.validate_stream_line(json.loads(line))
        for e in errs:
            failures.append(f"stream line {i}: {e}")
    if not n_lines:
        failures.append("exporter wrote no stream lines")
    prom_text = open(prom).read()
    for e in validate_metrics.validate_prometheus(prom_text):
        failures.append(f"prometheus exposition: {e}")
    doc = json.load(open(metrics))
    for e in validate_metrics.validate(doc):
        failures.append(f"metrics snapshot: {e}")
    if doc.get("rolling") is None:
        failures.append("metrics snapshot has no rolling block")
    slo_line = any("slo" in json.loads(ln) for ln in open(stream))
    if not slo_line:
        failures.append("no stream line carried the SLO digest")
    return {"stream_lines": n_lines,
            "prom_samples": sum(1 for ln in prom_text.splitlines()
                                if ln and not ln.startswith("#")),
            "dir": d}


def gate_disabled_hot_path(failures, bst, x):
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs.state import STATE

    obs.configure(enabled=False)
    obs.reset()
    # the disabled fast path must be the shared singletons: one flag
    # check, zero allocation, nothing recorded anywhere
    if obs.span("grow_tree") is not obs.span("serve.predict"):
        failures.append("disabled span is not the shared no-op "
                        "singleton (hot path allocates)")
    obs.inc("serve.ok")
    obs.observe("serve.predict", 1.0)
    obs.set_gauge("serve.degraded", 1)
    serve_loop(bst, x, requests=3)
    snap = STATE.registry.snapshot()
    recorded = (snap["counters"] or snap["gauges"] or snap["timings"])
    if recorded:
        failures.append(f"disabled telemetry still recorded: {recorded}")
    if STATE.rolling is not None and \
            STATE.rolling.window()["counters"]:
        failures.append("disabled telemetry still fed the rolling "
                        "window")
    return {"recorded": bool(recorded)}


def main() -> int:
    from lightgbm_tpu import obs

    failures = []
    bst, x = train_model()
    obs.configure(enabled=True)
    summary = {
        "healthy": gate_healthy(failures, bst, x),
        "injected_death": gate_injected_death(failures, bst, x),
        "exports": gate_exports(failures, bst, x),
        "disabled": gate_disabled_hot_path(failures, bst, x),
    }
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"OBS SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    hd = summary["healthy"]["counts"]
    dd = summary["injected_death"]["counts"]
    print(f"obs smoke PASS: healthy SLO ok "
          f"({hd['ok']} device-ok requests), injected death failed "
          f"availability (dark_fraction={dd['dark_fraction']}, "
          f"{dd['fallback']} fallbacks, 0 dropped), "
          f"{summary['exports']['stream_lines']} stream lines + "
          f"{summary['exports']['prom_samples']} exposition samples "
          f"validated, disabled path records nothing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
