#!/usr/bin/env python
"""CI smoke for multi-tenant fleet serving (docs/Serving.md "Model
fleets").

Builds a 3-tenant ``FleetServer``, retrains tenant 0 through the async
windowed-retrain pipeline (``RetrainPipeline(server=fleet,
tenant_id=0)``) while a prober hammers tenants 1 and 2, and gates the
three contracts the subsystem exists for:

1. **Zero-retrace tenant swap**: after the fleet warmup (which also
   compiles the index-write program) and window 0, every later window's
   swap must land as a device index write into already-compiled
   programs — the obs-tracked jit compile total must not move, and
   every swap must report ``fits`` (``swap_same_shape=True``).

2. **Serving on the untouched tenants never stops**: every probe on
   tenants 1..M-1 must succeed, and at least one must land strictly
   INSIDE a later window's training interval of tenant 0's retrain.

3. **Byte-identity vs solo servers**: tenants 1..M-1 are never
   swapped, so every probe answer must be byte-identical to the
   reference captured from each tenant's solo ``PredictionServer``
   before the run.

Exit 0 on success, 1 with a diagnostic on failure.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

WINDOW_ROWS = 5000
FEATURES = 10
WINDOWS = 3
TENANTS = 3

PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
          "device_growth": "on", "num_iterations": 6, "max_depth": 6}


def main() -> int:
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.pipeline import PreppedWindow, RetrainPipeline
    from lightgbm_tpu.serve import FleetServer, PredictionServer

    obs.configure(enabled=True)

    def train(seed):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((WINDOW_ROWS, FEATURES))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
        cfg = Config(PARAMS)
        ds = BinnedDataset.construct_from_matrix(x, cfg)
        ds.metadata.set_label(y)
        bst = create_boosting(cfg)
        bst.init_train(ds)
        bst.train_chunked(PARAMS["num_iterations"], chunk=3)
        bst._flush_pending()
        return bst

    tenants = [train(100 + m) for m in range(TENANTS)]
    fleet = FleetServer(tenants)
    probe_rows = np.zeros((128, FEATURES))
    probe_rows[:, 0] = np.linspace(-2, 2, 128)
    # byte-identity reference: the untouched tenants' solo servers
    solo_ref = [np.asarray(PredictionServer(tenants[m]).predict(
        probe_rows)) for m in range(TENANTS)]
    fleet.warmup([probe_rows.shape[0]])

    probe_log = []          # (timestamp, ok, byte_identical)
    probe_stop = threading.Event()

    def prober():
        while not probe_stop.is_set():
            t = time.perf_counter()
            try:
                ok, ident = True, True
                for m in range(1, TENANTS):
                    out = np.asarray(fleet.predict(m, probe_rows))
                    ok &= bool(np.isfinite(out).all())
                    ident &= bool(np.array_equal(out, solo_ref[m]))
            except Exception:   # noqa: BLE001 — the smoke records it
                ok = ident = False
            probe_log.append((t, ok, ident))
            time.sleep(0.02)

    def compiles_now():
        return sum(v["compiles"]
                   for v in obs.registry().snapshot()["jit"].values())

    def prep(w):
        rng = np.random.default_rng(1000 + w)
        x = rng.standard_normal((WINDOW_ROWS, FEATURES))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
        return PreppedWindow(label=y, dense=x, eval_dense=x,
                             eval_label=y)

    pipe = RetrainPipeline(PARAMS, chunk=3, server=fleet, tenant_id=0)

    state = {"compiles_after_w0": None, "prober": None}

    def on_window(res):
        if res.window == 0:
            state["compiles_after_w0"] = compiles_now()
            t = threading.Thread(target=prober, daemon=True)
            t.start()
            state["prober"] = t

    try:
        results = pipe.run(range(WINDOWS), prep, on_window=on_window)
    finally:
        probe_stop.set()
        if state["prober"] is not None:
            state["prober"].join(timeout=5.0)

    failures = []
    compiles_end = compiles_now()
    if state["compiles_after_w0"] is None:
        failures.append("window 0 never completed")
    elif compiles_end != state["compiles_after_w0"]:
        snap = obs.registry().snapshot()["jit"]
        failures.append(
            f"tenant swaps retraced: jit compiles went "
            f"{state['compiles_after_w0']} -> {compiles_end} ({snap})")

    if len(results) != WINDOWS:
        failures.append(f"expected {WINDOWS} windows, got {len(results)}")
    for res in results:
        if res.swap_same_shape is False:
            failures.append(f"window {res.window} tenant swap did not "
                            f"fit the fleet pads (index write degraded "
                            f"to a re-pack)")

    if not probe_log:
        failures.append("prober made no requests")
    else:
        if not all(ok for _, ok, _ in probe_log):
            bad = sum(1 for _, ok, _ in probe_log if not ok)
            failures.append(f"{bad}/{len(probe_log)} fleet probes "
                            f"failed on the untouched tenants")
        if not all(ident for _, _, ident in probe_log):
            bad = sum(1 for _, _, ident in probe_log if not ident)
            failures.append(
                f"{bad}/{len(probe_log)} probes were NOT byte-identical "
                f"to the untouched tenants' solo servers")
        spans = [r.train_span for r in results[1:]]
        mid_train = sum(1 for t, ok, _ in probe_log
                        if ok and any(t0 <= t <= t1 for t0, t1 in spans))
        if mid_train == 0:
            failures.append("no fleet probe succeeded during tenant 0's "
                            "retrain (serve-through-retrain not "
                            "demonstrated)")

    summary = {
        "tenants": TENANTS,
        "windows": len(results),
        "compiles_after_w0": state["compiles_after_w0"],
        "compiles_end": compiles_end,
        "probes": len(probe_log),
        "mid_train_probes": sum(
            1 for t, ok, _ in probe_log
            if ok and any(t0 <= t <= t1
                          for t0, t1 in (r.train_span
                                         for r in results[1:]))),
        "swap_fits": [r.swap_same_shape for r in results],
        "degraded_replicas": fleet.degraded_replicas(),
    }
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"FLEET SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"fleet smoke PASS: zero-retrace tenant swaps, "
          f"{summary['mid_train_probes']} mid-retrain serves on "
          f"untouched tenants, all probes byte-identical to solo")
    return 0


if __name__ == "__main__":
    sys.exit(main())
