// Dump reference BinMapper::FindBin outputs for parity fixtures.
//
// Reads cases from stdin:
//   <case_name> <max_bin> <min_data_in_bin> <use_missing> <zero_as_missing> <n>
//   v0 v1 ... v{n-1}
// and prints one JSON object per case:
//   {"name": ..., "num_bin": B, "missing_type": M,
//    "upper_bounds": [...]}   (upper bound of bin i = BinToValue(i))
//
// Build (see scripts/make_parity_fixtures.py):
//   g++ -O2 -std=c++11 -I /root/reference/include dump_ref_bins.cpp \
//       -L .refbuild -l_lightgbm -o dump_ref_bins
#include <LightGBM/bin.h>

#include <cstdio>
#include <string>
#include <vector>

int main() {
  char name[256];
  int max_bin, min_data_in_bin, use_missing, zero_as_missing, n;
  while (std::scanf("%255s %d %d %d %d %d", name, &max_bin, &min_data_in_bin,
                    &use_missing, &zero_as_missing, &n) == 6) {
    std::vector<double> values(n);
    for (int i = 0; i < n; ++i) std::scanf("%lf", &values[i]);
    LightGBM::BinMapper mapper;
    // min_split_data=0 and NumericalBin match DatasetLoader's call site
    // (dataset_loader.cpp ConstructBinMappersFromTextData)
    mapper.FindBin(values.data(), n, n, max_bin, min_data_in_bin, 0,
                   LightGBM::BinType::NumericalBin, use_missing != 0,
                   zero_as_missing != 0);
    std::printf("{\"name\": \"%s\", \"num_bin\": %d, \"missing_type\": %d, "
                "\"upper_bounds\": [",
                name, mapper.num_bin(),
                static_cast<int>(mapper.missing_type()));
    for (int b = 0; b < mapper.num_bin(); ++b) {
      double v = mapper.BinToValue(b);
      if (v > 1e300 * 1e8) {
        // the last numerical bin's upper bound is +inf; Python's json
        // parser accepts the "Infinity" spelling, bare "inf" it does not
        std::printf("%sInfinity", b ? ", " : "");
      } else {
        std::printf("%s%.17g", b ? ", " : "", v);
      }
    }
    std::printf("]}\n");
  }
  return 0;
}
