#!/usr/bin/env python
"""CI chaos-soak smoke (docs/Soak.md): the composed fleet soak must
reach a PASS verdict on the CPU container.

Runs the default scenario — 2 tenants x 3 windows x 1 injected
mid-window kill, plus one poisoned micro-batch, one dead-ingest-peer
timeout and one clock skew — through ``lightgbm_tpu.soak`` end to end
and gates:

1. **availability through the kill** — the ``serve.fleet`` SLO
   availability objective (>= 99.9 %, dark time counted) holds while
   tenant 0 is killed mid-window and resumed;
2. **resume byte-identity** — every scheduled kill fired, resumed
   from its checkpoint, and the resumed tenant's final model is
   byte-identical to an unfaulted reference replay;
3. **zero-retrace swaps** — no tenant swap after its first window
   changed shape (pinned serving signature held under chaos);
4. **zero dropped export lines** — the streaming exporter lost
   nothing, and every ``stream.jsonl`` line validates against the
   stream schema;
5. **verdict schema** — the full verdict passes
   ``validate_metrics.validate_soak``;
6. **seed determinism** — recompiling the timeline from the same
   scenario reproduces the verdict's ``timeline_digest`` byte for
   byte.

A bring-up failure in this container (accelerator runtime refusing to
initialize, native lib absent) is reported as SKIP and exits 0 —
environmental, same convention as ``check_multihost.py``; the
contract is re-gated on real chips by ``bench.py --suite soak``.
Gate failures exit 1 with diagnostics.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import tempfile
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "validate_metrics", os.path.join(REPO, "scripts",
                                     "validate_metrics.py"))
validate_metrics = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(validate_metrics)


def main() -> int:
    from lightgbm_tpu.soak import (SoakScenario, compile_timeline,
                                   run_and_report, timeline_digest)

    sc = SoakScenario()  # 2 tenants x 3 windows x 1 kill (seed 7)
    workdir = tempfile.mkdtemp(prefix="check_soak_")
    try:
        verdict = run_and_report(sc, workdir=workdir)
    except Exception as exc:  # bring-up, not a gate: SKIP (module doc)
        traceback.print_exc()
        print(f"SKIP: soak bring-up failed in this container: {exc}")
        return 0

    gates = verdict["gates"]
    stream_errors: list[str] = []
    stream_lines = 0
    stream_path = os.path.join(workdir, "stream.jsonl")
    if os.path.exists(stream_path):
        with open(stream_path) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                stream_lines += 1
                for err in validate_metrics.validate_stream_line(
                        json.loads(line)):
                    stream_errors.append(f"line {i}: {err}")
    verdict_errors = validate_metrics.validate_soak(verdict)
    replay = timeline_digest(sc, compile_timeline(sc))

    checks = {
        "availability >= 99.9% through the mid-window kill":
            bool(gates["availability"]["ok"]),
        "every scheduled kill fired, resumed, byte-identical":
            bool(gates["resume_byte_identity"]["ok"])
            and len(verdict["kills"]) == sc.kills,
        "zero retraced tenant swaps after window 0":
            bool(gates["zero_retrace_swaps"]["ok"]),
        "zero dropped / failed export lines":
            bool(gates["export"]["ok"]),
        f"stream.jsonl schema-valid ({stream_lines} lines)":
            stream_lines > 0 and not stream_errors,
        "verdict passes validate_metrics --soak":
            not verdict_errors,
        "same-seed replay reproduces the timeline digest":
            replay == verdict["timeline_digest"],
        "composed verdict PASS":
            bool(verdict["ok"]),
    }
    ok = True
    for name, passed in checks.items():
        print(f"{'PASS' if passed else 'FAIL'}  {name}")
        ok = ok and passed
    for err in stream_errors[:5] + verdict_errors[:5]:
        print(f"  - {err}")
    if not ok:
        print(json.dumps({k: v for k, v in verdict.items()
                          if k in ("gates", "kills", "load",
                                   "tenant_errors")}, indent=1,
                         default=str))
    print(f"soak digest: tenants={sc.tenants} windows={sc.windows} "
          f"kills={len(verdict['kills'])} "
          f"elapsed_s={round(verdict['elapsed_s'], 2)} "
          f"digest={verdict['timeline_digest'][:12]} "
          f"chip_pending={verdict['chip_pending']}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
