"""CI gate: the jaxlint incremental cache is correct AND fast.

Measures a cold full analysis (fresh cache directory) and a warm run on
the unchanged tree, in one process so the comparison is analyzer work,
not interpreter/jax import time.  Gates:

* the warm run replays byte-identical findings (rule/path/line/message);
* the warm run is flagged ``from_cache`` and completes in <= 25% of the
  cold run (acceptance bar; measured ~2% on the 90-file tree);
* touching one file invalidates exactly that — the next run is cold for
  the project rules, and the run after is warm again.

Run from the repo root: ``python scripts/check_jaxlint_cache.py``.
"""

import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from lightgbm_tpu.tools import jaxlint  # noqa: E402


def key(findings):
    return sorted((f.path, f.rule, f.line, f.col, f.message)
                  for f in findings)


def main() -> int:
    tmp = Path(tempfile.mkdtemp(prefix="jaxlint_cache_gate_"))
    cache = tmp / ".jaxlint_cache"
    try:
        t0 = time.perf_counter()
        cold = jaxlint.analyze_paths(["lightgbm_tpu"], root=str(REPO),
                                     cache_dir=str(cache))
        cold_s = time.perf_counter() - t0
        if cold.errors:
            print(f"FAIL: analyzer errors: {cold.errors}")
            return 1
        if cold.from_cache:
            print("FAIL: first run unexpectedly warm")
            return 1

        t0 = time.perf_counter()
        warm = jaxlint.analyze_paths(["lightgbm_tpu"], root=str(REPO),
                                     cache_dir=str(cache))
        warm_s = time.perf_counter() - t0

        if not warm.from_cache:
            print("FAIL: unchanged tree did not hit the cache")
            return 1
        if key(warm.findings) != key(cold.findings):
            print("FAIL: warm findings differ from cold findings")
            return 1
        ratio = warm_s / max(cold_s, 1e-9)
        print(f"cold {cold_s:.2f}s  warm {warm_s:.3f}s  "
              f"ratio {ratio:.1%}  findings {len(cold.findings)}")
        if ratio > 0.25:
            print("FAIL: warm run exceeded 25% of the cold run")
            return 1
        print("PASS: incremental jaxlint cache correct and "
              f"{1 / max(ratio, 1e-9):.0f}x faster on an unchanged tree")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
