#!/usr/bin/env python
"""CI smoke for causal trace propagation (docs/Observability.md
"Tracing & attribution").

Runs two synthetic windows through the async retrain pipeline with
``trace_context`` on, serves requests against the swapped-in model
(both the synchronous ``predict`` path and the micro-batch
``submit``/flush path), then asserts the causal chain the tracing
layer exists for:

1. **One trace**: every span the run records carries the pipeline's
   single root trace_id — across the prep worker thread, the training
   window, the hot-swap and the serve calls.
2. **Serve -> training-window ancestry**: the ``serve.predict`` span's
   ``model_span_id`` link resolves to the ``serve.swap`` span that
   installed the model, and the parent chain from that swap walks
   ``pipeline.window`` -> ``pipeline.prep_window`` -> the trace root —
   i.e. a served request is attributable to the exact training window
   that produced its model.
3. **Submit -> flush**: the ``serve.request`` span event emitted by
   the worker thread parents back to the submitting caller's span.
4. **Link integrity + readable lanes**: the Chrome export passes
   ``validate_metrics.py --trace`` rules (unique span_ids, no orphan
   parent_ids) and names every thread lane.
5. **Disabled hot path**: with obs off, ``span()`` hands back the
   shared no-op singleton and ``tracing.capture()`` is None — zero
   context objects allocated.

Exit 0 on success, 1 with diagnostics on failure.
"""

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

WINDOW_ROWS = 4000
FEATURES = 8
WINDOWS = 2

PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
          "device_growth": "on", "num_iterations": 4,
          "trace_context_enabled": True}


def main() -> int:
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import tracing
    from lightgbm_tpu.obs.state import STATE
    from lightgbm_tpu.pipeline import PreppedWindow, RetrainPipeline

    failures = []

    # --- 5. disabled hot path first, before anything enables obs
    obs.configure(enabled=False)
    if obs.span("a", cat="x") is not obs.span("b", cat="y"):
        failures.append("disabled span() is not the shared singleton")
    if tracing.capture() is not None:
        failures.append("disabled tracing.capture() allocated a context")

    obs.configure(enabled=True, trace_context=True)

    def prep(w):
        rng = np.random.default_rng(1000 + w)
        x = rng.standard_normal((WINDOW_ROWS, FEATURES))
        y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
        return PreppedWindow(label=y, dense=x, eval_dense=x,
                             eval_label=y)

    pipe = RetrainPipeline(PARAMS, chunk=2)
    rows = np.zeros((64, FEATURES))
    pipe.run(range(WINDOWS), prep)

    # serve against the last swapped model: sync + micro-batch paths,
    # under a caller-side request span (what an embedding service
    # holds when it calls in — the submit->flush edge parents to it)
    with obs.span("smoke.request", cat="serve"):
        pipe.server.predict(rows)
        pipe.server.start()
        try:
            pipe.server.submit(rows).result(timeout=30)
        finally:
            pipe.server.stop()

    with STATE.trace._lock:
        events = list(STATE.trace._events)
    spans = {}
    by_name = {}
    for ev in events:
        args = ev.args or {}
        sid = args.get("span_id")
        if sid:
            spans[sid] = (ev.name, args)
        by_name.setdefault(ev.name, []).append(args)

    # --- 1. one trace across the whole pipeline run: every span the
    # retrain loop records — prep thread, window, train, swap — shares
    # the root trace_id.  (Serve calls arriving AFTER the run mint
    # their own request traces; they join causally via the model link.)
    pipeline_traces = {a.get("trace_id") for name, a in spans.values()
                       if name.startswith("pipeline.")
                       or name in ("serve.swap", "flush_pending")}
    if len(pipeline_traces) != 1:
        failures.append(f"expected ONE pipeline trace_id, saw "
                        f"{pipeline_traces}")
    root_trace = next(iter(pipeline_traces), None)

    # --- 2. serve.predict -> swap -> window -> prep -> root
    preds = [a for a in by_name.get("serve.predict", [])
             if a.get("model_span_id")]
    if not preds:
        failures.append("no serve.predict span carries a model link")
    else:
        link = preds[-1]
        if link.get("model_trace_id") != root_trace:
            failures.append(
                f"serve.predict model_trace_id "
                f"{link.get('model_trace_id')} != root {root_trace}")
        chain, cur = [], link["model_span_id"]
        while cur is not None and cur in spans and len(chain) < 20:
            name, args = spans[cur]
            chain.append(name)
            cur = args.get("parent_id")
        if chain[:1] != ["serve.swap"]:
            failures.append(f"model link resolves to {chain[:1]}, "
                            f"not the serve.swap span")
        if "pipeline.window" not in chain \
                or "pipeline.prep_window" not in chain:
            failures.append(
                f"serve span ancestry never reaches the training "
                f"window (chain: {' -> '.join(chain)})")
        if cur is not None:
            failures.append(f"ancestry chain broke at unknown span "
                            f"{cur} (chain: {chain})")

    # --- 3. submit -> worker flush
    reqs = by_name.get("serve.request", [])
    if not reqs:
        failures.append("no serve.request span event from the worker")
    elif not any(r.get("parent_id") in spans for r in reqs):
        failures.append(f"serve.request parent_id does not resolve "
                        f"to a recorded span ({reqs[-1]})")

    # --- 4. exported chrome trace: validator rules + named lanes
    with tempfile.TemporaryDirectory() as td:
        trace_path = os.path.join(td, "trace.json")
        obs.dump_trace(trace_path)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "validate_metrics.py"),
             "--trace", trace_path],
            capture_output=True, text=True)
        if proc.returncode != 0:
            failures.append(f"validate_metrics --trace rejected the "
                            f"exported trace: {proc.stderr.strip()}")
        with open(trace_path) as fh:
            chrome = json.load(fh)
    evs = chrome["traceEvents"]
    tids = {e["tid"] for e in evs if e.get("ph") == "X"}
    named = {e["tid"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "thread_name"
             and e.get("args", {}).get("name")}
    if not tids <= named:
        failures.append(f"unnamed thread lanes: {tids - named}")
    if len(tids) < 2:
        failures.append(f"expected spans from >=2 threads (prep worker "
                        f"+ main), saw tids {tids}")

    summary = {
        "events": len(events),
        "spans": len(spans),
        "trace_id": root_trace,
        "serve_requests": len(reqs),
        "threads": len(tids),
    }
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"TRACE SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print("trace smoke PASS: serve span ancestry reaches the training "
          "window on one trace_id; disabled path stays no-op")
    return 0


if __name__ == "__main__":
    sys.exit(main())
