#!/usr/bin/env python
"""CI shard smoke: single-controller sharded training byte-identity +
zero-retrace warm window, on a forced 4-device host mesh.

Gates (scripts/check.sh full mode; docs/Sharding.md contract):

1. identity — with ``grad_quant_bits=8`` (int32 histogram scan, psum is
   integer-exact) the 4-device sharded trainer emits trees
   BYTE-identical to the single-device fused path, on both the fused
   and the per-iteration dispatch paths;
2. warm window — a second same-shape retrain window through a FRESH
   booster traces NOTHING new (the grower program cache holds across
   windows under sharding) and records a cache hit.

The heavy lifting runs in tests/_shard_worker.py (XLA's forced device
count must be set before jax initializes, hence the subprocess).  A
shard-environment failure is reported as SKIP with the reason and exits
0 — such failures in the CPU container are environmental (ROADMAP
memory note); the contract is re-gated on real multi-chip by
``bench.py --suite shard``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(os.path.dirname(HERE), "tests", "_shard_worker.py")


def main() -> int:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run([sys.executable, WORKER, "core"], env=env,
                          capture_output=True, text=True, timeout=540)
    if proc.returncode != 0:
        print(f"FAIL: shard worker rc={proc.returncode}\n"
              f"{proc.stderr[-3000:]}")
        return 1
    out = None
    for ln in reversed(proc.stdout.splitlines()):
        try:
            out = json.loads(ln)
            break
        except json.JSONDecodeError:
            continue
    if out is None:
        print(f"FAIL: worker printed no JSON\n{proc.stdout[-2000:]}")
        return 1
    if "skip" in out:
        print(f"SKIP: {out['skip']}")
        return 0

    checks = {
        "trees byte-identical (fused, 1 vs 4 devices, int8)":
            out.get("identity_fused") is True,
        "trees byte-identical (per-iteration sharded path)":
            out.get("identity_per_iter") is True,
        "f32 sharded run-to-run deterministic":
            out.get("f32_deterministic") is True,
        "bagging+feature_fraction shard-invariant":
            out.get("invariance_bag_ff") is True,
        "warm same-shape window traced nothing new":
            out.get("warm_window_new_compiles") == 0,
        "warm window hit the program cache":
            out.get("warm_window_cache_hit") is True,
    }
    ok = True
    for name, passed in checks.items():
        print(f"{'PASS' if passed else 'FAIL'}  {name}")
        ok = ok and passed
    digest = out.get("shard_digest")
    if digest:
        print(f"shard digest: devices={digest.get('devices')} "
              f"local_rows={digest.get('local_rows')} "
              f"sharded_dispatches={digest.get('sharded_dispatches')}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
