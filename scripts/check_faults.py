#!/usr/bin/env python
"""CI chaos smoke for the fault-tolerance layer (docs/Robustness.md).

Gates the two acceptance contracts of the robustness PR with the fault
registry standing in for real hardware death:

1. **Checkpoint/resume byte-identity** — a ``RetrainPipeline`` killed
   mid-stream (injected ``pipeline.prep`` fault at window 2) resumes
   from its per-window checkpoint and, under the deterministic config
   (``pipeline_rebin=false``, ``window_policy=fresh``), produces a
   final model BYTE-IDENTICAL to an uninterrupted reference run —
   while skipping the completed windows' prep entirely.

2. **Serve-through-device-death** — with a persistent injected
   ``serve.dispatch`` fault, the ``PredictionServer`` answers 100% of
   requests through the host fallback with outputs EXACTLY matching
   the host ``Booster.predict`` walk, trips its circuit breaker
   (``serve.degraded`` gauge = 1), and recovers to the device path
   once the fault clears.

Exit 0 on success, 1 with diagnostics on failure.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
          "num_iterations": 6, "device_growth": "on"}
WINDOWS = 4
ROWS = 4000
FEATURES = 8


def gate_pipeline_resume(failures):
    from lightgbm_tpu.pipeline import (PipelineError, PreppedWindow,
                                       RetrainPipeline)
    from lightgbm_tpu.robust import faults
    from lightgbm_tpu.robust.checkpoint import load_pipeline_checkpoint

    def make_prep(calls=None):
        def prep(w):
            if calls is not None:
                calls.append(w)
            rng = np.random.default_rng(300 + w)
            x = rng.standard_normal((ROWS, FEATURES))
            y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
            return PreppedWindow(label=y, dense=x)
        return prep

    kw = dict(window_policy="fresh", rebin_on_drift=False, serve=False)
    ref = RetrainPipeline(PARAMS, **kw)
    ref_final = ref.run(range(WINDOWS), make_prep())[-1] \
        .booster.model_to_string()

    ckpt = tempfile.mkdtemp(prefix="lgbm_faults_ckpt_")
    faults.configure("pipeline.prep:at=2")
    killed_at = None
    try:
        RetrainPipeline(PARAMS, checkpoint_dir=ckpt, **kw).run(
            range(WINDOWS), make_prep())
    except PipelineError as e:
        killed_at = e.window
    finally:
        faults.clear()
    if killed_at != 2:
        failures.append(f"injected prep fault killed window "
                        f"{killed_at!r}, expected 2")
        return {}
    cp = load_pipeline_checkpoint(ckpt)
    if cp is None or cp.window != 1:
        failures.append(f"checkpoint after the kill holds window "
                        f"{getattr(cp, 'window', None)!r}, expected 1")
        return {}

    calls = []
    resumed = RetrainPipeline.resume(ckpt, PARAMS, **kw)
    res = resumed.run(range(WINDOWS), make_prep(calls))
    final = res[-1].booster.model_to_string() if res else None
    if calls != [2, 3]:
        failures.append(f"resume re-prepped windows {calls}, "
                        f"expected [2, 3]")
    if final != ref_final:
        failures.append("resumed final model is NOT byte-identical to "
                        "the uninterrupted run")
    return {"killed_at": killed_at, "resumed_windows": calls,
            "byte_identical": final == ref_final}


def gate_serve_degrade(failures):
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.robust import CircuitBreaker, faults
    from lightgbm_tpu.serve.engine import PredictionServer

    rng = np.random.default_rng(7)
    x = rng.standard_normal((3000, FEATURES))
    y = (x[:, 0] > 0).astype(np.float64)
    cfg = Config(dict(PARAMS))
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(PARAMS["num_iterations"], chunk=3)
    bst._flush_pending()

    srv = PredictionServer(bst, breaker=CircuitBreaker(
        failure_threshold=2, reprobe_interval_s=0.05))
    srv.warmup([256])
    q = x[:256]
    host_ref = np.asarray(bst.predict(q))   # host walk (small batch)

    faults.configure("serve.dispatch:persist")
    answered = exact = 0
    requests = 20
    try:
        for _ in range(requests):
            out = np.asarray(srv.predict(q))
            answered += 1
            if np.array_equal(out, host_ref):
                exact += 1
    except Exception as e:   # noqa: BLE001 — the gate records it
        failures.append(f"request DROPPED under injected device death: "
                        f"{e!r}")
    finally:
        faults.clear()
    if answered != requests or exact != requests:
        failures.append(f"device-death serving: {answered}/{requests} "
                        f"answered, {exact}/{requests} host-exact")
    degraded_gauge = obs.registry().gauge("serve.degraded")
    if not srv.degraded or degraded_gauge != 1:
        failures.append(f"breaker did not trip (degraded={srv.degraded}"
                        f", gauge={degraded_gauge})")

    time.sleep(0.06)                        # past the re-probe window
    recovered = np.asarray(srv.predict(q))
    if srv.degraded or obs.registry().gauge("serve.degraded") != 0:
        failures.append("device path did not recover after the fault "
                        "cleared")
    if not np.allclose(recovered, host_ref, rtol=1e-4, atol=1e-6):
        failures.append("post-recovery device answers diverged from "
                        "host parity")
    return {"requests": requests, "answered": answered,
            "host_exact": exact,
            "fallbacks": obs.registry().counter(
                "serve.fallback_requests"),
            "recovered": not srv.degraded}


def main() -> int:
    from lightgbm_tpu import obs
    obs.configure(enabled=True)
    failures = []
    summary = {"pipeline": gate_pipeline_resume(failures),
               "serve": gate_serve_degrade(failures)}
    summary["obs_robust"] = obs.summary().get("robust")
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"FAULT SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print("fault smoke PASS: mid-stream kill resumed byte-identical, "
          f"{summary['serve']['host_exact']}/"
          f"{summary['serve']['requests']} requests served host-exact "
          "through injected device death, device path recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
