#!/usr/bin/env python
"""CI multi-host smoke: 2-process pod-slice training over a localhost
``jax.distributed`` coordinator vs a single-process baseline.

Gates (scripts/check.sh full mode; docs/Sharding.md multi-host
section):

1. identity — with ``grad_quant_bits=8`` (int32 histogram scan) the
   2-process ``data_sharding=multi_controller`` run emits trees
   BYTE-identical to the single-process ``single_controller`` run on
   the same 4-device global mesh, with bit-identical broadcast mapper
   layouts on both hosts;
2. warm window — a second same-shape retrain window on EVERY host
   traces nothing new;
3. fail-fast — a rank whose coordinator never answers raises the
   bounded peer-probe ``LightGBMError`` within the retry budget
   instead of hanging in ``jax.distributed.initialize``.

The heavy lifting runs in tests/_multihost_worker.py (one OS process
per rank; XLA's forced device count must be set before jax
initializes).  A pod bring-up failure in this container is reported as
SKIP with the reason and exits 0 — environmental (ROADMAP memory
note); the contract is re-gated on real pod slices by
``bench.py --suite shard --hosts N``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(os.path.dirname(HERE), "tests",
                      "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pod(scenario: str, hosts: int, outdir: str,
             timeout: int = 540) -> list[dict]:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    port = _free_port()
    logs = [os.path.join(outdir, f"{scenario}_r{r}.log")
            for r in range(hosts)]
    procs = []
    for r in range(hosts):
        with open(logs[r], "w") as fh:
            procs.append(subprocess.Popen(
                [sys.executable, WORKER, scenario, str(r), str(hosts),
                 str(port), outdir], env=env, stdout=fh,
                stderr=subprocess.STDOUT))
    for p in procs:
        p.wait(timeout=timeout)
    out = []
    for r in range(hosts):
        path = os.path.join(outdir, f"{scenario}_r{r}.json")
        if not os.path.exists(path):
            with open(logs[r]) as fh:
                tail = fh.read()[-3000:]
            raise RuntimeError(
                f"{scenario}: rank {r}/{hosts} wrote no result "
                f"(rc={procs[r].returncode})\n{tail}")
        with open(path) as fh:
            out.append(json.load(fh))
    return out


def main() -> int:
    outdir = tempfile.mkdtemp(prefix="check_mh_")
    subprocess.run([sys.executable, WORKER, "makedata", outdir],
                   check=True, capture_output=True)

    single = _run_pod("train", 1, outdir)[0]
    pod = _run_pod("train", 2, outdir)
    skip = next((r["skip"] for r in pod if "skip" in r), None)
    if skip is not None:
        print(f"SKIP: {skip}")
        return 0
    dead = _run_pod("deadcoord", 1, outdir, timeout=120)[0]

    checks = {
        "trees byte-identical (1 process vs 2-process pod, int8)":
            all(r["trees"] == single["trees"] for r in pod),
        "broadcast mapper layout bit-identical on both hosts":
            len({r["layout_digest"] for r in pod}) == 1
            and pod[0]["layout_digest"] == single["layout_digest"],
        "warm same-shape window traced nothing new on any host":
            all(r["warm_new_compiles"] == 0 for r in pod),
        "shard.hosts gauge reports the pod size":
            all(r["hosts_gauge"] == 2 for r in pod),
        "dead coordinator fails fast (bounded LightGBMError)":
            bool(dead["failfast_error"]) and dead["elapsed_s"] < 60,
    }
    ok = True
    for name, passed in checks.items():
        print(f"{'PASS' if passed else 'FAIL'}  {name}")
        ok = ok and passed
    print(f"pod digest: hosts=2 "
          f"ingest_rows_per_s={pod[0].get('ingest_rows_per_s')} "
          f"deadcoord_elapsed_s={round(dead['elapsed_s'], 2)}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
