#!/usr/bin/env python
"""Docs-freshness gate: ``docs/Parameters.md`` must match the schema.

Regenerates the parameter docs from ``lightgbm_tpu.params.PARAM_SCHEMA``
via :mod:`lightgbm_tpu.utils.gen_docs` and fails when the committed file
differs — the schema is the single source of truth, so a param change
without a doc regen is a CI error, not a silent drift.

Usage::

    python scripts/check_docs_params.py          # check, exit 1 on drift
    python scripts/check_docs_params.py --write  # regenerate in place

Run from ``scripts/check.sh`` and ``tests/test_checks.py``.
"""

from __future__ import annotations

import difflib
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
DOC = REPO / "docs" / "Parameters.md"


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    sys.path.insert(0, str(REPO))
    from lightgbm_tpu.utils.gen_docs import render

    fresh = render()
    if "--write" in argv:
        DOC.write_text(fresh)
        print(f"wrote {DOC}")
        return 0

    committed = DOC.read_text() if DOC.exists() else ""
    if committed == fresh:
        print(f"OK: {DOC} matches the parameter schema")
        return 0

    diff = list(difflib.unified_diff(
        committed.splitlines(keepends=True), fresh.splitlines(keepends=True),
        fromfile="docs/Parameters.md (committed)",
        tofile="docs/Parameters.md (regenerated)", n=2))
    sys.stderr.writelines(diff[:80])
    print(f"STALE: docs/Parameters.md is out of date with "
          f"lightgbm_tpu/params.py ({len(diff)} diff lines); regenerate "
          f"with `python scripts/check_docs_params.py --write`",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
