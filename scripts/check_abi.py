"""CI gate: the C ABI is in sync across all four surfaces, and the
native smoke test exercises the serving/fleet/warmup entry points.

No compiler needed — two grep-level checks on top of jaxlint's JL151
scanner:

1. a standalone ``--select JL151`` run over the package must report
   zero findings (header <-> cpp <-> bindings <-> adapter parity);
2. every ``LGBM_Serve*`` / ``LGBM_Fleet*`` / ``LGBM_Warmup*`` entry
   point the header declares must appear as a call in
   ``src/capi/smoke_test.cpp`` — a new serving ABI entry that ships
   without native smoke coverage fails CI here, not in a user's
   harness.

Run from the repo root: ``python scripts/check_abi.py``.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from lightgbm_tpu.tools.jaxlint.core import analyze_paths  # noqa: E402
from lightgbm_tpu.tools.jaxlint.rules.abi_parity import _scan_c  # noqa: E402

HEADER = REPO / "include" / "lightgbm_tpu" / "c_api.h"
SMOKE = REPO / "src" / "capi" / "smoke_test.cpp"
SMOKE_PREFIXES = ("LGBM_Serve", "LGBM_Fleet", "LGBM_Warmup")


def main() -> int:
    ok = True

    result = analyze_paths([str(REPO / "lightgbm_tpu")], root=str(REPO),
                           select={"JL151"})
    for path, msg in result.errors:
        print(f"check_abi: analyzer error in {path}: {msg}")
        ok = False
    for f in result.findings:
        print(f"check_abi: {f.path}:{f.line}: {f.rule} {f.message}")
        ok = False
    if ok:
        print("check_abi: JL151 parity clean "
              f"({result.files_scanned} files)")

    decls = _scan_c(HEADER.read_text(encoding="utf-8"), want_defs=False)
    targets = sorted(n for n in decls if n.startswith(SMOKE_PREFIXES))
    if not targets:
        print(f"check_abi: no serving entry points found in {HEADER} "
              "— scanner or header regression")
        return 1
    smoke = SMOKE.read_text(encoding="utf-8")
    missing = [n for n in targets
               if not re.search(rf"\b{n}\s*\(", smoke)]
    for n in missing:
        print(f"check_abi: header declares `{n}` but "
              f"{SMOKE.relative_to(REPO)} never calls it — extend the "
              "native smoke test to cover the new entry point")
    if missing:
        ok = False
    else:
        print(f"check_abi: smoke_test.cpp exercises all {len(targets)} "
              "Serve/Fleet/Warmup entry points")

    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
