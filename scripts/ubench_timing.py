#!/usr/bin/env python
"""Calibrate timing semantics on the axon-tunnel TPU backend.

block_until_ready vs device_get: a known-FLOP matmul chain tells us which
one reflects real device execution time.
"""
import json
import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/lgbm_tpu_xla"))
import jax
import jax.numpy as jnp
import numpy as np


def bench(name, fn, *args, flops=None, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    # block_until_ready timing
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    t_block = (time.perf_counter() - t0) / reps * 1e3
    # forced scalar fetch timing
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
        s = float(np.asarray(jnp.sum(out.astype(jnp.float32))
                             if out.dtype != jnp.float32 else jnp.sum(out)))
    t_fetch = (time.perf_counter() - t0) / reps * 1e3
    rec = {"case": name, "ms_block": round(t_block, 3),
           "ms_fetch": round(t_fetch, 3)}
    if flops:
        rec["tflops_block"] = round(flops / (t_block / 1e3) / 1e12, 1)
        rec["tflops_fetch"] = round(flops / (t_fetch / 1e3) / 1e12, 1)
    print(json.dumps(rec), flush=True)


def main():
    rng = np.random.default_rng(0)
    for m in (4096, 8192):
        a = jnp.asarray(rng.standard_normal((m, m)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        b = jnp.asarray(rng.standard_normal((m, m)).astype(np.float32)
                        ).astype(jnp.bfloat16)

        @jax.jit
        def chain(a, b):
            # 8 dependent matmuls -> 8 * 2*m^3 flops, can't be elided
            x = a
            for _ in range(8):
                x = jnp.dot(x, b, preferred_element_type=jnp.float32
                            ).astype(jnp.bfloat16)
                x = x / jnp.max(jnp.abs(x))
            return jnp.sum(x.astype(jnp.float32))

        bench(f"chain8_matmul_{m}", chain, a, b, flops=8 * 2 * m ** 3)

    # HBM bandwidth probe: big copy-add
    x = jnp.asarray(rng.standard_normal(2 ** 28).astype(np.float32))  # 1GB

    @jax.jit
    def sum_all(x):
        return jnp.sum(x)

    bench("sum_1GB", sum_all, x)


if __name__ == "__main__":
    main()
