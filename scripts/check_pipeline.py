#!/usr/bin/env python
"""CI smoke for the async windowed-retrain pipeline (docs/Pipeline.md).

Runs three same-shaped synthetic windows through
``lightgbm_tpu.pipeline.RetrainPipeline`` with the device grower on and
asserts the two contracts the subsystem exists for:

1. **Zero retraces after window 1**: once the first window has compiled
   the grower/serve/eval programs (the serve buckets are AOT-warmed at
   the first swap), every later window re-dispatches into cached
   programs — the obs-tracked jit compile total must not move between
   the end of window 1 and the end of the run.

2. **Serving never goes down**: a prober thread hammers
   ``PredictionServer.predict`` throughout; at least one request must
   succeed strictly INSIDE a later window's training interval (the
   mid-train serve), every request must succeed, and the post-train
   ``swap()`` must land shape-stable (``swap_same_shape=True``).

Exit 0 on success, 1 with a diagnostic on failure.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

WINDOW_ROWS = 6000
FEATURES = 10
WINDOWS = 3

PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "metric": "none",
          "device_growth": "on", "num_iterations": 6}


def main() -> int:
    from lightgbm_tpu import obs
    from lightgbm_tpu.pipeline import PreppedWindow, RetrainPipeline

    obs.configure(enabled=True)

    def make_window(seed):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((WINDOW_ROWS, FEATURES))
        y = (x[:, 0] + 0.5 * x[:, 1]
             + 0.2 * rng.standard_normal(WINDOW_ROWS) > 0).astype(
            np.float64)
        return x, y

    def prep(w):
        x, y = make_window(1000 + w)
        return PreppedWindow(label=y, dense=x, eval_dense=x,
                             eval_label=y)

    def eval_fn(pred, pw):
        err = float(np.mean((np.asarray(pred) >= 0.5)
                            != (pw.eval_label >= 0.5)))
        return {"prev_model_error": round(err, 4)}

    pipe = RetrainPipeline(PARAMS, chunk=3)

    probe_log = []          # (timestamp, ok)
    probe_stop = threading.Event()
    probe_rows = np.zeros((128, FEATURES))

    def prober():
        while not probe_stop.is_set():
            t = time.perf_counter()
            try:
                out = pipe.server.predict(probe_rows)
                ok = np.isfinite(np.asarray(out)).all()
            except Exception:   # noqa: BLE001 — the smoke records it
                ok = False
            probe_log.append((t, bool(ok)))
            time.sleep(0.02)

    def compiles_now():
        return sum(v["compiles"]
                   for v in obs.registry().snapshot()["jit"].values())

    state = {"compiles_after_w1": None, "prober": None}

    def on_window(res):
        if res.window == 0:
            # warm the prober's bucket, then unleash it: every compile
            # it needs exists before the window-1 boundary
            pipe.server.warmup([probe_rows.shape[0]])
            t = threading.Thread(target=prober, daemon=True)
            t.start()
            state["prober"] = t
        elif res.window == 1:
            state["compiles_after_w1"] = compiles_now()

    try:
        results = pipe.run(range(WINDOWS), prep, eval_fn=eval_fn,
                           on_window=on_window)
    finally:
        probe_stop.set()
        if state["prober"] is not None:
            state["prober"].join(timeout=5.0)

    failures = []
    compiles_end = compiles_now()
    if state["compiles_after_w1"] is None:
        failures.append("window 1 never completed")
    elif compiles_end != state["compiles_after_w1"]:
        snap = obs.registry().snapshot()["jit"]
        failures.append(
            f"retraces after window 1: jit compiles went "
            f"{state['compiles_after_w1']} -> {compiles_end} ({snap})")

    if len(results) != WINDOWS:
        failures.append(f"expected {WINDOWS} windows, got {len(results)}")
    for res in results[1:]:
        if res.swap_same_shape is not True:
            failures.append(f"window {res.window} swap changed shape "
                            f"(swap_same_shape={res.swap_same_shape})")

    if not probe_log:
        failures.append("prober made no requests")
    elif not all(ok for _, ok in probe_log):
        bad = sum(1 for _, ok in probe_log if not ok)
        failures.append(f"{bad}/{len(probe_log)} serve probes failed")
    else:
        spans = [r.train_span for r in results[1:]]
        mid_train = sum(1 for t, ok in probe_log
                        if ok and any(t0 <= t <= t1 for t0, t1 in spans))
        if mid_train == 0:
            failures.append("no serve probe succeeded during a retrain "
                            "(mid-train serving not demonstrated)")

    summary = {
        "windows": len(results),
        "compiles_after_w1": state["compiles_after_w1"],
        "compiles_end": compiles_end,
        "probes": len(probe_log),
        "mid_train_probes": sum(
            1 for t, ok in probe_log
            if ok and any(t0 <= t <= t1
                          for t0, t1 in (r.train_span
                                         for r in results[1:]))),
        "overlap_fraction": pipe.overlap_fraction,
        "rebinds": pipe.bins.rebinds,
        "policies": [r.policy for r in results],
        "errors": [r.eval_metrics for r in results],
    }
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"PIPELINE SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print("pipeline smoke PASS: zero retraces after window 1, "
          f"{summary['mid_train_probes']} mid-train serves, "
          f"overlap {summary['overlap_fraction']:.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
