#!/usr/bin/env python
"""CI fused-find smoke: fused find-best-in-wave == two-pass, byte-exact.

Fast contract check for the fused histogram+gain-scan wave layout
(``find_best_fusion``, ops/grow.py), run by ``scripts/check.sh``:

1. two boosters differing only in ``find_best_fusion=fused`` vs
   ``two_pass`` must emit byte-identical models — in f32 AND under the
   ``grad_quant_bits=8`` int32 scan (where identity is exact-arithmetic
   law, not luck);
2. the routing counters must prove the fused leg actually dispatched
   fused waves: ``grow.fused_find.*`` twins the leg's ``grow.hist.*``
   count, and the ``grow.wave_dispatch_factor`` gauge reads 1 (fused)
   vs 2 (two-pass).

Runs on the CPU backend, so tier-1 CI gates the contract without a
chip; ``bench.py --suite quant`` measures the fused-vs-two-pass pairing
for real on the TPU driver.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("LGBM_TPU_CHUNK", "8192")

ROWS = 3000
FEATURES = 8
PARAMS = {
    "objective": "binary", "verbosity": -1, "device_growth": "on",
    "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
    "seed": 20260807,
}


def _train(extra):
    import numpy as np

    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    rng = np.random.default_rng(11)
    x = rng.standard_normal((ROWS, FEATURES)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    cfg = Config({**PARAMS, **extra})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(4, chunk=2)
    bst._flush_pending()
    return bst


def _trees(bst) -> str:
    return bst.model_to_string().split("parameters:")[0]


def _pair_identical(label, extra) -> bool:
    from lightgbm_tpu import obs

    before = obs.registry().snapshot()["counters"]
    a = _train({**extra, "find_best_fusion": "fused"})
    mid = obs.registry().snapshot()["counters"]
    gauge_fused = obs.registry().snapshot()["gauges"].get(
        "grow.wave_dispatch_factor")
    b = _train({**extra, "find_best_fusion": "two_pass"})
    after = obs.registry().snapshot()["counters"]
    gauge_two = obs.registry().snapshot()["gauges"].get(
        "grow.wave_dispatch_factor")

    fused_hits = sum(
        mid.get(k, 0) - before.get(k, 0)
        for k in mid if k.startswith("grow.fused_find."))
    hist_hits = sum(
        mid.get(k, 0) - before.get(k, 0)
        for k in mid if k.startswith("grow.hist."))
    two_pass_fused_hits = sum(
        after.get(k, 0) - mid.get(k, 0)
        for k in after if k.startswith("grow.fused_find."))
    if fused_hits <= 0 or fused_hits != hist_hits:
        print(f"FAIL {label}: fused leg routing counters do not prove "
              f"fused dispatch (grow.fused_find={fused_hits}, "
              f"grow.hist={hist_hits})")
        return False
    if two_pass_fused_hits != 0:
        print(f"FAIL {label}: two-pass leg incremented grow.fused_find "
              f"({two_pass_fused_hits})")
        return False
    if gauge_fused != 1 or gauge_two != 2:
        print(f"FAIL {label}: grow.wave_dispatch_factor gauge "
              f"fused={gauge_fused} (want 1) two_pass={gauge_two} "
              f"(want 2)")
        return False
    if _trees(a) != _trees(b):
        print(f"FAIL {label}: fused and two-pass boosters produced "
              f"different models")
        return False
    print(f"{label}: models byte-identical, {fused_hits} fused "
          f"hist+find dispatches (factor 1 vs 2)")
    return True


def main() -> int:
    from lightgbm_tpu import obs
    from lightgbm_tpu.utils.log import set_verbosity

    set_verbosity(-1)
    obs.configure(enabled=True)
    ok = _pair_identical("f32 parity", {})
    ok = _pair_identical("int8 parity", {"grad_quant_bits": 8}) and ok
    print("fused-find smoke: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
