#!/usr/bin/env bash
# Single CI entrypoint: lint (ruff), static analysis (jaxlint against the
# committed baseline), telemetry-validator self-test, docs freshness, and
# the tier-1 pytest command from ROADMAP.md.  Runs every gate even after
# a failure so one run reports everything; exits nonzero if ANY failed.
#
# Usage: scripts/check.sh [--fast]   (--fast skips the tier-1 pytest run)

set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
declare -a results=()

step() {
    local name="$1"; shift
    echo "==> ${name}"
    if "$@"; then
        results+=("PASS  ${name}")
    else
        results+=("FAIL  ${name}")
        fail=1
    fi
    echo
}

# 1. ruff (pyproject [tool.ruff]); optional: the pinned CI image ships it,
#    dev boxes without it skip with a warning rather than a false failure
if command -v ruff >/dev/null 2>&1; then
    step "ruff" ruff check .
else
    echo "==> ruff: not installed, SKIPPED (pip install ruff)"
    results+=("SKIP  ruff (not installed)")
    echo
fi

# 2. jaxlint: new findings (not in jaxlint_baseline.json) fail the
#    build.  --fast runs incrementally against the content-hash cache
#    under .jaxlint_cache/ (unchanged files/tree replay instantly); the
#    full mode runs cold AND gates the cache itself (warm run must be
#    byte-identical and <= 25% of the cold wall time).  New findings
#    print as file:line:col in the CI log either way.
if [[ "${1:-}" == "--fast" ]]; then
    step "jaxlint (incremental)" python -m lightgbm_tpu.tools.jaxlint \
        lightgbm_tpu --baseline jaxlint_baseline.json \
        --cache-dir .jaxlint_cache
else
    # the gate script measures a guaranteed-cold run in a throwaway
    # cache dir and enforces warm <= 25% of cold with byte-identical
    # findings; the baseline-gated step itself uses the repo cache so
    # CI's persisted .jaxlint_cache actually pays off across runs
    step "jaxlint" python -m lightgbm_tpu.tools.jaxlint lightgbm_tpu \
        --baseline jaxlint_baseline.json --cache-dir .jaxlint_cache
    step "jaxlint cache gate (cold vs warm)" \
        python scripts/check_jaxlint_cache.py
fi

# 2b. jaxlint with NO baseline over the WHOLE package: the repo-wide
#     baseline ratcheted down to empty, so this pins an absolute
#     zero-findings contract with no baseline escape hatch (step 2
#     still runs separately to gate the baseline file itself).  Must
#     be a full-package scan: JL161's dead-registry-entry check is a
#     whole-program property — a subset scan that sees
#     robust/faults.py but not the arming calls in data/ and
#     boosting/ would report false dead entries
step "jaxlint (zero-debt, whole package)" python -m \
    lightgbm_tpu.tools.jaxlint lightgbm_tpu --no-baseline

# 2c. C-ABI smoke: JL151 parity standalone (header <-> cpp <-> bindings
#     <-> adapter table) plus a grep-level assertion that the native
#     smoke_test.cpp exercises every Serve*/Fleet*/Warmup* entry point
#     the header declares — no compiler needed in CI
step "abi parity + native smoke coverage" python scripts/check_abi.py

# 3. the telemetry schema validator validates itself
step "validate_metrics --self-test" \
    python scripts/validate_metrics.py --self-test

# 3b. bench-round regression guard: self-test, then diff the two
#     newest committed BENCH_r*.json rounds — a round that silently
#     lost >10% on a headline metric fails here, not in archaeology
step "bench_compare --self-test" \
    python scripts/bench_compare.py --self-test
step "bench_compare (committed rounds)" \
    python scripts/bench_compare.py --latest

# 4. docs/Parameters.md regenerates identically from the param schema
step "docs freshness" python scripts/check_docs_params.py

# 5. tier-1 tests (ROADMAP.md command)
if [[ "${1:-}" != "--fast" ]]; then
    # 5a. cold-start smoke: AOT warmup into a temp cache dir, then a
    #     fresh subprocess training run must report ZERO persistent-
    #     compile-cache misses for the warmed declaration
    #     (docs/ColdStart.md).  Spawns two XLA-compiling subprocesses,
    #     so it lives with the test runs, not the lint-speed --fast set
    step "coldstart smoke" python scripts/check_coldstart.py

    # 5b. pipeline smoke: 3 synth windows through the async windowed-
    #     retrain pipeline — zero retraces after window 1, serving
    #     answers mid-train, swaps stay shape-stable (docs/Pipeline.md)
    step "pipeline smoke" python scripts/check_pipeline.py

    # 5b2. fleet smoke: a 3-tenant FleetServer retrains tenant 0
    #      through the pipeline while tenants 1..2 serve — zero-retrace
    #      index-write swaps, >=1 successful serve strictly during the
    #      retrain, every probe byte-identical to the untouched
    #      tenants' solo servers (docs/Serving.md "Model fleets")
    step "fleet smoke" python scripts/check_fleet.py

    # 5b3. streaming-telemetry smoke: a healthy serve run must PASS its
    #      SLO spec and the same run under an LGBM_TPU_FAULTS persistent
    #      serve device-death injection must FAIL availability (the
    #      gate can fire); JSONL stream + Prometheus exposition
    #      validate; the disabled hot path stays a single flag check
    #      (docs/Observability.md "Streaming & SLOs")
    step "obs smoke" python scripts/check_obs.py

    # 5b4. trace smoke: a 2-window pipeline + serve round-trip with
    #      trace_context on — the serve.predict span's model link must
    #      walk swap -> window -> prep -> root on ONE trace_id, the
    #      submit->flush edge must parent to the caller, the export
    #      must pass --trace link validation with named thread lanes,
    #      and the disabled path must stay the no-op singleton
    #      (docs/Observability.md "Tracing & attribution")
    step "trace smoke" python scripts/check_trace.py

    # 5c. chaos smoke: a mid-stream kill (injected prep fault) resumes
    #     from the per-window checkpoint to a byte-identical final
    #     model, and serving under injected device death answers every
    #     request host-exact then recovers (docs/Robustness.md)
    step "fault smoke" python scripts/check_faults.py

    # 5d. quant smoke: the int8 Pallas wave-histogram kernel (interpret
    #     mode) must be BYTE-identical to the int8 einsum at kernel and
    #     whole-training level, with the int32 find-best scan active
    #     (ROUND8_NOTES.md)
    step "quant smoke" python scripts/check_quant.py

    # 5d2. fused-find smoke: the fused hist+gain-scan wave layout
    #      (find_best_fusion=fused) must train models BYTE-identical to
    #      the legacy two-pass layout in f32 and int8, with the
    #      grow.fused_find.* routing counters proving the fused program
    #      actually dispatched (one program per wave, not two)
    step "fused-find smoke" python scripts/check_fused.py

    # 5e. shard smoke: single-controller data-parallel training on a
    #     forced 4-device host mesh must emit trees byte-identical to
    #     the single-device fused path under grad_quant_bits=8, and a
    #     warm same-shape retrain window must trace NOTHING new
    #     (docs/Sharding.md)
    step "shard smoke" python scripts/check_shard.py

    # 5f. multi-host smoke: a 2-process localhost jax.distributed
    #     pod-slice run (data_sharding=multi_controller, one process
    #     per host streaming its own row stripe) must train trees
    #     byte-identical to the single-process single_controller run
    #     on the same 4-device global mesh, trace nothing new on warm
    #     windows on EVERY host, and fail fast against a dead
    #     coordinator (docs/Sharding.md "Multi-host pod slices")
    step "multihost smoke" python scripts/check_multihost.py

    # 5g. soak smoke: the composed fleet chaos soak (2 tenants x 3
    #     windows x 1 injected mid-window kill + poison batch + dead
    #     ingest peer + clock skew) must reach a PASS verdict on CPU:
    #     availability >= 99.9% through the kill, byte-identical
    #     resume, zero-retrace swaps after window 0, zero dropped
    #     export lines, and a same-seed replay reproducing the
    #     timeline digest (docs/Soak.md)
    step "soak smoke" python scripts/check_soak.py

    tier1() {
        rm -f /tmp/_t1.log
        timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ \
            -q -m 'not slow' --continue-on-collection-errors \
            -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
            | tee /tmp/_t1.log
        local rc=${PIPESTATUS[0]}
        echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' \
            /tmp/_t1.log | tr -cd . | wc -c)"
        return "$rc"
    }
    step "tier-1 pytest" tier1

    # 6. slow-marked tests: the heaviest fused-parity / multiprocess
    #    cases run here (full mode) instead of inside tier-1's 870 s
    #    budget; no timeout — these are minutes-long by design
    step "pytest (slow marked)" env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m slow \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly
fi

echo "=================================================="
for r in "${results[@]}"; do echo "$r"; done
exit "$fail"
