#!/usr/bin/env python
"""Schema-check a telemetry metrics JSON (docs/Observability.md).

Usage: ``python scripts/validate_metrics.py metrics.json``
Exit 0 when the document is schema-valid, 1 with one error per line
otherwise.  Also importable: ``validate(doc) -> list[str]`` (empty ==
valid).  ``tests/test_obs.py`` runs this against a live 2-iteration
``bench.py --metrics`` run so tier-1 exercises the enabled path end to
end.

``python scripts/validate_metrics.py --self-test`` checks the checker:
a synthetic known-good document must validate clean and each of a set
of planted schema violations must be caught (run from
``scripts/check.sh`` so CI notices when the validator itself rots).
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List

SCHEMA_NAME = "lightgbm-tpu-metrics"
SCHEMA_VERSION = 1

_TIMING_KEYS = ("count", "total_s", "mean_s", "p50_s", "p95_s", "max_s")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate(doc: Dict) -> List[str]:
    errors: List[str] = []

    def err(msg):
        errors.append(msg)

    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if doc.get("schema") != SCHEMA_NAME:
        err(f"schema != {SCHEMA_NAME!r}: {doc.get('schema')!r}")
    if doc.get("schema_version") != SCHEMA_VERSION:
        err(f"schema_version != {SCHEMA_VERSION}: "
            f"{doc.get('schema_version')!r}")
    for key in ("created_unix", "snapshot_unix"):
        if not _num(doc.get(key)):
            err(f"{key} missing or not a number")
    if not isinstance(doc.get("enabled"), bool):
        err("enabled missing or not a bool")

    counters = doc.get("counters")
    if not isinstance(counters, dict):
        err("counters missing or not an object")
    else:
        for k, v in counters.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                err(f"counter {k!r} is not a non-negative int: {v!r}")

    gauges = doc.get("gauges")
    if not isinstance(gauges, dict):
        err("gauges missing or not an object")
    else:
        for k, v in gauges.items():
            if not _num(v):
                err(f"gauge {k!r} is not a number: {v!r}")

    timings = doc.get("timings")
    if not isinstance(timings, dict):
        err("timings missing or not an object")
    else:
        for name, stat in timings.items():
            if not isinstance(stat, dict):
                err(f"timing {name!r} is not an object")
                continue
            for k in _TIMING_KEYS:
                if k not in stat:
                    err(f"timing {name!r} missing {k!r}")
                elif not _num(stat[k]):
                    err(f"timing {name!r}.{k} is not a number")
            if all(_num(stat.get(k)) for k in _TIMING_KEYS):
                if stat["count"] < 1:
                    err(f"timing {name!r} has count < 1")
                if stat["p50_s"] > stat["p95_s"] + 1e-9:
                    err(f"timing {name!r}: p50 > p95")
                if stat["p95_s"] > stat["max_s"] + 1e-9:
                    err(f"timing {name!r}: p95 > max")
                if stat["total_s"] + 1e-9 < stat["max_s"]:
                    err(f"timing {name!r}: total < max")

    jit = doc.get("jit")
    if not isinstance(jit, dict):
        err("jit missing or not an object")
    else:
        for name, ent in jit.items():
            if not isinstance(ent, dict):
                err(f"jit {name!r} is not an object")
                continue
            comp = ent.get("compiles")
            sigs = ent.get("signatures")
            if not isinstance(comp, int) or comp < 1:
                err(f"jit {name!r}.compiles is not a positive int")
            if not isinstance(sigs, dict) or not sigs:
                err(f"jit {name!r}.signatures missing or empty")
            elif isinstance(comp, int) and sum(sigs.values()) != comp:
                err(f"jit {name!r}: signature counts {sum(sigs.values())} "
                    f"!= compiles {comp}")

    mem = doc.get("device_memory", "MISSING")
    if mem == "MISSING":
        err("device_memory key missing (null is fine)")
    elif mem is not None:
        if not isinstance(mem, dict):
            err("device_memory is neither null nor an object")
        else:
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                v = mem.get(k)
                if not isinstance(v, int) or v < 0:
                    err(f"device_memory.{k} is not a non-negative int")

    events = doc.get("events")
    if not isinstance(events, dict):
        err("events missing or not an object")
    else:
        for k in ("recorded", "dropped"):
            v = events.get(k)
            if not isinstance(v, int) or v < 0:
                err(f"events.{k} is not a non-negative int")

    return errors


def validate_training_run(doc: Dict) -> List[str]:
    """Beyond schema shape: assertions a real (enabled) training run
    must satisfy — per-phase/iteration timings present, at least one
    tracked jit compile recorded."""
    errors = validate(doc)
    if errors:
        return errors
    if not doc["enabled"]:
        errors.append("run was not collected with telemetry enabled")
    timings = doc["timings"]
    if "train.iter" not in timings:
        errors.append("no train.iter timing (no boosting iteration ran?)")
    if not doc["jit"]:
        errors.append("no tracked jit compiles recorded")
    return errors


def _good_doc() -> Dict:
    """A minimal document that satisfies both ``validate`` and
    ``validate_training_run``."""
    return {
        "schema": SCHEMA_NAME, "schema_version": SCHEMA_VERSION,
        "created_unix": 1700000000.0, "snapshot_unix": 1700000001.0,
        "enabled": True,
        "counters": {"jit.compiles_total": 2},
        "gauges": {"device.bytes_in_use": 1024},
        "timings": {"train.iter": {"count": 2, "total_s": 0.5,
                                   "mean_s": 0.25, "p50_s": 0.2,
                                   "p95_s": 0.3, "max_s": 0.3}},
        "jit": {"grow": {"compiles": 2,
                         "signatures": {"f32[8,16]": 1, "f32[8,32]": 1}}},
        "device_memory": {"bytes_in_use": 1024,
                          "peak_bytes_in_use": 4096},
        "events": {"recorded": 10, "dropped": 0},
    }


def _mutate(doc: Dict, path, value) -> Dict:
    out = json.loads(json.dumps(doc))
    cur = out
    for k in path[:-1]:
        cur = cur[k]
    if value is _DELETE:
        del cur[path[-1]]
    else:
        cur[path[-1]] = value
    return out


_DELETE = object()

#: (description, mutation path, bad value, substring the error must carry)
_SELF_TEST_CASES = [
    ("wrong schema name", ("schema",), "other", "schema"),
    ("wrong schema version", ("schema_version",), 99, "schema_version"),
    ("missing enabled flag", ("enabled",), _DELETE, "enabled"),
    ("negative counter", ("counters", "jit.compiles_total"), -1,
     "non-negative"),
    ("boolean counter", ("counters", "jit.compiles_total"), True,
     "non-negative"),
    ("non-numeric gauge", ("gauges", "device.bytes_in_use"), "big",
     "gauge"),
    ("timing missing p95", ("timings", "train.iter", "p95_s"), _DELETE,
     "p95_s"),
    ("timing p50 > p95", ("timings", "train.iter", "p50_s"), 10.0,
     "p50 > p95"),
    ("timing total < max", ("timings", "train.iter", "total_s"), 0.01,
     "total < max"),
    ("jit signature count mismatch",
     ("jit", "grow", "signatures"), {"f32[8,16]": 5}, "compiles"),
    ("device_memory key dropped", ("device_memory",), _DELETE,
     "device_memory"),
    ("negative dropped events", ("events", "dropped"), -2, "events"),
]


def self_test() -> int:
    good = _good_doc()
    failures: List[str] = []
    errs = validate_training_run(good)
    if errs:
        failures.append(f"good document rejected: {errs}")
    for desc, path, value, needle in _SELF_TEST_CASES:
        errs = validate(_mutate(good, path, value))
        if not errs:
            failures.append(f"planted defect not caught: {desc}")
        elif not any(needle in e for e in errs):
            failures.append(
                f"planted defect {desc!r} caught with unexpected "
                f"message(s): {errs}")
    disabled = dict(_good_doc(), enabled=False)
    if "telemetry enabled" not in " ".join(
            validate_training_run(disabled)):
        failures.append("disabled run not rejected by "
                        "validate_training_run")
    if failures:
        for f in failures:
            print(f"SELF-TEST FAIL: {f}", file=sys.stderr)
        return 1
    print(f"OK: validator self-test passed "
          f"({len(_SELF_TEST_CASES) + 2} cases)")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv == ["--self-test"]:
        return self_test()
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    with open(argv[0]) as fh:
        doc = json.load(fh)
    errors = validate_training_run(doc)
    if errors:
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        return 1
    n_tim = len(doc["timings"])
    n_jit = sum(v["compiles"] for v in doc["jit"].values())
    print(f"OK: {argv[0]} schema-valid ({n_tim} timing series, "
          f"{n_jit} jit compiles, {doc['events']['recorded']} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
