#!/usr/bin/env python
"""Schema-check telemetry artifacts (docs/Observability.md).

Usage::

    python scripts/validate_metrics.py metrics.json     # snapshot doc
    python scripts/validate_metrics.py --stream s.jsonl # exporter stream
    python scripts/validate_metrics.py --prom m.prom    # exposition file
    python scripts/validate_metrics.py --trace t.json   # span links
    python scripts/validate_metrics.py --soak v.json    # soak verdict

Exit 0 when the document is schema-valid, 1 with one error per line
otherwise.  Also importable: ``validate(doc)`` /
``validate_stream_line(doc)`` / ``validate_prometheus(text)`` each
return ``list[str]`` (empty == valid).  ``tests/test_obs.py`` runs this
against a live 2-iteration ``bench.py --metrics`` run so tier-1
exercises the enabled path end to end.

``python scripts/validate_metrics.py --self-test`` checks the checker:
a synthetic known-good document must validate clean and each of a set
of planted schema violations must be caught (run from
``scripts/check.sh`` so CI notices when the validator itself rots).
"""

from __future__ import annotations

import json
import re
import sys
from typing import Dict, List, Optional

SCHEMA_NAME = "lightgbm-tpu-metrics"
SCHEMA_VERSION = 2
STREAM_SCHEMA_NAME = "lightgbm-tpu-stream"
STREAM_SCHEMA_VERSION = 1

_TIMING_KEYS = ("count", "total_s", "mean_s", "p50_s", "p95_s", "max_s")
_ROLL_TIMING_KEYS = ("count", "total_s", "mean_s", "p50_s", "p95_s",
                     "p99_s", "max_s")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate(doc: Dict) -> List[str]:
    errors: List[str] = []

    def err(msg):
        errors.append(msg)

    if not isinstance(doc, dict):
        return ["document is not a JSON object"]
    if doc.get("schema") != SCHEMA_NAME:
        err(f"schema != {SCHEMA_NAME!r}: {doc.get('schema')!r}")
    if doc.get("schema_version") != SCHEMA_VERSION:
        err(f"schema_version != {SCHEMA_VERSION}: "
            f"{doc.get('schema_version')!r}")
    for key in ("created_unix", "snapshot_unix"):
        if not _num(doc.get(key)):
            err(f"{key} missing or not a number")
    if not isinstance(doc.get("enabled"), bool):
        err("enabled missing or not a bool")

    counters = doc.get("counters")
    if not isinstance(counters, dict):
        err("counters missing or not an object")
    else:
        for k, v in counters.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                err(f"counter {k!r} is not a non-negative int: {v!r}")

    gauges = doc.get("gauges")
    if not isinstance(gauges, dict):
        err("gauges missing or not an object")
    else:
        for k, v in gauges.items():
            if not _num(v):
                err(f"gauge {k!r} is not a number: {v!r}")

    timings = doc.get("timings")
    if not isinstance(timings, dict):
        err("timings missing or not an object")
    else:
        for name, stat in timings.items():
            if not isinstance(stat, dict):
                err(f"timing {name!r} is not an object")
                continue
            for k in _TIMING_KEYS:
                if k not in stat:
                    err(f"timing {name!r} missing {k!r}")
                elif not _num(stat[k]):
                    err(f"timing {name!r}.{k} is not a number")
            if all(_num(stat.get(k)) for k in _TIMING_KEYS):
                if stat["count"] < 1:
                    err(f"timing {name!r} has count < 1")
                if stat["p50_s"] > stat["p95_s"] + 1e-9:
                    err(f"timing {name!r}: p50 > p95")
                if stat["p95_s"] > stat["max_s"] + 1e-9:
                    err(f"timing {name!r}: p95 > max")
                if stat["total_s"] + 1e-9 < stat["max_s"]:
                    err(f"timing {name!r}: total < max")

    jit = doc.get("jit")
    if not isinstance(jit, dict):
        err("jit missing or not an object")
    else:
        for name, ent in jit.items():
            if not isinstance(ent, dict):
                err(f"jit {name!r} is not an object")
                continue
            comp = ent.get("compiles")
            sigs = ent.get("signatures")
            if not isinstance(comp, int) or comp < 1:
                err(f"jit {name!r}.compiles is not a positive int")
            if not isinstance(sigs, dict) or not sigs:
                err(f"jit {name!r}.signatures missing or empty")
            elif isinstance(comp, int) and sum(sigs.values()) != comp:
                err(f"jit {name!r}: signature counts {sum(sigs.values())} "
                    f"!= compiles {comp}")

    mem = doc.get("device_memory", "MISSING")
    if mem == "MISSING":
        err("device_memory key missing (null is fine)")
    elif mem is not None:
        if not isinstance(mem, dict):
            err("device_memory is neither null nor an object")
        else:
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                v = mem.get(k)
                if not isinstance(v, int) or v < 0:
                    err(f"device_memory.{k} is not a non-negative int")

    events = doc.get("events")
    if not isinstance(events, dict):
        err("events missing or not an object")
    else:
        for k in ("recorded", "dropped"):
            v = events.get(k)
            if not isinstance(v, int) or v < 0:
                err(f"events.{k} is not a non-negative int")

    rolling = doc.get("rolling", "MISSING")
    if rolling == "MISSING":
        err("rolling key missing (null is fine)")
    elif rolling is not None:
        errors.extend(_validate_rolling(rolling))

    slo = doc.get("slo", "MISSING")
    if slo == "MISSING":
        err("slo key missing (null is fine)")
    elif slo is not None:
        errors.extend(_validate_slo_digest(slo))

    return errors


def _validate_rolling(roll) -> List[str]:
    """The rolling-window block (snapshot ``rolling`` key / the body of
    an exporter stream line): counter deltas+rates, gauge last/mean,
    timing percentiles over the window."""
    errors: List[str] = []
    err = errors.append
    if not isinstance(roll, dict):
        return ["rolling is neither null nor an object"]
    for k in ("bucket_s", "window_s", "now_unix"):
        if not _num(roll.get(k)):
            err(f"rolling.{k} missing or not a number")
    counters = roll.get("counters")
    if not isinstance(counters, dict):
        err("rolling.counters missing or not an object")
    else:
        for k, v in counters.items():
            if not isinstance(v, dict):
                err(f"rolling counter {k!r} is not an object")
                continue
            d = v.get("delta")
            if not isinstance(d, int) or isinstance(d, bool) or d < 0:
                err(f"rolling counter {k!r}.delta is not a "
                    f"non-negative int: {d!r}")
            r = v.get("rate_per_s")
            if not _num(r) or r < 0:
                err(f"rolling counter {k!r}.rate_per_s is not a "
                    f"non-negative number")
    gauges = roll.get("gauges")
    if not isinstance(gauges, dict):
        err("rolling.gauges missing or not an object")
    else:
        for k, v in gauges.items():
            if not isinstance(v, dict) or not _num(v.get("last")):
                err(f"rolling gauge {k!r} needs a numeric 'last'")
            elif v.get("mean") is not None and not _num(v["mean"]):
                err(f"rolling gauge {k!r}.mean is neither null nor a "
                    f"number")
    timings = roll.get("timings")
    if not isinstance(timings, dict):
        err("rolling.timings missing or not an object")
    else:
        for name, stat in timings.items():
            if not isinstance(stat, dict):
                err(f"rolling timing {name!r} is not an object")
                continue
            for k in _ROLL_TIMING_KEYS:
                if not _num(stat.get(k)):
                    err(f"rolling timing {name!r} missing numeric {k!r}")
            if all(_num(stat.get(k)) for k in _ROLL_TIMING_KEYS):
                if stat["count"] < 1:
                    err(f"rolling timing {name!r} has count < 1")
                if stat["p50_s"] > stat["p95_s"] + 1e-9:
                    err(f"rolling timing {name!r}: p50 > p95")
                if stat["p95_s"] > stat["p99_s"] + 1e-9:
                    err(f"rolling timing {name!r}: p95 > p99")
                if stat["p99_s"] > stat["max_s"] + 1e-9:
                    err(f"rolling timing {name!r}: p99 > max")
    return errors


def _validate_slo_digest(slo) -> List[str]:
    """The compact SloReport digest (snapshot/stream ``slo`` key, bench
    ``obs.slo``)."""
    errors: List[str] = []
    err = errors.append
    if not isinstance(slo, dict):
        return ["slo is neither null nor an object"]
    if not isinstance(slo.get("ok"), bool):
        err("slo.ok missing or not a bool")
    if not _num(slo.get("window_s")):
        err("slo.window_s missing or not a number")
    objectives = slo.get("objectives")
    if not isinstance(objectives, dict) or not objectives:
        err("slo.objectives missing or empty")
    else:
        for name, o in objectives.items():
            if not isinstance(o, dict):
                err(f"slo objective {name!r} is not an object")
                continue
            if not isinstance(o.get("ok"), bool):
                err(f"slo objective {name!r}.ok missing or not a bool")
            if not _num(o.get("target")):
                err(f"slo objective {name!r}.target is not a number")
            if o.get("observed") is not None and not _num(o["observed"]):
                err(f"slo objective {name!r}.observed is neither null "
                    f"nor a number")
        if (isinstance(slo.get("ok"), bool) and slo["ok"]
                and any(isinstance(o, dict) and o.get("ok") is False
                        for o in objectives.values())):
            err("slo.ok is true but an objective failed")
    return errors


def validate_stream_line(doc: Dict) -> List[str]:
    """One line of the exporter's JSONL time series
    (``stream_path``)."""
    if not isinstance(doc, dict):
        return ["stream line is not a JSON object"]
    errors: List[str] = []
    if doc.get("schema") != STREAM_SCHEMA_NAME:
        errors.append(f"stream schema != {STREAM_SCHEMA_NAME!r}: "
                      f"{doc.get('schema')!r}")
    if doc.get("schema_version") != STREAM_SCHEMA_VERSION:
        errors.append(f"stream schema_version != "
                      f"{STREAM_SCHEMA_VERSION}: "
                      f"{doc.get('schema_version')!r}")
    if not _num(doc.get("t_unix")):
        errors.append("stream t_unix missing or not a number")
    if doc.get("window_s") is None:
        # rolling opted out (configure(rolling=False)): the exporter
        # legitimately emits an empty-window line
        for k in ("counters", "gauges", "timings"):
            if doc.get(k) != {}:
                errors.append(f"stream line without a rolling window "
                              f"must carry an empty {k!r} object")
    else:
        errors.extend(_validate_rolling(
            {k: doc.get(k) for k in ("bucket_s", "window_s", "now_unix",
                                     "counters", "gauges", "timings")}))
    if doc.get("slo") is not None:
        errors.extend(_validate_slo_digest(doc["slo"]))
    return errors


SOAK_SCHEMA_NAME = "lightgbm-tpu-soak"
SOAK_SCHEMA_VERSION = 1
_SOAK_GATES = ("availability", "slo", "completed",
               "resume_byte_identity", "zero_retrace_swaps",
               "chaos_fired", "export", "throughput")
_SOAK_EVENT_KINDS = {"kill", "device_death", "poison", "dead_peer",
                     "clock_skew"}


def _validate_slo_report(slo) -> List[str]:
    """The FULL ``SloReport.to_json()`` (objectives as a LIST of
    SloResult objects — the compact digest's objectives are a dict,
    which :func:`_validate_slo_digest` covers)."""
    errors: List[str] = []
    err = errors.append
    if not isinstance(slo, dict):
        return ["slo is not an object"]
    if not isinstance(slo.get("ok"), bool):
        err("slo.ok missing or not a bool")
    if not _num(slo.get("window_s")):
        err("slo.window_s missing or not a number")
    objectives = slo.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        err("slo.objectives missing or not a non-empty list")
        return errors
    for o in objectives:
        if not isinstance(o, dict) or not o.get("name"):
            err("slo objective is not an object with a name")
            continue
        name = o["name"]
        if not isinstance(o.get("ok"), bool):
            err(f"slo objective {name!r}.ok missing or not a bool")
        if not o.get("comparator"):
            err(f"slo objective {name!r} missing comparator")
        if not _num(o.get("target")):
            err(f"slo objective {name!r}.target is not a number")
        if o.get("observed") is not None and not _num(o["observed"]):
            err(f"slo objective {name!r}.observed is neither null "
                f"nor a number")
    if (isinstance(slo.get("ok"), bool) and slo["ok"]
            and any(isinstance(o, dict) and o.get("ok") is False
                    for o in objectives)):
        err("slo.ok is true but an objective failed")
    return errors


def validate_soak(doc: Dict) -> List[str]:
    """Schema of a soak verdict (``--soak``; docs/Soak.md): the round's
    ``SOAK_r*.json`` wraps this under ``parsed``."""
    if not isinstance(doc, dict):
        return ["soak verdict is not a JSON object"]
    errors: List[str] = []
    err = errors.append
    if doc.get("schema") != SOAK_SCHEMA_NAME:
        err(f"soak schema != {SOAK_SCHEMA_NAME!r}: "
            f"{doc.get('schema')!r}")
    if doc.get("schema_version") != SOAK_SCHEMA_VERSION:
        err(f"soak schema_version != {SOAK_SCHEMA_VERSION}: "
            f"{doc.get('schema_version')!r}")
    if not isinstance(doc.get("ok"), bool):
        err("soak ok missing or not a bool")
    if not isinstance(doc.get("chip_pending"), bool):
        err("soak chip_pending missing or not a bool "
            "(the honesty flag is mandatory)")
    sc = doc.get("scenario")
    if not isinstance(sc, dict):
        err("soak scenario missing or not an object")
    else:
        for k in ("tenants", "windows", "seed"):
            if not _num(sc.get(k)):
                err(f"soak scenario.{k} missing or not a number")
    if not isinstance(doc.get("fault_spec"), str):
        err("soak fault_spec missing or not a string")
    digest = doc.get("timeline_digest")
    if not (isinstance(digest, str)
            and re.fullmatch(r"[0-9a-f]{64}", digest)):
        err("soak timeline_digest is not a sha256 hex digest")
    timeline = doc.get("timeline")
    if not isinstance(timeline, list):
        err("soak timeline missing or not a list")
    else:
        for i, e in enumerate(timeline):
            if not isinstance(e, dict) \
                    or e.get("kind") not in _SOAK_EVENT_KINDS:
                err(f"soak timeline[{i}] has no known event kind")
    errors.extend(f"soak {e}"
                  for e in _validate_slo_report(doc.get("slo")))
    gates = doc.get("gates")
    if not isinstance(gates, dict):
        err("soak gates missing or not an object")
    else:
        for name in _SOAK_GATES:
            g = gates.get(name)
            if not isinstance(g, dict) \
                    or not isinstance(g.get("ok"), bool):
                err(f"soak gate {name!r} missing or without a bool ok")
        if (isinstance(doc.get("ok"), bool) and doc["ok"]
                and any(isinstance(g, dict) and g.get("ok") is False
                        for g in gates.values())):
            err("soak ok is true but a gate failed")
        thr = gates.get("throughput")
        if isinstance(thr, dict):
            v = thr.get("train_s_per_1M_sampled_rows")
            if v is not None and not _num(v):
                err("soak throughput.train_s_per_1M_sampled_rows is "
                    "neither null nor a number")
            if not _num(thr.get("reference_s_per_1M")):
                err("soak throughput.reference_s_per_1M is not a "
                    "number")
    return errors


_PROM_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_PROM_SAMPLE = re.compile(
    r"^(?P<name>[^\s{]+)(?P<labels>\{[^}]*\})?\s+(?P<value>\S+)"
    r"(\s+\S+)?$")


def validate_prometheus(text: str) -> List[str]:
    """Prometheus text-exposition checks: metric-name legality, legal
    sample syntax, numeric values, no duplicate samples (same name +
    label set), at most one TYPE per family."""
    errors: List[str] = []
    err = errors.append
    seen_samples = set()
    typed = set()
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 3 and parts[1] == "TYPE":
                fam = parts[2]
                if not _PROM_NAME.match(fam):
                    err(f"line {ln}: illegal metric family name {fam!r}")
                if fam in typed:
                    err(f"line {ln}: duplicate TYPE for family {fam!r}")
                typed.add(fam)
            continue
        m = _PROM_SAMPLE.match(line)
        if not m:
            err(f"line {ln}: unparsable sample {line!r}")
            continue
        name = m.group("name")
        if not _PROM_NAME.match(name):
            err(f"line {ln}: illegal metric name {name!r}")
        try:
            float(m.group("value"))
        except ValueError:
            err(f"line {ln}: non-numeric sample value "
                f"{m.group('value')!r}")
        key = (name, m.group("labels") or "")
        if key in seen_samples:
            err(f"line {ln}: duplicate sample for {name}"
                f"{m.group('labels') or ''}")
        seen_samples.add(key)
    if not seen_samples:
        err("exposition has no samples")
    return errors


def validate_trace(doc) -> List[str]:
    """Span-link integrity for an exported trace (``--trace``).

    Accepts the Chrome-trace object (``obs.dump_trace``) or a plain
    list of event dicts (parsed ``dump_events_jsonl`` lines).  With
    ``trace_context`` on, span events carry ``trace_id``/``span_id``/
    ``parent_id`` in ``args``; the rules:

    * span_ids are unique and always accompanied by a trace_id;
    * every ``parent_id`` resolves to a recorded span (no orphans) and
      parent/child agree on trace_id;
    * parent chains terminate (no cycles);
    * cross-chain links (a serve span's ``model_span_id``) that resolve
      in-buffer must agree on ``model_trace_id`` — an unresolved link
      is NOT an error (the training span may predate a trace reset).
    """
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return ["chrome trace missing traceEvents array"]
    elif isinstance(doc, list):
        events = doc
    else:
        return ["trace document is neither a chrome-trace object nor "
                "an event list"]
    errors: List[str] = []
    err = errors.append
    spans: Dict[str, tuple] = {}   # span_id -> (name, trace_id, parent)
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            err(f"event {i} is not an object")
            continue
        args = ev.get("args")
        if not isinstance(args, dict):
            continue
        sid = args.get("span_id")
        if sid is None:
            continue
        name = ev.get("name", "?")
        trace = args.get("trace_id")
        if not trace:
            err(f"span {name!r} carries span_id {sid} but no trace_id")
        if sid in spans:
            err(f"duplicate span_id {sid} ({spans[sid][0]!r} and "
                f"{name!r})")
            continue
        spans[sid] = (name, trace, args.get("parent_id"))
    for sid, (name, trace, parent) in spans.items():
        if parent is None:
            continue
        if parent not in spans:
            err(f"orphan parent_id {parent} on span {name!r} ({sid})")
            continue
        ptrace = spans[parent][1]
        if trace and ptrace and trace != ptrace:
            err(f"span {name!r} trace_id {trace} != parent "
                f"{spans[parent][0]!r} trace_id {ptrace}")
    for sid in spans:
        seen = set()
        cur: Optional[str] = sid
        while cur is not None and cur in spans:
            if cur in seen:
                err(f"parent cycle reachable from span_id {sid}")
                break
            seen.add(cur)
            cur = spans[cur][2]
    for ev in events:
        if not isinstance(ev, dict):
            continue
        args = ev.get("args")
        if not isinstance(args, dict):
            continue
        link = args.get("model_span_id")
        if link and link in spans:
            want = args.get("model_trace_id")
            have = spans[link][1]
            if want and have and want != have:
                err(f"span {ev.get('name')!r} model_trace_id {want} "
                    f"!= linked span {spans[link][0]!r} trace_id "
                    f"{have}")
    return errors


def validate_training_run(doc: Dict) -> List[str]:
    """Beyond schema shape: assertions a real (enabled) training run
    must satisfy — per-phase/iteration timings present, at least one
    tracked jit compile recorded."""
    errors = validate(doc)
    if errors:
        return errors
    if not doc["enabled"]:
        errors.append("run was not collected with telemetry enabled")
    timings = doc["timings"]
    if "train.iter" not in timings:
        errors.append("no train.iter timing (no boosting iteration ran?)")
    if not doc["jit"]:
        errors.append("no tracked jit compiles recorded")
    return errors


def _good_doc() -> Dict:
    """A minimal document that satisfies both ``validate`` and
    ``validate_training_run``."""
    return {
        "schema": SCHEMA_NAME, "schema_version": SCHEMA_VERSION,
        "created_unix": 1700000000.0, "snapshot_unix": 1700000001.0,
        "enabled": True,
        "counters": {"jit.compiles_total": 2},
        "gauges": {"device.bytes_in_use": 1024},
        "timings": {"train.iter": {"count": 2, "total_s": 0.5,
                                   "mean_s": 0.25, "p50_s": 0.2,
                                   "p95_s": 0.3, "max_s": 0.3}},
        "jit": {"grow": {"compiles": 2,
                         "signatures": {"f32[8,16]": 1, "f32[8,32]": 1}}},
        "device_memory": {"bytes_in_use": 1024,
                          "peak_bytes_in_use": 4096},
        "events": {"recorded": 10, "dropped": 0},
        "rolling": {
            "bucket_s": 1.0, "window_s": 60.0,
            "now_unix": 1700000001.0,
            "counters": {"serve.ok": {"delta": 40,
                                      "rate_per_s": 0.666667}},
            "gauges": {"serve.degraded": {"last": 0, "mean": 0.0}},
            "timings": {"serve.predict": {
                "count": 40, "total_s": 0.08, "mean_s": 0.002,
                "p50_s": 0.002, "p95_s": 0.0024, "p99_s": 0.0024,
                "max_s": 0.0024}},
        },
        "slo": {
            "ok": True, "window_s": 60.0,
            "objectives": {
                "availability": {"target": 0.999, "observed": 1.0,
                                 "ok": True},
                "p95_ms": {"target": 50.0, "observed": 2.4,
                           "ok": True}},
            "counts": {"ok": 40, "fallback": 0, "failed": 0,
                       "input_errors": 0, "dark_fraction": 0.0},
        },
    }


def _good_stream_line() -> Dict:
    roll = _good_doc()["rolling"]
    return {"schema": STREAM_SCHEMA_NAME,
            "schema_version": STREAM_SCHEMA_VERSION,
            "t_unix": 1700000001.0, **roll,
            "slo": _good_doc()["slo"]}


_GOOD_PROM = """\
# TYPE lgbm_serve_ok_total counter
lgbm_serve_ok_total 40
# TYPE lgbm_serve_degraded gauge
lgbm_serve_degraded 0
# TYPE lgbm_serve_predict_seconds summary
lgbm_serve_predict_seconds{quantile="0.5"} 0.002
lgbm_serve_predict_seconds{quantile="0.95"} 0.0024
lgbm_serve_predict_seconds_sum 0.08
lgbm_serve_predict_seconds_count 40
"""


def _mutate(doc: Dict, path, value) -> Dict:
    out = json.loads(json.dumps(doc))
    cur = out
    for k in path[:-1]:
        cur = cur[k]
    if value is _DELETE:
        del cur[path[-1]]
    else:
        cur[path[-1]] = value
    return out


_DELETE = object()

#: (description, mutation path, bad value, substring the error must carry)
_SELF_TEST_CASES = [
    ("wrong schema name", ("schema",), "other", "schema"),
    ("wrong schema version", ("schema_version",), 99, "schema_version"),
    ("missing enabled flag", ("enabled",), _DELETE, "enabled"),
    ("negative counter", ("counters", "jit.compiles_total"), -1,
     "non-negative"),
    ("boolean counter", ("counters", "jit.compiles_total"), True,
     "non-negative"),
    ("non-numeric gauge", ("gauges", "device.bytes_in_use"), "big",
     "gauge"),
    ("timing missing p95", ("timings", "train.iter", "p95_s"), _DELETE,
     "p95_s"),
    ("timing p50 > p95", ("timings", "train.iter", "p50_s"), 10.0,
     "p50 > p95"),
    ("timing total < max", ("timings", "train.iter", "total_s"), 0.01,
     "total < max"),
    ("jit signature count mismatch",
     ("jit", "grow", "signatures"), {"f32[8,16]": 5}, "compiles"),
    ("device_memory key dropped", ("device_memory",), _DELETE,
     "device_memory"),
    ("negative dropped events", ("events", "dropped"), -2, "events"),
    ("rolling key dropped", ("rolling",), _DELETE, "rolling"),
    ("rolling counter negative delta",
     ("rolling", "counters", "serve.ok", "delta"), -1, "delta"),
    ("rolling timing p95 > p99",
     ("rolling", "timings", "serve.predict", "p95_s"), 9.0, "p95 > p99"),
    ("rolling gauge non-numeric last",
     ("rolling", "gauges", "serve.degraded", "last"), "dark", "last"),
    ("slo ok contradicts objectives",
     ("slo", "objectives", "availability", "ok"), False,
     "objective failed"),
    ("slo objectives emptied", ("slo", "objectives"), {}, "objectives"),
    ("slo non-bool ok", ("slo", "ok"), "yes", "slo.ok"),
]

def _good_soak_doc() -> Dict:
    """A minimal valid soak verdict (the docs/Soak.md schema)."""
    gates = {name: {"ok": True} for name in _SOAK_GATES}
    gates["throughput"].update(
        {"train_s_per_1M_sampled_rows": 2500.0,
         "reference_s_per_1M": 6.27, "chip_pending": True})
    return {
        "schema": SOAK_SCHEMA_NAME,
        "schema_version": SOAK_SCHEMA_VERSION,
        "scenario": {"tenants": 2, "windows": 3, "seed": 7},
        "fault_spec": "soak.kill:n=1,soak.clock:after=1:n=1",
        "timeline": [
            {"kind": "kill", "tenant": 0, "window": 1, "at": 0,
             "site": "soak.kill"},
            {"kind": "clock_skew", "at": 1, "site": "soak.clock"},
        ],
        "timeline_digest": "ab" * 32,
        "slo": {
            "spec": "availability>=0.999;source=serve.fleet",
            "source": "serve.fleet", "window_s": 600.0,
            "evaluated_unix": 1700000000.0, "ok": True,
            "objectives": [
                {"name": "availability", "comparator": ">=",
                 "target": 0.999, "observed": 1.0, "ok": True},
                {"name": "p95_ms", "comparator": "<=",
                 "target": 250.0, "observed": 12.5, "ok": True},
            ],
            "counts": {"ok": 700, "fallback": 0, "failed": 0,
                       "input_errors": 8, "dark_fraction": 0.0,
                       "availability": 1.0},
        },
        "gates": gates,
        "ok": True,
        "chip_pending": True,
    }


#: (description, mutation path, bad value, substring the error must
#: carry) — planted defects validate_soak must catch
_SOAK_SELF_TEST_CASES = [
    ("wrong soak schema", ("schema",), "other", "schema"),
    ("wrong soak schema version", ("schema_version",), 99,
     "schema_version"),
    ("missing chip_pending honesty flag", ("chip_pending",), _DELETE,
     "chip_pending"),
    ("non-bool verdict ok", ("ok",), "yes", "ok missing or not"),
    ("scenario dropped", ("scenario",), _DELETE, "scenario"),
    ("scenario without tenants", ("scenario", "tenants"), _DELETE,
     "tenants"),
    ("fault_spec dropped", ("fault_spec",), _DELETE, "fault_spec"),
    ("timeline digest not sha256", ("timeline_digest",), "xyz",
     "sha256"),
    ("timeline event with unknown kind", ("timeline", 0, "kind"),
     "meteor", "event kind"),
    ("slo objectives as dict (digest form, not full report)",
     ("slo", "objectives"), {}, "objectives"),
    ("slo objective missing comparator",
     ("slo", "objectives", 0, "comparator"), _DELETE, "comparator"),
    ("slo ok contradicts objective",
     ("slo", "objectives", 0, "ok"), False, "objective failed"),
    ("gate dropped", ("gates", "resume_byte_identity"), _DELETE,
     "resume_byte_identity"),
    ("gate without bool ok", ("gates", "export", "ok"), "fine",
     "export"),
    ("verdict ok contradicts a gate",
     ("gates", "availability", "ok"), False, "gate failed"),
    ("throughput reference dropped",
     ("gates", "throughput", "reference_s_per_1M"), _DELETE,
     "reference_s_per_1M"),
]


def _good_trace() -> Dict:
    """A chrome trace with one causal chain (root -> window -> swap)
    plus a serve span linking back to the swap."""
    def span(name, sid, trace="t1", parent=None, **extra):
        args = {"trace_id": trace, "span_id": sid, **extra}
        if parent:
            args["parent_id"] = parent
        return {"name": name, "cat": "x", "ph": "X", "pid": 0,
                "tid": 1, "ts": 0.0, "dur": 1.0, "args": args}
    return {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "lightgbm_tpu"}},
        span("pipeline.prep_window", "s1"),
        span("pipeline.window", "s2", parent="s1"),
        span("serve.swap", "s3", parent="s2"),
        span("serve.predict", "s4", model_trace_id="t1",
             model_span_id="s3"),
    ]}


#: (description, mutator(trace dict), substring the error must carry)
_TRACE_SELF_TEST_CASES = [
    ("orphan parent_id",
     lambda t: t["traceEvents"][2]["args"].update(parent_id="nope"),
     "orphan parent_id"),
    ("duplicate span_id",
     lambda t: t["traceEvents"][4]["args"].update(span_id="s1"),
     "duplicate span_id"),
    ("span_id without trace_id",
     lambda t: t["traceEvents"][2]["args"].pop("trace_id"),
     "no trace_id"),
    ("parent trace mismatch",
     lambda t: t["traceEvents"][3]["args"].update(trace_id="t2"),
     "trace_id"),
    ("model link trace mismatch",
     lambda t: t["traceEvents"][4]["args"].update(model_trace_id="t9"),
     "model_trace_id"),
    ("parent cycle",
     lambda t: t["traceEvents"][1]["args"].update(parent_id="s3"),
     "cycle"),
]

#: (description, bad exposition text, substring the error must carry)
_PROM_SELF_TEST_CASES = [
    ("illegal metric name",
     "# TYPE bad-name counter\nbad-name 1\n", "illegal metric name"),
    ("duplicate sample",
     "# TYPE lgbm_x_total counter\nlgbm_x_total 1\nlgbm_x_total 2\n",
     "duplicate sample"),
    ("duplicate TYPE",
     "# TYPE lgbm_x gauge\n# TYPE lgbm_x gauge\nlgbm_x 1\n",
     "duplicate TYPE"),
    ("non-numeric value", "lgbm_x NaNope\n", "non-numeric"),
    ("empty exposition", "# TYPE lgbm_x gauge\n", "no samples"),
]


def self_test() -> int:
    good = _good_doc()
    failures: List[str] = []
    errs = validate_training_run(good)
    if errs:
        failures.append(f"good document rejected: {errs}")
    for desc, path, value, needle in _SELF_TEST_CASES:
        errs = validate(_mutate(good, path, value))
        if not errs:
            failures.append(f"planted defect not caught: {desc}")
        elif not any(needle in e for e in errs):
            failures.append(
                f"planted defect {desc!r} caught with unexpected "
                f"message(s): {errs}")
    disabled = dict(_good_doc(), enabled=False)
    if "telemetry enabled" not in " ".join(
            validate_training_run(disabled)):
        failures.append("disabled run not rejected by "
                        "validate_training_run")
    # a snapshot without the streaming layer (rolling/slo null) is valid
    nulled = dict(_good_doc(), rolling=None, slo=None)
    errs = validate(nulled)
    if errs:
        failures.append(f"null rolling/slo rejected: {errs}")
    # the stream-line and exposition validators check themselves too
    errs = validate_stream_line(_good_stream_line())
    if errs:
        failures.append(f"good stream line rejected: {errs}")
    bad_line = dict(_good_stream_line(), schema="other")
    if not validate_stream_line(bad_line):
        failures.append("stream line with wrong schema not caught")
    # rolling-opted-out shape: window_s null + empty objects is valid,
    # null window with leftover data is not
    no_roll = {"schema": STREAM_SCHEMA_NAME,
               "schema_version": STREAM_SCHEMA_VERSION,
               "t_unix": 1700000001.0, "window_s": None,
               "counters": {}, "gauges": {}, "timings": {}}
    errs = validate_stream_line(no_roll)
    if errs:
        failures.append(f"rolling-disabled stream line rejected: {errs}")
    if not validate_stream_line(dict(no_roll,
                                     counters={"x": {"delta": 1}})):
        failures.append("null-window stream line with counters not "
                        "caught")
    errs = validate_trace(_good_trace())
    if errs:
        failures.append(f"good trace rejected: {errs}")
    # spans with no trace context (trace_context off) validate clean,
    # and an unresolved model link is legitimately not an error
    bare = {"traceEvents": [{"name": "x", "ph": "X", "pid": 0,
                             "tid": 1, "ts": 0.0, "dur": 1.0},
                            {"name": "serve.predict", "ph": "X",
                             "pid": 0, "tid": 1, "ts": 0.0, "dur": 1.0,
                             "args": {"model_span_id": "gone",
                                      "model_trace_id": "t0"}}]}
    errs = validate_trace(bare)
    if errs:
        failures.append(f"context-free trace rejected: {errs}")
    for desc, mutate, needle in _TRACE_SELF_TEST_CASES:
        t = _good_trace()
        mutate(t)
        errs = validate_trace(t)
        if not errs:
            failures.append(f"planted trace defect not caught: {desc}")
        elif not any(needle in e for e in errs):
            failures.append(
                f"planted trace defect {desc!r} caught with unexpected "
                f"message(s): {errs}")
    # the soak-verdict validator checks itself the same way
    errs = validate_soak(_good_soak_doc())
    if errs:
        failures.append(f"good soak verdict rejected: {errs}")
    for desc, path, value, needle in _SOAK_SELF_TEST_CASES:
        errs = validate_soak(_mutate(_good_soak_doc(), path, value))
        if not errs:
            failures.append(f"planted soak defect not caught: {desc}")
        elif not any(needle in e for e in errs):
            failures.append(
                f"planted soak defect {desc!r} caught with unexpected "
                f"message(s): {errs}")
    errs = validate_prometheus(_GOOD_PROM)
    if errs:
        failures.append(f"good exposition rejected: {errs}")
    for desc, text, needle in _PROM_SELF_TEST_CASES:
        errs = validate_prometheus(text)
        if not errs:
            failures.append(f"planted exposition defect not caught: "
                            f"{desc}")
        elif not any(needle in e for e in errs):
            failures.append(
                f"planted exposition defect {desc!r} caught with "
                f"unexpected message(s): {errs}")
    if failures:
        for f in failures:
            print(f"SELF-TEST FAIL: {f}", file=sys.stderr)
        return 1
    n = (len(_SELF_TEST_CASES) + len(_PROM_SELF_TEST_CASES)
         + len(_TRACE_SELF_TEST_CASES) + len(_SOAK_SELF_TEST_CASES)
         + 11)
    print(f"OK: validator self-test passed ({n} cases)")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv == ["--self-test"]:
        return self_test()
    if len(argv) == 2 and argv[0] == "--prom":
        errors = validate_prometheus(open(argv[1]).read())
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        if not errors:
            print(f"OK: {argv[1]} is valid Prometheus exposition")
        return 1 if errors else 0
    if len(argv) == 2 and argv[0] == "--trace":
        with open(argv[1]) as fh:
            head = fh.read(1)
            fh.seek(0)
            if head == "{":
                doc = json.load(fh)
                n_ev = len(doc.get("traceEvents", []))
            else:
                doc = [json.loads(line) for line in fh if line.strip()]
                n_ev = len(doc)
        errors = validate_trace(doc)
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        if not errors:
            print(f"OK: {argv[1]} span links intact ({n_ev} events)")
        return 1 if errors else 0
    if len(argv) == 2 and argv[0] == "--soak":
        with open(argv[1]) as fh:
            doc = json.load(fh)
        # accept the raw verdict, the committed round wrapper, and the
        # bench.py --suite soak result (verdict nested under "soak")
        if "parsed" in doc and "schema" not in doc:
            doc = doc["parsed"] or {}
        if "soak" in doc and "schema" not in doc:
            doc = doc["soak"] or {}
        errors = validate_soak(doc)
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        if not errors:
            gates = ",".join(sorted(doc.get("gates", {})))
            print(f"OK: {argv[1]} is a schema-valid soak verdict "
                  f"(ok={doc.get('ok')}, gates={gates})")
        return 1 if errors else 0
    if len(argv) == 2 and argv[0] == "--stream":
        errors = []
        n_lines = 0
        with open(argv[1]) as fh:
            for i, line in enumerate(fh, 1):
                if not line.strip():
                    continue
                n_lines += 1
                try:
                    doc = json.loads(line)
                except ValueError as e:
                    errors.append(f"line {i}: not JSON ({e})")
                    continue
                errors.extend(f"line {i}: {e}"
                              for e in validate_stream_line(doc))
        if not n_lines:
            errors.append("stream file has no lines")
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        if not errors:
            print(f"OK: {argv[1]} schema-valid ({n_lines} stream lines)")
        return 1 if errors else 0
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    with open(argv[0]) as fh:
        doc = json.load(fh)
    errors = validate_training_run(doc)
    if errors:
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        return 1
    n_tim = len(doc["timings"])
    n_jit = sum(v["compiles"] for v in doc["jit"].values())
    print(f"OK: {argv[0]} schema-valid ({n_tim} timing series, "
          f"{n_jit} jit compiles, {doc['events']['recorded']} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
