#!/usr/bin/env python
"""CI cold-start smoke: AOT warmup => a fresh training process is warm.

Fast contract check for the persistent-compile-cache story
(docs/ColdStart.md), run by scripts/check.sh:

1. spawn the ``lightgbm-tpu warmup`` CLI into a temp cache dir with a
   small declared (rows, features, config) shape;
2. spawn a FRESH subprocess that runs a real training of the SAME
   declaration (same synthetic generator, full iteration count — the
   warmup itself only runs one fused chunk + remainder);
3. assert the training process reports ZERO persistent-cache misses
   (every executable it dispatched was pre-compiled by the warmup) and
   a nonzero hit count.

A nonzero miss count means some program the production path dispatches
is not covered by the warmup's schedule — exactly the regression this
smoke exists to catch.

A second phase gates the PERSISTED STAGE PLAN contract (ROADMAP 1c):
a ``wave_plan=profiled`` run measures once and persists the derived
plan beside the compile cache; a FRESH subprocess of the same
declaration must adopt it from disk — plan_source ``persisted``, the
same plan digest, and ZERO re-profiles (``grow.plan_profiles`` == 0).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

ROWS = 3000
FEATURES = 8
DECLARATION = [
    "objective=binary", "num_leaves=15", "num_iterations=4",
    "fused_chunk=2", "device_growth=on", "max_bin=63", "verbosity=-1",
    "bagging_fraction=0.8", "bagging_freq=2", "feature_fraction=0.9",
]


def probe() -> int:
    """Fresh-process training run of the declared shape; prints the
    compile-cache counters as one JSON line."""
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import jax

    from lightgbm_tpu import compile_cache
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import set_verbosity
    from lightgbm_tpu.warmup import _synth_dataset

    set_verbosity(-1)
    from lightgbm_tpu import obs
    from lightgbm_tpu.ops import stage_plan as sp

    obs.configure(enabled=True)
    extra = [a.split("=", 1) for a in sys.argv[2:] if "=" in a]
    cfg = Config(dict([kv.split("=", 1) for kv in DECLARATION] + extra))
    compile_cache.configure_from_config(cfg)
    ds = _synth_dataset(ROWS, FEATURES, cfg)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(cfg.num_iterations, chunk=cfg.fused_chunk)
    jax.block_until_ready(bst.train_score)
    bst._flush_pending()
    out = compile_cache.counters()
    grower = getattr(bst, "_grower", None)
    out["plan_source"] = getattr(grower, "plan_source", None)
    out["plan_digest"] = sp.plan_digest(grower.stage_plan) \
        if grower is not None else None
    out["plan_profiles"] = obs.registry().counter("grow.plan_profiles")
    print(json.dumps(out))
    return 0


def main() -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    with tempfile.TemporaryDirectory(prefix="lgbm_coldstart_ci_") as tmp:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "LGBM_TPU_CHUNK": env.get("LGBM_TPU_CHUNK", "8192"),
            "LGBM_TPU_COMPILE_CACHE": tmp,
        })
        warm_cmd = ([sys.executable, "-m", "lightgbm_tpu", "warmup",
                     f"warmup_rows={ROWS}", f"warmup_features={FEATURES}"]
                    + DECLARATION)
        r = subprocess.run(warm_cmd, env=env, cwd=repo,
                           capture_output=True, text=True)
        if r.returncode != 0:
            print(f"FAIL warmup CLI rc={r.returncode}:\n"
                  f"{r.stderr[-2000:]}")
            return 1
        entries = len([f for f in os.listdir(tmp)
                       if f.endswith("-cache")])
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--probe"], env=env, cwd=repo,
                           capture_output=True, text=True)
        if r.returncode != 0:
            print(f"FAIL training probe rc={r.returncode}:\n"
                  f"{r.stderr[-2000:]}")
            return 1
        counters = json.loads(r.stdout.strip().splitlines()[-1])

        # phase 2 — persisted stage plans: a profiled run measures once
        # and persists beside the compile cache; a fresh subprocess of
        # the same declaration must adopt the plan from disk with ZERO
        # re-profiles (ROADMAP 1c / bench --suite coldstart's analog)
        runs = []
        for tag in ("profiled", "adopt"):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--probe",
                 "wave_plan=profiled"], env=env, cwd=repo,
                capture_output=True, text=True)
            if r.returncode != 0:
                print(f"FAIL stage-plan {tag} probe rc={r.returncode}:\n"
                      f"{r.stderr[-2000:]}")
                return 1
            runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
        plan_first, plan_second = runs
    print(f"coldstart smoke: warmup wrote {entries} cache entries; "
          f"fresh training run: {counters['hits']} hits, "
          f"{counters['misses']} misses")
    if counters["misses"] != 0:
        print("FAIL: the warmed cache did not cover the training run "
              "(a program the production path dispatches is missing "
              "from the warmup schedule)")
        return 1
    if counters["hits"] <= 0:
        print("FAIL: the training run never consulted the persistent "
              "cache (is it disabled?)")
        return 1
    print(f"stage plans: first run profiled {plan_first['plan_profiles']}"
          f"x (source={plan_first['plan_source']}); fresh run "
          f"re-profiled {plan_second['plan_profiles']}x "
          f"(source={plan_second['plan_source']})")
    if plan_first["plan_profiles"] != 1:
        print("FAIL: the wave_plan=profiled run did not measure exactly "
              "once")
        return 1
    if plan_second["plan_profiles"] != 0 \
            or plan_second["plan_source"] != "persisted":
        print("FAIL: the fresh subprocess re-profiled instead of "
              "adopting the persisted stage plan")
        return 1
    if plan_second["plan_digest"] != plan_first["plan_digest"]:
        print("FAIL: the adopted stage plan differs from the persisted "
              "one (digest mismatch)")
        return 1
    print("coldstart smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(probe() if "--probe" in sys.argv else main())
