#!/usr/bin/env python
"""CI cold-start smoke: AOT warmup => a fresh training process is warm.

Fast contract check for the persistent-compile-cache story
(docs/ColdStart.md), run by scripts/check.sh:

1. spawn the ``lightgbm-tpu warmup`` CLI into a temp cache dir with a
   small declared (rows, features, config) shape;
2. spawn a FRESH subprocess that runs a real training of the SAME
   declaration (same synthetic generator, full iteration count — the
   warmup itself only runs one fused chunk + remainder);
3. assert the training process reports ZERO persistent-cache misses
   (every executable it dispatched was pre-compiled by the warmup) and
   a nonzero hit count.

A nonzero miss count means some program the production path dispatches
is not covered by the warmup's schedule — exactly the regression this
smoke exists to catch.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

ROWS = 3000
FEATURES = 8
DECLARATION = [
    "objective=binary", "num_leaves=15", "num_iterations=4",
    "fused_chunk=2", "device_growth=on", "max_bin=63", "verbosity=-1",
    "bagging_fraction=0.8", "bagging_freq=2", "feature_fraction=0.9",
]


def probe() -> int:
    """Fresh-process training run of the declared shape; prints the
    compile-cache counters as one JSON line."""
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import jax

    from lightgbm_tpu import compile_cache
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import set_verbosity
    from lightgbm_tpu.warmup import _synth_dataset

    set_verbosity(-1)
    cfg = Config(dict(kv.split("=", 1) for kv in DECLARATION))
    compile_cache.configure_from_config(cfg)
    ds = _synth_dataset(ROWS, FEATURES, cfg)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(cfg.num_iterations, chunk=cfg.fused_chunk)
    jax.block_until_ready(bst.train_score)
    bst._flush_pending()
    print(json.dumps(compile_cache.counters()))
    return 0


def main() -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    with tempfile.TemporaryDirectory(prefix="lgbm_coldstart_ci_") as tmp:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "LGBM_TPU_CHUNK": env.get("LGBM_TPU_CHUNK", "8192"),
            "LGBM_TPU_COMPILE_CACHE": tmp,
        })
        warm_cmd = ([sys.executable, "-m", "lightgbm_tpu", "warmup",
                     f"warmup_rows={ROWS}", f"warmup_features={FEATURES}"]
                    + DECLARATION)
        r = subprocess.run(warm_cmd, env=env, cwd=repo,
                           capture_output=True, text=True)
        if r.returncode != 0:
            print(f"FAIL warmup CLI rc={r.returncode}:\n"
                  f"{r.stderr[-2000:]}")
            return 1
        entries = len([f for f in os.listdir(tmp)
                       if f.endswith("-cache")])
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--probe"], env=env, cwd=repo,
                           capture_output=True, text=True)
        if r.returncode != 0:
            print(f"FAIL training probe rc={r.returncode}:\n"
                  f"{r.stderr[-2000:]}")
            return 1
        counters = json.loads(r.stdout.strip().splitlines()[-1])
    print(f"coldstart smoke: warmup wrote {entries} cache entries; "
          f"fresh training run: {counters['hits']} hits, "
          f"{counters['misses']} misses")
    if counters["misses"] != 0:
        print("FAIL: the warmed cache did not cover the training run "
              "(a program the production path dispatches is missing "
              "from the warmup schedule)")
        return 1
    if counters["hits"] <= 0:
        print("FAIL: the training run never consulted the persistent "
              "cache (is it disabled?)")
        return 1
    print("coldstart smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(probe() if "--probe" in sys.argv else main())
