#!/usr/bin/env python
"""CI quant-parity smoke: int8 Pallas kernel == int8 einsum, byte-exact.

Fast contract check for the quantized histogram path
(``grad_quant_bits=8``), run by ``scripts/check.sh``:

1. kernel level — ``ops/hist_pallas.wave_hist_pallas`` in interpret
   mode must produce int32 histograms BIT-identical to the einsum
   formulation in ``ops/grow.GrowerPrograms._wave_hist`` (integer
   accumulation is associative, so any mismatch is a real layout or
   masking bug, never rounding);
2. training level — two boosters differing only in
   ``hist_kernel=interpret`` vs ``einsum`` must emit byte-identical
   models under the int32 find-best scan, and the routing counters
   must show the Pallas kernel actually served the pallas leg.

Runs on the CPU backend (interpret mode), so tier-1 CI gates the
contract without a chip; ``bench.py --suite quant`` measures the same
pairing for real on the TPU driver.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("LGBM_TPU_CHUNK", "8192")

ROWS = 3000
FEATURES = 8
PARAMS = {
    "objective": "binary", "verbosity": -1, "device_growth": "on",
    "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
    "grad_quant_bits": 8, "seed": 20260804,
}


def _train(extra):
    import numpy as np

    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    rng = np.random.default_rng(11)
    x = rng.standard_normal((ROWS, FEATURES)).astype(np.float32)
    y = (x[:, 0] + np.abs(x[:, 1]) > 0.5).astype(np.float32)
    cfg = Config({**PARAMS, **extra})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    bst = create_boosting(cfg)
    bst.init_train(ds)
    bst.train_chunked(4, chunk=2)
    bst._flush_pending()
    return bst


def _kernel_parity() -> bool:
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.ops.grow import DeviceGrower

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2000, FEATURES)).astype(np.float32)
    cfg = Config({**PARAMS, "hist_kernel": "interpret",
                  "grower_cache": False})
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label((x[:, 0] > 0).astype(np.float32))
    grower = DeviceGrower(ds, cfg)
    progs = grower.programs
    n = progs.n_pad
    w, k = progs.wave_width, progs.hist_cols
    leaf = jnp.asarray(rng.integers(0, w, n).astype(np.int32))
    ghk = jnp.asarray(
        rng.integers(-127, 128, (n, k)).astype(np.int8))
    pending = jnp.arange(w, dtype=jnp.int32)
    got = np.asarray(progs._wave_hist(grower.binned, leaf, ghk, pending))
    progs.use_pallas = False
    ref = np.asarray(progs._wave_hist(grower.binned, leaf, ghk, pending))
    if got.dtype != np.int32 or ref.dtype != np.int32:
        print(f"FAIL kernel parity: expected int32 histograms, got "
              f"pallas={got.dtype} einsum={ref.dtype}")
        return False
    if not np.array_equal(got, ref):
        bad = int((got != ref).sum())
        print(f"FAIL kernel parity: {bad} cells differ between the "
              f"int8 pallas kernel (interpret) and the int8 einsum")
        return False
    print(f"kernel parity: int8 pallas == int8 einsum bit-exact "
          f"({got.shape}, w={w}, k={k})")
    return True


def _training_parity() -> bool:
    from lightgbm_tpu import obs

    obs.configure(enabled=True)
    a = _train({"hist_kernel": "einsum"})
    before = obs.registry().snapshot()["counters"]
    b = _train({"hist_kernel": "interpret"})
    after = obs.registry().snapshot()["counters"]
    pallas_hits = after.get("grow.hist.pallas_int8", 0) \
        - before.get("grow.hist.pallas_int8", 0)
    if pallas_hits <= 0:
        print("FAIL training parity: the pallas leg never routed a "
              "dispatch through the pallas_int8 kernel "
              f"(counters: {after})")
        return False
    sa = a.model_to_string().split("parameters:")[0]
    sb = b.model_to_string().split("parameters:")[0]
    if sa != sb:
        print("FAIL training parity: int8 pallas and int8 einsum "
              "boosters produced different models")
        return False
    if not (a._grower.int_scan and b._grower.int_scan):
        print("FAIL training parity: int32 scan inactive at this shape "
              f"({a._grower.int_scan}, {b._grower.int_scan})")
        return False
    print(f"training parity: models byte-identical, int32 scan active, "
          f"{pallas_hits} pallas_int8 dispatches")
    return True


def main() -> int:
    from lightgbm_tpu.utils.log import set_verbosity

    set_verbosity(-1)
    ok = _kernel_parity()
    ok = _training_parity() and ok
    print("quant smoke: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
