#!/usr/bin/env python
"""Generate reference-parity fixtures under tests/fixtures/.

Drives the REFERENCE implementation's C API (``lib_lightgbm.so`` built
from ``/root/reference`` — see ``src/c_api.cpp``) via ctypes to produce
golden outputs this framework must reproduce:

* ``ref_<name>.model.txt``   — v2 model text saved by the reference
* ``ref_<name>.preds.txt``   — reference raw-score predictions on the
                               first PRED_ROWS rows of the training data
* ``ref_<name>.eval.json``   — reference train-metric curve
* ``ref_bins.jsonl``         — BinMapper::FindBin outputs (via
                               scripts/dump_ref_bins.cpp)
* ``ours_binary.model.txt`` + ``ref_preds_on_ours.txt`` — OUR trained
  model text and what the REFERENCE predicts after loading it (format
  round-trip evidence, generated once; the test replays our side)

Usage:  python scripts/make_parity_fixtures.py [--lib PATH]
Requires the reference build (cmake + make in .refbuild) and the
dump_ref_bins tool; see VERDICT r3 item 4 for the charter.
"""

from __future__ import annotations

import argparse
import ctypes
import json
import os
import subprocess
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

import parity_data as pd  # noqa: E402

FIXDIR = os.path.join(ROOT, "tests", "fixtures")

DTYPE_F64 = 1
PREDICT_RAW = 1


class Ref:
    """Minimal ctypes wrapper over the reference C API."""

    def __init__(self, lib_path):
        self.lib = ctypes.CDLL(lib_path)
        self.lib.LGBM_GetLastError.restype = ctypes.c_char_p
        # the fork changed LGBM_BoosterCreate to take a C++
        # unordered_map (its consumer is src/test.cpp); ref_shim.so
        # rebuilds the map from a plain param string
        self.shim = ctypes.CDLL(os.path.join(
            os.path.dirname(lib_path), "ref_shim.so"))

    def _check(self, rc):
        if rc != 0:
            raise RuntimeError(self.lib.LGBM_GetLastError().decode())

    def dataset(self, x, label, params=""):
        x = np.ascontiguousarray(x, np.float64)
        handle = ctypes.c_void_p()
        self._check(self.lib.LGBM_DatasetCreateFromMat(
            x.ctypes.data_as(ctypes.c_void_p), DTYPE_F64,
            ctypes.c_int32(x.shape[0]), ctypes.c_int32(x.shape[1]),
            ctypes.c_int(1), params.encode(), None,
            ctypes.byref(handle)))
        lab = np.ascontiguousarray(label, np.float32)
        self._check(self.lib.LGBM_DatasetSetField(
            handle, b"label", lab.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(lab)), ctypes.c_int(0)))
        return handle

    def train(self, ds, params, iters):
        bst = ctypes.c_void_p()
        self._check(self.shim.Shim_BoosterCreate(ds, params.encode(),
                                                 ctypes.byref(bst)))
        fin = ctypes.c_int(0)
        evals = []
        for _ in range(iters):
            self._check(self.lib.LGBM_BoosterUpdateOneIter(
                bst, ctypes.byref(fin)))
            out_len = ctypes.c_int(0)
            buf = (ctypes.c_double * 8)()
            self._check(self.lib.LGBM_BoosterGetEval(
                bst, ctypes.c_int(0), ctypes.byref(out_len), buf))
            evals.append([buf[i] for i in range(out_len.value)])
            if fin.value:
                break
        return bst, evals

    def save_to_string(self, bst):
        out_len = ctypes.c_int64(0)
        buf_len = 1 << 24
        buf = ctypes.create_string_buffer(buf_len)
        self._check(self.lib.LGBM_BoosterSaveModelToString(
            bst, ctypes.c_int(0), ctypes.c_int(-1),
            ctypes.c_int64(buf_len), ctypes.byref(out_len), buf))
        return buf.value.decode()

    def load_from_string(self, text):
        bst = ctypes.c_void_p()
        n_iters = ctypes.c_int(0)
        self._check(self.lib.LGBM_BoosterLoadModelFromString(
            text.encode(), ctypes.byref(n_iters), ctypes.byref(bst)))
        return bst

    def predict_raw(self, bst, x):
        x = np.ascontiguousarray(x, np.float64)
        nrow = x.shape[0]
        out_len = ctypes.c_int64(0)
        out = np.zeros(nrow * 8, np.float64)
        self._check(self.lib.LGBM_BoosterPredictForMat(
            bst, x.ctypes.data_as(ctypes.c_void_p), DTYPE_F64,
            ctypes.c_int32(nrow), ctypes.c_int32(x.shape[1]),
            ctypes.c_int(1), ctypes.c_int(PREDICT_RAW), ctypes.c_int(-1),
            b"", ctypes.byref(out_len), out.ctypes.data_as(
                ctypes.POINTER(ctypes.c_double))))
        return out[:out_len.value].copy()

    def free_booster(self, bst):
        self.lib.LGBM_BoosterFree(bst)

    def free_dataset(self, ds):
        self.lib.LGBM_DatasetFree(ds)


MODELS = {
    "binary": dict(
        label="bin",
        params="objective=binary metric=binary_logloss num_leaves=15 "
               "learning_rate=0.1 min_data_in_leaf=5 num_threads=1 "
               "verbosity=-1 max_bin=255",
        iters=20),
    "regression": dict(
        label="reg",
        params="objective=regression metric=l2 num_leaves=31 "
               "learning_rate=0.05 min_data_in_leaf=20 lambda_l1=0.5 "
               "lambda_l2=1.0 num_threads=1 verbosity=-1 max_bin=63",
        iters=15),
    "multiclass": dict(
        label="mc",
        params="objective=multiclass num_class=3 metric=multi_logloss "
               "num_leaves=7 learning_rate=0.1 min_data_in_leaf=10 "
               "num_threads=1 verbosity=-1 max_bin=127",
        iters=10),
}


def gen_models(ref: Ref):
    x = pd.make_features()
    y_bin, y_reg, y_mc = pd.make_labels(x)
    labels = {"bin": y_bin, "reg": y_reg, "mc": y_mc}
    for name, spec in MODELS.items():
        # bin with the model's own max_bin (the dataset owns binning; a
        # mismatched dataset-vs-train max_bin would silently train on
        # different bins than the recorded params claim)
        mb = spec["params"].split("max_bin=")[1].split()[0]
        ds = ref.dataset(x, labels[spec["label"]], f"max_bin={mb}")
        bst, evals = ref.train(ds, spec["params"], spec["iters"])
        text = ref.save_to_string(bst)
        preds = ref.predict_raw(bst, x[:pd.PRED_ROWS])
        with open(f"{FIXDIR}/ref_{name}.model.txt", "w") as fh:
            fh.write(text)
        np.savetxt(f"{FIXDIR}/ref_{name}.preds.txt", preds, fmt="%.17g")
        with open(f"{FIXDIR}/ref_{name}.eval.json", "w") as fh:
            json.dump({"params": spec["params"], "evals": evals}, fh,
                      indent=1)
        ref.free_booster(bst)
        ref.free_dataset(ds)
        print(f"{name}: {len(text)} chars, {len(preds)} preds, "
              f"final eval {evals[-1]}")

    # categorical model
    xc = pd.make_categorical_features()
    yc = pd.make_categorical_labels(xc)
    ds = ref.dataset(xc, yc, "max_bin=255 categorical_feature=0,1")
    params = ("objective=binary metric=binary_logloss num_leaves=15 "
              "learning_rate=0.1 min_data_in_leaf=5 num_threads=1 "
              "verbosity=-1 max_bin=255 categorical_feature=0,1 "
              "min_data_per_group=10 cat_smooth=10 cat_l2=10")
    bst, evals = ref.train(ds, params, 15)
    text = ref.save_to_string(bst)
    preds = ref.predict_raw(bst, xc[:pd.PRED_ROWS])
    with open(f"{FIXDIR}/ref_categorical.model.txt", "w") as fh:
        fh.write(text)
    np.savetxt(f"{FIXDIR}/ref_categorical.preds.txt", preds, fmt="%.17g")
    with open(f"{FIXDIR}/ref_categorical.eval.json", "w") as fh:
        json.dump({"params": params, "evals": evals}, fh, indent=1)
    ref.free_booster(bst)
    ref.free_dataset(ds)
    print(f"categorical: {len(text)} chars, final eval {evals[-1]}")


def gen_roundtrip(ref: Ref):
    """Train OUR framework, save v2 text, have the REFERENCE load it and
    predict; commit both sides."""
    from lightgbm_tpu.basic import Booster, Dataset

    x = pd.make_features()
    y_bin, _, _ = pd.make_labels(x)
    params = {"objective": "binary", "num_leaves": 15,
              "learning_rate": 0.1, "min_data_in_leaf": 5,
              "max_bin": 255, "verbosity": -1, "device_growth": "off",
              "deterministic": True}
    bst = Booster(params, Dataset(x, label=y_bin, params=params))
    for _ in range(10):
        bst.update()
    text = bst.model_to_string()
    with open(f"{FIXDIR}/ours_binary.model.txt", "w") as fh:
        fh.write(text)
    rbst = ref.load_from_string(text)
    preds = ref.predict_raw(rbst, x[:pd.PRED_ROWS])
    np.savetxt(f"{FIXDIR}/ref_preds_on_ours.txt", preds, fmt="%.17g")
    ref.free_booster(rbst)
    print(f"roundtrip: ours {len(text)} chars -> ref preds "
          f"mean {preds.mean():.6f}")


def gen_bins():
    tool = os.path.join(ROOT, ".refbuild", "dump_ref_bins")
    lines = []
    for name, max_bin, mdib, values in pd.bin_cases():
        v = np.asarray(values, np.float64)
        lines.append(f"{name} {max_bin} {mdib} 1 0 {len(v)}")
        lines.append(" ".join(f"{x:.17g}" for x in v))
    out = subprocess.run(
        [tool], input="\n".join(lines), capture_output=True, text=True,
        env={**os.environ,
             "LD_LIBRARY_PATH": os.path.join(ROOT, ".refbuild")},
        check=True)
    with open(f"{FIXDIR}/ref_bins.jsonl", "w") as fh:
        fh.write(out.stdout)
    print(f"bins: {len(out.stdout.splitlines())} cases")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lib", default=os.path.join(ROOT, ".refbuild",
                                                  "lib_lightgbm.so"))
    args = ap.parse_args()
    os.makedirs(FIXDIR, exist_ok=True)
    ref = Ref(args.lib)
    gen_bins()
    gen_models(ref)
    gen_roundtrip(ref)


if __name__ == "__main__":
    main()
