#!/usr/bin/env python
"""Bench-round regression guard: diff two bench.py result JSONs.

Compares every perf metric the two files share — ``ms_per_tree`` /
``rows_per_sec`` / speedups / coldstart ratios, including nested ones
(``legs.int8_pallas.ms_per_tree``, ``mslr.rows_per_sec``, ...) — and
flags changes worse than the threshold (default 10%) in each metric's
bad direction.  Accepts both raw ``bench.py`` stdout JSON and the
committed round wrapper (``BENCH_r*.json``: ``{"parsed": {...}}``).

Usage::

    python scripts/bench_compare.py OLD.json NEW.json
    python scripts/bench_compare.py --latest          # in-repo rounds:
        # per round FAMILY (BENCH_r*, MULTICHIP_r*, SOAK_r*, ...),
        # the newest round vs that family's previous parseable one
    python scripts/bench_compare.py --self-test       # CI sanity

Prints one JSON report line per compared pair (``regressions`` /
``improvements`` / ``unchanged`` + the obs digests of both runs when
present) and exits nonzero iff any metric regressed past the
threshold — CI runs ``--latest`` so a committed round that silently
loses >10% on a headline metric fails the build instead of being
archaeology.  Rounds only ever diff against their own family; a
global ordering would pair BENCH_r06 with MULTICHIP_r05 (different
suites = false regressions).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: metrics where smaller is better (matched on the LAST path component)
LOWER_BETTER = {
    "ms_per_tree", "time_per_tree_ms", "timed_s", "p50_ms", "p95_ms",
    "p99_ms", "psum_ms", "psum_ms_per_tree", "cold_warmup_compile_s",
    "warm_warmup_compile_s", "aot_warmup_compile_s",
}
#: metrics where bigger is better
HIGHER_BETTER = {
    "rows_per_sec", "rows_per_s", "speedup_vs_cpu", "aot_speedup",
    "shard_scaling_efficiency", "warm_speedup", "rows_per_s_per_model",
    "coverage",
}
#: units that orient the top-level "value" field when its metric name
#: doesn't already say (s/ms time down = good; x/fraction up = good)
_VALUE_LOWER_UNITS = ("s", "ms")
_VALUE_HIGHER_UNITS = ("x", "fraction", "rows/s")


def _unwrap(doc: dict) -> dict:
    """Raw bench.py output passes through; a committed round wrapper
    contributes its ``parsed`` block (None when the round crashed)."""
    if "parsed" in doc and "metric" not in doc:
        return doc["parsed"] or {}
    return doc


def extract_metrics(doc: dict) -> dict:
    """-> {dotted.path: (value, direction)} for every recognized
    numeric perf metric, walking nested suite results."""
    doc = _unwrap(doc)
    out = {}

    def walk(d, prefix):
        for k, v in d.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                if k == "obs":   # telemetry digest, not a perf metric
                    continue
                walk(v, path + ".")
                continue
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if k in LOWER_BETTER:
                out[path] = (float(v), "lower")
            elif k in HIGHER_BETTER:
                out[path] = (float(v), "higher")
            elif k == "value" and not d.get("chip_pending"):
                # chip-pending results (CPU-container evidence runs)
                # mark their headline "value" as not-chip-truth: a
                # cross-backend diff against a real TPU round's value
                # would flag a bogus regression.  Named nested metrics
                # (legs.*.ms_per_tree, ...) still compare — rounds of
                # the SAME suite share those paths and stay guarded.
                unit = str(d.get("unit", ""))
                if unit in _VALUE_LOWER_UNITS:
                    out[path] = (float(v), "lower")
                elif unit in _VALUE_HIGHER_UNITS:
                    out[path] = (float(v), "higher")

    walk(doc, "")
    return out


def obs_digest(doc: dict) -> dict:
    """Compact telemetry fingerprint of a run (when the round carried
    one): recompile totals and iteration percentiles explain WHY a
    number moved (e.g. a regression with jit_compiles_total up is a
    retrace bug, not a kernel slowdown)."""
    obs = _unwrap(doc).get("obs") or {}
    return {k: obs[k] for k in ("jit_compiles_total", "iter_p50_ms",
                                "iter_p95_ms", "events_recorded")
            if k in obs}


def compare(old: dict, new: dict, threshold: float) -> dict:
    om, nm = extract_metrics(old), extract_metrics(new)
    regressions, improvements, unchanged = [], [], []
    for path in sorted(set(om) & set(nm)):
        ov, direction = om[path]
        nv = nm[path][0]
        if ov == 0:
            continue
        # delta > 0 always means "got worse"
        delta = (nv - ov) / abs(ov) if direction == "lower" \
            else (ov - nv) / abs(ov)
        entry = {"metric": path, "old": ov, "new": nv,
                 "worse_by": round(delta, 4), "direction": direction}
        if delta > threshold:
            regressions.append(entry)
        elif delta < -threshold:
            improvements.append(entry)
        else:
            unchanged.append(path)
    return {
        "threshold": threshold,
        "compared": len(set(om) & set(nm)),
        "regressions": regressions,
        "improvements": improvements,
        "unchanged": unchanged,
        "obs_old": obs_digest(old),
        "obs_new": obs_digest(new),
    }


#: a committed round file: <FAMILY>_r<N>.json (BENCH_r06.json,
#: MULTICHIP_r05.json, SOAK_r01.json, ...).  Anything else in the glob
#: (BASELINE.json, BENCH_local_r4_preview.json's family
#: "BENCH_local") forms its own family or none, so it can never anchor
#: a cross-family diff
_ROUND_RE = re.compile(r"^([A-Za-z][A-Za-z0-9]*(?:_[A-Za-z0-9]+)*?)"
                       r"_r(\d+)\.json$")


def _family_round(path: str):
    """(family, round#) of a round file, or None when the name doesn't
    follow the <FAMILY>_r<N>.json convention."""
    m = _ROUND_RE.match(os.path.basename(path))
    if not m:
        return None
    return m.group(1), int(m.group(2))


def _round_key(path: str):
    fr = _family_round(path)
    return (fr[1] if fr else -1, path)


def latest_pairs(pattern: str):
    """Per-family newest pairs: group the glob's matches by their
    ``<FAMILY>_r<N>`` family prefix, and within EACH family return the
    newest round vs the previous PARSEABLE one (rounds whose
    ``parsed`` is null — crashed runs — can't anchor a diff).
    -> sorted [(family, old_path, new_path)].

    A single global ordering would interleave families (BENCH_r06 "vs"
    MULTICHIP_r05 diffs different suites = false regressions, and a
    young family like SOAK_r* would never pair at all)."""
    groups = {}
    for p in glob.glob(pattern):
        fr = _family_round(p)
        if fr is None:
            continue
        groups.setdefault(fr[0], []).append(p)
    pairs = []
    for fam in sorted(groups):
        usable = [p for p in sorted(groups[fam], key=_round_key)
                  if extract_metrics(json.load(open(p)))]
        if len(usable) >= 2:
            pairs.append((fam, usable[-2], usable[-1]))
    return pairs


def self_test() -> int:
    base = {"metric": "m", "value": 100.0, "unit": "s",
            "ms_per_tree": 50.0, "rows_per_sec": 1000.0,
            "legs": {"f32": {"ms_per_tree": 80.0}},
            "obs": {"jit_compiles_total": 3}}
    worse = json.loads(json.dumps(base))
    worse["ms_per_tree"] = 60.0          # +20%: regression
    worse["rows_per_sec"] = 1050.0       # +5%: within threshold
    worse["legs"]["f32"]["ms_per_tree"] = 70.0   # -12.5%: improvement
    rep = compare(base, worse, 0.10)
    assert [r["metric"] for r in rep["regressions"]] == ["ms_per_tree"], rep
    assert [r["metric"] for r in rep["improvements"]] \
        == ["legs.f32.ms_per_tree"], rep
    assert "rows_per_sec" in rep["unchanged"], rep
    assert rep["obs_old"] == {"jit_compiles_total": 3}
    # wrapper form + direction of higher-better metrics
    old = {"parsed": {"metric": "m", "value": 5.0, "unit": "x"}}
    new = {"parsed": {"metric": "m", "value": 4.0, "unit": "x"}}
    rep = compare(old, new, 0.10)
    assert [r["metric"] for r in rep["regressions"]] == ["value"], rep
    # crashed rounds (parsed: null) expose no metrics
    assert extract_metrics({"parsed": None, "rc": 1}) == {}
    # chip-pending rounds keep named metrics but drop the headline
    # "value" (a CPU container's value vs a TPU round's would diff
    # seconds against milliseconds of different machines)
    cp = {"metric": "m", "value": 9.0, "unit": "ms",
          "chip_pending": True,
          "legs": {"f32": {"ms_per_tree": 80.0}}}
    m = extract_metrics(cp)
    assert "value" not in m and "legs.f32.ms_per_tree" in m, m
    rep = compare({"metric": "m", "value": 200.0, "unit": "s"}, cp, 0.10)
    assert rep["compared"] == 0, rep
    # --latest groups rounds per family: each family pairs its own two
    # newest parseable rounds, never a cross-family diff, and files
    # outside the <FAMILY>_r<N>.json convention are ignored
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        def w(name, doc):
            with open(os.path.join(td, name), "w") as fh:
                json.dump(doc, fh)
        good = {"parsed": {"ms_per_tree": 50.0}}
        w("BENCH_r01.json", good)
        w("BENCH_r02.json", {"parsed": {"ms_per_tree": 52.0}})
        w("BENCH_r03.json", {"parsed": None, "rc": 1})  # crashed
        w("MULTICHIP_r01.json", good)
        w("MULTICHIP_r04.json", {"parsed": {"ms_per_tree": 49.0}})
        w("SOAK_r01.json", good)                  # young family: 1 round
        w("BASELINE.json", good)                  # not a round file
        w("BENCH_local_r4_preview.json", good)    # not <FAM>_r<N>.json
        pairs = latest_pairs(os.path.join(td, "*_r*.json"))
        assert [(f, os.path.basename(a), os.path.basename(b))
                for f, a, b in pairs] == [
            ("BENCH", "BENCH_r01.json", "BENCH_r02.json"),
            ("MULTICHIP", "MULTICHIP_r01.json", "MULTICHIP_r04.json"),
        ], pairs
        # numeric round ordering, not lexicographic
        w("MULTICHIP_r10.json", {"parsed": {"ms_per_tree": 48.0}})
        pairs = dict((f, (os.path.basename(a), os.path.basename(b)))
                     for f, a, b in latest_pairs(
                         os.path.join(td, "*_r*.json")))
        assert pairs["MULTICHIP"] == ("MULTICHIP_r04.json",
                                      "MULTICHIP_r10.json"), pairs
        # a second soak round makes the family pair up
        w("SOAK_r02.json", {"parsed": {"ms_per_tree": 51.0}})
        fams = [f for f, _, _ in latest_pairs(
            os.path.join(td, "*_r*.json"))]
        assert fams == ["BENCH", "MULTICHIP", "SOAK"], fams
    print("bench_compare self-test OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("files", nargs="*",
                    help="OLD.json NEW.json (bench.py output or "
                         "committed BENCH_r*.json round wrappers)")
    ap.add_argument("--latest", action="store_true",
                    help="for EACH round family matching --glob in the "
                         "repo root (BENCH_r*/MULTICHIP_r*/SOAK_r*/...)"
                         ", compare its two newest parseable rounds; "
                         "one report line per family")
    ap.add_argument("--glob", default="*_r*.json",
                    help="round pattern for --latest (matches are "
                         "grouped per <FAMILY>_r<N> family)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative worsening that counts as a "
                         "regression (default 0.10 = 10%%)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args()
    if args.self_test:
        return self_test()
    if args.latest:
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pairs = latest_pairs(os.path.join(here, args.glob))
        if not pairs:
            print(json.dumps({"skipped": "no round family has two "
                                         "parseable rounds",
                              "glob": args.glob}))
            return 0
        rc = 0
        for fam, old_path, new_path in pairs:
            with open(old_path) as fh:
                old = json.load(fh)
            with open(new_path) as fh:
                new = json.load(fh)
            report = compare(old, new, args.threshold)
            report["family"] = fam
            report["old_file"] = os.path.basename(old_path)
            report["new_file"] = os.path.basename(new_path)
            print(json.dumps(report))
            if report["regressions"]:
                rc = 1
        return rc
    if len(args.files) == 2:
        old_path, new_path = args.files
    else:
        ap.error("need OLD.json NEW.json, --latest, or --self-test")
    with open(old_path) as fh:
        old = json.load(fh)
    with open(new_path) as fh:
        new = json.load(fh)
    report = compare(old, new, args.threshold)
    report["old_file"] = os.path.basename(old_path)
    report["new_file"] = os.path.basename(new_path)
    print(json.dumps(report))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
