// Plain-C shim over the fork's modified C API.
//
// The reference fork changed LGBM_BoosterCreate (and the CSR/CSC dataset
// constructors) to take std::unordered_map<std::string,std::string>
// parameters (include/LightGBM/c_api.h:152,342 — its own consumer is
// src/test.cpp), which ctypes cannot call.  This shim rebuilds the map
// from a "key=value key=value" string and forwards, exporting an
// unmangled C ABI for scripts/make_parity_fixtures.py.  PredictForMat
// kept the plain const char* parameter in this fork, so the generator
// calls it directly via ctypes.
//
// Build: g++ -O2 -std=c++11 -fopenmp -shared -fPIC \
//   -I /root/reference/include scripts/ref_shim.cpp \
//   -L .refbuild -l_lightgbm -o .refbuild/ref_shim.so
#include <LightGBM/c_api.h>
#include <LightGBM/utils/common.h>

#include <string>
#include <unordered_map>

static std::unordered_map<std::string, std::string> ParseMap(
    const char* parameters) {
  std::unordered_map<std::string, std::string> out;
  for (const auto& kv :
       LightGBM::Common::Split(parameters, " \t\n\r")) {
    auto pos = kv.find('=');
    if (pos != std::string::npos) {
      out[kv.substr(0, pos)] = kv.substr(pos + 1);
    }
  }
  return out;
}

extern "C" {

int Shim_BoosterCreate(const void* train_data, const char* parameters,
                       void** out) {
  return LGBM_BoosterCreate(const_cast<void*>(train_data),
                            ParseMap(parameters), out);
}

}  // extern "C"
