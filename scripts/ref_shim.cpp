// Plain-C shim over the fork's modified C API.
//
// The reference fork changed LGBM_BoosterCreate / PredictForMat (and
// friends) to take std::unordered_map<std::string,std::string> parameters
// (include/LightGBM/c_api.h:152,342,632 — its own consumer is
// src/test.cpp), which ctypes cannot call.  This shim rebuilds the map
// from a "key=value key=value" string and forwards, exporting an
// unmangled C ABI for scripts/make_parity_fixtures.py.
//
// Build: g++ -O2 -std=c++11 -fopenmp -shared -fPIC \
//   -I /root/reference/include scripts/ref_shim.cpp \
//   -L .refbuild -l_lightgbm -o .refbuild/ref_shim.so
#include <LightGBM/c_api.h>
#include <LightGBM/utils/common.h>

#include <string>
#include <unordered_map>

static std::unordered_map<std::string, std::string> ParseMap(
    const char* parameters) {
  std::unordered_map<std::string, std::string> out;
  for (const auto& kv :
       LightGBM::Common::Split(parameters, " \t\n\r")) {
    auto pos = kv.find('=');
    if (pos != std::string::npos) {
      out[kv.substr(0, pos)] = kv.substr(pos + 1);
    }
  }
  return out;
}

extern "C" {

int Shim_BoosterCreate(const void* train_data, const char* parameters,
                       void** out) {
  return LGBM_BoosterCreate(const_cast<void*>(train_data),
                            ParseMap(parameters), out);
}

int Shim_BoosterPredictForMat(void* handle, const void* data, int data_type,
                              int32_t nrow, int32_t ncol, int is_row_major,
                              int predict_type, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  // PredictForMat kept the const char* parameter in this fork
  return LGBM_BoosterPredictForMat(handle, data, data_type, nrow, ncol,
                                   is_row_major, predict_type,
                                   num_iteration, parameter, out_len,
                                   out_result);
}

}  // extern "C"
