#!/usr/bin/env python
"""Microbenchmarks for the wave-histogram hot path (run on the real chip).

Timing protocol: the axon-tunnel backend makes naive per-dispatch timing
unreliable (block_until_ready returns implausible times for small
programs), so every case runs ITERS data-dependent repetitions inside ONE
jitted fori_loop and fetches a scalar at the end; per-iteration time is
(T(iters) - T(1)) / (iters - 1), which cancels dispatch + RTT overhead.

Usage: python scripts/ubench_hist.py [--rows N]
Each case prints one JSON line.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/lgbm_tpu_xla"))

import sys as _sys
_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

CHUNK = 32768


def run_case(name, body, state0, arrays=(), iters=8, flops=None,
             bytes_=None):
    """body: (state, i, arrays) -> state with a data dependency through
    state.  Big arrays MUST go through ``arrays`` (a closure constant
    ships inside the remote-compile request and blows its size limit)."""
    def make(k):
        @jax.jit
        def run(s, *arrs):
            s = jax.lax.fori_loop(0, k, lambda i, t: body(t, i, arrs), s)
            return jax.tree.map(
                lambda x: jnp.sum(x.astype(jnp.float32)) if x.ndim else x,
                s)
        return run

    def timed(run, s0):
        out = run(s0, *arrays)
        jax.block_until_ready(jax.tree.map(np.asarray, out))
        t0 = time.perf_counter()
        out = run(s0, *arrays)
        jax.tree.map(np.asarray, out)
        return time.perf_counter() - t0

    t1 = timed(make(1), state0)
    tk = timed(make(iters), state0)
    ms = (tk - t1) / (iters - 1) * 1e3
    rec = {"case": name, "ms": round(ms, 2),
           "ms_1": round(t1 * 1e3, 1), "ms_k": round(tk * 1e3, 1)}
    if flops:
        rec["tflops"] = round(flops / (ms / 1e3) / 1e12, 1)
    if bytes_:
        rec["gbps"] = round(bytes_ / (ms / 1e3) / 1e9, 1)
    print(json.dumps(rec), flush=True)
    return ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_500_000)
    ap.add_argument("--groups", type=int, default=28)
    ap.add_argument("--nb", type=int, default=64)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--cases", type=str, default="")
    args = ap.parse_args()

    n = (args.rows + CHUNK - 1) // CHUNK * CHUNK
    g, nb = args.groups, args.nb
    it = args.iters
    rng = np.random.default_rng(0)
    binned_np = rng.integers(0, nb, (n, g), dtype=np.uint8)
    binned = jnp.asarray(binned_np)
    binned_t = jnp.asarray(np.ascontiguousarray(binned_np.T))
    leaf_id = jnp.asarray(rng.integers(0, 64, n, dtype=np.int32))
    grad = jnp.asarray(rng.standard_normal(n, dtype=np.float32))
    hess = jnp.asarray(rng.random(n, dtype=np.float32))
    print(json.dumps({"case": "setup", "rows": n, "groups": g, "nb": nb,
                      "device": str(jax.devices()[0])}), flush=True)
    want = set(args.cases.split(",")) if args.cases else None

    def on(name):
        return want is None or name in want

    ghi = grad.astype(jnp.bfloat16)
    glo = (grad - ghi.astype(jnp.float32)).astype(jnp.bfloat16)
    hhi = hess.astype(jnp.bfloat16)
    one = jnp.ones((n,), jnp.bfloat16)
    gh5 = jnp.stack([ghi, glo, hhi,
                     (hess - hhi.astype(jnp.float32)).astype(jnp.bfloat16),
                     one], 1)
    gh3 = jnp.stack([ghi, hhi, one], 1)

    def hist_body(w, st, i, arrs):
        """One wave-histogram pass; the accumulator feeds the next pending
        set so iterations are data-dependent and can't be collapsed."""
        binned_a, leaf_a, ghk = arrs
        acc_sum, pending = st
        k = ghk.shape[1]
        n_chunks = n // CHUNK
        binned_c = binned_a.reshape(n_chunks, CHUNK, g)
        leaf_c = leaf_a.reshape(n_chunks, CHUNK)
        gh_c = ghk.reshape(n_chunks, CHUNK, k)

        def body(acc, xs):
            b, l, g5 = xs
            oh = jax.nn.one_hot(b, nb, dtype=jnp.bfloat16)
            lm = (l[:, None] == pending[None, :]).astype(jnp.bfloat16)
            bmat = (lm[:, :, None] * g5[:, None, :]).reshape(CHUNK, w * k)
            out = jnp.einsum("cgn,cb->gnb", oh, bmat,
                             preferred_element_type=jnp.float32)
            return acc + out, None

        acc0 = jnp.zeros((g, nb, w * k), jnp.float32)
        acc, _ = jax.lax.scan(body, acc0, (binned_c, leaf_c, gh_c))
        s = jnp.sum(acc)
        # data dependency: next pending shifts by a value derived from acc
        shift = (s * 1e-30).astype(jnp.int32) + 1
        return acc_sum + s, (pending + shift) % 64

    for name, ghk, w in [("hist5_w25", gh5, 25),
                         ("hist3_w25", gh3, 25),
                         ("hist3_w42", gh3, 42),
                         ("hist3_w4", gh3, 4),
                         ("hist3_w84", gh3, 84),
                         ("hist3_w126", gh3, 126)]:
        if not on(name):
            continue
        pend0 = jnp.arange(w, dtype=jnp.int32)
        flops = n * g * nb * w * ghk.shape[1] * 2
        run_case(name, functools.partial(hist_body, w),
                 (jnp.float32(0), pend0), arrays=(binned, leaf_id, ghk),
                 iters=it, flops=flops)

    # ---- Pallas v2 kernel vs the einsum --------------------------------
    def pallas_v2_body(w, ch, st, i, arrs):
        from lightgbm_tpu.ops.hist_pallas import wave_hist_pallas_v2
        binned_a, leaf_a, ghk = arrs
        acc_sum, pending = st
        out = wave_hist_pallas_v2(binned_a, leaf_a, ghk, pending,
                                  g=g, nb=nb, k=ghk.shape[1], w=w, ch=ch)
        s = jnp.sum(out)
        shift = (s * 1e-30).astype(jnp.int32) + 1
        return acc_sum + s, (pending + shift) % 64

    for name, w, ch in [("pallas2_w42_ch4096", 42, 4096),
                        ("pallas2_w128_ch4096", 128, 4096),
                        ("pallas2_w128_ch2048", 128, 2048),
                        ("pallas2_w4_ch4096", 4, 4096)]:
        if not on(name):
            continue
        pend0 = jnp.arange(w, dtype=jnp.int32)
        flops = n * g * nb * w * 3 * 2
        run_case(name, functools.partial(pallas_v2_body, w, ch),
                 (jnp.float32(0), pend0), arrays=(binned, leaf_id, gh3),
                 iters=it, flops=flops)

    # ---- row gather + compact (deep-wave path) -------------------------
    def compact_gather_body(m, st, i, arrs):
        binned_a, leaf_a, gh_a = arrs
        acc, pending = st
        mask = (leaf_a[:, None] == pending[None, :]).any(1)
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        tgt = jnp.where(mask & (pos < m), pos, m)
        buf = jnp.zeros((m + 1,), jnp.int32).at[tgt].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")[:m]
        sub = jnp.take(binned_a, buf, axis=0)
        subg = jnp.take(gh_a, buf, axis=0)
        s = (jnp.sum(sub[:, 0].astype(jnp.int32))
             + jnp.sum(subg[:, 2].astype(jnp.float32)))
        shift = (s * 1e-30).astype(jnp.int32) + 1
        return acc + s.astype(jnp.float32), (pending + shift) % 64

    for frac in (4, 16):
        nm = f"compact_gather_N/{frac}"
        if not on(nm):
            continue
        m = n // frac
        pend0 = jnp.arange(16, dtype=jnp.int32)
        run_case(nm, functools.partial(compact_gather_body, m),
                 (jnp.float32(0), pend0), arrays=(binned, leaf_id, gh3),
                 iters=it, bytes_=n * 5 + m * (g + 6 + 4))

    # gathered-quarter histogram: what a deep wave would cost end-to-end
    def deep_wave_body(m, w, st, i, arrs):
        binned_a, leaf_a, gh_a = arrs
        acc, pending = st
        mask = (leaf_a[:, None] == pending[None, :]).any(1)
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        tgt = jnp.where(mask & (pos < m), pos, m)
        buf = jnp.zeros((m + 1,), jnp.int32).at[tgt].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")[:m]
        sub_b = jnp.take(binned_a, buf, axis=0)
        sub_g = jnp.take(gh_a, buf, axis=0)
        sub_l = jnp.take(leaf_a, buf)
        n_chunks = m // CHUNK
        binned_c = sub_b.reshape(n_chunks, CHUNK, g)
        leaf_c = sub_l.reshape(n_chunks, CHUNK)
        gh_c = sub_g.reshape(n_chunks, CHUNK, 3)

        def body(a, xs):
            b, l, g5 = xs
            oh = jax.nn.one_hot(b, nb, dtype=jnp.bfloat16)
            lm = (l[:, None] == pending[None, :w]).astype(jnp.bfloat16)
            bmat = (lm[:, :, None] * g5[:, None, :]).reshape(CHUNK, w * 3)
            return a + jnp.einsum("cgn,cb->gnb", oh, bmat,
                                  preferred_element_type=jnp.float32), None

        acc0 = jnp.zeros((g, nb, w * 3), jnp.float32)
        a, _ = jax.lax.scan(body, acc0, (binned_c, leaf_c, gh_c))
        s = jnp.sum(a)
        shift = (s * 1e-30).astype(jnp.int32) + 1
        return acc + s, (pending + shift) % 64

    if on("deep_wave_N/4_w25"):
        m = n // 4
        pend0 = jnp.arange(16, dtype=jnp.int32)
        run_case("deep_wave_N/4_w25",
                 functools.partial(deep_wave_body, m, 25),
                 (jnp.float32(0), pend0), arrays=(binned, leaf_id, gh3),
                 iters=it, flops=m * g * nb * 25 * 3 * 2)

    # ---- split apply ---------------------------------------------------
    w = 25
    grp = jnp.asarray(rng.integers(0, g, w, dtype=np.int32))
    thr = jnp.asarray(rng.integers(0, nb, w, dtype=np.int32))
    rdel = jnp.asarray(rng.integers(1, 64, w, dtype=np.int32))

    def apply_unrolled_body(st, i, arrs):
        (bt,) = arrs
        leaf, acc = st
        upd = jnp.zeros((n,), jnp.int32)
        for j in range(w):
            col = jax.lax.dynamic_slice(bt, (grp[j], 0), (1, n))[0]
            goes = col.astype(jnp.int32) > thr[j]
            mask = (leaf == (j + i)) & goes
            upd = upd + jnp.where(mask, rdel[j], 0)
        leaf = (leaf + upd) % 64
        return leaf, acc + jnp.sum(upd).astype(jnp.float32)

    def apply_fused_body(st, i, arrs):
        (bt,) = arrs
        leaf, acc = st
        cols = jnp.take(bt, grp, axis=0).astype(jnp.int32)
        goes = cols > thr[:, None]
        lsel = jnp.arange(w, dtype=jnp.int32) + i
        mask = (leaf[None, :] == lsel[:, None]) & goes
        upd = (mask * rdel[:, None]).sum(0)
        leaf = (leaf + upd) % 64
        return leaf, acc + jnp.sum(upd).astype(jnp.float32)

    if on("apply_unrolled_w25"):
        run_case("apply_unrolled_w25", apply_unrolled_body,
                 (leaf_id, jnp.float32(0)), arrays=(binned_t,), iters=it)
    if on("apply_fused_w25"):
        run_case("apply_fused_w25", apply_fused_body,
                 (leaf_id, jnp.float32(0)), arrays=(binned_t,), iters=it)

    # ---- score update (one-hot matmul) --------------------------------
    def score_body(st, i, arrs):
        (leaf_a,) = arrs
        score, acc = st
        vals = (jnp.arange(256, dtype=jnp.float32) + acc * 1e-30)
        oh = jax.nn.one_hot(leaf_a, 256, dtype=jnp.bfloat16)
        vhi = vals.astype(jnp.bfloat16)
        vlo = (vals - vhi.astype(jnp.float32)).astype(jnp.bfloat16)
        upd = jnp.einsum("nl,lk->nk", oh, jnp.stack([vhi, vlo], 1),
                         preferred_element_type=jnp.float32)
        score = score + upd[:, 0] + upd[:, 1]
        return score, acc + score[0]

    if on("score_update"):
        run_case("score_update", score_body,
                 (jnp.zeros((n,), jnp.float32), jnp.float32(0)),
                 arrays=(leaf_id,), iters=it, flops=n * 256 * 2 * 2)

    # ---- HBM bandwidth reference --------------------------------------
    def bw_body(st, i, arrs):
        x, acc = st
        y = x * 1.0000001 + jnp.float32(1e-9) * acc
        return y, acc + y[0]

    if on("bw_copy_1GB"):
        big = jnp.asarray(rng.standard_normal(2 ** 28).astype(np.float32))
        run_case("bw_copy_1GB", bw_body, (big, jnp.float32(0)), iters=it,
                 bytes_=2 ** 28 * 8)


if __name__ == "__main__":
    main()
