#!/usr/bin/env python
"""HIGGS-shaped training benchmark vs the reference baselines.

The reference's headline number (BASELINE.md, ``docs/Experiments.rst:106``)
is 238.5 s for 500 boosting iterations on HIGGS (10.5M rows x 28 dense
features, num_leaves=255ish config); the OpenCL GPU learner's implied
wall-clock is ~80 s (``docs/GPU-Performance.rst:164-175``).  This script
reproduces that workload shape with synthetic data (HIGGS itself is not on
disk: standard-normal features with a planted nonlinear signal, so trees
have real structure to find) and times the training loop on whatever
backend JAX resolves (the driver runs it on one real TPU chip).

Prints exactly ONE line of JSON to stdout:
  {"metric": ..., "value": <train seconds>, "unit": "s",
   "vs_baseline": <value / 238.5>, ...extra diagnostic keys}

Modes:
  python bench.py                  # full: 10.5M x 28, 500 iters
  python bench.py --quick          # 1M x 28, 50 iters
  python bench.py --rows N --iters K --profile   # custom + phase sync
Environment overrides: BENCH_ROWS, BENCH_ITERS, BENCH_PROFILE=1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_CPU_S = 238.5   # docs/Experiments.rst:106 (500 iters, 2x E5-2670v3)
BASELINE_GPU_S = 80.0    # implied ~3x GPU speedup, docs/GPU-Performance.rst
BASELINE_MSLR_S = 215.32  # docs/Experiments.rst:109-110 (MS LTR, 500 iters)


def host_sentinel_ms() -> float:
    """Timed fixed numpy workload: a self-diagnosing host-load probe.

    The r4 driver run recorded 385 s where an idle host measured 234 s
    for identical device work — host CPU contention starved the dispatch
    loop.  Reporting this number alongside the benchmark makes such
    discrepancies attributable from the JSON alone (idle baseline for
    this op: ~35-60 ms; a loaded host measures several times that)."""
    a = np.random.default_rng(0).standard_normal((1024, 1024)) \
        .astype(np.float32)
    t0 = time.perf_counter()
    for _ in range(4):
        a = a @ a
        a /= max(float(np.abs(a).max()), 1e-30)
    return round((time.perf_counter() - t0) * 1e3, 1)


def timed_train(bst, iters: int, chunk_arg: int):
    """Warm-up + timed training loop shared by every suite.

    Returns (chunk_used, warm_iters, warmup_s, timed_s, iters_timed).
    Fused path (train_chunked) when the booster supports it; the warm-up
    burns exactly one chunk so every later dispatch hits the jit cache.
    """
    import jax
    chunk = chunk_arg if chunk_arg > 1 and bst.fused_eligible() else 0
    t0 = time.perf_counter()
    if chunk:
        warm = min(chunk, iters)
        bst.train_chunked(warm, chunk=chunk)
    else:
        warm = min(2, iters)
        for _ in range(warm):
            bst.train_one_iter()
    jax.block_until_ready(bst.train_score)
    warmup_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    if chunk:
        bst.train_chunked(iters - warm, chunk=chunk)
    else:
        for _ in range(iters - warm):
            if bst.train_one_iter():
                break
    jax.block_until_ready(bst.train_score)
    timed_s = time.perf_counter() - t0
    return chunk, warm, warmup_s, timed_s, bst.num_iterations() - warm


def _waves_per_tree(bst):
    """Mean wave count per tree from the booster's device handles (the
    fused path stacks one (chunk,) array per dispatch)."""
    handles = getattr(bst, "_wave_handles", None)
    if not handles:
        return None
    tot = cnt = 0
    for h in handles:
        a = np.asarray(h)
        tot += int(a.sum())
        cnt += int(a.size)
    return round(tot / max(cnt, 1), 2)


def _phases_from_obs() -> dict:
    """Per-phase totals reconstructed from the obs span data.

    The fused path (train_chunked) never touches the legacy TRAIN_TIMER,
    which left ``phases_s`` empty in BENCH_r05.json; the obs registry
    records the ``train.chunk`` spans (plus any phase.* timings from the
    host path) either way, so chunked runs keep per-phase attribution."""
    from lightgbm_tpu import obs
    if not obs.enabled():
        return {}
    timings = obs.registry().snapshot()["timings"]
    out = {}
    for name, stat in sorted(timings.items()):
        if name.startswith(("phase.", "train.", "flush_pending",
                            "grow.stage")):
            out[name] = round(stat["total_s"], 3)
    return out


def _stage_plan_fields(bst, args) -> dict:
    """Stage-plan attribution for the result JSON: the plan the run used
    (+ digest), and per-stage wave probe timings measured AFTER the
    timed region (so the probes' compiles never pollute the headline).
    ``--wave-plan profiled`` installs the derived plan at init instead;
    here we only report what profiling measures/would choose."""
    grower = getattr(bst, "_grower", None)
    if grower is None:
        return {}
    from lightgbm_tpu.ops import stage_plan as sp
    out = {
        "stage_plan": [[w, c] for w, c in grower.stage_plan],
        "stage_plan_digest": sp.plan_digest(grower.stage_plan),
        "stage_plan_source": grower.plan_source,
    }
    if not args.no_stage_profile:
        prof = grower.profile_stage_plan(reps=2, install=False)
        out["stage_wave_ms"] = {str(k): v
                                for k, v in prof["stage_ms"].items()}
        out["stage_fixed_ms"] = prof["fixed_ms"]
        out["stage_col_ms"] = prof["col_ms"]
        out["stage_plan_profiled"] = [[w, c] for w, c in prof["plan"]]
        out["stage_plan_profiled_digest"] = prof["plan_digest"]
    return out


def synth_higgs(rows: int, cols: int = 28, seed: int = 7):
    """Standard-normal features with a planted nonlinear binary signal.

    The signal weights come from a FIXED rng so train and held-out sets
    (different ``seed``) share one ground-truth concept.
    """
    wrng = np.random.default_rng(20260730)
    w1 = wrng.standard_normal(cols).astype(np.float32) / np.sqrt(cols)
    w2 = wrng.standard_normal(cols).astype(np.float32) / np.sqrt(cols)
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows, cols), dtype=np.float32)
    logits = (x @ w1) + np.abs(x @ w2) - 0.79  # ~balanced classes
    p = 1.0 / (1.0 + np.exp(-2.0 * logits))
    y = (rng.random(rows, dtype=np.float32) < p).astype(np.float32)
    return x, y


def synth_higgs_device(rows: int, cols: int = 28, seed: int = 7):
    """synth_higgs generated ON DEVICE: the bulk matrix never exists on
    host, so data generation is immune to driver-host CPU contention
    (r4's loaded-host run spent 26.9 s here vs 7.6 s idle).  Same
    planted-concept construction; jax.random instead of numpy."""
    import jax
    import jax.numpy as jnp
    wrng = np.random.default_rng(20260730)
    w1 = jnp.asarray(wrng.standard_normal(cols).astype(np.float32)
                     / np.sqrt(cols))
    w2 = jnp.asarray(wrng.standard_normal(cols).astype(np.float32)
                     / np.sqrt(cols))
    @jax.jit
    def gen(key, w1_, w2_):
        kx, ky = jax.random.split(key)
        x = jax.random.normal(kx, (rows, cols), jnp.float32)
        logits = (x @ w1_) + jnp.abs(x @ w2_) - 0.79
        p = 1.0 / (1.0 + jnp.exp(-2.0 * logits))
        y = (jax.random.uniform(ky, (rows,)) < p).astype(jnp.float32)
        return x, y

    x, y = gen(jax.random.PRNGKey(seed), w1, w2)
    return x, np.asarray(y, np.float32)


def run_higgs(args) -> dict:
    import jax
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.utils.log import TRAIN_TIMER, set_verbosity

    set_verbosity(0)
    backend = jax.default_backend()
    dev = str(jax.devices()[0])

    t0 = time.perf_counter()
    if args.host_data:
        x, y = synth_higgs(args.rows)
        xt = yt = None
        if args.eval_rows > 0:
            xt, yt = synth_higgs(args.eval_rows, seed=1234)
    else:
        x, y = synth_higgs_device(args.rows)
        xt = yt = None
        if args.eval_rows > 0:
            xt, yt = synth_higgs_device(args.eval_rows, seed=1234)
    t_gen = time.perf_counter() - t0

    cfg = Config({
        "objective": "binary", "metric": "auc",
        "num_leaves": args.num_leaves, "max_bin": args.max_bin,
        "learning_rate": args.learning_rate,
        "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
        "bagging_fraction": 1.0, "feature_fraction": 1.0,
        "verbosity": 0,
        "grad_quant_bits": args.quant_bits,
        "wave_plan": args.wave_plan,
        "device_growth": {"device": "on", "host": "off",
                          "auto": "auto"}[args.engine],
    })

    t0 = time.perf_counter()
    if args.host_data:
        ds = BinnedDataset.construct_from_matrix(x, cfg)
    else:
        ds = BinnedDataset.construct_from_device_matrix(x, cfg)
        jax.block_until_ready(ds.binned)
    ds.metadata.set_label(y)
    t_bin = time.perf_counter() - t0

    bst = create_boosting(cfg)
    TRAIN_TIMER.reset()
    TRAIN_TIMER.sync = args.profile

    sentinel_pre = host_sentinel_ms()

    # warm-up triggers + caches the XLA compile.  The SAME booster is
    # then timed for the remaining iterations (a fresh booster would
    # re-trace its jitted grower and put the compile back into the timed
    # region); per-iteration cost does not depend on the iteration
    # index, so wall-clock extrapolates linearly.
    #
    # Default path: K whole iterations fused into one device dispatch
    # (GBDT.train_chunked) — ONE program to compile, and the timed loop
    # touches the host once per K trees, so the recorded number tracks
    # device throughput even on a loaded driver host.
    t0 = time.perf_counter()
    bst.init_train(ds)
    t_init = time.perf_counter() - t0
    TRAIN_TIMER.reset()
    chunk, warm, t_warm, timed_s, iters_timed = timed_train(
        bst, args.iters, args.chunk)
    t_warm += t_init
    sentinel_post = host_sentinel_ms()
    per_iter = timed_s / max(iters_timed, 1)
    train_s = per_iter * bst.num_iterations()   # full-run equivalent

    auc = None
    if xt is not None:
        from lightgbm_tpu.ops.traverse import add_tree_score, device_tree
        import jax.numpy as jnp
        bst._flush_pending()
        if args.host_data:
            vds = BinnedDataset.construct_from_matrix(xt, cfg,
                                                      reference=ds)
        else:
            vds = BinnedDataset.construct_from_device_matrix(
                xt, cfg, reference=ds)
        binned_d = jnp.asarray(vds.binned)
        score = jnp.zeros(args.eval_rows, jnp.float32)
        for tree in bst.models:
            if tree.num_leaves > 1:
                score = add_tree_score(
                    score, binned_d, device_tree(tree, ds, cfg.num_leaves),
                    1.0)
        raw = np.asarray(score, np.float64)
        order = np.argsort(-raw, kind="stable")
        lbl = yt[order]
        tps = np.cumsum(lbl)
        fps = np.cumsum(1.0 - lbl)
        auc = float(np.trapezoid(tps, fps) / (tps[-1] * fps[-1])) \
            if tps[-1] > 0 and fps[-1] > 0 else float("nan")

    iters_run = bst.num_iterations()
    phases = {k: round(v, 3) for k, v in sorted(TRAIN_TIMER.acc.items())}
    if not phases:
        # fused path: TRAIN_TIMER never runs — rebuild from obs spans
        phases = _phases_from_obs()
    waves_per_tree = _waves_per_tree(bst)
    if args.profile and getattr(bst, "_grower", None) is not None:
        # per-phase ms for ONE wave's components, separately jitted and
        # synced (the production while_loop hides phases from the host)
        g, h = bst.objective.get_gradients(bst.train_score)
        if g.ndim > 1:
            g, h = g[0], h[0]
        phases["device_wave_ms"] = bst._grower.profile_phases(g, h)
    result = {
        "metric": f"higgs_synth_{args.rows}x28_{args.iters}iter_wallclock",
        "value": round(train_s, 3),
        "unit": "s",
        "vs_baseline": round(train_s / BASELINE_CPU_S, 4),
        "baseline_cpu_s": BASELINE_CPU_S,
        "baseline_gpu_s": BASELINE_GPU_S,
        "speedup_vs_cpu": round(BASELINE_CPU_S / train_s, 2),
        "rows": args.rows,
        "iters": iters_run,
        "timed_iters": iters_timed,
        "timed_s": round(timed_s, 3),
        # ms_per_tree is THE per-round comparison number (BENCH_r05:
        # 469.75 on higgs/v5e); time_per_tree_ms kept as a legacy alias
        "ms_per_tree": round(1000.0 * per_iter, 2),
        "time_per_tree_ms": round(1000.0 * per_iter, 2),
        "rows_per_sec": round(args.rows * iters_run / train_s, 0),
        # _synth suffix: quality on the synthetic planted-signal data —
        # NOT comparable with AUC numbers on the real HIGGS dataset
        "auc_synth": round(auc, 6) if auc is not None else None,
        "waves_per_tree": waves_per_tree,
        "grad_quant_bits": args.quant_bits,
        "backend": backend,
        "device": dev,
        "phases_s": phases,
        "profile_sync": args.profile,
        "gen_s": round(t_gen, 2),
        "bin_s": round(t_bin, 2),
        "warmup_compile_s": round(t_warm, 2),
        # actual XLA backend-compile seconds this process paid: the
        # component a warm persistent compile cache removes (tracing
        # stays; docs/ColdStart.md)
        "xla_compile_s": round(_cc_counters()["backend_compile_s"], 2),
        "fused_chunk": chunk,
        "host_sentinel_ms": [sentinel_pre, sentinel_post],
    }
    result.update(_stage_plan_fields(bst, args))
    return result


def synth_mslr(rows: int, cols: int = 136, n_queries: int = 6000,
               seed: int = 7):
    """MSLR-WEB10K-shaped synthetic LTR data: ~723k docs over ~6k queries
    with lognormal query sizes (~120 docs avg), 136 features, and 5-level
    relevance whose signal is a noisy nonlinear function of the features
    (so lambdarank has real structure to learn).  Shapes follow
    BASELINE.md "MS LTR" (docs/Experiments.rst:109,142-143)."""
    wrng = np.random.default_rng(20260731)
    w1 = wrng.standard_normal(cols).astype(np.float32) / np.sqrt(cols)
    w2 = wrng.standard_normal(cols).astype(np.float32) / np.sqrt(cols)
    rng = np.random.default_rng(seed)
    sizes = np.clip(rng.lognormal(4.45, 0.7, n_queries).astype(np.int64),
                    5, 1000)
    scale = rows / sizes.sum()
    sizes = np.maximum((sizes * scale).astype(np.int64), 2)
    total = int(sizes.sum())
    x = rng.standard_normal((total, cols), dtype=np.float32)
    # per-query quality offset so ranking within query is what matters
    qoff = np.repeat(rng.standard_normal(n_queries, dtype=np.float32),
                     sizes)
    util = ((x @ w1) + 0.7 * np.abs(x @ w2) + 0.8 * qoff
            + 0.9 * rng.standard_normal(total, dtype=np.float32))
    # 5 relevance levels from global utility quantiles (skewed like MSLR)
    qs = np.quantile(util, [0.55, 0.75, 0.90, 0.97])
    y = np.digitize(util, qs).astype(np.float32)
    return x, y, sizes


def _ndcg_at_k(scores, labels, qb, k=10):
    out = []
    lg = np.asarray([(1 << min(int(v), 30)) - 1 for v in range(32)],
                    np.float64)
    disc = 1.0 / np.log2(np.arange(2, k + 2))
    for i in range(len(qb) - 1):
        lo, hi = qb[i], qb[i + 1]
        lab = labels[lo:hi]
        if lab.max() <= 0:
            continue
        order = np.argsort(-scores[lo:hi], kind="stable")[:k]
        dcg = float((lg[lab[order].astype(np.int64)] * disc[:len(order)])
                    .sum())
        ideal = np.sort(lab)[::-1][:k]
        idcg = float((lg[ideal.astype(np.int64)] * disc[:len(ideal)])
                     .sum())
        out.append(dcg / idcg)
    return float(np.mean(out))


def run_mslr(args) -> dict:
    import jax
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    rows = 723_412 if not args.quick else 100_000
    iters = args.iters
    t0 = time.perf_counter()
    x, y, sizes = synth_mslr(rows)
    xt, yt, sizes_t = synth_mslr(120_000 if not args.quick else 30_000,
                                 n_queries=1000, seed=1234)
    t_gen = time.perf_counter() - t0

    cfg = Config({
        "objective": "lambdarank", "metric": "ndcg",
        "num_leaves": args.num_leaves, "max_bin": args.max_bin,
        "learning_rate": args.learning_rate,
        "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
        "verbosity": 0,
        "device_growth": {"device": "on", "host": "off",
                          "auto": "auto"}[args.engine],
    })
    t0 = time.perf_counter()
    ds = BinnedDataset.construct_from_matrix(x, cfg)
    ds.metadata.set_label(y)
    ds.metadata.set_query(sizes)
    t_bin = time.perf_counter() - t0

    bst = create_boosting(cfg)
    t0 = time.perf_counter()
    bst.init_train(ds)
    t_init = time.perf_counter() - t0
    chunk, warm, t_warm, timed_s, iters_timed = timed_train(
        bst, iters, args.chunk)
    t_warm += t_init
    per_iter = timed_s / max(iters_timed, 1)
    train_s = per_iter * bst.num_iterations()

    # NDCG@10 on held-out queries via the device traversal
    from lightgbm_tpu.ops.traverse import add_tree_score, device_tree
    import jax.numpy as jnp
    bst._flush_pending()
    vds = BinnedDataset.construct_from_matrix(xt, cfg, reference=ds)
    binned_d = jnp.asarray(vds.binned)
    score = jnp.zeros(xt.shape[0], jnp.float32)
    for tree in bst.models:
        if tree.num_leaves > 1:
            score = add_tree_score(
                score, binned_d, device_tree(tree, ds, cfg.num_leaves),
                1.0)
    raw = np.asarray(score, np.float64)
    qb = np.concatenate([[0], np.cumsum(sizes_t)])
    ndcg10 = _ndcg_at_k(raw, yt, qb, 10)

    return {
        "metric": f"mslr_synth_{rows}x136_{iters}iter_wallclock",
        "value": round(train_s, 3),
        "unit": "s",
        "vs_baseline": round(train_s / BASELINE_MSLR_S, 4),
        "baseline_cpu_s": BASELINE_MSLR_S,
        "rows": rows,
        "iters": bst.num_iterations(),
        "ms_per_tree": round(1000.0 * per_iter, 2),
        "time_per_tree_ms": round(1000.0 * per_iter, 2),
        # _synth suffix: NDCG on synthetic MSLR-shaped data; the ref
        # value is the reference's REAL-MSLR number, shown for context
        # only — the datasets differ, so the two are not comparable
        "ndcg10_synth": round(ndcg10, 6),
        "ndcg10_ref_real_mslr": 0.527371,
        "gen_s": round(t_gen, 2),
        "bin_s": round(t_bin, 2),
        "warmup_compile_s": round(t_warm, 2),
        "xla_compile_s": round(_cc_counters()["backend_compile_s"], 2),
        "fused_chunk": chunk,
    }


def run_serve(args) -> dict:
    """Packed-ensemble serving benchmark (lightgbm_tpu.serve): train a
    HIGGS-shaped model once, then measure PredictionServer throughput
    (rows/s) and per-call latency p50/p95 across a spread of batch
    sizes, plus the hot-swap retrace check the window loop relies on."""
    import jax
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.serve import PredictionServer

    rows = min(args.rows, 1_000_000 if not args.quick else 200_000)
    iters = min(args.iters, 50)
    x, y = synth_higgs(rows)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "max_bin": args.max_bin, "learning_rate": 0.1,
                  "verbosity": -1, "device_growth": "auto"})

    def train(seed_rows):
        ds = BinnedDataset.construct_from_matrix(seed_rows, cfg)
        ds.metadata.set_label(y[:seed_rows.shape[0]])
        bst = create_boosting(cfg)
        bst.init_train(ds)
        bst.train_chunked(iters, chunk=min(args.chunk or 10, iters))
        bst._flush_pending()
        return bst

    bst = train(x)
    server = PredictionServer(bst)

    batch = 65536 if not args.quick else 8192
    t0 = time.perf_counter()
    server.warmup((512, batch))
    warmup_s = time.perf_counter() - t0

    rng = np.random.default_rng(11)
    xq = rng.standard_normal((batch, x.shape[1]))
    reps = 8 if not args.quick else 4
    t0 = time.perf_counter()
    for _ in range(reps):
        out = server.predict(xq)
    timed_s = time.perf_counter() - t0
    assert np.isfinite(out).all()

    # small-batch latency distribution, sampled explicitly so the big-
    # batch throughput reps above don't pollute the percentiles
    lat_samples = []
    for _ in range(32):
        t1 = time.perf_counter()
        server.predict(xq[:512])
        lat_samples.append(time.perf_counter() - t1)

    # hot-swap: a same-shaped retrain window must not retrace
    snap = obs.registry().snapshot()["jit"] if obs.enabled() else {}
    compiles_before = sum(v["compiles"] for v in snap.values())
    same_shape = server.swap(train(x))
    server.predict(xq[:512])
    snap = obs.registry().snapshot()["jit"] if obs.enabled() else {}
    compiles_after = sum(v["compiles"] for v in snap.values())

    lat = {"latency_rows": 512,
           "latency_p50_ms": round(
               float(np.percentile(lat_samples, 50)) * 1e3, 3),
           "latency_p95_ms": round(
               float(np.percentile(lat_samples, 95)) * 1e3, 3)}
    pe = server.packed
    result = {
        "metric": f"serve_packed_{batch}row_batch_rows_per_sec",
        "value": round(batch * reps / timed_s, 0),
        "unit": "rows/s",
        "batch_rows": batch,
        "reps": reps,
        "timed_s": round(timed_s, 3),
        "warmup_s": round(warmup_s, 2),
        "trees": pe.num_trees,
        "tree_pad": int(pe.split_feature.shape[0]),
        "depth_pad": pe.max_depth,
        "swap_same_shape": bool(same_shape),
        "swap_retrace_zero": (compiles_after == compiles_before)
        if obs.enabled() else None,
        "backend": jax.default_backend(),
        **lat,
    }
    if int(getattr(args, "models", 0)) > 1:
        result["fleet"] = _run_fleet_leg(args, bst, xq, batch)
    if getattr(args, "slo", ""):
        # evaluated AFTER every serving leg; the verdict covers the
        # spec's TRAILING window (default 60 s, ring cap 120 s), not
        # the whole suite — size window_s to the suite duration if the
        # early legs must count
        result["slo"] = _slo_report(args.slo)
    return result


def _run_fleet_leg(args, bst, xq, batch) -> dict:
    """--suite serve --models M: sustained mixed-tenant throughput over
    an M-tenant FleetServer (every tenant seeded from the trained
    booster — the arrays, gathers and conversion cost are what a real
    fleet pays) plus the zero-retrace tenant hot-swap check.  The
    1M+ rows/s verdict is chip-pending like BENCH_r06: the CPU
    container records the numbers, the gate value needs the TPU run."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.serve import FleetServer

    m = int(args.models)
    replicas = int(os.environ.get("BENCH_SERVE_REPLICAS", "1")) or 1
    fs = FleetServer([bst] * m, replicas=replicas)
    t0 = time.perf_counter()
    fs.warmup((512, batch))
    warmup_s = time.perf_counter() - t0

    rng = np.random.default_rng(12)
    tids = rng.integers(0, m, batch).astype(np.int32)
    reps = 8 if not args.quick else 4
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fs.predict(tids, xq)
    timed_s = time.perf_counter() - t0
    assert np.isfinite(np.asarray(out)).all()

    lat_samples = []
    for _ in range(32):
        t1 = time.perf_counter()
        fs.predict(tids[:512], xq[:512])
        lat_samples.append(time.perf_counter() - t1)

    # a tenant retrain hand-off must be a zero-retrace index write;
    # without telemetry the check is unmeasured (null), never a
    # vacuous 0 == 0 pass
    snap = obs.registry().snapshot()["jit"] if obs.enabled() else {}
    compiles_before = sum(v["compiles"] for v in snap.values())
    fits = fs.swap_tenant(0, bst)
    fs.predict(tids[:512], xq[:512])
    snap = obs.registry().snapshot()["jit"] if obs.enabled() else {}
    compiles_after = sum(v["compiles"] for v in snap.values())
    retrace_zero = (compiles_after == compiles_before) \
        if obs.enabled() else None

    rows_per_s = batch * reps / timed_s
    return {
        "models": m,
        "replicas": replicas,
        "fleet_rows_per_s": round(rows_per_s, 0),
        "batch_rows": batch,
        "reps": reps,
        "timed_s": round(timed_s, 3),
        "warmup_s": round(warmup_s, 2),
        "tree_pad": int(fs.fleet.tree_pad),
        "fleet_latency_p50_ms": round(
            float(np.percentile(lat_samples, 50)) * 1e3, 3),
        "fleet_latency_p95_ms": round(
            float(np.percentile(lat_samples, 95)) * 1e3, 3),
        "tenant_swap_fits": bool(fits),
        "tenant_swap_retrace_zero": retrace_zero,
        # chip-pending gate (BENCH_r06 pattern): recorded on every
        # backend, meaningful as a pass/fail only on the TPU driver
        "pass_1m_rows_per_s": bool(rows_per_s >= 1.0e6),
    }


def _slo_report(spec_text: str) -> dict:
    """Evaluate a declarative SLO spec (obs/slo.py grammar) against the
    rolling telemetry the suite just produced and return the full
    report for the result JSON.  Latency/availability numbers from the
    CPU container are parity evidence, not chip truth — marked
    chip-pending exactly like ``pass_1m_rows_per_s``."""
    import jax
    from lightgbm_tpu.obs import slo
    out = slo.evaluate(spec_text).to_json()
    out["chip_pending"] = jax.default_backend() != "tpu"
    return out


def _cc_counters() -> dict:
    from lightgbm_tpu import compile_cache
    return compile_cache.counters()


def _kernel_route_counts(snapshot_before: dict,
                         prefixes=("grow.hist.",
                                   "grow.fused_find.")) -> dict:
    """grow.hist.* / grow.fused_find.* routing counter deltas since
    ``snapshot_before`` — which histogram kernel (einsum/pallas x
    bf16/int8) actually served the dispatches of one benchmark leg, and
    whether the find-best scan rode those dispatches (fused) or paid
    its own.  grow.hist.* keys keep their historical short form
    (``einsum_int8``); other prefixes keep a qualifier
    (``fused_find.einsum_int8``) so the two families stay distinct."""
    from lightgbm_tpu import obs
    if not obs.enabled():
        return {}
    now = obs.registry().snapshot()["counters"]
    out = {}
    for key, val in sorted(now.items()):
        for pre in prefixes:
            if key.startswith(pre):
                delta = val - snapshot_before.get(key, 0)
                if delta:
                    tag = key.split(pre, 1)[1]
                    if pre != "grow.hist.":
                        tag = pre.split("grow.", 1)[1] + tag
                    out[tag] = delta
                break
    return out


def run_quant(args) -> dict:
    """Paired quantization benchmark: f32 / int8-einsum / int8-pallas
    legs over ONE shared dataset in ONE process (warm compile cache,
    identical bins), reporting ms_per_tree per leg plus the speedup
    matrix — BENCH_r06's int8 claims as a single command producing a
    single JSON line.

    The pallas leg uses the VMEM kernel on TPU and interpret mode
    elsewhere (CPU: plumbing/parity validation, not a perf number);
    routing counters per leg record which kernel actually ran — the
    kernel only serves full-width stages whose stat columns fit one
    128-lane tile (wave_width * hist_cols <= 128), wider configs fall
    back to the einsum and the JSON says so."""
    import jax
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    backend = jax.default_backend()
    pallas_mode = "pallas" if backend == "tpu" else "interpret"
    # paired legs need ONE stage plan: each leg has its own config
    # digest (grad_quant_bits/hist_kernel differ), so wave_plan=auto's
    # profile-on-first-use would let every leg install a different
    # measured plan and the speedup matrix would conflate plan deltas
    # with kernel deltas.  Default to the byte-stable fixed ladder;
    # an explicit --wave-plan profiled still profiles per leg (then
    # waves_per_tree in the JSON is the cross-check).
    wave_plan = "fixed" if args.wave_plan == "auto" else args.wave_plan
    base = {
        "objective": "binary", "metric": "auc",
        "num_leaves": args.num_leaves, "max_bin": args.max_bin,
        "learning_rate": args.learning_rate,
        "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
        "bagging_fraction": 1.0, "feature_fraction": 1.0,
        "verbosity": 0, "wave_plan": wave_plan,
        "device_growth": {"device": "on", "host": "off",
                          "auto": "auto"}[args.engine],
    }
    t0 = time.perf_counter()
    if args.host_data:
        x, y = synth_higgs(args.rows)
        ds = BinnedDataset.construct_from_matrix(x, Config(base))
    else:
        x, y = synth_higgs_device(args.rows)
        ds = BinnedDataset.construct_from_device_matrix(x, Config(base))
        jax.block_until_ready(ds.binned)
    ds.metadata.set_label(y)
    t_prep = time.perf_counter() - t0

    legs = [
        ("f32", {"grad_quant_bits": 0}),
        ("int8_einsum", {"grad_quant_bits": 8, "hist_kernel": "einsum"}),
        # the paired find-best leg: identical kernel/quant config to
        # int8_einsum, but the gain scan pays its own dispatch per wave
        # instead of riding the histogram program — the fused_delta
        # block below is the tentpole's before/after on ONE dataset
        ("int8_two_pass", {"grad_quant_bits": 8,
                           "hist_kernel": "einsum",
                           "find_best_fusion": "two_pass"}),
        ("int8_pallas", {"grad_quant_bits": 8,
                         "hist_kernel": pallas_mode}),
    ]
    leg_out = {}
    for name, extra in legs:
        cfg = Config({**base, **extra})
        bst = create_boosting(cfg)
        before = obs.registry().snapshot()["counters"] \
            if obs.enabled() else {}
        t0 = time.perf_counter()
        bst.init_train(ds)
        t_init = time.perf_counter() - t0
        chunk, warm, t_warm, timed_s, iters_timed = timed_train(
            bst, args.iters, args.chunk)
        per_iter = timed_s / max(iters_timed, 1)
        grower = getattr(bst, "_grower", None)
        wpt = _waves_per_tree(bst)
        fused = bool(getattr(grower, "fused_find", False))
        leg_out[name] = {
            "ms_per_tree": round(1000.0 * per_iter, 2),
            "timed_s": round(timed_s, 3),
            "timed_iters": iters_timed,
            "warmup_compile_s": round(t_warm + t_init, 2),
            "waves_per_tree": wpt,
            "hist_kernel_tag": getattr(grower, "hist_kernel_tag", None),
            "int_scan": bool(getattr(grower, "int_scan", False)),
            "find_best_fusion": getattr(grower, "find_fusion", None),
            # program dispatches per tree under the leg's layout: a
            # fused wave is ONE dispatch, two-pass pays the second
            # find-best program every wave
            "dispatches_per_tree": round(wpt * (1 if fused else 2), 2)
            if wpt is not None else None,
            "kernel_dispatches": _kernel_route_counts(before),
        }

    def _speedup(a, b):
        return round(leg_out[a]["ms_per_tree"]
                     / max(leg_out[b]["ms_per_tree"], 1e-9), 3)

    return {
        "metric": f"quant_suite_higgs_{args.rows}x28_{args.iters}iter"
                  f"_ms_per_tree",
        "value": leg_out["int8_pallas"]["ms_per_tree"],
        "unit": "ms",
        "rows": args.rows,
        "iters": args.iters,
        "num_leaves": args.num_leaves,
        "max_bin": args.max_bin,
        "fused_chunk": args.chunk,
        "wave_plan": wave_plan,
        "prep_s": round(t_prep, 2),
        "legs": leg_out,
        "speedup": {
            "f32_vs_int8_einsum": _speedup("f32", "int8_einsum"),
            "f32_vs_int8_pallas": _speedup("f32", "int8_pallas"),
            "int8_einsum_vs_int8_pallas": _speedup("int8_einsum",
                                                   "int8_pallas"),
            "two_pass_vs_fused": _speedup("int8_two_pass",
                                          "int8_einsum"),
        },
        # the tentpole's before/after at matched kernel/quant config:
        # fused (int8_einsum) vs two_pass on the SAME shared dataset
        "fused_delta": {
            "ms_per_tree_fused": leg_out["int8_einsum"]["ms_per_tree"],
            "ms_per_tree_two_pass":
                leg_out["int8_two_pass"]["ms_per_tree"],
            "ms_per_tree_saved": round(
                leg_out["int8_two_pass"]["ms_per_tree"]
                - leg_out["int8_einsum"]["ms_per_tree"], 2),
            "waves_per_tree_fused":
                leg_out["int8_einsum"]["waves_per_tree"],
            "waves_per_tree_two_pass":
                leg_out["int8_two_pass"]["waves_per_tree"],
            "dispatches_per_tree_fused":
                leg_out["int8_einsum"]["dispatches_per_tree"],
            "dispatches_per_tree_two_pass":
                leg_out["int8_two_pass"]["dispatches_per_tree"],
        },
        "backend": backend,
        "device": str(jax.devices()[0]),
        # ms_per_tree numbers from a non-TPU container validate parity
        # and plumbing, not the chip: bench_compare skips cross-round
        # "value" comparisons for chip-pending results
        "chip_pending": backend != "tpu",
        "host_sentinel_ms": host_sentinel_ms(),
    }


def run_explain(args) -> dict:
    """Phase-attribution explainer (``--explain``): where does a tree's
    wall time actually go?

    Trains one quant-suite-shaped leg, then rebuilds the measured
    ms_per_tree from the device-phase probes (ops/grow.py): the
    per-stage-width wave histogram timings scaled by the stage plan and
    the observed waves/tree, find_best + split_apply per wave,
    score_update per tree, and the psum collective when sharded.  With
    ``profile_attribution`` on, each probe also carries its XLA
    cost-analysis estimate (FLOPs/bytes -> achieved GFLOP/s).  The
    report's ``coverage`` is attributed/measured (clamped at 1.0);
    the acceptance bar is >= 0.9 — anything the probes cannot see
    (while_loop glue, totals fetch, host dispatch) shows up as
    ``unattributed_ms`` instead of being papered over."""
    import jax
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    obs.configure(profile_attribution=True)
    backend = jax.default_backend()
    wave_plan = "fixed" if args.wave_plan == "auto" else args.wave_plan
    cfg = Config({
        "objective": "binary", "metric": "auc",
        "num_leaves": args.num_leaves, "max_bin": args.max_bin,
        "learning_rate": args.learning_rate,
        "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
        "bagging_fraction": 1.0, "feature_fraction": 1.0,
        "verbosity": 0, "wave_plan": wave_plan,
        "grad_quant_bits": args.quant_bits,
        "profile_attribution": True,
        "device_growth": {"device": "on", "host": "off",
                          "auto": "auto"}[args.engine],
    })
    t0 = time.perf_counter()
    if args.host_data:
        x, y = synth_higgs(args.rows)
        ds = BinnedDataset.construct_from_matrix(x, cfg)
    else:
        x, y = synth_higgs_device(args.rows)
        ds = BinnedDataset.construct_from_device_matrix(x, cfg)
        jax.block_until_ready(ds.binned)
    ds.metadata.set_label(y)
    t_prep = time.perf_counter() - t0

    bst = create_boosting(cfg)
    bst.init_train(ds)
    # per-chunk timing instead of timed_train's single aggregate: each
    # fused dispatch is blocked on individually, and the BEST chunk is
    # the attribution denominator — the probes measure steady-state
    # device time, so comparing them against a mean contaminated by
    # host scheduling noise would understate coverage
    chunk = args.chunk if args.chunk > 1 and bst.fused_eligible() else 0
    with obs.profile.device_trace(args.device_profile) as profiled:
        if chunk:
            t0 = time.perf_counter()
            bst.train_chunked(chunk, chunk=chunk)      # warm + compile
            jax.block_until_ready(bst.train_score)
            t_warm = time.perf_counter() - t0
            warm = chunk
            chunk_s = []
            # full chunks only: a shorter remainder would recompile
            # with a new scan length and pollute the timing
            while bst.num_iterations() + chunk <= args.iters:
                t0 = time.perf_counter()
                bst.train_chunked(chunk, chunk=chunk)
                jax.block_until_ready(bst.train_score)
                chunk_s.append(time.perf_counter() - t0)
            timed_s = sum(chunk_s)
            iters_timed = chunk * len(chunk_s)
            per_tree_ms = (min(chunk_s) / chunk * 1e3
                           if chunk_s else 0.0)
        else:
            chunk, warm, t_warm, timed_s, iters_timed = timed_train(
                bst, args.iters, args.chunk)
            per_tree_ms = timed_s / max(iters_timed, 1) * 1e3

    grower = getattr(bst, "_grower", None)
    result = {
        "metric": f"explain_higgs_{args.rows}x28_{args.iters}iter"
                  f"_coverage",
        "unit": "fraction",
        "rows": args.rows,
        "iters": args.iters,
        "num_leaves": args.num_leaves,
        "max_bin": args.max_bin,
        "quant_bits": args.quant_bits,
        "fused_chunk": chunk,
        "wave_plan": wave_plan,
        "prep_s": round(t_prep, 2),
        "timed_s": round(timed_s, 3),
        "timed_iters": iters_timed,
        # best-chunk per-tree time (the attribution denominator) plus
        # the noisier all-chunks mean for context
        "ms_per_tree": round(per_tree_ms, 2),
        "ms_per_tree_mean": round(
            timed_s / max(iters_timed, 1) * 1e3, 2),
        "device_profile": bool(profiled),
        "backend": backend,
        "device": str(jax.devices()[0]),
        # attribution numbers on a non-TPU backend validate the math,
        # not the chip (BENCH_r06 convention)
        "chip_pending": backend != "tpu",
        "host_sentinel_ms": host_sentinel_ms(),
    }
    if grower is None:
        # host engine: the legacy TRAIN_TIMER is the only attribution
        from lightgbm_tpu.utils.log import TRAIN_TIMER
        phases_ms = {k: v / max(iters_timed, 1) * 1e3
                     for k, v in TRAIN_TIMER.acc.items()}
        report = obs.profile.attribution_report(per_tree_ms, phases_ms)
        result["value"] = report["coverage"]
        result["attribution"] = report
        result["attribution_source"] = "host_train_timer"
        return result

    # device-phase probes on the trained grower's real operands
    g, h = bst.objective.get_gradients(bst.train_score)
    if g.ndim > 1:
        g, h = g[0], h[0]
    wave = grower.profile_phases(g, h, reps=10)
    prof = grower.profile_stage_plan(reps=2, install=False)
    psum = grower.profile_psum(reps=5)

    # replay the stage plan's wave sequence: a plan entry (w, cap)
    # runs width-w waves until the tree holds cap leaves (None = grown
    # out), and the splittable frontier roughly doubles per wave — so
    # [(4, 8), (30, None)] at 31 leaves is waves [4, 4, 4, 30, 30],
    # NOT "8 waves then a tail".  Per-wave hist cost then rolls up
    # from the per-width stage timings.
    L = int(args.num_leaves)
    widths, nl, pending = [], 1, 1
    for w, cap in grower.stage_plan:
        lim = L if cap is None else min(int(cap), L)
        while nl < lim:
            nsplit = min(pending, int(w), L - nl)
            if nsplit <= 0:
                break
            widths.append(int(w))
            nl += nsplit
            pending += nsplit
    plan_waves = float(len(widths)) or 1.0
    wpt = _waves_per_tree(bst) or plan_waves
    # trees terminate waves early: scale the full plan's cost down to
    # the waves that actually ran
    f = wpt / plan_waves
    stage_ms = prof.get("stage_ms") or {}
    full_hist = wave.get("wave_hist", 0.0)
    hist_ms = sum(stage_ms.get(w, full_hist) for w in widths) * f
    fused_find = bool(getattr(grower, "fused_find", False))
    costs = dict(wave.get("costs") or {})

    def _scale_cost(name, mult):
        c = costs.get(name)
        if c:
            costs[name] = {k: (v * mult if v is not None else None)
                           for k, v in c.items()}

    if fused_find:
        # fused find-best-in-wave: the gain scan rides the histogram
        # program, so the replay prices ONE phase per wave — pricing
        # hist and find as separate dispatches would claim a dispatch
        # (and its fixed overhead) the fused layout never pays
        phases_ms = {"fused_hist_find":
                     hist_ms + wave.get("find_best", 0.0) * wpt}
        _scale_cost("find_best", wpt)
        if "split_apply" in wave:
            phases_ms["split_apply"] = wave["split_apply"] * wpt
            _scale_cost("split_apply", wpt)
    else:
        phases_ms = {"wave_hist": hist_ms}
        for name in ("find_best", "split_apply"):
            if name in wave:
                phases_ms[name] = wave[name] * wpt
                _scale_cost(name, wpt)
    if "score_update" in wave:
        phases_ms["score_update"] = wave["score_update"]
    # the per-wave histogram cost estimate follows the same wave
    # sequence when the stage probes produced per-width costs
    stage_cost = prof.get("stage_cost") or {}
    if stage_cost and all(w in stage_cost for w in set(widths)):
        agg = {}
        for w in widths:
            for k, v in stage_cost[w].items():
                if v is not None:
                    agg[k] = agg.get(k, 0.0) + v
        costs["wave_hist"] = {k: v * f for k, v in agg.items()}
    else:
        _scale_cost("wave_hist", wpt)
    if fused_find:
        # fold the hist and find cost estimates into the single fused
        # phase so the FLOPs/bytes line up with the merged timing above
        merged = {}
        for name in ("wave_hist", "find_best"):
            for k, v in (costs.pop(name, None) or {}).items():
                if v is not None:
                    merged[k] = merged.get(k, 0.0) + v
        if merged:
            costs["fused_hist_find"] = merged
    if psum is not None:
        phases_ms["psum"] = psum["psum_ms"] * wpt
        if psum.get("cost"):
            costs["psum"] = {k: (v * wpt if v is not None else None)
                             for k, v in psum["cost"].items()}
    report = obs.profile.attribution_report(per_tree_ms, phases_ms,
                                            costs)
    result["value"] = report["coverage"]
    result["attribution"] = report
    result["attribution_source"] = "device_phase_probes"
    result["waves_per_tree"] = wpt
    result["plan_waves"] = plan_waves
    result["stage_plan"] = [[w, c] for w, c in grower.stage_plan]
    result["stage_wave_widths"] = widths
    result["stage_wave_ms"] = {str(k): v for k, v in stage_ms.items()}
    result["dispatch_floor_ms"] = wave.get("dispatch_floor")
    result["hist_kernel_tag"] = getattr(grower, "hist_kernel_tag", None)
    result["find_best_fusion"] = getattr(grower, "find_fusion", None)
    result["dispatches_per_tree"] = round(
        wpt * (1 if fused_find else 2), 2)
    return result


def _run_shard_multihost(args) -> dict:
    """``--suite shard --hosts N``: one OS process per pod host over a
    localhost ``jax.distributed`` coordinator (docs/Sharding.md
    multi-host section), side by side with a single-process
    ``single_controller`` leg over the SAME 4-device global mesh.

    Because the total device count is fixed, the two legs trace the
    same programs and — under the suite's int32 quant scan — must
    produce byte-identical trees; ``multihost_scaling_efficiency`` is
    therefore the pure runtime cost of the multi-controller plane
    (t_single_process / t_pod: 1.0 = the pod runtime is free).  Each
    host streams and bins only its own row stripe, so
    ``ingest_rows_per_s_per_host`` is the per-host streaming rate.
    CPU pod legs are always ``host_mesh=true`` — the processes share
    the machine's cores, so treat the timing as plumbing validation,
    not chip truth (same honesty contract as ``chip_pending``)."""
    import socket
    import subprocess
    import tempfile

    hosts = int(args.hosts)
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "_multihost_worker.py")
    outdir = tempfile.mkdtemp(prefix="bench_mh_")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    subprocess.run([sys.executable, worker, "makedata", outdir],
                   env=env, check=True, capture_output=True)

    def _leg(n_hosts):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen(
            [sys.executable, worker, "bench", str(r), str(n_hosts),
             str(port), outdir], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for r in range(n_hosts)]
        deadline = time.time() + 600
        for p in procs:
            p.wait(timeout=max(1, deadline - time.time()))
        out = []
        for r in range(n_hosts):
            path = os.path.join(outdir, f"bench_r{r}.json")
            if not os.path.exists(path):
                raise RuntimeError(
                    f"bench pod leg: rank {r}/{n_hosts} wrote no "
                    f"result (rc={procs[r].returncode})")
            with open(path) as fh:
                out.append(json.load(fh))
            os.remove(path)
        return out

    single = _leg(1)[0]
    pod = _leg(hosts)
    skip = next((r["skip"] for r in pod if "skip" in r), None)
    if skip is not None:
        return {"metric": f"shard_multihost_{hosts}proc_ms_per_tree",
                "value": None, "unit": "ms", "hosts": hosts,
                "skipped": skip, "host_mesh": True}
    single_ms, pod_ms = single["ms_per_tree"], pod[0]["ms_per_tree"]
    rates = [r["ingest_rows_per_s"] for r in pod
             if r.get("ingest_rows_per_s")]
    return {
        "metric": f"shard_multihost_{hosts}proc_ms_per_tree",
        "value": pod_ms,
        "unit": "ms",
        "hosts": hosts,
        "devices_total": 4,
        "legs": {
            "single_process": {"ms_per_tree": single_ms,
                               "load_s": single["load_s"]},
            "multihost": {"ms_per_tree": pod_ms,
                          "load_s": pod[0]["load_s"],
                          "broadcast_bytes": pod[0]["broadcast_bytes"]},
        },
        "multihost_scaling_efficiency": round(
            single_ms / max(pod_ms, 1e-9), 4),
        "ingest_rows_per_s_per_host": round(
            sum(rates) / len(rates), 1) if rates else None,
        "trees_byte_identical": all(
            r["trees"] == single["trees"] for r in pod),
        # localhost pod legs share one machine's cores by construction
        "host_mesh": True,
        "host_sentinel_ms": host_sentinel_ms(),
    }


def run_shard(args) -> dict:
    """Single-controller sharded-training benchmark (docs/Sharding.md):
    single-device vs N-device legs over ONE shared BinnedDataset in ONE
    process, plus a side-by-side against the multiprocess-style
    tree_learner=data mesh path — MULTICHIP_r06 as a single command.

    Emits ``shard_scaling_efficiency`` (= t_single / (D * t_sharded),
    strong scaling at fixed global rows), ``psum_ms_per_tree`` (the
    collective probe x waves/tree: the growth loop's entire sync cost),
    and — since the suite defaults to ``grad_quant_bits=8``'s int32
    scan — ``trees_byte_identical`` between the legs (the
    docs/Sharding.md contract, also gated in CI by check_shard.py).

    With fewer than 2 visible devices on a CPU backend the suite
    re-execs itself once under a forced 4-device host mesh, so the one
    command works on the container AND the TPU driver.  Non-TPU legs
    carry ``host_mesh=true`` — forced host-mesh "devices" share the
    machine's cores, so the scaling/psum timings there validate the
    plumbing, not the chip (same honesty contract as ``chip_pending``).

    ``--hosts N`` switches to the multi-process pod-slice legs
    (:func:`_run_shard_multihost`)."""
    if int(getattr(args, "hosts", 1) or 1) > 1:
        return _run_shard_multihost(args)
    import jax
    from lightgbm_tpu import obs
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.dataset import BinnedDataset

    want_d = int(getattr(args, "shard_devices", 0) or 0)
    if len(jax.devices()) < 2:
        if os.environ.get("BENCH_SHARD_REEXEC"):
            raise RuntimeError(
                "--suite shard needs >= 2 devices and the forced host "
                "mesh did not materialize")
        import subprocess
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count="
                            + str(want_d or 4)).strip()
        env["BENCH_SHARD_REEXEC"] = "1"
        proc = subprocess.run([sys.executable] + sys.argv, env=env,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"shard re-exec child failed rc={proc.returncode}:\n"
                f"{proc.stderr[-2000:]}")
        for ln in reversed(proc.stdout.splitlines()):
            try:
                child = json.loads(ln)
            except json.JSONDecodeError:
                continue
            child["reexec_forced_devices"] = want_d or 4
            # keep the child's telemetry digest (which saw the sharded
            # run) out of main()'s way — it overwrites "obs" with this
            # parent process's registry
            if "obs" in child:
                child["obs_child"] = child.pop("obs")
            return child
        raise RuntimeError("shard re-exec child printed no JSON")

    d = want_d or len(jax.devices())
    # int8 by default: the sharded byte-identity contract lives on the
    # int32 scan, and it is the production regime the suite certifies
    quant = args.quant_bits if args.quant_bits else 8
    base = {
        "objective": "binary", "metric": "auc",
        "num_leaves": args.num_leaves, "max_bin": args.max_bin,
        "learning_rate": args.learning_rate,
        "min_data_in_leaf": 20, "min_sum_hessian_in_leaf": 1e-3,
        "verbosity": 0, "wave_plan": "fixed", "device_growth": "on",
        "grad_quant_bits": quant,
    }
    t0 = time.perf_counter()
    if args.host_data:
        x, y = synth_higgs(args.rows)
        ds = BinnedDataset.construct_from_matrix(x, Config(base))
    else:
        x, y = synth_higgs_device(args.rows)
        ds = BinnedDataset.construct_from_device_matrix(x, Config(base))
        jax.block_until_ready(ds.binned)
    ds.metadata.set_label(y)
    t_prep = time.perf_counter() - t0

    legs = [
        ("single", {"data_sharding": "off"}),
        ("sharded", {"data_sharding": "single_controller",
                     "shard_devices": d}),
        # the multiprocess-mesh analog: the faithful per-split worker
        # learner over the same device mesh (no fused scan, per-wave
        # host dispatch) — the path single-controller sharding replaces
        ("mp_mesh", {"data_sharding": "off", "device_growth": "off",
                     "tree_learner": "data", "num_machines": d,
                     "grad_quant_bits": 0}),
    ]
    leg_out = {}
    models = {}
    psum = None
    for name, extra in legs:
        cfg = Config({**base, **extra})
        bst = create_boosting(cfg)
        t0 = time.perf_counter()
        bst.init_train(ds)
        t_init = time.perf_counter() - t0
        chunk, warm, t_warm, timed_s, iters_timed = timed_train(
            bst, args.iters, args.chunk)
        per_iter = timed_s / max(iters_timed, 1)
        grower = getattr(bst, "_grower", None)
        leg_out[name] = {
            "ms_per_tree": round(1000.0 * per_iter, 2),
            "timed_s": round(timed_s, 3),
            "timed_iters": iters_timed,
            "warmup_compile_s": round(t_warm + t_init, 2),
            "waves_per_tree": _waves_per_tree(bst),
            "fused": bool(chunk),
            "int_scan": bool(getattr(grower, "int_scan", False)),
        }
        if name in ("single", "sharded"):
            bst._flush_pending()
            models[name] = bst.model_to_string().split("\nparameters:",
                                                       1)[0]
        if name == "sharded" and grower is not None:
            # PR-16 attribution: enable cost capture so the probe also
            # lowers the collective program through cost_of and reports
            # its XLA bytes — a mesh-topology fact that stays honest on
            # forced host meshes where the wall-clock does not
            was_enabled = obs.enabled()
            obs.configure(enabled=True, profile_attribution=True)
            psum = grower.profile_psum(reps=5)
            if not was_enabled:
                obs.configure(enabled=False)
        del bst

    single_ms = leg_out["single"]["ms_per_tree"]
    shard_ms = leg_out["sharded"]["ms_per_tree"]
    waves = leg_out["sharded"]["waves_per_tree"] or 0.0
    psum_ms = (psum or {}).get("psum_ms")
    psum_cost = (psum or {}).get("cost")
    psum_bytes = (psum_cost or {}).get("bytes_accessed")
    host_mesh = jax.default_backend() != "tpu"
    return {
        "metric": f"shard_suite_higgs_{args.rows}x28_{args.iters}iter"
                  f"_{d}dev_ms_per_tree",
        "value": shard_ms,
        "unit": "ms",
        "rows": args.rows,
        "iters": args.iters,
        "num_leaves": args.num_leaves,
        "max_bin": args.max_bin,
        "grad_quant_bits": quant,
        "devices": d,
        "prep_s": round(t_prep, 2),
        "legs": leg_out,
        # strong scaling at fixed global rows: 1.0 = perfect.  On
        # host_mesh legs the "devices" share the machine's cores, so
        # the wall-clock ratios below are plumbing validation only —
        # chip-real numbers require host_mesh=false (a TPU backend)
        "host_mesh": host_mesh,
        "shard_scaling_efficiency": round(
            single_ms / max(d * shard_ms, 1e-9), 4),
        "speedup_vs_single": round(single_ms / max(shard_ms, 1e-9), 3),
        "speedup_vs_mp_mesh": round(
            leg_out["mp_mesh"]["ms_per_tree"] / max(shard_ms, 1e-9), 3),
        "psum_ms": psum_ms,
        "psum_ms_per_tree": round(psum_ms * waves, 3)
        if psum_ms is not None else None,
        # mesh-topology facts from the PR-16 attribution path (XLA
        # cost analysis of the collective program): honest even when
        # the timing above is not
        "psum_cost": psum_cost,
        "psum_bytes_per_tree": round(psum_bytes * waves)
        if psum_bytes else None,
        "trees_byte_identical": models["single"] == models["sharded"],
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "host_sentinel_ms": host_sentinel_ms(),
    }


def _coldstart_child(cmd, env, tag, expect_json=True):
    """Run a fresh-process bench/warmup child; returns its last
    parseable JSON line.  ``expect_json=False`` for the warmup CLI
    (which only logs); bench children that yield no JSON raise with
    the tag and output tail instead of handing None to the caller."""
    import subprocess
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"coldstart {tag} child failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}")
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    for ln in reversed(lines):
        try:
            return json.loads(ln)
        except json.JSONDecodeError:
            continue
    if expect_json:
        raise RuntimeError(
            f"coldstart {tag} child printed no JSON result line:\n"
            f"stdout tail: {proc.stdout[-1000:]}\n"
            f"stderr tail: {proc.stderr[-1000:]}")
    return None


def run_coldstart(args) -> dict:
    """Cold-start suite: how much of a fresh process's
    ``warmup_compile_s`` the persistent compile cache removes
    (docs/ColdStart.md).  Three fresh subprocesses against temp cache
    dirs: (1) cold — empty cache; (2) warm — same dir, so every
    executable loads from disk; (3) aot — a dir pre-filled by the
    ``lightgbm-tpu warmup`` CLI alone, the deployment-init story.
    Gates ``pass_5x``: warm cold-start >= 5x faster than cold."""
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    bench_cmd = [
        sys.executable, os.path.join(here, "bench.py"),
        "--suite", "higgs", "--rows", str(args.rows),
        "--iters", str(args.iters), "--chunk", str(args.chunk),
        "--num-leaves", str(args.num_leaves),
        "--max-bin", str(args.max_bin), "--eval-rows", "0",
        "--no-stage-profile", "--engine", args.engine,
        # no --compile-cache-dir: the child's default reads the
        # LGBM_TPU_COMPILE_CACHE env var set per leg below
    ]
    warm_cmd = [
        sys.executable, "-m", "lightgbm_tpu", "warmup",
        f"warmup_rows={args.rows}", "warmup_features=28",
        f"num_iterations={args.iters}", f"fused_chunk={args.chunk}",
        "objective=binary", f"num_leaves={args.num_leaves}",
        f"max_bin={args.max_bin}",
        "device_growth=" + {"device": "on", "host": "off",
                            "auto": "auto"}[args.engine],
        "verbosity=-1",
    ]
    out = {"metric": "coldstart_warm_speedup", "unit": "x",
           "rows": args.rows, "iters": args.iters, "chunk": args.chunk}
    with tempfile.TemporaryDirectory(prefix="lgbm_coldstart_") as tmp:
        dir_a = os.path.join(tmp, "a")
        dir_b = os.path.join(tmp, "b")
        env = dict(os.environ)
        env["LGBM_TPU_COMPILE_CACHE"] = dir_a
        cold = _coldstart_child(bench_cmd, env, "cold")
        warm = _coldstart_child(bench_cmd, env, "warm")
        env["LGBM_TPU_COMPILE_CACHE"] = dir_b
        _coldstart_child(warm_cmd, env, "aot-warmup", expect_json=False)
        aot = _coldstart_child(bench_cmd, env, "aot")
    cold_s = float(cold["warmup_compile_s"])
    warm_s = float(warm["warmup_compile_s"])
    aot_s = float(aot["warmup_compile_s"])
    cold_xla = float(cold.get("xla_compile_s", 0.0))
    warm_xla = float(warm.get("xla_compile_s", 0.0))
    aot_xla = float(aot.get("xla_compile_s", 0.0))
    out.update({
        "value": round(cold_s / max(warm_s, 1e-9), 2),
        "cold_warmup_compile_s": cold_s,
        "warm_warmup_compile_s": warm_s,
        "aot_warmup_compile_s": aot_s,
        "aot_speedup": round(cold_s / max(aot_s, 1e-9), 2),
        "pass_5x": cold_s >= 5.0 * warm_s,
        # the component the cache removes: actual XLA backend-compile
        # seconds (a warm process pays disk retrieval instead; what
        # remains of warmup_compile_s is per-process tracing, which on
        # CPU backends dominates the residual)
        "cold_xla_compile_s": cold_xla,
        "warm_xla_compile_s": warm_xla,
        "aot_xla_compile_s": aot_xla,
        "xla_compile_speedup": round(cold_xla / max(warm_xla, 1e-9), 1),
        "cold_compile_cache": cold.get("obs", {}).get("compile_cache"),
        "warm_compile_cache": warm.get("obs", {}).get("compile_cache"),
        "aot_compile_cache": aot.get("obs", {}).get("compile_cache"),
        "cold_train_s": cold.get("value"),
        "warm_train_s": warm.get("value"),
    })
    return out


def run_cache_admission(args) -> dict:
    """The fork's windowed cache-admission harness
    (examples/cache_admission.py) through the C API's chunked update —
    the workload this fork of LightGBM exists for.  Emits train seconds
    per 1M sampled rows vs the reference's 125.4 s/20M-request window.

    ``--pipeline`` runs the harness twice — the serial C-API loop, then
    the async retrain pipeline (lightgbm_tpu.pipeline) over the same
    trace — and reports the prep-overlap fraction plus the
    pipelined-vs-serial end-to-end speedup next to the headline metric
    (docs/Pipeline.md).  Serial runs first, so its compiled programs
    warm the in-process caches for the pipelined leg and the speedup
    isolates the pipelining itself, not compile time."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "examples", "cache_admission.py")
    spec = importlib.util.spec_from_file_location("cache_admission", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    argv = []
    if args.quick:
        argv = ["--requests", "400000", "--objects", "50000",
                "--window", "200000", "--sample", "100000"]
    result = mod.run(mod.build_arg_parser().parse_args(argv))
    if getattr(args, "pipeline", False):
        pipe = mod.run(mod.build_arg_parser().parse_args(
            argv + ["--pipeline"]))
        result["pipeline"] = {
            "value": pipe["value"],
            "total_s": pipe["total_s"],
            "overlap_fraction": pipe["overlap_fraction"],
            "rebinned_windows": pipe["rebinned_windows"],
            "windows": pipe["windows"],
        }
        result["pipeline_overlap_fraction"] = pipe["overlap_fraction"]
        result["pipeline_speedup_e2e"] = round(
            result["total_s"] / max(pipe["total_s"], 1e-9), 4)
    if getattr(args, "slo", ""):
        result["slo"] = _slo_report(args.slo)
    return result


def run_soak(args) -> dict:
    """``--suite soak``: the composed N-tenant CDN-fleet chaos soak
    (lightgbm_tpu/soak, docs/Soak.md) — per-tenant windowed retrains
    hot-swapping into a shared FleetServer under mixed-tenant query
    load and the scenario's seed-keyed fault timeline, gated on the
    SLO engine plus the harness invariants (resume byte-identity,
    zero-retrace swaps, throughput vs the 125.4 s/20M reference).

    The scenario comes from ``--soak-scenario`` (JSON file) or the
    ``LGBM_TPU_SOAK`` env override; default is the CI smoke shape
    (2 tenants x 3 windows x 1 kill)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.soak import SoakScenario, run_and_report

    path = getattr(args, "soak_scenario", "") or ""
    if path and not os.environ.get("LGBM_TPU_SOAK", ""):
        sc = SoakScenario.from_file(path)
    else:
        sc = SoakScenario.from_config(Config({}))
    verdict = run_and_report(sc)
    thr = verdict["gates"]["throughput"]
    return {
        "metric": "soak_train_s_per_1M_sampled_rows",
        "value": thr["train_s_per_1M_sampled_rows"],
        "unit": "s_per_1m_rows",
        "reference_s_per_1M": thr["reference_s_per_1M"],
        "ok": verdict["ok"],
        "gates": {name: g["ok"]
                  for name, g in verdict["gates"].items()},
        "timeline_digest": verdict["timeline_digest"],
        # non-TPU numbers validate the composition, not the chip
        "chip_pending": verdict["chip_pending"],
        "soak": verdict,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int,
                    default=int(os.environ.get("BENCH_ROWS", 10_500_000)))
    ap.add_argument("--iters", type=int,
                    default=int(os.environ.get("BENCH_ITERS", 500)))
    ap.add_argument("--num-leaves", type=int, default=255)
    ap.add_argument("--max-bin", type=int,
                    default=int(os.environ.get("BENCH_MAX_BIN", 63)),
                    help="63 matches the reference GPU learner's own "
                         "benchmark setting (docs/GPU-Performance.rst); "
                         "255 matches the CPU run")
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--chunk", type=int,
                    default=int(os.environ.get("BENCH_CHUNK", 20)),
                    help="boosting iterations fused per device dispatch "
                         "(GBDT.train_chunked); 0 = per-iteration path")
    ap.add_argument("--quick", action="store_true",
                    help="1M rows, 50 iterations")
    ap.add_argument("--host-data", action="store_true",
                    default=bool(int(os.environ.get("BENCH_HOST_DATA",
                                                    "0"))),
                    help="generate + bin the HIGGS data on host (the "
                         "r4 path); default generates and bins on "
                         "device")
    ap.add_argument("--profile", action="store_true",
                    default=bool(int(os.environ.get("BENCH_PROFILE", "0"))),
                    help="block per phase for honest phase attribution "
                         "(slows the run; don't use for the headline number)")
    ap.add_argument("--eval-rows", type=int, default=500_000,
                    help="held-out rows for AUC (0 disables)")
    ap.add_argument("--quant-bits", type=int,
                    default=int(os.environ.get("BENCH_QUANT_BITS", "0")),
                    choices=[0, 8],
                    help="grad_quant_bits: 8 = int8 stochastic-rounded "
                         "gradient histograms on the MXU's int8->int32 "
                         "path (dequantized before split gains, f32 leaf "
                         "refit); 0 = full-precision bf16 hi/lo")
    ap.add_argument("--wave-plan", choices=["auto", "fixed", "profiled"],
                    default=os.environ.get("BENCH_WAVE_PLAN", "auto"),
                    help="device grower stage plan: profiled = measure "
                         "per-stage wave cost at init and install the "
                         "derived plan; fixed = the byte-stable doubling "
                         "plan; auto = fixed unless a profiled plan is "
                         "cached for this shape/config")
    ap.add_argument("--no-stage-profile", action="store_true",
                    default=os.environ.get("BENCH_STAGE_PROFILE", "")
                    .lower() in ("0", "false", "no"),
                    help="skip the post-run per-stage wave probes (they "
                         "run AFTER the timed region and only add the "
                         "stage_wave_ms/stage_plan_profiled JSON fields)")
    ap.add_argument("--engine", choices=["auto", "device", "host"],
                    default="device",
                    help="device = on-device wave grower (one dispatch per "
                         "iteration); host = host-driven learner; auto = "
                         "device on TPU")
    ap.add_argument("--shard-devices", type=int,
                    default=int(os.environ.get("BENCH_SHARD_DEVICES",
                                               "0")),
                    help="--suite shard: mesh size for the sharded leg "
                         "(0 = all visible devices; on a 1-device CPU "
                         "backend the suite re-execs itself under a "
                         "forced 4-device host mesh)")
    ap.add_argument("--hosts", type=int,
                    default=int(os.environ.get("BENCH_HOSTS", "1")),
                    help="--suite shard: > 1 runs the multi-controller "
                         "pod-slice legs instead — N one-per-host "
                         "processes over a localhost jax.distributed "
                         "coordinator (4 global devices total), each "
                         "streaming its own row stripe, vs a single-"
                         "process single_controller leg on the same "
                         "mesh; emits multihost_scaling_efficiency, "
                         "ingest_rows_per_s_per_host and the byte-"
                         "identity verdict (docs/Sharding.md)")
    ap.add_argument("--explain", action="store_true",
                    help="alias for --suite explain: train one quant-"
                         "shaped leg, then rebuild its ms_per_tree from "
                         "the device-phase probes (per-stage wave "
                         "histogram x stage plan, find_best/split_apply "
                         "per wave, score_update per tree, psum when "
                         "sharded) into a phase-attribution report with "
                         "XLA FLOPs/bytes estimates; value = coverage "
                         "(attributed/measured, bar >= 0.9)")
    ap.add_argument("--device-profile",
                    default=os.environ.get("BENCH_DEVICE_PROFILE", ""),
                    help="--explain: also capture a jax.profiler device "
                         "trace of the timed region into this directory "
                         "(viewable in Perfetto/TensorBoard; silently "
                         "skipped where the profiler is unavailable)")
    ap.add_argument("--suite",
                    choices=["all", "higgs", "mslr", "cache", "serve",
                             "coldstart", "quant", "shard", "explain",
                             "soak"],
                    default=os.environ.get("BENCH_SUITE", "all"),
                    help="all = HIGGS headline + MSLR lambdarank "
                         "(both north stars, BASELINE.md); cache = the "
                         "fork's windowed cache-admission harness vs its "
                         "125.4 s/20M-window reference; serve = packed-"
                         "ensemble PredictionServer throughput + latency "
                         "p50/p95 + hot-swap retrace check; coldstart = "
                         "fresh-subprocess warmup_compile_s cold vs "
                         "persistent-compile-cache warm vs AOT-warmed "
                         "(docs/ColdStart.md; gates warm >= 5x cold); "
                         "quant = paired f32 / int8-einsum / int8-pallas "
                         "legs over one shared dataset in one process, "
                         "emitting ms_per_tree per leg + the speedup "
                         "matrix + kernel routing counters (BENCH_r06); "
                         "shard = single-device vs N-device single-"
                         "controller legs + the multiprocess mesh path "
                         "over one shared dataset, emitting "
                         "shard_scaling_efficiency, psum_ms_per_tree "
                         "and the byte-identity verdict (MULTICHIP_r06, "
                         "docs/Sharding.md); with --hosts N the suite "
                         "runs the multi-process pod-slice legs "
                         "instead; soak = the composed fleet chaos "
                         "soak to an SLO-gated verdict (SOAK_r*, "
                         "docs/Soak.md)")
    ap.add_argument("--soak-scenario",
                    default=os.environ.get("BENCH_SOAK_SCENARIO", ""),
                    help="--suite soak: JSON SoakScenario file "
                         "(docs/Soak.md); empty uses the CI smoke "
                         "shape, LGBM_TPU_SOAK overrides")
    ap.add_argument("--compile-cache-dir",
                    default=os.environ.get(
                        "LGBM_TPU_COMPILE_CACHE",
                        os.path.expanduser("~/.cache/lgbm_tpu_xla")),
                    help="persistent XLA compile cache directory "
                         "(lightgbm_tpu.compile_cache); '0' disables. "
                         "Default: LGBM_TPU_COMPILE_CACHE or "
                         "~/.cache/lgbm_tpu_xla")
    ap.add_argument("--cache-admission", action="store_true",
                    help="alias for --suite cache")
    ap.add_argument("--models", type=int,
                    default=int(os.environ.get("BENCH_MODELS", "4")),
                    help="--suite serve: tenant count M for the model-"
                         "fleet leg (FleetServer: M stacked boosters, "
                         "one jitted dispatch per mixed-tenant batch); "
                         "<= 1 skips the fleet leg")
    ap.add_argument("--pipeline", action="store_true",
                    help="--suite cache: also run the harness through "
                         "the async windowed-retrain pipeline "
                         "(lightgbm_tpu.pipeline) and report prep-"
                         "overlap fraction + pipelined-vs-serial end-"
                         "to-end speedup next to the headline metric")
    ap.add_argument("--slo", default=os.environ.get("BENCH_SLO", ""),
                    help="declarative SLO spec evaluated over the "
                         "rolling telemetry window after the suite "
                         "(obs/slo.py grammar, e.g. "
                         "'availability>=0.999,p95_ms<=50'); the serve "
                         "and cache suites embed the SloReport in the "
                         "result JSON (chip-pending on non-TPU "
                         "backends, like pass_1m_rows_per_s) and the "
                         "obs digest carries its compact form")
    ap.add_argument("--metrics", default=os.environ.get("BENCH_METRICS",
                                                        ""),
                    help="write the telemetry metrics JSON snapshot "
                         "(docs/Observability.md schema) to this path")
    ap.add_argument("--trace", default=os.environ.get("BENCH_TRACE", ""),
                    help="write a Chrome-trace/Perfetto timeline of the "
                         "run to this path")
    ap.add_argument("--no-obs", action="store_true",
                    default=os.environ.get("BENCH_NO_OBS", "").lower()
                    in ("1", "true", "yes"),
                    help="disable the telemetry registry entirely (it is "
                         "on by default so the result JSON carries "
                         "recompile counts and iteration percentiles; "
                         "per-dispatch cost is one flag check + a "
                         "signature hash)")
    args = ap.parse_args()
    if args.quick:
        args.rows = min(args.rows, 1_000_000)
        args.iters = min(args.iters, 50)
        args.chunk = min(args.chunk, 10)   # 50 = 10 warm + 4 x 10 timed
    if args.chunk > 1:
        # keep every dispatch the same scan length (one compiled
        # program), and keep the timed region non-empty: warm-up burns
        # one whole chunk, so chunk can be at most iters/2
        cap = min(args.chunk, max(args.iters // 2, 1))
        args.chunk = max(d for d in range(1, cap + 1)
                         if args.iters % d == 0)

    # telemetry: on by default so every BENCH_*.json round captures
    # recompile counts and p95 iteration time alongside the phase means
    from lightgbm_tpu import obs
    if not args.no_obs or args.metrics or args.trace or args.slo:
        obs.configure(enabled=True, sync=args.profile)
    else:
        # genuinely disable (env vars may have enabled it at import)
        obs.configure(enabled=False)

    # persistent compile cache: the padded-bucket programs recur across
    # runs (and the coldstart suite measures exactly this effect in
    # fresh child processes, via their LGBM_TPU_COMPILE_CACHE env)
    from lightgbm_tpu import compile_cache
    if args.suite != "coldstart":
        compile_cache.configure(args.compile_cache_dir)

    if args.cache_admission:
        args.suite = "cache"
    if args.explain:
        args.suite = "explain"
    if args.suite == "soak":
        result = run_soak(args)
    elif args.suite == "explain":
        result = run_explain(args)
    elif args.suite == "coldstart":
        result = run_coldstart(args)
    elif args.suite == "shard":
        result = run_shard(args)
    elif args.suite == "quant":
        result = run_quant(args)
    elif args.suite == "cache":
        result = run_cache_admission(args)
    elif args.suite == "serve":
        result = run_serve(args)
    elif args.suite == "mslr":
        result = run_mslr(args)
    else:
        result = run_higgs(args)
        if args.suite == "all":
            try:
                result["mslr"] = run_mslr(args)
            except Exception as e:   # noqa: BLE001 — keep the headline
                result["mslr"] = {"error": str(e)}

    if obs.enabled():
        result["obs"] = obs.summary()
        if args.metrics:
            obs.dump_metrics(args.metrics)
        if args.trace:
            obs.dump_trace(args.trace)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
