"""Distributed find-bin and pre-partitioned dataset construction.

The reference's multi-machine loader (``dataset_loader.cpp:765-923``)
splits bin finding across workers — machine ``i`` runs ``FindBin`` for
the contiguous feature block ``[start[i], start[i]+len[i])`` using its
OWN local sample, then the serialized ``BinMapper``s are Allgathered so
every machine shares identical mappers — and distributes rows either
round-robin or pre-partitioned (``:657-704``, one file shard per
machine; the dense matrix only ever exists per shard).

This module is the TPU build's analog.  The pieces are plain functions
so they run in two regimes:

* **single-controller** (this sandbox, tests): every shard's sample is
  visible in one process; ``allgather_mappers`` is a concatenation.
* **multi-controller** (``jax.distributed`` on a real pod): each process
  calls ``find_bin_shard`` on its local sample and passes a real
  gather hook (e.g. ``multihost_utils.process_allgather`` over the
  serialized states) to ``allgather_mappers``; the exactness contract
  is unchanged because mapper serialization round-trips bit-exactly
  (``BinMapper.to_state``/``from_state``).

Reference semantics preserved: bins for feature ``f`` come from the
OWNING shard's sample only (an accepted approximation), and the final
mapper list is identical on every shard.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils.log import LightGBMError
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper
from .dataset import BinnedDataset


def partition_features(num_total_features: int, num_machines: int):
    """(start, length) per machine — the reference's contiguous block
    split (dataset_loader.cpp:846-857)."""
    step = max((num_total_features + num_machines - 1) // num_machines, 1)
    start, length = [0] * num_machines, [0] * num_machines
    for i in range(num_machines - 1):
        length[i] = min(step, num_total_features - start[i])
        start[i + 1] = start[i] + length[i]
    length[num_machines - 1] = num_total_features - start[num_machines - 1]
    return start, length


def find_bin_shard(local_sample: np.ndarray, rank: int, num_machines: int,
                   config, categorical: Sequence[int] = (),
                   total_sample_cnt: Optional[int] = None,
                   num_data: Optional[int] = None):
    """Find bin mappers for THIS shard's owned feature block from its
    local sample.  Returns ``(start, serialized_mapper_states)`` where
    states are ``BinMapper.to_state()`` dicts (the CopyTo buffer analog,
    dataset_loader.cpp:885-899) ready to allgather."""
    local_sample = np.asarray(local_sample, np.float64)
    nf = local_sample.shape[1]
    start, length = partition_features(nf, num_machines)
    lo, ln = start[rank], length[rank]
    total = int(total_sample_cnt or local_sample.shape[0])
    nd = int(num_data or local_sample.shape[0])
    # EXACT mirror of the local path's scaling (dataset.py _find_bins;
    # dataset_loader.cpp:787) so identical samples give identical
    # mappers — the module's exactness contract
    filter_cnt = int(0.95 * config.min_data_in_leaf / max(nd, 1)
                     * local_sample.shape[0])
    cats = set(int(c) for c in categorical)
    states = []
    for f in range(lo, lo + ln):
        m = BinMapper()
        bt = BIN_CATEGORICAL if f in cats else BIN_NUMERICAL
        vals = local_sample[:, f]
        # recorded values only — exact zeros stay implicit, matching the
        # local path's `col != 0.0` classification (values below the
        # kZeroThreshold but nonzero are still "recorded" there)
        vals = vals[(vals != 0.0) | np.isnan(vals)]
        m.find_bin(vals, total, config.max_bin, config.min_data_in_bin,
                   filter_cnt, bin_type=bt,
                   use_missing=bool(config.use_missing),
                   zero_as_missing=bool(config.zero_as_missing))
        states.append(m.to_state())
    return lo, states


def allgather_mappers(shard_states, gather_fn=None,
                      num_total_features: Optional[int] = None
                      ) -> List[BinMapper]:
    """Assemble the full mapper list from every shard's
    ``(start, states)`` pair — the Allgather of serialized BinMappers
    (dataset_loader.cpp:900-917).  ``gather_fn`` exchanges the local
    pair for the list of all pairs under multi-controller; defaults to
    the identity for single-controller callers that already hold all
    shards.  Pass ``num_total_features`` to catch a partial gather (a
    dropped trailing shard is otherwise a contiguous prefix)."""
    if gather_fn is not None:
        shard_states = gather_fn(shard_states)
    pairs = sorted(shard_states, key=lambda p: p[0])
    expect = 0
    mappers: List[BinMapper] = []
    for lo, states in pairs:
        if lo != expect:
            raise LightGBMError(
                f"distributed find-bin shards misaligned: expected "
                f"feature {expect}, got {lo}")
        mappers.extend(BinMapper.from_state(s) for s in states)
        expect = lo + len(states)
    if num_total_features is not None and expect != num_total_features:
        raise LightGBMError(
            f"distributed find-bin gathered {expect} features, expected "
            f"{num_total_features} (partial gather?)")
    return mappers


def jax_process_gather(pair, max_bytes: int = 1 << 22):
    """The REAL multi-controller gather hook for ``allgather_mappers``:
    exchanges this process's ``(start, states)`` pair for every
    process's pair over ``jax.distributed`` (the analog of the
    reference's ``Network::Allgather`` of serialized BinMappers,
    dataset_loader.cpp:900-917).

    Serialized mappers are variable-size python objects, so each pair is
    pickled into a fixed-size length-prefixed uint8 buffer and exchanged
    with ``multihost_utils.process_allgather`` — the standard JAX idiom
    for host-blob exchange.  Requires ``jax.distributed.initialize`` to
    have run; single-controller callers never need this (the default
    identity hook already sees all shards)."""
    import pickle

    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    blob = pickle.dumps(pair)
    if len(blob) + 8 > max_bytes:
        raise LightGBMError(
            f"serialized mapper shard ({len(blob)} bytes) exceeds the "
            f"{max_bytes}-byte gather buffer; raise max_bytes")
    buf = np.zeros(max_bytes, np.uint8)
    buf[:8] = np.frombuffer(len(blob).to_bytes(8, "little"), np.uint8)
    buf[8:8 + len(blob)] = np.frombuffer(blob, np.uint8)
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(buf)))
    out = []
    for row in gathered.reshape(-1, max_bytes):
        ln = int.from_bytes(bytes(row[:8]), "little")
        out.append(pickle.loads(bytes(row[8:8 + ln])))
    return out


def construct_pre_partitioned(row_shards: Sequence[np.ndarray], config,
                              categorical: Sequence[int] = (),
                              sample_per_shard: int = 0):
    """Full pre-partitioned pipeline over already-sharded rows (the
    ``pre_partition=true`` path, dataset_loader.cpp:657-704): each shard
    finds bins for its owned feature block from ITS OWN rows (optionally
    subsampled), mappers are allgathered, and each shard's rows are
    binned ONE SHARD AT A TIME against the shared mappers — the dense
    float64 view exists only per shard, never globally.  The dataset
    structure (EFB bundling, group layout) comes from shard 0's rows,
    the same owner-shard approximation the reference accepts for bins.

    Returns ``(dataset, shard_row_offsets)``; the dataset's binned
    matrix is the concatenation of the shard blocks in shard order, so
    ``DataParallelTreeLearner`` places each block on its device
    unchanged (network.shard_rows contract)."""
    from ..utils.random import make_rng

    num_machines = len(row_shards)
    if num_machines == 0:
        raise LightGBMError("need at least one row shard")
    shards = [np.asarray(s, np.float64) for s in row_shards]
    nf = shards[0].shape[1]
    if any(s.shape[1] != nf for s in shards):
        raise LightGBMError("row shards disagree on feature count")
    total_rows = sum(s.shape[0] for s in shards)

    pairs = []
    for rank, s in enumerate(shards):
        sample = s
        if sample_per_shard and s.shape[0] > sample_per_shard:
            rng = make_rng(int(config.data_random_seed) + rank)
            sample = s[rng.choice(s.shape[0], sample_per_shard,
                                  replace=False)]
        pairs.append(find_bin_shard(sample, rank, num_machines, config,
                                    categorical,
                                    total_sample_cnt=sample.shape[0],
                                    num_data=total_rows))
    mappers = allgather_mappers(pairs, num_total_features=nf)

    # shard 0 defines the structure; the other shards bin against it
    # with reference alignment (CreateValid semantics) and only their
    # uint8 blocks are kept
    ds0 = BinnedDataset.construct_from_matrix(
        shards[0], config, categorical, predefined_mappers=mappers)
    blocks = [np.asarray(ds0.binned)]
    for s in shards[1:]:
        part = BinnedDataset.construct_from_matrix(s, config,
                                                   reference=ds0)
        blocks.append(np.asarray(part.binned))
    ds = ds0
    ds.binned = np.concatenate(blocks, axis=0)
    ds.num_data = total_rows
    from .dataset import Metadata
    ds.metadata = Metadata(total_rows)
    ds._raw = None
    offsets = np.concatenate(
        [[0], np.cumsum([s.shape[0] for s in shards])])
    return ds, offsets
