"""Two-round streaming text loading with a double-buffered reader.

The reference never materializes a Criteo-scale text file: ``two_round``
loading samples ``bin_construct_sample_cnt`` rows for bin finding in a
first pass, then re-streams the file and pushes binned rows directly into
the dataset (``dataset_loader.cpp:161-264``), with a double-buffered
async reader overlapping disk IO and parsing
(``utils/pipeline_reader.h:19-66``).

This module is the TPU build's equivalent: round one streams chunks
through a background reader thread, reservoir-samples rows, and counts
the total; round two re-streams and bins chunk-by-chunk into the
preallocated ``(N, G)`` uint8 matrix.  Peak host memory is
O(sample + chunk + N*G) — the dense float64 matrix never exists.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utils.file_io import open_text
from ..utils.log import LightGBMError, log_info
from .parser import _atof, _sniff

_CHUNK_BYTES = 8 << 20          # ~8 MB of text per chunk


def _chunk_reader(path: str, skip_header: bool) -> Iterator[List[str]]:
    """Yield lists of lines, double-buffered: a background thread reads
    the next chunk from disk while the consumer parses the current one
    (the ``PipelineReader`` pattern, utils/pipeline_reader.h:19-66)."""
    q: "queue.Queue" = queue.Queue(maxsize=2)

    def reader():
        try:
            with open_text(path) as fh:
                if skip_header:
                    fh.readline()
                while True:
                    lines = fh.readlines(_CHUNK_BYTES)
                    if not lines:
                        break
                    q.put(lines)
        except Exception as e:    # noqa: BLE001 — forwarded to consumer
            q.put(e)
        finally:
            q.put(None)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is None:
            break
        if isinstance(item, Exception):
            raise item
        yield item
    t.join()


class _Format:
    """Sniffed file format + per-chunk parse to a float64 matrix."""

    def __init__(self, path: str, config):
        self.header = bool(getattr(config, "header", False))
        with open_text(path) as fh:
            if self.header:
                self.header_line = fh.readline()
            sample = [fh.readline() for _ in range(50)]
        sample = [l for l in sample if l and l.strip()]
        if not sample:
            raise LightGBMError(f"empty data file {path}")
        self.kind = _sniff(sample)
        lc = str(getattr(config, "label_column", "") or "0")
        self.label_col = 0
        label_name = None
        if lc.startswith("name:"):
            label_name = lc[5:]
            if not self.header:
                raise LightGBMError(
                    "label_column=name: requires header=true")
        else:
            self.label_col = int(lc)
        if self.kind == "libsvm":
            self.num_cols = 0     # grows while scanning round one
            self.names = None
        else:
            self.delim = "\t" if self.kind == "tsv" else ","
            ncol = len(sample[0].rstrip("\n").split(self.delim))
            self.num_cols = ncol - 1          # minus label
            self.names = None
            if self.header:
                cols = [c.strip() for c in
                        self.header_line.rstrip("\n").split(self.delim)]
                if label_name is not None:
                    if label_name not in cols:
                        raise LightGBMError(
                            f"label column name {label_name!r} not found "
                            f"in header")
                    self.label_col = cols.index(label_name)
                self.names = [c for i, c in enumerate(cols)
                              if i != self.label_col]

    def parse_chunk(self, lines: List[str], num_features: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (x (n, num_features) float64, label (n,) float64)."""
        if self.kind == "libsvm":
            labels, rows, cols, vals = [], [], [], []
            for line in lines:
                toks = line.split()
                if not toks:
                    continue
                labels.append(float(toks[0]))
                r = len(labels) - 1
                for t in toks[1:]:
                    c, v = t.split(":", 1)
                    c = int(c)
                    if c < num_features:
                        rows.append(r)
                        cols.append(c)
                        vals.append(float(v))
            x = np.zeros((len(labels), num_features), np.float64)
            if cols:
                x[rows, cols] = vals
            return x, np.asarray(labels, np.float64)
        out = np.empty((len(lines), self.num_cols + 1), np.float64)
        n = 0
        for line in lines:
            if not line.strip():
                continue
            toks = line.rstrip("\n").split(self.delim)
            out[n, :len(toks)] = [_atof(t) for t in toks]
            if len(toks) < out.shape[1]:
                out[n, len(toks):] = np.nan
            n += 1
        out = out[:n]
        label = out[:, self.label_col]
        x = np.delete(out, self.label_col, axis=1)
        return x, label

    def scan_columns(self, lines: List[str]) -> int:
        """libsvm round-one helper: max feature index + 1 in this chunk."""
        mx = 0
        for line in lines:
            for t in line.split()[1:]:
                c = t.split(":", 1)[0]
                mx = max(mx, int(c) + 1)
        return mx


def iter_parsed_chunks(path: str, config, num_features: int):
    """Public chunked-parse entry point: yields ``(x, label)`` float64
    chunks behind the double-buffered reader.  Used by the CLI's
    streaming prediction (``predictor.hpp:170-259`` analog)."""
    fmt = _Format(path, config)
    for lines in _chunk_reader(path, fmt.header):
        yield fmt.parse_chunk(lines, num_features)


def load_text_two_round(path: str, config, categorical=(),
                        reference=None):
    """Stream-load ``path`` into a BinnedDataset without materializing
    the float64 matrix (dataset_loader.cpp:161-264 semantics).

    Returns ``(dataset, label)``.
    """
    from .dataset import BinnedDataset

    if not os.path.exists(path):
        raise LightGBMError(f"could not open data file {path}")
    fmt = _Format(path, config)
    sample_cnt_target = int(config.bin_construct_sample_cnt)
    rng = np.random.default_rng(config.data_random_seed & 0x7FFFFFFF)

    # ---- round one: count rows, reservoir-sample for bin finding ------
    n_total = 0
    num_cols = fmt.num_cols
    reservoir: Optional[np.ndarray] = None      # (sample, F) float64
    res_filled = 0
    for lines in _chunk_reader(path, fmt.header):
        if fmt.kind == "libsvm":
            num_cols = max(num_cols, fmt.scan_columns(lines))
            fmt.num_cols = num_cols
        x, _ = fmt.parse_chunk(lines, num_cols)
        if reservoir is None:
            reservoir = np.zeros((sample_cnt_target, x.shape[1]))
        elif x.shape[1] > reservoir.shape[1]:   # libsvm column growth
            pad = np.zeros((sample_cnt_target,
                            x.shape[1] - reservoir.shape[1]))
            reservoir = np.hstack([reservoir, pad])
        # chunk-vectorized reservoir sampling: fill the head directly,
        # then draw all acceptance slots for the chunk's remaining rows
        # in one rng call (duplicate slots keep the LAST writer, matching
        # sequential reservoir order via np's last-write-wins on argsorted
        # unique; a per-row Python loop here costs minutes at 10M rows)
        m = x.shape[0]
        take_head = min(max(sample_cnt_target - res_filled, 0), m)
        if take_head:
            reservoir[res_filled:res_filled + take_head, :x.shape[1]] = \
                x[:take_head]
            res_filled += take_head
        rest = np.arange(take_head, m)
        if len(rest):
            slots = rng.integers(0, n_total + rest + 1)
            accept = slots < sample_cnt_target
            rs, ss = rest[accept], slots[accept]
            if len(rs):
                # later rows overwrite earlier ones on slot collisions
                reservoir[ss, :] = 0.0
                reservoir[ss, :x.shape[1]] = x[rs]
        n_total += m
    if n_total == 0:
        raise LightGBMError(f"data file {path} is empty")
    sample = reservoir[:res_filled]
    log_info(f"two-round load: {n_total} rows, sampled {res_filled} "
             f"for bin finding ({fmt.kind})")

    # ---- bin finding + bundling from the sample ------------------------
    ds = BinnedDataset.construct_streaming_begin(
        sample, n_total, num_cols, config, categorical,
        feature_names=fmt.names, reference=reference)

    # ---- round two: bin chunk-wise into the (N, G) matrix --------------
    start = 0
    label = np.zeros(n_total, np.float64)
    for lines in _chunk_reader(path, fmt.header):
        x, y = fmt.parse_chunk(lines, num_cols)
        ds.construct_streaming_push(x, start)
        label[start:start + len(y)] = y
        start += x.shape[0]
    ds.construct_streaming_finish()
    ds.metadata.set_label(label)
    return ds, label
