"""Two-round streaming text loading with a double-buffered reader.

The reference never materializes a Criteo-scale text file: ``two_round``
loading samples ``bin_construct_sample_cnt`` rows for bin finding in a
first pass, then re-streams the file and pushes binned rows directly into
the dataset (``dataset_loader.cpp:161-264``), with a double-buffered
async reader overlapping disk IO and parsing
(``utils/pipeline_reader.h:19-66``).

This module is the TPU build's equivalent: round one streams chunks
through a background reader thread, reservoir-samples rows, and counts
the total; round two re-streams and bins chunk-by-chunk into the
preallocated ``(N, G)`` uint8 matrix.  Peak host memory is
O(sample + chunk + N*G) — the dense float64 matrix never exists.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .. import obs
from ..robust import faults
from ..utils.file_io import open_text
from ..utils.log import LightGBMError, log_info
from .parser import _atof, _sniff

_CHUNK_BYTES = 8 << 20          # ~8 MB of text per chunk


def _chunk_reader(path: str,
                  skip_header: bool) -> Iterator[Tuple[int, List[str]]]:
    """Yield ``(first_line_number, lines)`` chunks, double-buffered: a
    background thread reads the next chunk from disk while the consumer
    parses the current one (the ``PipelineReader`` pattern,
    utils/pipeline_reader.h:19-66).  Line numbers are 1-based file
    positions so parse errors can name the offending row.

    Abandonment-safe (docs/Robustness.md): if the consumer stops early
    — a parse error propagates, the generator is closed or collected —
    the ``finally`` block trips ``stop`` and the reader's bounded put
    notices within 0.1 s, so the thread can NEVER hang forever blocked
    on the full queue (the failure mode of an unconditional
    ``q.put``)."""
    q: "queue.Queue" = queue.Queue(maxsize=2)
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def reader():
        line_no = 1
        try:
            faults.check("io.read")
            with open_text(path) as fh:
                if skip_header:
                    fh.readline()
                    line_no += 1
                while True:
                    lines = fh.readlines(_CHUNK_BYTES)
                    if not lines:
                        break
                    if not put((line_no, lines)):
                        return
                    line_no += len(lines)
        except Exception as e:    # noqa: BLE001 — forwarded to consumer
            put(e)
            return
        put(None)

    def next_item():
        # timed get + liveness check: a reader killed mid-chunk (OOM,
        # interpreter teardown) must surface as an error, not hang the
        # consumer forever on an empty queue
        while True:
            try:
                return q.get(timeout=0.5)
            except queue.Empty:
                if t.is_alive():
                    continue
                try:
                    # the reader may have delivered its last item (or
                    # sentinel) between the timeout and the death check
                    return q.get_nowait()
                except queue.Empty:
                    raise LightGBMError(
                        f"stream reader thread for {path} died "
                        "without delivering a result") from None

    t = threading.Thread(target=reader, daemon=True,
                         name="lgbm-stream-reader")
    t.start()
    try:
        while True:
            item = next_item()
            if item is None:
                break
            if isinstance(item, LightGBMError):
                raise item
            if isinstance(item, Exception):
                raise LightGBMError(
                    f"failed reading data file {path}: {item}") from item
            yield item
    finally:
        stop.set()
        # unpark a reader blocked on a full queue, then reap it
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)


def _parse_chunk_checked(fmt: "_Format", path: str, line_no: int,
                         lines: List[str], num_cols: int):
    """``fmt.parse_chunk`` with failure context: a poisoned row (bad
    float, truncated ``feat:value`` token, ragged line) surfaces as a
    :class:`LightGBMError` naming the FILE and LINE instead of a bare
    ``ValueError`` from deep inside numpy."""
    try:
        faults.check("stream.parse")
        return fmt.parse_chunk(lines, num_cols)
    except LightGBMError:
        raise
    except Exception as e:      # noqa: BLE001 — re-raised with location
        row = _locate_bad_line(fmt, lines, num_cols)
        where = (f"line {line_no + row}: {lines[row].rstrip()!r}"
                 if row is not None
                 else f"lines {line_no}-{line_no + len(lines) - 1}")
        raise LightGBMError(
            f"failed to parse data file {path} at {where} "
            f"(truncated or malformed row?): {e}") from e


def _locate_bad_line(fmt: "_Format", lines: List[str],
                     num_cols: int) -> Optional[int]:
    """Error-path-only bisect: which single line fails to parse."""
    for i, line in enumerate(lines):
        try:
            fmt.parse_chunk([line], num_cols)
        except Exception:       # noqa: BLE001 — probing
            return i
    return None


class _Format:
    """Sniffed file format + per-chunk parse to a float64 matrix."""

    def __init__(self, path: str, config):
        self.header = bool(getattr(config, "header", False))
        with open_text(path) as fh:
            if self.header:
                self.header_line = fh.readline()
            sample = [fh.readline() for _ in range(50)]
        sample = [l for l in sample if l and l.strip()]
        if not sample:
            raise LightGBMError(f"empty data file {path}")
        self.kind = _sniff(sample)
        lc = str(getattr(config, "label_column", "") or "0")
        self.label_col = 0
        label_name = None
        if lc.startswith("name:"):
            label_name = lc[5:]
            if not self.header:
                raise LightGBMError(
                    "label_column=name: requires header=true")
        else:
            self.label_col = int(lc)
        if self.kind == "libsvm":
            self.num_cols = 0     # grows while scanning round one
            self.names = None
        else:
            self.delim = "\t" if self.kind == "tsv" else ","
            ncol = len(sample[0].rstrip("\n").split(self.delim))
            self.num_cols = ncol - 1          # minus label
            self.names = None
            if self.header:
                cols = [c.strip() for c in
                        self.header_line.rstrip("\n").split(self.delim)]
                if label_name is not None:
                    if label_name not in cols:
                        raise LightGBMError(
                            f"label column name {label_name!r} not found "
                            f"in header")
                    self.label_col = cols.index(label_name)
                self.names = [c for i, c in enumerate(cols)
                              if i != self.label_col]

    def parse_chunk(self, lines: List[str], num_features: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (x (n, num_features) float64, label (n,) float64)."""
        if self.kind == "libsvm":
            labels, rows, cols, vals = [], [], [], []
            for line in lines:
                toks = line.split()
                if not toks:
                    continue
                labels.append(float(toks[0]))
                r = len(labels) - 1
                for t in toks[1:]:
                    c, v = t.split(":", 1)
                    c = int(c)
                    if c < num_features:
                        rows.append(r)
                        cols.append(c)
                        vals.append(float(v))
            x = np.zeros((len(labels), num_features), np.float64)
            if cols:
                x[rows, cols] = vals
            return x, np.asarray(labels, np.float64)
        out = np.empty((len(lines), self.num_cols + 1), np.float64)
        n = 0
        for line in lines:
            if not line.strip():
                continue
            toks = line.rstrip("\n").split(self.delim)
            out[n, :len(toks)] = [_atof(t) for t in toks]
            if len(toks) < out.shape[1]:
                out[n, len(toks):] = np.nan
            n += 1
        out = out[:n]
        label = out[:, self.label_col]
        x = np.delete(out, self.label_col, axis=1)
        return x, label

    def scan_columns(self, lines: List[str]) -> int:
        """libsvm round-one helper: max feature index + 1 in this chunk."""
        mx = 0
        for line in lines:
            for t in line.split()[1:]:
                c = t.split(":", 1)[0]
                mx = max(mx, int(c) + 1)
        return mx


def iter_parsed_chunks(path: str, config, num_features: int):
    """Public chunked-parse entry point: yields ``(x, label)`` float64
    chunks behind the double-buffered reader.  Used by the CLI's
    streaming prediction (``predictor.hpp:170-259`` analog)."""
    fmt = _Format(path, config)
    for line_no, lines in _chunk_reader(path, fmt.header):
        yield _parse_chunk_checked(fmt, path, line_no, lines,
                                   num_features)


def _round_one(path: str, fmt: "_Format", config
               ) -> Tuple[np.ndarray, int, int]:
    """Round one of a two-round load: stream the file once behind the
    double-buffered reader, count rows, grow the libsvm column bound,
    and reservoir-sample ``bin_construct_sample_cnt`` rows for bin
    finding.  Returns ``(sample, n_total, num_cols)``."""
    sample_cnt_target = int(config.bin_construct_sample_cnt)
    rng = np.random.default_rng(config.data_random_seed & 0x7FFFFFFF)
    n_total = 0
    num_cols = fmt.num_cols
    reservoir: Optional[np.ndarray] = None      # (sample, F) float64
    res_filled = 0
    for line_no, lines in _chunk_reader(path, fmt.header):
        if fmt.kind == "libsvm":
            try:
                num_cols = max(num_cols, fmt.scan_columns(lines))
            except Exception as e:   # noqa: BLE001 — located below
                raise LightGBMError(
                    f"failed to parse data file {path} near line "
                    f"{line_no} (truncated feature:value token?): "
                    f"{e}") from e
            fmt.num_cols = num_cols
        x, _ = _parse_chunk_checked(fmt, path, line_no, lines, num_cols)
        if reservoir is None:
            reservoir = np.zeros((sample_cnt_target, x.shape[1]))
        elif x.shape[1] > reservoir.shape[1]:   # libsvm column growth
            pad = np.zeros((sample_cnt_target,
                            x.shape[1] - reservoir.shape[1]))
            reservoir = np.hstack([reservoir, pad])
        # chunk-vectorized reservoir sampling: fill the head directly,
        # then draw all acceptance slots for the chunk's remaining rows
        # in one rng call (duplicate slots keep the LAST writer, matching
        # sequential reservoir order via np's last-write-wins on argsorted
        # unique; a per-row Python loop here costs minutes at 10M rows)
        m = x.shape[0]
        take_head = min(max(sample_cnt_target - res_filled, 0), m)
        if take_head:
            reservoir[res_filled:res_filled + take_head, :x.shape[1]] = \
                x[:take_head]
            res_filled += take_head
        rest = np.arange(take_head, m)
        if len(rest):
            slots = rng.integers(0, n_total + rest + 1)
            accept = slots < sample_cnt_target
            rs, ss = rest[accept], slots[accept]
            if len(rs):
                # later rows overwrite earlier ones on slot collisions
                reservoir[ss, :] = 0.0
                reservoir[ss, :x.shape[1]] = x[rs]
        n_total += m
    if n_total == 0:
        raise LightGBMError(f"data file {path} is empty")
    sample = reservoir[:res_filled]
    log_info(f"two-round load: {n_total} rows, sampled {res_filled} "
             f"for bin finding ({fmt.kind})")
    return sample, n_total, num_cols


def _round_two(path: str, fmt: "_Format", ds, num_cols: int,
               n_total: int,
               row_span: Optional[Tuple[int, int]] = None) -> np.ndarray:
    """Round two: re-stream the file and bin chunk-wise into the
    preallocated ``(N, G)`` matrix; returns the full label vector.

    ``row_span=(lo, hi)`` restricts BINNING to the global row block
    ``[lo, hi)``, pushed at LOCAL coordinates ``row - lo`` — the
    host-sharded ingest path, where ``ds`` holds only this host's
    padded block.  Labels are always parsed for every row (gradients
    are computed host-side from the replicated score, so every pod
    host needs the global label vector).  The double-buffered reader's
    liveness timeout and parse-location errors apply to the filtered
    path unchanged."""
    start = 0
    label = np.zeros(n_total, np.float64)
    lo, hi = row_span if row_span is not None else (0, n_total)
    for line_no, lines in _chunk_reader(path, fmt.header):
        x, y = _parse_chunk_checked(fmt, path, line_no, lines, num_cols)
        m = x.shape[0]
        label[start:start + len(y)] = y
        a, b = max(start, lo), min(start + m, hi)
        if a < b:
            ds.construct_streaming_push(x[a - start:b - start], a - lo)
        start += m
    ds.construct_streaming_finish()
    return label


def load_text_two_round(path: str, config, categorical=(),
                        reference=None):
    """Stream-load ``path`` into a BinnedDataset without materializing
    the float64 matrix (dataset_loader.cpp:161-264 semantics).

    Returns ``(dataset, label)``.
    """
    from .dataset import BinnedDataset

    if not os.path.exists(path):
        raise LightGBMError(f"could not open data file {path}")
    fmt = _Format(path, config)
    sample, n_total, num_cols = _round_one(path, fmt, config)

    # ---- bin finding + bundling from the sample ------------------------
    ds = BinnedDataset.construct_streaming_begin(
        sample, n_total, num_cols, config, categorical,
        feature_names=fmt.names, reference=reference)

    # ---- round two: bin chunk-wise into the (N, G) matrix --------------
    label = _round_two(path, fmt, ds, num_cols, n_total)
    ds.metadata.set_label(label)
    return ds, label


def load_text_multihost(path: str, config, categorical=()):
    """Pod-slice two-round streaming load (docs/Sharding.md).

    Bins and bundles must be found ONCE for the whole pod — per-host
    bin finding would give each host different mappers and silently
    diverge the models — so host 0 runs round one over the full file
    (count + reservoir sample + find-bin, exactly the single-process
    path) and broadcasts the serialized mapper reference over the blob
    plane one port above the coordinator.  Every host (including host
    0, for byte-identical mapper state) then rebuilds the skeleton
    from the SAME bytes, allocates only its contiguous padded row
    block ``[lo, hi)`` of the pod layout, and streams round two
    locally: labels parse globally, binning is row-span filtered, so
    the ``(N, G)`` matrix memory and binning compute scale per host.

    Returns ``(dataset, label)`` where ``dataset.num_data`` is the
    GLOBAL row count, ``dataset.binned`` holds only this host's padded
    block, and ``dataset.host_shard`` / ``dataset.host_row_span`` mark
    the layout for ``DeviceGrower`` (which validates the span).

    A peer that dies during ingest surfaces as a
    :class:`LightGBMError` naming the host and file: the reference
    broadcast and the post-ingest layout handshake both ride the
    deadline-bound blob plane (host 0 names the hosts that never
    connected; peers get the ``net.connect`` retry error), and parse /
    reader-thread failures inside the filtered round keep their file +
    line context, prefixed with this host's rank.
    """
    from .dataset import BinnedDataset
    from ..ops.shard import (make_pod_mesh, multihost_params,
                             multihost_setup, process_row_span,
                             shard_local_rows)
    from ..parallel.network import broadcast_blob, pod_broadcast_address
    from ..pipeline.bins import (reference_from_bytes,
                                 reference_layout_digest,
                                 reference_to_bytes)

    resolved = multihost_params(config)
    if resolved is None:
        raise LightGBMError(
            "load_text_multihost: no coordinator configured — set "
            "coordinator_address/num_hosts/host_rank (or the "
            "LGBM_TPU_COORDINATOR/LGBM_TPU_NUM_HOSTS/"
            "LGBM_TPU_HOST_RANK env vars)")
    coord = resolved[0]
    rank, hosts = multihost_setup(config)
    mesh = make_pod_mesh()
    addr = pod_broadcast_address(coord)

    def _blob_round(payload, what):
        try:
            return broadcast_blob(payload, address=addr,
                                  num_hosts=hosts, rank=rank,
                                  config=config)
        except LightGBMError as e:
            raise LightGBMError(
                f"sharded ingest of {path} failed on host {rank} "
                f"during {what}: {e}") from e

    if not os.path.exists(path):
        raise LightGBMError(
            f"could not open data file {path} (host {rank})")
    fmt = _Format(path, config)

    # ---- round one on host 0 only, reference over the blob plane ------
    blob = None
    if rank == 0:
        sample, n_total, num_cols = _round_one(path, fmt, config)
        ref = BinnedDataset.construct_streaming_begin(
            sample, n_total, num_cols, config, categorical,
            feature_names=fmt.names)
        ref.binned = None     # mappers/bundles only; blocks stay local
        blob = reference_to_bytes(
            ref, extra={"n_total": n_total, "num_cols": num_cols})
    blob = _blob_round(blob, "mapper-reference broadcast")
    skeleton, extra = reference_from_bytes(blob)
    n_total = int(extra["n_total"])
    num_cols = int(extra["num_cols"])
    if fmt.kind == "libsvm":
        fmt.num_cols = num_cols   # adopt host 0's global column bound

    # ---- this host's contiguous padded block of the pod row layout ----
    n_loc = shard_local_rows(n_total, int(mesh.devices.size), config)
    lo, hi = process_row_span(mesh, n_loc)
    ds = BinnedDataset.construct_streaming_begin(
        np.zeros((0, num_cols)), hi - lo, num_cols, config, categorical,
        feature_names=fmt.names, reference=skeleton)

    # ---- round two: parse globally, bin this host's span locally ------
    t0 = time.perf_counter()
    try:
        label = _round_two(path, fmt, ds, num_cols, n_total,
                           row_span=(lo, hi))
    except LightGBMError as e:
        raise LightGBMError(f"[host {rank}] {e}") from e
    binned_rows = max(0, min(hi, n_total) - min(lo, n_total))
    obs.set_gauge("ingest.rows_per_s",
                  binned_rows / max(time.perf_counter() - t0, 1e-9))

    # ---- flip to the global-row contract the grower validates ---------
    ds.num_data = n_total
    ds.metadata = type(ds.metadata)(n_total)
    ds.host_shard = True
    ds.host_row_span = (lo, hi)
    ds.metadata.set_label(label)

    # ---- post-ingest handshake: liveness barrier + layout cross-check -
    my_digest = reference_layout_digest(ds).encode()
    echoed = _blob_round(my_digest if rank == 0 else None,
                         "post-ingest layout handshake")
    if echoed != my_digest:
        raise LightGBMError(
            f"host {rank} binned {path} with a different feature "
            f"layout than host 0 (digest {my_digest.decode()[:12]} vs "
            f"{echoed.decode()[:12]}); pod ingest diverged")
    log_info(f"multihost load: host {rank}/{hosts} holds rows "
             f"[{lo}, {hi}) of {n_total} "
             f"({binned_rows} real, {fmt.kind})")
    return ds, label
