"""Binned dataset construction: sampling, bin finding, EFB, group storage.

TPU-native analog of the reference's ``Dataset`` / ``FeatureGroup`` /
``DatasetLoader`` stack (``src/io/dataset.cpp``, ``include/LightGBM/
feature_group.h:16-76``, ``src/io/dataset_loader.cpp``).  The binned matrix is
a dense ``(num_data, num_groups)`` uint8 array destined for HBM: every feature
group holds <= 256 total bins (the same cap the reference applies to its GPU
learner) so one byte per group-cell always suffices and histograms have a
static 256-bin axis.

Group-slot encoding matches the reference (feature_group.h:33-51,128-136):
slot 0 of every group means "all features at their default bin"; feature ``f``
with bin ``b != default_bin(f)`` maps to ``offset(f) + b - (1 if
default_bin(f) == 0 else 0)``.  The reference reconstructs the skipped default
bin on the fly (``FixHistogram``); here the split scanner does the same
reconstruction on device from leaf totals.
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import obs
from ..config import Config
from ..utils.log import LightGBMError, log_info, log_warning
from ..utils.random import make_rng
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper

MAX_GROUP_BIN = 256   # static histogram bin axis on device
BINARY_MAGIC = b"LIGHTGBM_TPU_DATASET_V1\n"


class Metadata:
    """Labels, weights, query boundaries, init scores
    (reference ``Metadata``, dataset.h:36-248, src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label):
        label = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            raise LightGBMError(
                f"label length {len(label)} != num_data {self.num_data}")
        self.label = label

    def set_weights(self, weights):
        if weights is None:
            self.weights = None
            return
        weights = np.ascontiguousarray(weights, dtype=np.float32).reshape(-1)
        if len(weights) != self.num_data:
            raise LightGBMError(
                f"weight length {len(weights)} != num_data {self.num_data}")
        self.weights = weights
        self._update_query_weights()

    def set_query(self, group):
        """``group`` is per-query sizes (LightGBM python convention) or
        boundaries if already cumulative starting at 0."""
        if group is None:
            self.query_boundaries = None
            self.query_weights = None
            return
        group = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        if len(group) > 0 and group[0] == 0:
            boundaries = group     # already boundaries
        else:
            boundaries = np.concatenate([[0], np.cumsum(group)])
        if boundaries[-1] != self.num_data:
            raise LightGBMError(
                f"sum of query counts {boundaries[-1]} != num_data {self.num_data}")
        self.query_boundaries = boundaries.astype(np.int64)
        self._update_query_weights()

    def _update_query_weights(self):
        # per-query weight = mean of row weights in query (reference
        # metadata.cpp query weight derivation)
        if self.weights is not None and self.query_boundaries is not None:
            nq = len(self.query_boundaries) - 1
            qw = np.zeros(nq, dtype=np.float32)
            for i in range(nq):
                lo, hi = self.query_boundaries[i], self.query_boundaries[i + 1]
                qw[i] = self.weights[lo:hi].mean() if hi > lo else 0.0
            self.query_weights = qw

    def set_init_score(self, init_score):
        if init_score is None:
            self.init_score = None
            return
        init_score = np.ascontiguousarray(init_score, dtype=np.float64)
        self.init_score = init_score.reshape(-1)

    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class FeatureGroupInfo:
    """Static description of one feature group (bundle)."""

    __slots__ = ("feature_indices", "bin_offsets", "num_total_bin")

    def __init__(self, feature_indices: List[int], bin_mappers: List[BinMapper]):
        self.feature_indices = list(feature_indices)
        # slot 0 reserved for "all defaults" (reference feature_group.h:33-45)
        self.bin_offsets = [1]
        total = 1
        for m in bin_mappers:
            nb = m.num_bin - (1 if m.default_bin == 0 else 0)
            total += nb
            self.bin_offsets.append(total)
        self.num_total_bin = total


class BinnedDataset:
    """Host-side binned dataset; the learner uploads `.binned` to HBM."""

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bin_mappers: List[Optional[BinMapper]] = []
        self.groups: List[FeatureGroupInfo] = []
        self.binned: Optional[np.ndarray] = None       # (N, G) uint8
        self.metadata: Optional[Metadata] = None
        self.feature_names: List[str] = []
        self.used_features: List[int] = []             # original idx, non-trivial
        # per-used-feature flattened lookups (device metadata)
        self.f_group: np.ndarray = np.empty(0, np.int32)
        self.f_offset: np.ndarray = np.empty(0, np.int32)
        self.f_num_bin: np.ndarray = np.empty(0, np.int32)
        self.f_default_bin: np.ndarray = np.empty(0, np.int32)
        self.f_missing_type: np.ndarray = np.empty(0, np.int32)  # 0/1/2 none/zero/nan
        self.f_is_categorical: np.ndarray = np.empty(0, np.int32)
        self.monotone_constraints: np.ndarray = np.empty(0, np.int32)
        self.feature_penalty: np.ndarray = np.empty(0, np.float64)
        self.reference: Optional["BinnedDataset"] = None
        self.device_binned: bool = False   # .binned lives on device (jnp)

    # -- accessors ---------------------------------------------------------
    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def num_features(self) -> int:
        return len(self.used_features)

    def group_bin_boundaries(self) -> np.ndarray:
        out = [0]
        for g in self.groups:
            out.append(out[-1] + g.num_total_bin)
        return np.asarray(out, dtype=np.int64)

    # -- construction ------------------------------------------------------
    @classmethod
    def construct_from_matrix(
            cls, data: np.ndarray, config: Config,
            categorical: Sequence[int] = (),
            feature_names: Optional[Sequence[str]] = None,
            reference: Optional["BinnedDataset"] = None,
            predefined_mappers: Optional[List[Optional[BinMapper]]] = None,
    ) -> "BinnedDataset":
        """Build from a dense float matrix (rows, features).

        ``reference`` given -> validation-style construction reusing its bin
        mappers and grouping (reference ``Dataset::CreateValid``,
        dataset.cpp:368).  ``predefined_mappers`` supports distributed
        find-bin where mappers were allgathered from other workers.
        """
        data = np.asarray(data)
        if data.ndim != 2:
            raise LightGBMError("data must be 2-dimensional")
        n, num_feat = data.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_feat
        ds.metadata = Metadata(n)
        if feature_names is None:
            ds.feature_names = [f"Column_{i}" for i in range(num_feat)]
        else:
            ds.feature_names = list(feature_names)

        if reference is not None:
            ds._align_with_reference(data, reference)
            return ds

        ds._find_bins(data, config, set(int(c) for c in categorical),
                      predefined_mappers)
        ds._bundle_features(data, config)
        ds._build_group_matrix(data)
        ds._build_feature_lookups(config)
        return ds

    # -- device-native construction ---------------------------------------
    @classmethod
    def construct_from_device_matrix(
            cls, data_dev, config: Config,
            feature_names: Optional[Sequence[str]] = None,
            reference: Optional["BinnedDataset"] = None,
    ) -> "BinnedDataset":
        """TPU-native construction: bin FINDING runs on a small host
        sample (GreedyFindBin is inherently sequential per feature), but
        the full (N, F) float32 matrix is binned ON DEVICE — the host
        never touches the bulk data.  This keeps dataset construction
        off the host CPU (a loaded driver host measured 25 s host
        binning for HIGGS; the device path is milliseconds of VPU work)
        and pairs with on-device data generation so the bulk matrix
        never crosses the host<->device link at all.

        Exactness: bin boundaries are float64 midpoints; comparing the
        float32 inputs against boundaries rounded DOWN to float32
        reproduces the host's ``v <= bound64`` decisions bit-for-bit
        for float32 data (v <= b64  <=>  v <= round_down32(b64)).

        Numerical features only (the categorical LUT stays host-side);
        ``reference`` adopts a training set's mappers (CreateValid).
        """
        import jax.numpy as jnp
        n, num_feat = (int(s) for s in data_dev.shape)
        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_feat
        ds.metadata = Metadata(n)
        ds.feature_names = ([f"Column_{i}" for i in range(num_feat)]
                            if feature_names is None
                            else list(feature_names))
        if reference is not None:
            if num_feat != reference.num_total_features:
                raise LightGBMError(
                    f"validation data has {num_feat} features, train has "
                    f"{reference.num_total_features}")
            ds._align_with_reference_shared(reference)
        else:
            sample_cnt = min(n, int(config.bin_construct_sample_cnt))
            rng = make_rng(config.data_random_seed)
            idx = (np.sort(rng.choice(n, size=sample_cnt, replace=False))
                   if sample_cnt < n else np.arange(n))
            sample = np.asarray(
                jnp.take(data_dev, jnp.asarray(idx), axis=0), np.float64)
            ds._find_bins(sample, config, set(), None, presampled=True)
            ds._bundle_features(sample, config)
            ds._build_feature_lookups(config)
        if any(m.bin_type == BIN_CATEGORICAL for m in ds.bin_mappers
               if m is not None):
            raise LightGBMError(
                "construct_from_device_matrix supports numerical "
                "features only; use construct_from_matrix")
        ds.binned = ds._bin_on_device(data_dev)
        ds.device_binned = True
        return ds

    def _bin_on_device(self, data_dev):
        """(N, F) f32 device matrix -> (N, G) uint8 device matrix using
        the host-found bin mappers; bundle conflicts resolve by feature
        order (last writer wins), matching _build_group_matrix."""
        import jax
        import jax.numpy as jnp
        specs = []
        for group in self.groups:
            fspecs = []
            for sub, f in enumerate(group.feature_indices):
                m = self.bin_mappers[f]
                n_search = m.num_bin - (1 if m.missing_type == "nan"
                                        else 0)
                b64 = np.asarray(m.bin_upper_bound[:n_search - 1],
                                 np.float64)
                b32 = b64.astype(np.float32)
                over = b32.astype(np.float64) > b64
                b32[over] = np.nextafter(b32[over],
                                         np.float32(-np.inf))
                shift = 1 if m.default_bin == 0 else 0
                fspecs.append((f, b32, int(m.num_bin),
                               int(m.default_bin), m.missing_type,
                               int(group.bin_offsets[sub]), shift))
            specs.append(fspecs)

        @jax.jit
        def build(x):
            cols = []
            for fspecs in specs:
                col = jnp.zeros(x.shape[0], jnp.int32)
                for (f, b32, num_bin, default_bin, mt, off,
                     shift) in fspecs:
                    v = x[:, f]
                    nanm = jnp.isnan(v)
                    filled = jnp.where(nanm, jnp.float32(0.0), v)
                    b = jnp.searchsorted(jnp.asarray(b32), filled,
                                         side="left").astype(jnp.int32)
                    if mt == "nan":
                        b = jnp.where(nanm, num_bin - 1, b)
                    col = jnp.where(b != default_bin, b + off - shift,
                                    col)
                cols.append(col)
            return jnp.stack(cols, axis=1).astype(jnp.uint8)

        build = obs.track_jit("dataset.build_binned", build)
        return build(data_dev)

    # -- CSR-native construction ------------------------------------------
    @classmethod
    def construct_from_csr(
            cls, indptr, indices, values, num_col: int, config: Config,
            categorical: Sequence[int] = (),
            feature_names: Optional[Sequence[str]] = None,
            reference: Optional["BinnedDataset"] = None,
    ) -> "BinnedDataset":
        """Bin directly from CSR triplets without densifying.

        Host memory stays proportional to nnz plus the final (N, G) uint8
        binned matrix — the dense float64 matrix is never materialised.
        This is the analog of the reference's
        ``LGBM_DatasetCreateFromCSR`` (``src/c_api.cpp``, ``c_api.h:50-234``)
        and serves the fork harness's retrain-every-window workload
        (``src/test.cpp:243-298``).
        """
        indptr = np.asarray(indptr, np.int64)
        indices = np.asarray(indices, np.int64)
        values = np.asarray(values, np.float64)
        n = len(indptr) - 1
        num_col = int(num_col)
        ds = cls()
        ds.num_data = n
        ds.num_total_features = num_col
        ds.metadata = Metadata(n)
        ds.feature_names = ([f"Column_{i}" for i in range(num_col)]
                            if feature_names is None else list(feature_names))

        # column-major view of the nonzeros (one stable sort, O(nnz))
        row_ids = np.repeat(np.arange(n, dtype=np.int64),
                            np.diff(indptr))
        order = np.argsort(indices, kind="stable")
        col_sorted = indices[order]
        rows_by_col = row_ids[order]
        vals_by_col = values[order]
        col_bounds = np.searchsorted(col_sorted,
                                     np.arange(num_col + 1, dtype=np.int64))

        if reference is not None:
            if num_col != reference.num_total_features:
                raise LightGBMError(
                    f"validation data has {num_col} features, train has "
                    f"{reference.num_total_features}")
            ds._align_with_reference_shared(reference)
            ds._build_group_matrix_csr(col_bounds, rows_by_col, vals_by_col)
            return ds

        # stage 1: sampled bin finding per feature (recorded = nonzero/NaN
        # values of sampled rows; zeros implicit - the same contract as the
        # reference's sparse sampling, dataset_loader.cpp:161-264)
        sample_cnt = min(n, int(config.bin_construct_sample_cnt))
        rng = make_rng(config.data_random_seed)
        if sample_cnt < n:
            sample_idx = np.sort(rng.choice(n, size=sample_cnt,
                                            replace=False))
        else:
            sample_idx = np.arange(n)
        in_sample = np.zeros(n, bool)
        in_sample[sample_idx] = True
        sample_pos = np.full(n, -1, np.int64)
        sample_pos[sample_idx] = np.arange(sample_cnt)

        filter_cnt = int(0.95 * config.min_data_in_leaf / max(n, 1)
                         * sample_cnt)
        cat = set(int(c) for c in categorical)
        ds.bin_mappers = []
        nz_masks: Dict[int, np.ndarray] = {}
        nz_counts: Dict[int, int] = {}
        for f in range(num_col):
            s, e = col_bounds[f], col_bounds[f + 1]
            rs = rows_by_col[s:e]
            vs = vals_by_col[s:e]
            keep = in_sample[rs]
            vs_s = vs[keep]
            rec_mask = (vs_s != 0.0) | np.isnan(vs_s)
            recorded = vs_s[rec_mask]
            m = BinMapper()
            m.find_bin(recorded, sample_cnt, config.max_bin,
                       config.min_data_in_bin, filter_cnt,
                       BIN_CATEGORICAL if f in cat else BIN_NUMERICAL,
                       config.use_missing, config.zero_as_missing)
            ds.bin_mappers.append(m)
            mask = np.zeros(sample_cnt, bool)
            mask[sample_pos[rs[keep][rec_mask]]] = True
            nz_masks[f] = mask
            nz_counts[f] = int(mask.sum())
        ds.used_features = [f for f in range(num_col)
                            if not ds.bin_mappers[f].is_trivial]
        if not ds.used_features:
            log_warning("There are no meaningful features, as all feature "
                        "values are constant.")

        # stage 2: EFB bundling on the sampled masks
        if not ds.used_features:
            ds.groups = []
        elif not config.enable_bundle or len(ds.used_features) == 1:
            ds._set_groups([[f] for f in ds.used_features])
        else:
            ds._set_groups(ds._bundle_from_masks(config, nz_masks,
                                                 nz_counts, sample_cnt))

        ds._build_group_matrix_csr(col_bounds, rows_by_col, vals_by_col)
        ds._build_feature_lookups(config)
        return ds

    # -- streaming (two-round) construction --------------------------------
    @classmethod
    def construct_streaming_begin(
            cls, sample: np.ndarray, n_total: int, num_cols: int, config,
            categorical: Sequence[int] = (),
            feature_names: Optional[Sequence[str]] = None,
            reference: Optional["BinnedDataset"] = None,
    ) -> "BinnedDataset":
        """Start a two-round streaming construction: bins and bundles are
        found from ``sample`` (a ``bin_construct_sample_cnt``-row matrix)
        scaled to ``n_total`` rows, the ``(N, G)`` uint8 matrix is
        preallocated, and chunks arrive via
        :meth:`construct_streaming_push` (reference
        ``dataset_loader.cpp:161-264`` two-round load)."""
        ds = cls()
        ds.num_data = int(n_total)
        ds.num_total_features = int(num_cols)
        ds.metadata = Metadata(ds.num_data)
        ds.feature_names = ([f"Column_{i}" for i in range(num_cols)]
                            if feature_names is None
                            else list(feature_names))
        if reference is not None:
            if num_cols != reference.num_total_features:
                raise LightGBMError(
                    f"data has {num_cols} features, reference has "
                    f"{reference.num_total_features}")
            ds._align_with_reference_shared(reference)
            ds.binned = np.zeros((ds.num_data, len(ds.groups)), np.uint8)
            return ds

        sample = np.asarray(sample, np.float64)
        sample_cnt = sample.shape[0]
        # filter count scaled to the sample (dataset_loader.cpp:787)
        filter_cnt = int(0.95 * config.min_data_in_leaf
                         / max(n_total, 1) * sample_cnt)
        cat = set(int(c) for c in categorical)
        ds.bin_mappers = []
        nz_masks = {}
        nz_counts = {}
        for f in range(num_cols):
            col = sample[:, f]
            mask = (col != 0.0) | np.isnan(col)
            recorded = col[mask]
            m = BinMapper()
            m.find_bin(recorded, sample_cnt, config.max_bin,
                       config.min_data_in_bin, filter_cnt,
                       BIN_CATEGORICAL if f in cat else BIN_NUMERICAL,
                       config.use_missing, config.zero_as_missing)
            ds.bin_mappers.append(m)
            nz_masks[f] = mask
            nz_counts[f] = int(mask.sum())
        ds.used_features = [f for f in range(num_cols)
                            if not ds.bin_mappers[f].is_trivial]
        if not ds.used_features:
            log_warning("There are no meaningful features, as all feature "
                        "values are constant.")
            ds.groups = []
        elif not config.enable_bundle or len(ds.used_features) == 1:
            ds._set_groups([[f] for f in ds.used_features])
        else:
            ds._set_groups(ds._bundle_from_masks(config, nz_masks,
                                                 nz_counts, sample_cnt))
        ds._build_feature_lookups(config)
        ds.binned = np.zeros((ds.num_data, len(ds.groups)), np.uint8)
        return ds

    def construct_streaming_push(self, chunk: np.ndarray,
                                 start_row: int) -> None:
        """Bin ``chunk`` rows into ``binned[start_row:...]`` (the analog
        of ``Dataset::PushOneRow``, dataset.h:318-341, chunk-vectorized).
        """
        chunk = np.asarray(chunk, np.float64)
        end = start_row + chunk.shape[0]
        if end > self.num_data:
            raise LightGBMError("streaming push beyond declared num_data")
        out = self.binned[start_row:end]
        for gid, group in enumerate(self.groups):
            col_out = out[:, gid]
            for sub, f in enumerate(group.feature_indices):
                m = self.bin_mappers[f]
                bins = m.values_to_bins(chunk[:, f])
                offset = group.bin_offsets[sub]
                slot = bins + offset - (1 if m.default_bin == 0 else 0)
                non_default = bins != m.default_bin
                col_out[non_default] = slot[non_default].astype(np.uint8)

    def construct_streaming_finish(self) -> None:
        """End of the stream (placeholder for integrity checks)."""

    def _set_groups(self, feature_groups) -> None:
        self.groups = [FeatureGroupInfo(g, [self.bin_mappers[f] for f in g])
                       for g in feature_groups]
        for g in self.groups:
            if g.num_total_bin > MAX_GROUP_BIN:
                raise LightGBMError(
                    f"feature group exceeds {MAX_GROUP_BIN} bins; "
                    f"reduce max_bin (got {g.num_total_bin})")

    def _align_with_reference_shared(self, reference) -> None:
        """Adopt the training set's mappers/grouping (CreateValid)."""
        self.reference = reference
        self.bin_mappers = reference.bin_mappers
        self.groups = reference.groups
        self.used_features = reference.used_features
        self.f_group = reference.f_group
        self.f_offset = reference.f_offset
        self.f_num_bin = reference.f_num_bin
        self.f_default_bin = reference.f_default_bin
        self.f_missing_type = reference.f_missing_type
        self.f_is_categorical = reference.f_is_categorical
        self.monotone_constraints = reference.monotone_constraints
        self.feature_penalty = reference.feature_penalty
        self.feature_names = reference.feature_names

    def _build_group_matrix_csr(self, col_bounds, rows_by_col,
                                vals_by_col) -> None:
        """(N, G) uint8 matrix straight from column-sorted nonzeros: rows
        not recorded for a feature stay at the group default slot 0,
        exactly like the dense path's non_default masking."""
        n = self.num_data
        binned = np.zeros((n, len(self.groups)), dtype=np.uint8)
        for gid, group in enumerate(self.groups):
            col_out = binned[:, gid]
            for sub, f in enumerate(group.feature_indices):
                m = self.bin_mappers[f]
                s, e = col_bounds[f], col_bounds[f + 1]
                bins = m.values_to_bins(vals_by_col[s:e])
                offset = group.bin_offsets[sub]
                slot = bins + offset - (1 if m.default_bin == 0 else 0)
                non_default = bins != m.default_bin
                col_out[rows_by_col[s:e][non_default]] = \
                    slot[non_default].astype(np.uint8)
        self.binned = binned

    # -- stage 1: bin mappers ---------------------------------------------
    def _find_bins(self, data: np.ndarray, config: Config,
                   categorical: set, predefined,
                   presampled: bool = False) -> None:
        n = self.num_data
        if presampled:
            # data IS the sample (device construction pulls it to host
            # before calling); filter_cnt still scales by the true n
            sample_cnt = len(data)
            sample_idx = np.arange(sample_cnt)
        else:
            sample_cnt = min(n, int(config.bin_construct_sample_cnt))
            rng = make_rng(config.data_random_seed)
            if sample_cnt < n:
                sample_idx = np.sort(rng.choice(n, size=sample_cnt,
                                                replace=False))
            else:
                sample_idx = np.arange(n)
        self._sample_idx = sample_idx
        sampled = np.asarray(data[sample_idx], dtype=np.float64)

        # filter count mirrors dataset_loader.cpp:787 scaling to the sample
        filter_cnt = int(0.95 * config.min_data_in_leaf / max(n, 1) * sample_cnt)
        self.bin_mappers = []
        for f in range(self.num_total_features):
            if predefined is not None and predefined[f] is not None:
                self.bin_mappers.append(predefined[f])
                continue
            col = sampled[:, f]
            bin_type = BIN_CATEGORICAL if f in categorical else BIN_NUMERICAL
            m = BinMapper()
            # recorded values contract: pass non-zero entries + NaNs, zeros
            # are implicit (matches the sparse sampling path of the loader)
            recorded = col[(col != 0.0) | np.isnan(col)]
            m.find_bin(recorded, sample_cnt, config.max_bin,
                       config.min_data_in_bin, filter_cnt, bin_type,
                       config.use_missing, config.zero_as_missing)
            self.bin_mappers.append(m)
        self.used_features = [f for f in range(self.num_total_features)
                              if not self.bin_mappers[f].is_trivial]
        if not self.used_features:
            log_warning("There are no meaningful features, as all feature "
                        "values are constant.")

    # -- stage 2: EFB bundling --------------------------------------------
    def _bundle_features(self, data: np.ndarray, config: Config) -> None:
        used = self.used_features
        if not used:
            self.groups = []
            return
        if not config.enable_bundle or len(used) == 1:
            feature_groups = [[f] for f in used]
        else:
            feature_groups = self._fast_feature_bundling(data, config)
        self.groups = [FeatureGroupInfo(g, [self.bin_mappers[f] for f in g])
                       for g in feature_groups]
        for g in self.groups:
            if g.num_total_bin > MAX_GROUP_BIN:
                raise LightGBMError(
                    f"feature group exceeds {MAX_GROUP_BIN} bins; "
                    f"reduce max_bin (got {g.num_total_bin})")

    def _fast_feature_bundling(self, data: np.ndarray, config: Config):
        """Greedy conflict-bounded bundling (reference dataset.cpp:66-210).

        Tries two orderings (original and by descending non-zero count),
        keeps whichever yields fewer groups, then breaks small sparse groups
        back apart.  Groups are capped at 256 total bins like the GPU path.
        """
        sample_idx = getattr(self, "_sample_idx", np.arange(self.num_data))
        sampled = np.asarray(data[sample_idx], dtype=np.float64)
        total_sample = len(sample_idx)
        # per-feature recorded(sample-row) masks
        nz_masks = {}
        nz_counts = {}
        for f in self.used_features:
            col = sampled[:, f]
            mask = (col != 0.0) | np.isnan(col)
            nz_masks[f] = mask
            nz_counts[f] = int(mask.sum())
        return self._bundle_from_masks(config, nz_masks, nz_counts,
                                       total_sample)

    def _bundle_from_masks(self, config: Config, nz_masks, nz_counts,
                           total_sample: int):
        """The greedy conflict-bounded grouping over sampled
        recorded-row masks (shared by the dense and CSR paths)."""
        used = self.used_features
        max_error_cnt = int(total_sample * config.max_conflict_rate)
        filter_cnt = int(0.95 * config.min_data_in_leaf
                         / max(self.num_data, 1) * total_sample)

        def extra_bins(f):
            m = self.bin_mappers[f]
            return m.num_bin - (1 if m.default_bin == 0 else 0)

        def find_groups(order):
            groups: List[List[int]] = []
            marks: List[np.ndarray] = []
            conflict_cnt: List[int] = []
            non_zero_cnt: List[int] = []
            num_bin: List[int] = []
            for f in order:
                cur_nz = nz_counts[f]
                placed = False
                for gid in range(len(groups)):
                    if non_zero_cnt[gid] + cur_nz > total_sample + max_error_cnt:
                        continue
                    if num_bin[gid] + extra_bins(f) > MAX_GROUP_BIN:
                        continue
                    rest_max = max_error_cnt - conflict_cnt[gid]
                    cnt = int((marks[gid] & nz_masks[f]).sum())
                    if cnt <= rest_max:
                        rest_nz = int((cur_nz - cnt) * self.num_data
                                      / max(total_sample, 1))
                        if rest_nz < filter_cnt:
                            continue
                        groups[gid].append(f)
                        conflict_cnt[gid] += cnt
                        non_zero_cnt[gid] += cur_nz - cnt
                        marks[gid] |= nz_masks[f]
                        num_bin[gid] += extra_bins(f)
                        placed = True
                        break
                if not placed:
                    groups.append([f])
                    marks.append(nz_masks[f].copy())
                    conflict_cnt.append(0)
                    non_zero_cnt.append(cur_nz)
                    num_bin.append(1 + extra_bins(f))
            return groups

        order1 = list(used)
        order2 = sorted(used, key=lambda f: -nz_counts[f])
        g1 = find_groups(order1)
        g2 = find_groups(order2)
        groups = g2 if len(g2) < len(g1) else g1

        # take small sparse groups apart (dataset.cpp:185-205)
        out: List[List[int]] = []
        for g in groups:
            if len(g) <= 1 or len(g) >= 5:
                out.append(g)
                continue
            cnt_nz = sum(int(self.num_data * (1.0 - self.bin_mappers[f].sparse_rate))
                         for f in g)
            sparse_rate = 1.0 - cnt_nz / max(self.num_data, 1)
            if sparse_rate >= config.sparse_threshold and config.is_enable_sparse:
                out.extend([[f] for f in g])
            else:
                out.append(g)
        return out

    # -- stage 3: binned group matrix -------------------------------------
    def _build_group_matrix(self, data: np.ndarray) -> None:
        n = self.num_data
        g_count = len(self.groups)
        binned = np.zeros((n, g_count), dtype=np.uint8)
        for gid, group in enumerate(self.groups):
            col_out = binned[:, gid]
            for sub, f in enumerate(group.feature_indices):
                m = self.bin_mappers[f]
                bins = m.values_to_bins(np.asarray(data[:, f], dtype=np.float64))
                offset = group.bin_offsets[sub]
                slot = bins + offset - (1 if m.default_bin == 0 else 0)
                non_default = bins != m.default_bin
                # later features of a bundle overwrite on (rare) conflicts,
                # same as the reference's push order
                col_out[non_default] = slot[non_default].astype(np.uint8)
        self.binned = binned

    # -- stage 4: per-feature device lookups ------------------------------
    def _build_feature_lookups(self, config: Optional[Config]) -> None:
        nf = len(self.used_features)
        self.f_group = np.zeros(nf, np.int32)
        self.f_offset = np.zeros(nf, np.int32)
        self.f_num_bin = np.zeros(nf, np.int32)
        self.f_default_bin = np.zeros(nf, np.int32)
        self.f_missing_type = np.zeros(nf, np.int32)
        self.f_is_categorical = np.zeros(nf, np.int32)
        pos = {}
        for i, f in enumerate(self.used_features):
            pos[f] = i
        for gid, group in enumerate(self.groups):
            for sub, f in enumerate(group.feature_indices):
                i = pos[f]
                m = self.bin_mappers[f]
                self.f_group[i] = gid
                self.f_offset[i] = group.bin_offsets[sub]
                self.f_num_bin[i] = m.num_bin
                self.f_default_bin[i] = m.default_bin
                self.f_missing_type[i] = {"none": 0, "zero": 1, "nan": 2}[m.missing_type]
                self.f_is_categorical[i] = 1 if m.bin_type == BIN_CATEGORICAL else 0

        mono = np.zeros(nf, np.int32)
        pen = np.ones(nf, np.float64)
        if config is not None:
            mc = list(config.monotone_constraints or [])
            fp = list(config.feature_contri or [])
            for i, f in enumerate(self.used_features):
                if f < len(mc):
                    mono[i] = int(mc[f])
                if f < len(fp):
                    pen[i] = float(fp[f])
        self.monotone_constraints = mono
        self.feature_penalty = pen

    # -- validation alignment ---------------------------------------------
    def _align_with_reference(self, data: np.ndarray,
                              reference: "BinnedDataset") -> None:
        if data.shape[1] != reference.num_total_features:
            raise LightGBMError(
                f"validation data has {data.shape[1]} features, train has "
                f"{reference.num_total_features}")
        self._align_with_reference_shared(reference)
        self._build_group_matrix(np.asarray(data))

    def check_align(self, other: "BinnedDataset") -> bool:
        """Reference ``Dataset::CheckAlign`` (dataset.h:300-316)."""
        return (self.num_total_features == other.num_total_features
                and self.num_groups == other.num_groups
                and all(a.num_total_bin == b.num_total_bin
                        for a, b in zip(self.groups, other.groups)))

    # -- subset for bagging ------------------------------------------------
    def copy_subset(self, indices: np.ndarray) -> "BinnedDataset":
        """Row-subset copy (reference ``Dataset::CopySubset``, dataset.cpp:436)."""
        sub = BinnedDataset()
        sub.num_data = len(indices)
        sub.num_total_features = self.num_total_features
        sub.bin_mappers = self.bin_mappers
        sub.groups = self.groups
        sub.used_features = self.used_features
        sub.f_group = self.f_group
        sub.f_offset = self.f_offset
        sub.f_num_bin = self.f_num_bin
        sub.f_default_bin = self.f_default_bin
        sub.f_missing_type = self.f_missing_type
        sub.f_is_categorical = self.f_is_categorical
        sub.monotone_constraints = self.monotone_constraints
        sub.feature_penalty = self.feature_penalty
        sub.feature_names = self.feature_names
        sub.binned = self.binned[indices]
        md = Metadata(sub.num_data)
        old = self.metadata
        if old is not None:
            if old.label is not None:
                md.label = old.label[indices]
            if old.weights is not None:
                md.weights = old.weights[indices]
            if old.init_score is not None:
                ns = len(old.init_score) // max(old.num_data, 1)
                md.init_score = old.init_score.reshape(ns, -1)[:, indices].reshape(-1) \
                    if ns > 1 else old.init_score[indices]
        sub.metadata = md
        return sub

    # -- binary cache ------------------------------------------------------
    def save_binary(self, path: str) -> None:
        """Dataset binary cache (reference ``SaveBinaryFile``, dataset.cpp:542)."""
        state = {
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "feature_names": self.feature_names,
            "used_features": self.used_features,
            "mappers": [m.to_state() if m else None for m in self.bin_mappers],
            "groups": [g.feature_indices for g in self.groups],
            "binned": self.binned,
            "label": None if self.metadata is None else self.metadata.label,
            "weights": None if self.metadata is None else self.metadata.weights,
            "query_boundaries": (None if self.metadata is None
                                 else self.metadata.query_boundaries),
            "init_score": None if self.metadata is None else self.metadata.init_score,
            "monotone": self.monotone_constraints,
            "penalty": self.feature_penalty,
        }
        with open(path, "wb") as fh:
            fh.write(BINARY_MAGIC)
            pickle.dump(state, fh, protocol=4)
        log_info(f"Saved binary dataset to {path}")

    @classmethod
    def is_binary_file(cls, path: str) -> bool:
        try:
            with open(path, "rb") as fh:
                return fh.read(len(BINARY_MAGIC)) == BINARY_MAGIC
        except OSError:
            return False

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        with open(path, "rb") as fh:
            if fh.read(len(BINARY_MAGIC)) != BINARY_MAGIC:
                raise LightGBMError(f"{path} is not a lightgbm_tpu binary dataset")
            state = pickle.load(fh)
        ds = cls()
        ds.num_data = state["num_data"]
        ds.num_total_features = state["num_total_features"]
        ds.feature_names = state["feature_names"]
        ds.used_features = state["used_features"]
        ds.bin_mappers = [BinMapper.from_state(s) if s else None
                          for s in state["mappers"]]
        ds.groups = [FeatureGroupInfo(g, [ds.bin_mappers[f] for f in g])
                     for g in state["groups"]]
        ds.binned = state["binned"]
        ds.metadata = Metadata(ds.num_data)
        if state["label"] is not None:
            ds.metadata.label = state["label"]
        ds.metadata.weights = state["weights"]
        ds.metadata.query_boundaries = state["query_boundaries"]
        ds.metadata.init_score = state["init_score"]
        ds.metadata._update_query_weights()
        ds._build_feature_lookups(None)
        ds.monotone_constraints = state["monotone"]
        ds.feature_penalty = state["penalty"]
        return ds
