"""BinMapper: per-feature value -> bin mapping.

Re-implements the behaviour of the reference ``BinMapper``
(``src/io/bin.cpp:74-402``, ``include/LightGBM/bin.h:452-488``) in
numpy/python: greedy equal-count binning over sampled distinct values with the
zero bin treated specially, count-sorted categorical bins, and the three
missing-value modes (None / Zero / NaN — NaN always maps to the last bin).
The algorithm and edge-case semantics match the reference so that bin
boundaries — and therefore trees and metrics — are comparable; the code is
written fresh for a dense TPU-resident representation (no sparse/default-bin
skipping: the TPU build keeps full dense histograms, so the reference's
``FixHistogram`` reconstruction is unnecessary).
"""

from __future__ import annotations

import math
from typing import List, Sequence

import numpy as np

# values with |v| <= kZeroThreshold are "zero".  The reference writes the
# literal as 1e-35f (meta.h:40) — a float32 constant promoted to double —
# so the working threshold is float32(1e-35), not double 1e-35; matching
# it exactly keeps the -kZeroThreshold/+kZeroThreshold bin bounds
# bit-identical (tests/test_parity.py)
K_ZERO_THRESHOLD = float(np.float32(1e-35))

MISSING_NONE = "none"
MISSING_ZERO = "zero"
MISSING_NAN = "nan"

BIN_NUMERICAL = "numerical"
BIN_CATEGORICAL = "categorical"


def _double_upper_bound(v: float) -> float:
    """Next representable double above v (reference Common::GetDoubleUpperBound)."""
    return float(np.nextafter(np.float64(v), np.float64(np.inf)))


def _feq(a: float, b: float) -> bool:
    """Ordered approximate-equality used when merging near-identical doubles
    (reference Common::CheckDoubleEqualOrdered)."""
    upper = float(np.nextafter(np.float64(a), np.float64(np.inf)))
    return a <= b <= upper


def _greedy_find_bin_scalar(distinct_values: np.ndarray, counts: np.ndarray,
                            max_bin: int, total_cnt: int,
                            min_data_in_bin: int) -> List[float]:
    """Reference-shaped scalar implementation of GreedyFindBin
    (bin.cpp:74-150); kept as the semantics oracle for the vectorized
    version below (tests fuzz one against the other)."""
    num_distinct = len(distinct_values)
    bounds: List[float] = []
    if max_bin <= 0:
        raise ValueError("max_bin must be positive")
    if num_distinct == 0:
        return [math.inf]
    if num_distinct <= max_bin:
        cur = 0
        for i in range(num_distinct - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                val = _double_upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _feq(bounds[-1], val):
                    bounds.append(val)
                    cur = 0
        bounds.append(math.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper = []
    lower = [float(distinct_values[0])]
    cur = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur += int(counts[i])
        if (is_big[i] or cur >= mean_bin_size
                or (is_big[i + 1] and cur >= max(1.0, mean_bin_size * 0.5))):
            upper.append(float(distinct_values[i]))
            lower.append(float(distinct_values[i + 1]))
            if len(upper) >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    for i in range(len(upper)):
        val = _double_upper_bound((upper[i] + lower[i + 1]) / 2.0)
        if not bounds or not _feq(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int,
                     min_data_in_bin: int) -> List[float]:
    """Greedy equal-count binning (reference GreedyFindBin, bin.cpp:74-150).

    Vectorized: instead of walking every distinct value, each emitted
    boundary is located with O(log n) searches (cumulative-count
    searchsorted + next-big-bin lookup), so the cost is O(max_bin log n)
    rather than O(n) Python iterations.  Bit-identical to the scalar
    oracle above (fuzz-tested)."""
    num_distinct = len(distinct_values)
    if max_bin <= 0:
        raise ValueError("max_bin must be positive")
    if num_distinct == 0:
        return [math.inf]
    bounds: List[float] = []
    if num_distinct <= max_bin:
        # small case: emit a boundary whenever >= min_data_in_bin rows
        # accumulated; the scalar loop is already O(max_bin)
        return _greedy_find_bin_scalar(distinct_values, counts, max_bin,
                                       total_cnt, min_data_in_bin)

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    counts = np.asarray(counts, np.int64)
    mean0 = total_cnt / max_bin
    is_big = counts >= mean0
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    cum = np.cumsum(counts)                       # inclusive prefix counts
    cum_nb = np.cumsum(np.where(is_big, 0, counts))  # non-big prefix
    big_idx = np.nonzero(is_big)[0]

    upper: List[float] = []
    lower: List[float] = [float(distinct_values[0])]
    i0 = 0                                        # first index of open bin
    limit = num_distinct - 1                      # scalar loop scans [0, n-2]
    while len(upper) < max_bin - 1:
        base = cum[i0 - 1] if i0 > 0 else 0
        # condition A: is_big[i]
        j = np.searchsorted(big_idx, i0)
        i_a = int(big_idx[j]) if j < len(big_idx) else limit
        # condition B: cur = cum[i] - base >= mean_bin_size (clamped to the
        # open segment: mean can hit 0 at the tail, where the scalar loop
        # still fires no earlier than the running index)
        i_b = max(int(np.searchsorted(cum, base + mean_bin_size)), i0)
        # condition C: is_big[i+1] and cur >= max(1, mean/2)
        i_half = int(np.searchsorted(cum, base + max(1.0,
                                                     mean_bin_size * 0.5)))
        jj = np.searchsorted(big_idx, max(i0, i_half) + 1)
        i_c = int(big_idx[jj]) - 1 if jj < len(big_idx) else limit
        i = min(i_a, i_b, i_c)
        if i >= limit:        # no boundary fires within the scanned range
            break
        upper.append(float(distinct_values[i]))
        lower.append(float(distinct_values[i + 1]))
        if len(upper) >= max_bin - 1:
            break
        # rest_sample_cnt drops by all non-big counts consumed so far
        if not is_big[i]:
            nb_consumed = int(cum_nb[i])
            rest_bin_cnt -= 1
            mean_bin_size = (rest_sample_cnt - nb_consumed) \
                / max(rest_bin_cnt, 1)
        i0 = i + 1
    for i in range(len(upper)):
        val = _double_upper_bound((upper[i] + lower[i + 1]) / 2.0)
        if not bounds or not _feq(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def _find_bin_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                              max_bin: int, total_sample_cnt: int,
                              min_data_in_bin: int) -> List[float]:
    """Bin negative and positive halves separately with a dedicated zero bin
    (reference FindBinWithZeroAsOneBin, bin.cpp:152-206)."""
    neg_mask = distinct_values <= -K_ZERO_THRESHOLD
    pos_mask = distinct_values > K_ZERO_THRESHOLD
    zero_mask = ~neg_mask & ~pos_mask
    left_cnt_data = int(counts[neg_mask].sum())
    cnt_zero = int(counts[zero_mask].sum())
    right_cnt_data = int(counts[pos_mask].sum())

    left_idx = np.nonzero(~neg_mask)[0]
    left_cnt = int(left_idx[0]) if len(left_idx) else len(distinct_values)

    bounds: List[float] = []
    if left_cnt > 0:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bounds = _greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                  left_max_bin, left_cnt_data, min_data_in_bin)
        bounds[-1] = -K_ZERO_THRESHOLD

    right_idx = np.nonzero(pos_mask[left_cnt:])[0]
    if len(right_idx):
        right_start = left_cnt + int(right_idx[0])
        right_max_bin = max_bin - 1 - len(bounds)
        if right_max_bin <= 0:
            raise ValueError("max_bin too small for zero-as-one-bin split")
        right_bounds = _greedy_find_bin(distinct_values[right_start:],
                                        counts[right_start:], right_max_bin,
                                        right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right_bounds)
    else:
        bounds.append(math.inf)
    return bounds


def _need_filter(cnt_in_bin: Sequence[int], total_cnt: int, filter_cnt: int,
                 bin_type: str) -> bool:
    """True if no split of this feature can satisfy min_data constraints
    (reference NeedFilter, bin.cpp:50-72)."""
    if bin_type == BIN_NUMERICAL:
        s = 0
        for c in cnt_in_bin[:-1]:
            s += c
            if s >= filter_cnt and total_cnt - s >= filter_cnt:
                return False
        return True
    if len(cnt_in_bin) <= 2:
        for c in cnt_in_bin[:-1]:
            if c >= filter_cnt and total_cnt - c >= filter_cnt:
                return False
        return True
    return False


class BinMapper:
    """Per-feature value->bin mapping, serializable for distributed find-bin."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: str = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: str = BIN_NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([math.inf])
        self.bin_2_categorical: np.ndarray = np.empty(0, dtype=np.int64)
        self.categorical_2_bin: dict = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int, min_split_data: int,
                 bin_type: str = BIN_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> "BinMapper":
        """Construct the mapping from sampled values of one feature.

        ``values`` are the sampled *recorded* values; ``total_sample_cnt`` is
        the number of sampled rows (unrecorded rows are implicit zeros), the
        same contract as reference ``BinMapper::FindBin`` (bin.cpp:208-402).
        """
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]
        num_sample_values = len(values)

        if not use_missing:
            self.missing_type = MISSING_NONE
            na_cnt = 0
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - num_sample_values - na_cnt)
        if zero_cnt < 0:
            zero_cnt = 0

        # distinct values with counts; merge near-equal doubles (pairwise
        # CheckDoubleEqualOrdered on consecutive sorted samples, as the
        # reference does), fold the implicit zeros in at their sorted
        # position.  Vectorized: group boundaries are where the next value
        # exceeds nextafter(prev); the group's representative is its LAST
        # member (the scalar loop kept overwriting with ``cur``).
        values.sort(kind="stable")
        if num_sample_values > 0:
            same = values[1:] <= np.nextafter(values[:-1], np.inf)
            starts = np.concatenate([[0], np.nonzero(~same)[0] + 1])
            ends = np.concatenate([starts[1:], [num_sample_values]])
            dv = values[ends - 1]
            cv = (ends - starts).astype(np.int64)
            # zero-group insertion exactly where the scalar loop put it:
            # between a group ending < 0 and the next starting > 0 (note:
            # the scalar test uses the RAW neighbours values[i-1], values[i]
            # of the group boundary, which are the group's last/next-first)
            prevs = values[starts[1:] - 1]
            curs = values[starts[1:]]
            zpos = np.nonzero((prevs < 0.0) & (curs > 0.0))[0]
            if len(zpos):
                at = int(zpos[0]) + 1
                dv = np.insert(dv, at, 0.0)
                cv = np.insert(cv, at, zero_cnt)
            elif values[0] > 0.0 and zero_cnt > 0:
                dv = np.concatenate([[0.0], dv])
                cv = np.concatenate([[zero_cnt], cv])
            elif values[-1] < 0.0 and zero_cnt > 0:
                dv = np.concatenate([dv, [0.0]])
                cv = np.concatenate([cv, [zero_cnt]])
        else:
            dv = np.asarray([0.0])
            cv = np.asarray([zero_cnt], dtype=np.int64)

        if len(dv) == 0:
            dv = np.asarray([0.0])
            cv = np.asarray([max(total_sample_cnt - na_cnt, 0)],
                            dtype=np.int64)
        self.min_val = float(dv[0])
        self.max_val = float(dv[-1])

        cnt_in_bin: List[int] = []
        if bin_type == BIN_NUMERICAL:
            if self.missing_type == MISSING_NAN:
                bounds = _find_bin_zero_as_one_bin(
                    dv, cv, max_bin - 1, total_sample_cnt - na_cnt, min_data_in_bin)
                bounds.append(math.nan)
            else:
                bounds = _find_bin_zero_as_one_bin(
                    dv, cv, max_bin, total_sample_cnt, min_data_in_bin)
                if self.missing_type == MISSING_ZERO and len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            i_bins = np.searchsorted(self.bin_upper_bound, dv, side="left")
            cnt_in_bin = np.bincount(i_bins, weights=cv.astype(np.float64),
                                     minlength=self.num_bin
                                     ).astype(np.int64).tolist()
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
        else:
            cnt_in_bin = self._find_bin_categorical(
                dv, cv, na_cnt, total_sample_cnt, max_bin, min_data_in_bin)

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and _need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data, bin_type):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            self.sparse_rate = (cnt_in_bin[self.default_bin]
                                / max(total_sample_cnt, 1))
        else:
            self.sparse_rate = 1.0
        return self

    def _find_bin_categorical(self, dv, cv, na_cnt, total_sample_cnt, max_bin,
                              min_data_in_bin) -> List[int]:
        """Count-sorted categorical binning (reference bin.cpp:302-377)."""
        cats: List[int] = []
        counts: List[int] = []
        for v, c in zip(dv, cv):
            iv = int(v)
            if iv < 0:
                na_cnt += int(c)
                continue
            if cats and iv == cats[-1]:
                counts[-1] += int(c)
            else:
                cats.append(iv)
                counts.append(int(c))
        self.num_bin = 0
        rest_cnt = total_sample_cnt - na_cnt
        cnt_in_bin: List[int] = []
        self.categorical_2_bin = {}
        b2c: List[int] = []
        if rest_cnt > 0 and cats:
            order = np.argsort(np.asarray(counts), kind="stable")[::-1]
            cats = [cats[i] for i in order]
            counts = [counts[i] for i in order]
            # bin 0 must not be category 0 (default/zero category keeps a
            # non-zero bin id, reference bin.cpp:330-338)
            if cats[0] == 0:
                if len(cats) == 1:
                    cats.append(cats[0] + 1)
                    counts.append(0)
                cats[0], cats[1] = cats[1], cats[0]
                counts[0], counts[1] = counts[1], counts[0]
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            used_cnt = 0
            max_bin = min(len(cats), max_bin)
            cur = 0
            while cur < len(cats) and (used_cnt < cut_cnt or self.num_bin < max_bin):
                if counts[cur] < min_data_in_bin and cur > 1:
                    break
                b2c.append(cats[cur])
                self.categorical_2_bin[cats[cur]] = self.num_bin
                used_cnt += counts[cur]
                cnt_in_bin.append(counts[cur])
                self.num_bin += 1
                cur += 1
            if cur == len(cats) and na_cnt > 0:
                b2c.append(-1)   # -1 represents NaN
                self.categorical_2_bin[-1] = self.num_bin
                cnt_in_bin.append(0)
                self.num_bin += 1
            if cur == len(cats) and na_cnt == 0:
                self.missing_type = MISSING_NONE
            elif na_cnt == 0:
                self.missing_type = MISSING_ZERO
            else:
                self.missing_type = MISSING_NAN
            if cnt_in_bin:
                cnt_in_bin[-1] += total_sample_cnt - used_cnt
        self.bin_2_categorical = np.asarray(b2c, dtype=np.int64)
        return cnt_in_bin

    # ------------------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Scalar value->bin (reference bin.h:452-488)."""
        if isinstance(value, float) and math.isnan(value):
            if self.missing_type == MISSING_NAN:
                return self.num_bin - 1
            value = 0.0
        if self.bin_type == BIN_NUMERICAL:
            hi = self.num_bin - (2 if self.missing_type == MISSING_NAN else 1)
            lo = 0
            while lo < hi:
                mid = (hi + lo - 1) // 2
                if value <= self.bin_upper_bound[mid]:
                    hi = mid
                else:
                    lo = mid + 1
            return lo
        iv = int(value)
        if iv < 0:
            return self.num_bin - 1
        return self.categorical_2_bin.get(iv, self.num_bin - 1)

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin for a column of raw values."""
        values = np.asarray(values, dtype=np.float64)
        out = np.empty(len(values), dtype=np.int32)
        nan_mask = np.isnan(values)
        if self.bin_type == BIN_NUMERICAL:
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            filled = np.where(nan_mask, 0.0, values)
            # bin = first i with value <= upper_bound[i]; side='left' on the
            # ascending bounds gives exactly that, clamped to the last
            # searchable bin when value exceeds every bound
            out[:] = np.searchsorted(self.bin_upper_bound[:n_search - 1],
                                     filled, side="left")
            if self.missing_type == MISSING_NAN:
                out[nan_mask] = self.num_bin - 1
        else:
            iv = np.where(nan_mask, -1, values).astype(np.int64)
            default = self.num_bin - 1
            if len(self.bin_2_categorical):
                max_cat = int(max(self.categorical_2_bin.keys(), default=0))
                if max_cat < (1 << 22):
                    lut = np.full(max_cat + 2, default, dtype=np.int32)
                    for c, b in self.categorical_2_bin.items():
                        if c >= 0:
                            lut[c] = b
                    clipped = np.clip(iv, 0, max_cat + 1)
                    out[:] = lut[clipped]
                    out[iv < 0] = default
                    out[iv > max_cat] = default
                else:
                    out[:] = [self.categorical_2_bin.get(int(v), default)
                              if v >= 0 else default for v in iv]
            else:
                out[:] = default
        return out

    # ------------------------------------------------------------------
    def bin_to_value(self, bin_idx: int) -> float:
        """Representative raw value of a bin (used for threshold output)."""
        if self.bin_type == BIN_NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    def to_state(self) -> dict:
        """Serializable state (analog of CopyTo for distributed find-bin and
        the dataset binary cache)."""
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": self.bin_2_categorical.tolist(),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_state(cls, state: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(state["num_bin"])
        m.missing_type = state["missing_type"]
        m.is_trivial = bool(state["is_trivial"])
        m.sparse_rate = float(state["sparse_rate"])
        m.bin_type = state["bin_type"]
        m.bin_upper_bound = np.asarray(state["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = np.asarray(state["bin_2_categorical"], dtype=np.int64)
        m.categorical_2_bin = {int(c): i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(state["min_val"])
        m.max_val = float(state["max_val"])
        m.default_bin = int(state["default_bin"])
        return m

    # feature_infos string for the text model format: numerical "[min:max]",
    # categorical "cat1:cat2:..." (reference dataset.cpp feature infos)
    def feature_info_str(self) -> str:
        if self.is_trivial:
            return "none"
        if self.bin_type == BIN_NUMERICAL:
            return f"[{self.min_val}:{self.max_val}]"
        return ":".join(str(int(c)) for c in self.bin_2_categorical)
