"""Text data parsers: CSV / TSV / LibSVM with format auto-detection.

Mirrors the reference parser behaviour (``src/io/parser.cpp:1-169``): sniff
the delimiter and format by inspecting sample lines, then parse label +
feature columns.  A C++ fast path (``native/text_parser.cpp``) accelerates
large files when the shared library is built; this module is the always-
available fallback and the single source of semantics.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils.log import log_info


def _sniff(lines: List[str]) -> str:
    """Return 'libsvm', 'tsv' or 'csv' (reference Parser::CreateParser)."""
    def is_libsvm(line):
        toks = line.split()
        if not toks:
            return False
        colon = sum(1 for t in toks[1:] if ":" in t)
        return colon > 0 and colon == len(toks) - 1
    votes = {"libsvm": 0, "tsv": 0, "csv": 0}
    for line in lines:
        if is_libsvm(line):
            votes["libsvm"] += 1
        elif "\t" in line:
            votes["tsv"] += 1
        elif "," in line:
            votes["csv"] += 1
        elif len(line.split()) > 1:
            votes["tsv"] += 1      # space-separated handled like tsv
    return max(votes, key=votes.get)


def parse_libsvm(lines, num_features: Optional[int] = None):
    labels, rows, cols, vals = [], [], [], []
    for line in lines:
        toks = line.split()
        if not toks:
            continue
        labels.append(float(toks[0]))
        for t in toks[1:]:
            c, v = t.split(":", 1)
            rows.append(len(labels) - 1)
            cols.append(int(c))
            vals.append(float(v))
    nf = (max(cols) + 1 if cols else 0) if num_features is None \
        else num_features
    x = np.zeros((len(labels), nf), np.float64)
    if cols:
        x[rows, cols] = vals
    return x, np.asarray(labels, np.float64)


def parse_delimited(lines, delim, label_column=0, header=False,
                    ignore_columns=()):
    names = None
    if header and lines:
        names = [c.strip() for c in lines[0].split(delim)]
        lines = lines[1:]
    rows = []
    for line in lines:
        if not line.strip():
            continue
        rows.append([_atof(t) for t in line.rstrip("\n").split(delim)])
    mat = np.asarray(rows, np.float64)
    if mat.size == 0:
        return np.zeros((0, 0)), np.zeros(0), names
    label = None
    keep = [c for c in range(mat.shape[1]) if c not in set(ignore_columns)]
    if label_column is not None and 0 <= label_column < mat.shape[1]:
        label = mat[:, label_column]
        keep = [c for c in keep if c != label_column]
    x = mat[:, keep]
    if names:
        names = [names[c] for c in keep]
    return x, label, names


def _atof(tok: str) -> float:
    tok = tok.strip()
    if not tok or tok.lower() in ("na", "nan", "null", "none", "?"):
        return np.nan
    try:
        return float(tok)
    except ValueError:
        return np.nan


def load_text_file(path: str, config) -> Tuple[np.ndarray,
                                               Optional[np.ndarray],
                                               Optional[List[str]]]:
    """Load a training text file -> (features, label, feature_names)."""
    from ..utils.file_io import open_text
    with open_text(path) as fh:
        lines = fh.readlines()
    lines = [l for l in lines if l.strip()]
    header = bool(getattr(config, "header", False))
    sample = lines[1 if header else 0:50]
    fmt = _sniff(sample)
    label_col = 0
    lc = str(getattr(config, "label_column", "") or "0")
    if lc.startswith("name:"):
        label_col = None       # resolved after header parse
    elif lc != "":
        label_col = int(lc)
    if fmt == "libsvm":
        x, y = parse_libsvm(lines)
        log_info(f"Loaded {x.shape[0]} rows x {x.shape[1]} features "
                 f"(libsvm) from {path}")
        return x, y, None
    delim = "\t" if fmt == "tsv" else ","
    x, y, names = parse_delimited(lines, delim, label_col, header)
    log_info(f"Loaded {x.shape[0]} rows x {x.shape[1]} features "
             f"({fmt}) from {path}")
    return x, y, names


def load_query_file(path: str) -> Optional[np.ndarray]:
    """Side file ``<data>.query`` with per-query counts
    (reference Metadata query loading)."""
    from ..utils.file_io import exists, open_text
    if not exists(path):
        return None
    with open_text(path) as fh:
        return np.loadtxt(fh).astype(np.int64).reshape(-1)


def load_weight_file(path: str) -> Optional[np.ndarray]:
    from ..utils.file_io import exists, open_text
    if not exists(path):
        return None
    with open_text(path) as fh:
        return np.loadtxt(fh).astype(np.float32).reshape(-1)


def load_init_score_file(path: str) -> Optional[np.ndarray]:
    from ..utils.file_io import exists, open_text
    if not exists(path):
        return None
    with open_text(path) as fh:
        return np.loadtxt(fh).astype(np.float64)
