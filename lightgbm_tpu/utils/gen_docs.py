"""Render ``docs/Parameters.md`` from the annotated parameter schema.

The reference generates ``docs/Parameters.rst`` and the alias table from
the ``Config`` struct's doc-comments via ``helper/parameter_generator.py``
(SURVEY §5 calls the one-schema-generates-everything property
load-bearing).  Here the single source of truth is
``lightgbm_tpu.params.PARAM_SCHEMA``; this module renders the markdown
doc, and ``tests/test_api.py`` asserts the committed file is not stale.

Usage: ``python -m lightgbm_tpu.utils.gen_docs [output_path]``
"""

from __future__ import annotations

import sys

from ..params import PARAM_SCHEMA

_SECTION_TITLES = {
    "core": "Core Parameters",
    "learning": "Learning Control Parameters",
    "io": "IO Parameters",
    "objective": "Objective Parameters",
    "metric": "Metric Parameters",
    "network": "Network Parameters",
    "device": "Device Parameters",
}
_SECTION_ORDER = ("core", "learning", "io", "objective", "metric",
                  "network", "device")


def _fmt_default(p) -> str:
    v = p.default
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return f'"{v}"' if v else '""'
    if isinstance(v, (list, tuple)):
        return '""' if not v else ",".join(str(x) for x in v)
    return str(v)


def _fmt_type(p) -> str:
    t = p.type
    if t is bool:
        return "bool"
    if t is int:
        return "int"
    if t is float:
        return "double"
    if t is list:
        return "multi-value string"
    return "string"


def render() -> str:
    out = ["# Parameters", "",
           "Generated from `lightgbm_tpu/params.py` "
           "(`python -m lightgbm_tpu.utils.gen_docs`). "
           "Do not edit by hand — the schema is the single source of "
           "truth for the parser, the alias table, and this document, "
           "mirroring the reference's `helper/parameter_generator.py` "
           "flow over `include/LightGBM/config.h`.", ""]
    for section in _SECTION_ORDER:
        params = [p for p in PARAM_SCHEMA if p.section == section]
        if not params:
            continue
        out.append(f"## {_SECTION_TITLES[section]}")
        out.append("")
        for p in params:
            head = (f"- `{p.name}` : {_fmt_type(p)}, "
                    f"default = `{_fmt_default(p)}`")
            if p.check:
                head += f", constraint: `{p.check}`"
            out.append(head)
            for alias in p.aliases:
                out.append(f"  - alias: `{alias}`")
            if p.desc:
                out.append(f"  - {p.desc}")
        out.append("")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else "docs/Parameters.md"
    text = render()
    with open(path, "w") as fh:
        fh.write(text)
    print(f"wrote {path} ({text.count(chr(10))} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
