"""Virtual file IO: scheme-dispatched readers/writers.

The reference abstracts its file access behind ``VirtualFileReader`` /
``VirtualFileWriter`` so local files and HDFS share one interface
(``src/io/file_io.cpp:13,54``; HDFS behind ``USE_HDFS``).  The TPU
build's analog is a small scheme registry:

* plain paths and ``file://`` open locally;
* ``*.gz`` paths transparently decompress (text mode) — the practical
  equivalent of the reference's seekable binary streams for the text
  loaders here;
* other schemes (``hdfs://``, ``gs://``, ``s3://``) dispatch through
  ``register_scheme`` so an embedder can plug a filesystem in without
  touching the loaders.  Without a registered handler they raise a
  clear error instead of a bare ``FileNotFoundError``.

Every text ingest path (parsers, the two-round streaming loader, config
files) opens files through :func:`open_text`.
"""

from __future__ import annotations

import gzip
import os
import threading
from typing import Callable, Dict, IO

from .log import LightGBMError

# scheme -> callable(path, mode) -> file object
_SCHEMES: Dict[str, Callable[[str, str], IO]] = {}
_schemes_lock = threading.Lock()


def register_scheme(scheme: str, opener: Callable[[str, str], IO]) -> None:
    """Plug a filesystem in (the USE_HDFS analog): ``opener(path, mode)``
    receives the FULL path including the scheme prefix."""
    with _schemes_lock:
        _SCHEMES[scheme.rstrip(":/")] = opener


def _scheme_of(path: str) -> str:
    head, sep, _ = path.partition("://")
    return head if sep and "/" not in head else ""


def open_text(path: str, mode: str = "r") -> IO:
    """Open a text stream for any supported path form."""
    scheme = _scheme_of(path)
    if scheme in ("", "file"):
        local = path[len("file://"):] if scheme == "file" else path
        if "r" in mode and not os.path.exists(local):
            raise LightGBMError(f"could not open data file {path}")
        if local.endswith(".gz"):
            return gzip.open(local, mode if "t" in mode else mode + "t")
        return open(local, mode)
    opener = _SCHEMES.get(scheme)
    if opener is None:
        raise LightGBMError(
            f"no filesystem registered for scheme {scheme}:// "
            f"(use lightgbm_tpu.utils.file_io.register_scheme)")
    return opener(path, mode)


def exists(path: str) -> bool:
    scheme = _scheme_of(path)
    if scheme in ("", "file"):
        local = path[len("file://"):] if scheme == "file" else path
        return os.path.exists(local)
    try:
        fh = open_text(path)
    except Exception:   # noqa: BLE001 — any failure means "not readable"
        return False
    fh.close()
    return True
