"""Logging with LightGBM-style levels (reference: utils/log.h:1-105).

Levels: Fatal < Warning < Info < Debug.  ``log_fatal`` raises, matching the
reference where ``Log::Fatal`` throws ``std::runtime_error``.  Verbosity is
controlled globally via :func:`set_verbosity` (config param ``verbosity``:
<0 fatal only, 0 warning, 1 info, >=2 debug).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional

_FATAL, _WARNING, _INFO, _DEBUG = -1, 0, 1, 2
_verbosity = _INFO
_callback: Optional[Callable[[str], None]] = None
#: guards the module-level configuration writes below (verbosity,
#: callback, timer sink) — all reachable from embedder threads
_state_lock = threading.Lock()


class LightGBMError(RuntimeError):
    """Error raised by the framework (reference: Log::Fatal throw)."""


def set_verbosity(level: int) -> None:
    global _verbosity
    with _state_lock:
        _verbosity = level


def get_verbosity() -> int:
    return _verbosity


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    """Redirect log output (reference: R callback redirection)."""
    global _callback
    with _state_lock:
        _callback = cb


def _emit(msg: str) -> None:
    if _callback is not None:
        _callback(msg + "\n")
    else:
        sys.stderr.write(msg + "\n")
        sys.stderr.flush()


def log_debug(msg: str) -> None:
    if _verbosity >= _DEBUG:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= _INFO:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= _WARNING:
        _emit(f"[LightGBM-TPU] [Warning] {msg}")


def log_fatal(msg: str) -> None:
    raise LightGBMError(msg)


#: optional observer called as ``sink(tag, seconds)`` on every Timer.stop;
#: the obs subsystem installs one so phase timings also land in its
#: metrics registry (``phase.<tag>`` timing histograms)
_TIMER_SINK: Optional[Callable[[str, float], None]] = None


def set_timer_sink(sink: Optional[Callable[[str, float], None]]) -> None:
    global _TIMER_SINK
    with _state_lock:
        _TIMER_SINK = sink


class Timer:
    """Accumulating per-phase wall-clock timer.

    First-class version of the reference's compile-time TIMETAG counters
    (``serial_tree_learner.cpp:14-41``): ``timer.start("hist")`` /
    ``timer.stop("hist")`` accumulate, ``timer.report()`` pretty-prints
    totals with call counts and per-call means.

    Thread-safe: the process-global ``TRAIN_TIMER`` is reachable from
    callbacks and the C-API embed path, which may run on other threads.
    Concurrent ``start`` of the *same* tag keeps the latest t0 (the
    earlier start is lost — per-tag nesting is not a supported pattern).

    With ``sync=True`` the :meth:`stop_sync` variant blocks on the device
    value before stopping the clock, so phase times attribute device work to
    the phase that dispatched it (JAX dispatch is async; without syncing,
    device time piles up at the next host fetch).  Leave ``sync=False`` in
    production — blocking per phase serialises the device pipeline.
    """

    def __init__(self):
        self.acc = {}
        self.counts = {}
        self._t0 = {}
        self.sync = False
        self._lock = threading.Lock()

    def start(self, tag: str) -> None:
        with self._lock:
            self._t0[tag] = time.perf_counter()

    def stop(self, tag: str) -> None:
        with self._lock:
            t0 = self._t0.pop(tag, None)
            if t0 is None:
                return
            dt = time.perf_counter() - t0
            self.acc[tag] = self.acc.get(tag, 0.0) + dt
            self.counts[tag] = self.counts.get(tag, 0) + 1
        sink = _TIMER_SINK   # snapshot: a concurrent unset must not race
        if sink is not None:
            sink(tag, dt)

    def stop_sync(self, tag: str, value=None):
        """Stop after blocking on ``value`` when ``sync`` profiling is on."""
        if self.sync and value is not None:
            import jax
            jax.block_until_ready(value)
        self.stop(tag)
        return value

    def report(self) -> str:
        """``hist=1.200s/240 (5.0ms), fetch=0.010s`` — total, call count
        and per-call mean (count omitted for single-call tags)."""
        with self._lock:
            items = sorted(self.acc.items())
            counts = dict(self.counts)
        parts = []
        for k, v in items:
            c = counts.get(k, 0)
            if c > 1:
                parts.append(f"{k}={v:.3f}s/{c} ({v / c * 1e3:.1f}ms)")
            else:
                parts.append(f"{k}={v:.3f}s")
        return ", ".join(parts)

    def reset(self) -> None:
        with self._lock:
            self.acc.clear()
            self.counts.clear()
            self._t0.clear()


#: process-global training-phase timer (wired through the tree learner and
#: the boosting loop; ``bench.py`` reads and resets it)
TRAIN_TIMER = Timer()
