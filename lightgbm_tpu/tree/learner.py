"""Serial (single-device) leaf-wise tree learner.

TPU-native re-design of the reference ``SerialTreeLearner``
(``src/treelearner/serial_tree_learner.cpp:157-221``): the host drives the
best-first loop and owns the tree bookkeeping; the device owns the binned
matrix, gradients, leaf index partition, histogram construction and the
best-split scan.  Per split the device work is

  1. stable partition of the split leaf's (padded) index window,
  2. histogram of the *smaller* child (one-hot matmul over its rows),
  3. larger child = parent - smaller (histogram subtraction trick,
     serial_tree_learner.cpp:508-513),
  4. fused best-split scan for both children,

and the only host<->device synchronisation is fetching the two children's
small best-split records.  Leaf windows are padded to power-of-two buckets so
the number of compiled programs stays ~log2(N).

The device interactions are isolated behind hook methods (``_init_state``,
``_leaf_histogram``, ``_leaf_totals``, ``_find_best``, ``_partition``,
``_subtract``, ``bagging_state``) that the distributed learners override:
data-parallel reshards rows over the mesh and psum-reduces histograms,
feature-parallel shards the scan and allreduce-maxes the split record,
voting-parallel adds the top-k election (``lightgbm_tpu/parallel/``).

Monotone-constraint midpoint propagation mirrors
serial_tree_learner.cpp:765-776; forced splits (JSON BFS) mirror
``ForceSplits`` (serial_tree_learner.cpp:546-701).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.histogram import (_gather_rows, _histogram_scan, bucket_size,
                             num_chunks_for, subtract_histogram)
from ..ops.partition import _partition_kernel, apply_leaf_outputs
from ..ops.split import (F_DEFAULT_LEFT, F_FEATURE, F_GAIN, F_IS_CAT,
                         F_LEFT_C, F_LEFT_G, F_LEFT_H, F_LEFT_OUT,
                         F_RIGHT_C, F_RIGHT_G, F_RIGHT_H, F_RIGHT_OUT,
                         F_THRESHOLD, SplitContext)
from .. import obs
from ..utils.log import TRAIN_TIMER, log_warning
from .tree import Tree, categorical_bitsets


class SplitParams(NamedTuple):
    """Host-side decoded split of one leaf, fed to the partition kernel."""
    group: int
    offset: int
    width: int
    default_bin: int
    num_bin: int
    missing: int
    threshold: int
    default_left: bool
    is_cat: bool
    cat_member: np.ndarray    # (256,) bool


@functools.partial(jax.jit, static_argnames=("m", "num_chunks", "dp"))
def _window_histogram(binned, grad, hess, buffer, begin, start, count, m,
                      num_chunks, dp=False):
    """Fused slice + gather + histogram for one leaf window."""
    win = jax.lax.dynamic_slice(buffer, (begin,), (m,))
    bins, gh = _gather_rows(binned, grad, hess, win, start, count)
    return _histogram_scan(bins, gh, num_chunks, dp)


@functools.partial(jax.jit, static_argnames=("m",), donate_argnums=(1,))
def _window_partition(binned, buffer, begin, m, start, count, group, offset,
                      width, default_bin, num_bin, missing, threshold,
                      default_left, is_cat, cat_member):
    """Fused slice + stable partition + write-back (buffer donated)."""
    win = jax.lax.dynamic_slice(buffer, (begin,), (m,))
    new_win, _ = _partition_kernel(binned, win, start, count, group, offset,
                                   width, default_bin, num_bin, missing,
                                   threshold, default_left, is_cat,
                                   cat_member)
    return jax.lax.dynamic_update_slice(buffer, new_win, (begin,))


@jax.jit
def _hist_totals(hist):
    """Leaf totals from any single group's slots (every row lands in exactly
    one slot per group)."""
    return hist[0].sum(axis=0)


# recompile tracking for the host-learner's hot jits: the padded window
# sizes (`m`) bucket the shapes, so the number of distinct signatures —
# and therefore compiles — is observable per training run / per window
_window_histogram = obs.track_jit("window_histogram", _window_histogram)
_window_partition = obs.track_jit("window_partition", _window_partition)
_hist_totals = obs.track_jit("hist_totals", _hist_totals)


class _LeafInfo:
    __slots__ = ("leaf_id", "begin", "count", "total", "cmin", "cmax",
                 "hist", "best", "depth", "output")

    def __init__(self, leaf_id, begin, count, total, cmin, cmax, hist, depth,
                 output):
        self.leaf_id = leaf_id
        self.begin = begin
        self.count = count          # global row count
        self.total = total          # (g, h, c) floats on host
        self.cmin = cmin
        self.cmax = cmax
        self.hist = hist            # learner-specific device handle or None
        self.best = None            # device (packed, cat mask) from find_best
        self.depth = depth
        self.output = output        # current leaf output value


class SerialTreeLearner:
    """Grows one tree from (grad, hess) device arrays."""

    def __init__(self, config, dataset):
        self.config = config
        self.dataset = dataset
        self.binned = jnp.asarray(dataset.binned)
        self.num_data = dataset.num_data
        self.n_pad = bucket_size(max(self.num_data, 1))
        self.ctx = SplitContext(dataset, config)
        self._full_indices = jnp.arange(self.n_pad, dtype=jnp.int32)
        self._rng = np.random.RandomState(
            (config.feature_fraction_seed if config.feature_fraction_seed
             else config.seed + 2) & 0x7FFFFFFF)
        self.forced_splits = None   # parsed forced-split JSON (dict) or None
        # reference gpu_use_dp: double-precision-equivalent accumulation
        self._dp = bool(getattr(config, "gpu_use_dp", False))
        # int8 histogram quantization is a device-grower representation
        # (ops/grow.py); this host path is the full-precision reference
        # the quantized-parity tests compare against, so it NEVER
        # quantizes.  Surface that on the first host-grown tree when the
        # config asked for it (device_growth off/ineligible fallback) —
        # warned lazily because every booster constructs this learner
        # even when the device grower ends up doing all the growing.
        self._warn_quant = int(getattr(config, "grad_quant_bits", 0)
                               or 0) > 0

    @property
    def traverse_binned(self):
        """(N, G) device matrix for full-traversal score paths; the sharded
        learners override this with a replicated copy."""
        return self.binned

    # ------------------------------------------------------------------
    def _feature_mask(self) -> jnp.ndarray:
        nf = self.dataset.num_features
        frac = self.config.feature_fraction
        if frac >= 1.0 or nf <= 1:
            return jnp.ones(nf, dtype=bool)
        k = max(1, int(math.ceil(nf * frac)))
        chosen = self._rng.choice(nf, size=k, replace=False)
        mask = np.zeros(nf, dtype=bool)
        mask[chosen] = True
        return jnp.asarray(mask)

    def _window(self, begin: int, count: int):
        """(slice_begin, static size M, start offset) for a leaf region."""
        m = min(bucket_size(max(count, 1)), self.n_pad)
        b = min(begin, self.n_pad - m)
        return b, m, begin - b

    # ------------------------------------------------------------------
    # overridable device hooks
    # ------------------------------------------------------------------
    def bagging_state(self, seed: int, fraction: float):
        """Device bagging selection; returns (opaque state for ``train``'s
        ``indices_buffer``, global selected count)."""
        from ..ops.bagging import bagging_partition
        key = jax.random.PRNGKey(seed)
        buf, cnt = bagging_partition(key, self.n_pad, self.num_data,
                                     fraction)
        return buf, int(cnt)

    def goss_state(self, seed: int, score_abs, top_rate: float,
                   other_rate: float):
        """GOSS row selection (goss.hpp:88-133): returns (opaque buffer
        state, global selected count, (N,) grad/hess multiplier).  The
        distributed learners override this with rank-local selection, like
        the reference running GOSS on each rank's rows."""
        from ..ops.bagging import goss_partition
        key = jax.random.PRNGKey(seed)
        pad = self.n_pad - self.num_data
        if pad > 0:
            score_abs = jnp.concatenate(
                [score_abs, jnp.zeros(pad, jnp.float32)])
        buf, cnt, mult = goss_partition(
            key, score_abs, self.n_pad,
            jnp.asarray(self.num_data, jnp.int32),
            jnp.asarray(top_rate, jnp.float32),
            jnp.asarray(other_rate, jnp.float32))
        return buf, int(cnt), mult[:self.num_data]

    def _init_state(self, indices_buffer, data_count, grad, hess):
        """Set up the per-tree partition state; returns possibly-resharded
        (grad, hess) used by all later hook calls."""
        if indices_buffer is None:
            indices_buffer = self._full_indices
            data_count = self.num_data
        # private copy: the partition kernel donates (in-place updates) the
        # buffer, and the caller's bagging buffer must survive across trees
        self.buffer = jnp.array(indices_buffer, copy=True)
        self.data_count = data_count
        return grad, hess

    def _leaf_histogram(self, grad, hess, info: _LeafInfo):
        b, m, start = self._window(info.begin, info.count)
        num_chunks = num_chunks_for(m)
        TRAIN_TIMER.start("hist")
        out = _window_histogram(self.binned, grad, hess, self.buffer,
                                jnp.asarray(b, jnp.int32),
                                jnp.asarray(start, jnp.int32),
                                jnp.asarray(info.count, jnp.int32), m,
                                num_chunks, self._dp)
        return TRAIN_TIMER.stop_sync("hist", out)

    def _leaf_totals(self, hist) -> np.ndarray:
        TRAIN_TIMER.start("totals_fetch")
        out = np.asarray(_hist_totals(hist), np.float64)
        TRAIN_TIMER.stop("totals_fetch")
        return out

    def _subtract(self, parent_hist, small_hist):
        return subtract_histogram(parent_hist, small_hist)

    def _find_best(self, info: _LeafInfo, feature_mask):
        flat = info.hist.reshape(-1, 3)
        TRAIN_TIMER.start("find_split")
        out = self.ctx.find_best(flat, info.total, (info.cmin, info.cmax),
                                 feature_mask)
        return TRAIN_TIMER.stop_sync("find_split", out)

    def _partition(self, info: _LeafInfo, sp: SplitParams, left_count: int,
                   right_count: int, right_leaf: int):
        """Partition the leaf's rows; left child keeps ``info.leaf_id``."""
        b, m, start = self._window(info.begin, info.count)
        i32 = lambda v: jnp.asarray(v, jnp.int32)
        TRAIN_TIMER.start("partition")
        self.buffer = _window_partition(
            self.binned, self.buffer, i32(b), m, i32(start), i32(info.count),
            i32(sp.group), i32(sp.offset), i32(sp.width), i32(sp.default_bin),
            i32(sp.num_bin), i32(sp.missing), i32(sp.threshold),
            jnp.asarray(sp.default_left), jnp.asarray(sp.is_cat),
            jnp.asarray(sp.cat_member))
        TRAIN_TIMER.stop_sync("partition", self.buffer)

    # ------------------------------------------------------------------
    def train(self, grad, hess, indices_buffer=None, data_count=None,
              feature_mask=None) -> Tree:
        """Grow one tree.  ``indices_buffer`` is the opaque bagging state
        from ``bagging_state`` (serial: a device (n_pad,) int32 permutation
        whose first ``data_count`` entries are the usable rows); defaults to
        all rows."""
        cfg = self.config
        if self._warn_quant:
            self._warn_quant = False
            log_warning("grad_quant_bits is only applied by the "
                        "on-device grower; the host tree learner keeps "
                        "full-precision f32 histograms")
        grad, hess = self._init_state(indices_buffer, data_count, grad, hess)
        if feature_mask is None:
            feature_mask = self._feature_mask()

        tree = Tree(cfg.num_leaves)
        leaves: Dict[int, _LeafInfo] = {}

        if self.dataset.num_groups == 0 or self.dataset.num_features == 0:
            # no usable features: single-leaf tree from the root sums
            g, h = map(float, (jnp.sum(grad), jnp.sum(hess)))
            root = _LeafInfo(0, 0, self.data_count,
                             np.asarray([g, h, self.data_count]),
                             -math.inf, math.inf, None, 0,
                             self._leaf_output(g, h))
            tree.leaf_value[0] = root.output
            leaves[0] = root
            self.leaves = leaves
            return tree

        # root
        root = _LeafInfo(0, 0, self.data_count, None, -math.inf, math.inf,
                         None, 0, 0.0)
        root.hist = self._leaf_histogram(grad, hess, root)
        root.total = self._leaf_totals(root.hist)
        root.output = self._leaf_output(root.total[0], root.total[1])
        tree.leaf_value[0] = root.output
        leaves[0] = root
        self._schedule_find_best(root, feature_mask)

        forced_queue = self._init_forced(tree)
        if forced_queue:
            self._run_forced(tree, leaves, forced_queue, grad, hess,
                             feature_mask)

        while len(leaves) < cfg.num_leaves:
            best_leaf, best = self._pick_best_leaf(leaves, None)
            if best_leaf is None:
                break
            self._apply_split(tree, leaves, best_leaf, best, grad, hess,
                              feature_mask)

        self.leaves = leaves
        return tree

    # ------------------------------------------------------------------
    def _leaf_output(self, sum_g, sum_h):
        cfg = self.config
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        reg = max(abs(sum_g) - l1, 0.0) * (1 if sum_g >= 0 else -1) \
            if l1 > 0 else sum_g
        out = -reg / (sum_h + l2) if (sum_h + l2) != 0 else 0.0
        mds = cfg.max_delta_step
        if mds > 0 and abs(out) > mds:
            out = math.copysign(mds, out)
        return out

    def _splittable(self, info: _LeafInfo) -> bool:
        cfg = self.config
        if info.count <= 2 * cfg.min_data_in_leaf:
            return False
        if info.total[1] <= 2 * cfg.min_sum_hessian_in_leaf:
            return False
        if cfg.max_depth > 0 and info.depth >= cfg.max_depth:
            return False
        return True

    def _schedule_find_best(self, info: _LeafInfo, feature_mask):
        if not self._splittable(info):
            info.best = None
            return
        info.best = self._find_best(info, feature_mask)

    def _pick_best_leaf(self, leaves, forced_queue):
        TRAIN_TIMER.start("fetch")
        # batch the pending device fetches (usually the two new children)
        # into one transfer instead of one round trip each
        pending = [leaf for leaf in leaves
                   if leaves[leaf].best is not None
                   and not isinstance(leaves[leaf].best[0], np.ndarray)]
        if pending:
            fetched = jax.device_get([leaves[leaf].best[0]
                                      for leaf in pending])
            for leaf, vec in zip(pending, fetched):
                leaves[leaf].best = (np.asarray(vec), leaves[leaf].best[1])
        best_leaf, best_rec, best_gain = None, None, 0.0
        for leaf in sorted(leaves):
            info = leaves[leaf]
            if info.best is None:
                continue
            gain = info.best[0][F_GAIN]
            if gain > best_gain:
                best_leaf, best_rec, best_gain = leaf, info.best, gain
        TRAIN_TIMER.stop("fetch")
        if best_leaf is None:
            return None, None
        return best_leaf, best_rec

    # ------------------------------------------------------------------
    def _apply_split(self, tree, leaves, leaf, best, grad, hess, feature_mask,
                     forced=False):
        ds = self.dataset
        info = leaves[leaf]
        vec, mask_dev = best
        f = int(vec[F_FEATURE])
        real_f = ds.used_features[f]
        mapper = ds.bin_mappers[real_f]
        nb = int(ds.f_num_bin[f])
        default_bin = int(ds.f_default_bin[f])
        is_cat = bool(vec[F_IS_CAT])
        sp = SplitParams(
            group=int(ds.f_group[f]),
            offset=int(ds.f_offset[f]),
            width=nb - (1 if default_bin == 0 else 0),
            default_bin=default_bin,
            num_bin=nb,
            missing=int(ds.f_missing_type[f]),
            threshold=int(vec[F_THRESHOLD]),
            default_left=bool(vec[F_DEFAULT_LEFT]),
            is_cat=is_cat,
            cat_member=(np.asarray(mask_dev, bool) if is_cat
                        else np.zeros(256, bool)))

        left_sum = np.asarray([vec[F_LEFT_G], vec[F_LEFT_H], vec[F_LEFT_C]],
                              np.float64)
        right_sum = np.asarray([vec[F_RIGHT_G], vec[F_RIGHT_H],
                                vec[F_RIGHT_C]], np.float64)
        left_out = float(vec[F_LEFT_OUT])
        right_out = float(vec[F_RIGHT_OUT])
        gain = float(vec[F_GAIN])

        if is_cat:
            member_bins = [int(bb) for bb in np.nonzero(sp.cat_member)[0]
                           if bb < nb]
            bitset_inner, bitset = categorical_bitsets(mapper, member_bins)
            right_leaf = tree.split_categorical(
                leaf, f, real_f, bitset_inner, bitset, left_out, right_out,
                int(left_sum[2]), int(right_sum[2]), gain, sp.missing)
        else:
            threshold_double = mapper.bin_to_value(sp.threshold)
            right_leaf = tree.split(
                leaf, f, real_f, sp.threshold, threshold_double, left_out,
                right_out, int(left_sum[2]), int(right_sum[2]), gain,
                sp.missing, sp.default_left)

        lc, rc = int(left_sum[2]), int(right_sum[2])
        # device partition (no sync needed: counts come from the SplitInfo)
        self._partition(info, sp, lc, rc, right_leaf)

        cmin, cmax = info.cmin, info.cmax
        lmin, lmax, rmin, rmax = cmin, cmax, cmin, cmax
        mono = int(ds.monotone_constraints[f])
        if mono != 0 and not is_cat:
            mid = (left_out + right_out) / 2.0
            if mono > 0:
                lmax, rmin = mid, mid
            else:
                lmin, rmax = mid, mid

        left_info = _LeafInfo(leaf, info.begin, lc, left_sum, lmin, lmax,
                              None, info.depth + 1, left_out)
        right_info = _LeafInfo(right_leaf, info.begin + lc, rc, right_sum,
                               rmin, rmax, None, info.depth + 1, right_out)
        leaves[leaf] = left_info
        leaves[right_leaf] = right_info

        # histogram: build the smaller child, subtract for the larger
        small, large = ((left_info, right_info) if lc <= rc
                        else (right_info, left_info))
        need = self._splittable(small) or self._splittable(large)
        if need:
            small.hist = self._leaf_histogram(grad, hess, small)
            large.hist = self._subtract(info.hist, small.hist)
        info.hist = None
        self._schedule_find_best(left_info, feature_mask)
        self._schedule_find_best(right_info, feature_mask)
        return right_leaf

    # ------------------------------------------------------------------
    # forced splits (reference ForceSplits, serial_tree_learner.cpp:546-701)
    def _init_forced(self, tree):
        """Returns the BFS queue of (leaf, spec-dict) forced splits."""
        if not self.forced_splits:
            return []
        return [(0, self.forced_splits)]

    def _run_forced(self, tree, leaves, forced_queue, grad, hess,
                    feature_mask):
        """BFS-apply the forced-split JSON before best-gain growth
        (reference ForceSplits).  A branch whose forced split is invalid
        (unused feature, min_data/min_hessian violation) is abandoned with
        a warning, like the reference's CHECK-and-skip behaviour."""
        cfg = self.config
        while forced_queue and len(leaves) < cfg.num_leaves:
            leaf, spec = forced_queue.pop(0)
            right = self._apply_forced_split(tree, leaves, leaf, spec,
                                             grad, hess, feature_mask)
            if right is None:
                continue
            if isinstance(spec.get("left"), dict):
                forced_queue.append((leaf, spec["left"]))
            if isinstance(spec.get("right"), dict):
                forced_queue.append((right, spec["right"]))

    def _apply_forced_split(self, tree, leaves, leaf, spec, grad, hess,
                            feature_mask):
        ds = self.dataset
        cfg = self.config
        info = leaves[leaf]
        real_f = int(spec.get("feature", -1))
        try:
            fi = ds.used_features.index(real_f)
        except ValueError:
            log_warning(f"forced split on unused feature {real_f}; "
                        f"skipping branch")
            return None
        if info.hist is None or not self._splittable(info):
            return None
        mapper = ds.bin_mappers[real_f]
        if bool(ds.f_is_categorical[fi]):
            log_warning("forced categorical splits are not supported; "
                        "skipping branch")
            return None
        thr_bin = int(mapper.value_to_bin(float(spec["threshold"])))
        nb = int(ds.f_num_bin[fi])
        db = int(ds.f_default_bin[fi])
        miss = int(ds.f_missing_type[fi])
        thr_bin = min(thr_bin, nb - 2) if nb > 1 else 0
        # feature histogram with the default bin reconstructed
        flat = np.asarray(info.hist, np.float64).reshape(-1, 3)
        grp = int(ds.f_group[fi])
        off = int(ds.f_offset[fi])
        shift = 1 if db == 0 else 0
        fh = np.zeros((256, 3), np.float64)
        for b in range(nb):
            if b != db:
                fh[b] = flat[grp * 256 + off + b - shift]
        fh[db] = np.maximum(info.total - fh[:nb].sum(0) + fh[db], 0.0)
        # left = bins <= thr (partition-kernel semantics, default_left
        # False: the NaN bin goes right)
        left_bins = np.arange(nb) <= thr_bin
        if miss == 2:
            left_bins[nb - 1] = False
        left = fh[:nb][left_bins].sum(0)
        right_sum = info.total - left
        if (left[2] < cfg.min_data_in_leaf
                or right_sum[2] < cfg.min_data_in_leaf
                or left[1] < cfg.min_sum_hessian_in_leaf
                or right_sum[1] < cfg.min_sum_hessian_in_leaf):
            log_warning(f"forced split on feature {real_f} violates "
                        f"min_data/min_hessian constraints; skipping branch")
            return None
        left_out = self._leaf_output(left[0], left[1])
        right_out = self._leaf_output(right_sum[0], right_sum[1])
        vec = np.zeros(13, np.float32)
        vec[F_GAIN] = 0.0
        vec[F_FEATURE] = fi
        vec[F_THRESHOLD] = thr_bin
        vec[F_DEFAULT_LEFT] = 0.0
        vec[F_IS_CAT] = 0.0
        vec[F_LEFT_G], vec[F_LEFT_H], vec[F_LEFT_C] = left
        vec[F_RIGHT_G], vec[F_RIGHT_H], vec[F_RIGHT_C] = right_sum
        vec[F_LEFT_OUT] = left_out
        vec[F_RIGHT_OUT] = right_out
        return self._apply_split(tree, leaves, leaf,
                                 (vec, np.zeros(256, bool)), grad, hess,
                                 feature_mask, forced=True)

    # ------------------------------------------------------------------
    def leaf_regions(self):
        """[(leaf, begin, count)] of the final partition, by position."""
        return sorted(((leaf, li.begin, li.count)
                       for leaf, li in self.leaves.items()),
                      key=lambda t: t[1])

    def update_score(self, score, tree: Tree, multiplier: float = 1.0):
        """Train-score update via leaf partitions (ScoreUpdater::AddScore).
        Only positions inside the bagged region get updates; out-of-bag rows
        are the boosting layer's job (gbdt.cpp:451-471)."""
        regions = self.leaf_regions()
        data_count = sum(r[2] for r in regions)
        begins = jnp.asarray([r[1] for r in regions], jnp.int32)
        values = jnp.asarray(
            [tree.leaf_value[r[0]] * multiplier for r in regions], jnp.float32)
        idx = self.buffer[:self.num_data] if self.n_pad != self.num_data \
            else self.buffer
        return apply_leaf_outputs(score, idx, begins, values,
                                  jnp.asarray(data_count, jnp.int32))

    def leaf_indices_host(self) -> Dict[int, np.ndarray]:
        """Per-leaf raw row indices (host); used by RenewTreeOutput."""
        buf = np.asarray(self.buffer[:self.num_data])
        return {leaf: buf[b:b + c] for leaf, b, c in self.leaf_regions()}
