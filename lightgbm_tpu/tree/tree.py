"""Decision-tree model: flat arrays, prediction, text/JSON serialization.

Re-implements the reference ``Tree`` (``include/LightGBM/tree.h:20-518``,
``src/io/tree.cpp``) on numpy arrays.  Node wiring, decision-type bit
encoding (bit0 categorical, bit1 default_left, bits>=2 missing type) and the
text serialization field set are kept byte-compatible with the reference's
"v2" model format so models round-trip between the two implementations.
"""

from __future__ import annotations

import math
from typing import Dict, List

import numpy as np

from ..data.binning import K_ZERO_THRESHOLD

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

_K_MAX_VAL = math.inf


def _avoid_inf(x: float) -> float:
    """Common::AvoidInf — clamp +-inf to +-1e300 for serialization."""
    if x >= 1e300:
        return 1e300
    if x <= -1e300:
        return -1e300
    return float(x)


def construct_bitset(values) -> List[int]:
    """Common::ConstructBitset: list of ints -> uint32 bitset words."""
    if len(values) == 0:
        return []
    n_words = int(max(values)) // 32 + 1
    words = [0] * n_words
    for v in values:
        v = int(v)
        words[v // 32] |= (1 << (v % 32))
    return words


def categorical_bitsets(mapper, member_bins):
    """(inner-bin bitset, raw-category bitset) for a categorical split whose
    LEFT side is the given bin set.  Shared by the host learner and the
    device grower's record replay so the subtle parts — the
    ``bin_2_categorical[b] >= 0`` NaN-bin exclusion and the 256-bin cap —
    live in exactly one place."""
    member_bins = [int(b) for b in member_bins if int(b) < 256]
    bitset_inner = construct_bitset(member_bins)
    cats = [int(mapper.bin_2_categorical[b]) for b in member_bins
            if b < len(mapper.bin_2_categorical)
            and mapper.bin_2_categorical[b] >= 0]
    return bitset_inner, construct_bitset(cats)


def find_in_bitset(words, val: int) -> bool:
    i1 = val // 32
    if val < 0 or i1 >= len(words):
        return False
    return bool((words[i1] >> (val % 32)) & 1)


class Tree:
    """One decision tree.  Leaves are referenced as ``~leaf`` in child arrays
    (matching the reference encoding: child >= 0 internal node, < 0 leaf)."""

    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        n = max_leaves
        self.num_leaves = 1
        self.left_child = np.zeros(n - 1, np.int32)
        self.right_child = np.zeros(n - 1, np.int32)
        self.split_feature_inner = np.zeros(n - 1, np.int32)
        self.split_feature = np.zeros(n - 1, np.int32)
        self.threshold_in_bin = np.zeros(n - 1, np.int32)
        self.threshold = np.zeros(n - 1, np.float64)
        self.decision_type = np.zeros(n - 1, np.int8)
        self.split_gain = np.zeros(n - 1, np.float64)
        self.leaf_parent = np.full(n, -1, np.int32)
        self.leaf_value = np.zeros(n, np.float64)
        self.leaf_count = np.zeros(n, np.int64)
        self.internal_value = np.zeros(n - 1, np.float64)
        self.internal_count = np.zeros(n - 1, np.int64)
        self.leaf_depth = np.zeros(n, np.int32)
        self.shrinkage = 1.0
        # categorical split storage: threshold_in_bin/threshold hold an index
        # into cat_boundaries; bitsets are over inner bins / raw categories
        self.num_cat = 0
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []
        self.cat_boundaries_inner: List[int] = [0]
        self.cat_threshold_inner: List[int] = []

    # ------------------------------------------------------------------
    def _split_common(self, leaf, feature, real_feature, left_value,
                     right_value, left_cnt, right_cnt, gain):
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = feature
        self.split_feature[new_node] = real_feature
        self.split_gain[new_node] = _avoid_inf(gain)
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        # parent's output becomes the internal (expected) value
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = 0.0 if math.isnan(left_value) else left_value
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[self.num_leaves] = (0.0 if math.isnan(right_value)
                                            else right_value)
        self.leaf_count[self.num_leaves] = right_cnt
        self.leaf_depth[self.num_leaves] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        return new_node

    def split(self, leaf, feature, real_feature, threshold_bin,
              threshold_double, left_value, right_value, left_cnt, right_cnt,
              gain, missing_type: int, default_left: bool) -> int:
        """Numerical split; returns the new (right) leaf index."""
        node = self._split_common(leaf, feature, real_feature, left_value,
                                  right_value, left_cnt, right_cnt, gain)
        dt = 0
        if default_left:
            dt |= K_DEFAULT_LEFT_MASK
        dt |= (int(missing_type) & 3) << 2
        self.decision_type[node] = dt
        self.threshold_in_bin[node] = threshold_bin
        self.threshold[node] = _avoid_inf(threshold_double)
        self.num_leaves += 1
        return self.num_leaves - 1

    def split_categorical(self, leaf, feature, real_feature, bitset_inner,
                          bitset, left_value, right_value, left_cnt,
                          right_cnt, gain, missing_type: int) -> int:
        """Categorical split: bitset_inner over bins, bitset over raw
        category values; returns the new (right) leaf index."""
        node = self._split_common(leaf, feature, real_feature, left_value,
                                  right_value, left_cnt, right_cnt, gain)
        dt = K_CATEGORICAL_MASK | ((int(missing_type) & 3) << 2)
        self.decision_type[node] = dt
        self.threshold_in_bin[node] = self.num_cat
        self.threshold[node] = self.num_cat
        self.num_cat += 1
        self.cat_threshold_inner.extend(int(w) for w in bitset_inner)
        self.cat_boundaries_inner.append(len(self.cat_threshold_inner))
        self.cat_threshold.extend(int(w) for w in bitset)
        self.cat_boundaries.append(len(self.cat_threshold))
        self.num_leaves += 1
        return self.num_leaves - 1

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float):
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value[:max(self.num_leaves - 1, 0)] *= rate
        self.shrinkage *= rate

    def add_bias(self, val: float):
        self.leaf_value[:self.num_leaves] += val
        self.internal_value[:max(self.num_leaves - 1, 0)] += val
        self.shrinkage = 1.0

    def set_leaf_output(self, leaf: int, value: float):
        self.leaf_value[leaf] = value

    def expected_value(self) -> float:
        if self.num_leaves == 1:
            return float(self.leaf_value[0])
        return float(self.internal_value[0])

    # -- prediction (vectorized numpy over raw feature values) ----------
    def _decision_matrix(self, node: np.ndarray, fval: np.ndarray) -> np.ndarray:
        """goes-left per row given current node vector (raw values).
        Mirrors NumericalDecision / CategoricalDecision (tree.h:212-278)."""
        dt = self.decision_type[node]
        is_cat = (dt & K_CATEGORICAL_MASK) != 0
        default_left = (dt & K_DEFAULT_LEFT_MASK) != 0
        missing = (dt.astype(np.int32) >> 2) & 3
        nan_mask = np.isnan(fval)
        v = np.where(nan_mask & (missing != 2), 0.0, fval)
        is_miss = ((missing == 1) & (np.abs(v) <= K_ZERO_THRESHOLD)) | \
                  ((missing == 2) & nan_mask)
        left = np.where(is_miss, default_left, v <= self.threshold[node])
        if self.num_cat > 0 and is_cat.any():
            ci = np.nonzero(is_cat)[0]
            for i in ci:
                fv = fval[i]
                iv = -1 if np.isnan(fv) else int(fv)
                if np.isnan(fv) and missing[i] != 2:
                    iv = 0
                cat_idx = int(self.threshold[node[i]])
                lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
                left[i] = (iv >= 0 and
                           find_in_bitset(self.cat_threshold[lo:hi], iv))
        return left

    def predict_leaf(self, data: np.ndarray) -> np.ndarray:
        """Leaf index per row for a dense (rows, features) raw matrix."""
        n = data.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)
        active = np.ones(n, bool)
        out = np.zeros(n, np.int32)
        while active.any():
            idx = np.nonzero(active)[0]
            cur = node[idx]
            fval = data[idx, self.split_feature[cur]]
            left = self._decision_matrix(cur, fval)
            nxt = np.where(left, self.left_child[cur], self.right_child[cur])
            leaf_mask = nxt < 0
            out[idx[leaf_mask]] = ~nxt[leaf_mask]
            node[idx] = np.where(leaf_mask, 0, nxt)
            active[idx] = ~leaf_mask
        return out

    def predict(self, data: np.ndarray) -> np.ndarray:
        return self.leaf_value[self.predict_leaf(data)]

    def depth(self) -> int:
        return int(self.leaf_depth[:self.num_leaves].max())

    # -- SHAP-style feature contributions (tree.h:466-485) ----------------
    def predict_contrib_row(self, row: np.ndarray, contribs: np.ndarray):
        """TreeSHAP for one row; adds into contribs (num_features + 1,)."""
        contribs[-1] += self.expected_value()
        if self.num_leaves == 1:
            return
        _tree_shap(self, row, contribs)

    # -- serialization -----------------------------------------------------
    def to_string(self) -> str:
        n = self.num_leaves

        def arr(a, k):
            return " ".join(_fmt(v) for v in a[:k])

        lines = [f"num_leaves={n}", f"num_cat={self.num_cat}"]
        lines.append("split_feature=" + arr(self.split_feature, n - 1))
        lines.append("split_gain=" + arr(self.split_gain, n - 1))
        lines.append("threshold=" + " ".join(
            _fmt_double(v) for v in self.threshold[:n - 1]))
        lines.append("decision_type=" + arr(self.decision_type, n - 1))
        lines.append("left_child=" + arr(self.left_child, n - 1))
        lines.append("right_child=" + arr(self.right_child, n - 1))
        lines.append("leaf_value=" + " ".join(
            _fmt_double(v) for v in self.leaf_value[:n]))
        lines.append("leaf_count=" + arr(self.leaf_count, n))
        lines.append("internal_value=" + arr(self.internal_value, n - 1))
        lines.append("internal_count=" + arr(self.internal_count, n - 1))
        if self.num_cat > 0:
            lines.append("cat_boundaries=" + " ".join(
                str(v) for v in self.cat_boundaries))
            lines.append("cat_threshold=" + " ".join(
                str(v) for v in self.cat_threshold))
        lines.append(f"shrinkage={_fmt(self.shrinkage)}")
        return "\n".join(lines) + "\n\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        n = int(kv["num_leaves"])
        t = cls(max(n, 2))
        t.num_leaves = n
        t.num_cat = int(kv.get("num_cat", 0))

        def ints(key, count, dtype=np.int64):
            if count <= 0 or key not in kv or not kv[key].strip():
                return np.zeros(max(count, 0), dtype)
            return np.asarray([int(float(x)) for x in kv[key].split()], dtype)

        def floats(key, count):
            if count <= 0 or key not in kv or not kv[key].strip():
                return np.zeros(max(count, 0), np.float64)
            return np.asarray([float(x) for x in kv[key].split()], np.float64)

        if n > 1:
            t.split_feature = ints("split_feature", n - 1, np.int32)
            t.split_feature_inner = t.split_feature.copy()
            t.split_gain = floats("split_gain", n - 1)
            t.threshold = floats("threshold", n - 1)
            t.threshold_in_bin = np.zeros(n - 1, np.int32)
            t.decision_type = ints("decision_type", n - 1, np.int8)
            t.left_child = ints("left_child", n - 1, np.int32)
            t.right_child = ints("right_child", n - 1, np.int32)
            t.internal_value = floats("internal_value", n - 1)
            t.internal_count = ints("internal_count", n - 1)
        t.leaf_value = np.resize(floats("leaf_value", n), max(n, 2))
        t.leaf_count = np.resize(ints("leaf_count", n)
                                 if "leaf_count" in kv else np.zeros(n, np.int64),
                                 max(n, 2))
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
            # inner bitsets unavailable from file; raw-value prediction only
            t.cat_boundaries_inner = list(t.cat_boundaries)
            t.cat_threshold_inner = list(t.cat_threshold)
        t.shrinkage = float(kv.get("shrinkage", 1))
        # rebuild leaf parents/depths
        t.leaf_parent = np.full(max(n, 2), -1, np.int32)
        for node in range(n - 1):
            for child in (t.left_child[node], t.right_child[node]):
                if child < 0:
                    t.leaf_parent[~child] = node
        return t

    def to_json(self) -> dict:
        def node_json(idx):
            if idx < 0:
                leaf = ~idx
                return {
                    "leaf_index": int(leaf),
                    "leaf_value": float(self.leaf_value[leaf]),
                    "leaf_count": int(self.leaf_count[leaf]),
                }
            dt = int(self.decision_type[idx])
            is_cat = bool(dt & K_CATEGORICAL_MASK)
            missing = (dt >> 2) & 3
            out = {
                "split_index": int(idx),
                "split_feature": int(self.split_feature[idx]),
                "split_gain": float(self.split_gain[idx]),
                "threshold": float(self.threshold[idx]),
                "decision_type": "==" if is_cat else "<=",
                "default_left": bool(dt & K_DEFAULT_LEFT_MASK),
                "missing_type": ["None", "Zero", "NaN"][missing],
                "internal_value": float(self.internal_value[idx]),
                "internal_count": int(self.internal_count[idx]),
                "left_child": node_json(int(self.left_child[idx])),
                "right_child": node_json(int(self.right_child[idx])),
            }
            return out

        return {
            "num_leaves": int(self.num_leaves),
            "num_cat": int(self.num_cat),
            "shrinkage": float(self.shrinkage),
            "tree_structure": node_json(0 if self.num_leaves > 1 else -1),
        }

    def to_if_else(self, index: int, is_predict_leaf: bool) -> str:
        """C++ if-else codegen (reference SaveModelToIfElse,
        gbdt_model_text.cpp:150-240)."""
        name = "PredictTree" + str(index) + ("Leaf" if is_predict_leaf else "")
        body = self._node_if_else(0 if self.num_leaves > 1 else -1,
                                  is_predict_leaf, 1)
        return (f"double {name}(const double* arr) {{\n{body}}}\n")

    def _node_if_else(self, idx: int, leaf_mode: bool, indent: int) -> str:
        pad = "  " * indent
        if idx < 0:
            val = (~idx) if leaf_mode else self.leaf_value[~idx]
            return f"{pad}return {val};\n"
        dt = int(self.decision_type[idx])
        f = int(self.split_feature[idx])
        missing = (dt >> 2) & 3
        default_left = bool(dt & K_DEFAULT_LEFT_MASK)
        if dt & K_CATEGORICAL_MASK:
            cat_idx = int(self.threshold[idx])
            lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
            words = ",".join(str(w) for w in self.cat_threshold[lo:hi])
            cond = (f"CategoricalDecision(arr[{f}], (const uint32_t[]){{{words}}}, "
                    f"{hi - lo})")
        else:
            thr = repr(float(self.threshold[idx]))
            checks = []
            if missing == 1:
                miss = f"IsZero(arr[{f}])"
            elif missing == 2:
                miss = f"std::isnan(arr[{f}])"
            else:
                miss = "false"
            cond = (f"(({miss}) ? {str(default_left).lower()} : "
                    f"(arr[{f}] <= {thr}))")
        left = self._node_if_else(int(self.left_child[idx]), leaf_mode, indent + 1)
        right = self._node_if_else(int(self.right_child[idx]), leaf_mode, indent + 1)
        return (f"{pad}if ({cond}) {{\n{left}{pad}}} else {{\n{right}{pad}}}\n")


def _fmt(v) -> str:
    if isinstance(v, (np.floating, float)):
        return repr(float(v)) if v != int(v) else str(int(v))
    return str(int(v))


def _fmt_double(v) -> str:
    return np.format_float_positional(
        float(v), precision=17, unique=True, trim="0")


# ---------------------------------------------------------------------------
# TreeSHAP (reference src/io/tree.cpp TreeSHAP / PredictContrib)
# ---------------------------------------------------------------------------

class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, f=-1, z=0.0, o=0.0, w=0.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w


def _extend_path(path, unique_depth, zero_fraction, one_fraction, feature_index):
    path[unique_depth] = _PathElement(feature_index, zero_fraction,
                                      one_fraction,
                                      1.0 if unique_depth == 0 else 0.0)
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight
                           * (unique_depth - i) / (unique_depth + 1))


def _unwind_path(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = (tmp - path[i].pweight * zero_fraction
                                * (unique_depth - i) / (unique_depth + 1))
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1)
                               / (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path, unique_depth, path_index):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = (path[i].pweight - tmp * zero_fraction
                                * (unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / (zero_fraction * (unique_depth - i)
                                         / (unique_depth + 1)))
    return total


class _BatchPath:
    """Path state for TreeSHAP over a BATCH of rows.

    The Lundberg recursion's control flow — DFS order, which feature sits
    at each path position, where a duplicate feature is found — depends
    only on the TREE, not the row; only the numeric fractions/pweights
    are row-dependent.  So the scalar algorithm vectorizes by promoting
    each path element's (zero_fraction, one_fraction, pweight) to a
    (rows,) array while feature indices stay scalars.  This replaces the
    reference's per-row ``TreeSHAP`` (``tree.h:466-485``,
    ``src/io/tree.cpp``) with one whose cost is amortized over the whole
    batch — the O(rows x trees) pure-Python loop was unusable beyond toy
    sizes.
    """

    __slots__ = ("feature", "zero", "one", "pweight")

    def __init__(self, depth_cap, rows):
        self.feature = np.full(depth_cap, -1, np.int64)
        self.zero = np.zeros((depth_cap, rows))
        self.one = np.zeros((depth_cap, rows))
        self.pweight = np.zeros((depth_cap, rows))

    def fork(self, k):
        """Copy of the first ``k`` path positions.  Positions >= k are
        left uninitialized: _extend_batch always writes a position fully
        before any read, so stale tails are never observed."""
        out = _BatchPath.__new__(_BatchPath)
        out.feature = self.feature.copy()
        out.zero = np.empty_like(self.zero)
        out.one = np.empty_like(self.one)
        out.pweight = np.empty_like(self.pweight)
        out.zero[:k] = self.zero[:k]
        out.one[:k] = self.one[:k]
        out.pweight[:k] = self.pweight[:k]
        return out


def _extend_batch(p: _BatchPath, ud, zero_fraction, one_fraction, feature):
    p.feature[ud] = feature
    p.zero[ud] = zero_fraction
    p.one[ud] = one_fraction
    p.pweight[ud] = 1.0 if ud == 0 else 0.0
    for i in range(ud - 1, -1, -1):
        p.pweight[i + 1] += one_fraction * p.pweight[i] * (i + 1) / (ud + 1)
        p.pweight[i] = zero_fraction * p.pweight[i] * (ud - i) / (ud + 1)


def _unwind_batch(p: _BatchPath, ud, path_index):
    one = p.one[path_index]
    zero = p.zero[path_index]
    nonzero = one != 0
    safe_one = np.where(nonzero, one, 1.0)
    safe_zero = np.where(zero != 0, zero, 1.0)
    next_one = p.pweight[ud].copy()
    for i in range(ud - 1, -1, -1):
        tmp = p.pweight[i].copy()   # value copy: the row write below
        # would otherwise corrupt the old pweight next_one still needs
        pw_nz = next_one * (ud + 1) / ((i + 1) * safe_one)
        pw_z = tmp * (ud + 1) / (safe_zero * (ud - i))
        p.pweight[i] = np.where(nonzero, pw_nz, pw_z)
        # the zero-one_fraction branch leaves next_one untouched
        next_one = np.where(nonzero,
                            tmp - pw_nz * zero * (ud - i) / (ud + 1),
                            next_one)
    for i in range(path_index, ud):
        p.feature[i] = p.feature[i + 1]
        p.zero[i] = p.zero[i + 1]
        p.one[i] = p.one[i + 1]


def _unwound_sum_batch(p: _BatchPath, ud, path_index):
    one = p.one[path_index]
    zero = p.zero[path_index]
    nonzero = one != 0
    safe_one = np.where(nonzero, one, 1.0)
    safe_zero = np.where(zero != 0, zero, 1.0)
    next_one = p.pweight[ud].copy()
    total = np.zeros_like(next_one)
    for i in range(ud - 1, -1, -1):
        tmp = next_one * (ud + 1) / ((i + 1) * safe_one)
        total += np.where(nonzero, tmp,
                          p.pweight[i] * (ud + 1) / (safe_zero * (ud - i)))
        # the zero-one_fraction branch leaves next_one untouched
        next_one = np.where(nonzero,
                            p.pweight[i] - tmp * zero * (ud - i) / (ud + 1),
                            next_one)
    return total


def _decide_left_batch(tree: Tree, rows: np.ndarray, node: int):
    """(rows,) bool: whether each row follows the left child at node.
    Delegates to Tree._decision_matrix so the split-decision semantics
    (missing modes, zero threshold, categorical bitsets) live in exactly
    one place."""
    nodes = np.full(rows.shape[0], node, np.int32)
    return tree._decision_matrix(nodes, rows[:, tree.split_feature[node]])


def _structural_depth(tree: Tree) -> int:
    """Max depth from the children arrays (leaf_depth is not serialized
    in model text, so it cannot be trusted for loaded trees); cached on
    the tree since SHAP calls this once per row-chunk."""
    cached = getattr(tree, "_shap_depth", None)
    if cached is not None:
        return cached
    depth = {0: 0}
    max_d = 0
    for node in range(tree.num_leaves - 1):
        d = depth[node] + 1
        for c in (int(tree.left_child[node]), int(tree.right_child[node])):
            if c >= 0:
                depth[c] = d
        max_d = max(max_d, d)
    tree._shap_depth = max_d
    return max_d


def tree_shap_batch(tree: Tree, rows: np.ndarray, contribs: np.ndarray):
    """TreeSHAP for a batch: rows (B, F) float64, contribs (B, F+1)
    accumulated in place (last column gets the expected value)."""
    contribs[:, -1] += tree.expected_value()
    if tree.num_leaves <= 1:
        return
    depth_cap = _structural_depth(tree) + 2
    nrows = rows.shape[0]

    def child_count(c):
        return float(tree.leaf_count[~c] if c < 0
                     else tree.internal_count[c])

    def recurse(node, ud, parent: _BatchPath, parent_zero, parent_one,
                parent_feature):
        path = parent.fork(ud + 1)
        _extend_batch(path, ud, parent_zero, parent_one, parent_feature)

        if node < 0:
            leaf_v = float(tree.leaf_value[~node])
            for i in range(1, ud + 1):
                w = _unwound_sum_batch(path, ud, i)
                contribs[:, path.feature[i]] += (
                    w * (path.one[i] - path.zero[i]) * leaf_v)
            return

        left_mask = _decide_left_batch(tree, rows, node)
        node_count = max(float(tree.internal_count[node]), 1.0)
        lc = int(tree.left_child[node])
        rc = int(tree.right_child[node])
        l_zero = child_count(lc) / node_count
        r_zero = child_count(rc) / node_count

        inc_zero = np.ones(nrows)
        inc_one = np.ones(nrows)
        feature = int(tree.split_feature[node])
        path_index = 0
        while path_index <= ud:
            if path.feature[path_index] == feature:
                break
            path_index += 1
        if path_index != ud + 1:
            inc_zero = path.zero[path_index].copy()
            inc_one = path.one[path_index].copy()
            _unwind_batch(path, ud, path_index)
            ud -= 1

        recurse(lc, ud + 1, path, l_zero * inc_zero,
                inc_one * left_mask.astype(np.float64), feature)
        recurse(rc, ud + 1, path, r_zero * inc_zero,
                inc_one * (~left_mask).astype(np.float64), feature)

    root = _BatchPath(depth_cap, nrows)
    # the root "extend" carries the sentinel parent (feature -1, one=1)
    recurse(0, 0, root, 1.0, np.ones(nrows), -1)


def _tree_shap(tree: Tree, row, contribs, node=0, unique_depth=0,
               parent_path=None, parent_zero_fraction=1.0,
               parent_one_fraction=1.0, parent_feature_index=-1):
    path = [(_PathElement(p.feature_index, p.zero_fraction, p.one_fraction,
                          p.pweight) if p else _PathElement())
            for p in (parent_path or [])]
    path.extend(_PathElement() for _ in range(unique_depth + 1 - len(path)))
    _extend_path(path, unique_depth, parent_zero_fraction,
                 parent_one_fraction, parent_feature_index)

    if node < 0:
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            contribs[el.feature_index] += (
                w * (el.one_fraction - el.zero_fraction)
                * tree.leaf_value[leaf])
        return

    # internal node
    fval = row[tree.split_feature[node]]
    dt = int(tree.decision_type[node])
    is_cat = bool(dt & K_CATEGORICAL_MASK)
    missing = (dt >> 2) & 3
    default_left = bool(dt & K_DEFAULT_LEFT_MASK)
    if np.isnan(fval) and missing != 2:
        v = 0.0
    else:
        v = fval
    if is_cat:
        iv = int(v) if not np.isnan(v) else -1
        cat_idx = int(tree.threshold[node])
        lo, hi = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
        left = iv >= 0 and find_in_bitset(tree.cat_threshold[lo:hi], iv)
    else:
        if (missing == 1 and abs(v) <= K_ZERO_THRESHOLD) \
                or (missing == 2 and np.isnan(v)):
            left = default_left
        else:
            left = v <= tree.threshold[node]
    hot = tree.left_child[node] if left else tree.right_child[node]
    cold = tree.right_child[node] if left else tree.left_child[node]

    def child_count(c):
        return (tree.leaf_count[~c] if c < 0 else tree.internal_count[c])

    node_count = tree.internal_count[node]
    hot_zero_fraction = child_count(hot) / max(node_count, 1)
    cold_zero_fraction = child_count(cold) / max(node_count, 1)
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # if we have already split on this feature, undo and merge fractions
    path_index = 0
    feature = int(tree.split_feature[node])
    while path_index <= unique_depth:
        if path[path_index].feature_index == feature:
            break
        path_index += 1
    if path_index != unique_depth + 1:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(tree, row, contribs, int(hot), unique_depth + 1, path,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, feature)
    _tree_shap(tree, row, contribs, int(cold), unique_depth + 1, path,
               cold_zero_fraction * incoming_zero_fraction, 0.0, feature)
