"""lightgbm-compatible ``Dataset`` / ``Booster`` wrappers.

API surface mirrors the reference python package (``python-package/lightgbm/
basic.py:626,1450``) so user code written against LightGBM v2.2.2 keeps
working; underneath sits the TPU runtime (BinnedDataset + GBDT) instead of
the ctypes C API.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .boosting import create_boosting
from .boosting.gbdt import GBDT
from .config import Config, normalize_params
from .data.dataset import BinnedDataset, Metadata
from .utils.log import LightGBMError

__all__ = ["Dataset", "Booster", "LightGBMError"]


def _is_sparse(data) -> bool:
    return hasattr(data, "tocsr") and hasattr(data, "nnz")


def _to_2d_float(data, feature_name=None):
    """Coerce user input (ndarray / pandas / scipy sparse / list) to a dense
    float64 matrix + feature names.  (Sparse inputs in the Dataset
    construction path never reach this - they bin CSR-natively; this
    densify only serves prediction batches and is chunked by callers.)"""
    names = None
    if hasattr(data, "toarray"):          # scipy sparse
        data = data.toarray()
    elif hasattr(data, "values") and hasattr(data, "columns"):  # DataFrame
        names = [str(c) for c in data.columns]
        data = data.values
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if arr.ndim != 2:
        raise LightGBMError("data must be 2-dimensional")
    if feature_name not in (None, "auto"):
        names = list(feature_name)
    return np.ascontiguousarray(arr), names


def _resolve_categorical(categorical_feature, feature_names, num_features):
    if categorical_feature in (None, "auto", []):
        return []
    out = []
    for c in categorical_feature:
        if isinstance(c, str):
            if feature_names and c in feature_names:
                out.append(feature_names.index(c))
            else:
                raise LightGBMError(f"unknown categorical feature name {c}")
        else:
            ci = int(c)
            if ci >= num_features:
                raise LightGBMError("categorical_feature index out of range")
            out.append(ci)
    return sorted(set(out))


class Dataset:
    """Training/validation data holder (lazy binning construction,
    reference basic.py:626-1449)."""

    def __init__(self, data, label=None, reference=None, weight=None,
                 group=None, init_score=None, silent=False,
                 feature_name="auto", categorical_feature="auto",
                 params=None, free_raw_data=True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._handle: Optional[BinnedDataset] = None
        self.used_indices = None
        self._predictor = None
        self.raw: Optional[np.ndarray] = None   # kept for valid-set metrics

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._handle is not None:
            return self
        params = dict(self.params)
        if self.reference is not None:
            self.reference.construct()
            params = {**self.reference.params, **params}
        cfg = Config(params)
        if self.used_indices is not None and self.reference is not None:
            # subset construction (cv folds, bagging subsets) never touches
            # raw data: it slices the parent's binned matrix
            self.reference.construct()
            self._handle = self.reference._handle.copy_subset(
                np.asarray(self.used_indices, np.int64))
            self._set_metadata(self._handle, subset=True)
            return self
        if isinstance(self.data, str):
            if BinnedDataset.is_binary_file(self.data):
                self._handle = BinnedDataset.load_binary(self.data)
                self._set_metadata(self._handle)
                return self
            from .data.parser import load_text_file
            arr, label, names = load_text_file(self.data, cfg)
            if self.label is None and label is not None:
                self.label = label
        elif _is_sparse(self.data):
            arr, names = None, (list(self.feature_name)
                                if self.feature_name not in (None, "auto")
                                else None)
        else:
            arr, names = _to_2d_float(self.data, self.feature_name)
        ref_handle = (self.reference._handle if self.reference is not None
                      else None)
        if arr is None:
            # CSR-native path: bin straight from the sparse structure
            # (memory ~ nnz), never densifying
            csr = self.data.tocsr()
            cats = _resolve_categorical(
                self.categorical_feature
                if self.categorical_feature != "auto" else None,
                names, csr.shape[1])
            self._handle = BinnedDataset.construct_from_csr(
                csr.indptr, csr.indices, csr.data, csr.shape[1], cfg, cats,
                feature_names=names, reference=ref_handle)
            self._set_metadata(self._handle)
            self.raw = csr if not self.free_raw_data else None
        else:
            cats = _resolve_categorical(
                self.categorical_feature
                if self.categorical_feature != "auto" else None,
                names, arr.shape[1])
            self._handle = BinnedDataset.construct_from_matrix(
                arr, cfg, cats, feature_names=names, reference=ref_handle)
            self._set_metadata(self._handle)
            self.raw = arr if not self.free_raw_data else None
        if self.free_raw_data and not isinstance(self.data, str):
            self.data = None
        return self

    def _set_metadata(self, handle: BinnedDataset, subset=False):
        if handle.metadata is None:
            handle.metadata = Metadata(handle.num_data)
        md = handle.metadata
        if self.label is not None:
            md.set_label(np.asarray(self.label))
        if self.weight is not None:
            md.set_weights(np.asarray(self.weight))
        if self.group is not None:
            md.set_query(np.asarray(self.group))
        if self.init_score is not None:
            md.set_init_score(np.asarray(self.init_score))

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, silent=silent,
                       params=params,
                       feature_name=self.feature_name,
                       categorical_feature=self.categorical_feature)

    def subset(self, used_indices, params=None) -> "Dataset":
        ds = Dataset(None, reference=self, params=params or self.params,
                     feature_name=self.feature_name,
                     categorical_feature=self.categorical_feature)
        ds.used_indices = sorted(int(i) for i in used_indices)
        return ds

    def save_binary(self, filename) -> "Dataset":
        self.construct()._handle.save_binary(filename)
        return self

    # -- field get/set --------------------------------------------------
    def set_label(self, label):
        self.label = label
        if self._handle is not None and label is not None:
            self._handle.metadata.set_label(np.asarray(label))
        return self

    def set_weight(self, weight):
        self.weight = weight
        if self._handle is not None and weight is not None:
            self._handle.metadata.set_weights(np.asarray(weight))
        return self

    def set_group(self, group):
        self.group = group
        if self._handle is not None and group is not None:
            self._handle.metadata.set_query(np.asarray(group))
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(
                None if init_score is None else np.asarray(init_score))
        return self

    def set_reference(self, reference):
        if self._handle is not None:
            raise LightGBMError("cannot set reference after constructed")
        self.reference = reference
        return self

    def get_label(self):
        if self._handle is not None and self._handle.metadata.label is not None:
            return np.asarray(self._handle.metadata.label)
        return None if self.label is None else np.asarray(self.label)

    def get_weight(self):
        if self._handle is not None:
            w = self._handle.metadata.weights
            return None if w is None else np.asarray(w)
        return self.weight

    def get_group(self):
        if self._handle is not None:
            qb = self._handle.metadata.query_boundaries
            return None if qb is None else np.diff(qb)
        return self.group

    def get_init_score(self):
        return self.init_score

    def num_data(self) -> int:
        self.construct()
        return self._handle.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._handle.num_total_features

    def get_feature_name(self):
        self.construct()
        return list(self._handle.feature_names)

    def set_categorical_feature(self, categorical_feature):
        if self._handle is not None and \
                categorical_feature != self.categorical_feature:
            raise LightGBMError(
                "cannot set categorical feature after constructed")
        self.categorical_feature = categorical_feature
        return self

    def set_feature_name(self, feature_name):
        self.feature_name = feature_name
        if self._handle is not None and feature_name not in (None, "auto"):
            if len(feature_name) != self._handle.num_total_features:
                raise LightGBMError("length of feature names doesn't equal "
                                    "with num_feature")
            self._handle.feature_names = [str(f) for f in feature_name]
        return self


class Booster:
    """Boosting model driver (reference basic.py:1450-2415)."""

    def __init__(self, params=None, train_set=None, model_file=None,
                 model_str=None, silent=False):
        self.params = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set: Optional[Dataset] = None
        self.name_valid_sets: List[str] = []
        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError("Training data should be Dataset instance")
            train_set.construct()
            cfg = Config(self.params)
            self._gbdt = create_boosting(cfg)
            self._gbdt.init_train(train_set._handle)
            self._train_set = train_set
        elif model_file is not None:
            self._gbdt = GBDT.load_model_from_file(model_file,
                                                   Config(self.params))
        elif model_str is not None:
            self._gbdt = GBDT.load_model_from_string(model_str,
                                                     Config(self.params))
        else:
            raise TypeError("At least one of train_set, model_file or "
                            "model_str should be not None")

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self._gbdt.add_valid(data._handle, name)
        self.name_valid_sets.append(name)
        return self

    def update(self, train_set=None, fobj=None) -> bool:
        """One boosting iteration; returns True if stopped early
        (no more splits).  Drives ``GBDT.train_chunked`` — a single
        iteration takes the per-iteration device path, but the unified
        driver keeps host bagging state consistent when fused chunks
        (``update_chunked``, ``engine.train``) and single updates mix."""
        if train_set is not None:
            raise LightGBMError(
                "resetting training data mid-training is not supported yet")
        if fobj is None:
            return self._gbdt.train_chunked(1)
        grad, hess = fobj(self._curr_pred_for_fobj(), self._train_set)
        return self.__boost(grad, hess)

    def update_chunked(self, n_iters: int, chunk: int = None) -> bool:
        """Train ``n_iters`` iterations, fusing up to ``chunk`` whole
        iterations into one device dispatch when the configuration
        allows (``GBDT.train_chunked``); returns True if training
        stopped early.  ``chunk`` defaults to the booster's
        ``fused_chunk`` param (so ``fused_chunk<=1`` disables fusing
        here too, like every other driver).  Callback/eval cadence does
        not apply here — use ``engine.train`` when per-iteration hooks
        are needed."""
        if chunk is None:
            chunk = max(int(getattr(self._gbdt.config, "fused_chunk",
                                    20)), 0)
        return self._gbdt.train_chunked(n_iters, chunk=chunk)

    def _curr_pred_for_fobj(self):
        score = np.asarray(self._gbdt.train_score, np.float64)
        if score.shape[0] == 1:
            return score[0]
        return score.T.reshape(-1)

    def __boost(self, grad, hess):
        grad = np.asarray(grad, np.float32)
        hess = np.asarray(hess, np.float32)
        num_model = self._gbdt.num_model
        n = self._gbdt.num_data
        if grad.size != n * num_model:
            raise LightGBMError(
                f"gradients size mismatch: {grad.size} != {n * num_model}")
        if num_model > 1:
            grad = grad.reshape(n, num_model).T
            hess = hess.reshape(n, num_model).T
        return self._gbdt.train_one_iter(grad, hess)

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def current_iteration(self) -> int:
        return self._gbdt.num_iterations()

    def num_trees(self) -> int:
        return len(self._gbdt.models)

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_model

    # ------------------------------------------------------------------
    def eval_train(self, feval=None):
        return self._eval("training", self._gbdt.eval_train(), feval,
                          is_train=True)

    def eval_valid(self, feval=None):
        return self._eval(None, self._gbdt.eval_valid(), feval,
                          is_train=False)

    def _eval(self, name, records, feval, is_train):
        out = [(d, n, v, b) for d, n, v, b in records]
        if feval is not None:
            if is_train and self._train_set is not None:
                pred = self._inner_eval_pred(self._gbdt.train_score)
                res = feval(pred, self._train_set)
                out.extend(_feval_records("training", res))
            if not is_train:
                for v in self._gbdt.valid_sets:
                    pred = self._inner_eval_pred(v.score)
                    holder = Dataset.__new__(Dataset)
                    holder._handle = v.dataset
                    holder.label = v.dataset.metadata.label
                    holder.group = None
                    res = feval(pred, holder)
                    out.extend(_feval_records(v.name, res))
        return out

    def _inner_eval_pred(self, score):
        s = np.asarray(score, np.float64)
        if self._gbdt.average_output:
            # RF: summed scores average to the output directly (rf.hpp
            # EvalOneMetric passes a null objective — no conversion)
            s = s / max(self._gbdt.num_iterations(), 1)
        elif self._gbdt.objective is not None:
            s = self._gbdt.objective.convert_output(s)
        return s[0] if s.shape[0] == 1 else s.T.reshape(-1)

    # ------------------------------------------------------------------
    def predict(self, data, num_iteration=-1, raw_score=False,
                pred_leaf=False, pred_contrib=False, data_has_header=False,
                is_reshape=True, **kwargs):
        if isinstance(data, Dataset):
            raise TypeError("Cannot use Dataset instance for prediction, "
                            "please use raw data instead")
        if _is_sparse(data) and not pred_leaf and not pred_contrib:
            # sparse inputs predict in row chunks so peak dense memory is
            # bounded regardless of the matrix height (the fork harness
            # predicts 20M-request windows from CSR, src/test.cpp:211-241)
            csr = data.tocsr()
            chunk = max(1, 1 << 16)
            outs = [self._gbdt.predict(csr[i:i + chunk].toarray(),
                                       num_iteration=num_iteration,
                                       raw_score=raw_score)
                    for i in range(0, csr.shape[0], chunk)]
            return np.concatenate(outs, axis=0)
        arr, _ = _to_2d_float(data)
        return self._gbdt.predict(arr, num_iteration=num_iteration,
                                  raw_score=raw_score, pred_leaf=pred_leaf,
                                  pred_contrib=pred_contrib)

    def refit(self, data, label, decay_rate=0.9, **kwargs):
        """Refit leaf values on new data (reference RefitTree,
        gbdt.cpp:265-288): returns a NEW Booster sharing this model's
        tree structure with leaf values re-fit against ``label`` with
        ``decay_rate`` (``GBDT.refit_leaves`` holds the vectorized
        core — the windowed-retrain pipeline's ``refit``/``warm``
        policies drive the same code from binned leaf assignments)."""
        arr, _ = _to_2d_float(data)
        new_booster = Booster(model_str=self.model_to_string(),
                              params=self.params)
        new_booster._gbdt.refit_leaves(arr, label, decay_rate=decay_rate)
        return new_booster

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration=-1, start_iteration=0) -> str:
        return self._gbdt.model_to_string(start_iteration, num_iteration)

    def save_model(self, filename, num_iteration=-1,
                   start_iteration=0) -> "Booster":
        self._gbdt.save_model_to_file(filename, start_iteration,
                                      num_iteration)
        return self

    def dump_model(self, num_iteration=-1, start_iteration=0) -> dict:
        g = self._gbdt
        return {
            "name": "tree",
            "version": "v2",
            "num_class": max(g.num_model, 1),
            "num_tree_per_iteration": g.num_model,
            "label_index": 0,
            "max_feature_idx": g.max_feature_idx,
            "objective": (g.objective.to_string() if g.objective
                          else g.loaded_objective_str),
            "average_output": g.average_output,
            "feature_names": g.feature_names,
            "tree_info": [
                {"tree_index": i, **t.to_json()}
                for i, t in enumerate(g.models)],
        }

    def feature_importance(self, importance_type="split", iteration=-1):
        return self._gbdt.feature_importance(importance_type, iteration)

    def feature_name(self):
        return list(self._gbdt.feature_names)

    # -- misc -----------------------------------------------------------
    def reset_parameter(self, params) -> "Booster":
        norm = normalize_params(params)
        self.params.update(norm)
        cfg = Config(self.params)
        self._gbdt.config = cfg
        self._gbdt.shrinkage_rate = cfg.learning_rate
        if hasattr(self._gbdt, "learner"):
            from .ops.split import SplitHyper
            self._gbdt.learner.config = cfg
            self._gbdt.learner.ctx.hyper = SplitHyper.from_config(cfg)
        return self

    def set_train_data_name(self, name):
        self._train_data_name = name
        return self

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo):
        return Booster(model_str=self.model_to_string(), params=self.params)

    def __getstate__(self):
        state = {"params": self.params,
                 "model_str": self.model_to_string(),
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self._train_set = None
        self.name_valid_sets = []
        self._gbdt = GBDT.load_model_from_string(state["model_str"],
                                                 Config(self.params))


def _feval_records(dataset_name, res):
    if isinstance(res, list):
        return [(dataset_name, n, v, b) for n, v, b in res]
    n, v, b = res
    return [(dataset_name, n, v, b)]
