"""Developer tooling that ships with the package (static analysis,
codegen helpers).  Nothing here is imported by the runtime library."""
